"""Example: dynamic model serving with a control stream (capability C6).

Two model versions are published while events flow; a DelMessage retires the
model mid-stream and affected lanes become empty predictions — the stream
never dies. Mirrors the reference's ``withSupportStream`` dynamic API
(SURVEY.md §4.3).

Run:  python examples/dynamic_serving.py [--platform cpu]
"""

import pathlib
import sys
import tempfile

try:  # installed package (pip install -e .)
    import flink_jpmml_tpu  # noqa: F401
except ImportError:  # source checkout without install: add the repo root
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import numpy as np

from flink_jpmml_tpu.utils.demo import demo_backend
from flink_jpmml_tpu.assets_gen import gen_iris_lr
from flink_jpmml_tpu.models.control import AddMessage, DelMessage
from flink_jpmml_tpu.runtime.sources import ControlSource
from flink_jpmml_tpu.serving import DynamicScorer


def main() -> None:
    print(f"backend: {demo_backend()}")
    workdir = tempfile.mkdtemp(prefix="fjt-dyn-")
    v1 = gen_iris_lr(workdir, seed=7)
    v2_dir = tempfile.mkdtemp(prefix="fjt-dyn2-")
    v2 = gen_iris_lr(v2_dir, seed=99)

    ctrl = ControlSource()
    scorer = DynamicScorer(control=ctrl, batch_size=64)
    rng = np.random.default_rng(1)
    vectors = rng.normal(3.0, 2.0, size=(8, 4)).astype(np.float32).tolist()
    events = [("iris", v) for v in vectors]

    print("no model served yet:")
    out = scorer.finish(scorer.submit(events))
    print("  empty lanes:", sum(p.is_empty for p, _ in out), "/", len(out))

    ctrl.push(AddMessage("iris", 1, v1, timestamp=1.0))
    out = scorer.finish(scorer.submit(events))
    print("after Add v1:", [p.target.label for p, _ in out[:4]])

    ctrl.push(AddMessage("iris", 2, v2, timestamp=2.0))
    out = scorer.finish(scorer.submit(events))
    print("after Add v2 (latest wins):", [p.target.label for p, _ in out[:4]])

    ctrl.push(DelMessage("iris", 2, timestamp=3.0))
    out = scorer.finish(scorer.submit(events))
    print("after Del v2 (v1 serves again):", [p.target.label for p, _ in out[:4]])

    state = scorer.state()
    print("checkpointable registry state:", state)


if __name__ == "__main__":
    main()
