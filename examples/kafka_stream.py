"""Example: GBM scoring over the Kafka wire protocol with exact resume.

BASELINE config 2's "Kafka tabular stream", end to end on real protocol
bytes: an in-process broker (`MiniKafkaBroker`, the same Fetch v4 /
magic-2 record-batch format a real broker serves) feeds packed-f32 rows
to a `KafkaBlockSource` driving the production `BlockPipeline`; halfway
through, the pipeline is stopped and a fresh one resumes from the
checkpointed Kafka offset — every record scored exactly once.

Run:  python examples/kafka_stream.py [--platform cpu]   (or on the TPU)
"""

import argparse
import pathlib
import sys
import tempfile
import time

try:  # installed package (pip install -e .)
    import flink_jpmml_tpu  # noqa: F401
except ImportError:  # source checkout without install: add the repo root
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import numpy as np

from flink_jpmml_tpu.utils.demo import demo_backend
from flink_jpmml_tpu.assets_gen import gen_gbm
from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.pmml import parse_pmml_file
from flink_jpmml_tpu.runtime.block import BlockPipeline
from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager
from flink_jpmml_tpu.runtime.kafka import KafkaBlockSource, MiniKafkaBroker
from flink_jpmml_tpu.utils.config import BatchConfig, RuntimeConfig


def main() -> None:
    print(f"backend: {demo_backend()}")
    ap = argparse.ArgumentParser()
    ap.add_argument("--partitions", type=int, default=1,
                    help="topic partitions (round-robin interleaved "
                         "consumption; one checkpointed offset resumes "
                         "every partition cursor)")
    args = ap.parse_args()
    workdir = tempfile.mkdtemp(prefix="fjt-kafka-")
    pmml = gen_gbm(workdir, n_trees=50, depth=5, n_features=8)
    cm = compile_pmml(parse_pmml_file(pmml), batch_size=256)

    rng = np.random.default_rng(11)
    N = 20_000
    data = rng.normal(0.0, 1.5, size=(N, 8)).astype(np.float32)

    broker = MiniKafkaBroker(topic="features",
                             n_partitions=args.partitions)
    if args.partitions > 1:
        broker.append_rows_round_robin(data)
    else:
        broker.append_rows(data)
    print(f"broker on {broker.host}:{broker.port}, "
          f"{broker.high_watermark} records in topic 'features' "
          f"({args.partitions} partition(s))")

    cfg = RuntimeConfig(
        batch=BatchConfig(size=256, deadline_us=2000),
        checkpoint_interval_s=0.05,
    )
    ckdir = str(pathlib.Path(workdir, "ck"))
    scored = []

    def sink(out, n, first_off):
        scored.append((first_off, n))

    def make_pipe():
        src = KafkaBlockSource(
            broker.host, broker.port, "features", n_cols=8, max_wait_ms=20,
            partitions=list(range(args.partitions)),
            interleave="strict",  # round-robin producer below: the exact-seek fast path
        )
        return src, BlockPipeline(
            src, cm, sink, cfg, checkpoint=CheckpointManager(ckdir)
        )

    def wait_until(pipe, target, timeout_s=60.0):
        deadline = time.monotonic() + timeout_s
        while pipe.committed_offset < target:
            err = getattr(pipe, "_error", None)
            if err is not None:
                raise RuntimeError(f"pipeline failed: {err!r}")
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"stalled at offset {pipe.committed_offset} (<{target})"
                )
            time.sleep(0.005)

    # first run: stop mid-stream
    src1, pipe1 = make_pipe()
    pipe1.start()
    wait_until(pipe1, N // 3)
    pipe1.stop()
    pipe1.join(timeout=30.0)
    src1.close()
    print(f"run 1 stopped at committed offset {pipe1.committed_offset}")

    # restart: resume from the checkpointed Kafka offset
    src2, pipe2 = make_pipe()
    assert pipe2.restore()
    print(f"run 2 resumes at offset {pipe2.committed_offset}")
    t0 = time.perf_counter()
    pipe2.start()
    wait_until(pipe2, N)
    pipe2.stop()
    pipe2.join(timeout=30.0)
    src2.close()
    dt = time.perf_counter() - t0
    broker.close()

    covered = np.zeros(N, np.int64)
    for off, n in scored:
        covered[off : off + n] += 1
    assert (covered == 1).all(), "exactly-once violated"
    print(
        f"scored all {N} records exactly once; run 2: "
        f"{(N - pipe1.committed_offset) / dt:,.0f} rec/s through the "
        "Kafka wire"
    )


if __name__ == "__main__":
    main()
