"""Example: automatic failure recovery — kill -9 a scoring worker,
watch the supervisor restart it from its checkpoint.

The recovery half of the reference's Flink restart strategies
(SURVEY.md §6 "Failure detection / elastic recovery"), end to end: a
worker process scores a GBM over the Kafka wire with commit-after-sink
checkpointing and beats to the supervisor; this parent SIGKILLs it
mid-stream; the `Supervisor` (runtime/supervisor.py) detects the death,
respawns the worker with bounded backoff, the worker restores the
committed offset and drains the rest — no operator action anywhere.

Run:  python examples/supervised_pipeline.py   (CPU-only; the worker
pins the CPU backend so the demo runs identically with or without a
TPU attached)
"""

import os
import pathlib
import signal
import sys
import tempfile
import textwrap
import time

try:  # installed package (pip install -e .)
    import flink_jpmml_tpu  # noqa: F401
except ImportError:  # source checkout without install: add the repo root
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

REPO = str(pathlib.Path(__file__).resolve().parent.parent)

import numpy as np

from flink_jpmml_tpu.assets_gen import gen_gbm
from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager
from flink_jpmml_tpu.runtime.kafka import MiniKafkaBroker
from flink_jpmml_tpu.runtime.supervisor import (
    RestartPolicy, Supervisor, WorkerSpec,
)

_WORKER = textwrap.dedent(
    """
    import sys, time
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    from flink_jpmml_tpu.compile import compile_pmml
    from flink_jpmml_tpu.pmml import parse_pmml_file
    from flink_jpmml_tpu.runtime.block import BlockPipeline
    from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager
    from flink_jpmml_tpu.runtime.kafka import KafkaBlockSource
    from flink_jpmml_tpu.runtime.supervisor import reporter_from_env
    from flink_jpmml_tpu.utils.config import BatchConfig, RuntimeConfig

    host, port, pmml, ckdir, total = (
        sys.argv[1], int(sys.argv[2]), sys.argv[3], sys.argv[4],
        int(sys.argv[5]),
    )
    rep = reporter_from_env()  # beat to the supervising coordinator
    cm = compile_pmml(parse_pmml_file(pmml), batch_size=128)
    src = KafkaBlockSource(host, port, "features", n_cols=6,
                           max_wait_ms=20)
    pipe = BlockPipeline(
        src, cm, lambda out, n, off: None,
        RuntimeConfig(batch=BatchConfig(size=128, deadline_us=2000),
                      checkpoint_interval_s=0.05),
        checkpoint=CheckpointManager(ckdir),
    )
    resumed = pipe.restore()
    print(f"[worker] {{'resumed at ' + str(pipe.committed_offset) if resumed else 'fresh start'}}",
          flush=True)
    pipe.start()
    while pipe.committed_offset < total:
        time.sleep(0.02)
    pipe.stop(); pipe.join(timeout=30.0)
    src.close()
    print(f"[worker] drained all {{total}} records", flush=True)
    """
)


def main() -> None:
    workdir = tempfile.mkdtemp(prefix="fjt-supervised-")
    pmml = gen_gbm(workdir, n_trees=20, depth=4, n_features=6)
    ckdir = os.path.join(workdir, "ck")

    rng = np.random.default_rng(13)
    N = 30_000
    data = rng.normal(0.0, 1.5, size=(N, 6)).astype(np.float32)
    broker = MiniKafkaBroker(topic="features")
    broker.append_rows(data)
    print(f"broker on {broker.host}:{broker.port}, {N} records")

    spec = WorkerSpec(
        "scorer",
        [sys.executable, "-c", _WORKER.format(repo=REPO),
         broker.host, str(broker.port), pmml, ckdir, str(N)],
    )
    sup = Supervisor(
        [spec],
        policy=RestartPolicy(max_restarts=3, backoff_s=0.2),
        heartbeat_timeout_s=2.0,
        on_restart=lambda wid, n: print(
            f"[supervisor] restarted {wid} (restart #{n})"
        ),
    )
    sup.start()
    try:
        # let the worker commit real progress, then murder it
        def committed():
            st = CheckpointManager(ckdir).load_latest()
            return st["source_offset"] if st else 0

        while committed() < N // 4:
            if sup.status()["scorer"]["gave_up"]:
                raise SystemExit(
                    "worker never started (supervisor gave up)"
                )
            time.sleep(0.05)
        pid = sup.status()["scorer"]["pid"]
        print(f"[parent] kill -9 worker pid {pid} at committed offset "
              f"{committed():,}")
        os.kill(pid, signal.SIGKILL)

        # zero operator action from here: detection -> respawn -> resume
        while not sup.status()["scorer"]["finished"]:
            if sup.status()["scorer"]["gave_up"]:
                raise SystemExit("supervisor gave up (unexpected)")
            time.sleep(0.1)
        st = sup.status()["scorer"]
        print(f"[parent] worker finished after {st['restarts']} automatic "
              f"restart(s); final committed offset {committed():,} / {N:,}")
    finally:
        sup.stop()
        broker.close()


if __name__ == "__main__":
    main()
