"""Example: stacked modelChain ensemble, sharded over a device mesh
(BASELINE config 5).

A MiningModel modelChain — inner GBM whose output field feeds a logistic
calibration RegressionModel — over a wide (default 10k) sparse feature
space, scored with the batch axis sharded across all available devices
(data parallelism over ICI; the reference's only parallelism is Flink
operator DP, SURVEY.md §3 P1). On a CPU host run with
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
  python examples/stacked_sharded.py
to get the virtual 8-device mesh; on a TPU slice the same code shards over
the real chips.

Run:  python examples/stacked_sharded.py [--platform cpu] [--features 10000]
"""

import argparse
import pathlib
import sys
import tempfile

try:  # installed package (pip install -e .)
    import flink_jpmml_tpu  # noqa: F401
except ImportError:  # source checkout without install: add the repo root
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import os

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # the axon TPU plugin ignores the env var; force via config before the
    # backend initializes so the virtual multi-device CPU mesh is honored
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

from flink_jpmml_tpu.utils.demo import demo_backend
from flink_jpmml_tpu.assets_gen import gen_stacked
from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.parallel.mesh import make_mesh
from flink_jpmml_tpu.parallel.sharding import dp_sharded
from flink_jpmml_tpu.pmml import parse_pmml_file


def main() -> None:
    print(f"backend: {demo_backend()}")
    ap = argparse.ArgumentParser()
    ap.add_argument("--features", type=int, default=10_000)
    ap.add_argument("--trees", type=int, default=50)
    ap.add_argument("--batch", type=int, default=2048)
    args = ap.parse_args()

    workdir = tempfile.mkdtemp(prefix="fjt-stacked-")
    pmml = gen_stacked(
        workdir, n_trees=args.trees, depth=4, n_features=args.features,
        wide_lr=True,  # the full config-5 shape: GBM + wide LR + calibration
    )
    doc = parse_pmml_file(pmml)

    import jax

    from flink_jpmml_tpu.utils.config import MeshConfig

    n = len(jax.devices())
    # data x model mesh: the wide LR stage feature-shards over `model`
    n_model = 2 if n % 2 == 0 and n >= 2 else 1
    mesh = make_mesh(MeshConfig(data=n // n_model, model=n_model))
    print(f"mesh: {mesh.shape} over {n} devices")

    # mesh-aware compile: the wide stage's [F] coefficient tensors are
    # feature-sharded INSIDE the compiled scorer (GSPMD inserts the
    # tp_linear-style partial-matmul + psum); narrow params replicate
    sharded = compile_pmml(doc, mesh=mesh)
    print(f"TP-sharded param leaves: {list(sharded.tp_sharded_leaves) or '(pure-DP mesh)'}")

    rng = np.random.default_rng(0)
    # sparse-ish stream: most features zero, a few hot
    X = np.zeros((args.batch, args.features), np.float32)
    hot = rng.integers(0, args.features, size=(args.batch, 32))
    X[np.arange(args.batch)[:, None], hot] = rng.normal(
        0.0, 1.0, size=hot.shape
    )
    M = np.zeros_like(X, bool)

    out = sharded.predict(X, M)
    values = np.asarray(out.value)
    print(f"scored {args.batch} x {args.features}-dim records "
          f"(batch sharded over data, wide-LR features over model, "
          f"{mesh.shape}); "
          f"calibrated score range [{values.min():.4f}, {values.max():.4f}]")

    # plain DP on the same document stays available (params replicated)
    dp = dp_sharded(compile_pmml(doc), mesh)
    np.testing.assert_allclose(
        np.asarray(dp.predict(X, M).value), values, rtol=2e-5, atol=1e-6
    )
    print("DP-replicated predict agrees with the TP-sharded compile")


if __name__ == "__main__":
    main()
