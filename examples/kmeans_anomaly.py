"""Example: streaming K-Means anomaly scoring (BASELINE config 4).

A center-based ClusteringModel lowers to a batched squared-euclidean
cdist + argmin (compile/clustering.py). The anomaly signal is the distance
to the winning centroid — records far from every center are flagged.
Mirrors the reference's K-Means-over-Iris example job (SURVEY.md §3 D2).

Run:  python examples/kmeans_anomaly.py [--platform cpu]
"""

import pathlib
import sys
import tempfile

try:  # installed package (pip install -e .)
    import flink_jpmml_tpu  # noqa: F401
except ImportError:  # source checkout without install: add the repo root
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import numpy as np

from flink_jpmml_tpu.utils.demo import demo_backend
from flink_jpmml_tpu.assets_gen import gen_kmeans
from flink_jpmml_tpu.api import ModelReader, StreamEnvironment
from flink_jpmml_tpu.utils.config import BatchConfig, RuntimeConfig


def main() -> None:
    print(f"backend: {demo_backend()}")
    workdir = tempfile.mkdtemp(prefix="fjt-kmeans-")
    pmml = gen_kmeans(workdir, k=5, n_features=4)
    print(f"model: {pmml}")

    rng = np.random.default_rng(1)
    normal = rng.normal(0.0, 2.0, size=(990, 4))
    outliers = rng.normal(12.0, 0.5, size=(10, 4))  # far from every center
    stream = np.vstack([normal, outliers]).astype(np.float32).tolist()

    env = StreamEnvironment(
        RuntimeConfig(batch=BatchConfig(size=256, deadline_us=2000))
    )
    sink = (
        env.from_collection(stream)
        .quick_evaluate(ModelReader(pmml))
        .collect()
    )
    env.execute(timeout=120.0)

    # prediction.target.probabilities carries per-cluster distances; the
    # winning distance is the anomaly score
    dists = np.asarray(
        [min(p.target.probabilities.values()) for p, _v in sink.items]
    )
    thresh = np.percentile(dists, 99)
    flagged = int((dists > thresh).sum())
    print(f"scored {len(dists)} records; p99 distance {thresh:.2f}; "
          f"{flagged} anomalies flagged "
          f"(last 10 records are the planted outliers: "
          f"{[round(float(d), 1) for d in dists[-10:]]})")


if __name__ == "__main__":
    main()
