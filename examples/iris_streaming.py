"""Example: stream Iris vectors through a logistic-regression PMML.

Reference parity: the examples module's K-Means/Iris jobs (SURVEY.md §3 row
D2 [UNVERIFIED]). Generates the fixture, builds a pipeline with the fluent
API, scores a finite stream, prints predictions + runtime metrics.

Run:  python examples/iris_streaming.py [--platform cpu]
"""

import pathlib
import sys
import tempfile

try:  # installed package (pip install -e .)
    import flink_jpmml_tpu  # noqa: F401
except ImportError:  # source checkout without install: add the repo root
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import numpy as np

from flink_jpmml_tpu.utils.demo import demo_backend
from flink_jpmml_tpu.assets_gen import gen_iris_lr
from flink_jpmml_tpu.api import ModelReader, StreamEnvironment
from flink_jpmml_tpu.utils.config import BatchConfig, RuntimeConfig


def main() -> None:
    print(f"backend: {demo_backend()}")
    workdir = tempfile.mkdtemp(prefix="fjt-iris-")
    pmml_path = gen_iris_lr(workdir)
    print(f"model: {pmml_path}")

    rng = np.random.default_rng(0)
    vectors = rng.normal(3.0, 2.0, size=(1000, 4)).astype(np.float32).tolist()
    vectors[7] = [float("nan")] * 4  # one dirty record: lane goes empty (C5)

    env = StreamEnvironment(
        RuntimeConfig(batch=BatchConfig(size=256, deadline_us=2000))
    )
    sink = (
        env.from_collection(vectors)
        .quick_evaluate(ModelReader(pmml_path))
        .collect()
    )
    env.execute(timeout=60.0)

    preds = sink.items
    print(f"scored {len(preds)} records")
    for i in (0, 1, 7):
        pred, vec = preds[i]
        if pred.is_empty:
            print(f"  record {i}: EMPTY (dirty input)")
        else:
            probs = {k: round(v, 3) for k, v in pred.target.probabilities.items()}
            print(f"  record {i}: {pred.target.label} {probs}")

    snap = env.metrics.snapshot()
    print(
        "metrics: records/s={:.0f} p50={:.2f}ms p99={:.2f}ms batches={:.0f}".format(
            snap["records_out_per_s"],
            snap.get("record_latency_s_p50", 0) * 1e3,
            snap.get("record_latency_s_p99", 0) * 1e3,
            snap["batches"],
        )
    )


if __name__ == "__main__":
    main()
