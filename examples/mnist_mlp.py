"""Example: MNIST-shaped MLP NeuralNetwork scoring (BASELINE config 3).

A 784→256→10 NeuralNetwork PMML lowers to a bf16-friendly matmul chain on
the MXU (compile/neural.py); the stream carries dense pixel vectors. The
reference would walk JPMML's per-record neuron graph on the CPU.

Run:  python examples/mnist_mlp.py [--platform cpu]
"""

import pathlib
import sys
import tempfile

try:  # installed package (pip install -e .)
    import flink_jpmml_tpu  # noqa: F401
except ImportError:  # source checkout without install: add the repo root
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import numpy as np

from flink_jpmml_tpu.utils.demo import demo_backend
from flink_jpmml_tpu.assets_gen import gen_mlp
from flink_jpmml_tpu.api import ModelReader, StreamEnvironment
from flink_jpmml_tpu.utils.config import BatchConfig, RuntimeConfig


def main() -> None:
    print(f"backend: {demo_backend()}")
    workdir = tempfile.mkdtemp(prefix="fjt-mlp-")
    pmml = gen_mlp(workdir, n_inputs=784, hidden=(256,), n_classes=10)
    print(f"model: {pmml}")

    rng = np.random.default_rng(0)
    images = rng.uniform(0.0, 1.0, size=(512, 784)).astype(np.float32).tolist()

    env = StreamEnvironment(
        RuntimeConfig(batch=BatchConfig(size=256, deadline_us=2000))
    )
    sink = (
        env.from_collection(images)
        .quick_evaluate(ModelReader(pmml))
        .collect()
    )
    env.execute(timeout=120.0)

    preds = [p for p, _vec in sink.items]
    by_digit = {}
    for p in preds:
        by_digit[p.target.label] = by_digit.get(p.target.label, 0) + 1
    print(f"scored {len(preds)} images; class histogram: {by_digit}")
    top = preds[0]
    print(f"first image → digit {top.target.label} "
          f"(p={top.score.value:.3f})")


if __name__ == "__main__":
    main()
