"""Example: every supported PMML model family through one streaming run.

Generates a small document per family (the shapes real exporters emit —
R glm/multinom, sklearn IsolationForest, libsvm, credit scorecards…),
streams a batch of records through the runtime against each, and prints
a one-line summary per family. This is the "switching user" tour: the
reference scored any JPMML-supported model class; so does this framework.

Run:  python examples/model_zoo.py [--platform cpu]   (or on the TPU)
"""

import pathlib
import sys
import tempfile

try:  # installed package (pip install -e .)
    import flink_jpmml_tpu  # noqa: F401
except ImportError:  # source checkout without install: add the repo root
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import numpy as np

from flink_jpmml_tpu.utils.demo import demo_backend
from flink_jpmml_tpu.api import ModelReader, StreamEnvironment
from flink_jpmml_tpu.assets_gen import (
    gen_gbm,
    gen_iris_lr,
    gen_kmeans,
    gen_mlp,
    gen_stacked,
)
from flink_jpmml_tpu.utils.config import BatchConfig, RuntimeConfig

SCORECARD = """<PMML version="4.3"><DataDictionary>
  <DataField name="f0" optype="continuous" dataType="double"/>
  <DataField name="f1" optype="continuous" dataType="double"/>
  <DataField name="s" optype="continuous" dataType="double"/>
  </DataDictionary>
  <Scorecard functionName="regression" initialScore="500"
      useReasonCodes="true" baselineScore="30">
  <MiningSchema><MiningField name="s" usageType="target"/>
    <MiningField name="f0"/><MiningField name="f1"/></MiningSchema>
  <Output><OutputField name="rc" feature="reasonCode" rank="1"/></Output>
  <Characteristics>
    <Characteristic name="c0" reasonCode="F0_LOW">
      <Attribute partialScore="50"><SimplePredicate field="f0"
        operator="greaterThan" value="0"/></Attribute>
      <Attribute partialScore="-20"><True/></Attribute>
    </Characteristic>
    <Characteristic name="c1" reasonCode="F1_HIGH">
      <Attribute partialScore="35"><SimplePredicate field="f1"
        operator="lessThan" value="1"/></Attribute>
      <Attribute partialScore="-10"><True/></Attribute>
    </Characteristic>
  </Characteristics></Scorecard></PMML>"""

RULESET = """<PMML version="4.3"><DataDictionary>
  <DataField name="f0" optype="continuous" dataType="double"/>
  <DataField name="f1" optype="continuous" dataType="double"/>
  <DataField name="cls" optype="categorical" dataType="string">
    <Value value="accept"/><Value value="review"/><Value value="reject"/>
  </DataField></DataDictionary>
  <RuleSetModel functionName="classification">
  <MiningSchema><MiningField name="cls" usageType="target"/>
    <MiningField name="f0"/><MiningField name="f1"/></MiningSchema>
  <RuleSet defaultScore="review" defaultConfidence="0.5">
    <RuleSelectionMethod criterion="firstHit"/>
    <SimpleRule score="reject" confidence="0.95">
      <CompoundPredicate booleanOperator="and">
        <SimplePredicate field="f0" operator="lessThan" value="-1"/>
        <SimplePredicate field="f1" operator="lessThan" value="0"/>
      </CompoundPredicate></SimpleRule>
    <SimpleRule score="accept" confidence="0.9">
      <SimplePredicate field="f0" operator="greaterThan" value="0.5"/>
    </SimpleRule>
  </RuleSet></RuleSetModel></PMML>"""

GLM = """<PMML version="4.3"><DataDictionary>
  <DataField name="f0" optype="continuous" dataType="double"/>
  <DataField name="f1" optype="continuous" dataType="double"/>
  <DataField name="y" optype="continuous" dataType="double"/>
  </DataDictionary>
  <GeneralRegressionModel functionName="regression"
      modelType="generalizedLinear" linkFunction="logit">
  <MiningSchema><MiningField name="y" usageType="target"/>
    <MiningField name="f0"/><MiningField name="f1"/></MiningSchema>
  <ParameterList><Parameter name="p0"/><Parameter name="p1"/>
    <Parameter name="p2"/></ParameterList>
  <CovariateList><Predictor name="f0"/><Predictor name="f1"/>
  </CovariateList>
  <PPMatrix>
    <PPCell value="1" predictorName="f0" parameterName="p1"/>
    <PPCell value="2" predictorName="f1" parameterName="p2"/>
  </PPMatrix>
  <ParamMatrix>
    <PCell parameterName="p0" beta="-0.3"/>
    <PCell parameterName="p1" beta="1.2"/>
    <PCell parameterName="p2" beta="-0.4"/>
  </ParamMatrix></GeneralRegressionModel></PMML>"""

NAIVE_BAYES = """<PMML version="4.3"><DataDictionary>
  <DataField name="f0" optype="continuous" dataType="double"/>
  <DataField name="f1" optype="continuous" dataType="double"/>
  <DataField name="cls" optype="categorical" dataType="string">
    <Value value="pos"/><Value value="neg"/></DataField>
  </DataDictionary>
  <NaiveBayesModel functionName="classification" threshold="0.001">
  <MiningSchema><MiningField name="cls" usageType="target"/>
    <MiningField name="f0"/><MiningField name="f1"/></MiningSchema>
  <BayesInputs>
    <BayesInput fieldName="f0"><TargetValueStats>
      <TargetValueStat value="pos"><GaussianDistribution mean="1.0"
        variance="1.0"/></TargetValueStat>
      <TargetValueStat value="neg"><GaussianDistribution mean="-1.0"
        variance="1.5"/></TargetValueStat>
    </TargetValueStats></BayesInput>
    <BayesInput fieldName="f1"><TargetValueStats>
      <TargetValueStat value="pos"><GaussianDistribution mean="0.0"
        variance="2.0"/></TargetValueStat>
      <TargetValueStat value="neg"><GaussianDistribution mean="0.5"
        variance="1.0"/></TargetValueStat>
    </TargetValueStats></BayesInput>
  </BayesInputs>
  <BayesOutput fieldName="cls"><TargetValueCounts>
    <TargetValueCount value="pos" count="60"/>
    <TargetValueCount value="neg" count="40"/>
  </TargetValueCounts></BayesOutput></NaiveBayesModel></PMML>"""

SVM = """<PMML version="4.3"><DataDictionary>
  <DataField name="f0" optype="continuous" dataType="double"/>
  <DataField name="f1" optype="continuous" dataType="double"/>
  <DataField name="cls" optype="categorical" dataType="string">
    <Value value="in"/><Value value="out"/></DataField>
  </DataDictionary>
  <SupportVectorMachineModel functionName="classification">
  <MiningSchema><MiningField name="cls" usageType="target"/>
    <MiningField name="f0"/><MiningField name="f1"/></MiningSchema>
  <RadialBasisKernelType gamma="0.8"/>
  <VectorDictionary numberOfVectors="2">
    <VectorFields numberOfFields="2">
      <FieldRef field="f0"/><FieldRef field="f1"/></VectorFields>
    <VectorInstance id="v1"><Array n="2" type="real">0 0</Array>
    </VectorInstance>
    <VectorInstance id="v2"><Array n="2" type="real">2 2</Array>
    </VectorInstance>
  </VectorDictionary>
  <SupportVectorMachine targetCategory="in" alternateTargetCategory="out">
    <SupportVectors numberOfSupportVectors="2">
      <SupportVector vectorId="v1"/><SupportVector vectorId="v2"/>
    </SupportVectors>
    <Coefficients absoluteValue="0.2">
      <Coefficient value="-1.0"/><Coefficient value="1.0"/>
    </Coefficients>
  </SupportVectorMachine>
  </SupportVectorMachineModel></PMML>"""

KNN = """<PMML version="4.3"><DataDictionary>
  <DataField name="f0" optype="continuous" dataType="double"/>
  <DataField name="f1" optype="continuous" dataType="double"/>
  <DataField name="cls" optype="categorical" dataType="string">
    <Value value="a"/><Value value="b"/></DataField>
  </DataDictionary>
  <NearestNeighborModel functionName="classification"
      numberOfNeighbors="3">
  <MiningSchema><MiningField name="cls" usageType="target"/>
    <MiningField name="f0"/><MiningField name="f1"/></MiningSchema>
  <ComparisonMeasure kind="distance"><euclidean/></ComparisonMeasure>
  <KNNInputs><KNNInput field="f0"/><KNNInput field="f1"/></KNNInputs>
  <TrainingInstances>
    <InstanceFields>
      <InstanceField field="f0" column="f0"/>
      <InstanceField field="f1" column="f1"/>
      <InstanceField field="cls" column="cls"/>
    </InstanceFields>
    <InlineTable>
      <row><f0>0</f0><f1>0</f1><cls>a</cls></row>
      <row><f0>0.5</f0><f1>0.5</f1><cls>a</cls></row>
      <row><f0>2</f0><f1>2</f1><cls>b</cls></row>
      <row><f0>2.5</f0><f1>1.5</f1><cls>b</cls></row>
      <row><f0>-1</f0><f1>2</f1><cls>b</cls></row>
    </InlineTable>
  </TrainingInstances></NearestNeighborModel></PMML>"""

IFOREST = """<PMML version="4.4"><DataDictionary>
  <DataField name="f0" optype="continuous" dataType="double"/>
  <DataField name="f1" optype="continuous" dataType="double"/>
  <DataField name="s" optype="continuous" dataType="double"/>
  </DataDictionary>
  <AnomalyDetectionModel functionName="regression"
      algorithmType="iforest" sampleDataSize="128">
  <MiningSchema><MiningField name="s" usageType="target"/>
    <MiningField name="f0"/><MiningField name="f1"/></MiningSchema>
  <MiningModel functionName="regression">
    <MiningSchema><MiningField name="s" usageType="target"/>
      <MiningField name="f0"/><MiningField name="f1"/></MiningSchema>
    <Segmentation multipleModelMethod="average">
      <Segment><True/><TreeModel functionName="regression">
        <MiningSchema><MiningField name="s" usageType="target"/>
          <MiningField name="f0"/><MiningField name="f1"/></MiningSchema>
        <Node id="0"><True/>
          <Node id="1" score="2"><SimplePredicate field="f0"
            operator="greaterThan" value="2"/></Node>
          <Node id="2" score="7"><True/></Node>
        </Node></TreeModel></Segment>
      <Segment><True/><TreeModel functionName="regression">
        <MiningSchema><MiningField name="s" usageType="target"/>
          <MiningField name="f0"/><MiningField name="f1"/></MiningSchema>
        <Node id="0"><True/>
          <Node id="1" score="3"><SimplePredicate field="f1"
            operator="lessThan" value="-2"/></Node>
          <Node id="2" score="6"><True/></Node>
        </Node></TreeModel></Segment>
    </Segmentation></MiningModel>
  </AnomalyDetectionModel></PMML>"""


GP = """<PMML version="4.3"><DataDictionary>
  <DataField name="f0" optype="continuous" dataType="double"/>
  <DataField name="f1" optype="continuous" dataType="double"/>
  <DataField name="y" optype="continuous" dataType="double"/>
  </DataDictionary>
  <GaussianProcessModel functionName="regression">
  <MiningSchema><MiningField name="y" usageType="target"/>
    <MiningField name="f0"/><MiningField name="f1"/></MiningSchema>
  <RadialBasisKernel gamma="1.0" noiseVariance="0.1" lambda="1.0"/>
  <TrainingInstances recordCount="3">
    <InstanceFields>
      <InstanceField field="f0" column="f0"/>
      <InstanceField field="f1" column="f1"/>
      <InstanceField field="y" column="y"/>
    </InstanceFields>
    <InlineTable>
      <row><f0>0</f0><f1>0</f1><y>1.0</y></row>
      <row><f0>1</f0><f1>1</f1><y>-0.5</y></row>
      <row><f0>-1</f0><f1>0.5</f1><y>2.0</y></row>
    </InlineTable>
  </TrainingInstances></GaussianProcessModel></PMML>"""

BASELINE_Z = """<PMML version="4.2"><DataDictionary>
  <DataField name="f0" optype="continuous" dataType="double"/>
  </DataDictionary>
  <BaselineModel functionName="regression">
  <MiningSchema><MiningField name="f0"/></MiningSchema>
  <TestDistributions field="f0" testStatistic="zValue">
    <Baseline><GaussianDistribution mean="0.5" variance="1.44"/></Baseline>
  </TestDistributions></BaselineModel></PMML>"""

ASSOC = """<PMML version="4.2"><DataDictionary>
  <DataField name="beer" optype="continuous" dataType="double"/>
  <DataField name="chips" optype="continuous" dataType="double"/>
  <DataField name="wine" optype="continuous" dataType="double"/>
  <DataField name="bread" optype="continuous" dataType="double"/>
  </DataDictionary>
  <AssociationModel functionName="associationRules"
      numberOfTransactions="1000" numberOfItems="4"
      minimumSupport="0.1" minimumConfidence="0.5"
      numberOfItemsets="4" numberOfRules="2">
  <MiningSchema>
    <MiningField name="beer"/><MiningField name="chips"/>
    <MiningField name="wine"/><MiningField name="bread"/>
  </MiningSchema>
  <Item id="1" value="beer"/><Item id="2" value="chips"/>
  <Item id="3" value="wine"/><Item id="4" value="bread"/>
  <Itemset id="s1"><ItemRef itemRef="1"/></Itemset>
  <Itemset id="s2"><ItemRef itemRef="2"/></Itemset>
  <Itemset id="s3"><ItemRef itemRef="3"/></Itemset>
  <Itemset id="s4"><ItemRef itemRef="4"/></Itemset>
  <AssociationRule id="r1" support="0.4" confidence="0.7"
      antecedent="s1" consequent="s2"/>
  <AssociationRule id="r2" support="0.3" confidence="0.8"
      antecedent="s3" consequent="s4"/>
  </AssociationModel></PMML>"""


TIMESERIES = """<PMML version="4.3"><DataDictionary>
  <DataField name="h" optype="continuous" dataType="integer"/>
  <DataField name="sales" optype="continuous" dataType="double"/>
  </DataDictionary>
  <TimeSeriesModel functionName="timeSeries" bestFit="ExponentialSmoothing">
  <MiningSchema><MiningField name="sales" usageType="target"/>
    <MiningField name="h"/></MiningSchema>
  <ExponentialSmoothing>
    <Level alpha="0.3" smoothedValue="120.5"/>
    <Trend_ExpoSmooth trend="damped_additive" gamma="0.1" smoothedValue="2.5"
        phi="0.85"/>
    <Seasonality_ExpoSmooth type="multiplicative" period="4" gamma="0.2">
      <Array n="4" type="real">1.1 0.9 1.05 0.95</Array>
    </Seasonality_ExpoSmooth>
  </ExponentialSmoothing></TimeSeriesModel></PMML>"""

# seasonal ARIMA(1,1,1)(0,1,0)_4 with drift over a short quarterly series
ARIMA = """<PMML version="4.4"><DataDictionary>
  <DataField name="h" optype="continuous" dataType="integer"/>
  <DataField name="demand" optype="continuous" dataType="double"/>
  </DataDictionary>
  <TimeSeriesModel functionName="timeSeries" bestFit="ARIMA">
  <MiningSchema><MiningField name="demand" usageType="target"/>
    <MiningField name="h"/></MiningSchema>
  <TimeSeries usage="original">
    <TimeValue index="1" value="52.1"/><TimeValue index="2" value="47.3"/>
    <TimeValue index="3" value="55.8"/><TimeValue index="4" value="60.2"/>
    <TimeValue index="5" value="54.6"/><TimeValue index="6" value="49.9"/>
    <TimeValue index="7" value="58.4"/><TimeValue index="8" value="63.0"/>
    <TimeValue index="9" value="57.2"/><TimeValue index="10" value="52.4"/>
    <TimeValue index="11" value="61.1"/><TimeValue index="12" value="65.7"/>
  </TimeSeries>
  <ARIMA constantTerm="0.1" predictionMethod="conditionalLeastSquares">
    <NonseasonalComponent p="1" d="1" q="1">
      <AR><Array type="real" n="1">0.4</Array></AR>
      <MA>
        <MACoefficients><Array type="real" n="1">0.3</Array>
        </MACoefficients>
        <Residuals><Array type="real" n="1">0.25</Array></Residuals>
      </MA>
    </NonseasonalComponent>
    <SeasonalComponent P="0" D="1" Q="0" period="4"/>
  </ARIMA></TimeSeriesModel></PMML>"""

BAYESNET = """<PMML version="4.3"><DataDictionary>
  <DataField name="rain" optype="categorical" dataType="string">
    <Value value="yes"/><Value value="no"/></DataField>
  <DataField name="sprinkler" optype="categorical" dataType="string">
    <Value value="on"/><Value value="off"/></DataField>
  <DataField name="grass" optype="categorical" dataType="string">
    <Value value="wet"/><Value value="dry"/></DataField>
  </DataDictionary>
  <BayesianNetworkModel functionName="classification">
  <MiningSchema><MiningField name="rain" usageType="target"/>
    <MiningField name="sprinkler"/><MiningField name="grass"/></MiningSchema>
  <BayesianNetworkNodes>
    <DiscreteNode name="rain">
      <ValueProbability value="yes" probability="0.2"/>
      <ValueProbability value="no" probability="0.8"/>
    </DiscreteNode>
    <DiscreteNode name="sprinkler">
      <DiscreteConditionalProbability>
        <ParentValue parent="rain" value="yes"/>
        <ValueProbability value="on" probability="0.01"/>
        <ValueProbability value="off" probability="0.99"/>
      </DiscreteConditionalProbability>
      <DiscreteConditionalProbability>
        <ParentValue parent="rain" value="no"/>
        <ValueProbability value="on" probability="0.4"/>
        <ValueProbability value="off" probability="0.6"/>
      </DiscreteConditionalProbability>
    </DiscreteNode>
    <DiscreteNode name="grass">
      <DiscreteConditionalProbability>
        <ParentValue parent="sprinkler" value="on"/>
        <ParentValue parent="rain" value="yes"/>
        <ValueProbability value="wet" probability="0.99"/>
        <ValueProbability value="dry" probability="0.01"/>
      </DiscreteConditionalProbability>
      <DiscreteConditionalProbability>
        <ParentValue parent="sprinkler" value="on"/>
        <ParentValue parent="rain" value="no"/>
        <ValueProbability value="wet" probability="0.9"/>
        <ValueProbability value="dry" probability="0.1"/>
      </DiscreteConditionalProbability>
      <DiscreteConditionalProbability>
        <ParentValue parent="sprinkler" value="off"/>
        <ParentValue parent="rain" value="yes"/>
        <ValueProbability value="wet" probability="0.8"/>
        <ValueProbability value="dry" probability="0.2"/>
      </DiscreteConditionalProbability>
      <DiscreteConditionalProbability>
        <ParentValue parent="sprinkler" value="off"/>
        <ParentValue parent="rain" value="no"/>
        <ValueProbability value="wet" probability="0.0"/>
        <ValueProbability value="dry" probability="1.0"/>
      </DiscreteConditionalProbability>
    </DiscreteNode>
  </BayesianNetworkNodes></BayesianNetworkModel></PMML>"""

TEXTMODEL = """<PMML version="4.2"><DataDictionary>
  <DataField name="ball" optype="continuous" dataType="double"/>
  <DataField name="goal" optype="continuous" dataType="double"/>
  <DataField name="oven" optype="continuous" dataType="double"/>
  <DataField name="salt" optype="continuous" dataType="double"/>
  </DataDictionary>
  <TextModel functionName="classification" numberOfTerms="4"
      numberOfDocuments="2">
  <MiningSchema>
    <MiningField name="ball"/><MiningField name="goal"/>
    <MiningField name="oven"/><MiningField name="salt"/>
  </MiningSchema>
  <TextDictionary><Array n="4" type="string">ball goal oven salt</Array>
  </TextDictionary>
  <TextCorpus><TextDocument id="sports"/><TextDocument id="cooking"/>
  </TextCorpus>
  <DocumentTermMatrix><Matrix>
    <Array n="4" type="real">5 3 0 0</Array>
    <Array n="4" type="real">0 0 4 6</Array>
  </Matrix></DocumentTermMatrix>
  <TextModelNormalization localTermWeights="logarithmic"
      globalTermWeights="none" documentNormalization="cosine"/>
  <TextModelSimilarity similarityType="cosine"/>
  </TextModel></PMML>"""


def main() -> None:
    print(f"backend: {demo_backend()}")
    workdir = tempfile.mkdtemp(prefix="fjt-zoo-")
    rng = np.random.default_rng(7)

    docs = [
        ("RegressionModel (Iris LR)", gen_iris_lr(workdir), 4),
        ("TreeModel ensemble (GBM)",
         gen_gbm(workdir, n_trees=30, depth=4, n_features=6), 6),
        ("NeuralNetwork (MLP)",
         gen_mlp(workdir, n_inputs=16, hidden=(16,), n_classes=3), 16),
        ("ClusteringModel (KMeans)",
         gen_kmeans(workdir, k=3, n_features=4), 4),
        ("MiningModel modelChain (stacked)",
         gen_stacked(workdir, n_features=8, n_trees=10), 8),
    ]
    inline = [
        ("Scorecard (+reason codes)", SCORECARD, 2),
        ("RuleSetModel", RULESET, 2),
        ("GeneralRegressionModel (GLM)", GLM, 2),
        ("NaiveBayesModel", NAIVE_BAYES, 2),
        ("SupportVectorMachineModel", SVM, 2),
        ("NearestNeighborModel (KNN)", KNN, 2),
        ("AnomalyDetectionModel (iforest)", IFOREST, 2),
        ("GaussianProcessModel (RBF)", GP, 2),
        ("BaselineModel (zValue)", BASELINE_Z, 1),
        ("AssociationModel (baskets)", ASSOC, 4),
        ("TimeSeriesModel (Holt-Winters)", TIMESERIES, 1),
        ("TimeSeriesModel (seasonal ARIMA)", ARIMA, 1),
        ("BayesianNetworkModel (sprinkler)", BAYESNET, 2),
        ("TextModel (tf-idf cosine)", TEXTMODEL, 4),
    ]
    for i, (name, xml, arity) in enumerate(inline):
        path = str(pathlib.Path(workdir, f"zoo_{i}.pmml"))
        pathlib.Path(path).write_text(xml)
        docs.append((name, path, arity))

    print(f"{'family':38s} {'records':>7s}  sample result")
    for name, path, arity in docs:
        env = StreamEnvironment(
            RuntimeConfig(batch=BatchConfig(size=32, deadline_us=2000))
        )
        if "Bayesian" in name:
            # categorical inputs ride the dense path as value CODES
            vectors = rng.integers(0, 2, size=(64, arity)).astype(
                np.float32
            ).tolist()
        else:
            vectors = rng.normal(0.5, 1.2, size=(64, arity)).astype(
                np.float32
            ).tolist()
        sink = env.from_collection(vectors).evaluate(
            ModelReader(path)
        ).collect()
        env.execute(timeout=120.0)
        p = next((x for x in sink.items if not x.is_empty), None)
        if p is None:
            desc = "all lanes empty?!"
        elif p.target is not None and p.target.label is not None:
            desc = f"label={p.target.label}"
            if p.outputs:
                desc += f" outputs={p.outputs}"
        else:
            desc = f"value={p.score.value:.4f}"
            if p.outputs:
                desc += f" outputs={p.outputs}"
        print(f"{name:38s} {len(sink.items):7d}  {desc}")


if __name__ == "__main__":
    main()
