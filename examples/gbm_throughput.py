"""Example: 500-tree GBM scored over a tabular stream (BASELINE config 2).

The north-star workload: a histogram-trained gradient-boosted ensemble
scoring a high-rate feature stream. The reference runs JPMML-Evaluator's
per-record tree walk inside a Flink flatMap (SURVEY.md §4.1 hot loop);
here the engine's StaticScorer picks the quantized rank wire
(compile/qtrees.py) automatically — each record crosses to the device as
32 uint8 threshold ranks and the whole micro-batch is scored by the
Pallas VMEM-resident kernel (TPU) or the int8 einsum path.

Run:  python examples/gbm_throughput.py  [--trees 500 --seconds 3]
bench.py is the measured version of this pipeline.
"""

import argparse
import pathlib
import sys
import tempfile
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import numpy as np

from assets.generate import gen_gbm
from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.pmml import parse_pmml_file
from flink_jpmml_tpu.runtime.engine import Pipeline, StaticScorer
from flink_jpmml_tpu.runtime.sinks import NullSink
from flink_jpmml_tpu.runtime.sources import InMemorySource
from flink_jpmml_tpu.utils.config import BatchConfig, RuntimeConfig


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trees", type=int, default=500)
    ap.add_argument("--features", type=int, default=32)
    ap.add_argument("--records", type=int, default=200_000)
    args = ap.parse_args()

    workdir = tempfile.mkdtemp(prefix="fjt-gbm-")
    pmml = gen_gbm(workdir, n_trees=args.trees, n_features=args.features)
    doc = parse_pmml_file(pmml)
    cm = compile_pmml(doc, batch_size=16384)
    q = cm.quantized_scorer()
    print(
        f"model: {args.trees} trees | rank wire: "
        f"{q.wire.bytes_per_record if q else 'n/a'} B/record | "
        f"kernel backend: {q.backend if q else 'f32'}"
    )

    scorer = StaticScorer(cm)
    rng = np.random.default_rng(0)
    block = [
        {f"f{j}": float(v) for j, v in enumerate(row)}
        for row in rng.normal(0.0, 1.5, size=(args.records, args.features))
    ]
    source = InMemorySource(block)
    sink = NullSink()
    pipe = Pipeline(
        source,
        scorer,
        sink,
        RuntimeConfig(batch=BatchConfig(size=16384, deadline_us=5000)),
    )
    t0 = time.perf_counter()
    pipe.run_until_exhausted(timeout=600.0)
    dt = time.perf_counter() - t0
    snap = pipe.metrics.snapshot()
    print(f"scored {sink.count} records in {dt:.2f}s "
          f"({sink.count / dt:,.0f} rec/s through the full pipeline)")
    print(f"metrics: {snap}")


if __name__ == "__main__":
    main()
