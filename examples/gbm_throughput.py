"""Example: 500-tree GBM scored over a tabular stream (BASELINE config 2).

The north-star workload: a histogram-trained gradient-boosted ensemble
scoring a high-rate feature stream. The reference runs JPMML-Evaluator's
per-record tree walk inside a Flink flatMap (SURVEY.md §4.1 hot loop);
here the *production* BlockPipeline drives the quantized rank wire
(compile/qtrees.py) end to end — f32 blocks flow through the C++ ring,
are encoded to uint8 threshold ranks by the multithreaded bucketizer, and
the whole micro-batch is scored by the Pallas VMEM-resident kernel (TPU)
or the int8 einsum path. No Python object per record exists anywhere.

Run:  python examples/gbm_throughput.py [--platform cpu] [--kafka]  [--trees 500 --seconds 3]
bench.py is the driver-measured version of this same pipeline shape.
"""

import argparse
import pathlib
import sys
import tempfile
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import numpy as np

from flink_jpmml_tpu.utils.demo import demo_backend
from flink_jpmml_tpu.assets_gen import gen_gbm
from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.pmml import parse_pmml_file
from flink_jpmml_tpu.runtime.block import BlockPipeline, CyclingBlockSource
from flink_jpmml_tpu.utils.config import BatchConfig, RuntimeConfig


def main() -> None:
    print(f"backend: {demo_backend()}")
    ap = argparse.ArgumentParser()
    ap.add_argument("--trees", type=int, default=500)
    ap.add_argument("--features", type=int, default=32)
    ap.add_argument("--batch", type=int, default=16384)
    ap.add_argument("--seconds", type=float, default=3.0)
    ap.add_argument("--kafka", action="store_true",
                    help="stream through the real Kafka wire protocol "
                         "(in-process broker + C++ record-batch decode) "
                         "instead of the in-memory source")
    args = ap.parse_args()

    workdir = tempfile.mkdtemp(prefix="fjt-gbm-")
    pmml = gen_gbm(workdir, n_trees=args.trees, n_features=args.features)
    doc = parse_pmml_file(pmml)
    cm = compile_pmml(doc, batch_size=args.batch)
    q = cm.quantized_scorer()
    print(
        f"model: {args.trees} trees | rank wire: "
        f"{q.wire.bytes_per_record if q else 'n/a'} B/record | "
        f"kernel backend: {q.backend if q else 'f32'}"
    )

    rng = np.random.default_rng(0)
    data = rng.normal(0.0, 1.5, size=(4 * args.batch, args.features)).astype(
        np.float32
    )
    count = [0]

    def sink(out, n, first_off):
        # force the D2H round trip so the printed rate counts *completed*
        # scoring, not async dispatches still queued on the device
        np.asarray(out.value if hasattr(out, "value") else
                   out[0] if isinstance(out, tuple) else out)
        count[0] += n

    broker = None
    if args.kafka:
        from flink_jpmml_tpu.runtime.kafka import (
            KafkaBlockSource, MiniKafkaBroker,
        )

        broker = MiniKafkaBroker(topic="gbm")
        broker.append_rows(data)
        hw = broker.high_watermark

        class _Cycling(KafkaBlockSource):
            def poll(self):
                if self._next >= hw:
                    self.seek(0)
                return super().poll()

        source = _Cycling(
            broker.host, broker.port, "gbm",
            n_cols=args.features, max_wait_ms=20,
        )
        print(f"kafka broker on {broker.host}:{broker.port}, "
              f"{hw} records cycling")
    else:
        source = CyclingBlockSource(data, block_size=args.batch)
    pipe = BlockPipeline(
        source,
        cm,
        sink,
        RuntimeConfig(batch=BatchConfig(size=args.batch, deadline_us=5000)),
    )
    print(f"pipeline backend: {pipe.backend} | native ring: {pipe.native}")
    if q is not None:
        # one warm dispatch so jit compile stays outside the timed window
        import jax

        jax.block_until_ready(q.predict_wire(q.wire.encode(data[: args.batch])))
    else:
        cm.warmup()
    try:
        t0 = time.perf_counter()
        pipe.run_for(seconds=args.seconds)
        dt = time.perf_counter() - t0
        snap = pipe.metrics.snapshot()
        print(f"scored {count[0]:,} records in {dt:.2f}s "
              f"({count[0] / dt:,.0f} rec/s through the full block pipeline)")
        print(f"metrics: {snap}")
    finally:
        if broker is not None:
            source.close()
            broker.close()


if __name__ == "__main__":
    main()
