"""Benchmark: 500-tree GBM scoring throughput on one TPU chip.

BASELINE config 2 / north star: "score a 500-tree GBM PMML over a stream at
>= 1M records/sec with no CPU evaluator in the hot path". The reference
(flink-jpmml) walks every tree per record on the CPU inside
JPMML-Evaluator; here the whole micro-batch is three einsums on the MXU.

Measured: steady-state records/sec through the scoring hot path — fresh
host batches each iteration (host->device transfer included), jitted
ensemble scoring, validity decode back on the host (device->host included),
with a 2-deep in-flight window exactly like the streaming runtime. Compile
and warmup excluded.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
vs_baseline is the ratio against the 1M rec/s north-star target
(the reference publishes no numbers of its own - BASELINE.md).
"""

import argparse
import json
import os
import pathlib
import sys
import tempfile
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))

NORTH_STAR_REC_S = 1_000_000.0


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trees", type=int, default=500)
    ap.add_argument("--depth", type=int, default=6)
    ap.add_argument("--features", type=int, default=32)
    ap.add_argument("--batch", type=int, default=8192)
    ap.add_argument("--seconds", type=float, default=3.0)
    args = ap.parse_args()

    import jax
    import numpy as np

    from assets.generate import gen_gbm
    from flink_jpmml_tpu.compile import compile_pmml
    from flink_jpmml_tpu.pmml import parse_pmml_file

    cache_dir = os.path.join(
        tempfile.gettempdir(),
        f"fjt-bench-{args.trees}x{args.depth}x{args.features}",
    )
    os.makedirs(cache_dir, exist_ok=True)
    pmml = os.path.join(cache_dir, f"gbm_{args.trees}.pmml")
    if not os.path.exists(pmml):
        gen_gbm(
            cache_dir,
            n_trees=args.trees,
            depth=args.depth,
            n_features=args.features,
        )

    cm = compile_pmml(parse_pmml_file(pmml), batch_size=args.batch)

    rng = np.random.default_rng(0)
    n_buf = 8  # rotate pre-built host batches (fresh arrays, no caching)
    host_batches = [
        rng.normal(0, 1, size=(args.batch, args.features)).astype(np.float32)
        for _ in range(n_buf)
    ]
    M = np.zeros((args.batch, args.features), bool)

    def run_once(i):
        out = cm.predict(host_batches[i % n_buf], M)  # async dispatch
        return out

    # warmup: compile + stabilize
    for i in range(3):
        jax.block_until_ready(run_once(i))

    # timed: 2-deep in-flight window, decode validity on the host each batch
    in_flight = []
    n_batches = 0
    t0 = time.perf_counter()
    deadline = t0 + args.seconds
    i = 0
    while time.perf_counter() < deadline or n_batches < 10:
        in_flight.append(run_once(i))
        i += 1
        if len(in_flight) >= 2:
            out = in_flight.pop(0)
            _ = np.asarray(out.valid)  # device->host sync + decode input
            n_batches += 1
        if n_batches >= 10 and time.perf_counter() >= deadline:
            break
    while in_flight:
        out = in_flight.pop(0)
        _ = np.asarray(out.valid)
        n_batches += 1
    dt = time.perf_counter() - t0

    rec_s = n_batches * args.batch / dt
    print(
        json.dumps(
            {
                "metric": f"gbm{args.trees}_records_per_sec_per_chip",
                "value": round(rec_s, 1),
                "unit": "records/s/chip",
                "vs_baseline": round(rec_s / NORTH_STAR_REC_S, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
