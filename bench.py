"""Benchmark: 500-tree GBM scoring throughput on one TPU chip.

BASELINE config 2 / north star: "score a 500-tree GBM PMML over a stream at
>= 1M records/sec with no CPU evaluator in the hot path". The reference
(flink-jpmml) walks every tree per record on the CPU inside
JPMML-Evaluator; here scoring is three int8/bf16 einsums on the MXU and the
stream crosses the host↔device link as per-feature threshold *ranks*
(uint8 — the rank wire of compile/qtrees.py, bit-exact with f32 scoring),
so a 32-feature record costs 32 bytes in and 2 bytes (bf16 score) out.

Measured: the full streaming pipeline in steady state —
  host featurize (f32 → rank codes, thread pool, standing in for the C++
  ingest plane) → host→device transfer → jitted ensemble scoring →
  device→host score readback — with a bounded in-flight window exactly
  like the streaming runtime. Compile and warmup excluded. Every score
  batch is materialized on the host before it counts.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}
vs_baseline is the ratio against the 1M rec/s north-star target
(the reference publishes no numbers of its own - BASELINE.md). The line
also carries "device_value" — the pure device-side scoring rate with the
batch already resident — and "backend". When the TPU backend cannot be
initialized within the bounded probe (retries with hard per-attempt
timeouts), the bench falls back to the CPU backend at diagnostic scale and
still prints a capture with "backend": "cpu-fallback" and an "error" field
describing the TPU failure (exit 0 — a labelled number beats an empty
artifact, which is what round 1 recorded). Only a wedged in-process init
after a *successful* probe produces "value": 0 + non-zero exit, via the
watchdog, and that too within a bounded time.
"""

import argparse
import collections
import json
import os
import pathlib
import subprocess
import sys
import tempfile
import threading
import time
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))

NORTH_STAR_REC_S = 1_000_000.0


def _fail_line(metric: str, error: str) -> None:
    print(json.dumps({
        "metric": metric,
        "value": 0.0,
        "unit": "records/s/chip",
        "vs_baseline": 0.0,
        "error": error,
    }), flush=True)


def probe_backend(attempts: int, timeout_s: float):
    """Bounded out-of-process backend probe, retried with backoff.

    A wedged PJRT init cannot be interrupted from inside the process, so
    the probe runs ``jax.default_backend()`` in a child with a hard
    timeout. Returns ``(backend_name, None)`` on success or
    ``(None, error)`` once every attempt has failed — the caller then
    falls back to a clearly-labelled CPU capture rather than recording
    nothing (the round-1 BENCH artifact was rc=1 with no number at all)."""
    err = "unknown"
    for k in range(attempts):
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.default_backend())"],
                capture_output=True, text=True, timeout=timeout_s,
            )
            if r.returncode == 0 and r.stdout.strip():
                return r.stdout.strip().splitlines()[-1], None
            err = (r.stderr or "backend probe failed").strip()[-500:]
        except subprocess.TimeoutExpired:
            err = f"backend init exceeded {timeout_s:.0f}s (attempt {k + 1})"
        if k + 1 < attempts:
            time.sleep(min(5.0 * (k + 1), 15.0))
    return None, f"backend unavailable after {attempts} attempts: {err}"


def arm_watchdog(metric: str, timeout_s: float) -> dict:
    """Belt to the probe's braces: if the *parent's* own backend init still
    wedges (tunnel raced between probe and init), emit the diagnostic line
    and hard-exit instead of hanging the driver."""
    state = {"ready": False}

    def run():
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout_s:
            if state["ready"]:
                return
            time.sleep(1.0)
        _fail_line(metric, f"in-process backend init wedged > {timeout_s:.0f}s")
        os._exit(1)

    threading.Thread(target=run, daemon=True).start()
    return state


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trees", type=int, default=500)
    ap.add_argument("--depth", type=int, default=6)
    ap.add_argument("--features", type=int, default=32)
    ap.add_argument("--batch", type=int, default=262144,
                    help="records per dispatch (scored in --chunk chunks)")
    ap.add_argument("--chunk", type=int, default=16384)
    ap.add_argument("--window", type=int, default=2,
                    help="batches in flight before blocking on readback")
    ap.add_argument("--seconds", type=float, default=4.0)
    ap.add_argument("--f32-wire", action="store_true",
                    help="ship raw f32 features instead of the rank wire")
    ap.add_argument("--probe-timeout", type=float, default=100.0,
                    help="per-attempt backend probe bound (seconds)")
    ap.add_argument("--probe-attempts", type=int, default=3)
    ap.add_argument("--block-pipeline", action="store_true",
                    help="measure through the production BlockPipeline "
                         "(ring + rank wire) instead of the hand loop — "
                         "the engine-vs-bench parity check")
    args = ap.parse_args()

    metric = f"gbm{args.trees}_records_per_sec_per_chip"
    backend, probe_err = probe_backend(args.probe_attempts, args.probe_timeout)
    watchdog = arm_watchdog(metric, 2.0 * args.probe_timeout)

    import jax
    import jax.numpy as jnp
    import numpy as np

    if backend is None:
        # TPU tunnel down: capture a CPU number, clearly labelled, instead
        # of an empty artifact. The env-var route is ignored by the axon
        # plugin in this image; the config API works (tests/conftest.py).
        jax.config.update("jax_platforms", "cpu")
        backend = "cpu-fallback"
    if backend.startswith("cpu"):
        # full-size dispatches would allocate GBs of einsum intermediates
        # on the CPU backend; shrink to a diagnostic-scale workload (also
        # when the machine simply has no TPU and the probe reported "cpu")
        args.chunk = min(args.chunk, 1024)
        args.batch = min(args.batch, 8 * args.chunk)
        args.seconds = min(args.seconds, 3.0)
    # keep the dispatch/chunk contract valid for any flag combination
    args.batch = max(args.chunk, (args.batch // args.chunk) * args.chunk)

    jax.devices()  # force backend init under the watchdog, not mid-compile
    watchdog["ready"] = True

    from assets.generate import gen_gbm
    from flink_jpmml_tpu.compile import compile_pmml
    from flink_jpmml_tpu.pmml import parse_pmml_file

    cache_dir = os.path.join(
        tempfile.gettempdir(),
        f"fjt-bench-{args.trees}x{args.depth}x{args.features}-h254",
    )
    os.makedirs(cache_dir, exist_ok=True)
    pmml = os.path.join(cache_dir, f"gbm_{args.trees}.pmml")
    if not os.path.exists(pmml):
        gen_gbm(
            cache_dir,
            n_trees=args.trees,
            depth=args.depth,
            n_features=args.features,
        )
    doc = parse_pmml_file(pmml)

    B, C, F = args.batch, args.chunk, args.features
    K = B // C  # batch was normalized to a multiple of chunk above

    rng = np.random.default_rng(0)
    pool_f32 = [
        rng.normal(0.0, 1.5, size=(B, F)).astype(np.float32) for _ in range(4)
    ]

    cm = compile_pmml(doc, batch_size=C)

    if args.block_pipeline:
        # the production path: f32 blocks → C++ ring → bucketizer →
        # quantized scoring → sink. Same model, same chunk size; reported
        # under the same metric so the two numbers are directly comparable.
        from flink_jpmml_tpu.runtime.block import (
            BlockPipeline, CyclingBlockSource,
        )
        from flink_jpmml_tpu.utils.config import BatchConfig, RuntimeConfig

        count = [0]

        def bsink(out, n, first_off):
            # force the D2H round trip so the rate counts *completed*
            # work, same as the hand loop — not async dispatches
            np.asarray(out.value if hasattr(out, "value") else
                       out[0] if isinstance(out, tuple) else out)
            count[0] += n

        pipe = BlockPipeline(
            CyclingBlockSource(np.concatenate(pool_f32), block_size=C),
            cm,
            bsink,
            RuntimeConfig(batch=BatchConfig(size=C, deadline_us=5000)),
            use_quantized=not args.f32_wire,
        )
        q = None if args.f32_wire else cm.quantized_scorer()
        if q is not None:
            jax.block_until_ready(
                q.predict_wire(q.wire.encode(pool_f32[0][:C]))
            )
        else:
            cm.warmup()
        t0 = time.perf_counter()
        pipe.run_for(seconds=args.seconds)
        dt = time.perf_counter() - t0
        rate = count[0] / dt
        line = {
            "metric": metric,
            "value": round(rate, 1),
            "unit": "records/s/chip",
            "vs_baseline": round(rate / NORTH_STAR_REC_S, 3),
            "device_value": None,  # keys uniform with the hand-loop line
            "backend": f"{backend}/{pipe.backend}",
        }
        if probe_err is not None:
            line["error"] = probe_err
        print(json.dumps(line))
        return

    if args.f32_wire:
        inner = getattr(cm._jit_fn, "__wrapped__", cm._jit_fn)
        params = cm.params

        @jax.jit
        def run(p, X):
            def body(c, x):
                out = inner(p, x, jnp.isnan(x))
                return c, out.value.astype(jnp.bfloat16)
            _, vals = jax.lax.scan(body, 0, X.reshape(K, C, F))
            return vals.reshape(-1)

        def encode(X):
            return X
    else:
        q = cm.quantized_scorer()
        assert q is not None, "bench GBM must be rank-wire eligible"
        qfn = getattr(q._jit_fn, "__wrapped__", q._jit_fn)
        params = q.params

        @jax.jit
        def run(p, Xq):
            def body(c, xq):
                return c, qfn(p, xq).astype(jnp.bfloat16)
            _, vals = jax.lax.scan(body, 0, Xq.reshape(K, C, F))
            return vals.reshape(-1)

        def encode(X):
            return q.wire.encode(X)

    # ---- pipeline: featurize (threads) → h2d → score → d2h readback ----
    enc_pool = ThreadPoolExecutor(max_workers=2)

    # warm: compile + first transfers (excluded from the measurement)
    warm = np.asarray(run(params, jax.device_put(encode(pool_f32[0]))))
    assert warm.shape == (B,) and np.isfinite(
        warm.astype(np.float32)
    ).all(), "warmup produced non-finite scores"

    PRE = args.window + 2  # encoded batches staged ahead of the transfer
    encoded = collections.deque(
        enc_pool.submit(encode, pool_f32[i % len(pool_f32)])
        for i in range(PRE)
    )
    inflight = collections.deque()
    done_records = 0
    i = 0
    t0 = time.perf_counter()
    deadline = t0 + args.seconds
    while True:
        now = time.perf_counter()
        if now >= deadline and not inflight:
            break
        if now < deadline:
            Xq = encoded.popleft().result()
            encoded.append(
                enc_pool.submit(encode, pool_f32[(i + PRE) % len(pool_f32)])
            )
            inflight.append(run(params, jax.device_put(Xq)))
            i += 1
        while len(inflight) > (args.window if now < deadline else 0):
            scores = np.asarray(inflight.popleft())  # forces the round trip
            done_records += scores.shape[0]
    dt = time.perf_counter() - t0
    enc_pool.shutdown(wait=False)
    rate = done_records / dt

    # pure device-side rate: batch already resident, no host link in the
    # loop — separates chip capability from the (possibly tunneled) link
    Xq_dev = jax.device_put(encode(pool_f32[0]))
    jax.block_until_ready(run(params, Xq_dev))
    reps = 0
    out = None
    t1 = time.perf_counter()
    dev_deadline = t1 + min(3.0, args.seconds)
    while time.perf_counter() < dev_deadline:
        out = run(params, Xq_dev)
        reps += 1
    jax.block_until_ready(out)
    dev_rate = reps * B / (time.perf_counter() - t1)

    line = {
        "metric": metric,
        "value": round(rate, 1),
        "unit": "records/s/chip",
        "vs_baseline": round(rate / NORTH_STAR_REC_S, 3),
        "device_value": round(dev_rate, 1),
        "backend": backend,
    }
    if probe_err is not None:
        line["error"] = probe_err
    print(json.dumps(line))


if __name__ == "__main__":
    main()
