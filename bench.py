#!/usr/bin/env python
"""Driver entry: one JSON line of benchmark capture (see
flink_jpmml_tpu/bench.py for the measurement itself; installed
deployments get the same via the ``fjt-bench`` console script)."""

from flink_jpmml_tpu.bench import main

if __name__ == "__main__":
    main()
