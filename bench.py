"""Benchmark: 500-tree GBM scoring throughput on one TPU chip.

BASELINE config 2 / north star: "score a 500-tree GBM PMML over a stream at
>= 1M records/sec with no CPU evaluator in the hot path". The reference
(flink-jpmml) walks every tree per record on the CPU inside
JPMML-Evaluator; here scoring is three int8/bf16 einsums on the MXU and the
stream crosses the host↔device link as per-feature threshold *ranks*
(uint8 — the rank wire of compile/qtrees.py, bit-exact with f32 scoring),
so a 32-feature record costs 32 bytes in and 2 bytes (bf16 score) out.

Measured: the full streaming pipeline in steady state —
  host featurize (f32 → rank codes, thread pool, standing in for the C++
  ingest plane) → host→device transfer → jitted ensemble scoring →
  device→host score readback — with a bounded in-flight window exactly
  like the streaming runtime. Compile and warmup excluded. Every score
  batch is materialized on the host before it counts.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
vs_baseline is the ratio against the 1M rec/s north-star target
(the reference publishes no numbers of its own - BASELINE.md).
"""

import argparse
import collections
import json
import os
import pathlib
import sys
import tempfile
import time
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))

NORTH_STAR_REC_S = 1_000_000.0


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trees", type=int, default=500)
    ap.add_argument("--depth", type=int, default=6)
    ap.add_argument("--features", type=int, default=32)
    ap.add_argument("--batch", type=int, default=262144,
                    help="records per dispatch (scored in --chunk chunks)")
    ap.add_argument("--chunk", type=int, default=16384)
    ap.add_argument("--window", type=int, default=2,
                    help="batches in flight before blocking on readback")
    ap.add_argument("--seconds", type=float, default=4.0)
    ap.add_argument("--f32-wire", action="store_true",
                    help="ship raw f32 features instead of the rank wire")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from assets.generate import gen_gbm
    from flink_jpmml_tpu.compile import compile_pmml
    from flink_jpmml_tpu.pmml import parse_pmml_file

    cache_dir = os.path.join(
        tempfile.gettempdir(),
        f"fjt-bench-{args.trees}x{args.depth}x{args.features}-h254",
    )
    os.makedirs(cache_dir, exist_ok=True)
    pmml = os.path.join(cache_dir, f"gbm_{args.trees}.pmml")
    if not os.path.exists(pmml):
        gen_gbm(
            cache_dir,
            n_trees=args.trees,
            depth=args.depth,
            n_features=args.features,
        )
    doc = parse_pmml_file(pmml)

    B, C, F = args.batch, args.chunk, args.features
    assert B % C == 0
    K = B // C

    rng = np.random.default_rng(0)
    pool_f32 = [
        rng.normal(0.0, 1.5, size=(B, F)).astype(np.float32) for _ in range(4)
    ]

    cm = compile_pmml(doc, batch_size=C)
    if args.f32_wire:
        inner = getattr(cm._jit_fn, "__wrapped__", cm._jit_fn)
        params = cm.params

        @jax.jit
        def run(p, X):
            def body(c, x):
                out = inner(p, x, jnp.isnan(x))
                return c, out.value.astype(jnp.bfloat16)
            _, vals = jax.lax.scan(body, 0, X.reshape(K, C, F))
            return vals.reshape(-1)

        def encode(X):
            return X
    else:
        q = cm.quantized_scorer()
        assert q is not None, "bench GBM must be rank-wire eligible"
        qfn = getattr(q._jit_fn, "__wrapped__", q._jit_fn)
        params = q.params

        @jax.jit
        def run(p, Xq):
            def body(c, xq):
                return c, qfn(p, xq).astype(jnp.bfloat16)
            _, vals = jax.lax.scan(body, 0, Xq.reshape(K, C, F))
            return vals.reshape(-1)

        def encode(X):
            return q.wire.encode(X)

    # ---- pipeline: featurize (threads) → h2d → score → d2h readback ----
    enc_pool = ThreadPoolExecutor(max_workers=2)

    # warm: compile + first transfers (excluded from the measurement)
    warm = np.asarray(run(params, jax.device_put(encode(pool_f32[0]))))
    assert warm.shape == (B,) and np.isfinite(
        warm.astype(np.float32)
    ).all(), "warmup produced non-finite scores"

    PRE = args.window + 2  # encoded batches staged ahead of the transfer
    encoded = collections.deque(
        enc_pool.submit(encode, pool_f32[i % len(pool_f32)])
        for i in range(PRE)
    )
    inflight = collections.deque()
    done_records = 0
    i = 0
    t0 = time.perf_counter()
    deadline = t0 + args.seconds
    while True:
        now = time.perf_counter()
        if now >= deadline and not inflight:
            break
        if now < deadline:
            Xq = encoded.popleft().result()
            encoded.append(
                enc_pool.submit(encode, pool_f32[(i + PRE) % len(pool_f32)])
            )
            inflight.append(run(params, jax.device_put(Xq)))
            i += 1
        while len(inflight) > (args.window if now < deadline else 0):
            scores = np.asarray(inflight.popleft())  # forces the round trip
            done_records += scores.shape[0]
    dt = time.perf_counter() - t0
    enc_pool.shutdown(wait=False)

    rate = done_records / dt
    print(json.dumps({
        "metric": f"gbm{args.trees}_records_per_sec_per_chip",
        "value": round(rate, 1),
        "unit": "records/s/chip",
        "vs_baseline": round(rate / NORTH_STAR_REC_S, 3),
    }))


if __name__ == "__main__":
    main()
