#!/usr/bin/env python
"""Metric-name drift lint (CI tier-1 via tests/test_metrics_lint.py).

Every metric name the runtime registers must appear in the operator
catalogue (docs/operations.md, "Metric name catalogue" table) and vice
versa — a renamed counter that silently vanishes from dashboards, or a
documented metric nothing emits, both fail this check.

Static, regex-level, zero imports of the package (runs in milliseconds
and cannot be skewed by which code paths a test run happened to
execute): every ``.counter("...")`` / ``.gauge(...)`` /
``.histogram(...)`` / ``.reservoir(...)`` call with a literal (or
f-string-literal) first argument is an emission site. F-string
placeholders normalize to ``*`` — the same wildcard the catalogue uses
for dynamic segments (``stage_*_s``, ``scorer_backend_*``,
``kafka_lag{partition="*"}``).

Exit 0 = in sync; 1 = drift (each direction listed); 2 = the catalogue
table could not be found (the docs structure changed under the lint —
fix the parser, don't delete the contract).
"""

from __future__ import annotations

import pathlib
import re
import sys
from typing import Set, Tuple

REPO = pathlib.Path(__file__).resolve().parent.parent
SRC = REPO / "flink_jpmml_tpu"
DOCS = REPO / "docs" / "operations.md"

# .counter("name") / .gauge(f"...") — single or double quoted literal
_CALL = re.compile(
    r"\.(counter|gauge|histogram|reservoir)\(\s*(f?)(\"([^\"]+)\"|'([^']+)')"
)
_CATALOGUE_HEAD = "### Metric name catalogue"
_ROW_NAME = re.compile(r"^\|\s*`([^`]+)`")


def _normalize_fstring(s: str) -> str:
    """f-string literal → catalogue wildcard form: ``{{``/``}}`` are
    literal braces, any ``{expr}`` placeholder becomes ``*``."""
    s = s.replace("{{", "\x00").replace("}}", "\x01")
    s = re.sub(r"\{[^{}]*\}", "*", s)
    return s.replace("\x00", "{").replace("\x01", "}")


def code_names() -> Set[Tuple[str, str]]:
    """→ {(name, 'file:line')} for every literal registration site."""
    out: Set[Tuple[str, str]] = set()
    for path in sorted(SRC.rglob("*.py")):
        text = path.read_text(encoding="utf-8")
        for m in _CALL.finditer(text):
            is_f = bool(m.group(2))
            raw = m.group(4) if m.group(4) is not None else m.group(5)
            name = _normalize_fstring(raw) if is_f else raw
            line = text.count("\n", 0, m.start()) + 1
            out.add((name, f"{path.relative_to(REPO)}:{line}"))
    return out


def doc_names() -> Set[str]:
    text = DOCS.read_text(encoding="utf-8")
    try:
        section = text.split(_CATALOGUE_HEAD, 1)[1]
    except IndexError:
        print(
            f"metrics-lint: {_CATALOGUE_HEAD!r} section not found in "
            f"{DOCS}", file=sys.stderr,
        )
        sys.exit(2)
    names: Set[str] = set()
    in_table = False
    for line in section.splitlines():
        if line.startswith("|"):
            in_table = True
            m = _ROW_NAME.match(line)
            if m and m.group(1) not in ("Name",):
                names.add(m.group(1))
        elif in_table:
            break  # one table; the first non-| line after it ends it
    if not names:
        print(
            f"metrics-lint: catalogue table empty/unparseable in {DOCS}",
            file=sys.stderr,
        )
        sys.exit(2)
    return names


def main() -> int:
    emitted = code_names()
    documented = doc_names()
    emitted_names = {n for n, _ in emitted}
    rc = 0
    undocumented = sorted(emitted_names - documented)
    if undocumented:
        rc = 1
        for n in undocumented:
            sites = sorted(s for name, s in emitted if name == n)
            print(
                f"metrics-lint: `{n}` is emitted ({', '.join(sites)}) "
                "but missing from the docs/operations.md catalogue"
            )
    unemitted = sorted(documented - emitted_names)
    if unemitted:
        rc = 1
        for n in unemitted:
            print(
                f"metrics-lint: `{n}` is in the docs/operations.md "
                "catalogue but nothing in flink_jpmml_tpu/ registers it"
            )
    if rc == 0:
        print(
            f"metrics-lint: {len(emitted_names)} metric names in sync "
            "with the catalogue"
        )
    return rc


if __name__ == "__main__":
    sys.exit(main())
