#!/usr/bin/env python
"""Metric-name + fleet-merge-rule lint (CI tier-1 via
tests/test_metrics_lint.py).

Two contracts, both static (regex-level, zero imports of the package —
runs in milliseconds and cannot be skewed by which code paths a test
run happened to execute):

1. **Name sync** — every metric name the runtime registers must appear
   in the operator catalogue (docs/operations.md, "Metric name
   catalogue" table) and vice versa: a renamed counter that silently
   vanishes from dashboards, or a documented metric nothing emits,
   both fail.
2. **Merge-rule sync** — every catalogue row must declare its
   fleet-merge semantics in the Merge column (counters `sum`,
   histograms/sketches `buckets`, gauges `sum`/`min`/`max`/`worst-of`)
   and the gauge declarations must MATCH what
   ``utils/metrics.py merge_structs`` actually does (its
   ``_GAUGE_MERGE_MAX_PREFIXES``/``_GAUGE_MERGE_MIN_PREFIXES`` tables,
   parsed from source) — a gauge documented worst-of that the code
   sums renders fleet dashboards arithmetic nonsense. Conversely,
   every prefix rule in those tables must be exercised by at least one
   catalogue gauge row, so a dead or typo'd prefix can't linger.

Every ``.counter("...")`` / ``.gauge(...)`` / ``.histogram(...)`` /
``.reservoir(...)`` / ``.sketch(...)`` call with a literal (or
f-string-literal) first argument is an emission site. F-string
placeholders normalize to ``*`` — the same wildcard the catalogue uses
for dynamic segments (``stage_*_s``, ``kafka_lag{partition="*"}``).

Exit 0 = in sync; 1 = drift (each direction listed); 2 = the catalogue
table could not be found (the docs structure changed under the lint —
fix the parser, don't delete the contract).
"""

from __future__ import annotations

import ast
import pathlib
import re
import sys
from typing import Dict, Set, Tuple

REPO = pathlib.Path(__file__).resolve().parent.parent
SRC = REPO / "flink_jpmml_tpu"
DOCS = REPO / "docs" / "operations.md"
METRICS_PY = SRC / "utils" / "metrics.py"

# .counter("name") / .gauge(f"...") — single or double quoted literal
_CALL = re.compile(
    r"\.(counter|gauge|histogram|reservoir|sketch)"
    r"\(\s*(f?)(\"([^\"]+)\"|'([^']+)')"
)
_CATALOGUE_HEAD = "### Metric name catalogue"
_ROW = re.compile(
    r"^\|\s*`([^`]+)`\s*\|\s*([a-z]+)\s*\|\s*([a-z-]+)\s*\|"
)
_PREFIX_TABLE_NAMES = (
    "_GAUGE_MERGE_MAX_PREFIXES", "_GAUGE_MERGE_MIN_PREFIXES",
)

# what the Merge column may say, per kind; gauges are checked against
# the CODE's merge mode below, not just this vocabulary
_MERGE_VOCAB = {
    "counter": {"sum"},
    "histogram": {"buckets"},
    "sketch": {"buckets"},
    "gauge": {"sum", "max", "min", "worst-of"},
    "reservoir": {"none"},
}


def _normalize_fstring(s: str) -> str:
    """f-string literal → catalogue wildcard form: ``{{``/``}}`` are
    literal braces, any ``{expr}`` placeholder becomes ``*``."""
    s = s.replace("{{", "\x00").replace("}}", "\x01")
    s = re.sub(r"\{[^{}]*\}", "*", s)
    return s.replace("\x00", "{").replace("\x01", "}")


def code_names() -> Set[Tuple[str, str, str]]:
    """→ {(name, kind, 'file:line')} for every literal registration
    site."""
    out: Set[Tuple[str, str, str]] = set()
    for path in sorted(SRC.rglob("*.py")):
        text = path.read_text(encoding="utf-8")
        for m in _CALL.finditer(text):
            kind = m.group(1)
            is_f = bool(m.group(2))
            raw = m.group(4) if m.group(4) is not None else m.group(5)
            name = _normalize_fstring(raw) if is_f else raw
            line = text.count("\n", 0, m.start()) + 1
            out.add((name, kind, f"{path.relative_to(REPO)}:{line}"))
    return out


def gauge_merge_prefixes(
    path: pathlib.Path = METRICS_PY,
) -> Dict[str, Tuple[str, ...]]:
    """Parse the merge prefix tables out of utils/metrics.py via
    ``ast.parse`` (no package import). Walking the real AST instead of
    a to-the-closing-paren regex means comments INSIDE the tuple
    literals — parens, quotes, whatever — can't truncate the match
    and silently fail the lint with exit 2 (the PR 12 wart)."""
    out: Dict[str, Tuple[str, ...]] = {}
    try:
        tree = ast.parse(path.read_text(encoding="utf-8"))
    except SyntaxError as e:
        print(
            f"metrics-lint: {path} does not parse ({e}) — fix the "
            "module, the lint reads its assignments",
            file=sys.stderr,
        )
        sys.exit(2)
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for target in node.targets:
            if (
                isinstance(target, ast.Name)
                and target.id in _PREFIX_TABLE_NAMES
            ):
                try:
                    val = ast.literal_eval(node.value)
                except (SyntaxError, ValueError):
                    continue
                if isinstance(val, (tuple, list)) and all(
                    isinstance(s, str) for s in val
                ):
                    out[target.id] = tuple(val)
    missing = [n for n in _PREFIX_TABLE_NAMES if n not in out]
    if missing:
        print(
            "metrics-lint: could not parse the gauge merge prefix "
            f"table(s) {missing} from {path} — fix the parser, don't "
            "drop the contract",
            file=sys.stderr,
        )
        sys.exit(2)
    return out


def _code_gauge_mode(name: str, prefixes: Dict[str, Tuple[str, ...]]) -> str:
    """What merge_structs does to this gauge (mirror of
    ``_gauge_merge_mode``, driven by the parsed tables; min checked
    first, as in the code)."""
    base = name.split("{", 1)[0]
    if base.startswith(prefixes["_GAUGE_MERGE_MIN_PREFIXES"]):
        return "min"
    if base.startswith(prefixes["_GAUGE_MERGE_MAX_PREFIXES"]):
        return "max"
    return "sum"


def rank_family_default(path: pathlib.Path = METRICS_PY) -> str:
    """Parse ``_RANK_FAMILY_DEFAULT`` (the cardinality governor's
    default top-K ranking family) out of utils/metrics.py."""
    try:
        tree = ast.parse(path.read_text(encoding="utf-8"))
    except SyntaxError:
        sys.exit(2)  # gauge_merge_prefixes already reported it
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if (
                    isinstance(target, ast.Name)
                    and target.id == "_RANK_FAMILY_DEFAULT"
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)
                ):
                    return node.value.value
    print(
        f"metrics-lint: _RANK_FAMILY_DEFAULT not found in {path} — "
        "fix the parser, don't drop the contract",
        file=sys.stderr,
    )
    sys.exit(2)


def doc_rows() -> Dict[str, Tuple[str, str]]:
    """→ {name: (kind, merge)} from the catalogue table."""
    text = DOCS.read_text(encoding="utf-8")
    try:
        section = text.split(_CATALOGUE_HEAD, 1)[1]
    except IndexError:
        print(
            f"metrics-lint: {_CATALOGUE_HEAD!r} section not found in "
            f"{DOCS}", file=sys.stderr,
        )
        sys.exit(2)
    rows: Dict[str, Tuple[str, str]] = {}
    in_table = False
    for line in section.splitlines():
        if line.startswith("|"):
            in_table = True
            m = _ROW.match(line)
            if m and m.group(1) not in ("Name",):
                rows[m.group(1)] = (m.group(2), m.group(3))
        elif in_table:
            break  # one table; the first non-| line after it ends it
    if not rows:
        print(
            f"metrics-lint: catalogue table empty/unparseable in {DOCS} "
            "(each row needs | `name` | kind | merge | meaning |)",
            file=sys.stderr,
        )
        sys.exit(2)
    return rows


def main() -> int:
    emitted = code_names()
    documented = doc_rows()
    emitted_names = {n for n, _, _ in emitted}
    rc = 0

    # -- direction 1: every emission site documented -----------------------
    undocumented = sorted(emitted_names - set(documented))
    if undocumented:
        rc = 1
        for n in undocumented:
            sites = sorted(s for name, _, s in emitted if name == n)
            print(
                f"metrics-lint: `{n}` is emitted ({', '.join(sites)}) "
                "but missing from the docs/operations.md catalogue"
            )
    unemitted = sorted(set(documented) - emitted_names)
    if unemitted:
        rc = 1
        for n in unemitted:
            print(
                f"metrics-lint: `{n}` is in the docs/operations.md "
                "catalogue but nothing in flink_jpmml_tpu/ registers it"
            )

    # -- direction 2: merge declarations match the code --------------------
    prefixes = gauge_merge_prefixes()
    for name, (kind, merge) in sorted(documented.items()):
        vocab = _MERGE_VOCAB.get(kind)
        if vocab is None:
            rc = 1
            print(
                f"metrics-lint: `{name}` has unknown kind {kind!r} "
                f"(one of {sorted(_MERGE_VOCAB)})"
            )
            continue
        if merge not in vocab:
            rc = 1
            print(
                f"metrics-lint: `{name}` ({kind}) declares merge "
                f"{merge!r}; a {kind}'s merge must be one of "
                f"{sorted(vocab)}"
            )
            continue
        if kind == "gauge":
            mode = _code_gauge_mode(name, prefixes)
            ok = (
                merge == mode
                or (merge == "worst-of" and mode in ("max", "min"))
            )
            if not ok:
                rc = 1
                print(
                    f"metrics-lint: `{name}` declares merge {merge!r} "
                    f"but utils/metrics.merge_structs {mode}s it — "
                    "fix the catalogue row or the "
                    "_GAUGE_MERGE_*_PREFIXES tables"
                )

    # -- direction 3: every prefix rule exercised by a catalogue row -------
    doc_gauges = [
        name.split("{", 1)[0]
        for name, (kind, _) in documented.items() if kind == "gauge"
    ]
    for table in ("_GAUGE_MERGE_MAX_PREFIXES", "_GAUGE_MERGE_MIN_PREFIXES"):
        for prefix in prefixes[table]:
            if not any(g.startswith(prefix) for g in doc_gauges):
                rc = 1
                print(
                    f"metrics-lint: merge prefix {prefix!r} in "
                    f"utils/metrics.py {table} matches no catalogue "
                    "gauge row — dead rule or missing documentation"
                )

    # -- direction 4: the governor's default rank family is real -----------
    # FJT_METRICS_MAX_SERIES folds per-tenant families to top-K ranked
    # by _RANK_FAMILY_DEFAULT's counter; a renamed family would
    # silently degrade every governed fold to magnitude ranking
    rank = rank_family_default()
    doc_bases = {name.split("{", 1)[0] for name in documented}
    if rank not in doc_bases:
        rc = 1
        print(
            f"metrics-lint: governor rank family {rank!r} "
            "(_RANK_FAMILY_DEFAULT, utils/metrics.py) names no "
            "catalogued metric base"
        )

    if rc == 0:
        print(
            f"metrics-lint: {len(emitted_names)} metric names in sync "
            "with the catalogue (merge rules + governor rank family "
            "verified)"
        )
    return rc


if __name__ == "__main__":
    sys.exit(main())
