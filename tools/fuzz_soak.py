"""Extended fuzz soak: arbitrary seed ranges over the test_fuzz
generators, on any backend — the on-device evidence tool behind the
"N seeds on-device clean" claims in docs/parity.md.

The pytest suite pins fixed seed ranges so CI stays deterministic and
fast; this driver reuses the exact same generators and the exact same
lane-by-lane compiled-vs-oracle assertion, but sweeps as many seeds as
a soak budget allows, on whichever backend the session resolves
(run plainly for the real chip; FJT_TEST_PLATFORM-style CPU pinning is
the test suite's business, not this tool's).

Usage:
  python tools/fuzz_soak.py [--families trees,mining,regression,...]
                            [--seeds 100] [--start 10000]
Prints one summary line per family and exits nonzero on any parity
failure (the failing seed is in the assertion message — replay it by
passing --start <seed> --seeds 1).
"""

import argparse
import os
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import numpy as np

from tests import test_fuzz as tf


def _soak_trees(seed):
    rng = np.random.default_rng(seed)
    doc, recs = None, None
    doc = tf._doc(tf._rand_tree_model(rng))
    recs = tf._rand_records(rng, 64)
    tf._assert_parity(doc, recs, f"tree seed={seed}")


def _soak_mining(seed):
    # mirrors TestFuzzMining.test_random_regression_ensemble_parity
    # (the generator is inline there, not a module helper)
    from flink_jpmml_tpu.pmml import ir

    rng = np.random.default_rng(seed)
    n_seg = int(rng.integers(2, 5))
    segments = tuple(
        ir.Segment(
            predicate=(
                ir.TruePredicate()
                if rng.random() < 0.5
                else tf._rand_predicate(rng, 1)
            ),
            model=ir.TreeModelIR(
                function_name="regression",
                mining_schema=tf._schema(),
                root=tf._rand_tree(rng, False, max_depth=2),
                missing_value_strategy=str(rng.choice(
                    ["none", "defaultChild", "nullPrediction"]
                )),
                split_characteristic="multiSplit",
            ),
            segment_id=f"s{i}",
            weight=float(np.round(rng.uniform(0.5, 2.0), 2)),
        )
        for i in range(n_seg)
    )
    method = str(rng.choice(
        ["sum", "average", "weightedAverage", "max", "median",
         "selectFirst"]
    ))
    model = ir.MiningModelIR(
        function_name="regression",
        mining_schema=tf._schema(),
        segmentation=ir.Segmentation(
            multiple_model_method=method, segments=segments
        ),
    )
    doc = tf._doc(model)
    recs = tf._rand_records(rng, 32)
    tf._assert_parity(doc, recs, f"mining {method} seed={seed}")


def _soak_regression(seed):
    rng = np.random.default_rng(seed)
    doc = tf._doc(tf._rand_regression_model(rng))
    recs = tf._rand_records(rng, 64)
    tf._assert_parity(doc, recs, f"regression seed={seed}")


def _soak_neural(seed):
    rng = np.random.default_rng(seed)
    doc = tf._doc(tf._rand_nn_model(rng))
    recs = tf._rand_records(rng, 64)
    tf._assert_parity(doc, recs, f"neural seed={seed}")


def _soak_glm(seed):
    rng = np.random.default_rng(seed)
    doc = tf._doc(tf._rand_glm_model(rng))
    recs = tf._rand_records(rng, 64)
    tf._assert_parity(doc, recs, f"glm seed={seed}")


def _soak_scorecard(seed):
    # mirrors TestFuzzScorecard.test_random_scorecard_parity
    from flink_jpmml_tpu.pmml import ir

    rng = np.random.default_rng(seed)
    chars = []
    for ci in range(int(rng.integers(1, 4))):
        attrs = [
            ir.ScorecardAttribute(
                predicate=tf._rand_predicate(rng, 1),
                partial_score=float(np.round(rng.normal(0, 20), 1)),
            )
            for _ in range(int(rng.integers(1, 4)))
        ]
        if rng.random() < 0.8:
            attrs.append(ir.ScorecardAttribute(
                predicate=ir.TruePredicate(),
                partial_score=float(np.round(rng.normal(0, 5), 1)),
            ))
        chars.append(ir.Characteristic(
            name=f"ch{ci}", attributes=tuple(attrs)
        ))
    model = ir.ScorecardIR(
        function_name="regression",
        mining_schema=tf._schema(),
        characteristics=tuple(chars),
        initial_score=float(np.round(rng.normal(100, 20), 1)),
        use_reason_codes=False,
    )
    doc = tf._doc(model)
    recs = tf._rand_records(rng, 40)
    tf._assert_parity(doc, recs, f"scorecard seed={seed}")


def _soak_sarima(seed):
    # mirrors TestFuzzArima.test_random_sarima_parity
    from flink_jpmml_tpu.pmml import parse_pmml
    from tests.test_timeseries import _arima_xml, _ns, _sc

    rng = np.random.default_rng(seed)
    p = int(rng.integers(0, 3))
    d = int(rng.integers(0, 2))
    q = int(rng.integers(0, 3))
    s = int(rng.integers(2, 5)) if rng.random() < 0.6 else 0
    P = int(rng.integers(0, 2)) if s else 0
    D = int(rng.integers(0, 2)) if s else 0
    Q = int(rng.integers(0, 2)) if s else 0
    if s and not (P or D or Q):
        D = 1

    def coefs(n):
        return tuple(round(float(v), 3)
                     for v in rng.uniform(-0.65, 0.65, size=n))

    n_res = q + s * Q
    residuals = tuple(
        round(float(v), 3) for v in rng.normal(0, 0.4, size=n_res)
    )
    n_hist = d + s * D + (p + s * P) + int(rng.integers(8, 16))
    t = np.arange(n_hist)
    hist = tuple(
        round(float(v), 3)
        for v in 40
        + 0.8 * t
        + (4 * np.sin(2 * np.pi * t / s) if s else 0)
        + rng.normal(0, 1.0, size=n_hist)
    )
    transformation = str(
        rng.choice(("none", "none", "logarithmic", "squareroot"))
    )
    body = _ns(p, d, q, ar=coefs(p), ma=coefs(q),
               residuals=residuals if n_res else ())
    if s:
        body += _sc(P, D, Q, s, sar=coefs(P), sma=coefs(Q))
    doc = parse_pmml(_arima_xml(
        body, hist,
        constant=round(float(rng.uniform(-0.5, 0.5)), 3),
        transformation=transformation,
    ))
    recs = []
    for _ in range(24):
        roll = rng.random()
        if roll < 0.1:
            recs.append({})
        elif roll < 0.2:
            recs.append({"h": None})
        elif roll < 0.3:
            recs.append({"h": float(rng.uniform(0.6, 20.0))})
        else:
            recs.append({"h": int(rng.integers(1, 31))})
    tf._assert_parity(doc, recs, f"sarima seed={seed}")


FAMILIES = {
    "trees": _soak_trees,
    "mining": _soak_mining,
    "regression": _soak_regression,
    "neural": _soak_neural,
    "glm": _soak_glm,
    "scorecard": _soak_scorecard,
    "sarima": _soak_sarima,
}


# --chaos mode: one compiled model shared across every seed (the chaos
# is in the FAULT composition, not the model)
_CHAOS_MODEL = None


def _chaos_model():
    global _CHAOS_MODEL
    if _CHAOS_MODEL is None:
        import tempfile

        from flink_jpmml_tpu.assets_gen import gen_gbm
        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.pmml import parse_pmml_file

        tmp = tempfile.mkdtemp(prefix="fjt-chaos-model-")
        _CHAOS_MODEL = compile_pmml(
            parse_pmml_file(
                gen_gbm(tmp, n_trees=4, depth=3, n_features=5)
            ),
            batch_size=32,
        )
    return _CHAOS_MODEL


def _soak_chaos(seed):
    """One chaos iteration: a seeded random COMPOSITION of fault kinds
    (broker death, slow fetch, dispatch delay, checkpoint failure,
    worker wedge, poison records, decode poison, DEVICE faults —
    everything except worker_crash and chip_loss, which would kill the
    soak process itself; the kill-anywhere half lives in ``bench.py
    --recovery-drill`` / ``--device-fault-drill``) against a real
    Kafka→BlockPipeline stream with checkpoints + DLQ. Verifies the
    delivery contract every time: every offset either reaches the sink
    or sits in the DLQ, poison lands in the DLQ exactly — and device
    faults land NOWHERE (the ladder re-dispatches or serves the
    fallback tier; a sick device must never quarantine clean records
    nor lose any, even composed with e.g. a concurrent broker death) —
    and the stream drains to the end despite the weather."""
    import os
    import tempfile

    from flink_jpmml_tpu.runtime import faults
    from flink_jpmml_tpu.runtime.block import BlockPipeline
    from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager
    from flink_jpmml_tpu.runtime.dlq import DeadLetterQueue
    from flink_jpmml_tpu.runtime.kafka import (
        KafkaBlockSource, MiniKafkaBroker,
    )
    from flink_jpmml_tpu.utils.config import BatchConfig, RuntimeConfig
    from flink_jpmml_tpu.utils.metrics import MetricsRegistry

    rng = np.random.default_rng(seed)
    cm = _chaos_model()
    N = 1500
    data = rng.normal(0, 1.0, size=(N, 5)).astype(np.float32)
    tmp = tempfile.mkdtemp(prefix="fjt-chaos-")
    broker = MiniKafkaBroker(topic="chaos")
    pipe = None
    try:
        # interleave decode poison at random positions
        decode_offsets = []
        positions = sorted(
            int(p) for p in rng.choice(
                N, size=int(rng.integers(0, 3)), replace=False,
            )
        )
        produced = 0
        for p in positions:
            broker.append_rows(data[produced:p])
            decode_offsets.append(broker.append(b"chaff"))
            produced = p
        broker.append_rows(data[produced:])
        total = N + len(decode_offsets)
        # score poison via the harness, offsets in the log domain
        score_poison = []
        for _ in range(int(rng.integers(0, 3))):
            o = int(rng.integers(0, total))
            while o in decode_offsets or o in score_poison:
                o = (o + 1) % total
            score_poison.append(o)
        spec = [
            f"poison_record:offset={o}" for o in score_poison
        ]
        menu = [
            f"slow_fetch:delay_ms=2:p=0.05:seed={seed}",
            f"broker_death:n={int(rng.integers(1, 3))}"
            f":p=0.02:seed={seed}",
            f"dispatch_delay:delay_ms=1:p=0.05:seed={seed}",
            f"checkpoint_fail:n={int(rng.integers(1, 3))}",
            "worker_wedge:wedge_s=0.05:n=1",
            # device kinds (runtime/devfault.py): persistent-ish error
            # streaks exercise redispatch→breaker→fallback, OOM streaks
            # the batch-size bisection — composed freely with the rest
            f"device_error:site=device_readback"
            f":n={int(rng.integers(2, 10))}",
            f"device_oom:site=device_dispatch"
            f":n={int(rng.integers(1, 4))}",
        ]
        picks = rng.choice(
            len(menu), size=int(rng.integers(1, len(menu) + 1)),
            replace=False,
        )
        spec += [menu[i] for i in picks]
        emitted = []

        def sink(out, n, first_off):
            emitted.append((first_off, n))

        m = MetricsRegistry()
        dlq = DeadLetterQueue(os.path.join(tmp, "ck", "dlq"), metrics=m)
        src = KafkaBlockSource(
            broker.host, broker.port, "chaos", n_cols=5,
            max_wait_ms=10, metrics=m, dlq=dlq,
        )
        os.environ["FJT_RETRY_BASE_S"] = "0.01"
        # fast breaker geometry so a device_error streak can complete
        # its open→half-open→closed lifecycle within one soak seed
        os.environ["FJT_FAILOVER_COOLDOWN_S"] = "0.1"
        os.environ["FJT_FAILOVER_GREENS"] = "1"
        assert faults.install_from_env(",".join(spec)), spec
        pipe = BlockPipeline(
            src, cm, sink,
            RuntimeConfig(
                batch=BatchConfig(size=32, deadline_us=1000),
                checkpoint_interval_s=0.05,
            ),
            metrics=m,
            checkpoint=CheckpointManager(os.path.join(tmp, "ck")),
            dlq=dlq,
            max_dispatch_chunks=4,
        )
        pipe.start()
        deadline = time.perf_counter() + 60.0
        while (
            pipe.committed_offset < total
            and pipe._error is None
            and time.perf_counter() < deadline
        ):
            time.sleep(0.01)
        pipe.stop()
        pipe.join(timeout=20.0)
        pipe = None
        src.close()
        assert pipe is None
        covered = np.zeros(total, np.int64)
        for off, n in emitted:
            covered[off: off + n] += 1
        quarantined = sorted(set(dlq.offsets()))
        expected = sorted(set(decode_offsets) | set(score_poison))
        assert quarantined == expected, (
            f"chaos seed={seed}: DLQ {quarantined} != {expected} "
            f"(spec {spec})"
        )
        missing = sorted(
            int(o) for o in np.flatnonzero(covered == 0)
        )
        assert missing == expected, (
            f"chaos seed={seed}: sink gaps {missing[:10]} != "
            f"quarantined {expected} (spec {spec})"
        )
    finally:
        faults.clear()
        if pipe is not None:
            try:
                pipe.stop()
                pipe.join(timeout=10.0)
            except Exception:
                pass
        broker.close()
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)


def _soak_mesh_chaos(seed):
    """One MESH chaos iteration (PR 16): ``chip_loss`` — survivable on
    a mesh since the KIND_LOST rung rebuilds over the surviving chips
    in place — composed with kafka-side weather (slow fetch, broker
    death, dispatch delay) against a mesh-sharded Kafka→BlockPipeline
    stream. Verifies degraded-mesh mode under churn: every offset
    reaches the sink exactly (zero loss, zero duplication — no
    restart), the DLQ stays EMPTY (a dead chip never quarantines
    records), every injected chip loss performed a rebuild, and the
    surviving data width dropped accordingly."""
    import os
    import tempfile

    from flink_jpmml_tpu.parallel.mesh import make_mesh
    from flink_jpmml_tpu.runtime import faults
    from flink_jpmml_tpu.runtime.block import BlockPipeline
    from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager
    from flink_jpmml_tpu.runtime.dlq import DeadLetterQueue
    from flink_jpmml_tpu.runtime.kafka import (
        KafkaBlockSource, MiniKafkaBroker,
    )
    from flink_jpmml_tpu.utils.config import (
        BatchConfig, MeshConfig, RuntimeConfig,
    )
    from flink_jpmml_tpu.utils.metrics import MetricsRegistry

    import jax

    n_dev = jax.device_count()
    assert n_dev >= 4, (
        f"mesh chaos needs >= 4 devices, found {n_dev} (set XLA_FLAGS="
        "--xla_force_host_platform_device_count=8)"
    )
    mesh = make_mesh(
        MeshConfig(data=4, model=2 if n_dev >= 8 else 1),
        allow_subset=True,
    )
    rng = np.random.default_rng(seed)
    cm = _chaos_model()
    N = 1504  # divides by 32; the mesh pad keeps partials dispatchable
    data = rng.normal(0, 1.0, size=(N, 5)).astype(np.float32)
    tmp = tempfile.mkdtemp(prefix="fjt-meshchaos-")
    broker = MiniKafkaBroker(topic="meshchaos")
    pipe = None
    try:
        broker.append_rows(data)
        # chip loss is the profile's anchor; width 4 survives two
        losses = int(rng.integers(1, 3))
        spec = [f"chip_loss:n={losses}"]
        menu = [
            f"slow_fetch:delay_ms=2:p=0.05:seed={seed}",
            f"broker_death:n={int(rng.integers(1, 3))}"
            f":p=0.02:seed={seed}",
            f"dispatch_delay:delay_ms=1:p=0.05:seed={seed}",
        ]
        picks = rng.choice(
            len(menu), size=int(rng.integers(1, len(menu) + 1)),
            replace=False,
        )
        spec += [menu[i] for i in picks]
        emitted = []

        def sink(out, n, first_off):
            emitted.append((first_off, n))

        m = MetricsRegistry()
        dlq = DeadLetterQueue(os.path.join(tmp, "ck", "dlq"), metrics=m)
        src = KafkaBlockSource(
            broker.host, broker.port, "meshchaos", n_cols=5,
            max_wait_ms=10, metrics=m, dlq=dlq,
        )
        os.environ["FJT_RETRY_BASE_S"] = "0.01"
        assert faults.install_from_env(",".join(spec)), spec
        pipe = BlockPipeline(
            src, cm, sink,
            RuntimeConfig(
                batch=BatchConfig(size=32, deadline_us=1000),
                checkpoint_interval_s=0.05,
            ),
            metrics=m,
            checkpoint=CheckpointManager(os.path.join(tmp, "ck")),
            dlq=dlq,
            max_dispatch_chunks=4,
            mesh=mesh,
        )
        pipe.start()
        deadline = time.perf_counter() + 120.0
        while (
            pipe.committed_offset < N
            and pipe._error is None
            and time.perf_counter() < deadline
        ):
            time.sleep(0.01)
        pipe.stop()
        pipe.join(timeout=20.0)
        err = pipe._error
        pipe = None
        src.close()
        assert err is None, f"mesh chaos seed={seed}: died {err!r}"
        covered = np.zeros(N, np.int64)
        for off, n in emitted:
            covered[off: off + n] += 1
        assert (covered == 1).all(), (
            f"mesh chaos seed={seed}: coverage "
            f"min={covered.min()} max={covered.max()} (spec {spec})"
        )
        assert sorted(set(dlq.offsets())) == [], (
            f"mesh chaos seed={seed}: chip loss quarantined records"
        )
        fired = faults.stats().get("chip_loss", 0)
        c = m.struct_snapshot()["counters"]
        assert c.get("mesh_rebuilds", 0) >= fired >= 1, (
            f"mesh chaos seed={seed}: {fired} chip losses but "
            f"{c.get('mesh_rebuilds', 0)} rebuilds (spec {spec})"
        )
    finally:
        faults.clear()
        if pipe is not None:
            try:
                pipe.stop()
                pipe.join(timeout=10.0)
            except Exception:
                pass
        broker.close()
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)


_ZOO_DOCS = []


def _zoo_docs():
    """Six tiny distinct GBMs, built once per soak process (the chaos
    seeds churn tenants, not documents)."""
    if not _ZOO_DOCS:
        import tempfile

        from flink_jpmml_tpu.assets_gen import gen_gbm

        tmp = tempfile.mkdtemp(prefix="fjt-zoochaos-docs-")
        _ZOO_DOCS.extend(
            gen_gbm(tmp, n_trees=4 + i, depth=3, n_features=4,
                    seed=70 + i, name=f"zc{i}")
            for i in range(6)
        )
    return _ZOO_DOCS


def _soak_zoo_chaos(seed):
    """One ZOO chaos iteration: seeded tenant churn (Del / re-Add /
    version bump) composed with device faults against a zoo-enabled
    DynamicScorer. Verifies the per-tenant delivery contract every
    round: every submitted record gets exactly one prediction (C5
    totality), warm-served tenants' lanes are non-empty — a device
    fault mid-pack must redispatch, never surface — and unserved
    (churned-out) tenants' lanes are empty, never misrouted to a
    packmate."""
    import os
    import time as _t

    from flink_jpmml_tpu.models.control import AddMessage, DelMessage
    from flink_jpmml_tpu.models.core import ModelId
    from flink_jpmml_tpu.runtime import faults
    from flink_jpmml_tpu.runtime.sources import ControlSource
    from flink_jpmml_tpu.serving.scorer import DynamicScorer

    rng = np.random.default_rng(seed)
    docs = _zoo_docs()
    tenants = [f"zc{i}" for i in range(len(docs))]
    fields = [f"f{j}" for j in range(4)]
    data = rng.normal(0, 1.2, size=(4096, 4)).astype(np.float32)
    data[rng.random(size=data.shape) < 0.02] = np.nan

    os.environ["FJT_RETRY_BASE_S"] = "0.01"
    ctrl = ControlSource()
    sc = DynamicScorer(control=ctrl, batch_size=128, auto_rollout=False,
                       zoo=True)
    version = {}
    served = {}  # name -> every version currently registered: a Del
    # must cover ALL of them — deleting only the newest correctly
    # falls back to the older served version (latest-wins), which is
    # not "dead"
    for i, name in enumerate(tenants):
        version[name] = 1
        served[name] = {1}
        ctrl.push(AddMessage(name, 1, docs[i], timestamp=_t.time()))
    sc._drain_control()
    live = set(tenants)

    def wait_live(timeout_s=120.0):
        deadline = _t.monotonic() + timeout_s
        for name in sorted(live):
            mid = ModelId(name, version[name])
            while sc.registry.model_if_warm(mid) is None:
                err = sc.registry.warm_error(mid)
                assert err is None, (
                    f"zoo chaos seed={seed}: {mid.key()} warm "
                    f"failed {err!r}"
                )
                assert _t.monotonic() < deadline, (
                    f"zoo chaos seed={seed}: {mid.key()} never warmed"
                )
                _t.sleep(0.005)

    wait_live()
    cursor = 0
    try:
        for rnd in range(8):
            # seeded churn between rounds: Del a live tenant, revive a
            # dead one, or bump a live tenant's version (same document
            # - the swap re-packs, the outputs stay total)
            act = rng.integers(0, 4)
            if act == 0 and len(live) > 2:
                victim = sorted(live)[int(rng.integers(0, len(live)))]
                # a version bump leaves the PRIOR version served;
                # latest-wins routing falls back to it after a Del of
                # the newest — "dead" means NO version remains, so the
                # Del must cover every version ever registered
                for v in sorted(served[victim]):
                    ctrl.push(DelMessage(victim, v,
                                         timestamp=_t.time()))
                served[victim] = set()
                live.discard(victim)
            elif act == 1 and len(live) < len(tenants):
                dead = sorted(set(tenants) - live)
                name = dead[int(rng.integers(0, len(dead)))]
                version[name] += 1
                served[name].add(version[name])
                ctrl.push(AddMessage(
                    name, version[name], docs[tenants.index(name)],
                    timestamp=_t.time(),
                ))
                live.add(name)
            elif act == 2:
                name = sorted(live)[int(rng.integers(0, len(live)))]
                version[name] += 1
                served[name].add(version[name])
                ctrl.push(AddMessage(
                    name, version[name], docs[tenants.index(name)],
                    timestamp=_t.time(),
                ))
            sc._drain_control()
            wait_live()
            if rng.random() < 0.6:
                # readback site only: the record-path scorer's fault
                # ladder lives in finish() (classify → redispatch); a
                # launch-time fault propagates to the BLOCK pipelines'
                # direct-dispatch handler, which this soak doesn't drive
                # streaks stay within the FJT_DEVICE_RETRIES budget
                # (2): the record path has no fallback tier below the
                # retry ladder — a longer streak escalates BY CONTRACT
                kind = ("device_error", "device_oom")[
                    int(rng.integers(0, 2))
                ]
                faults.inject(kind, site="device_readback",
                              n=int(rng.integers(1, 3)))
            rows = int(rng.integers(8, 64))
            ev, owner = [], []
            for name in tenants:
                for _ in range(rows):
                    rec = dict(zip(
                        fields, data[cursor % len(data)].tolist()
                    ))
                    rec["_key"] = f"k{cursor}"
                    cursor += 1
                    ev.append((name, rec))
                    owner.append(name)
            out = sc.finish(sc.submit(ev))
            assert len(out) == len(ev), (
                f"zoo chaos seed={seed} round={rnd}: "
                f"{len(out)} predictions for {len(ev)} records"
            )
            for (p, _), name in zip(out, owner):
                if name in live:
                    assert not p.is_empty, (
                        f"zoo chaos seed={seed} round={rnd}: live "
                        f"tenant {name} got an empty lane"
                    )
                else:
                    assert p.is_empty, (
                        f"zoo chaos seed={seed} round={rnd}: dead "
                        f"tenant {name} got a prediction (misrouted "
                        "packmate output)"
                    )
    finally:
        faults.clear()


# --chaos --stateful: the keyed-state profile. The worker must be a
# SUBPROCESS (unlike _soak_chaos) because the profile's crash axis is
# real SIGKILLs — parent kills at seeded committed-offset targets plus
# in-worker ``worker_crash`` weather — and the parity claim is about
# what survives them. One tiny GBM per soak process, like _chaos_model.
_STATE_PMML = []


def _state_chaos_pmml():
    if not _STATE_PMML:
        import tempfile

        from flink_jpmml_tpu.assets_gen import gen_gbm

        tmp = tempfile.mkdtemp(prefix="fjt-statechaos-model-")
        _STATE_PMML.append(
            gen_gbm(tmp, n_trees=4, depth=3, n_features=5)
        )
    return _STATE_PMML[0]


_STATE_CHAOS_WORKER = r'''
import os, sys, time
# per-incarnation fault seed BEFORE the package imports (env faults arm
# at import): seeded p-gates draw a fresh pattern per incarnation, so a
# site-targeted crash can't deterministically re-fire forever
os.environ["FJT_FAULTS"] = os.environ.get("FJT_FAULTS", "").replace(
    "PIDSEED", str(os.getpid())
)
sys.path.insert(0, sys.argv[10])
import jax
jax.config.update("jax_platforms", "cpu")  # correctness soak: host-side
import numpy as np
from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.pmml import parse_pmml_file
from flink_jpmml_tpu.runtime.block import BlockPipeline, FiniteBlockSource
from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager
from flink_jpmml_tpu.runtime.dlq import DeadLetterQueue
from flink_jpmml_tpu.runtime import state as state_mod
from flink_jpmml_tpu.utils.config import BatchConfig, RuntimeConfig
from flink_jpmml_tpu.utils.metrics import MetricsRegistry

pmml, ckdir, outpath, emitpath = sys.argv[1:5]
seed, records, keys, capacity, B = (int(v) for v in sys.argv[5:10])
# every incarnation regenerates the IDENTICAL keyed stream from the
# seed — the chaos is in the faults, the stream is the constant
rng = np.random.default_rng(seed)
data = rng.normal(0.0, 1.0, size=(records, 5)).astype(np.float32)
data[:, 0] = rng.integers(0, keys, size=records).astype(np.float32)
cm = compile_pmml(parse_pmml_file(pmml), batch_size=B)
m = MetricsRegistry()
dlq = DeadLetterQueue(os.path.join(ckdir, "dlq"), metrics=m)
emit = open(emitpath, "a", buffering=1)

def sink(out, n, first_off):
    emit.write("%d %d\n" % (first_off, n))

pipe = BlockPipeline(
    # block == dispatch batch + a far fill deadline: every dispatch is
    # one aligned B-sized block, so a restore replays the exact batch
    # boundaries of the reference life (the byte-parity precondition —
    # scatter-add order is fixed within a batch, reassociated across a
    # different split)
    FiniteBlockSource(data, block_size=B), cm, sink,
    RuntimeConfig(
        batch=BatchConfig(size=B, deadline_us=5_000_000),
        checkpoint_interval_s=0.05,
    ),
    metrics=m,
    checkpoint=CheckpointManager(ckdir),
    dlq=dlq,
    state=state_mod.StateSpec(capacity=capacity, key_col=0),
)
pipe.restore()
pipe.start()
while pipe.committed_offset < records and pipe._error is None:
    time.sleep(0.02)
pipe.stop()
pipe.join(timeout=30.0)
if pipe._error is not None:
    raise SystemExit("state chaos worker died: %r" % (pipe._error,))
tbl = pipe._state
jax.block_until_ready(tbl.values)
c = m.struct_snapshot()["counters"]
tmp_out = outpath + ".tmp"
np.savez(
    tmp_out,
    values=np.asarray(tbl.values),
    keys=tbl._keys, occ=tbl._occ,
    applied_hi=np.int64(tbl.applied_hi),
    state_rollbacks=np.int64(c.get("state_rollbacks", 0)),
)
os.replace(tmp_out + ".npz", outpath)  # np.savez appends .npz
emit.close()
'''


def _soak_stateful_chaos(seed):
    """One STATEFUL chaos iteration (ISSUE 19): seeded faults —
    worker crashes (parent SIGKILLs at committed-offset targets plus
    in-worker ``worker_crash`` weather), ``device_oom``/``device_error``
    streaks, and ``poison_record`` offsets — against a keyed stream
    through a state-armed checkpointed BlockPipeline, run as supervised
    subprocess incarnations. Per seed, against a same-poison fault-free
    reference life:

    - delivery contract (every life): the stream drains, the DLQ holds
      the poison offsets EXACTLY, and the sink's only gaps are those
      quarantined offsets — crashes and device faults lose nothing and
      quarantine nothing;
    - exactly-once fold accounting (every life): NO key ever folds
      MORE records than its ground-truth occurrence count in the
      seeded stream — no crash/replay/re-dispatch composition may
      double-fold. Folding FEWER is legitimate only for rollback
      seeds: a dispatch error (poison or device fault) restores the
      last checkpoint snapshot, shedding a wall-clock-sized window of
      folds by design (bounded, counted loss — ``state_rollbacks``);
    - state parity: when the composition has no rollback source (kills
      and ``worker_crash`` weather only), every key's fold count must
      equal ground truth exactly AND the final table must be
      BYTE-identical to an uninterrupted fault-free reference life —
      the bench kill-parity claim extended to crash weather with the
      DLQ wired."""
    import os
    import shutil
    import signal
    import subprocess
    import tempfile

    from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager
    from flink_jpmml_tpu.runtime.dlq import DeadLetterQueue

    rng = np.random.default_rng(seed)
    records, keys, capacity, B = 2048, 256, 2048, 32
    pmml = _state_chaos_pmml()
    repo = str(pathlib.Path(__file__).resolve().parent.parent)
    tmp = tempfile.mkdtemp(prefix="fjt-statechaos-")
    try:
        # ---- seeded composition --------------------------------------
        poison = []
        for _ in range(int(rng.integers(0, 3))):
            o = int(rng.integers(0, records))
            while o in poison:
                o = (o + 1) % records
            poison.append(o)
        pspec = [f"poison_record:offset={o}" for o in poison]
        kills = int(rng.integers(0, 3))
        if not poison and not kills:
            kills = 1  # never a degenerate fault-free seed
        weather, dev_budget = [], 0
        if rng.random() < 0.4:
            # SIGKILL-anywhere weather: parity-SAFE — exactly-once
            # restore covers any kill instant, in-worker or parent
            weather.append(
                "worker_crash:site=checkpoint_write:p=0.01:n=1"
                ":after_s=0.3:seed=PIDSEED"
            )
        if rng.random() < 0.5:
            dmenu = []
            for kind, site, lo, hi in (
                ("device_error", "device_readback", 2, 6),
                ("device_oom", "device_dispatch", 1, 4),
            ):
                n = int(rng.integers(lo, hi))
                dmenu.append((f"{kind}:site={site}:n={n}", n))
            picks = rng.choice(
                len(dmenu), size=int(rng.integers(1, len(dmenu) + 1)),
                replace=False,
            )
            weather += [dmenu[i][0] for i in picks]
            dev_budget = sum(dmenu[i][1] for i in picks)
        chaos_spec = pspec + weather
        if kills:
            # stretch the drain so the parent's committed-offset poll
            # can land its kills (pure delay: no state effect)
            chaos_spec.append("dispatch_delay:delay_ms=2")

        # ---- one supervised life -------------------------------------
        def run_life(tag, spec, kill_targets, timeout_s=150.0):
            ckdir = os.path.join(tmp, f"ck-{tag}")
            outpath = os.path.join(tmp, f"state-{tag}.npz")
            emitpath = os.path.join(tmp, f"emit-{tag}.log")
            open(emitpath, "w").close()
            argv = [
                sys.executable, "-c", _STATE_CHAOS_WORKER,
                pmml, ckdir, outpath, emitpath, str(seed),
                str(records), str(keys), str(capacity), str(B), repo,
            ]
            env = dict(os.environ)
            env.update({
                "JAX_PLATFORMS": "cpu",
                "FJT_FAULTS": ",".join(spec),
                "FJT_RETRY_BASE_S": "0.01",
                "FJT_FAILOVER_COOLDOWN_S": "0.1",
                "FJT_FAILOVER_GREENS": "1",
                "FJT_XLA_CACHE": os.path.join(tmp, "xla"),
                "FJT_AUTOTUNE_CACHE": os.path.join(tmp, "autotune"),
            })

            def committed():
                try:
                    st = CheckpointManager(ckdir).load_latest()
                    return int(st["source_offset"]) if st else 0
                except Exception:
                    return 0

            pending = list(kill_targets)
            incarnations = 0
            deadline = time.monotonic() + timeout_s
            while True:
                assert incarnations < 25, (
                    f"stateful chaos seed={seed} ({tag}): restart "
                    f"storm without drain (spec {spec})"
                )
                assert time.monotonic() < deadline, (
                    f"stateful chaos seed={seed} ({tag}): no drain in "
                    f"{timeout_s}s, committed "
                    f"{committed()}/{records} (spec {spec})"
                )
                proc = subprocess.Popen(
                    argv, env=env, stdout=subprocess.DEVNULL,
                    stderr=subprocess.PIPE, text=True,
                )
                incarnations += 1
                killed_this = False
                while proc.poll() is None:
                    if pending and committed() >= pending[0]:
                        os.kill(proc.pid, signal.SIGKILL)
                        proc.wait(timeout=10)
                        pending.pop(0)
                        killed_this = True
                        break
                    if time.monotonic() >= deadline:
                        proc.kill()
                        proc.wait(timeout=10)
                        break
                    time.sleep(0.02)
                if killed_this:
                    continue
                if proc.returncode == 0:
                    assert os.path.exists(outpath), (
                        f"stateful chaos seed={seed} ({tag}): worker "
                        "exited 0 without its table dump"
                    )
                    return outpath, emitpath, ckdir, incarnations
                if proc.returncode == -signal.SIGKILL:
                    continue  # in-worker worker_crash weather: respawn
                raise AssertionError(
                    f"stateful chaos seed={seed} ({tag}): worker "
                    f"rc={proc.returncode} (spec {spec}): "
                    f"{(proc.stderr.read() or '')[-600:]}"
                )

        # a dispatch error rolls the table back to the LAST CHECKPOINT
        # snapshot (wall-clock interval ⇒ nondeterministic shed
        # window), so exact parity is only claimable for compositions
        # with no rollback source at all
        rollback_free = not poison and dev_budget == 0

        targets = [
            int(records * (i + 1) / (kills + 1)) for i in range(kills)
        ]
        ch_path, ch_emit, ch_ck, incarnations = run_life(
            "chaos", chaos_spec, targets,
        )
        lives = [("chaos", ch_path, ch_emit, ch_ck)]
        if rollback_free:
            ref_path, ref_emit, ref_ck, _ = run_life("ref", [], [])
            lives.append(("ref", ref_path, ref_emit, ref_ck))

        # ---- ground truth: the seeded stream's per-key-hash counts ---
        from flink_jpmml_tpu.parallel.partitioner import stable_hash_vec

        gt = np.random.default_rng(seed)
        gt.normal(0.0, 1.0, size=(records, 5))  # same draw order
        raw = gt.integers(0, keys, size=records).astype(np.float32)
        kh = stable_hash_vec(raw.astype(np.int64))
        uk, true_n = np.unique(kh, return_counts=True)
        true = dict(zip(uk.tolist(), true_n.tolist()))

        def counts(d):
            occ = d["occ"].astype(bool)
            # values carries scratch/padding rows past capacity; the
            # mirror indexes only the table proper
            vals = d["values"][: occ.shape[0]]
            return dict(zip(
                d["keys"][occ].tolist(), vals[occ, 0].tolist(),
            ))

        expected = sorted(poison)
        for tag, outpath, emitpath, ckdir in lives:
            # ---- delivery contract -----------------------------------
            covered = np.zeros(records, np.int64)
            with open(emitpath) as f:
                for line in f:
                    parts = line.split()
                    if len(parts) != 2:
                        continue  # torn final line at a SIGKILL
                    off, n = int(parts[0]), int(parts[1])
                    covered[off: off + n] += 1
            q = sorted(set(DeadLetterQueue(
                os.path.join(ckdir, "dlq")
            ).offsets()))
            assert q == expected, (
                f"stateful chaos seed={seed} ({tag}): DLQ {q} != "
                f"{expected} (spec {chaos_spec})"
            )
            missing = sorted(
                int(o) for o in np.flatnonzero(covered == 0)
            )
            assert missing == expected, (
                f"stateful chaos seed={seed} ({tag}): sink gaps "
                f"{missing[:10]} != quarantined {expected} "
                f"(spec {chaos_spec})"
            )
            # ---- exactly-once fold accounting ------------------------
            folded = counts(np.load(outpath))
            for k, n in folded.items():
                assert k in true and n <= true[k], (
                    f"stateful chaos seed={seed} ({tag}): key {k} "
                    f"folded {n} records vs {true.get(k, 0)} in the "
                    f"stream — a replay or re-dispatch double-folded "
                    f"(spec {chaos_spec})"
                )
            if rollback_free:
                deficit = sum(true.values()) - sum(folded.values())
                assert deficit == 0, (
                    f"stateful chaos seed={seed} ({tag}): {deficit} "
                    f"folds lost with no rollback source composed "
                    f"(spec {chaos_spec})"
                )

        # ---- byte parity (rollback-free compositions only) -----------
        if rollback_free:
            ref_v = np.load(ref_path)["values"]
            ch_v = np.load(ch_path)["values"]
            assert ref_v.tobytes() == ch_v.tobytes(), (
                f"stateful chaos seed={seed}: table diverged from the "
                f"fault-free reference after {incarnations} "
                f"incarnations / {kills} kills (spec {chaos_spec})"
            )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--families", default=",".join(FAMILIES))
    ap.add_argument("--seeds", type=int, default=50)
    ap.add_argument("--start", type=int, default=100_000)
    ap.add_argument("--chaos", action="store_true",
                    help="fault-composition soak instead of parity "
                         "families: each seed drives a random mix of "
                         "FJT_FAULTS kinds through a Kafka→pipeline "
                         "stream and verifies the delivery contract "
                         "(no loss, poison exactly in the DLQ)")
    ap.add_argument("--mesh", action="store_true",
                    help="with --chaos: the MESH profile instead — "
                         "chip_loss composed with kafka faults against "
                         "a mesh-sharded pipeline (simulated 8-device "
                         "host), verifying degraded-mesh serving under "
                         "churn")
    ap.add_argument("--zoo", action="store_true",
                    help="with --chaos: the ZOO profile instead — "
                         "tenant churn (Del/re-Add/version bump) "
                         "composed with device faults against the "
                         "packed multi-tenant scorer, verifying the "
                         "per-tenant delivery contract")
    ap.add_argument("--stateful", action="store_true",
                    help="with --chaos: the STATEFUL profile instead — "
                         "seeded worker crashes (SIGKILL), device_oom/"
                         "device_error streaks, and poison offsets "
                         "over a keyed stream through a state-armed "
                         "checkpointed pipeline (subprocess "
                         "incarnations), asserting state parity vs a "
                         "fault-free reference + the delivery "
                         "contract per seed")
    args = ap.parse_args()

    if args.mesh:
        # the virtual-device flag must land before the backend inits
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

    import jax

    if args.mesh:
        jax.config.update("jax_platforms", "cpu")

    print(f"backend: {jax.default_backend()}", flush=True)
    failures = 0
    if args.chaos:
        if args.zoo:
            fn, name = _soak_zoo_chaos, "zoo-chaos"
        elif args.mesh:
            fn, name = _soak_mesh_chaos, "mesh-chaos"
        elif args.stateful:
            fn, name = _soak_stateful_chaos, "stateful-chaos"
        else:
            fn, name = _soak_chaos, "chaos"
        t0 = time.perf_counter()
        ok = 0
        for s in range(args.start, args.start + args.seeds):
            try:
                fn(s)
                ok += 1
            except AssertionError as e:
                failures += 1
                print(f"FAIL {name} seed={s}: {e}", flush=True)
        dt = time.perf_counter() - t0
        print(
            f"{name}: {ok}/{args.seeds} seeds clean in {dt:.1f}s",
            flush=True,
        )
        return 1 if failures else 0
    for fam in args.families.split(","):
        fam = fam.strip()
        if fam not in FAMILIES:
            print(f"unknown family {fam!r}; have {sorted(FAMILIES)}")
            return 2
        fn = FAMILIES[fam]
        t0 = time.perf_counter()
        ok = 0
        for s in range(args.start, args.start + args.seeds):
            try:
                fn(s)
                ok += 1
            except AssertionError as e:
                failures += 1
                print(f"FAIL {fam} seed={s}: {e}", flush=True)
        dt = time.perf_counter() - t0
        print(
            f"{fam}: {ok}/{args.seeds} seeds clean in {dt:.1f}s",
            flush=True,
        )
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
