"""Extended fuzz soak: arbitrary seed ranges over the test_fuzz
generators, on any backend — the on-device evidence tool behind the
"N seeds on-device clean" claims in docs/parity.md.

The pytest suite pins fixed seed ranges so CI stays deterministic and
fast; this driver reuses the exact same generators and the exact same
lane-by-lane compiled-vs-oracle assertion, but sweeps as many seeds as
a soak budget allows, on whichever backend the session resolves
(run plainly for the real chip; FJT_TEST_PLATFORM-style CPU pinning is
the test suite's business, not this tool's).

Usage:
  python tools/fuzz_soak.py [--families trees,mining,regression,...]
                            [--seeds 100] [--start 10000]
Prints one summary line per family and exits nonzero on any parity
failure (the failing seed is in the assertion message — replay it by
passing --start <seed> --seeds 1).
"""

import argparse
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import numpy as np

from tests import test_fuzz as tf


def _soak_trees(seed):
    rng = np.random.default_rng(seed)
    doc, recs = None, None
    doc = tf._doc(tf._rand_tree_model(rng))
    recs = tf._rand_records(rng, 64)
    tf._assert_parity(doc, recs, f"tree seed={seed}")


def _soak_mining(seed):
    # mirrors TestFuzzMining.test_random_regression_ensemble_parity
    # (the generator is inline there, not a module helper)
    from flink_jpmml_tpu.pmml import ir

    rng = np.random.default_rng(seed)
    n_seg = int(rng.integers(2, 5))
    segments = tuple(
        ir.Segment(
            predicate=(
                ir.TruePredicate()
                if rng.random() < 0.5
                else tf._rand_predicate(rng, 1)
            ),
            model=ir.TreeModelIR(
                function_name="regression",
                mining_schema=tf._schema(),
                root=tf._rand_tree(rng, False, max_depth=2),
                missing_value_strategy=str(rng.choice(
                    ["none", "defaultChild", "nullPrediction"]
                )),
                split_characteristic="multiSplit",
            ),
            segment_id=f"s{i}",
            weight=float(np.round(rng.uniform(0.5, 2.0), 2)),
        )
        for i in range(n_seg)
    )
    method = str(rng.choice(
        ["sum", "average", "weightedAverage", "max", "median",
         "selectFirst"]
    ))
    model = ir.MiningModelIR(
        function_name="regression",
        mining_schema=tf._schema(),
        segmentation=ir.Segmentation(
            multiple_model_method=method, segments=segments
        ),
    )
    doc = tf._doc(model)
    recs = tf._rand_records(rng, 32)
    tf._assert_parity(doc, recs, f"mining {method} seed={seed}")


def _soak_regression(seed):
    rng = np.random.default_rng(seed)
    doc = tf._doc(tf._rand_regression_model(rng))
    recs = tf._rand_records(rng, 64)
    tf._assert_parity(doc, recs, f"regression seed={seed}")


def _soak_neural(seed):
    rng = np.random.default_rng(seed)
    doc = tf._doc(tf._rand_nn_model(rng))
    recs = tf._rand_records(rng, 64)
    tf._assert_parity(doc, recs, f"neural seed={seed}")


def _soak_glm(seed):
    rng = np.random.default_rng(seed)
    doc = tf._doc(tf._rand_glm_model(rng))
    recs = tf._rand_records(rng, 64)
    tf._assert_parity(doc, recs, f"glm seed={seed}")


FAMILIES = {
    "trees": _soak_trees,
    "mining": _soak_mining,
    "regression": _soak_regression,
    "neural": _soak_neural,
    "glm": _soak_glm,
}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--families", default=",".join(FAMILIES))
    ap.add_argument("--seeds", type=int, default=50)
    ap.add_argument("--start", type=int, default=100_000)
    args = ap.parse_args()

    import jax

    print(f"backend: {jax.default_backend()}", flush=True)
    failures = 0
    for fam in args.families.split(","):
        fam = fam.strip()
        if fam not in FAMILIES:
            print(f"unknown family {fam!r}; have {sorted(FAMILIES)}")
            return 2
        fn = FAMILIES[fam]
        t0 = time.perf_counter()
        ok = 0
        for s in range(args.start, args.start + args.seeds):
            try:
                fn(s)
                ok += 1
            except AssertionError as e:
                failures += 1
                print(f"FAIL {fam} seed={s}: {e}", flush=True)
        dt = time.perf_counter() - t0
        print(
            f"{fam}: {ok}/{args.seeds} seeds clean in {dt:.1f}s",
            flush=True,
        )
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
