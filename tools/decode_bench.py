#!/usr/bin/env python
"""Standalone Kafka record-batch decode microbench.

Races the three decode tiers of ``runtime/kafka.py``'s
``decode_record_batches_rows`` — the per-record Python walk (the
parity oracle), the vectorized numpy decoder (offset tables + bulk
gather + word-parallel CRC32C), and the native C++ decoder — over one
synthetic fixed-width tabular record set, parity-checking byte
equality before timing. Prints the same JSON row the bench artifact
embeds as ``kafka_mode.decode_bench``, so a regression in any tier is
visible both standalone and in every captured bench line.

    python tools/decode_bench.py [--records N] [--n-cols C]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

# runnable from anywhere, package install not required (cf. perf_smoke)
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--records", type=int, default=40_000,
                    help="record count for the vectorized/native tiers")
    ap.add_argument("--n-cols", type=int, default=28,
                    help="f32 features per record (wire value = 4×this)")
    ap.add_argument("--py-records", type=int, default=4_000,
                    help="record count for the (slow) python-walk tier")
    args = ap.parse_args(argv)

    from flink_jpmml_tpu.bench import run_decode_bench

    line = run_decode_bench(
        records=args.records, n_cols=args.n_cols,
        py_records=args.py_records,
    )
    print(json.dumps(line))
    return 0 if line["parity"] else 1


if __name__ == "__main__":
    sys.exit(main())
