#!/usr/bin/env python
"""Perf-smoke check for the overlapped dispatch pipeline (CI tier-1).

Runs a tiny GBM stream through the production BlockPipeline — and a raw
:class:`OverlappedDispatcher` window — under ``JAX_PLATFORMS=cpu``, and
fails loudly on exactly the regressions new concurrency code breeds:

- **ordering**: sink deliveries must arrive in contiguous offset order
  (the dispatcher's FIFO contract feeding the commit protocol);
- **loss/duplication**: every source record reaches the sink once;
- **shutdown hangs**: the whole check runs under a hard watchdog that
  dumps all thread stacks and force-exits non-zero if the pipeline
  wedges instead of draining.

Seconds-cheap by design (tier-1 guards it — tests/test_perf_smoke.py);
exit 0 = healthy, 1 = assertion failure, 2 = watchdog fired.
"""

import faulthandler
import os
import pathlib
import sys
import tempfile
import threading

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# runnable from anywhere: the repo root (one level up) on the path
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

WATCHDOG_S = float(os.environ.get("FJT_SMOKE_WATCHDOG_S", 120.0))


def _watchdog():
    """Force-exit with stacks when the pipeline wedges: a hang is the
    failure mode this smoke exists to catch, so it must terminate."""
    faulthandler.dump_traceback(file=sys.stderr)
    print(
        f"perf-smoke: WATCHDOG after {WATCHDOG_S:.0f}s — "
        "pipeline shutdown hang",
        file=sys.stderr,
        flush=True,
    )
    os._exit(2)


def check_dispatcher_ordering() -> None:
    """Raw window FIFO under adversarial completion timing: leaves that
    become ready out of order must still complete in launch order."""
    import time

    from flink_jpmml_tpu.runtime.pipeline import OverlappedDispatcher

    class _Leaf:
        def __init__(self, i):
            self.i = i
            # later launches get SHORTER waits: readiness order is the
            # reverse of launch order, the worst case for FIFO delivery
            self.delay = max(0.0, (8 - i) * 0.002)

        def block_until_ready(self):
            time.sleep(self.delay)

    seen = []
    disp = OverlappedDispatcher(
        depth=3, complete=lambda out, meta: seen.append(meta)
    )
    for i in range(32):
        disp.launch(lambda i=i: _Leaf(i), meta=i)
    disp.close()
    assert seen == list(range(32)), f"dispatcher order broke: {seen[:10]}..."
    assert len(disp) == 0, "close() left work in flight"


def check_block_pipeline() -> None:
    """Tiny GBM through the production overlapped block pipeline:
    exhaustive drain, in-order contiguous sink offsets, no loss."""
    import numpy as np

    from assets.generate import gen_gbm
    from flink_jpmml_tpu.compile import compile_pmml
    from flink_jpmml_tpu.pmml import parse_pmml_file
    from flink_jpmml_tpu.runtime.block import BlockPipeline, FiniteBlockSource

    with tempfile.TemporaryDirectory() as tmp:
        doc = parse_pmml_file(
            gen_gbm(tmp, n_trees=10, depth=3, n_features=4)
        )
    cm = compile_pmml(doc, batch_size=64)
    rng = np.random.default_rng(0)
    data = rng.normal(0.0, 1.0, size=(1000, 4)).astype(np.float32)

    deliveries = []

    def sink(out, n, first_off):
        np.asarray(out if not hasattr(out, "value") else out.value)
        deliveries.append((first_off, n))

    pipe = BlockPipeline(
        FiniteBlockSource(data, block_size=100),
        cm,
        sink,
        in_flight=3,
        use_native=False,
    )
    pipe.run_until_exhausted(timeout=60.0)

    total = sum(n for _, n in deliveries)
    assert total == 1000, f"lost/duplicated records: {total} != 1000"
    cursor = 0
    for first_off, n in deliveries:
        assert first_off == cursor, (
            f"out-of-order sink delivery at offset {first_off}, "
            f"expected {cursor}"
        )
        cursor += n
    assert pipe.committed_offset == 1000, pipe.committed_offset
    snap = pipe.metrics.snapshot()
    assert snap["records_out"] == 1000, snap["records_out"]
    assert snap["dispatches"] >= 1


def main() -> int:
    timer = threading.Timer(WATCHDOG_S, _watchdog)
    timer.daemon = True
    timer.start()
    check_dispatcher_ordering()
    print("perf-smoke: dispatcher ordering OK", flush=True)
    check_block_pipeline()
    print("perf-smoke: block pipeline drain/ordering OK", flush=True)
    timer.cancel()
    return 0


if __name__ == "__main__":
    sys.exit(main())
