#!/usr/bin/env python
"""Perf-smoke check for the overlapped dispatch pipeline (CI tier-1).

Runs a tiny GBM stream through the production BlockPipeline — and a raw
:class:`OverlappedDispatcher` window — under ``JAX_PLATFORMS=cpu``, and
fails loudly on exactly the regressions new concurrency code breeds:

- **ordering**: sink deliveries must arrive in contiguous offset order
  (the dispatcher's FIFO contract feeding the commit protocol);
- **loss/duplication**: every source record reaches the sink once;
- **shutdown hangs**: the whole check runs under a hard watchdog that
  dumps all thread stacks and force-exits non-zero if the pipeline
  wedges instead of draining;
- **fused-encode divergence**: the on-device featurize stage
  (compile/qtrees.py fused path) must stay byte-identical to the host
  bucketizer, through the production pipeline too;
- **autotune-cache fragility**: a corrupt on-disk autotune cache must
  read as empty (silent re-tune) — never crash a compile or a sweep;
- **kernel-search budget rot**: the learned predict-then-verify search
  (compile/costmodel.py + autotune) must time at most top-K of the
  layout×tile candidate space, land its timings in the kernel cost
  ledger as feature rows a replayed fit predicts within a sane band,
  treat a stale search-space tag as no cache entry, and keep the
  ``--no-kernel-search`` ablation flag wired;
- **scrape-surface rot**: a live pipeline's ``/metrics`` endpoint
  (obs/server.py) must serve parseable Prometheus text whose
  ``fjt_records_out`` is non-zero and whose histogram ``_count``
  matches its ``+Inf`` bucket — the fleet dashboard's ground truth —
  and, since the attribution plane landed, non-zero per-stage
  ``fjt_stage_seconds`` histograms, a live ``fjt_device_mfu`` gauge,
  and at least one Prometheus exemplar whose trace id resolves to a
  ``latency_exemplar`` flight-recorder event;
- **observability overhead**: the stage ledger + sampled device
  profiler must cost ≤2% of hand-loop dispatch throughput — measured
  as per-launch attribution ops against per-launch dispatch time (the
  tripwire for anyone adding per-batch work to the obs plane);
- **rollout-plane drift**: the canary hash split must hand the
  candidate its configured fraction ±1% with zero shadow-traffic sink
  leakage (the ``bench.py --rollout-drill`` engine at smoke scale);
- **freshness-plane rot**: the ``bench.py --load-shape burst:2x``
  burst-recovery drill at smoke scale — event-time ``watermark_lag_s``
  must build under a 2× burst and recover within a bounded drain
  window with a finite ``lag_drain_eta_s`` en route, the composite
  ``pressure`` score must reach ≥0.5 under the burst and decay after,
  and a live mid-drain ``/metrics`` scrape must expose non-zero
  ``record_staleness_s`` buckets, ``pressure`` in [0,1], and
  per-partition ``watermark_lag_s`` (the acceptance surface ROADMAP
  item 5's adaptive-batching controller will read);
- **overload-plane rot**: the ``bench.py --overload-drill`` engine at
  smoke scale — p99 ≤ deadline at 80% of measured capacity, bounded
  p99 plus a NON-ZERO explicit ``shed_records`` counter at 150%
  offered load, and post-surge recovery to <1.05× the steady-state
  p99 (ROADMAP item 5's acceptance drill, tier-1-guarded);
- **drift-plane rot**: the ``bench.py --drift-drill`` engine at smoke
  scale — the perturbed feature's ``drift_alarm`` fires while the
  control feature stays quiet and the fleet-merged sketch quantiles
  equal the per-worker state merge exactly — plus a live ``/metrics``
  scrape of a baselined production pipeline asserting non-zero
  ``fjt_drift_score`` gauges and feature-profile counters, and the
  ≤2%-of-dispatch overhead bound on the sampled profile path (the
  unsampled gate is µs-scale, and the accumulated-overhead budget
  keeps the sampled work under 2% of wall clock by construction);
- **journey-trace rot**: the record-journey plane (``obs/trace.py``) —
  the unarmed per-dispatch gate must stay ≤2µs, the accumulated-
  overhead budget must hold when armed (a zero-budget store sheds its
  own bookkeeping, never the pipeline's throughput), and a live
  ``/trace`` scrape must retrieve ≥1 complete journey whose sink hop's
  trace id matches a ``latency_exemplar`` flight event (the
  fjt-top → fjt-trace pivot's ground truth);
- **device-fault-plane rot**: the recovery ladder (``runtime/
  devfault.py`` + ``serving/failover.py``) at smoke scale — an
  injected persistent ``device_error`` streak must trip the circuit
  breaker onto the host fallback tier (a live ``/metrics`` scrape
  mid-outage shows ``fjt_failover_state`` open and non-zero
  ``fjt_fallback_records``), the breaker must re-close on green
  probes, redispatch must land records, the stream must drain with
  zero loss, and the unarmed device fault-hook sites must stay ≤2µs;
- **fault-hook overhead**: with ``FJT_FAULTS`` unset, the injection
  hooks on the fetch/dispatch/checkpoint/score paths
  (``runtime/faults.py fire()``) must be a genuine no-op — sub-µs per
  call and no installed plan — so the harness costs nothing when it
  isn't drilling.

Seconds-cheap by design (tier-1 guards it — tests/test_perf_smoke.py);
exit 0 = healthy, 1 = assertion failure, 2 = watchdog fired.
"""

import faulthandler
import os
import pathlib
import shutil
import sys
import tempfile
import threading

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# runnable from anywhere: the repo root (one level up) on the path
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

WATCHDOG_S = float(os.environ.get("FJT_SMOKE_WATCHDOG_S", 150.0))

# hermetic autotune cache: the smoke must neither inherit a developer's
# real ~/.cache entries (a cached "fused" config would change which
# path check_block_pipeline exercises) nor pollute them
os.environ["FJT_AUTOTUNE_CACHE"] = os.path.join(
    tempfile.mkdtemp(prefix="fjt-smoke-at-"), "autotune.json"
)


def _watchdog():
    """Force-exit with stacks when the pipeline wedges: a hang is the
    failure mode this smoke exists to catch, so it must terminate."""
    faulthandler.dump_traceback(file=sys.stderr)
    print(
        f"perf-smoke: WATCHDOG after {WATCHDOG_S:.0f}s — "
        "pipeline shutdown hang",
        file=sys.stderr,
        flush=True,
    )
    os._exit(2)


def check_dispatcher_ordering() -> None:
    """Raw window FIFO under adversarial completion timing: leaves that
    become ready out of order must still complete in launch order."""
    import time

    from flink_jpmml_tpu.runtime.pipeline import OverlappedDispatcher

    class _Leaf:
        def __init__(self, i):
            self.i = i
            # later launches get SHORTER waits: readiness order is the
            # reverse of launch order, the worst case for FIFO delivery
            self.delay = max(0.0, (8 - i) * 0.002)

        def block_until_ready(self):
            time.sleep(self.delay)

    seen = []
    disp = OverlappedDispatcher(
        depth=3, complete=lambda out, meta: seen.append(meta)
    )
    for i in range(32):
        disp.launch(lambda i=i: _Leaf(i), meta=i)
    disp.close()
    assert seen == list(range(32)), f"dispatcher order broke: {seen[:10]}..."
    assert len(disp) == 0, "close() left work in flight"


def check_block_pipeline() -> None:
    """Tiny GBM through the production overlapped block pipeline:
    exhaustive drain, in-order contiguous sink offsets, no loss."""
    import numpy as np

    from assets.generate import gen_gbm
    from flink_jpmml_tpu.compile import compile_pmml
    from flink_jpmml_tpu.pmml import parse_pmml_file
    from flink_jpmml_tpu.runtime.block import BlockPipeline, FiniteBlockSource

    with tempfile.TemporaryDirectory() as tmp:
        doc = parse_pmml_file(
            gen_gbm(tmp, n_trees=10, depth=3, n_features=4)
        )
    cm = compile_pmml(doc, batch_size=64)
    rng = np.random.default_rng(0)
    data = rng.normal(0.0, 1.0, size=(1000, 4)).astype(np.float32)

    deliveries = []

    def sink(out, n, first_off):
        np.asarray(out if not hasattr(out, "value") else out.value)
        deliveries.append((first_off, n))

    pipe = BlockPipeline(
        FiniteBlockSource(data, block_size=100),
        cm,
        sink,
        in_flight=3,
        use_native=False,
    )
    pipe.run_until_exhausted(timeout=60.0)

    total = sum(n for _, n in deliveries)
    assert total == 1000, f"lost/duplicated records: {total} != 1000"
    cursor = 0
    for first_off, n in deliveries:
        assert first_off == cursor, (
            f"out-of-order sink delivery at offset {first_off}, "
            f"expected {cursor}"
        )
        cursor += n
    assert pipe.committed_offset == 1000, pipe.committed_offset
    snap = pipe.metrics.snapshot()
    assert snap["records_out"] == 1000, snap["records_out"]
    assert snap["dispatches"] >= 1


def check_kafka_pipeline() -> None:
    """Pipelined-ingest tripwire (ISSUE 14): the Kafka wire path with
    the prefetch/decode sidecar armed end to end — in-order no-loss
    delivery through a real (loopback) broker, a non-zero
    ``prefetch_depth`` high-water proving the sidecar actually ran
    ahead, decode-tier byte parity (python walk vs vectorized numpy),
    and the ``--no-prefetch`` ablation (serial ingest) still passing
    the same ordering contract."""
    import time

    import numpy as np

    from assets.generate import gen_gbm
    from flink_jpmml_tpu.compile import compile_pmml
    from flink_jpmml_tpu.pmml import parse_pmml_file
    from flink_jpmml_tpu.runtime.block import BlockPipeline
    from flink_jpmml_tpu.runtime.kafka import (
        KafkaBlockSource,
        MiniKafkaBroker,
        decode_record_batches_rows_py,
        decode_record_batches_rows_vec,
        encode_record_batch,
    )
    from flink_jpmml_tpu.runtime.prefetch import PrefetchedBlockSource
    from flink_jpmml_tpu.utils.metrics import MetricsRegistry

    with tempfile.TemporaryDirectory() as tmp:
        doc = parse_pmml_file(
            gen_gbm(tmp, n_trees=10, depth=3, n_features=4)
        )
    cm = compile_pmml(doc, batch_size=64)
    rng = np.random.default_rng(3)
    data = rng.normal(size=(6000, 4)).astype(np.float32)
    data[17, 2] = np.nan  # missing-value lane rides the wire too

    # decode-tier parity: canonical layout AND the header-carrying
    # fallback must be byte-identical to the python oracle
    vals = [data[i].tobytes() for i in range(256)]
    for hdrs in (None, [[("traceparent", b"00-ab-cd-01")]] + [None] * 255):
        buf = encode_record_batch(7, vals, timestamp_ms=5, headers=hdrs)
        o1, r1 = decode_record_batches_rows_py(buf, 4)
        o2, r2 = decode_record_batches_rows_vec(buf, 4)
        assert (o1 == o2).all() and r1.tobytes() == r2.tobytes(), (
            "vectorized decode diverged from the python oracle"
        )

    def run(prefetch: bool) -> dict:
        broker = MiniKafkaBroker(topic="smoke")
        src = None
        try:
            broker.append_rows(data)
            km = MetricsRegistry()
            src = KafkaBlockSource(
                broker.host, broker.port, "smoke",
                n_cols=4, max_wait_ms=20, metrics=km,
            )
            deliveries = []

            def sink(out, n, first_off):
                deliveries.append((first_off, n))

            pipe = BlockPipeline(
                src, cm, sink, metrics=km, in_flight=2,
                prefetch=prefetch,
            )
            if prefetch:
                assert isinstance(pipe._source, PrefetchedBlockSource)
            else:
                assert pipe._source is src, "ablation still wrapped"
            pipe.start()
            deadline = time.monotonic() + 60.0
            while (
                sum(n for _, n in deliveries) < 6000
                and time.monotonic() < deadline
            ):
                time.sleep(0.02)
            pipe.stop()
            pipe.join(timeout=30.0)
            total = sum(n for _, n in deliveries)
            assert total == 6000, f"lost records: {total} != 6000"
            cursor = 0
            for first_off, n in deliveries:
                assert first_off == cursor, (
                    f"out-of-order delivery at {first_off} != {cursor} "
                    f"(prefetch={prefetch})"
                )
                cursor += n
            return km.struct_snapshot()
        finally:
            if src is not None:
                src.close()
            broker.close()

    snap = run(True)
    assert snap["gauges"]["prefetch_depth"]["max"] > 0, (
        "prefetch sidecar never queued a batch ahead"
    )
    assert snap["counters"].get("prefetch_batches", 0) >= 1, (
        snap["counters"]
    )
    snap2 = run(False)
    assert "prefetch_batches" not in snap2["counters"], (
        "--no-prefetch ablation still ran the sidecar"
    )


def check_fused_pipeline_parity() -> None:
    """Fused on-device encode through the production BlockPipeline:
    byte-identical codes vs the host bucketizer, and identical decoded
    scores for the whole stream (no loss, no divergence)."""
    import numpy as np

    from assets.generate import gen_gbm
    from flink_jpmml_tpu.compile import compile_pmml
    from flink_jpmml_tpu.pmml import parse_pmml_file
    from flink_jpmml_tpu.runtime.block import BlockPipeline, FiniteBlockSource

    with tempfile.TemporaryDirectory() as tmp:
        doc = parse_pmml_file(
            gen_gbm(tmp, n_trees=10, depth=3, n_features=4)
        )
    cm = compile_pmml(doc, batch_size=64)
    q = cm.quantized_scorer()
    assert q is not None and q.supports_fused, "fused path unavailable"
    rng = np.random.default_rng(1)
    data = rng.normal(0.0, 1.5, size=(1000, 4)).astype(np.float32)
    data[rng.random(size=data.shape) < 0.2] = np.nan

    # 1) encode-stage byte parity
    host_codes = q.wire.encode(data)
    dev_codes = np.asarray(q.encode_device(data))
    assert dev_codes.dtype == host_codes.dtype
    assert np.array_equal(dev_codes, host_codes), "fused encode diverged"

    # 2) whole-stream parity through the production pipeline: host-mode
    # run vs fused-mode run over the same stream — identical dispatch
    # shapes, so byte-identical codes must mean BIT-identical scores
    def run_pipeline(mode):
        q.encode_mode = mode
        got = np.full((1000,), np.nan, np.float32)

        def sink(out, n, first_off):
            vals = np.asarray(
                out if not hasattr(out, "value") else out.value, np.float32
            )[:n]
            got[first_off : first_off + n] = vals

        pipe = BlockPipeline(
            FiniteBlockSource(data, block_size=100),
            cm,
            sink,
            in_flight=2,
            use_native=False,
        )
        pipe.run_until_exhausted(timeout=60.0)
        assert np.isfinite(got).all(), f"{mode} pipeline lost records"
        return got, pipe.metrics.snapshot()

    ref, snap_host = run_pipeline("host")
    got, snap_fused = run_pipeline("fused")
    # the two runs may pick different drain/aggregation boundaries (the
    # fill-or-deadline ring is timing-dependent), so scores compare at
    # f32 noise tolerance; the CODES above are the bit-exactness check
    assert np.allclose(got, ref, rtol=1e-5, atol=1e-6), (
        "fused pipeline scores diverged from the host-encode oracle"
    )
    # fused ships raw f32 (4 bytes/feature) vs the uint8 wire (1): the
    # staged-bytes accounting must reflect it (ratio has slack because
    # per-run padding differs with drain boundaries)
    ratio = snap_fused["h2d_bytes"] / max(snap_host["h2d_bytes"], 1)
    assert 3.5 < ratio < 4.6, (
        f"fused h2d accounting wrong (bytes ratio {ratio:.2f}, expected ~4)"
    )


def check_autotune_cache_roundtrip() -> None:
    """Sweep → persist → cache-consult round trip, plus the corrupt-file
    contract: garbage on disk means silent re-tune, not a crash."""
    import json

    import numpy as np

    from assets.generate import gen_gbm
    from flink_jpmml_tpu.compile import autotune
    from flink_jpmml_tpu.compile.qtrees import build_quantized_scorer
    from flink_jpmml_tpu.pmml import parse_pmml_file

    with tempfile.TemporaryDirectory() as tmp:
        doc = parse_pmml_file(
            gen_gbm(tmp, n_trees=10, depth=3, n_features=4)
        )
    rng = np.random.default_rng(2)
    X = rng.normal(0.0, 1.5, size=(64, 4)).astype(np.float32)
    prev_cache = os.environ.get("FJT_AUTOTUNE_CACHE")
    with tempfile.TemporaryDirectory() as tmp:
        os.environ["FJT_AUTOTUNE_CACHE"] = os.path.join(tmp, "at.json")
        try:
            q = build_quantized_scorer(doc, batch_size=64)
            cfg = autotune.ensure_tuned(q, X, repeats=1)
            assert cfg.source == "sweep"
            with open(autotune.cache_path()) as f:
                assert json.load(f)["entries"], "sweep did not persist"
            q2 = build_quantized_scorer(doc, batch_size=64)
            assert q2.tuned is not None and q2.tuned.source == "cache", (
                "fresh compile did not consult the cache"
            )
            # corrupt the file: everything must keep working silently
            with open(autotune.cache_path(), "w") as f:
                f.write("\x00garbage{{{")
            q3 = build_quantized_scorer(doc, batch_size=64)  # no crash
            assert q3.tuned is None
            cfg3 = autotune.ensure_tuned(q3, X, repeats=1)
            assert cfg3.source == "sweep", "corrupt cache did not re-tune"
            with open(autotune.cache_path()) as f:
                assert json.load(f)["entries"], "re-tune did not rewrite"
        finally:
            if prev_cache is None:
                os.environ.pop("FJT_AUTOTUNE_CACHE", None)
            else:
                os.environ["FJT_AUTOTUNE_CACHE"] = prev_cache


def check_kernel_search() -> None:
    """Learned kernel search tripwire (ISSUE 11): the predict-then-
    verify search must complete within its candidate budget (top-K
    timed, NOT the full layout×tile space), feed the kernel cost
    ledger rows a ledger-replay fit predicts within a sane band, honor
    the stale-space-tag invalidation, and keep the
    ``--no-kernel-search`` bench ablation flag wired."""
    import json
    import math

    import numpy as np

    from assets.generate import gen_gbm
    from flink_jpmml_tpu.compile import autotune, costmodel, layouts
    from flink_jpmml_tpu.compile.qtrees import build_quantized_scorer
    from flink_jpmml_tpu.obs import profiler
    from flink_jpmml_tpu.pmml import parse_pmml_file

    with tempfile.TemporaryDirectory() as tmp:
        doc = parse_pmml_file(
            gen_gbm(tmp, n_trees=12, depth=3, n_features=4)
        )
    rng = np.random.default_rng(6)
    X = rng.normal(0.0, 1.5, size=(64, 4)).astype(np.float32)
    prev_cache = os.environ.get("FJT_AUTOTUNE_CACHE")
    with tempfile.TemporaryDirectory() as tmp:
        os.environ["FJT_AUTOTUNE_CACHE"] = os.path.join(tmp, "at.json")
        try:
            q = build_quantized_scorer(
                doc, batch_size=64, backend="pallas", pallas_interpret=True
            )
            cfg = autotune.ensure_tuned(q, X, repeats=1, top_k=3)
            s = cfg.search
            assert s is not None and s["space"] == layouts.SPACE_TAG
            # the budget: top-K timed, not the full space
            assert s["timed"] <= s["top_k"] == 3, s
            assert s["candidates_total"] > s["top_k"], s
            assert cfg.layout in (
                "ref", "bfs", "mega", "mega_bfs"
            ), cfg.layout
            # every timed candidate became a ledger training row with
            # features, and a replayed fit predicts each row within a
            # sane band (interpret-mode timings are noisy; the band
            # checks sanity, not precision)
            rows = costmodel.training_rows(
                profiler.cost_ledger_path()
            )
            assert len(rows) >= s["timed"] > 0, (len(rows), s)
            model = costmodel.fit_from_ledger(
                path=profiler.cost_ledger_path(), min_rows=1
            )
            assert model is not None, "ledger replay produced no fit"
            for feats, y in rows:
                pred = model.predict(feats)
                assert pred is not None and pred > 0
                assert abs(math.log(pred / y)) < math.log(16.0), (
                    f"ledger-replay prediction {pred} vs observed {y} "
                    "outside the 16x sanity band"
                )
            # stale space tag ⇒ silent re-search (the cached pre-layout
            # winner must never pin a new binary)
            key = autotune.backend_key(q)
            path = autotune.cache_path()
            data = json.load(open(path))
            entry = data["entries"][f"{q.model_hash}|{key}"]
            entry["space"] = "space-v0:obsolete"
            path.write_text(json.dumps(data))
            assert autotune.lookup(q.model_hash, key) is None, (
                "an obsolete-space cache entry was honoured"
            )
            # the --no-kernel-search ablation gate: legacy ref-only
            # tile sweep, no layout candidates
            os.environ["FJT_KERNEL_SEARCH_DISABLE"] = "1"
            try:
                q2 = build_quantized_scorer(
                    doc, batch_size=64, backend="pallas",
                    pallas_interpret=True,
                )
                cfg2 = autotune.sweep(q2, X, repeats=1, top_k=3)
                assert cfg2.search["mode"] == "legacy", cfg2.search
                assert cfg2.layout == "ref"
            finally:
                os.environ.pop("FJT_KERNEL_SEARCH_DISABLE", None)
        finally:
            if prev_cache is None:
                os.environ.pop("FJT_AUTOTUNE_CACHE", None)
            else:
                os.environ["FJT_AUTOTUNE_CACHE"] = prev_cache
    # the bench flag itself stays wired (parse-level, no measurement)
    from flink_jpmml_tpu.bench import build_arg_parser

    ns = build_arg_parser().parse_args(["--no-kernel-search"])
    assert ns.no_kernel_search and not ns.kernel_search
    ns = build_arg_parser().parse_args(["--kernel-search"])
    assert ns.kernel_search


def check_obs_scrape() -> None:
    """Live-pipeline /metrics tripwire: run a small stream with an
    ObsServer attached to its registry, scrape over real HTTP, and
    assert the scrape is a truthful Prometheus rendering — non-zero
    ``fjt_records_out``, histogram ``_count`` == ``+Inf`` bucket,
    non-zero per-stage ``fjt_stage_seconds`` attribution, a live
    ``fjt_device_mfu`` gauge (the sampled profiler fired), and ≥1
    exemplar resolving to a ``latency_exemplar`` flight event."""
    import re
    import urllib.request

    import numpy as np

    from assets.generate import gen_gbm
    from flink_jpmml_tpu.compile import compile_pmml
    from flink_jpmml_tpu.obs import recorder as flight
    from flink_jpmml_tpu.obs.server import ObsServer
    from flink_jpmml_tpu.pmml import parse_pmml_file
    from flink_jpmml_tpu.runtime.block import BlockPipeline, FiniteBlockSource

    with tempfile.TemporaryDirectory() as tmp:
        doc = parse_pmml_file(
            gen_gbm(tmp, n_trees=10, depth=3, n_features=4)
        )
    cm = compile_pmml(doc, batch_size=64)
    rng = np.random.default_rng(3)
    data = rng.normal(0.0, 1.0, size=(1000, 4)).astype(np.float32)

    def sink(out, n, first_off):
        np.asarray(out if not hasattr(out, "value") else out.value)

    pipe = BlockPipeline(
        FiniteBlockSource(data, block_size=100), cm, sink,
        in_flight=2, use_native=False,
    )
    srv = ObsServer.for_registry(pipe.metrics)
    try:
        pipe.run_until_exhausted(timeout=60.0)
        # a plain scrape serves classic 0.0.4 — which must stay free of
        # exemplar suffixes (a stock text parser rejects a page with
        # them); the OpenMetrics-negotiated scrape carries them
        with urllib.request.urlopen(srv.url + "/metrics", timeout=10) as r:
            assert r.status == 200
            assert "trace_id" not in r.read().decode(), (
                "exemplars leaked into a classic 0.0.4 scrape"
            )
        req = urllib.request.Request(
            srv.url + "/metrics",
            headers={"Accept": "application/openmetrics-text"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200
            assert "openmetrics-text" in r.headers.get("Content-Type", "")
            text = r.read().decode()
        assert text.endswith("# EOF\n"), "OpenMetrics page missing # EOF"
        metrics = {}
        for line in text.splitlines():
            if line.startswith("#") or not line.strip():
                continue
            # exemplar suffixes (` # {trace_id="..."} v ts`) are not
            # part of the sample value
            line = line.split(" # ", 1)[0]
            name, value = line.rsplit(" ", 1)
            metrics[name] = float(value)
        assert metrics.get("fjt_records_out") == 1000, (
            f"scraped fjt_records_out={metrics.get('fjt_records_out')}"
            " != 1000"
        )
        assert metrics.get("fjt_dispatches", 0) >= 1
        inf_bucket = metrics.get('fjt_batch_latency_s_bucket{le="+Inf"}')
        assert inf_bucket is not None and inf_bucket >= 1, (
            "batch latency histogram missing from the scrape"
        )
        assert metrics.get("fjt_batch_latency_s_count") == inf_bucket, (
            "histogram _count != +Inf bucket — non-cumulative render"
        )
        # the attribution plane: per-stage histograms with samples
        stage_counts = {
            name: v for name, v in metrics.items()
            if name.startswith("fjt_stage_seconds_count")
        }
        assert stage_counts and any(v > 0 for v in stage_counts.values()), (
            f"no stage_seconds attribution in the scrape: {stage_counts}"
        )
        for stage in ("encode", "sink"):
            key = f'fjt_stage_seconds_count{{stage="{stage}"}}'
            assert metrics.get(key, 0) > 0, f"{key} missing/zero"
        # the live roofline: the sampled device profiler must have
        # fired at least once during a real pipeline run
        assert metrics.get("fjt_device_samples", 0) >= 1, (
            "device profiler never sampled"
        )
        assert metrics.get("fjt_device_mfu", 0) > 0, (
            "live fjt_device_mfu gauge missing/zero"
        )
        # ≥1 exemplar on the wire, resolvable to its flight event
        tids = re.findall(r'# \{trace_id="([^"]+)"\}', text)
        assert tids, "no Prometheus exemplars in the scrape"
        flight_tids = {
            e.get("trace_id") for e in flight.events()
            if e.get("kind") == "latency_exemplar"
        }
        assert set(tids) & flight_tids, (
            "scraped exemplar trace ids don't resolve to "
            "latency_exemplar flight events"
        )
        with urllib.request.urlopen(srv.url + "/healthz", timeout=10) as r:
            assert r.status == 200
    finally:
        srv.close()


def check_attribution_overhead() -> None:
    """Observability-overhead tripwire: the per-launch attribution work
    (stage ledger observes + the profiler's sampling predicate) must
    cost ≤2% of dispatch-loop throughput; the 'off' arm is the
    identical dispatcher with its ledger/profiler stripped (the
    pre-attribution hot path).

    Estimator: this runs on shared CI machines whose load bursts swing
    a short window's throughput several-fold, so ANY on-vs-off
    differential (medians, paired windows — both tried) flakes. The
    throughput delta equals per_launch_attr_cost / per_launch_time, so
    measure the two factors directly instead, each as ONE long
    continuous timing (bursts average out within a measurement and
    cancel between two back-to-back ones): the real attributed
    dispatch loop for the denominator, and a tight loop over exactly
    the ops a steady-state launch adds — one ``queue_wait``
    ledger-observe, the sampling predicate, and the per-launch
    ``dispatch_profile`` build — for the numerator."""
    import time

    import numpy as np

    from flink_jpmml_tpu.obs import attr, profiler
    from flink_jpmml_tpu.runtime.pipeline import OverlappedDispatcher
    from flink_jpmml_tpu.utils.metrics import MetricsRegistry

    a = np.random.default_rng(4).normal(size=(128, 128)).astype(np.float32)

    class _Leaf:
        __slots__ = ()

        def block_until_ready(self):
            pass

    _leaf = _Leaf()

    def dispatch():
        # ~1 ms of real numpy work per launch — the scale of a real
        # full-batch dispatch, so the per-launch attribution cost (a
        # few µs) is judged against a production-shaped denominator
        for _ in range(24):
            np.dot(a, a)
        return _leaf

    m_on = MetricsRegistry()
    prof = profiler.DeviceProfiler(m_on, interval_s=0.25)
    ledger = attr.ledger_for(m_on)
    prof_payload = {"records": 64, "flops_per_record": 1280.0,
                    "bytes_per_record": 6.0, "model": "smoke",
                    "backend": "fake"}

    disp = OverlappedDispatcher(depth=2, metrics=m_on, profiler=prof)
    assert disp._ledger is ledger
    for _ in range(20):  # warm allocator + code paths
        disp.launch(dispatch, profile=prof_payload)
    launches = 400
    t0 = time.perf_counter()
    for _ in range(launches):
        disp.launch(dispatch, profile=prof_payload)
    per_launch = (time.perf_counter() - t0) / launches
    disp.close()

    # a representative scorer stand-in so dispatch_profile walks its
    # real getattr/cache path (params shape scan caches on first call)
    class _FakeWire:
        fields = ["a", "b", "c", "d"]
        bytes_per_record = 8.0

    class _FakeScorer:
        params = {"split": np.zeros((10, 8, 8), dtype=np.float32)}
        wire = _FakeWire()
        backend = "fake"
        encode_mode = "host"
        model_hash = "smoke"

    fake_q = _FakeScorer()
    n = 50_000
    t0 = time.perf_counter()
    for _ in range(n):
        ledger.observe("queue_wait", 3e-4)
        prof.should_sample()
        # every real launch site builds this per launch too
        # (block.py / scorer.py pass it as profile=)
        attr.dispatch_profile(fake_q, 64)
    per_attr = (time.perf_counter() - t0) / n

    ratio = per_attr / per_launch
    assert ratio <= 0.02, (
        f"attribution overhead {100 * ratio:.2f}% > 2% "
        f"({per_attr * 1e6:.2f}µs attr ops vs "
        f"{per_launch * 1e6:.0f}µs per launch)"
    )
    # the on-arm must actually have attributed something, or the
    # comparison proves nothing
    snap = m_on.struct_snapshot()
    assert any(
        k.startswith("stage_seconds") for k in snap["histograms"]
    ), "on-arm recorded no stage attribution"
    assert snap["counters"].get("device_samples", 0) >= 1, (
        "on-arm profiler never sampled"
    )


def check_rollout_drill() -> None:
    """Rollout control-plane tripwire: the bench drill's engine at smoke
    scale — canary split ratio ±1% absolute, zero shadow sink leakage,
    zero disagreement on a byte-identical candidate. (The end-to-end
    guardrail promote/rollback drills live in tests/test_rollout.py;
    this guards the routing arithmetic every one of them rests on.)"""
    from flink_jpmml_tpu.bench import run_rollout_drill

    line = run_rollout_drill(records=4096, fraction=0.2, batch=256)
    assert line["ok"], line
    assert line["shadow_compared"] > 0, line


def check_freshness_burst_drill() -> None:
    """Burst-recovery tripwire: the ``--load-shape burst:2x`` drill at
    smoke scale, with the live mid-drain ``/metrics`` scrape asserted
    against the freshness plane's acceptance surface. The drill's own
    geometry (sink deadline-paced between base and burst rate) keeps it
    host-speed-independent; shrunk phases keep it seconds-cheap."""
    import re

    from flink_jpmml_tpu.bench import run_burst_drill

    line = run_burst_drill(
        base_rate=8_000.0,
        burst_factor=2.0,
        steady_s=1.5,
        burst_s=2.5,
        drain_timeout_s=15.0,
        scrape=True,
    )
    assert line["ok"], {k: line[k] for k in ("checks", "recovery_s",
                                             "peak_wm_lag_s",
                                             "peak_pressure")}
    checks = line["checks"]
    assert checks["recovered"] and checks["lag_built"], checks
    assert checks["pressure_peaked"] and checks["pressure_decayed"], checks
    assert checks["eta_finite_during_drain"], checks
    assert line["recovery_s"] is not None and line["recovery_s"] <= 15.0
    assert line["records_scored"] > 0

    # the live scrape captured mid-drain: the fleet dashboard's view of
    # the same drill must carry the freshness families with real values
    text = line["metrics_scrape"]
    assert text, "burst drill captured no /metrics page"
    samples = {}
    for ln in text.splitlines():
        if ln.startswith("#") or not ln.strip():
            continue
        name, value = ln.split(" # ", 1)[0].rsplit(" ", 1)
        samples[name] = float(value)
    inf = samples.get('fjt_record_staleness_s_bucket{le="+Inf"}')
    assert inf is not None and inf > 0, (
        "no record_staleness_s observations in the live scrape"
    )
    assert samples.get("fjt_record_staleness_s_count") == inf
    p = samples.get("fjt_pressure")
    assert p is not None and 0.0 <= p <= 1.0, f"fjt_pressure={p}"
    wm_keys = [
        k for k in samples
        if re.match(r'fjt_watermark_lag_s\{partition="[^"]+"\}', k)
    ]
    assert wm_keys, "no per-partition fjt_watermark_lag_s in the scrape"
    assert all(samples[k] >= 0 for k in wm_keys)
    assert "fjt_lag_drain_eta_s" in samples
    assert samples.get("fjt_watermark_ts", 0) > 1e9  # a real event time

    # the artifact's embedded varz struct carries the same families
    # (the bench-artifact contract fjt-top --freshness renders)
    varz = line["varz"]
    assert varz["histograms"]["record_staleness_s"]["n"] > 0
    assert "pressure" in varz["gauges"]
    assert 'watermark_lag_s{partition="0"}' in varz["gauges"]


def check_overload_drill() -> None:
    """Overload tripwire: the ``--overload-drill`` engine at smoke
    scale. Asserts the three ROADMAP item 5 acceptance properties —
    deadline met at 80% capacity, bounded-p99 + explicit shed at 150%,
    clean recovery — against THIS host's measured capacity (the drill
    self-calibrates, so it is as meaningful on a CI CPU as on a TPU)."""
    from flink_jpmml_tpu.bench import run_overload_drill

    line = run_overload_drill(phase_s=2.0, surge_s=2.5,
                              drain_timeout_s=10.0)
    assert line["ok"], line["checks"]
    assert all(line["checks"].values()), line["checks"]
    assert line["shed_records"] > 0, line["shed_records"]
    assert line["p99_base_ms"] <= line["deadline_ms"], (
        line["p99_base_ms"], line["deadline_ms"],
    )
    # recovery is the drill's own check (1.05x with a small absolute
    # floor for sub-ms baselines); don't re-derive a stricter one here
    # the artifact's struct carries the overload families the
    # fjt-top --overload panel renders
    varz = line["varz"]
    assert "shed_level" in varz["gauges"]
    assert 'shed_records{lane="block"}' in varz["counters"]
    assert varz["counters"]["admitted_records"] > 0


def check_drift_plane() -> None:
    """Data-drift-plane tripwire: (1) the bench drill at smoke scale —
    right feature alarms, control stays quiet, fleet merge exact; (2) a
    baselined production BlockPipeline whose live /metrics scrape
    carries real drift telemetry; (3) the dispatch-path overhead bound:
    the unsampled per-dispatch gate vs a production-shaped ~1 ms launch
    (the attribution-tripwire estimator), and the sampled path held
    ≤2% of wall clock by the plane's accumulated-overhead budget."""
    import time
    import urllib.request

    import numpy as np

    from assets.generate import gen_gbm
    from flink_jpmml_tpu.bench import run_drift_drill
    from flink_jpmml_tpu.compile import compile_pmml
    from flink_jpmml_tpu.obs import drift
    from flink_jpmml_tpu.obs.server import ObsServer
    from flink_jpmml_tpu.pmml import parse_pmml_file
    from flink_jpmml_tpu.runtime.block import BlockPipeline, FiniteBlockSource
    from flink_jpmml_tpu.utils.metrics import MetricsRegistry

    # 1) the drill engine at smoke scale
    line = run_drift_drill(records_per_phase=4096, batch=256)
    assert line["ok"] and line["merge_exact"], line
    model = line["model"]
    assert line["perturbed_feature"] in (
        line["drift"][model]["alarmed_features"]
    ), line["drift"]
    assert line["psi_control"] < 0.25, line["psi_control"]
    # the drill's artifact carries the drift varz family
    assert line["varz"]["sketches"], "drill varz carries no sketches"

    # 2) live pipeline scrape: baseline → shifted stream → /metrics
    with tempfile.TemporaryDirectory() as tmp:
        doc = parse_pmml_file(
            gen_gbm(tmp, n_trees=10, depth=3, n_features=4)
        )
        cm = compile_pmml(doc, batch_size=64)
        rng = np.random.default_rng(5)
        base = rng.normal(0.0, 1.0, size=(1000, 4)).astype(np.float32)
        shifted = base.copy()
        shifted[:, 1] += 4.0
        metrics = MetricsRegistry()
        store = drift.BaselineStore(os.path.join(tmp, "bl"))
        plane = drift.install(
            metrics, interval_s=0.0, budget_frac=0, store=store
        )
        mon = plane.monitor
        mon.min_n = 200
        mon.dwell_s = 0.0
        mon._interval = 0.0

        def sink(out, n, first_off):
            np.asarray(out if not hasattr(out, "value") else out.value)

        def run_stream(data):
            pipe = BlockPipeline(
                FiniteBlockSource(data, block_size=100), cm, sink,
                in_flight=2, use_native=False, metrics=metrics,
            )
            pipe.run_until_exhausted(timeout=60.0)

        run_stream(base)
        saved = drift.snapshot_registry(metrics, store=store)
        assert saved, "pipeline recorded no drift profiles to baseline"
        run_stream(shifted)
        srv = ObsServer.for_registry(metrics)
        try:
            with urllib.request.urlopen(
                srv.url + "/metrics", timeout=10
            ) as r:
                assert r.status == 200
                text = r.read().decode()
        finally:
            srv.close()
        samples = {}
        for ln in text.splitlines():
            if ln.startswith("#") or not ln.strip():
                continue
            name, value = ln.split(" # ", 1)[0].rsplit(" ", 1)
            samples[name] = float(value)
        score_keys = [
            k for k in samples if k.startswith("fjt_drift_score{")
        ]
        assert score_keys, "no fjt_drift_score gauges in the live scrape"
        assert any(samples[k] > 0 for k in score_keys), (
            "every scraped fjt_drift_score is zero after a 4-sigma "
            f"shift: { {k: samples[k] for k in score_keys} }"
        )
        rec_keys = [
            k for k in samples
            if k.startswith("fjt_drift_feature_records{")
        ]
        assert rec_keys and all(samples[k] > 0 for k in rec_keys), (
            "feature-profile counters missing from the dispatch path"
        )
        assert any(
            k.startswith("fjt_feature_missing_rate{") for k in samples
        ), "no missing-rate gauges in the scrape"

        # 3) overhead bound on the dispatch path
        q = cm.quantized_scorer()
        assert q is not None
        X = base[:256]
        a = rng.normal(size=(128, 128)).astype(np.float32)
        launches = 200
        t0 = time.perf_counter()
        for _ in range(launches):
            for _ in range(24):  # ~1 ms of real work per launch
                np.dot(a, a)
        per_launch = (time.perf_counter() - t0) / launches
        # (a) the steady-state per-dispatch cost is the unsampled gate
        m2 = MetricsRegistry()
        gate_plane = drift.install(m2, interval_s=3600.0)
        gate_plane.record_features(q, X)  # the one sample; rest gate
        n = 50_000
        t0 = time.perf_counter()
        for _ in range(n):
            gate_plane.record_features(q, X)
        per_gate = (time.perf_counter() - t0) / n
        ratio = per_gate / per_launch
        assert ratio <= 0.02, (
            f"drift gate costs {100 * ratio:.2f}% of a launch "
            f"({per_gate * 1e6:.2f}µs vs {per_launch * 1e6:.0f}µs)"
        )
        # (b) the sampled path: an interval-0 plane hammered for half a
        # second must stay within its 2% accumulated-overhead budget.
        # Best-of-3: the 0.5s window is short enough that one scheduler
        # hiccup inside a sampled pass can inflate the fraction past
        # the slack on a loaded box — the contract is that the budget
        # is HOLDABLE, so any quiet window satisfies it
        frac = None
        for _ in range(3):
            m3 = MetricsRegistry()
            busy_plane = drift.install(
                m3, interval_s=0.0, budget_frac=0.02
            )
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < 0.5:
                busy_plane.record_features(q, X)
            assert busy_plane.stats()["sampled"] >= 2, busy_plane.stats()
            attempt = busy_plane.overhead_fraction()
            frac = attempt if frac is None else min(frac, attempt)
            if frac <= 0.03:
                break
        assert frac <= 0.03, (
            f"sampled drift profiling consumed {100 * frac:.1f}% of "
            "wall clock — the overhead budget is not holding"
        )


def check_journey_trace() -> None:
    """Record-journey-tracing tripwire (obs/trace.py): (1) the
    unarmed hot-path gate — ``store_for`` with ``FJT_JOURNEY_DIR``
    unset — must cost ≤2µs per dispatch (a dict miss + one env
    lookup); (2) armed, the accumulated-overhead budget must hold (a
    zero-budget store drops every non-terminal hop); (3) a live
    pipeline's ``/trace`` scrape must retrieve ≥1 COMPLETE journey
    (dispatch + sink hops) whose sink hop's trace id matches a
    ``latency_exemplar`` flight event's — the fjt-top → fjt-trace
    pivot's ground truth."""
    import json
    import time
    import urllib.request

    import numpy as np

    from assets.generate import gen_gbm
    from flink_jpmml_tpu.compile import compile_pmml
    from flink_jpmml_tpu.obs import recorder as flight
    from flink_jpmml_tpu.obs import trace as trace_mod
    from flink_jpmml_tpu.obs.server import ObsServer
    from flink_jpmml_tpu.pmml import parse_pmml_file
    from flink_jpmml_tpu.runtime.block import BlockPipeline, FiniteBlockSource
    from flink_jpmml_tpu.utils.metrics import MetricsRegistry

    # 1) the unsampled gate: env unset, nothing armed
    assert not os.environ.get("FJT_JOURNEY_DIR"), (
        "FJT_JOURNEY_DIR leaked into the smoke env"
    )
    m_gate = MetricsRegistry()
    assert trace_mod.store_for(m_gate) is None
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        trace_mod.store_for(m_gate)
    per_call = (time.perf_counter() - t0) / n
    assert per_call <= 2e-6, (
        f"unarmed journey gate costs {per_call * 1e6:.2f}µs/dispatch > 2µs"
    )

    with tempfile.TemporaryDirectory() as tmp:
        # 2) the budget: a zero-budget store must shed its own work
        m_budget = MetricsRegistry()
        store = trace_mod.install(
            m_budget, os.path.join(tmp, "b"), budget_frac=0.0, head_n=0
        )
        for i in range(2000):
            ctx = trace_mod.context_for(i * 64)
            store.hop("dispatch", ctx, i * 64, 64)
            store.finish(ctx, i * 64, 64, latency_s=0.001)
        snap = m_budget.struct_snapshot()["counters"]
        dropped = sum(
            v for k, v in snap.items() if k.startswith("journeys_dropped")
        )
        assert dropped > 0 and snap.get("journeys_sampled", 0) == 0, (
            f"zero-budget store persisted work: {snap}"
        )

        # 3) live pipeline + /trace scrape + exemplar linkage
        doc = parse_pmml_file(
            gen_gbm(tmp, n_trees=10, depth=3, n_features=4)
        )
        cm = compile_pmml(doc, batch_size=64)
        rng = np.random.default_rng(7)
        data = rng.normal(0.0, 1.0, size=(1000, 4)).astype(np.float32)
        metrics = MetricsRegistry()
        trace_mod.install(metrics, os.path.join(tmp, "journeys"))

        def sink(out, n_, first_off):
            np.asarray(out if not hasattr(out, "value") else out.value)

        pipe = BlockPipeline(
            FiniteBlockSource(data, block_size=100), cm, sink,
            in_flight=2, use_native=False, metrics=metrics,
        )
        srv = ObsServer.for_registry(metrics)
        try:
            pipe.run_until_exhausted(timeout=60.0)
            with urllib.request.urlopen(
                srv.url + "/trace", timeout=10
            ) as r:
                assert r.status == 200
                payload = json.loads(r.read().decode())
        finally:
            srv.close()
        rows = payload["journeys"]
        assert rows, "live /trace scrape returned no journey rows"
        by_id = {}
        for row in rows:
            by_id.setdefault(row.get("trace_id"), set()).add(row["kind"])
        complete = {
            tid for tid, kinds in by_id.items()
            if {"dispatch", "sink"} <= kinds
        }
        assert complete, f"no complete journeys in the scrape: {by_id}"
        exemplar_tids = {
            e.get("trace_id") for e in flight.events()
            if e.get("kind") == "latency_exemplar"
        }
        assert complete & exemplar_tids, (
            "no scraped journey's sink hop matches a latency_exemplar "
            f"trace id (journeys {sorted(complete)[:4]}, exemplars "
            f"{sorted(t for t in exemplar_tids if t)[:4]})"
        )
        snap = metrics.struct_snapshot()["counters"]
        assert snap.get("journeys_sampled", 0) >= 1, snap


def check_recovery_drill() -> None:
    """Delivery-correctness tripwire: the ``--recovery-drill`` engine
    at smoke scale — one parent SIGKILL + poison records + decode
    poison against a supervised Kafka pipeline. Asserts the kill →
    restart → invariants chain: zero loss, bounded duplication,
    parseable checkpoints, poison offsets exactly in the DLQ, and the
    ``fjt-dlq redrive`` round-trip. The crash-loop (hard-poison)
    convergence needs ~log2(batch) restarts, so it runs only in the
    full ``bench.py --recovery-drill``, not here."""
    from flink_jpmml_tpu.bench import run_recovery_drill

    line = run_recovery_drill(
        records=4_000, kills=1, poison=1, hard_poison=False,
        decode_poison_n=1, timeout_s=120.0, max_restarts=20,
        throttle_ms=25.0, kill_dwell=(0.05, 0.25),
    )
    assert line["ok"], line
    assert line["parent_kills"] >= 1, line
    assert line["restarts"] >= 1, line
    assert line["redrive_ok"], line
    assert len(line["quarantined"]) == 2, line  # 1 score + 1 decode
    assert line["max_dup"] <= line["restarts"] + 1, line


def check_device_fault() -> None:
    """Device-fault resilience tripwire (runtime/devfault.py +
    serving/failover.py): unarmed hook-site overhead ≤2µs; then a
    smoke-scale outage — a persistent injected ``device_error`` streak
    trips the circuit onto the host fallback tier while a live
    ``/metrics`` scrape observes it (``fjt_failover_state`` open,
    non-zero ``fjt_fallback_records``), the breaker re-closes on green
    probes, redispatch lands records, and the paced stream drains with
    zero loss and in-order sinks."""
    import re
    import time
    import urllib.request

    import numpy as np

    from assets.generate import gen_gbm
    from flink_jpmml_tpu.compile import compile_pmml
    from flink_jpmml_tpu.obs.server import ObsServer
    from flink_jpmml_tpu.pmml import parse_pmml_file
    from flink_jpmml_tpu.runtime import faults
    from flink_jpmml_tpu.runtime.block import BlockPipeline, BlockSource

    # -- unarmed overhead: the new device hook sites ride the same
    #    no-op contract as every other fault site
    assert not faults.active(), "faults armed — no-op check invalid"
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        faults.fire("device_readback")
        faults.fire("device_dispatch")
    per_call = (time.perf_counter() - t0) / (2 * n)
    assert per_call <= 2e-6, (
        f"inactive device fault hook costs {per_call * 1e6:.2f}µs/call"
    )

    class PacedSource(BlockSource):
        """One block per interval: on a CPU host the fallback tier
        runs at device speed, and an instantly-available stream would
        drain inside one open-circuit window — pacing leaves traffic
        for the half-open probes that must re-close the breaker."""

        def __init__(self, data, block, interval_s):
            self._data = data
            self._block = block
            self._interval = interval_s
            self._pos = 0
            self._next_t = 0.0

        def poll(self):
            if self._pos >= self._data.shape[0]:
                return None
            now = time.monotonic()
            if now < self._next_t:
                return None
            self._next_t = now + self._interval
            blk = self._data[self._pos: self._pos + self._block]
            off = self._pos
            self._pos += blk.shape[0]
            return off, blk

        @property
        def exhausted(self):
            return self._pos >= self._data.shape[0]

    with tempfile.TemporaryDirectory() as tmp:
        doc = parse_pmml_file(
            gen_gbm(tmp, n_trees=8, depth=3, n_features=5)
        )
    cm = compile_pmml(doc, batch_size=64)
    rng = np.random.default_rng(17)
    N = 12_288
    data = rng.normal(0.0, 1.0, size=(N, 5)).astype(np.float32)
    emitted = []

    def sink(out, n_rec, first_off):
        emitted.append((first_off, n_rec))

    env_saved = {
        k: os.environ.get(k)
        for k in ("FJT_FAILOVER", "FJT_FAILOVER_COOLDOWN_S",
                  "FJT_FAILOVER_GREENS", "FJT_RETRY_BASE_S")
    }
    os.environ["FJT_FAILOVER"] = "1"  # arm without a DLQ: env opt-in
    os.environ["FJT_FAILOVER_COOLDOWN_S"] = "0.2"
    os.environ["FJT_FAILOVER_GREENS"] = "1"
    os.environ["FJT_RETRY_BASE_S"] = "0.005"
    srv = None
    pipe = None
    try:
        # 7 fires: batch 1 (1 + 2 retries) opens the circuit; probe 1
        # burns 3 more and re-opens; probe 2's initial readback burns
        # the last, its first RETRY succeeds (redispatch_records), and
        # the next green completion closes the circuit
        faults.inject("device_error", site="device_readback", n=7)
        pipe = BlockPipeline(
            PacedSource(data, 64, 0.004), cm, sink,
            in_flight=2, use_native=False, max_dispatch_chunks=1,
        )
        srv = ObsServer.for_registry(pipe.metrics)
        pipe.start()
        saw_open = False
        saw_fallback = 0.0
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if pipe._error is not None:
                raise pipe._error
            try:
                with urllib.request.urlopen(
                    srv.url + "/metrics", timeout=5
                ) as r:
                    page = r.read().decode()
            except OSError:
                page = ""
            m = re.search(
                r'fjt_failover_state\{model="static"\} ([0-9.]+)', page
            )
            if m and float(m.group(1)) >= 2.0:
                saw_open = True
                fb = re.search(r"fjt_fallback_records ([0-9.e+]+)", page)
                if fb:
                    saw_fallback = max(saw_fallback, float(fb.group(1)))
            if pipe._source.exhausted and not len(pipe._ring):
                break
            time.sleep(0.02)
        pipe._drain_all = True
        pipe.stop()
        pipe.join(timeout=30.0)
        assert saw_open, (
            "live scrape never observed fjt_failover_state open"
        )
        assert saw_fallback > 0, (
            "live scrape never observed non-zero fjt_fallback_records "
            "during the outage"
        )
        snap = pipe.metrics.struct_snapshot()
        g = snap.get("gauges", {})
        state = g.get('failover_state{model="static"}', {}).get("value")
        assert state == 0.0, (
            f"circuit did not re-close (failover_state {state})"
        )
        c = snap.get("counters", {})
        assert c.get("fallback_records", 0) > 0
        assert c.get("redispatch_records", 0) > 0, (
            "no redispatched records — the transient ladder never won"
        )
        assert c.get('device_fault_total{kind="device_error"}', 0) >= 7
        covered = np.zeros(N, np.int64)
        for off, n_rec in emitted:
            covered[off: off + n_rec] += 1
        assert (covered == 1).all(), (
            f"loss/dup under device faults: "
            f"lost={int((covered == 0).sum())} "
            f"dup={int((covered > 1).sum())}"
        )
        offs = [o for o, _ in emitted]
        assert offs == sorted(offs), "sink order violated under faults"
    finally:
        faults.clear()
        if pipe is not None:
            try:
                pipe.stop()
                pipe.join(timeout=10.0)
            except Exception:
                pass
        if srv is not None:
            srv.close()
        for k, v in env_saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def check_fault_hooks_noop() -> None:
    """Fault harness zero-overhead contract: with FJT_FAULTS unset,
    fire() must be a global load + None check (≤ 2 µs even on a loaded
    CI machine — measured ~0.3 µs), and injection must be fully
    reversible (clear() restores the no-op path)."""
    import time

    from flink_jpmml_tpu.runtime import faults

    assert not faults.active(), (
        "faults installed with FJT_FAULTS unset — the no-op path is "
        "not the default"
    )
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        faults.fire("kafka_fetch")
    per_call = (time.perf_counter() - t0) / n
    assert per_call <= 2e-6, (
        f"inactive fault hook costs {per_call * 1e6:.2f}µs/call > 2µs"
    )
    # injection engages the real paths... and clear() fully disarms
    f = faults.inject("slow_fetch", delay_ms=1, n=1)
    faults.fire("kafka_fetch")
    assert f.fires == 1 and faults.stats() == {"slow_fetch": 1}
    faults.clear()
    assert not faults.active()
    faults.fire("kafka_fetch")  # no plan: must be inert again
    assert f.fires == 1


def check_mesh_gate_noop() -> None:
    """Single-chip mesh-gate zero-overhead contract (PR 16): with no
    mesh configured, the multichip promotion adds exactly two
    operations to the dispatch hot path — a getattr-with-default on
    ``batch_divisor`` (the pad-target rounding in ``_score_f32``) and
    a ``_mesh_obs is None`` test in the completion path. Both together
    must cost ≤ 2 µs/dispatch (measured ~0.2 µs), and the telemetry /
    window plumbing must stay fully disengaged for single-chip
    models."""
    import time

    from flink_jpmml_tpu.obs import mesh as mesh_obs
    from flink_jpmml_tpu.parallel.assignment import mesh_in_flight
    from flink_jpmml_tpu.utils.metrics import MetricsRegistry

    class _SingleChipModel:  # a CompiledModel has no mesh attrs
        batch_size = 512

    model = _SingleChipModel()
    obs = None
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        target = 512
        target += (-target) % getattr(model, "batch_divisor", 1)
        if obs is not None:
            raise AssertionError("unreachable")
    per_call = (time.perf_counter() - t0) / n
    assert per_call <= 2e-6, (
        f"single-chip mesh gate costs {per_call * 1e6:.2f}µs/dispatch "
        "> 2µs"
    )
    # disengagement: no telemetry for single-chip models, and the
    # mesh-aware window leaves the single-chip depth untouched
    assert mesh_obs.telemetry_for(MetricsRegistry(), model) is None
    assert mesh_in_flight(None, 2) == 2


def check_zoo_pack() -> None:
    """Multi-tenant packed-scoring tripwire: byte parity packed-vs-solo
    (zero cross-tenant leakage), LRU eviction + warm-pool re-admit
    identity under a 1-byte FJT_ZOO_BYTES cap, and a lenient
    pack-vs-solo wall-clock ratio. (The 1,000-model acceptance capture
    is ``bench.py --zoo``; this guards the pack path's correctness on
    every smoke run.)"""
    import time

    import numpy as np

    from flink_jpmml_tpu.assets_gen import gen_gbm
    from flink_jpmml_tpu.models.control import AddMessage
    from flink_jpmml_tpu.models.core import ModelId
    from flink_jpmml_tpu.runtime.sources import ControlSource
    from flink_jpmml_tpu.serving.scorer import DynamicScorer

    tmp = tempfile.mkdtemp(prefix="fjt-smoke-zoo-")
    tenants, features, rows = 6, 4, 64
    docs = [
        gen_gbm(tmp, n_trees=4 + i, depth=3, n_features=features,
                seed=50 + i, name=f"z{i}")
        for i in range(tenants)
    ]
    fields = [f"f{j}" for j in range(features)]
    rng = np.random.default_rng(5)
    data = rng.normal(0.0, 1.0, size=(
        tenants * rows * 8, features)).astype(np.float32)
    data[rng.random(size=data.shape) < 0.02] = np.nan  # missing lanes

    def build(zoo):
        ctrl = ControlSource()
        sc = DynamicScorer(control=ctrl, batch_size=256,
                           auto_rollout=False, zoo=zoo)
        for i in range(tenants):
            ctrl.push(AddMessage(f"z{i}", 1, docs[i],
                                 timestamp=time.time()))
        sc._drain_control()
        deadline = time.monotonic() + 120.0
        for i in range(tenants):
            mid = ModelId(f"z{i}", 1)
            while sc.registry.model_if_warm(mid) is None:
                assert sc.registry.warm_error(mid) is None, mid.key()
                assert time.monotonic() < deadline, (
                    f"{mid.key()} never warmed"
                )
                time.sleep(0.01)
        return sc

    def batch(round_i):
        ev = []
        for i in range(tenants):
            base = (round_i * tenants + i) * rows
            for j in range(rows):
                rec = dict(zip(
                    fields, data[(base + j) % len(data)].tolist()
                ))
                rec["_key"] = f"k{base + j}"
                ev.append((f"z{i}", rec))
        return ev

    def run(sc, rounds):
        out = []
        for r in rounds:
            for p, _ in sc.finish(sc.submit(batch(r))):
                out.append(None if p.is_empty else p.score.value)
        return out

    sc_solo = build(None)

    # tight caps: width-2 packs, a byte cap that can hold exactly one —
    # every group admit evicts the previous pack, round 2 re-admits
    # from the warm pool; parity across both rounds pins the
    # eviction/re-admit identity
    env_keys = ("FJT_PACK_MAX", "FJT_ZOO_BYTES", "FJT_AUTOTUNE_DISABLE")
    saved = {k: os.environ.get(k) for k in env_keys}
    os.environ.update({"FJT_PACK_MAX": "2", "FJT_ZOO_BYTES": "1",
                       "FJT_AUTOTUNE_DISABLE": "1"})
    try:
        sc_zoo = build(True)
        want = run(sc_solo, [0, 1])
        got = run(sc_zoo, [0, 1])
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    assert got == want, (
        "packed-vs-solo parity broke (cross-tenant leakage or "
        "reduction-order drift)"
    )
    c = sc_zoo.metrics.struct_snapshot()["counters"]
    assert c.get("pack_dispatches", 0) > 0, "zoo never packed a dispatch"
    assert c.get("zoo_evictions", 0) > 0, (
        "1-byte FJT_ZOO_BYTES cap never evicted a pack"
    )
    assert c.get("warm_pool_hits", 0) > 0, (
        "round 2 rebuilt its packs instead of re-admitting from the "
        "warm pool"
    )

    # lenient wall-clock tripwire under default caps (one wide pack,
    # no thrash): packed dispatch must not be pathologically slower
    # than solo — the real >=75% throughput gate lives in bench --zoo
    sc_fast = build(True)
    run(sc_fast, [0])  # plan + pack compile outside timing
    t0 = time.perf_counter()
    run(sc_fast, [1, 2, 3])
    dt_zoo = time.perf_counter() - t0
    t0 = time.perf_counter()
    run(sc_solo, [1, 2, 3])
    dt_solo = time.perf_counter() - t0
    assert dt_zoo <= 3.0 * dt_solo + 0.25, (
        f"packed path took {dt_zoo:.3f}s vs solo {dt_solo:.3f}s "
        "(> 3x tripwire)"
    )
    shutil.rmtree(tmp, ignore_errors=True)


def check_history() -> None:
    """Telemetry-history tripwire (obs/history.py): the unarmed
    ``history_for`` gate costs ≤2µs/call (the journey-store contract);
    an armed recorder keeps its accumulated bookkeeping under the 2%
    budget while capturing for real; and a live pipeline's ``/history``
    frames RECONCILE over HTTP — the summed counter deltas equal the
    registry's cumulative totals exactly."""
    import json
    import time
    import urllib.request

    import numpy as np

    from assets.generate import gen_gbm
    from flink_jpmml_tpu.compile import compile_pmml
    from flink_jpmml_tpu.obs import history
    from flink_jpmml_tpu.obs.server import ObsServer
    from flink_jpmml_tpu.pmml import parse_pmml_file
    from flink_jpmml_tpu.runtime.block import (
        BlockPipeline, FiniteBlockSource,
    )
    from flink_jpmml_tpu.utils.metrics import MetricsRegistry

    env_saved = {
        k: os.environ.get(k)
        for k in ("FJT_HISTORY_DIR", "FJT_HISTORY_RES",
                  "FJT_HISTORY_INTERVAL_S", "FJT_METRICS_MAX_SERIES")
    }
    for k in env_saved:
        os.environ.pop(k, None)
    srv = None
    try:
        # -- unarmed: a dict miss + one env lookup, nothing records
        m_idle = MetricsRegistry()
        n = 200_000
        t0 = time.perf_counter()
        for _ in range(n):
            history.history_for(m_idle)
        per_call = (time.perf_counter() - t0) / n
        assert per_call <= 2e-6, (
            f"unarmed history gate costs {per_call * 1e6:.2f}µs/call"
        )

        # -- armed: real captures against the accumulated-overhead
        #    budget, paced at the production default cadence
        with tempfile.TemporaryDirectory() as tmp:
            m_armed = MetricsRegistry()
            c = m_armed.counter("records_out")
            g = m_armed.gauge("pressure")
            rec = history.HistoryRecorder(
                m_armed, tmp, src="smoke", interval_s=0.05,
                resolutions=(0.05, 1.0), start_thread=False,
            )
            t_end = time.monotonic() + 1.0
            while time.monotonic() < t_end:
                c.inc(100)
                g.set(0.5)
                rec.maybe_capture()
                time.sleep(0.005)
            frac = rec.overhead_fraction()
            rec.close()
            assert frac <= 0.02, (
                f"armed history overhead {100 * frac:.2f}% > 2% budget"
            )

        # -- live scrape: /history frames reconcile with the registry's
        #    cumulative counters across a real pipeline run
        with tempfile.TemporaryDirectory() as tmp:
            doc = parse_pmml_file(
                gen_gbm(tmp, n_trees=10, depth=3, n_features=4)
            )
        cm = compile_pmml(doc, batch_size=64)
        rng = np.random.default_rng(5)
        data = rng.normal(0.0, 1.0, size=(1000, 4)).astype(np.float32)

        def sink(out, n_rec, first_off):
            np.asarray(out if not hasattr(out, "value") else out.value)

        hdir = tempfile.mkdtemp(prefix="fjt-smoke-history-")
        try:
            pipe = BlockPipeline(
                FiniteBlockSource(data, block_size=100), cm, sink,
                in_flight=2, use_native=False,
            )
            rec = history.install(
                pipe.metrics, directory=hdir, src="smoke",
                interval_s=0.05, start_thread=False,
            )
            # the baseline capture happens BEFORE any traffic, so the
            # frame deltas cover the whole run
            rec.maybe_capture()
            srv = ObsServer.for_registry(pipe.metrics)
            pipe.run_until_exhausted(timeout=60.0)
            time.sleep(0.06)  # past the interval gate
            rec.maybe_capture()
            rec.flush()
            with urllib.request.urlopen(
                srv.url + "/history?source=smoke", timeout=10
            ) as r:
                assert r.status == 200
                payload = json.loads(r.read().decode())
            frames = payload.get("frames") or []
            assert frames, "live /history served no frames"
            total = 0.0
            for f in frames:
                v = (f.get("counters") or {}).get("records_out")
                if v is not None:
                    total += history.wire_float(v)
            cum = pipe.metrics.struct_snapshot()["counters"][
                "records_out"
            ]
            assert total == cum == 1000, (
                f"/history deltas ({total}) don't reconcile with the "
                f"registry cumulative ({cum})"
            )
        finally:
            shutil.rmtree(hdir, ignore_errors=True)
    finally:
        if srv is not None:
            srv.close()
        for k, v in env_saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def check_stateful() -> None:
    """Keyed-state tripwire (runtime/state.py + compile/statekernel.py):
    the unarmed per-dispatch additions (the ``state is None`` branch +
    ``split_output`` on a stateless output) must stay ≤2µs; an armed
    dispatch — host slot routing + the fused gather/scatter state
    stage — must stay within a small constant factor of the stateless
    dispatch at smoke scale (a per-record host loop would be 100×); a
    mid-run snapshot restored into a fresh table and replayed from
    offset 0 must converge to the single-life table BYTE-exactly (the
    exactly-once replay guard); and a live stateful pipeline's
    ``/metrics`` scrape must show non-zero ``fjt_state_resident_keys``."""
    import time
    import urllib.request

    import numpy as np

    from assets.generate import gen_gbm
    from flink_jpmml_tpu.compile import compile_pmml
    from flink_jpmml_tpu.obs.server import ObsServer
    from flink_jpmml_tpu.pmml import parse_pmml_file
    from flink_jpmml_tpu.runtime import state as state_mod
    from flink_jpmml_tpu.runtime.block import (
        BlockPipeline, FiniteBlockSource,
    )
    from flink_jpmml_tpu.runtime.pipeline import dispatch_quantized

    import jax

    # -- unarmed gate: the stateless hot path's only new per-dispatch
    #    work is `state is None` branches plus split_output on the raw
    #    output object
    out_stateless = np.zeros(64, np.float32)
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        state_mod.split_output(out_stateless)
    per_call = (time.perf_counter() - t0) / n
    assert per_call <= 2e-6, (
        f"unarmed state gate costs {per_call * 1e6:.2f}µs/dispatch"
    )

    with tempfile.TemporaryDirectory() as tmp:
        doc = parse_pmml_file(
            gen_gbm(tmp, n_trees=10, depth=3, n_features=4)
        )
    cm = compile_pmml(doc, batch_size=256)
    q = cm.quantized_scorer()
    B, rounds = 256, 40
    rng = np.random.default_rng(11)
    X = rng.normal(0.0, 1.0, size=(rounds * B, 4)).astype(np.float32)
    X[:, 0] = rng.integers(0, 5000, size=rounds * B).astype(np.float32)

    def run(table):
        last = None
        t_run0 = time.perf_counter()
        for i in range(rounds):
            xb = X[i * B:(i + 1) * B]
            if table is None:
                last = dispatch_quantized(q, xb)
            else:
                last = dispatch_quantized(
                    q, xb, state=table,
                    offsets=np.arange(i * B, (i + 1) * B),
                )
        jax.block_until_ready(last)
        return time.perf_counter() - t_run0

    spec = state_mod.StateSpec(capacity=8192, key_col=0)
    # warm both entries (compiles are not the overhead under test)
    run(None)
    run(state_mod.KeyedStateTable(spec))
    t_plain = run(None)
    t_armed = run(state_mod.KeyedStateTable(spec))
    assert t_armed <= 5.0 * t_plain + 0.25, (
        f"armed state overhead unbounded: {t_armed:.3f}s armed vs "
        f"{t_plain:.3f}s stateless over {rounds} dispatches"
    )

    # -- kill→restore parity at smoke scale: snapshot mid-run (the
    #    checkpoint a killed incarnation leaves), restore into a fresh
    #    table, replay the WHOLE stream from offset 0 — the replayed
    #    prefix bypasses (exactly-once), the suffix re-applies, and the
    #    final buffer equals the single-life table bitwise
    ref = state_mod.KeyedStateTable(spec)
    payload = None
    for i in range(rounds):
        out = dispatch_quantized(
            q, X[i * B:(i + 1) * B], state=ref,
            offsets=np.arange(i * B, (i + 1) * B),
        )
        if i == rounds // 2 - 1:
            jax.block_until_ready(ref.values)
            payload = ref.to_payload()
    jax.block_until_ready(out)
    ref_vals = np.asarray(ref.values).copy()
    rep = state_mod.KeyedStateTable(spec)
    assert rep.from_payload(payload), "state payload restore failed"
    assert rep.skip_until == (rounds // 2) * B
    for i in range(rounds):
        out = dispatch_quantized(
            q, X[i * B:(i + 1) * B], state=rep,
            offsets=np.arange(i * B, (i + 1) * B),
        )
    jax.block_until_ready(out)
    assert np.array_equal(ref_vals, np.asarray(rep.values)), (
        "kill→restore replay diverged from the single-life state table"
    )

    # -- live scrape: a stateful pipeline's /metrics shows the family
    srv = None
    try:
        data = rng.normal(0.0, 1.0, size=(2048, 4)).astype(np.float32)
        data[:, 0] = rng.integers(0, 500, size=2048).astype(np.float32)
        seen = []

        def sink(out, n_rec, first_off):
            seen.append(n_rec)

        pipe = BlockPipeline(
            FiniteBlockSource(data, block_size=256), cm, sink,
            in_flight=2, use_native=False,
            state=state_mod.StateSpec(capacity=4096, key_col=0),
        )
        srv = ObsServer.for_registry(pipe.metrics)
        pipe.run_until_exhausted(timeout=60.0)
        assert sum(seen) == 2048, f"stateful pipeline lost records: {seen}"
        with urllib.request.urlopen(srv.url + "/metrics", timeout=10) as r:
            assert r.status == 200
            text = r.read().decode()
        resident = None
        for line in text.splitlines():
            if line.startswith("fjt_state_resident_keys"):
                resident = float(line.split()[-1])
        assert resident is not None and resident > 0, (
            f"/metrics shows no live state_resident_keys: {resident}"
        )
    finally:
        if srv is not None:
            srv.close()


def main() -> int:
    timer = threading.Timer(WATCHDOG_S, _watchdog)
    timer.daemon = True
    timer.start()
    check_dispatcher_ordering()
    print("perf-smoke: dispatcher ordering OK", flush=True)
    check_block_pipeline()
    print("perf-smoke: block pipeline drain/ordering OK", flush=True)
    check_kafka_pipeline()
    print("perf-smoke: kafka pipeline OK", flush=True)
    check_fused_pipeline_parity()
    print("perf-smoke: fused encode parity OK", flush=True)
    check_autotune_cache_roundtrip()
    print("perf-smoke: autotune cache roundtrip OK", flush=True)
    check_kernel_search()
    print("perf-smoke: kernel search OK", flush=True)
    check_obs_scrape()
    print("perf-smoke: obs /metrics scrape OK", flush=True)
    check_attribution_overhead()
    print("perf-smoke: attribution overhead OK", flush=True)
    check_rollout_drill()
    print("perf-smoke: rollout drill OK", flush=True)
    check_freshness_burst_drill()
    print("perf-smoke: freshness burst drill OK", flush=True)
    check_overload_drill()
    print("perf-smoke: overload drill OK", flush=True)
    check_drift_plane()
    print("perf-smoke: drift plane OK", flush=True)
    check_journey_trace()
    print("perf-smoke: journey trace OK", flush=True)
    check_recovery_drill()
    print("perf-smoke: recovery drill OK", flush=True)
    check_device_fault()
    print("perf-smoke: device fault plane OK", flush=True)
    check_fault_hooks_noop()
    print("perf-smoke: fault hooks no-op OK", flush=True)
    check_mesh_gate_noop()
    print("perf-smoke: mesh gate no-op OK", flush=True)
    check_zoo_pack()
    print("perf-smoke: zoo pack OK", flush=True)
    check_history()
    print("perf-smoke: history OK", flush=True)
    check_stateful()
    print("perf-smoke: keyed state OK", flush=True)
    timer.cancel()
    return 0


if __name__ == "__main__":
    sys.exit(main())
