#!/usr/bin/env python3
"""Per-metric trend lines over the repo's bench history — and a
regression tripwire against the best prior capture.

Sources, oldest→newest:

- ``BENCH_r*.json`` — the driver's end-of-round artifacts (their
  ``parsed`` JSON line, ordered by the embedded round number ``n``);
- ``docs/bench_captures.jsonl`` — verbatim mid-round captures, in file
  order (rows without a ``metric`` field, like the header note, skip).

Every numeric field of a capture becomes one series keyed
``metric.field`` and split by ``backend`` (a cpu-fallback capture must
never be judged against a TPU best — they are different machines).
Latency-named fields (``*latency*``, ``*_ms``) trend lower-better;
everything else higher-better.

The tripwire: for each series with ≥2 points, the LATEST point is
compared against the best PRIOR point; worse by more than
``--tolerance`` (default 10%) prints ``REGRESSED`` and exits 2 —
wire-able into CI next to tools/metrics_lint.py. ``--metric`` narrows
the check, ``--json`` emits the trajectories machine-readably.

    python tools/bench_trend.py
    python tools/bench_trend.py --metric gbm500_records_per_sec_per_chip.value
    python tools/bench_trend.py --tolerance 0.25 --json
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")

#: Fields that are never performance series (identity / free-text /
#: config echo / orchestration bookkeeping), whatever their type.
_SKIP_FIELDS = {"metric", "unit", "backend", "error", "note", "cmd",
                "rc", "n", "ok", "attempts", "probes", "elapsed_s"}


def _lower_better(field: str) -> bool:
    f = field.lower()
    return "latency" in f or f.endswith("_ms") or "stall" in f


def _numeric_fields(row: dict) -> Dict[str, float]:
    out = {}
    for k, v in row.items():
        if k in _SKIP_FIELDS or isinstance(v, bool):
            continue
        if isinstance(v, (int, float)):
            out[k] = float(v)
    return out


def load_rows(repo: str) -> List[Tuple[str, dict]]:
    """→ [(origin label, capture row)] oldest→newest: round artifacts
    by round number, then the captures log in file order."""
    rows: List[Tuple[str, dict]] = []
    arts = []
    for p in glob.glob(os.path.join(repo, "BENCH_r*.json")):
        m = _ROUND_RE.search(p)
        if not m:
            continue
        try:
            with open(p, encoding="utf-8") as f:
                d = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        parsed = d.get("parsed") if isinstance(d, dict) else None
        if isinstance(parsed, dict) and parsed.get("metric"):
            arts.append((int(d.get("n") or m.group(1)),
                         os.path.basename(p), parsed))
    for _, label, parsed in sorted(arts):
        rows.append((label, parsed))
    cap = os.path.join(repo, "docs", "bench_captures.jsonl")
    try:
        with open(cap, encoding="utf-8") as f:
            for i, ln in enumerate(f):
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    r = json.loads(ln)
                except json.JSONDecodeError:
                    continue  # torn/annotated line: log, not data
                if isinstance(r, dict) and r.get("metric"):
                    rows.append((f"captures:{i + 1}", r))
    except OSError:
        pass
    return rows


def trajectories(
    rows: List[Tuple[str, dict]],
) -> Dict[Tuple[str, str], List[Tuple[str, float]]]:
    """→ {(series key "metric.field", backend): [(origin, value)]}."""
    out: Dict[Tuple[str, str], List[Tuple[str, float]]] = {}
    for origin, row in rows:
        backend = str(row.get("backend") or "")
        metric = str(row.get("metric"))
        for field, v in _numeric_fields(row).items():
            out.setdefault(
                (f"{metric}.{field}", backend), []
            ).append((origin, v))
    return out


def check(
    series: Dict[Tuple[str, str], List[Tuple[str, float]]],
    tolerance: float,
    only: Optional[List[str]] = None,
) -> Tuple[List[dict], List[dict]]:
    """→ (report rows, regressions). Latest vs best PRIOR per series."""
    report, regressions = [], []
    for (key, backend), pts in sorted(series.items()):
        if only and key not in only:
            continue
        values = [v for _, v in pts]
        latest_origin, latest = pts[-1]
        row = {
            "series": key,
            "backend": backend,
            "points": len(pts),
            "values": values[-8:],
            "latest": latest,
            "latest_origin": latest_origin,
        }
        if len(pts) >= 2:
            prior = values[:-1]
            lower = _lower_better(key.rsplit(".", 1)[1])
            best = min(prior) if lower else max(prior)
            row["best_prior"] = best
            if best:
                delta = (
                    (latest - best) / abs(best) if not lower
                    else (best - latest) / abs(best)
                )
                # delta > 0 = improvement in the metric's own direction
                row["delta_vs_best"] = round(delta, 4)
                row["regressed"] = delta < -tolerance
                if row["regressed"]:
                    regressions.append(row)
        report.append(row)
    return report, regressions


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench-trend",
        description="Per-metric bench trajectories + regression "
                    "tripwire vs the best prior capture.",
    )
    ap.add_argument("--repo", default=None,
                    help="repo root (default: this file's parent's "
                         "parent)")
    ap.add_argument("--metric", action="append", default=None,
                    help="only this series key (metric.field); "
                         "repeatable")
    ap.add_argument("--tolerance", type=float, default=0.10,
                    help="allowed fractional slack vs the best prior "
                         "before a series counts as regressed "
                         "(default 0.10)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as JSON")
    args = ap.parse_args(argv)
    repo = args.repo or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    rows = load_rows(repo)
    if not rows:
        print(f"no bench captures under {repo!r}", file=sys.stderr)
        return 1
    report, regressions = check(
        trajectories(rows), args.tolerance, only=args.metric
    )
    if args.json:
        json.dump(
            {"rows": len(rows), "series": report,
             "regressions": [r["series"] for r in regressions]},
            sys.stdout, indent=1, sort_keys=True,
        )
        print()
    else:
        for r in report:
            traj = " -> ".join(f"{v:g}" for v in r["values"])
            line = (
                f"{r['series']}"
                + (f" [{r['backend']}]" if r["backend"] else "")
                + f"  ({r['points']} pts)  {traj}"
            )
            if "delta_vs_best" in r:
                line += (
                    f"   {100 * r['delta_vs_best']:+.1f}% vs best prior"
                )
                if r.get("regressed"):
                    line += "   REGRESSED"
            print(line)
    if regressions:
        print(
            f"{len(regressions)} series regressed past "
            f"{100 * args.tolerance:.0f}% tolerance: "
            + ", ".join(r["series"] for r in regressions),
            file=sys.stderr,
        )
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
