"""FJT_XLA_CACHE: opt-in persistent XLA compilation cache — a restarted
worker warms compiled models from disk instead of recompiling."""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow  # subprocess compile drill


def test_cache_populates_and_reloads(tmp_path):
    cache = str(tmp_path / "xla")
    prog = """
import tempfile, time
import flink_jpmml_tpu
import jax
# the production threshold (0.5s) skips trivial compiles; persist
# everything for this tiny test model
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
from flink_jpmml_tpu.assets_gen import gen_gbm
from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.pmml import parse_pmml_file
d = tempfile.mkdtemp()
doc = parse_pmml_file(gen_gbm(d, n_trees=20, depth=4, n_features=6))
t0 = time.time()
compile_pmml(doc, batch_size=256).warmup()
print(f"COMPILE_S={time.time()-t0:.2f}")
"""
    env = dict(
        os.environ,
        FJT_PLATFORM="cpu",
        FJT_XLA_CACHE=cache,
        PYTHONPATH=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    r1 = subprocess.run(
        [sys.executable, "-c", prog], env=env, capture_output=True,
        text=True, timeout=240,
    )
    assert r1.returncode == 0, r1.stderr[-800:]
    entries = os.listdir(cache)
    assert entries, "persistent cache stayed empty after a compile"
    # second process: same model compiles against the populated cache
    r2 = subprocess.run(
        [sys.executable, "-c", prog], env=env, capture_output=True,
        text=True, timeout=240,
    )
    assert r2.returncode == 0, r2.stderr[-800:]
    assert "COMPILE_S=" in r2.stdout
