"""Pipelined ingest (ISSUE 14): the prefetch/decode sidecar
(runtime/prefetch.py) and the vectorized/zero-copy decode tiers
(runtime/kafka.py).

The sidecar is a PERFORMANCE change, not a semantics change — these
tests pin every contract that has to survive the move off-thread:
byte parity of the vectorized decoder against the python-walk oracle
(NaN/±inf payloads, wrong-length poison, header-carrying records,
CRC damage), strict delivery ordering, seek / reconnect / shutdown
drills, freshness-stamp and journey-hop preservation through the
handoff queue, and DLQ routing from the decode thread.
"""

import threading
import time

import numpy as np
import pytest

from flink_jpmml_tpu.runtime import prefetch as prefetch_mod
from flink_jpmml_tpu.runtime.kafka import (
    KafkaBlockSource,
    KafkaPartitionError,
    KafkaRecordSource,
    MiniKafkaBroker,
    crc32c,
    crc32c_vec,
    decode_record_batches_rows,
    decode_record_batches_rows_py,
    decode_record_batches_rows_vec,
    encode_record_batch,
)
from flink_jpmml_tpu.runtime.prefetch import (
    PrefetchedBlockSource,
    PrefetchedRecordSource,
    maybe_wrap_block,
    maybe_wrap_records,
)
from flink_jpmml_tpu.utils.metrics import MetricsRegistry


def _drain_blocks(src, want, timeout=30.0):
    got = []
    pos = 0
    deadline = time.monotonic() + timeout
    while pos < want and time.monotonic() < deadline:
        polled = src.poll()
        if polled is None:
            time.sleep(0.002)
            continue
        got.append(polled)
        pos += polled[1].shape[0]
    return got, pos


class TestCrcVec:
    def test_concurrent_cold_start_is_race_free(self):
        """The engine is shared across decode sidecars and broker
        handler threads; a lazily-extended operator chain raced and
        poisoned the table caches PERMANENTLY (review finding, pinned:
        the chain is now frozen at construction)."""
        from flink_jpmml_tpu.runtime.kafka import _Crc32cVec

        import random

        rng = random.Random(5)
        datas = [
            bytes(rng.randrange(256) for _ in range(1500 + i * 53))
            for i in range(8)
        ]
        expected = [crc32c(d) for d in datas]
        for _ in range(20):
            eng = _Crc32cVec()  # cold caches every round
            results = [None] * 8
            threads = [
                threading.Thread(
                    target=lambda i=i: results.__setitem__(
                        i, eng.crc(datas[i])
                    )
                )
                for i in range(8)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert results == expected
            # and nothing sticky: serial rechecks stay right
            assert [eng.crc(d) for d in datas] == expected

    def test_known_vector_and_parity(self):
        assert crc32c_vec(b"123456789") == 0xE3069283
        import random

        rng = random.Random(11)
        for ln in (0, 1, 7, 8, 9, 63, 64, 65, 127, 509, 4096, 40001):
            data = bytes(rng.randrange(256) for _ in range(ln))
            assert crc32c_vec(data) == crc32c(data), ln
            assert crc32c_vec(memoryview(data)) == crc32c(data)


class TestVectorizedDecodeParity:
    N_COLS = 6

    def _rows(self, n, seed=0):
        rng = np.random.default_rng(seed)
        rows = rng.normal(size=(n, self.N_COLS)).astype(np.float32)
        rows[min(3, n - 1), 0] = np.nan
        rows[min(5, n - 1), 1] = np.inf
        rows[min(7, n - 1), 2] = -np.inf
        return rows

    def _buf(self, rows, base=0, headers=None, timestamp_ms=0):
        vals = [rows[i].tobytes() for i in range(rows.shape[0])]
        return encode_record_batch(
            base, vals, timestamp_ms=timestamp_ms, headers=headers
        )

    def _assert_parity(self, buf):
        o1, r1 = decode_record_batches_rows_py(buf, self.N_COLS)
        o2, r2 = decode_record_batches_rows_vec(buf, self.N_COLS)
        assert (o1 == o2).all()
        assert r1.tobytes() == r2.tobytes()
        # the native-or-vec dispatcher agrees too
        o3, r3 = decode_record_batches_rows(buf, self.N_COLS)
        assert (o1 == o3).all() and r1.tobytes() == r3.tobytes()
        return o1, r1

    def test_canonical_multi_batch_with_partial_tail(self):
        rows = self._rows(1200, seed=1)
        buf = b"".join(
            self._buf(rows[i : i + 512], base=i)
            for i in range(0, 1200, 512)
        )
        offs, dec = self._assert_parity(buf + buf[:25])
        assert offs.shape[0] == 1200
        assert dec.tobytes() == rows.tobytes()
        # memoryview input: the zero-copy fetch path's shape
        o2, r2 = decode_record_batches_rows_vec(
            memoryview(buf), self.N_COLS
        )
        assert r2.tobytes() == rows.tobytes()

    def test_varint_width_boundary(self):
        # offset deltas cross the 1→2-byte varint width at 64: the
        # closed-form offset table must track it exactly
        rows = self._rows(130, seed=2)
        self._assert_parity(self._buf(rows, base=1_000_000))

    def test_header_records_fall_back_byte_identically(self):
        rows = self._rows(100, seed=3)
        hdrs = [None] * 100
        hdrs[4] = [("traceparent", b"00-aa-bb-01")]
        buf = self._buf(rows, headers=hdrs)
        offs, dec = self._assert_parity(buf)
        assert dec.tobytes() == rows.tobytes()

    def test_wrong_length_value_raises_on_every_tier(self):
        buf = encode_record_batch(0, [b"\x01" * 9])
        for fn in (
            decode_record_batches_rows_py,
            decode_record_batches_rows_vec,
            decode_record_batches_rows,
        ):
            with pytest.raises(ValueError):
                fn(buf, self.N_COLS)

    def test_crc_damage_raises_on_every_tier(self):
        rows = self._rows(64, seed=4)
        buf = bytearray(self._buf(rows))
        buf[70] ^= 0xFF  # inside the records region
        for fn in (
            decode_record_batches_rows_py,
            decode_record_batches_rows_vec,
        ):
            with pytest.raises(ValueError, match="CRC32C"):
                fn(bytes(buf), self.N_COLS)

    def test_empty_buffer(self):
        o, r = decode_record_batches_rows_vec(b"", self.N_COLS)
        assert o.shape == (0,) and r.shape == (0, self.N_COLS)


class TestPrefetchedBlockSource:
    def test_ordering_and_no_loss(self):
        data = np.arange(3000 * 3, dtype=np.float32).reshape(3000, 3)
        broker = MiniKafkaBroker(topic="p")
        try:
            broker.append_rows(data)
            m = MetricsRegistry()
            src = PrefetchedBlockSource(
                KafkaBlockSource(
                    broker.host, broker.port, "p",
                    n_cols=3, max_wait_ms=20,
                ),
                depth=3, metrics=m,
            )
            try:
                got, pos = _drain_blocks(src, 3000)
                assert pos == 3000
                cursor = 0
                merged = []
                for off, blk in got:
                    assert off == cursor
                    cursor += blk.shape[0]
                    merged.append(blk)
                assert np.concatenate(merged).tobytes() == data.tobytes()
                snap = m.struct_snapshot()
                assert snap["counters"]["prefetch_records"] == 3000
                assert snap["gauges"]["prefetch_depth"]["max"] >= 1
            finally:
                src.close()
        finally:
            broker.close()

    def test_seek_discards_prefetched_batches(self):
        data = np.arange(2000 * 2, dtype=np.float32).reshape(2000, 2)
        broker = MiniKafkaBroker(topic="s")
        try:
            broker.append_rows(data)
            src = PrefetchedBlockSource(
                KafkaBlockSource(
                    broker.host, broker.port, "s",
                    n_cols=2, max_wait_ms=20,
                    # small fetches so several batches queue ahead
                    max_bytes=2048,
                ),
                depth=4,
            )
            try:
                got, pos = _drain_blocks(src, 200)
                assert pos >= 200
                # let the sidecar run ahead, then rewind mid-stream
                time.sleep(0.05)
                src.seek(100)
                polled = None
                deadline = time.monotonic() + 15.0
                while polled is None and time.monotonic() < deadline:
                    polled = src.poll()
                off, blk = polled
                # the first post-seek block starts EXACTLY at the seek
                # offset: nothing stale crossed the handoff queue
                assert off == 100
                assert blk[0, 0] == data[100, 0]
            finally:
                src.close()
        finally:
            broker.close()

    def test_survives_broker_restart(self):
        data = np.arange(400 * 3, dtype=np.float32).reshape(400, 3)
        broker = MiniKafkaBroker(topic="r")
        port = broker.port
        src = PrefetchedBlockSource(
            KafkaBlockSource(
                broker.host, port, "r", n_cols=3, max_wait_ms=20,
            ),
            depth=2,
        )
        broker.append_rows(data[:250])
        got, pos = _drain_blocks(src, 250)
        assert pos == 250
        broker.close()  # broker dies mid-stream
        # outage: polls yield None (inner reconnect/backoff), no raise
        assert src.poll() is None
        broker2 = MiniKafkaBroker(topic="r", port=port)
        try:
            broker2.append_rows(data)
            got2, pos2 = _drain_blocks(src, 150)
            assert pos2 == 150
            assert got2[0][0] == 250  # resumed at exactly the cursor
            src.close()
        finally:
            broker2.close()

    def test_partition_error_propagates(self):
        broker = MiniKafkaBroker(topic="x", n_partitions=1)
        try:
            src = PrefetchedBlockSource(
                KafkaBlockSource(
                    broker.host, broker.port, "x",
                    partition=7,  # phantom partition: fail fast
                    n_cols=2, max_wait_ms=20,
                ),
                depth=2,
            )
            with pytest.raises(KafkaPartitionError):
                deadline = time.monotonic() + 15.0
                while time.monotonic() < deadline:
                    src.poll()
                    time.sleep(0.002)
            # sticky: the next poll re-raises instead of hanging
            with pytest.raises(KafkaPartitionError):
                src.poll()
            src.close()
        finally:
            broker.close()

    def test_seek_after_sidecar_error_recovers(self):
        """A seek/restore after a sidecar exception must discard the
        queued pre-seek batches, drop the sticky error, and spawn a
        fresh sidecar (review finding, pinned: the dead-thread pause
        used to skip all three)."""

        class _Inner:
            prefetchable = True
            exhausted = False

            def __init__(self):
                self.cursor = 0
                self.fail_at = 3  # batches 0,1,2 queue, then death

            def poll(self):
                off = self.cursor
                if off == self.fail_at:
                    self.fail_at = None  # fail once
                    raise ConnectionError("boom")
                self.cursor += 1
                return off, np.full((1, 2), off, np.float32)

            def seek(self, offset):
                self.cursor = offset

            def close(self):
                pass

        src = PrefetchedBlockSource(_Inner(), depth=8)
        src.poll()  # start the sidecar
        t = src._thread
        deadline = time.monotonic() + 10.0
        while t.is_alive() and time.monotonic() < deadline:
            time.sleep(0.002)  # sidecar queues 0..2, then dies
        assert not t.is_alive()
        # seek with stale batches STILL QUEUED: they must not survive
        src.seek(0)
        polled = None
        deadline = time.monotonic() + 10.0
        while polled is None and time.monotonic() < deadline:
            polled = src.poll()
        # fresh sidecar, re-seeked source, nothing stale: offset 0 again
        assert polled is not None and polled[0] == 0
        src.close()

    def test_shutdown_joins_sidecar(self):
        broker = MiniKafkaBroker(topic="c")
        try:
            src = PrefetchedBlockSource(
                KafkaBlockSource(
                    broker.host, broker.port, "c",
                    n_cols=2, max_wait_ms=20,
                ),
                depth=2,
            )
            src.poll()  # start the sidecar
            t = src._thread
            assert t is not None and t.is_alive()
            src.close()
            assert not t.is_alive()
        finally:
            broker.close()

    def test_checkpoint_hooks_proxy_to_inner(self):
        broker = MiniKafkaBroker(topic="h", n_partitions=2)
        try:
            inner = KafkaBlockSource(
                broker.host, broker.port, "h",
                partitions=[0, 1], n_cols=2, max_wait_ms=20,
            )
            src = maybe_wrap_block(inner, enable=True)
            assert isinstance(src, PrefetchedBlockSource)
            # vector-mode checkpoint state resolves through the wrapper
            state = src.checkpoint_state(0)
            assert state == {"offset": 0, "cursors": {"0": 0, "1": 0}}
            assert src.restore_state(state) == 0
            src.close()
        finally:
            broker.close()

    def test_freshness_stamps_survive_the_handoff(self):
        rng = np.random.default_rng(6)
        data = rng.normal(size=(256, 4)).astype(np.float32)
        broker = MiniKafkaBroker(topic="fresh")
        m = MetricsRegistry()
        try:
            now_ms = int(time.time() * 1000)
            broker.append_rows(data, timestamp_ms=now_ms - 3_000)
            src = maybe_wrap_block(
                KafkaBlockSource(
                    broker.host, broker.port, "fresh",
                    n_cols=4, max_wait_ms=20, metrics=m,
                ),
                metrics=m,
            )
            assert isinstance(src, PrefetchedBlockSource)
            try:
                got, pos = _drain_blocks(src, 256, timeout=15.0)
                assert pos == 256
                g = m.struct_snapshot()["gauges"]
                wm_lag = g.get('watermark_lag_s{partition="0"}')
                assert wm_lag is not None
                assert 2.5 <= wm_lag["value"] < 60.0
                # the sink side still consumes the sidecar's stamps
                from flink_jpmml_tpu.obs.freshness import freshness_for

                freshness_for(m).observe_sink(0, 256)
                h = m.histogram("record_staleness_s")
                assert h.count() >= 1
                assert h.quantile(0.5) == pytest.approx(3.0, abs=2.0)
            finally:
                src.close()
        finally:
            broker.close()

    def test_journey_ingest_hops_from_decode_thread(
        self, tmp_path, monkeypatch
    ):
        from flink_jpmml_tpu.obs import trace as trace_mod

        monkeypatch.setenv("FJT_JOURNEY_DIR", str(tmp_path / "j"))
        rng = np.random.default_rng(8)
        data = rng.normal(size=(64, 3)).astype(np.float32)
        broker = MiniKafkaBroker(topic="j")
        m = MetricsRegistry()
        try:
            broker.append_rows(data)
            src = maybe_wrap_block(
                KafkaBlockSource(
                    broker.host, broker.port, "j",
                    n_cols=3, max_wait_ms=20, metrics=m,
                ),
                metrics=m,
            )
            try:
                got, pos = _drain_blocks(src, 64, timeout=15.0)
                assert pos == 64
                store = trace_mod.store_for(m)
                assert store is not None
                # the ingest hop was recorded (durably) from the
                # SIDECAR thread, keyed to the fetched run's offsets
                rows = trace_mod.read_rows(store.directory)
                ingests = [r for r in rows if r["kind"] == "ingest"]
                assert ingests, rows
                assert ingests[0]["first_off"] == 0
            finally:
                src.close()
        finally:
            broker.close()

    def test_dlq_routing_from_decode_thread(self, tmp_path):
        from flink_jpmml_tpu.runtime.dlq import DeadLetterQueue

        rng = np.random.default_rng(9)
        data = rng.normal(size=(100, 4)).astype(np.float32)
        broker = MiniKafkaBroker(topic="d")
        m = MetricsRegistry()
        dlq = DeadLetterQueue(str(tmp_path / "dlq"), metrics=m)
        try:
            broker.append_rows(data[:50])
            broker.append(b"\xde\xad")  # poison: wrong-length value
            broker.append_rows(data[50:])
            src = maybe_wrap_block(
                KafkaBlockSource(
                    broker.host, broker.port, "d",
                    n_cols=4, max_wait_ms=20, metrics=m, dlq=dlq,
                ),
                metrics=m,
            )
            assert isinstance(src, PrefetchedBlockSource)
            try:
                got, pos = _drain_blocks(src, 100, timeout=15.0)
                assert pos == 100  # 100 good rows; poison skipped
                offsets = set()
                for off, blk in got:
                    offsets.update(range(off, off + blk.shape[0]))
                assert 50 not in offsets  # the poison offset
                entries = list(dlq.scan())
                assert len(entries) == 1
                assert entries[0]["offset"] == 50
                assert entries[0]["reason"] == "decode"
                snap = m.struct_snapshot()["counters"]
                assert snap['decode_errors{partition="0"}'] == 1
            finally:
                src.close()
        finally:
            broker.close()


class TestPrefetchedRecordSource:
    def test_rechunks_to_max_n_in_order(self):
        broker = MiniKafkaBroker(topic="rec")
        try:
            vals = [b'{"i": %d}' % i for i in range(500)]
            broker.append(*vals)
            src = maybe_wrap_records(
                KafkaRecordSource(
                    broker.host, broker.port, "rec", max_wait_ms=20,
                ),
            )
            assert isinstance(src, PrefetchedRecordSource)
            try:
                out = []
                deadline = time.monotonic() + 20.0
                while len(out) < 500 and time.monotonic() < deadline:
                    out.extend(src.poll(64))
                assert [r["i"] for _, r in out] == list(range(500))
                # record-source offsets are "position after": 1-based
                assert [o for o, _ in out] == list(range(1, 501))
            finally:
                src.close()
        finally:
            broker.close()


class TestWrapPolicy:
    def test_env_kill_switch_wins(self, monkeypatch):
        class _Src:
            prefetchable = True

        monkeypatch.setenv(prefetch_mod.ENV_DISABLE, "1")
        s = _Src()
        assert maybe_wrap_block(s, enable=True) is s
        assert maybe_wrap_records(s, enable=True) is s

    def test_auto_wraps_only_marked_sources(self):
        class _Plain:
            pass

        class _Marked:
            prefetchable = True

            def close(self):
                pass

        assert maybe_wrap_block(_Plain()) is not None
        assert not isinstance(maybe_wrap_block(_Plain()),
                              PrefetchedBlockSource)
        wrapped = maybe_wrap_block(_Marked())
        assert isinstance(wrapped, PrefetchedBlockSource)
        # no double wrap
        assert maybe_wrap_block(wrapped) is wrapped

    def test_depth_env(self, monkeypatch):
        monkeypatch.setenv(prefetch_mod.ENV_DEPTH, "9")
        assert prefetch_mod.env_depth() == 9
        monkeypatch.setenv(prefetch_mod.ENV_DEPTH, "junk")
        assert prefetch_mod.env_depth() == prefetch_mod.DEFAULT_DEPTH


class TestPressurePrefetchComponent:
    def test_occupancy_feeds_the_composite(self):
        from flink_jpmml_tpu.obs.pressure import PressureMonitor

        clk = {"t": 1000.0}
        m = MetricsRegistry()
        mon = PressureMonitor(
            m, windows=((2.0, 0.5),), clock=lambda: clk["t"]
        )
        mon.tick()
        mon.note_prefetch(0.9)  # sidecar peak-hold between ticks
        clk["t"] += 1.0
        out = mon.tick()
        assert out["prefetch"] == pytest.approx(0.9)
        assert out["pressure"] == pytest.approx(0.9)
        assert m.gauge("pressure_prefetch").get() == pytest.approx(0.9)
        # gauge-read path too (no peak noted since)
        m.gauge("prefetch_occupancy").set(0.4)
        clk["t"] += 1.0
        out = mon.tick()
        assert out["prefetch"] == pytest.approx(0.4)
        assert "prefetch" in mon.health()["pressure"]["components"]


class TestPipelineIntegration:
    def test_stop_parks_the_sidecar(self):
        """BlockPipelineBase.stop() must stop the sidecar it created."""
        import tempfile

        from assets.generate import gen_gbm
        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.pmml import parse_pmml_file
        from flink_jpmml_tpu.runtime.block import BlockPipeline

        with tempfile.TemporaryDirectory() as tmp:
            doc = parse_pmml_file(
                gen_gbm(tmp, n_trees=5, depth=2, n_features=3)
            )
        cm = compile_pmml(doc, batch_size=32)
        rng = np.random.default_rng(0)
        data = rng.normal(size=(2000, 3)).astype(np.float32)
        broker = MiniKafkaBroker(topic="pi")
        src = None
        try:
            broker.append_rows(data)
            src = KafkaBlockSource(
                broker.host, broker.port, "pi",
                n_cols=3, max_wait_ms=20,
            )
            seen = []
            pipe = BlockPipeline(
                src, cm, lambda out, n, off: seen.append(n),
                use_native=False,
            )
            assert isinstance(pipe._source, PrefetchedBlockSource)
            pipe.start()
            deadline = time.monotonic() + 30.0
            while sum(seen) < 2000 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert sum(seen) == 2000
            pipe.stop()
            pipe.join(timeout=15.0)
            t = pipe._source._thread
            assert t is None or not t.is_alive()
        finally:
            if src is not None:
                src.close()
            broker.close()
