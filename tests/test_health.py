"""Failure detection (parallel/health.py): heartbeat liveness, death
declaration, elastic recovery, coordinator-restart resilience."""

import time

from flink_jpmml_tpu.parallel.health import HealthCoordinator, HealthReporter


def _wait(cond, timeout=10.0, msg="condition never held"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.01)
    raise AssertionError(msg)


class TestHealth:
    def test_alive_dead_recover_cycle(self):
        deaths, recoveries = [], []
        coord = HealthCoordinator(
            timeout_s=0.6,
            on_dead=deaths.append,
            on_recover=recoveries.append,
        )
        try:
            r1 = HealthReporter(coord.host, coord.port, "w1",
                                interval_s=0.1)
            r2 = HealthReporter(coord.host, coord.port, "w2",
                                interval_s=0.1)
            _wait(lambda: set(coord.alive()) == {"w1", "w2"},
                  msg="workers never registered")
            # kill w2's heartbeats → declared dead within the timeout
            r2.stop()
            _wait(lambda: coord.dead() == ["w2"],
                  msg="w2 never declared dead")
            assert deaths == ["w2"]
            assert coord.alive() == ["w1"]
            # the worker restarts (new reporter, same id): elastic rejoin
            r2b = HealthReporter(coord.host, coord.port, "w2",
                                 interval_s=0.1)
            _wait(lambda: set(coord.alive()) == {"w1", "w2"},
                  msg="w2 never recovered")
            assert recoveries == ["w2"]
            assert coord.dead() == []
            r1.stop()
            r2b.stop()
        finally:
            coord.close()

    def test_reporter_survives_coordinator_restart(self):
        coord = HealthCoordinator(timeout_s=0.6)
        port = coord.port
        rep = HealthReporter(coord.host, port, "w", interval_s=0.05)
        try:
            _wait(lambda: coord.alive() == ["w"])
            coord.close()  # outage: the reporter reconnects with backoff
            time.sleep(0.3)
            coord2 = HealthCoordinator(port=port, timeout_s=0.6)
            try:
                _wait(lambda: coord2.alive() == ["w"],
                      msg="reporter never re-registered after restart")
            finally:
                coord2.close()
        finally:
            rep.stop()
            coord.close()

    def test_crashing_callback_does_not_disable_detection(self):
        deaths = []

        def bad_hook(wid):
            deaths.append(wid)
            raise RuntimeError("supervisor hook broke")

        coord = HealthCoordinator(timeout_s=0.5, on_dead=bad_hook)
        try:
            r1 = HealthReporter(coord.host, coord.port, "a",
                                interval_s=0.1)
            r2 = HealthReporter(coord.host, coord.port, "b",
                                interval_s=0.1)
            _wait(lambda: set(coord.alive()) == {"a", "b"})
            r1.stop()
            _wait(lambda: "a" in coord.dead(), msg="a never declared")
            # the hook raised — detection must still work for b
            r2.stop()
            _wait(lambda: set(coord.dead()) == {"a", "b"},
                  msg="detection disabled after callback crash")
            assert set(deaths) == {"a", "b"}
        finally:
            coord.close()

    def test_remove_and_expiry(self):
        coord = HealthCoordinator(timeout_s=0.3, expire_after_s=0.5)
        try:
            rep = HealthReporter(coord.host, coord.port, "tmp",
                                 interval_s=0.05)
            _wait(lambda: coord.alive() == ["tmp"])
            rep.stop()
            _wait(lambda: coord.dead() == ["tmp"])
            # expiry drops the long-dead worker from the registry
            _wait(lambda: coord.dead() == [] and coord.alive() == [],
                  msg="dead worker never expired")
            # remove() deregisters immediately
            rep2 = HealthReporter(coord.host, coord.port, "tmp2",
                                  interval_s=0.05)
            _wait(lambda: coord.alive() == ["tmp2"])
            rep2.stop()
            time.sleep(0.15)  # drain any frame already in the socket buffer
            coord.remove("tmp2")
            assert coord.alive() == [] and coord.dead() == []
        finally:
            coord.close()

    def test_garbage_frame_ignored(self):
        import socket
        import struct

        coord = HealthCoordinator(timeout_s=1.0)
        try:
            rep = HealthReporter(coord.host, coord.port, "ok",
                                 interval_s=0.05)
            with socket.create_connection(
                (coord.host, coord.port)
            ) as s:
                s.sendall(struct.pack(">I", 7) + b"not-json")
            _wait(lambda: coord.alive() == ["ok"])
            assert coord.dead() == []
            rep.stop()
        finally:
            coord.close()
