"""Scorecard and RuleSetModel families: the reference scores any
JPMML-supported model class (SURVEY.md §1 C1), so these close real model
-family gaps. Golden-diffed compiled vs oracle vs hand-computed values."""

import numpy as np
import pytest

from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.pmml import parse_pmml
from flink_jpmml_tpu.pmml.interp import evaluate

SCORECARD = """<PMML version="4.3"><DataDictionary>
  <DataField name="age" optype="continuous" dataType="double"/>
  <DataField name="income" optype="continuous" dataType="double"/>
  <DataField name="score" optype="continuous" dataType="double"/>
  </DataDictionary>
  <Scorecard functionName="regression" initialScore="100"
      useReasonCodes="true" reasonCodeAlgorithm="pointsBelow"
      baselineScore="25">
  <MiningSchema><MiningField name="score" usageType="target"/>
    <MiningField name="age"/><MiningField name="income"/></MiningSchema>
  <Output>
    <OutputField name="sc" feature="predictedValue"/>
    <OutputField name="rc1" feature="reasonCode" rank="1"/>
    <OutputField name="rc2" feature="reasonCode" rank="2"/>
  </Output>
  <Characteristics>
    <Characteristic name="ageCh" reasonCode="AGE" baselineScore="30">
      <Attribute partialScore="10">
        <SimplePredicate field="age" operator="isMissing"/></Attribute>
      <Attribute partialScore="40" reasonCode="AGE_YOUNG">
        <SimplePredicate field="age" operator="lessThan" value="30"/>
      </Attribute>
      <Attribute partialScore="20"><True/></Attribute>
    </Characteristic>
    <Characteristic name="incomeCh" reasonCode="INC">
      <Attribute partialScore="5">
        <CompoundPredicate booleanOperator="or">
          <SimplePredicate field="income" operator="isMissing"/>
          <SimplePredicate field="income" operator="lessThan" value="1000"/>
        </CompoundPredicate></Attribute>
      <Attribute partialScore="35"><True/></Attribute>
    </Characteristic>
  </Characteristics></Scorecard></PMML>"""


class TestScorecard:
    def test_hand_computed_scores(self):
        doc = parse_pmml(SCORECARD)
        cm = compile_pmml(doc)
        cases = [
            # (record, expected = 100 + age partial + income partial)
            ({"age": 25.0, "income": 5000.0}, 100 + 40 + 35),
            ({"age": 45.0, "income": 500.0}, 100 + 20 + 5),
            ({"income": 5000.0}, 100 + 10 + 35),          # age missing
            ({"age": 30.0}, 100 + 20 + 5),                # income missing
        ]
        preds = cm.score_records([r for r, _ in cases])
        for (rec, want), p in zip(cases, preds):
            o = evaluate(doc, rec)
            assert o.value == want, rec
            assert p.score.value == pytest.approx(want), rec

    def test_parity_randomized(self):
        doc = parse_pmml(SCORECARD)
        cm = compile_pmml(doc)
        rng = np.random.default_rng(0)
        recs = []
        for _ in range(200):
            rec = {}
            if rng.random() > 0.2:
                rec["age"] = float(rng.uniform(15, 80))
            if rng.random() > 0.2:
                rec["income"] = float(rng.uniform(0, 9000))
            recs.append(rec)
        for rec, p in zip(recs, cm.score_records(recs)):
            o = evaluate(doc, rec)
            assert not p.is_empty and o.value is not None
            assert p.score.value == pytest.approx(o.value), rec

    def test_reason_codes_ranked_points_below(self):
        doc = parse_pmml(SCORECARD)
        cm = compile_pmml(doc)
        # age=45 → AGE partial 20 (baseline 30, diff 10)
        # income=5000 → INC partial 35 (baseline 25, diff −10)
        rec = {"age": 45.0, "income": 5000.0}
        p = cm.score_records([rec])[0]
        o = evaluate(doc, rec)
        assert o.reason_codes == ("AGE", "INC")
        assert p.outputs["rc1"] == "AGE"
        assert p.outputs["rc2"] == "INC"
        # young age picks the attribute-level override code
        rec2 = {"age": 20.0, "income": 500.0}
        p2 = cm.score_records([rec2])[0]
        o2 = evaluate(doc, rec2)
        # age partial 40 (diff −10), income partial 5 (diff 20): INC first
        assert o2.reason_codes == ("INC", "AGE_YOUNG")
        assert p2.outputs["rc1"] == "INC"
        assert p2.outputs["rc2"] == "AGE_YOUNG"

    def test_unmatched_characteristic_is_empty_lane(self):
        xml = """<PMML version="4.3"><DataDictionary>
          <DataField name="x" optype="continuous" dataType="double"/>
          <DataField name="score" optype="continuous" dataType="double"/>
          </DataDictionary>
          <Scorecard functionName="regression" initialScore="0"
              useReasonCodes="false">
          <MiningSchema><MiningField name="score" usageType="target"/>
            <MiningField name="x"/></MiningSchema>
          <Characteristics><Characteristic name="c">
            <Attribute partialScore="1">
              <SimplePredicate field="x" operator="greaterThan" value="0"/>
            </Attribute>
          </Characteristic></Characteristics></Scorecard></PMML>"""
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        preds = cm.score_records([{"x": 1.0}, {"x": -1.0}, {}])
        assert [p.is_empty for p in preds] == [False, True, True]
        assert evaluate(doc, {"x": -1.0}).is_missing
        assert preds[0].score.value == 1.0


RULESET = """<PMML version="4.3"><DataDictionary>
  <DataField name="a" optype="continuous" dataType="double"/>
  <DataField name="b" optype="continuous" dataType="double"/>
  <DataField name="cls" optype="categorical" dataType="string">
    <Value value="lo"/><Value value="mid"/><Value value="hi"/></DataField>
  </DataDictionary>
  <RuleSetModel functionName="classification">
  <MiningSchema><MiningField name="cls" usageType="target"/>
    <MiningField name="a"/><MiningField name="b"/></MiningSchema>
  <RuleSet defaultScore="mid" defaultConfidence="0.3">
    <RuleSelectionMethod criterion="{criterion}"/>
    <SimpleRule id="r1" score="hi" weight="2.0" confidence="0.9">
      <SimplePredicate field="a" operator="greaterThan" value="1"/>
    </SimpleRule>
    <CompoundRule>
      <SimplePredicate field="b" operator="greaterThan" value="0"/>
      <SimpleRule id="r2" score="lo" weight="3.0" confidence="0.8">
        <SimplePredicate field="a" operator="lessThan" value="0"/>
      </SimpleRule>
      <SimpleRule id="r3" score="hi" weight="1.5" confidence="0.7">
        <True/>
      </SimpleRule>
    </CompoundRule>
    <SimpleRule id="r4" score="lo" weight="0.5" confidence="0.6">
      <SimplePredicate field="b" operator="lessOrEqual" value="0"/>
    </SimpleRule>
  </RuleSet></RuleSetModel></PMML>"""


class TestRuleSet:
    def _doc(self, criterion):
        return parse_pmml(RULESET.format(criterion=criterion))

    def _parity(self, criterion, n=200, seed=1):
        doc = self._doc(criterion)
        cm = compile_pmml(doc)
        rng = np.random.default_rng(seed)
        recs = []
        for _ in range(n):
            rec = {}
            if rng.random() > 0.2:
                rec["a"] = float(rng.normal())
            if rng.random() > 0.2:
                rec["b"] = float(rng.normal())
            recs.append(rec)
        for rec, p in zip(recs, cm.score_records(recs)):
            o = evaluate(doc, rec)
            assert not p.is_empty  # defaultScore keeps every lane total
            assert p.target.label == o.label, (criterion, rec)
            assert p.score.value == pytest.approx(o.value, rel=1e-5), (
                criterion, rec,
            )
        return doc

    def test_first_hit(self):
        doc = self._parity("firstHit")
        # a>1 fires r1 regardless of b
        o = evaluate(doc, {"a": 2.0, "b": 1.0})
        assert o.label == "hi" and o.value == pytest.approx(0.9)
        # nested compound rule: b>0 AND a<0 → r2
        o = evaluate(doc, {"a": -1.0, "b": 1.0})
        assert o.label == "lo" and o.value == pytest.approx(0.8)
        # nothing fires (a missing, b missing) → default
        o = evaluate(doc, {})
        assert o.label == "mid" and o.value == pytest.approx(0.3)

    def test_weighted_sum(self):
        doc = self._parity("weightedSum")
        # a=2, b=1: r1 (hi, 2.0) + r3 (hi, 1.5) fire → hi total 3.5 over
        # 2 fired rules
        o = evaluate(doc, {"a": 2.0, "b": 1.0})
        assert o.label == "hi"
        assert o.value == pytest.approx(3.5 / 2)

    def test_weighted_max(self):
        doc = self._parity("weightedMax")
        # a=-1, b=1: r2 (lo, w3.0) and r3 (hi, w1.5) fire → r2 wins
        o = evaluate(doc, {"a": -1.0, "b": 1.0})
        assert o.label == "lo" and o.value == pytest.approx(0.8)

    def test_no_default_goes_empty(self):
        xml = RULESET.format(criterion="firstHit").replace(
            ' defaultScore="mid" defaultConfidence="0.3"', ""
        )
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        p = cm.score_records([{}])[0]
        assert p.is_empty
        assert evaluate(doc, {}).is_missing


class TestReviewRegressions:
    def test_ragged_characteristic_unmatched_is_invalid(self):
        """A characteristic with fewer attributes than the widest one
        must still yield an invalid lane when nothing matches (review:
        padded slots vacuously matched)."""
        xml = """<PMML version="4.3"><DataDictionary>
          <DataField name="x" optype="continuous" dataType="double"/>
          <DataField name="y" optype="continuous" dataType="double"/>
          <DataField name="score" optype="continuous" dataType="double"/>
          </DataDictionary>
          <Scorecard functionName="regression" initialScore="0"
              useReasonCodes="false">
          <MiningSchema><MiningField name="score" usageType="target"/>
            <MiningField name="x"/><MiningField name="y"/></MiningSchema>
          <Characteristics>
            <Characteristic name="wide">
              <Attribute partialScore="1">
                <SimplePredicate field="x" operator="lessThan" value="0"/>
              </Attribute>
              <Attribute partialScore="2">
                <SimplePredicate field="x" operator="lessThan" value="5"/>
              </Attribute>
              <Attribute partialScore="3"><True/></Attribute>
            </Characteristic>
            <Characteristic name="narrow">
              <Attribute partialScore="10">
                <SimplePredicate field="y" operator="greaterThan" value="0"/>
              </Attribute>
            </Characteristic>
          </Characteristics></Scorecard></PMML>"""
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        recs = [
            {"x": 1.0, "y": 1.0},   # both match → 2 + 10
            {"x": 1.0, "y": -1.0},  # narrow unmatched → EMPTY
            {"x": 9.0, "y": 2.0},   # 3 + 10
        ]
        preds = cm.score_records(recs)
        for rec, p in zip(recs, preds):
            o = evaluate(doc, rec)
            assert o.is_missing == p.is_empty, rec
        assert [p.is_empty for p in preds] == [False, True, False]
        assert preds[0].score.value == pytest.approx(12.0)
        assert preds[2].score.value == pytest.approx(13.0)

    def test_inactive_declared_fields_never_invalidate(self):
        """Extra declared columns (incl. a categorical target with
        values) in the record must not trip returnInvalid on either path
        (review: the oracle sanitized ALL DataDictionary fields)."""
        xml = """<PMML version="4.3"><DataDictionary>
          <DataField name="f" optype="continuous" dataType="double"/>
          <DataField name="extra" optype="categorical" dataType="string">
            <Value value="p"/><Value value="q"/></DataField>
          <DataField name="y" optype="categorical" dataType="string">
            <Value value="no"/><Value value="yes"/></DataField>
          </DataDictionary>
          <RegressionModel functionName="classification"
              normalizationMethod="softmax">
          <MiningSchema><MiningField name="y" usageType="target"/>
            <MiningField name="f"/></MiningSchema>
          <RegressionTable intercept="0.5" targetCategory="yes">
            <NumericPredictor name="f" coefficient="1.0"/></RegressionTable>
          <RegressionTable intercept="0" targetCategory="no"/>
          </RegressionModel></PMML>"""
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        rec = {"f": 1.0, "extra": "undeclared!", "y": "maybe"}
        o = evaluate(doc, rec)
        assert not o.is_missing  # inactive fields never invalidate
        p = cm.score_records([rec])[0]
        assert not p.is_empty
        assert p.target.label == o.label


COMPLEX_SC = """<PMML version="4.3"><DataDictionary>
  <DataField name="bal" optype="continuous" dataType="double"/>
  <DataField name="score" optype="continuous" dataType="double"/>
  </DataDictionary>
  <Scorecard functionName="regression" initialScore="50"
      useReasonCodes="false">
  <MiningSchema><MiningField name="score" usageType="target"/>
    <MiningField name="bal"/></MiningSchema>
  <Characteristics>
    <Characteristic name="balCh">
      <Attribute>
        <SimplePredicate field="bal" operator="greaterOrEqual" value="0"/>
        <ComplexPartialScore>
          <Apply function="*"><Constant>0.1</Constant>
            <FieldRef field="bal"/></Apply>
        </ComplexPartialScore>
      </Attribute>
      <Attribute>
        <True/>
        <ComplexPartialScore>
          <Apply function="ln"><FieldRef field="bal"/></Apply>
        </ComplexPartialScore>
      </Attribute>
    </Characteristic>
  </Characteristics></Scorecard></PMML>"""


class TestComplexPartialScore:
    def test_computed_partial_parity(self):
        doc = parse_pmml(COMPLEX_SC)
        cm = compile_pmml(doc)
        for bal in (0.0, 120.0, 7.5):
            rec = {"bal": bal}
            hand = 50.0 + 0.1 * bal
            o = evaluate(doc, rec)
            p = cm.score_records([rec])[0]
            assert o.value == pytest.approx(hand)
            assert p.score.value == pytest.approx(hand, rel=1e-5)

    def test_failed_expression_empties_lane(self):
        # bal < 0: first attribute doesn't match; the fallback computes
        # ln(bal) which fails for negatives -> empty lane on BOTH paths
        doc = parse_pmml(COMPLEX_SC)
        cm = compile_pmml(doc)
        rec = {"bal": -5.0}
        assert evaluate(doc, rec).value is None
        assert cm.score_records([rec])[0].is_empty
        # ... while a positive bal through the SAME fallback branch works
        # (exercise ln on the matched-second-attribute path)
        doc2 = parse_pmml(COMPLEX_SC.replace(
            'operator="greaterOrEqual" value="0"',
            'operator="greaterOrEqual" value="1000"',
        ))
        cm2 = compile_pmml(doc2)
        import math

        rec2 = {"bal": 20.0}
        hand = 50.0 + math.log(20.0)
        assert evaluate(doc2, rec2).value == pytest.approx(hand)
        assert cm2.score_records([rec2])[0].score.value == pytest.approx(
            hand, rel=1e-5
        )

    def test_mixed_static_and_complex(self):
        xml = COMPLEX_SC.replace(
            "<Attribute>\n        <SimplePredicate",
            '<Attribute partialScore="99">\n        <SimplePredicate',
            1,
        ).replace(
            "<ComplexPartialScore>\n          <Apply function=\"*\"><Constant>0.1</Constant>\n            <FieldRef field=\"bal\"/></Apply>\n        </ComplexPartialScore>\n      </Attribute>",
            "</Attribute>",
            1,
        )
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        rec = {"bal": 3.0}
        assert evaluate(doc, rec).value == pytest.approx(149.0)
        assert cm.score_records([rec])[0].score.value == pytest.approx(
            149.0, rel=1e-6
        )
