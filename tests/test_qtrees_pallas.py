"""Pallas VMEM-resident quantized kernel (qtrees_pallas.py) parity.

Runs in Pallas interpreter mode on the CPU test backend; the math is
identical to the compiled TPU kernel (same trace), so interpret-mode parity
plus the XLA-path golden tests pin the kernel's semantics.
"""

import numpy as np
import pytest

from assets.generate import gen_gbm
from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.compile.qtrees import build_quantized_scorer
from flink_jpmml_tpu.pmml import parse_pmml_file


def _doc(tmp_path, **kw):
    return parse_pmml_file(gen_gbm(str(tmp_path), **kw))


class TestPallasParity:
    def test_matches_xla_and_f32_paths(self, tmp_path):
        doc = _doc(tmp_path, n_trees=21, depth=4, n_features=8)
        B = 64
        cm = compile_pmml(doc, batch_size=B)
        qx = build_quantized_scorer(doc, batch_size=B, backend="xla")
        qp = build_quantized_scorer(
            doc, batch_size=B, backend="pallas", pallas_interpret=True
        )
        assert qp is not None and qp.backend == "pallas"
        rng = np.random.default_rng(0)
        X = rng.normal(0.0, 1.5, size=(B, 8)).astype(np.float32)
        X[rng.random(size=X.shape) < 0.2] = np.nan
        Xq = qp.wire.encode(X)
        got = np.asarray(qp.predict_wire(Xq), np.float32)
        ref_x = np.asarray(qx.predict_wire(Xq), np.float32)
        M = np.isnan(X)
        ref_f = np.asarray(
            cm.predict(np.nan_to_num(X, nan=0.0), M).value, np.float32
        )
        np.testing.assert_allclose(got, ref_x, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(got, ref_f, rtol=1e-4, atol=1e-5)

    def test_group_padding_trees_not_multiple_of_gt(self, tmp_path):
        # 19 trees: pads to 20 (GT=4) — padded trees must contribute zero
        doc = _doc(tmp_path, n_trees=19, depth=3, n_features=4)
        B = 32
        qx = build_quantized_scorer(doc, batch_size=B, backend="xla")
        qp = build_quantized_scorer(
            doc, batch_size=B, backend="pallas", pallas_interpret=True
        )
        rng = np.random.default_rng(1)
        X = rng.normal(size=(B, 4)).astype(np.float32)
        Xq = qp.wire.encode(X)
        np.testing.assert_allclose(
            np.asarray(qp.predict_wire(Xq)),
            np.asarray(qx.predict_wire(Xq)),
            rtol=1e-4, atol=1e-5,
        )

    def test_oversized_batch_chunks_through_fixed_grid(self, tmp_path):
        # the kernel bakes out_shape=(batch_size,): batches larger than the
        # compile batch must be scored in chunks, not silently truncated
        doc = _doc(tmp_path, n_trees=13, depth=3, n_features=4)
        B = 32
        qx = build_quantized_scorer(doc, batch_size=B, backend="xla")
        qp = build_quantized_scorer(
            doc, batch_size=B, backend="pallas", pallas_interpret=True
        )
        rng = np.random.default_rng(2)
        for n in (B - 5, B, 2 * B, 2 * B + 7):
            X = rng.normal(size=(n, 4)).astype(np.float32)
            X[rng.random(size=X.shape) < 0.15] = np.nan
            preds = qp.score(X)
            assert len(preds) == n
            ref = qx.score(X)
            got_v = np.asarray([p.score.value for p in preds])
            ref_v = np.asarray([p.score.value for p in ref])
            np.testing.assert_allclose(got_v, ref_v, rtol=1e-4, atol=1e-5)

    def test_u16_wire_not_pallas_eligible(self, tmp_path):
        doc = _doc(tmp_path, n_trees=300, depth=5, n_features=2,
                   hist_bins=None)
        qp = build_quantized_scorer(
            doc, batch_size=64, backend="pallas", pallas_interpret=True
        )
        assert qp is None  # u16 ranks are not bf16-exact
        qa = build_quantized_scorer(
            doc, batch_size=64, backend="auto", pallas_interpret=True
        )
        assert qa is not None and qa.backend == "xla"


from flink_jpmml_tpu.pmml import parse_pmml
from test_qtrees import _forest_xml


class TestPallasClassification:
    """VERDICT r2 missing #4: the classification-vote kernel
    (qtrees_pallas._kernel_cls) gets the same interpret-mode parity
    treatment as the regression kernel."""

    def _pair(self, xml, B):
        doc = parse_pmml(xml)
        qx = build_quantized_scorer(doc, batch_size=B, backend="xla")
        qp = build_quantized_scorer(
            doc, batch_size=B, backend="pallas", pallas_interpret=True
        )
        assert qp is not None and qp.backend == "pallas"
        assert qp.is_classification and qx.is_classification
        return doc, qx, qp

    def _assert_triple_parity(self, qx, qp, X):
        Xq = qp.wire.encode(X)
        got_v, got_p, got_l = qp.predict_wire(Xq)
        ref_v, ref_p, ref_l = qx.predict_wire(Xq)
        # identical bf16-split tables on both backends → labels match
        # exactly, vote shares to f32 rounding
        np.testing.assert_array_equal(np.asarray(got_l), np.asarray(ref_l))
        np.testing.assert_allclose(
            np.asarray(got_p), np.asarray(ref_p), rtol=1e-5, atol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(got_v), np.asarray(ref_v), rtol=1e-5, atol=1e-6
        )

    def test_vote_tables_are_bf16_split_pair(self):
        # regression guard for the round-3 on-device failure: the class
        # tables must reach the kernel as the bf16 hi/lo SPLIT pair (the
        # XLA path's operands). A single reconstructed f32 table gets
        # truncated to bf16 by the MXU at default dot precision, which
        # interpret-mode CPU runs cannot detect.
        import jax.numpy as jnp

        _, _, qp = self._pair(_forest_xml("majorityVote", n_trees=8), 32)
        gp = qp.params
        assert "vals_lo" in gp
        assert np.asarray(gp["vals"]).dtype == jnp.bfloat16
        assert np.asarray(gp["vals_lo"]).dtype == jnp.bfloat16

    def test_auto_selects_pallas_for_vote_forests(self):
        # the root-caused fix reopens auto selection (VERDICT r3 #2)
        doc = parse_pmml(_forest_xml("majorityVote", n_trees=8))
        qa = build_quantized_scorer(
            doc, batch_size=32, backend="auto", pallas_interpret=True
        )
        assert qa is not None and qa.backend == "pallas"

    def test_majority_vote_matches_xla_and_f32(self):
        B = 64
        doc, qx, qp = self._pair(_forest_xml("majorityVote", n_trees=8), B)
        cm = compile_pmml(doc, batch_size=B)
        rng = np.random.default_rng(3)
        X = rng.normal(0, 1.5, size=(B, 4)).astype(np.float32)
        X[rng.random(size=X.shape) < 0.2] = np.nan
        self._assert_triple_parity(qx, qp, X)
        # f32 reference path agrees on labels and probabilities
        M = np.isnan(X)
        ref = cm.predict(np.nan_to_num(X, nan=0.0), M)
        _, got_p, got_l = qp.predict_wire(qp.wire.encode(X))
        np.testing.assert_array_equal(
            np.asarray(got_l), np.asarray(ref.label_idx)
        )
        np.testing.assert_allclose(
            np.asarray(got_p), np.asarray(ref.probs), rtol=1e-3, atol=1e-4
        )

    def test_weighted_majority_vote_matches(self):
        B = 32
        _, qx, qp = self._pair(
            _forest_xml("weightedMajorityVote", weighted=True, n_trees=9), B
        )
        rng = np.random.default_rng(4)
        X = rng.normal(0, 1.5, size=(B, 4)).astype(np.float32)
        X[rng.random(size=X.shape) < 0.25] = np.nan
        self._assert_triple_parity(qx, qp, X)

    def test_group_padding_classification(self):
        # 10 trees pad to 12 (GT=4): padded trees' count rows never match,
        # so they add zero votes
        B = 32
        _, qx, qp = self._pair(_forest_xml("majorityVote", n_trees=10), B)
        rng = np.random.default_rng(5)
        X = rng.normal(size=(B, 4)).astype(np.float32)
        self._assert_triple_parity(qx, qp, X)

    def test_oversized_batch_chunks_classification_triple(self):
        # hits the chunked classification-triple concat branch of
        # QuantizedScorer.predict_wire (tuple outputs per fixed-grid chunk)
        B = 32
        _, qx, qp = self._pair(_forest_xml("majorityVote", n_trees=7), B)
        rng = np.random.default_rng(6)
        for n in (B - 9, B, 2 * B, 2 * B + 7):
            X = rng.normal(size=(n, 4)).astype(np.float32)
            X[rng.random(size=X.shape) < 0.15] = np.nan
            preds = qp.score(X)
            ref = qx.score(X)
            assert len(preds) == n
            for a, b in zip(preds, ref):
                assert a.target.label == b.target.label
                assert abs(a.score.value - b.score.value) < 1e-4
