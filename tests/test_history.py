"""Telemetry history plane (ISSUE 18): the exact delta-frame codec,
THE merge (fleet aggregation across sources == downsampling across
time, bitwise), durable segment rings with torn-tail tolerance,
counter-reset fallback, range-query semantics, the cardinality
governor at zoo scale, and the bench-trend regression tripwire.

The heavyweight incident drill (SIGKILL mid-incident, reconstruction
from durable frames alone) lives in ``bench.py --history-drill``;
these are the fast algebraic pins it relies on.
"""

import json
import os
import random
import signal
import subprocess
import sys
import time
from fractions import Fraction

import pytest

from flink_jpmml_tpu.obs import history
from flink_jpmml_tpu.utils.metrics import MetricsRegistry, govern_struct

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# synthetic snapshots / frames
# ---------------------------------------------------------------------------


def _struct(ts, uptime, counters=None, gauges=None):
    return {
        "ts": float(ts),
        "uptime_s": float(uptime),
        "counters": dict(counters or {}),
        "gauges": {
            n: {"value": float(v), "max": float(v)}
            for n, v in (gauges or {}).items()
        },
        "histograms": {},
    }


def _frame(src, t0, t1, counters, gauges=None, res=1.0):
    """One delta frame whose counter deltas are exactly ``counters``."""
    prev = _struct(t0, 1.0, {n: 0.0 for n in counters})
    cur = _struct(t1, 1.0 + (t1 - t0), counters, gauges)
    return history.capture_frame(prev, cur, src, res, t0=t0, t1=t1)


# adversarial float values: non-representable decimal sums, huge/tiny
# magnitude mixes that float addition would absorb or reorder
_ADVERSARIAL = [0.1, 0.2, 0.3, 1e-17, 1e17, 3.333333333333333, 7.0]


# ---------------------------------------------------------------------------
# exact wire codec
# ---------------------------------------------------------------------------


def test_wire_codec_is_exact():
    total = Fraction(0)
    for v in _ADVERSARIAL * 3:
        total += history._dec(v)
    wire = history._enc(total)
    assert history._dec(wire) == total
    # the float projection is the nearest float, not the identity
    assert abs(history.wire_float(wire) - float(total)) <= abs(
        float(total)
    ) * 1e-15
    # a plain dyadic float stays a plain float on the wire
    assert history._enc(Fraction(0.5)) == 0.5
    # ten 0.1s sum exactly, where fsum/float addition would not
    s = sum((history._dec(0.1) for _ in range(10)), Fraction(0))
    assert s == Fraction(0.1) * 10


# ---------------------------------------------------------------------------
# THE merge: associative + commutative, bitwise
# ---------------------------------------------------------------------------


def _adversarial_frames():
    frames = []
    for si, src in enumerate(("w0", "w1", "w2", "w3")):
        for slot in range(3):
            t0 = float(slot)
            counters = {
                "records_out": _ADVERSARIAL[(si + slot) % len(_ADVERSARIAL)],
                "shed_records": _ADVERSARIAL[(si * 3 + slot) % len(_ADVERSARIAL)],
            }
            gauges = {"queue_depth": float(si) + 0.1 * slot}
            frames.append(
                _frame(src, t0, t0 + 1.0, counters, gauges=gauges)
            )
    return frames


def test_merge_bitwise_invariant_under_adversarial_orderings():
    frames = _adversarial_frames()
    baseline = history.canonical(history.merge_frames(frames))
    for seed in (0, 7, 11, 23, 41):
        shuffled = list(frames)
        random.Random(seed).shuffle(shuffled)
        assert (
            history.canonical(history.merge_frames(shuffled)) == baseline
        ), f"merge not order-invariant (seed {seed})"


def test_merge_bitwise_associative_under_adversarial_groupings():
    frames = _adversarial_frames()
    baseline = history.canonical(history.merge_frames(frames))
    for seed in (3, 13, 29):
        rng = random.Random(seed)
        shuffled = list(frames)
        rng.shuffle(shuffled)
        # random binary grouping: merge random sub-groups, then merge
        # the partials — nested merge must equal the flat merge bitwise
        partials = []
        i = 0
        while i < len(shuffled):
            k = rng.randint(1, 4)
            partials.append(history.merge_frames(shuffled[i:i + k]))
            i += k
        rng.shuffle(partials)
        assert (
            history.canonical(history.merge_frames(partials)) == baseline
        ), f"merge not associative (seed {seed})"


def test_downsample_cascade_equals_direct_bitwise():
    # fine frames on a 0.5s grid over 0..20s, two sources
    frames = []
    for src in ("w0", "w1"):
        for i in range(40):
            t0 = i * 0.5
            frames.append(
                _frame(
                    src, t0, t0 + 0.5,
                    {"records_out": _ADVERSARIAL[i % len(_ADVERSARIAL)]},
                    gauges={"queue_depth": float(i % 5)},
                    res=0.5,
                )
            )
    direct = history.downsample(frames, 5.0)
    cascaded = history.downsample(history.downsample(frames, 1.0), 5.0)
    assert len(direct) == len(cascaded) == 4
    for d, c in zip(direct, cascaded):
        assert history.canonical(d) == history.canonical(c)


def test_gauge_merge_semantics():
    a = _frame("w0", 0.0, 1.0, {}, gauges={"queue_depth": 3.0})
    b = _frame("w1", 0.0, 1.0, {}, gauges={"queue_depth": 5.0})
    m = history.merge_frames([a, b])
    g = m["gauges"]["queue_depth"]
    assert g["min"] == 3.0 and g["max"] == 5.0
    assert set(g["last"]) == {"w0", "w1"}
    # default (sum-merged) gauge: the combined last is the fleet sum
    assert history.combined_last("queue_depth", g["last"]) == 8.0


# ---------------------------------------------------------------------------
# counter-reset fallback
# ---------------------------------------------------------------------------


def test_counter_reset_falls_back_to_cumulative():
    prev = _struct(10.0, 50.0, {"records_out": 100.0})
    cur = _struct(11.0, 51.0, {"records_out": 40.0})  # went backwards
    f = history.capture_frame(prev, cur, "w0", 1.0)
    assert history.wire_float(f["counters"]["records_out"]) == 40.0
    assert f["resets"] == 1

    # a backwards uptime flips EVERY family into the fallback at once,
    # even ones whose cumulative advanced across the restart boundary
    prev = _struct(10.0, 50.0, {"records_out": 60.0, "batches": 9.0})
    cur = _struct(11.0, 2.0, {"records_out": 70.0, "batches": 12.0})
    f = history.capture_frame(prev, cur, "w0", 1.0)
    assert history.wire_float(f["counters"]["records_out"]) == 70.0
    assert history.wire_float(f["counters"]["batches"]) == 12.0
    assert f["resets"] == 2

    # the normal path is a true delta
    prev = _struct(10.0, 50.0, {"records_out": 60.0})
    cur = _struct(11.0, 51.0, {"records_out": 70.0})
    f = history.capture_frame(prev, cur, "w0", 1.0)
    assert history.wire_float(f["counters"]["records_out"]) == 10.0
    assert f["resets"] == 0


# ---------------------------------------------------------------------------
# durable rings: retention under a byte budget, torn tails
# ---------------------------------------------------------------------------


def test_ring_retention_under_byte_budget(tmp_path):
    m = MetricsRegistry()
    store = history.HistoryStore(
        str(tmp_path), metrics=m, max_bytes=48 * 1024,
        resolutions=(1.0,), segment_bytes=4096,
    )
    for i in range(600):
        store.append(
            _frame("w0", float(i), float(i + 1), {"records_out": 1.0 * i})
        )
    store.close()
    assert store.bytes_total() <= 48 * 1024 + 4096  # budget + open tail
    frames = history.read_frames(str(tmp_path))
    assert frames, "retention emptied the store"
    # the OLDEST segments were dropped, the newest survive
    assert frames[0]["t0"] > 0.0
    assert frames[-1]["t0"] == 599.0
    snap = m.struct_snapshot()
    assert snap["counters"]['history_dropped{reason="ring_gc"}'] > 0
    assert snap["counters"]["history_frames"] == 600.0


def test_torn_tail_and_garbage_lines_are_skipped(tmp_path):
    store = history.HistoryStore(str(tmp_path), resolutions=(1.0,))
    for i in range(5):
        store.append(
            _frame("w0", float(i), float(i + 1), {"records_out": 2.0})
        )
    store.close()
    segs = sorted(
        p for p in os.listdir(str(tmp_path)) if p.endswith(".jsonl")
    )
    with open(os.path.join(str(tmp_path), segs[-1]), "a") as f:
        f.write('not json at all\n')
        f.write('{"v":1,"src":"w0","res":1.0,"t0":99.0,"t1":')  # torn
    frames = history.read_frames(str(tmp_path))
    assert len(frames) == 5
    assert all(f["t0"] < 99.0 for f in frames)


_KILL_CHILD = r"""
import sys, time
from flink_jpmml_tpu.obs import history
d = sys.argv[1]
store = history.HistoryStore(d, resolutions=(1.0,))
i = 0
while True:
    prev = {"ts": float(i), "uptime_s": 1.0,
            "counters": {"records_out": float(i)}, "gauges": {},
            "histograms": {}}
    cur = {"ts": float(i + 1), "uptime_s": 2.0,
           "counters": {"records_out": float(i + 1)}, "gauges": {},
           "histograms": {}}
    store.append(history.capture_frame(prev, cur, "w0", 1.0))
    i += 1
    time.sleep(0.002)
"""


def test_sigkill_mid_append_leaves_a_readable_store(tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-c", _KILL_CHILD, str(tmp_path)],
        cwd=REPO, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
    )
    try:
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise AssertionError(
                    "writer died early: "
                    + proc.stderr.read().decode(errors="replace")[-2000:]
                )
            if len(history.read_frames(str(tmp_path))) >= 5:
                break
            time.sleep(0.02)
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=10)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
    frames = history.read_frames(str(tmp_path))
    assert len(frames) >= 5
    # each surviving frame is whole: delta of exactly one record
    for f in frames:
        assert history.wire_float(f["counters"]["records_out"]) == 1.0
    # and the survivors are a contiguous prefix of the write order
    t0s = [f["t0"] for f in frames]
    assert t0s == sorted(t0s)
    assert t0s == [float(i) for i in range(len(t0s))]


# ---------------------------------------------------------------------------
# range-query semantics (the /history contract)
# ---------------------------------------------------------------------------


def _populated_store(tmp_path):
    store = history.HistoryStore(str(tmp_path), resolutions=(1.0, 5.0))
    fine = []
    for src in ("w0", "w1"):
        for i in range(10):
            fine.append(
                _frame(
                    src, float(i), float(i + 1),
                    {"records_out": 3.0, "records_in": 4.0},
                    gauges={"queue_depth": float(i)},
                )
            )
    for f in fine:
        store.append(f)
    for f in history.downsample(fine, 5.0):
        store.append(f)
    # a supervisor-side aggregate frame, distinct so leaks are visible
    store.append(
        _frame(history.FLEET_SRC, 0.0, 10.0, {"records_out": 60.0})
    )
    store.close()
    return fine


def test_query_range_step_and_source_semantics(tmp_path):
    _populated_store(tmp_path)
    d = str(tmp_path)

    # default read EXCLUDES the _fleet aggregate (it double-counts)
    p = history.query(d, step=1.0)
    assert p["frames"]
    assert all(
        history.FLEET_SRC not in f["src"].split("+")
        for f in p["frames"]
    )
    total = sum(
        history.wire_float(f["counters"]["records_out"])
        for f in p["frames"]
    )
    assert total == 60.0  # 2 sources x 10 slots x 3

    # ...but the aggregate is reachable by explicit ask
    p = history.query(d, sources=[history.FLEET_SRC])
    assert len(p["frames"]) == 1
    assert history.wire_float(
        p["frames"][0]["counters"]["records_out"]
    ) == 60.0

    # step picks the coarsest stored resolution that still resolves it
    assert history.query(d, step=5.0)["res"] == 5.0
    assert history.query(d, step=1.0)["res"] == 1.0
    assert history.query(d, step=7.0)["res"] == 5.0

    # start/end bound the window
    p = history.query(d, start=3.0, end=6.0, step=1.0)
    assert all(
        f["t1"] >= 3.0 and f["t0"] <= 6.0 for f in p["frames"]
    )
    assert p["frames"]

    # a step-window merge folds both sources into one frame per slot
    p = history.query(d, step=5.0, start=0.0, end=10.0)
    assert len(p["frames"]) == 2
    for f in p["frames"]:
        assert history.wire_float(f["counters"]["records_out"]) == 30.0

    # name projection trims sections and emits plotting series
    p = history.query(d, names=["records_out"], step=1.0)
    for f in p["frames"]:
        assert set(f["counters"]) <= {"records_out"}
        assert not f["gauges"]
    assert "records_out" in p.get("series", {})


def test_query_params_decodes_http_query_strings():
    qargs = history.query_params(
        {
            "name": ["records_out,headroom_frac"],
            "source": ["w0"],
            "start": ["3.0"],
            "end": ["9"],
            "step": ["5"],
        }
    )
    assert qargs["names"] == ["records_out", "headroom_frac"]
    assert qargs["sources"] == ["w0"]
    assert qargs["start"] == 3.0 and qargs["end"] == 9.0
    assert qargs["step"] == 5.0


def test_replay_cli_json_on_a_directory(tmp_path, capsys):
    _populated_store(tmp_path)
    from flink_jpmml_tpu import cli

    rc = cli.replay_main(
        [str(tmp_path), "--step", "1", "--json", "--panel", "none"]
    )
    assert rc == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["frames"]
    assert payload["resolutions"] == [1.0, 5.0]


# ---------------------------------------------------------------------------
# cardinality governor at zoo scale
# ---------------------------------------------------------------------------


def test_governor_bounds_1000_tenants_with_exact_totals():
    zoo = 1000
    m = MetricsRegistry()
    for i in range(zoo):
        # prebuilt name: tests synthesize members of the catalogued
        # tenant_records family, the serving plane owns the literal
        name = 'tenant_records{model="z%04d"}' % i
        m.counter(name).inc(i + 1)
    snap = m.struct_snapshot()
    governed = govern_struct(snap, max_series=8)
    tenant = {
        n: v for n, v in governed["counters"].items()
        if n.startswith("tenant_records{")
    }
    assert len(tenant) == 8
    other = tenant.pop('tenant_records{model="_other"}')
    # the heaviest tenants survive by name; the tail folds exactly
    assert 'tenant_records{model="z0999"}' in tenant
    assert 'tenant_records{model="z0000"}' not in tenant
    assert sum(tenant.values()) + other == zoo * (zoo + 1) / 2
    # the input is never mutated
    assert len(
        [n for n in snap["counters"] if n.startswith("tenant_records{")]
    ) == zoo


def test_govern_frame_matches_struct_governor_exactly():
    zoo = 1000
    counters = {
        'tenant_records{model="z%04d"}' % i: float(i + 1)
        for i in range(zoo)
    }
    frame = _frame("w0", 0.0, 1.0, counters)
    governed = history.govern_frame(frame, max_series=8)
    tenant = {
        n: v for n, v in governed["counters"].items()
        if n.startswith("tenant_records{")
    }
    assert len(tenant) == 8
    assert 'tenant_records{model="_other"}' in tenant
    total = sum(
        (history._dec(v) for v in tenant.values()), Fraction(0)
    )
    assert total == Fraction(zoo * (zoo + 1), 2)
    # ungoverned input frame is untouched
    assert len(frame["counters"]) == zoo
    # governed frames still merge bitwise-deterministically
    a = history.canonical(history.merge_frames([governed, governed]))
    b = history.canonical(
        history.merge_frames([governed, dict(governed)])
    )
    assert a == b


# ---------------------------------------------------------------------------
# bench-trend tripwire
# ---------------------------------------------------------------------------


def _trend(repo, *extra):
    return subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "tools", "bench_trend.py"),
            "--repo", str(repo), *extra,
        ],
        capture_output=True, text=True, timeout=60,
    )


def _write_round(repo, n, value, latency_ms):
    with open(os.path.join(str(repo), f"BENCH_r{n}.json"), "w") as f:
        json.dump(
            {
                "n": n,
                "parsed": {
                    "metric": "gbm_tput", "backend": "tpu",
                    "value": value, "latency_ms": latency_ms,
                },
            },
            f,
        )


def test_bench_trend_tripwire(tmp_path):
    _write_round(tmp_path, 1, 100.0, 5.0)
    _write_round(tmp_path, 2, 104.0, 4.9)
    p = _trend(tmp_path)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "REGRESSED" not in p.stdout

    # latest throughput regresses >10% vs the best prior -> exit 2
    _write_round(tmp_path, 3, 80.0, 4.9)
    p = _trend(tmp_path)
    assert p.returncode == 2, p.stdout + p.stderr
    assert "gbm_tput.value" in p.stdout and "REGRESSED" in p.stdout
    # ...and a wider tolerance forgives the same point
    assert _trend(tmp_path, "--tolerance", "0.5").returncode == 0

    # latency fields trend LOWER-better: a latency spike trips even
    # when throughput recovers
    _write_round(tmp_path, 4, 105.0, 9.0)
    p = _trend(tmp_path, "--metric", "gbm_tput.latency_ms")
    assert p.returncode == 2, p.stdout + p.stderr
    assert "gbm_tput.latency_ms" in p.stdout

    # a cpu-fallback capture is a separate series, never judged
    # against the tpu best
    with open(os.path.join(str(tmp_path), "BENCH_r5.json"), "w") as f:
        json.dump(
            {
                "n": 5,
                "parsed": {
                    "metric": "gbm_tput", "backend": "cpu",
                    "value": 1.0, "latency_ms": 500.0,
                },
            },
            f,
        )
    assert _trend(tmp_path, "--metric", "gbm_tput.value").returncode == 0
