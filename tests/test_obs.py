"""Observability plane (ISSUE 3): mergeable histograms, the flight
recorder, span export, the /metrics exposition endpoint, and the
heartbeat-piggybacked fleet view.

The acceptance drill (slow-marked, like every process-spawning test):
a supervised two-worker run exposes an aggregated Prometheus /metrics
endpoint whose histogram quantiles equal the merge of the individual
worker registries, and SIGKILLing a worker produces a flight-recorder
JSONL dump containing the death event.
"""

import json
import math
import os
import signal
import subprocess
import sys
import textwrap
import time
import urllib.request

import pytest

from flink_jpmml_tpu.obs import recorder, spans
from flink_jpmml_tpu.obs.server import ObsServer, prometheus_text
from flink_jpmml_tpu.utils.metrics import (
    Histogram,
    MetricsRegistry,
    merge_structs,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _wait(pred, timeout_s: float, interval_s: float = 0.02) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval_s)
    return pred()


def _get(url: str):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read().decode()


# ---------------------------------------------------------------------------
# Histogram
# ---------------------------------------------------------------------------


class TestHistogram:
    def test_empty_quantile_is_none(self):
        h = Histogram()
        assert h.quantile(0.5) is None
        assert h.count() == 0

    def test_quantile_bounds(self):
        """quantile(q) is an upper bound on the true nearest-rank
        quantile, within one bucket ratio (10^(1/4) at the default
        4 buckets/decade)."""
        import random

        rng = random.Random(7)
        vals = [rng.uniform(1e-5, 10.0) for _ in range(500)]
        h = Histogram()
        for v in vals:
            h.observe(v)
        s = sorted(vals)
        ratio = 10.0 ** (1.0 / 4.0)
        for q in (0.5, 0.9, 0.99, 0.999):
            true = s[min(len(s) - 1, max(math.ceil(q * len(s)) - 1, 0))]
            got = h.quantile(q)
            assert true <= got <= true * ratio * (1 + 1e-9), (q, true, got)

    def test_max_clamp_and_overflow(self):
        h = Histogram()
        h.observe(5e3)  # above hi: overflow bucket
        assert h.quantile(0.5) == 5e3  # clamped to the observed max
        h2 = Histogram()
        h2.observe(1e-9)  # below lo: absorbed by bucket 0
        assert h2.quantile(0.5) == 1e-9

    def test_merge_associativity(self):
        """(a ⊕ b) ⊕ c == a ⊕ (b ⊕ c) == bucketing of the combined
        stream — the property reservoirs cannot offer."""
        import random

        rng = random.Random(11)
        streams = [
            [rng.uniform(1e-6, 100.0) for _ in range(200)]
            for _ in range(3)
        ]

        def hist(vals):
            h = Histogram()
            for v in vals:
                h.observe(v)
            return h

        c = hist(streams[2])
        left = hist(streams[0]).merge(hist(streams[1])).merge(c)
        bc = hist(streams[1]).merge(hist(streams[2]))
        right = hist(streams[0]).merge(bc)
        combined = hist(streams[0] + streams[1] + streams[2])

        def buckets(h):
            s = h.state()
            return (s["counts"], s["n"], s["max"], s["layout"])

        # bucket counts (what quantiles read) merge EXACTLY in any
        # association; the float `sum` is add-order-sensitive in its
        # last ulp, so it gets an approx check
        assert buckets(left) == buckets(right) == buckets(combined)
        assert left.sum() == pytest.approx(combined.sum())
        for q in (0.5, 0.99, 0.999):
            assert left.quantile(q) == combined.quantile(q)

    def test_merge_layout_mismatch_raises(self):
        with pytest.raises(ValueError):
            Histogram().merge(Histogram(lo=1e-3))

    def test_state_roundtrip(self):
        h = Histogram()
        for v in (0.001, 0.02, 3.0, 5e4):
            h.observe(v)
        h2 = Histogram.from_state(
            json.loads(json.dumps(h.state()))  # through the JSON wire
        )
        assert h2.state() == h.state()
        assert h2.quantile(0.5) == h.quantile(0.5)

    def test_registry_snapshot_has_p999(self):
        m = MetricsRegistry()
        for _ in range(10):
            m.histogram("lat_s").observe(0.01)
        snap = m.snapshot()
        assert "lat_s_p50" in snap
        assert "lat_s_p99" in snap
        assert "lat_s_p999" in snap


class TestMergeStructs:
    def test_counters_gauges_histograms(self):
        a, b = MetricsRegistry(), MetricsRegistry()
        a.counter("records_out").inc(10)
        b.counter("records_out").inc(5)
        b.counter("only_b").inc(1)
        a.gauge("inflight_depth").set(2)
        b.gauge("inflight_depth").set(3)
        a.gauge("inflight_depth").set(1)  # a's max stays 2
        a.histogram("lat_s").observe(0.001)
        b.histogram("lat_s").observe(1.0)
        merged = merge_structs(
            [a.struct_snapshot(), b.struct_snapshot()]
        )
        assert merged["counters"]["records_out"] == 15
        assert merged["counters"]["only_b"] == 1
        # gauge values ADD (fleet total in-flight), maxes take the max
        assert merged["gauges"]["inflight_depth"]["value"] == 4
        assert merged["gauges"]["inflight_depth"]["max"] == 3
        h = Histogram.from_state(merged["histograms"]["lat_s"])
        assert h.count() == 2
        assert h.sum() == pytest.approx(1.001)

    def test_empty_and_none_sources_skipped(self):
        m = MetricsRegistry()
        m.counter("x").inc()
        merged = merge_structs([None, {}, m.struct_snapshot()])
        assert merged["counters"]["x"] == 1

    def test_garbage_snapshots_never_raise(self):
        """One worker with version skew (changed layout, custom
        snapshot_fn shape, plain garbage) must not turn every fleet
        merge — and hence every supervisor /metrics scrape — into an
        exception; bad entries are skipped, good ones survive."""
        good = MetricsRegistry()
        good.counter("records_out").inc(7)
        good.histogram("lat_s").observe(0.01)
        skewed = Histogram(lo=1e-3)  # different layout, same name
        skewed.observe(0.5)
        garbage = [
            "not a dict",
            {"counters": "nope", "gauges": 3, "histograms": ["x"]},
            {"counters": {"records_out": "NaN-ish", "ok": 1},
             "gauges": {"g": {"value": "x"}, "g2": 5},
             "histograms": {"lat_s": {"layout": [1e-3, 1e3, 4]},
                            "broken": {"no": "layout"},
                            "lat2_s": None},
             "uptime_s": "soon"},
            {"histograms": {"lat_s": skewed.state()}},
        ]
        merged = merge_structs(garbage + [good.struct_snapshot()])
        assert merged["counters"]["records_out"] == 7
        assert merged["counters"]["ok"] == 1
        h = Histogram.from_state(merged["histograms"]["lat_s"])
        assert h.count() >= 1  # the unmergeable layout was skipped


# ---------------------------------------------------------------------------
# Prometheus exposition
# ---------------------------------------------------------------------------


class TestPrometheusText:
    def test_golden_render(self):
        """Pinned text-format output: counter, gauge (+_max twin),
        histogram (cumulative buckets + +Inf + sum/count), uptime —
        over a tiny 1-bucket-per-decade layout so the golden is
        readable."""
        m = MetricsRegistry()
        m.counter("records_out").inc(3)
        m.gauge("inflight_depth").set(2)
        h = m.histogram("lat_s", lo=0.01, hi=1.0, buckets_per_decade=1)
        h.observe(0.005)  # bucket 0 (below lo)
        h.observe(0.05)  # bucket 1
        h.observe(7.0)  # overflow
        s = m.struct_snapshot()
        s["uptime_s"] = 12.5  # pin the one nondeterministic field
        got = prometheus_text({None: s})
        expected = (
            "# TYPE fjt_inflight_depth gauge\n"
            "fjt_inflight_depth 2\n"
            "# TYPE fjt_inflight_depth_max gauge\n"
            "fjt_inflight_depth_max 2\n"
            "# TYPE fjt_lat_s histogram\n"
            'fjt_lat_s_bucket{le="0.01"} 1\n'
            'fjt_lat_s_bucket{le="0.1"} 2\n'
            'fjt_lat_s_bucket{le="1"} 2\n'
            'fjt_lat_s_bucket{le="+Inf"} 3\n'
            "fjt_lat_s_sum 7.055\n"
            "fjt_lat_s_count 3\n"
            "# TYPE fjt_records_out counter\n"
            "fjt_records_out 3\n"
            "# TYPE fjt_uptime_s gauge\n"
            "fjt_uptime_s 12.5\n"
        )
        assert got == expected

    def test_exemplar_exposition_golden(self):
        """Pinned OpenMetrics exemplar syntax: the bucket line holding
        an exemplar grows ` # {trace_id="..."} value unix_ts` — the
        link a p99 scrape follows to the flight-recorder event. Only
        the exemplar-bearing bucket carries one; the suffix must ride
        through the struct wire form (state → from_state → render).
        Exemplars are OpenMetrics-only: the classic 0.0.4 render of the
        same struct must stay suffix-free (a stock Prometheus text
        parser rejects a page with them)."""
        m = MetricsRegistry()
        h = m.histogram(
            'stage_seconds{stage="sink"}',
            lo=0.01, hi=1.0, buckets_per_decade=1,
        )
        h.observe(0.05)
        h.observe(0.5, exemplar="abc-1")
        s = m.struct_snapshot()
        s["uptime_s"] = 1.0
        # pin the exemplar's wall-clock stamp (the one nondeterministic
        # field on the line)
        s["histograms"]['stage_seconds{stage="sink"}']["exemplars"]["2"][2] = 99.5
        got = prometheus_text({None: s}, openmetrics=True)
        expected = (
            "# TYPE fjt_stage_seconds histogram\n"
            'fjt_stage_seconds_bucket{stage="sink",le="0.01"} 0\n'
            'fjt_stage_seconds_bucket{stage="sink",le="0.1"} 1\n'
            'fjt_stage_seconds_bucket{stage="sink",le="1"} 2'
            ' # {trace_id="abc-1"} 0.5 99.5\n'
            'fjt_stage_seconds_bucket{stage="sink",le="+Inf"} 2\n'
            'fjt_stage_seconds_sum{stage="sink"} 0.55\n'
            'fjt_stage_seconds_count{stage="sink"} 2\n'
            "# TYPE fjt_uptime_s gauge\n"
            "fjt_uptime_s 1\n"
            "# EOF\n"
        )
        assert got == expected
        classic = prometheus_text({None: s})
        assert "trace_id" not in classic and "# EOF" not in classic
        # classic counters keep their type; OpenMetrics declares them
        # unknown (same sample names — _total would rename the series)
        m2 = MetricsRegistry()
        m2.counter("records_out").inc(3)
        assert "# TYPE fjt_records_out counter" in prometheus_text({None: m2})
        om = prometheus_text({None: m2}, openmetrics=True)
        assert "# TYPE fjt_records_out unknown" in om
        assert "fjt_records_out 3\n" in om and om.endswith("# EOF\n")

    def test_worker_labels_and_unlabeled_aggregate(self):
        agg, w0 = MetricsRegistry(), MetricsRegistry()
        agg.counter("records_out").inc(15)
        w0.counter("records_out").inc(15)
        text = prometheus_text({None: agg, "w0": w0})
        assert "fjt_records_out 15\n" in text
        assert 'fjt_records_out{worker="w0"} 15\n' in text
        # one TYPE line per metric name across all sources
        assert text.count("# TYPE fjt_records_out counter") == 1

    def test_labelled_registry_name_passthrough(self):
        m = MetricsRegistry()
        m.gauge('kafka_lag{partition="3"}').set(42)
        text = prometheus_text({None: m})
        assert 'fjt_kafka_lag{partition="3"} 42\n' in text
        text2 = prometheus_text({"w1": m})
        assert 'fjt_kafka_lag{partition="3",worker="w1"} 42\n' in text2


class TestObsServer:
    def test_endpoints(self):
        m = MetricsRegistry()
        m.counter("records_out").inc(9)
        m.histogram("lat_s").observe(0.01)
        health = {"ok": True}
        srv = ObsServer.for_registry(m, health_fn=lambda: dict(health))
        try:
            status, text = _get(srv.url + "/metrics")
            assert status == 200
            assert "fjt_records_out 9\n" in text
            assert 'fjt_lat_s_bucket{le="+Inf"} 1\n' in text

            status, body = _get(srv.url + "/varz")
            assert status == 200
            varz = json.loads(body)
            assert varz[""]["counters"]["records_out"] == 9

            status, body = _get(srv.url + "/healthz")
            assert status == 200 and json.loads(body)["ok"] is True

            health["ok"] = False
            try:
                _get(srv.url + "/healthz")
                raise AssertionError("expected 503")
            except urllib.error.HTTPError as e:
                assert e.code == 503

            try:
                _get(srv.url + "/nope")
                raise AssertionError("expected 404")
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_ring_bounds_and_order(self):
        r = recorder.FlightRecorder(capacity=4)
        for i in range(10):
            r.record("tick", i=i)
        evs = r.events()
        assert [e["i"] for e in evs] == [6, 7, 8, 9]
        assert [e["seq"] for e in evs] == [7, 8, 9, 10]

    def test_dump_jsonl_with_reason(self, tmp_path, monkeypatch):
        monkeypatch.setenv("FJT_FLIGHT_DIR", str(tmp_path))
        r = recorder.FlightRecorder()
        r.record("kafka_reconnect", topic="t")
        r.record("worker_death", worker="w0", returncode=-9)
        path = r.dump(reason="test")
        assert path is not None and os.path.dirname(path) == str(tmp_path)
        lines = [
            json.loads(ln)
            for ln in open(path, encoding="utf-8")
            if ln.strip()
        ]
        assert lines[0] == {
            "t": lines[0]["t"], "kind": "dump", "reason": "test"
        }
        kinds = [ln["kind"] for ln in lines[1:]]
        assert kinds == ["kafka_reconnect", "worker_death"]
        assert lines[2]["worker"] == "w0"

    def test_dump_prunes_old_files(self, tmp_path, monkeypatch):
        monkeypatch.setenv("FJT_FLIGHT_DIR", str(tmp_path))
        r = recorder.FlightRecorder()
        r.record("e")
        for _ in range(20):
            assert r.dump(reason="spam") is not None
        files = [n for n in os.listdir(tmp_path) if n.startswith("flight-")]
        assert len(files) <= 16

    def test_prune_keeps_newest_by_timestamp_not_filename(
        self, tmp_path, monkeypatch
    ):
        """Lexicographic filename order interleaves pids (999 sorts
        after 1000): across worker restarts that deleted the FRESH
        dumps and kept a stale one forever. The prune key is the
        embedded µs timestamp."""
        monkeypatch.setenv("FJT_FLIGHT_DIR", str(tmp_path))
        stale = tmp_path / "flight-999-1000000.jsonl"  # old, high pid
        stale.write_text("{}\n")
        for i in range(recorder._KEEP_DUMPS + 3):
            (tmp_path / f"flight-1000-{2000000 + i}.jsonl").write_text(
                "{}\n"
            )
        r = recorder.FlightRecorder()
        r.record("e")
        path = r.dump(reason="now")  # timestamped time.time()*1e6: newest
        assert path is not None
        kept = sorted(
            n for n in os.listdir(tmp_path) if n.startswith("flight-")
        )
        assert len(kept) <= recorder._KEEP_DUMPS
        assert "flight-999-1000000.jsonl" not in kept  # stale pruned
        assert os.path.basename(path) in kept  # the new dump survives

    def test_unjsonable_fields_fall_back_to_repr(self, tmp_path):
        r = recorder.FlightRecorder()
        r.record("odd", obj=object())
        path = r.dump(path=str(tmp_path / "d.jsonl"))
        assert path is not None
        assert "odd" in open(path, encoding="utf-8").read()


# ---------------------------------------------------------------------------
# Span export
# ---------------------------------------------------------------------------


class TestSpans:
    def test_disabled_without_env(self, monkeypatch):
        monkeypatch.delenv("FJT_TRACE_DIR", raising=False)
        assert spans.writer() is None
        spans.emit("noop", 0.0, 1.0)  # must be a silent no-op

    def test_emit_writes_perfetto_events(self, tmp_path, monkeypatch):
        monkeypatch.setenv("FJT_TRACE_DIR", str(tmp_path))
        spans.emit("featurize", 1.0, 0.5, n=64)
        w = spans.writer()
        assert w is not None and os.path.dirname(w.path) == str(tmp_path)
        spans.flush()  # the writer buffers now; make the event visible
        raw = open(w.path, encoding="utf-8").read()
        # JSON Array Format, truncated-array tolerant: strip the
        # trailing comma and close it ourselves, like the loaders do
        events = json.loads(raw.rstrip().rstrip(",") + "]")
        ev = events[-1]
        assert ev["name"] == "featurize" and ev["ph"] == "X"
        assert ev["ts"] == 1e6 and ev["dur"] == 5e5
        assert ev["args"] == {"n": 64}

    def test_size_bound_truncates_once(self, tmp_path, monkeypatch):
        monkeypatch.setenv("FJT_TRACE_DIR", str(tmp_path))
        w = spans.SpanWriter(str(tmp_path / "t.trace.json"), max_bytes=400)
        for i in range(100):
            w.emit("s", float(i), 0.001)
        w.close()
        raw = open(w.path, encoding="utf-8").read()
        assert len(raw) < 700  # bounded, not 100 events
        assert raw.count("TRACE TRUNCATED") == 1


# ---------------------------------------------------------------------------
# Heartbeat piggyback (in-process: reporter → coordinator)
# ---------------------------------------------------------------------------


class TestHeartbeatPiggyback:
    def test_snapshots_reach_coordinator(self):
        from flink_jpmml_tpu.parallel.health import (
            HealthCoordinator, HealthReporter,
        )

        reg = MetricsRegistry()
        reg.counter("records_out").inc(123)
        reg.histogram("batch_latency_s").observe(0.02)
        coord = HealthCoordinator(timeout_s=5.0)
        rep = HealthReporter(
            coord.host, coord.port, "w0", interval_s=0.05,
            snapshot_fn=reg.struct_snapshot,
        )
        try:
            assert _wait(
                lambda: "w0" in coord.metrics_snapshots(), 10.0
            ), coord.metrics_snapshots()
            snap = coord.metrics_snapshots()["w0"]
            assert snap["counters"]["records_out"] == 123
            h = Histogram.from_state(snap["histograms"]["batch_latency_s"])
            assert h.count() == 1
            # remove() drops the snapshot with the registration
            coord.remove("w0")
            assert "w0" not in coord.metrics_snapshots()
        finally:
            rep.stop()
            coord.close()

    def test_broken_snapshot_fn_does_not_stop_beats(self):
        from flink_jpmml_tpu.parallel.health import (
            HealthCoordinator, HealthReporter,
        )

        coord = HealthCoordinator(timeout_s=5.0)
        rep = HealthReporter(
            coord.host, coord.port, "w0", interval_s=0.05,
            snapshot_fn=lambda: 1 / 0,
        )
        try:
            assert _wait(lambda: coord.last_seen("w0") is not None, 10.0)
            assert coord.metrics_snapshots() == {}
        finally:
            rep.stop()
            coord.close()


# ---------------------------------------------------------------------------
# Supervisor: death dump (fast) + the two-worker acceptance drill (slow)
# ---------------------------------------------------------------------------


class TestSupervisorDeathDump:
    def test_worker_death_dumps_flight_jsonl(self, tmp_path, monkeypatch):
        """A supervised worker crash writes a postmortem JSONL dump
        whose events include the death (trivial worker: no package
        import, so this stays in the fast tier)."""
        from flink_jpmml_tpu.runtime.supervisor import (
            RestartPolicy, Supervisor, WorkerSpec,
        )

        monkeypatch.setenv("FJT_FLIGHT_DIR", str(tmp_path))
        sup = Supervisor(
            [WorkerSpec("w0", [sys.executable, "-c", "import sys; sys.exit(3)"])],
            policy=RestartPolicy(max_restarts=0),
            heartbeat_timeout_s=None,
        )
        sup.start()
        try:
            assert _wait(
                lambda: any(
                    n.startswith("flight-") for n in os.listdir(tmp_path)
                ),
                15.0,
            ), os.listdir(tmp_path)
            events = []
            for n in sorted(os.listdir(tmp_path)):
                if n.startswith("flight-"):
                    with open(tmp_path / n, encoding="utf-8") as f:
                        events += [json.loads(ln) for ln in f if ln.strip()]
            deaths = [e for e in events if e.get("kind") == "worker_death"]
            assert deaths and deaths[0]["worker"] == "w0"
            assert deaths[0]["returncode"] == 3
            spawns = [e for e in events if e.get("kind") == "worker_spawn"]
            assert spawns and spawns[0]["worker"] == "w0"
        finally:
            sup.stop()


_OBS_WORKER = """
import os, sys, time
sys.path.insert(0, {repo!r})
from flink_jpmml_tpu.runtime.supervisor import reporter_from_env
from flink_jpmml_tpu.utils.metrics import MetricsRegistry

wid = os.environ["FJT_WORKER_ID"]
reg = MetricsRegistry()
reg.counter("records_out").inc(100 if wid == "w0" else 50)
h = reg.histogram("batch_latency_s")
# deliberately disjoint per-worker distributions so the merged
# quantiles differ from either worker's own
vals = (0.0012, 0.012) if wid == "w0" else (0.12, 0.9)
for v in vals:
    for _ in range(50):
        h.observe(v)
rep = reporter_from_env(interval_s=0.05, metrics=reg)
assert rep is not None
time.sleep(300)
"""


@pytest.mark.slow
class TestFleetMetricsDrill:
    def test_two_worker_aggregate_and_death_dump(
        self, tmp_path, monkeypatch
    ):
        """The acceptance drill: aggregated /metrics quantiles == the
        merge of the individual worker registries, and a SIGKILLed
        worker leaves a flight dump containing the death event."""
        from flink_jpmml_tpu.runtime.supervisor import (
            RestartPolicy, Supervisor, WorkerSpec,
        )

        flight_dir = tmp_path / "flight"
        monkeypatch.setenv("FJT_FLIGHT_DIR", str(flight_dir))
        body = textwrap.dedent(_OBS_WORKER.format(repo=REPO))
        sup = Supervisor(
            [
                WorkerSpec("w0", [sys.executable, "-c", body]),
                WorkerSpec("w1", [sys.executable, "-c", body]),
            ],
            policy=RestartPolicy(max_restarts=3, backoff_s=0.05),
            heartbeat_timeout_s=2.0,
            first_beat_timeout_s=60.0,  # worker startup imports jax
        )
        sup.start()
        srv = sup.start_obs_server()
        try:
            assert _wait(
                lambda: set(sup.metrics_snapshots()) == {"w0", "w1"},
                60.0,
            ), sup.metrics_snapshots().keys()

            # heartbeat-piggybacked snapshots reach Supervisor.status()
            st = sup.status()
            assert st["w0"]["metrics"]["counters"]["records_out"] == 100
            assert st["w1"]["metrics"]["counters"]["records_out"] == 50

            # one scrape serves aggregate + per-worker consistently
            status, body_ = _get(srv.url + "/varz")
            assert status == 200
            varz = json.loads(body_)
            assert set(varz) == {"", "w0", "w1"}
            merged_local = merge_structs([varz["w0"], varz["w1"]])
            # the aggregate also folds in the supervisor's own (empty
            # here) registry, whose uptime_s exceeds the young workers'
            # — uptime is nondeterministic either way, so compare
            # everything but it
            agg = dict(varz[""])
            agg.pop("uptime_s", None)
            merged_local.pop("uptime_s", None)
            # capture timestamps: the aggregate's min-of-ts folds in a
            # third (supervisor) snapshot — nondeterministic like uptime
            agg.pop("ts", None)
            merged_local.pop("ts", None)
            assert agg == merged_local

            # the aggregated histogram's quantiles equal the merge of
            # the individual worker registries' histograms — exactly
            h_agg = Histogram.from_state(
                varz[""]["histograms"]["batch_latency_s"]
            )
            h_merge = Histogram.from_state(
                varz["w0"]["histograms"]["batch_latency_s"]
            ).merge(Histogram.from_state(
                varz["w1"]["histograms"]["batch_latency_s"]
            ))
            for q in (0.5, 0.99, 0.999):
                assert h_agg.quantile(q) == h_merge.quantile(q)
            # and the known combined stream pins the estimator: 200
            # obs, p50 = rank-100 value (0.012) ≤ edge < 0.012·10^¼
            assert 0.012 <= h_agg.quantile(0.5) <= 0.012 * 1.7783
            assert 0.9 <= h_agg.quantile(0.999) <= 0.9 * 1.7783

            status, text = _get(srv.url + "/metrics")
            assert status == 200
            assert "fjt_records_out 150\n" in text
            assert 'fjt_records_out{worker="w0"} 100\n' in text
            assert 'fjt_records_out{worker="w1"} 50\n' in text
            assert 'fjt_batch_latency_s_count 200\n' in text

            status, body_ = _get(srv.url + "/healthz")
            assert status == 200 and json.loads(body_)["ok"] is True

            # kill w0: the supervisor's watcher dumps the ring
            pid = sup.status()["w0"]["pid"]
            os.kill(pid, signal.SIGKILL)
            assert _wait(
                lambda: flight_dir.is_dir() and any(
                    n.startswith("flight-")
                    for n in os.listdir(flight_dir)
                ),
                30.0,
            )
            events = []
            for n in sorted(os.listdir(flight_dir)):
                if n.startswith("flight-"):
                    with open(flight_dir / n, encoding="utf-8") as f:
                        events += [
                            json.loads(ln) for ln in f if ln.strip()
                        ]
            deaths = [
                e for e in events
                if e.get("kind") == "worker_death"
                and e.get("worker") == "w0"
            ]
            assert deaths, [e.get("kind") for e in events]
            # the dead worker's LAST snapshot still serves (postmortem)
            assert "w0" in sup.metrics_snapshots()
        finally:
            sup.stop()


# ---------------------------------------------------------------------------
# Freshness plane (ISSUE 7): watermarks, lag forecasting, backpressure
# ---------------------------------------------------------------------------


class _Clock:
    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class TestFreshnessWatermarks:
    def test_partition_watermark_never_regresses(self):
        """Property: under a random out-of-order event-time stream, each
        partition watermark and the low-watermark advance monotonically."""
        import random

        from flink_jpmml_tpu.obs.freshness import FreshnessTracker

        rng = random.Random(7)
        m = MetricsRegistry()
        tr = FreshnessTracker(m)
        seen = {}
        prev_low = None
        base = 1_700_000_000.0
        for i in range(500):
            part = rng.choice(["0", "1", "2"])
            ts = base + rng.uniform(-60.0, 60.0)
            tr.observe_source(part, ts - rng.uniform(0, 5), ts, now=base + 120)
            with tr._mu:
                wm = tr._part_wm[part]
            assert wm >= seen.get(part, wm), "partition watermark regressed"
            assert wm >= ts  # covers this batch
            had_all = len(seen) == 3
            seen[part] = wm
            low = tr.low_watermark()
            assert low == min(seen.values())
            # monotone once the partition set is stable (a NEW partition
            # joining may legitimately lower the min — Flink semantics)
            if had_all:
                assert low >= prev_low, "low-watermark regressed"
            prev_low = low
        assert tr.low_watermark() == min(seen.values())

    def test_stage_watermark_monotone_across_boundaries(self):
        from flink_jpmml_tpu.obs.freshness import FreshnessTracker

        tr = FreshnessTracker(MetricsRegistry())
        assert tr.advance_stage("dispatch", 100.0) == 100.0
        # an out-of-order / replayed batch never regresses the stage
        assert tr.advance_stage("dispatch", 40.0) == 100.0
        assert tr.advance_stage("dispatch", None) == 100.0
        assert tr.advance_stage("dispatch", 130.0) == 130.0
        assert tr.stage_watermark("dispatch") == 130.0
        assert tr.stage_watermark("unknown") is None

    def test_propagate_low_watermark_exports_stage_gauge(self):
        """The hot-path stage propagation is observable: it exports
        watermark_stage_ts{stage=*} (fleet MIN, like watermark_ts) and
        follows the slowest partition in one locked step."""
        from flink_jpmml_tpu.obs.freshness import FreshnessTracker

        m = MetricsRegistry()
        tr = FreshnessTracker(m)
        # no partitions yet: nothing to propagate, no gauge registered
        # (an eager 0.0 would pin the fleet MIN at the epoch)
        assert tr.propagate_low_watermark("dispatch") is None
        assert not any(
            k.startswith("watermark_stage_ts")
            for k in m.struct_snapshot()["gauges"]
        )
        tr.observe_source("0", 90.0, 100.0, now=200.0)
        tr.observe_source("1", 140.0, 150.0, now=200.0)
        assert tr.propagate_low_watermark("dispatch") == 100.0
        g = m.struct_snapshot()["gauges"]
        assert g['watermark_stage_ts{stage="dispatch"}']["value"] == 100.0
        # the slowest partition advances → the stage follows
        tr.observe_source("0", 110.0, 120.0, now=200.0)
        assert tr.propagate_low_watermark("dispatch") == 120.0
        assert tr.stage_watermark("dispatch") == 120.0
        g = m.struct_snapshot()["gauges"]
        assert g['watermark_stage_ts{stage="dispatch"}']["value"] == 120.0
        # a dispatched batch's OWN ingest stamps override the (fresher)
        # fetch-time watermark: backlogged records crossing ring→device
        # must read old, not fresh (review finding, pinned)
        tr.observe_source("0", 900.0, 1000.0, now=1200.0)
        tr.observe_source("1", 900.0, 1000.0, now=1200.0)
        tr.stamp_ingest(0, 32, 140.0, 150.0)  # old backlog at ring head
        assert tr.propagate_low_watermark("dispatch", 0, 32) == 150.0
        g = m.struct_snapshot()["gauges"]
        assert g['watermark_stage_ts{stage="dispatch"}']["value"] == 150.0
        # the stamps were peeked, not consumed: the sink still books them
        tr.observe_sink(0, 32, now=1200.0)
        assert m.histogram("record_staleness_s").count() == 2

    def test_no_event_time_is_ignored(self):
        """timestamp 0 = "no event time" (the native encoder's default):
        no watermark, no gauges, no 1970-staleness."""
        from flink_jpmml_tpu.obs.freshness import FreshnessTracker

        m = MetricsRegistry()
        tr = FreshnessTracker(m)
        tr.observe_source("0", 0.0, 0.0)
        tr.stamp_ingest(0, 64, 0.0, 0.0)
        tr.observe_batch(0.0, 0.0)
        tr.observe_sink(0, 64)
        assert tr.low_watermark() is None
        assert m.histogram("record_staleness_s").count() == 0
        g = m.struct_snapshot()["gauges"]
        assert "watermark_ts" not in g  # lazily registered: idle worker
        # must not pin the fleet MIN merge at 0

    def test_stamp_channel_rechunking_and_staleness(self):
        """Ingest stamps survive the drain re-chunking offsets between
        ingest and sink; staleness books two bounding observations per
        consumed stamp and the sink watermark advances."""
        from flink_jpmml_tpu.obs.freshness import FreshnessTracker

        m = MetricsRegistry()
        tr = FreshnessTracker(m)
        now = 1_700_000_000.0
        tr.stamp_ingest(0, 100, now - 30.0, now - 10.0)
        tr.stamp_ingest(100, 100, now - 8.0, now - 4.0)
        h = m.histogram("record_staleness_s")
        # sink consumes 0..150: all of stamp 1, half of stamp 2
        tr.observe_sink(0, 150, now=now)
        assert h.count() == 4
        assert abs(h.sum() - (30.0 + 10.0 + 8.0 + 4.0)) < 1e-6
        assert tr.stage_watermark("sink") == now - 4.0
        # the remainder of stamp 2 books on the next sink batch
        tr.observe_sink(150, 50, now=now)
        assert h.count() == 6
        assert m.gauge("watermark_ts").get() == now - 4.0

    def test_stamp_bound_drops_oldest(self):
        from flink_jpmml_tpu.obs import freshness

        tr = freshness.FreshnessTracker(MetricsRegistry())
        for i in range(freshness._MAX_STAMPS + 10):
            tr.stamp_ingest(i * 10, 10, 1e9, 1e9 + 1)
        assert len(tr._stamps) == freshness._MAX_STAMPS
        assert tr._stamps_dropped == 10

    def test_reset_stamps_keeps_watermarks(self):
        from flink_jpmml_tpu.obs.freshness import FreshnessTracker

        m = MetricsRegistry()
        tr = FreshnessTracker(m)
        tr.observe_source("0", 50.0, 60.0, now=100.0)
        tr.stamp_ingest(0, 10, 50.0, 60.0)
        tr.reset_stamps()
        tr.observe_sink(0, 10, now=100.0)
        assert m.histogram("record_staleness_s").count() == 0
        assert tr.low_watermark() == 60.0  # event time never regresses

    def test_freshness_for_is_per_registry_singleton(self):
        from flink_jpmml_tpu.obs.freshness import freshness_for

        m1, m2 = MetricsRegistry(), MetricsRegistry()
        assert freshness_for(m1) is freshness_for(m1)
        assert freshness_for(m1) is not freshness_for(m2)
        assert freshness_for(None) is None

    def test_fleet_merge_min_watermark_worst_lag(self):
        """The DrJAX merge-exactly discipline, pinned alongside the PR 6
        worst-of gauge rules: fleet watermark_ts is the MIN of workers
        (freshness = the slowest worker), lag/age/pressure gauges the
        MAX — an average must never hide a straggler."""
        workers = []
        for wm, lag, press in ((1000.0, 4.0, 0.2), (940.0, 9.5, 0.9),
                               (985.0, 0.1, 0.4)):
            m = MetricsRegistry()
            m.gauge("watermark_ts").set(wm)
            m.gauge('watermark_stage_ts{stage="dispatch"}').set(wm + 5)
            m.gauge('watermark_lag_s{partition="0"}').set(lag)
            m.gauge('kafka_lag_age_s{partition="0"}').set(lag / 2)
            m.gauge("lag_drain_eta_s").set(lag * 3)
            m.gauge("lag_diverging").set(1.0 if lag > 5 else 0.0)
            m.gauge("pressure").set(press)
            m.gauge("ring_occupancy").set(press / 2)
            workers.append(m.struct_snapshot())
        g = merge_structs(workers)["gauges"]
        assert g["watermark_ts"]["value"] == 940.0  # MIN of workers
        assert (
            g['watermark_stage_ts{stage="dispatch"}']["value"] == 945.0
        )  # stage watermarks MIN too
        assert g['watermark_lag_s{partition="0"}']["value"] == 9.5
        assert g['kafka_lag_age_s{partition="0"}']["value"] == 4.75
        assert g["lag_drain_eta_s"]["value"] == 28.5
        assert g["lag_diverging"]["value"] == 1.0  # one diverging worker
        assert g["pressure"]["value"] == 0.9  # diverges the fleet
        assert g["ring_occupancy"]["value"] == 0.45

    def test_merge_is_associative_and_order_free(self):
        import itertools

        structs = []
        for wm in (300.0, 100.0, 200.0):
            m = MetricsRegistry()
            m.gauge("watermark_ts").set(wm)
            m.gauge("pressure").set(wm / 1000.0)
            structs.append(m.struct_snapshot())
        outs = [
            (merge_structs(list(p))["gauges"]["watermark_ts"]["value"],
             merge_structs(list(p))["gauges"]["pressure"]["value"])
            for p in itertools.permutations(structs)
        ]
        assert set(outs) == {(100.0, 0.3)}


class TestLagForecaster:
    def _mk(self, clk, **kw):
        from flink_jpmml_tpu.obs.freshness import LagForecaster

        m = MetricsRegistry()
        kw.setdefault("window_s", 10.0)
        kw.setdefault("stale_s", 30.0)
        return m, LagForecaster(m, clock=clk, **kw)

    def test_finite_eta_while_draining(self):
        clk = _Clock()
        m, fc = self._mk(clk)
        fc.observe("0", produced=10_000, consumed=0)
        clk.advance(2.0)
        # 2s later: produced +400 (200/s), consumed +2400 (1200/s),
        # backlog 8000 → ETA = 8000 / 1000 net-drain = 8 s
        fc.observe("0", produced=10_400, consumed=2_400)
        assert m.gauge("lag_drain_eta_s").get() == pytest.approx(8.0)
        assert m.gauge("lag_trend").get() == pytest.approx(-1000.0)
        assert m.gauge("lag_diverging").get() == 0.0

    def test_divergence_flag_and_flight_event(self):
        clk = _Clock()
        m, fc = self._mk(clk)
        before = len([e for e in recorder.events()
                      if e.get("kind") == "lag_divergence"])
        fc.observe("0", produced=10_000, consumed=0)
        clk.advance(2.0)
        fc.observe("0", produced=14_000, consumed=1_000)
        assert m.gauge("lag_diverging").get() == 1.0
        ev = [e for e in recorder.events()
              if e.get("kind") == "lag_divergence"]
        assert len(ev) == before + 1
        assert ev[-1]["lag_records"] == 13_000
        # rate-limited: an immediate second compute does not re-fire
        clk.advance(1.0)
        fc.observe("0", produced=16_000, consumed=1_500)
        assert len([e for e in recorder.events()
                    if e.get("kind") == "lag_divergence"]) == before + 1

    def test_drained_backlog_reads_zero_eta(self):
        clk = _Clock()
        m, fc = self._mk(clk)
        fc.observe("0", produced=5_000, consumed=4_990)
        clk.advance(2.0)
        fc.observe("0", produced=5_200, consumed=5_190)
        # ~a fetch's worth of lag is healthy pipelining, not backlog
        assert m.gauge("lag_drain_eta_s").get() == 0.0
        assert m.gauge("lag_diverging").get() == 0.0

    def test_stalled_partition_age_stamps_and_flags_once(self):
        clk = _Clock()
        m, fc = self._mk(clk, stale_s=5.0)
        before = len([e for e in recorder.events()
                      if e.get("kind") == "kafka_lag_stale"])
        fc.observe("0", produced=100, consumed=100)
        clk.advance(1.5)
        fc.observe("1", produced=100, consumed=100)
        clk.advance(8.5)  # partition 0 last observed 10 s ago
        fc.observe("1", produced=200, consumed=200)
        age = m.gauge('kafka_lag_age_s{partition="0"}').get()
        assert age == pytest.approx(10.0)
        assert fc.stale_partitions() == ("0",)
        stale = [e for e in recorder.events()
                 if e.get("kind") == "kafka_lag_stale"]
        assert len(stale) == before + 1 and stale[-1]["partition"] == "0"
        # still stale: no second event
        clk.advance(2.0)
        fc.observe("1", produced=300, consumed=300)
        assert len([e for e in recorder.events()
                    if e.get("kind") == "kafka_lag_stale"]) == before + 1
        # a fresh observation recovers it (re-stall would re-fire)
        fc.observe("0", produced=400, consumed=400)
        assert fc.stale_partitions() == ()

    def test_disabled_without_registry(self):
        from flink_jpmml_tpu.obs.freshness import LagForecaster

        fc = LagForecaster(None)
        assert not fc.enabled
        fc.observe("0", 100, 0)  # no-op, never raises
        fc.sweep()

    def test_scrape_ages_a_wedged_consumer(self):
        """A wedged consumer (full ring, blocked ingest thread) never
        re-enters the fetch path, so neither observe() nor the
        reconnect-path sweep runs again — the /metrics scrape itself
        must age kafka_lag_age_s and fire the staleness crossing, or
        the staleness detector goes stale in exactly the scenario it
        exists to expose (review finding, pinned)."""
        clk = _Clock()
        m, fc = self._mk(clk, stale_s=5.0)
        fc.observe("0", produced=100, consumed=80)
        snap = m.struct_snapshot()
        assert snap["gauges"]['kafka_lag_age_s{partition="0"}'][
            "value"] == 0.0
        base_stale = len([e for e in recorder.events()
                          if e.get("kind") == "kafka_lag_stale"])
        clk.advance(9.0)  # consumer wedges: no observe, no fetch
        snap = m.struct_snapshot()  # the scrape drives the sweep
        assert snap["gauges"]['kafka_lag_age_s{partition="0"}'][
            "value"] == pytest.approx(9.0)
        assert len([e for e in recorder.events()
                    if e.get("kind") == "kafka_lag_stale"]
                   ) == base_stale + 1
        # a collected forecaster unregisters its weak hook: the scrape
        # must not resurrect or crash on it
        import gc

        del fc
        gc.collect()
        m.struct_snapshot()

    def test_env_window_and_stale_config(self, monkeypatch):
        from flink_jpmml_tpu.obs.freshness import LagForecaster

        monkeypatch.setenv("FJT_LAG_WINDOW_S", "2.5")
        monkeypatch.setenv("FJT_LAG_STALE_S", "7")
        fc = LagForecaster(MetricsRegistry())
        assert fc._window == 2.5 and fc._stale == 7.0
        monkeypatch.setenv("FJT_LAG_WINDOW_S", "garbage")
        monkeypatch.setenv("FJT_LAG_STALE_S", "-3")
        fc = LagForecaster(MetricsRegistry())
        assert fc._window == 10.0 and fc._stale == 30.0  # defaults


class TestPressureMonitor:
    def _mk(self, clk, windows=((2.0, 0.5),)):
        from flink_jpmml_tpu.obs.pressure import PressureMonitor

        m = MetricsRegistry()
        return m, PressureMonitor(m, windows=windows, clock=clk)

    def test_score_is_max_of_components(self):
        from flink_jpmml_tpu.obs import attr

        clk = _Clock()
        m, mon = self._mk(clk)
        mon.tick()  # establish delta baselines
        m.gauge("ring_occupancy").set(0.3)
        m.counter("dispatches").inc(10)
        m.counter("window_full_launches").inc(6)
        clk.advance(1.0)
        out = mon.tick()
        assert out["ring"] == pytest.approx(0.3)
        assert out["window"] == pytest.approx(0.6)
        assert out["wait"] == pytest.approx(0.0)
        assert out["pressure"] == pytest.approx(0.6)
        assert m.gauge("pressure").get() == pytest.approx(0.6)
        # admission wait dominates when the window share is idle: 0.8 s
        # of queue_wait over a 1 s tick = 0.8
        m.histogram(attr.stage_metric_name("queue_wait")).observe(0.8)
        clk.advance(1.0)
        out = mon.tick()
        assert out["wait"] == pytest.approx(0.8)
        assert out["pressure"] == pytest.approx(0.8)

    def test_scrape_ticks_a_wedged_pipeline(self):
        """The batch-completion paths stop calling maybe_tick the
        moment a sink wedges — the /metrics scrape (struct_snapshot)
        must keep the breach tracker evaluating, like the freshness
        detectors' scrape-side aging (review finding, pinned)."""
        clk = _Clock()
        m, mon = self._mk(clk, windows=((2.0, 0.5),))
        m.gauge("ring_occupancy").set(1.0)  # ring filled, then wedge:
        breached = False                    # nobody ticks from batches
        for _ in range(6):
            clk.advance(0.5)
            m.struct_snapshot()  # the scrape drives the tick
            breached = breached or mon.breached
        assert breached
        assert m.gauge("pressure").get() == 1.0

    def test_concurrent_ticks_cannot_interleave_baselines(self):
        """The delta baselines are read-modify-write state shared by
        every submit thread's maybe_tick: two racing ticks interleaving
        `d = get() - base; base += d` advance the baseline past the
        real counter, clamping a genuinely saturated window-full
        fraction to 0 forever (review finding). Pin: a second tick
        parks on the monitor lock BEFORE reading the counters while a
        first tick is mid-update."""
        import threading

        from flink_jpmml_tpu.obs.pressure import PressureMonitor

        m = MetricsRegistry()
        mon = PressureMonitor(m, windows=((60.0, 0.8),))
        real = mon._dispatches
        entered = threading.Event()
        release = threading.Event()
        reads: list = []

        class _SlowCounter:
            def get(self):
                reads.append(threading.current_thread().name)
                entered.set()
                release.wait(5.0)
                return real.get()

        mon._dispatches = _SlowCounter()
        t1 = threading.Thread(target=mon.tick, name="tick-1")
        t1.start()
        assert entered.wait(5.0)
        t2 = threading.Thread(target=mon.tick, name="tick-2")
        t2.start()
        t2.join(0.3)
        # unlocked baselines would let tick-2 straight into get();
        # serialized ticks hold it at the lock with ONE read issued
        assert t2.is_alive()
        assert reads == ["tick-1"]
        release.set()
        t1.join(5.0)
        t2.join(5.0)
        assert not t1.is_alive() and not t2.is_alive()
        assert reads == ["tick-1", "tick-2"]

    def test_breach_and_clear_transitions(self):
        clk = _Clock()
        m, mon = self._mk(clk, windows=((2.0, 0.5),))
        base_b = len([e for e in recorder.events()
                      if e.get("kind") == "pressure_breach"])
        base_c = len([e for e in recorder.events()
                      if e.get("kind") == "pressure_clear"])
        m.gauge("ring_occupancy").set(1.0)
        transitions = []
        for _ in range(6):
            out = mon.tick()
            if out["transition"]:
                transitions.append(out["transition"])
            clk.advance(0.5)
        assert transitions == ["breach"]
        assert mon.breached
        assert m.counter("pressure_breaches").get() == 1
        assert len([e for e in recorder.events()
                    if e.get("kind") == "pressure_breach"]) == base_b + 1
        health = mon.health()["pressure"]
        assert health["ok"] is False and health["score"] == 1.0
        # pressure collapses: the window mean decays below threshold
        m.gauge("ring_occupancy").set(0.0)
        for _ in range(8):
            out = mon.tick()
            if out["transition"]:
                transitions.append(out["transition"])
            clk.advance(0.5)
        assert transitions == ["breach", "clear"]
        assert not mon.breached
        assert len([e for e in recorder.events()
                    if e.get("kind") == "pressure_clear"]) == base_c + 1
        assert mon.health()["pressure"]["ok"] is True

    def test_cold_start_does_not_breach_on_first_tick(self):
        clk = _Clock()
        m, mon = self._mk(clk, windows=((60.0, 0.5),))
        m.gauge("ring_occupancy").set(1.0)
        out = mon.tick()
        assert out["transition"] is None and not out["breached"]

    def test_maybe_tick_rate_limit(self):
        clk = _Clock()
        m, mon = self._mk(clk)
        assert mon.maybe_tick() is not None
        clk.advance(0.1)
        assert mon.maybe_tick() is None  # < interval_s
        clk.advance(0.5)
        assert mon.maybe_tick() is not None

    def test_health_fn_composes(self):
        clk = _Clock()
        m, mon = self._mk(clk)
        fn = mon.health_fn(lambda: {"ok": True, "workers": 2})
        out = fn()
        assert out["ok"] is True and out["workers"] == 2
        assert out["pressure"]["ok"] is True

    def test_env_windows_parsing(self, monkeypatch):
        from flink_jpmml_tpu.obs.pressure import PressureMonitor

        monkeypatch.setenv("FJT_PRESSURE_WINDOWS", "5:0.9,120:0.4")
        mon = PressureMonitor(MetricsRegistry())
        assert mon.windows == ((5.0, 0.9), (120.0, 0.4))
        # garbage entries drop; all-garbage falls back to the default
        monkeypatch.setenv("FJT_PRESSURE_WINDOWS", "bogus,:,-1:0.5,0:2")
        mon = PressureMonitor(MetricsRegistry())
        assert mon.windows == ((10.0, 0.8), (60.0, 0.6))

    def test_pressure_for_is_per_registry_singleton(self):
        from flink_jpmml_tpu.obs.pressure import pressure_for

        m1, m2 = MetricsRegistry(), MetricsRegistry()
        assert pressure_for(m1) is pressure_for(m1)
        assert pressure_for(m1) is not pressure_for(m2)
        assert pressure_for(None) is None

class TestSinkWatermarkCap:
    def test_sink_watermark_capped_by_straggler_partition(self):
        """A stalled partition holding OLD unscored records must hold
        watermark_ts back: the sink watermark is capped by the source
        low-watermark, so 'everything up to watermark_ts was scored'
        stays true — the straggler the fleet MIN merge exists to
        surface, not hide (review finding, pinned)."""
        from flink_jpmml_tpu.obs.freshness import FreshnessTracker

        m = MetricsRegistry()
        tr = FreshnessTracker(m)
        now = 1_700_000_000.0
        # partition 1 stalled 90 s ago; partition 0 is fresh
        tr.observe_source("1", now - 95.0, now - 90.0, now=now)
        tr.observe_source("0", now - 1.0, now - 0.5, now=now)
        tr.stamp_ingest(0, 64, now - 1.0, now - 0.5)
        tr.observe_sink(0, 64, now=now)
        # NOT now-0.5: partition 1's 90 s-old records are unscored
        assert m.gauge("watermark_ts").get() == now - 90.0
        # the offsetless micro-batch path obeys the same cap
        tr.observe_batch(now - 0.4, now - 0.2, now=now, partition="0")
        assert m.gauge("watermark_ts").get() == now - 90.0
        # the straggler catches up: the sink watermark follows the new
        # low-watermark (now partition 0's, advanced by observe_batch)
        tr.observe_source("1", now - 0.3, now - 0.1, now=now)
        tr.stamp_ingest(64, 64, now - 0.3, now - 0.1)
        tr.observe_sink(64, 64, now=now)
        assert m.gauge("watermark_ts").get() == now - 0.2
