"""utils/metrics.py: counters, latency reservoirs, snapshots — the
observability layer every pipeline reports through (SURVEY.md §6)."""

import threading

from flink_jpmml_tpu.utils.metrics import Counter, MetricsRegistry, Reservoir


class TestCounter:
    def test_inc_and_get(self):
        c = Counter()
        c.inc()
        c.inc(2.5)
        assert c.get() == 3.5

    def test_thread_safety(self):
        c = Counter()

        def bump():
            for _ in range(10_000):
                c.inc()

        ts = [threading.Thread(target=bump) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert c.get() == 40_000  # no lost increments


class TestReservoir:
    def test_empty_quantile_is_none(self):
        r = Reservoir()
        assert r.quantile(0.5) is None
        assert r.count() == 0

    def test_quantiles_exact_small(self):
        r = Reservoir()
        for v in (5.0, 1.0, 3.0, 2.0, 4.0):
            r.observe(v)
        assert r.quantile(0.0) == 1.0
        assert r.quantile(0.5) == 3.0
        assert r.quantile(0.99) == 5.0  # clamped to the max sample
        assert r.count() == 5

    def test_ring_keeps_most_recent(self):
        r = Reservoir(capacity=4)
        for v in (100.0, 100.0, 100.0, 100.0):
            r.observe(v)
        # four newer observations fully displace the old regime
        for v in (1.0, 2.0, 3.0, 4.0):
            r.observe(v)
        assert r.count() == 4
        assert r.quantile(0.99) == 4.0  # no 100.0 survivor

    def test_single_observation(self):
        r = Reservoir()
        r.observe(7.5)
        assert r.quantile(0.5) == 7.5
        assert r.quantile(0.99) == 7.5

    def test_nearest_rank_small_samples(self):
        """ceil(q·n)-1 nearest-rank: the old int(q·n) over-indexed small
        samples (the p50 of 2 observations returned their MAX)."""
        r = Reservoir()
        r.observe(1.0)
        r.observe(2.0)
        assert r.quantile(0.5) == 1.0  # median of 2 = the lower one
        assert r.quantile(0.99) == 2.0
        r.observe(3.0)
        assert r.quantile(0.5) == 2.0  # odd n: the true middle
        assert r.quantile(1.0) == 3.0
        assert r.quantile(0.0) == 1.0


class TestRegistry:
    def test_names_are_stable_handles(self):
        m = MetricsRegistry()
        assert m.counter("x") is m.counter("x")
        assert m.reservoir("lat") is m.reservoir("lat")

    def test_snapshot_shape(self):
        m = MetricsRegistry()
        m.counter("records_out").inc(100)
        m.reservoir("lat_s").observe(0.25)
        m.reservoir("lat_s").observe(0.75)
        m.reservoir("empty")  # registered but never observed
        snap = m.snapshot()
        assert snap["records_out"] == 100
        assert snap["records_out_per_s"] > 0
        assert snap["uptime_s"] > 0
        # nearest-rank convention: ceil(q*n)-1 — the p50 of two samples
        # is the LOWER one (int(q*n) over-indexed small samples)
        assert snap["lat_s_p50"] == 0.25
        assert snap["lat_s_p99"] == 0.75
        # unobserved reservoirs contribute no NaN/None keys
        assert not any(k.startswith("empty") for k in snap)
