"""Fault-injection harness (runtime/faults.py) + retry/backoff
(utils/retry.py): the ISSUE 8 acceptance faults drilled through the
REAL code paths —

- **broker death** → the kafka reconnect/backoff path recovers and the
  stream resumes with nothing lost;
- **slow fetch** → the delay lands in the real fetch histogram;
- **checkpoint-write failure** → the retry/backoff path saves anyway
  (and an unrecoverable streak raises loudly);
- plus dispatch delay, worker wedge, the env grammar, and the capped
  full-jitter backoff schedule itself.
"""

import os
import time

import numpy as np
import pytest

from flink_jpmml_tpu.obs import recorder as flight
from flink_jpmml_tpu.runtime import faults
from flink_jpmml_tpu.utils.metrics import MetricsRegistry
from flink_jpmml_tpu.utils.retry import Backoff

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


class TestGrammar:
    def test_parse_spec(self):
        fs = faults.parse_spec(
            "slow_fetch:delay_ms=40:p=0.5,broker_death:after_s=5:for_s=2"
        )
        assert [f.kind for f in fs] == ["slow_fetch", "broker_death"]
        assert fs[0].delay_s == pytest.approx(0.04)
        assert fs[0].p == 0.5
        assert fs[1].after_s == 5.0 and fs[1].for_s == 2.0

    def test_unknown_kind_raises(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            faults.parse_spec("segfault:delay_ms=1")

    def test_bad_param_raises(self):
        with pytest.raises(ValueError, match="bad fault param"):
            faults.parse_spec("slow_fetch:delay_ms")

    def test_install_from_env(self):
        assert faults.install_from_env("worker_wedge:wedge_s=0.01:n=1")
        assert faults.active()
        faults.clear()
        # garbage is skipped loudly, never fatal; nothing installs
        assert not faults.install_from_env("not_a_fault:x=1")
        assert not faults.active()
        assert not faults.install_from_env("")

    def test_count_and_probability_gates(self):
        f = faults.inject("dispatch_delay", delay_ms=0, n=3)
        for _ in range(10):
            faults.fire("dispatch")
        assert f.fires == 3
        # p=0 never fires regardless of the count budget
        faults.clear()
        f2 = faults.inject("dispatch_delay", delay_ms=0, p=0.0)
        for _ in range(50):
            faults.fire("dispatch")
        assert f2.fires == 0

    def test_seeded_probability_is_deterministic(self):
        def run():
            faults.clear()
            f = faults.inject("dispatch_delay", delay_ms=0, p=0.5, seed=7)
            pattern = []
            for _ in range(32):
                before = f.fires
                faults.fire("dispatch")
                pattern.append(f.fires > before)
            return pattern

        assert run() == run()


class TestBackoff:
    def test_full_jitter_schedule(self):
        # rng pinned at 1.0 exposes the ceiling sequence
        b = Backoff("t", base_s=0.1, cap_s=1.0, max_attempts=10,
                    rng=lambda: 1.0, sleep=lambda s: None)
        delays = [b.next_delay() for _ in range(6)]
        assert delays == pytest.approx([0.1, 0.2, 0.4, 0.8, 1.0, 1.0])
        # jitter draws UNDER the ceiling
        b2 = Backoff("t", base_s=0.1, cap_s=1.0, rng=lambda: 0.25,
                     sleep=lambda s: None)
        assert b2.next_delay() == pytest.approx(0.025)

    def test_reset_rearms_schedule_and_gauge(self):
        m = MetricsRegistry()
        b = Backoff("t", base_s=0.1, cap_s=1.0, metrics=m,
                    rng=lambda: 1.0, sleep=lambda s: None)
        b.next_delay()
        b.next_delay()
        assert m.snapshot()["reconnect_backoff_s"] == pytest.approx(0.2)
        b.reset()
        assert b.attempts == 0
        assert m.snapshot()["reconnect_backoff_s"] == 0.0
        assert b.next_delay() == pytest.approx(0.1)  # schedule restarted

    def test_give_up_event_once_per_streak(self):
        m = MetricsRegistry()
        b = Backoff("drill", base_s=0.001, max_attempts=3, metrics=m,
                    sleep=lambda s: None)
        for _ in range(6):
            b.sleep()
        assert b.exhausted
        give_ups = [
            e for e in flight.events() if e["kind"] == "retry_give_up"
            and e.get("what") == "drill"
        ]
        assert len(give_ups) == 1  # once per streak, not per retry
        assert m.snapshot()["retry_give_ups"] == 1.0

    def test_env_overrides(self, monkeypatch):
        monkeypatch.setenv("FJT_RETRY_BASE_S", "0.2")
        monkeypatch.setenv("FJT_RETRY_CAP_S", "0.5")
        monkeypatch.setenv("FJT_RETRY_MAX", "2")
        b = Backoff("t", base_s=0.01, cap_s=9.0, max_attempts=99)
        assert b.base_s == 0.2 and b.cap_s == 0.5 and b.max_attempts == 2


def _broker_and_source(metrics=None, rows=512):
    from flink_jpmml_tpu.runtime.kafka import (
        KafkaBlockSource, MiniKafkaBroker,
    )

    broker = MiniKafkaBroker(topic="faults")
    data = np.arange(rows * 4, dtype=np.float32).reshape(rows, 4)
    broker.append_rows(data)
    src = KafkaBlockSource(
        broker.host, broker.port, "faults", n_cols=4,
        max_wait_ms=10, reconnect_backoff_s=0.002, metrics=metrics,
        # small fetches: the stream must OUTLIVE the injected fault so
        # recovery has something left to resume
        max_bytes=2048,
    )
    return broker, src, data


class TestKafkaFaultDrills:
    def test_broker_death_recovers_through_backoff(self):
        """ISSUE 8 acceptance fault #1: injected broker death rides the
        real reconnect path — polls fail while the fault is active, the
        backoff streak grows, and when the 'broker' heals the stream
        resumes exactly where it left off (nothing lost, nothing
        duplicated)."""
        m = MetricsRegistry()
        broker, src, data = _broker_and_source(metrics=m)
        try:
            got = src.poll()
            assert got is not None and got[0] == 0
            consumed = got[1].shape[0]
            faults.inject("broker_death", n=4)
            dead_polls = 0
            while faults.stats().get("broker_death", 0) < 4:
                assert src.poll() is None  # the reconnect path, looping
                dead_polls += 1
                assert dead_polls < 50
            # the streak is visible while the broker is down...
            assert m.snapshot()["reconnect_backoff_s"] > 0.0
            reconnects = [
                e for e in flight.events()
                if e["kind"] == "kafka_reconnect"
            ]
            assert len(reconnects) >= 4
            assert reconnects[-1]["attempt"] >= 2  # a growing streak
            # ...and the fault budget exhausted = the broker healed
            healed = None
            for _ in range(50):
                healed = src.poll()
                if healed is not None:
                    break
            assert healed is not None
            assert healed[0] == consumed  # resume AT the cursor
            assert m.snapshot()["reconnect_backoff_s"] == 0.0  # reset
        finally:
            src.close()
            broker.close()

    def test_slow_fetch_lands_in_fetch_histogram(self):
        """ISSUE 8 acceptance fault #2: the injected delay is measured
        by the SAME kafka_fetch_s histogram a real slow broker would
        feed — the telemetry plane sees the fault, not a synthetic."""
        m = MetricsRegistry()
        broker, src, _ = _broker_and_source(metrics=m)
        try:
            faults.inject("slow_fetch", delay_ms=60, n=2)
            polls = 0
            while faults.stats().get("slow_fetch", 0) < 2 and polls < 50:
                src.poll()
                polls += 1
            h = m.histogram("kafka_fetch_s")
            state = h.state()
            assert state["max"] >= 0.06, state
        finally:
            src.close()
            broker.close()


class TestDispatchAndWedge:
    def test_dispatch_delay_injected_at_launch(self):
        from flink_jpmml_tpu.runtime.pipeline import OverlappedDispatcher

        class _Leaf:
            def block_until_ready(self):
                pass

        disp = OverlappedDispatcher(depth=1)
        faults.inject("dispatch_delay", delay_ms=40, n=1)
        t0 = time.monotonic()
        disp.launch(lambda: _Leaf())
        dt = time.monotonic() - t0
        disp.close()
        assert dt >= 0.04

    def test_worker_wedge_stalls_the_score_loop(self):
        """The wedge fires in the real block score loop: a wedged run
        takes visibly longer than a clean one over the same stream but
        still drains completely (the supervisor's wedge-kill plane is
        what would reap a longer one)."""
        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.pmml import parse_pmml
        from flink_jpmml_tpu.runtime.block import (
            BlockPipeline, FiniteBlockSource,
        )
        from tests.test_overload import _CONST_XML

        cm = compile_pmml(parse_pmml(_CONST_XML.format(c=1.0)),
                          batch_size=32)
        data = np.zeros((128, 1), np.float32)

        def run():
            sunk = [0]
            pipe = BlockPipeline(
                FiniteBlockSource(data, block_size=32), cm,
                lambda out, n, off: sunk.__setitem__(0, sunk[0] + n),
                in_flight=2, use_native=False,
            )
            t0 = time.monotonic()
            pipe.run_until_exhausted(timeout=60.0)
            return time.monotonic() - t0, sunk[0]

        clean_dt, clean_n = run()
        faults.inject("worker_wedge", wedge_s=0.4, n=1)
        wedged_dt, wedged_n = run()
        assert clean_n == wedged_n == 128  # the stream still drains
        # the wedge sleep sits on the score thread's critical path; the
        # bound is the wedge itself — a clean-vs-wedged comparison
        # would flake whenever the (first, cold) clean run pays more
        # than 0.4 s of compile/scheduling noise
        assert wedged_dt >= 0.35


class TestCheckpointFaultDrill:
    def test_transient_failures_retry_then_succeed(self, tmp_path,
                                                   monkeypatch):
        """ISSUE 8 acceptance fault #3: two injected mid-write failures
        ride the retry/backoff path and the snapshot still lands."""
        monkeypatch.setenv("FJT_RETRY_BASE_S", "0.001")
        from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager

        faults.inject("checkpoint_fail", n=2)
        mgr = CheckpointManager(str(tmp_path))
        mgr.save({"source_offset": 11})
        assert mgr.load_latest() == {"source_offset": 11}
        retries = [
            e for e in flight.events()
            if e["kind"] == "checkpoint_save_retry"
        ]
        assert len(retries) >= 2
        saves = [
            e for e in flight.events() if e["kind"] == "checkpoint_save"
        ]
        assert saves and saves[-1]["retries"] == 2

    def test_persistent_failure_exhausts_and_raises(self, tmp_path,
                                                    monkeypatch):
        monkeypatch.setenv("FJT_RETRY_BASE_S", "0.001")
        monkeypatch.setenv("FJT_RETRY_MAX", "3")
        from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager
        from flink_jpmml_tpu.utils.exceptions import CheckpointException

        faults.inject("checkpoint_fail")  # no budget: never heals
        mgr = CheckpointManager(str(tmp_path))
        with pytest.raises(CheckpointException, match="after 3 retries"):
            mgr.save({"source_offset": 1})
        assert any(
            e["kind"] == "checkpoint_save_failed"
            for e in flight.events()
        )
        assert not list(tmp_path.glob("ckpt-*.json"))


class TestPoisonAndCrashKinds:
    """ISSUE 12: the delivery-correctness chaos primitives."""

    def test_poison_record_offset_targeting(self):
        import numpy as np

        f = faults.inject("poison_record", offset=5)
        with pytest.raises(faults.InjectedPoisonRecord) as ei:
            faults.fire("score_batch", offsets=np.arange(3, 8))
        assert ei.value.offsets == (5,)
        faults.fire("score_batch", offsets=np.arange(10, 20))  # no hit
        assert f.fires == 1
        # an offset-less call at the site never fires a targeted fault
        faults.fire("score_batch")
        assert f.fires == 1

    def test_poison_record_every_targeting(self):
        faults.inject("poison_record", every=4)
        with pytest.raises(faults.InjectedPoisonRecord) as ei:
            faults.fire("score_batch", offsets=[1, 2, 3, 8, 12])
        assert ei.value.offsets == (8, 12)

    def test_poison_record_needs_targeting(self):
        with pytest.raises(ValueError, match="offset= or every="):
            faults.inject("poison_record", p=1.0)

    def test_worker_crash_site_selection(self):
        fs = faults.parse_spec(
            "worker_crash:site=kafka_fetch:n=1,worker_crash:n=1"
        )
        assert [f.site for f in fs] == ["kafka_fetch", "score_loop"]
        with pytest.raises(ValueError, match="unknown fault site"):
            faults.parse_spec("worker_crash:site=bogus")
        with pytest.raises(ValueError, match="only meaningful"):
            faults.parse_spec("slow_fetch:site=dispatch")

    def test_worker_crash_sigkills_subprocess(self):
        # jax-free child: the kill primitive itself is cheap to pin
        import subprocess
        import sys

        proc = subprocess.run(
            [sys.executable, "-c", (
                "import os\n"
                "os.environ['FJT_FAULTS'] = "
                "'worker_crash:site=dispatch:n=1'\n"
                "import sys\n"
                f"sys.path.insert(0, {REPO!r})\n"
                "from flink_jpmml_tpu.runtime import faults\n"
                "faults.fire('dispatch')\n"
                "print('survived')\n"
            )],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == -9
        assert "survived" not in proc.stdout


_REPLAY_WORKER = r"""
import glob, os, sys
sys.path.insert(0, sys.argv[2])
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.pmml import parse_pmml_file
from flink_jpmml_tpu.runtime.block import BlockPipeline, FiniteBlockSource
from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager
from flink_jpmml_tpu.utils.config import BatchConfig, RuntimeConfig

tmp = sys.argv[1]
pmml = glob.glob(os.path.join(tmp, "*.pmml"))[0]
cm = compile_pmml(parse_pmml_file(pmml), batch_size=32)
rng = np.random.default_rng(0)
N = 256
data = rng.normal(0, 1, size=(N, 4)).astype(np.float32)
out = open(os.path.join(tmp, "sink.log"), "a", buffering=1)

def sink(o, n, first_off):
    out.write(f"E {first_off} {n}\n")

pipe = BlockPipeline(
    FiniteBlockSource(data, 64), cm, sink,
    RuntimeConfig(
        batch=BatchConfig(size=32, deadline_us=1000),
        checkpoint_interval_s=0.01,
    ),
    checkpoint=CheckpointManager(os.path.join(tmp, "ck")),
    max_dispatch_chunks=1,
)
pipe.restore()
out.write(f"R {pipe.committed_offset}\n")
pipe.run_until_exhausted(timeout=60)
out.write(f"D {pipe.committed_offset}\n")
"""


class TestMidBatchKillReplayBoundary:
    pytestmark = pytest.mark.slow  # two jax subprocesses

    def test_suffix_replays_exactly_once_per_restart(self, tmp_path):
        """ISSUE 12 satellite (process-kill half; the deterministic
        in-process half is in tests/test_runtime.py): SIGKILL landing
        BETWEEN dispatch and offset commit — incarnation 1 dies the
        instant offset 130's batch reaches the score_batch hook, after
        earlier batches committed — and the restart replays the
        uncommitted suffix exactly once, skipping nothing."""
        import os
        import subprocess
        import sys

        import numpy as np

        from flink_jpmml_tpu.assets_gen import gen_gbm

        gen_gbm(str(tmp_path), n_trees=3, depth=3, n_features=4)
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["FJT_XLA_CACHE"] = str(tmp_path / "xla")
        env.pop("FJT_RESTART_STREAK", None)
        # incarnation 1: die mid-batch (after drain+dispatch of the
        # batch holding offset 130, before its commit)
        env1 = dict(env)
        env1["FJT_FAULTS"] = "worker_crash:site=score_batch:offset=130"
        p1 = subprocess.run(
            [sys.executable, "-c", _REPLAY_WORKER,
             str(tmp_path), REPO],
            env=env1, capture_output=True, text=True, timeout=120,
        )
        assert p1.returncode == -9, p1.stderr[-2000:]
        # incarnation 2: clean resume
        env2 = dict(env)
        env2.pop("FJT_FAULTS", None)
        p2 = subprocess.run(
            [sys.executable, "-c", _REPLAY_WORKER,
             str(tmp_path), REPO],
            env=env2, capture_output=True, text=True, timeout=120,
        )
        assert p2.returncode == 0, p2.stderr[-2000:]

        emitted, restores = [], []
        for ln in open(tmp_path / "sink.log"):
            kind, *rest = ln.split()
            if kind == "E":
                emitted.append((int(rest[0]), int(rest[1])))
            elif kind == "R":
                restores.append(int(rest[0]))
        assert restores[0] == 0 and len(restores) == 2
        c = restores[1]  # the kill landed between c's commit and 130
        assert 0 < c <= 130
        covered = np.zeros(256, np.int64)
        for off, n in emitted:
            covered[off: off + n] += 1
        assert (covered >= 1).all(), "a record was skipped"
        # below the restore point: exactly once; the uncommitted
        # suffix: at most once per incarnation (== exactly once per
        # restart); nothing ever thrice
        assert (covered[:c] == 1).all()
        assert (covered <= 2).all()
