"""Fault-injection harness (runtime/faults.py) + retry/backoff
(utils/retry.py): the ISSUE 8 acceptance faults drilled through the
REAL code paths —

- **broker death** → the kafka reconnect/backoff path recovers and the
  stream resumes with nothing lost;
- **slow fetch** → the delay lands in the real fetch histogram;
- **checkpoint-write failure** → the retry/backoff path saves anyway
  (and an unrecoverable streak raises loudly);
- plus dispatch delay, worker wedge, the env grammar, and the capped
  full-jitter backoff schedule itself.
"""

import time

import numpy as np
import pytest

from flink_jpmml_tpu.obs import recorder as flight
from flink_jpmml_tpu.runtime import faults
from flink_jpmml_tpu.utils.metrics import MetricsRegistry
from flink_jpmml_tpu.utils.retry import Backoff


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


class TestGrammar:
    def test_parse_spec(self):
        fs = faults.parse_spec(
            "slow_fetch:delay_ms=40:p=0.5,broker_death:after_s=5:for_s=2"
        )
        assert [f.kind for f in fs] == ["slow_fetch", "broker_death"]
        assert fs[0].delay_s == pytest.approx(0.04)
        assert fs[0].p == 0.5
        assert fs[1].after_s == 5.0 and fs[1].for_s == 2.0

    def test_unknown_kind_raises(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            faults.parse_spec("segfault:delay_ms=1")

    def test_bad_param_raises(self):
        with pytest.raises(ValueError, match="bad fault param"):
            faults.parse_spec("slow_fetch:delay_ms")

    def test_install_from_env(self):
        assert faults.install_from_env("worker_wedge:wedge_s=0.01:n=1")
        assert faults.active()
        faults.clear()
        # garbage is skipped loudly, never fatal; nothing installs
        assert not faults.install_from_env("not_a_fault:x=1")
        assert not faults.active()
        assert not faults.install_from_env("")

    def test_count_and_probability_gates(self):
        f = faults.inject("dispatch_delay", delay_ms=0, n=3)
        for _ in range(10):
            faults.fire("dispatch")
        assert f.fires == 3
        # p=0 never fires regardless of the count budget
        faults.clear()
        f2 = faults.inject("dispatch_delay", delay_ms=0, p=0.0)
        for _ in range(50):
            faults.fire("dispatch")
        assert f2.fires == 0

    def test_seeded_probability_is_deterministic(self):
        def run():
            faults.clear()
            f = faults.inject("dispatch_delay", delay_ms=0, p=0.5, seed=7)
            pattern = []
            for _ in range(32):
                before = f.fires
                faults.fire("dispatch")
                pattern.append(f.fires > before)
            return pattern

        assert run() == run()


class TestBackoff:
    def test_full_jitter_schedule(self):
        # rng pinned at 1.0 exposes the ceiling sequence
        b = Backoff("t", base_s=0.1, cap_s=1.0, max_attempts=10,
                    rng=lambda: 1.0, sleep=lambda s: None)
        delays = [b.next_delay() for _ in range(6)]
        assert delays == pytest.approx([0.1, 0.2, 0.4, 0.8, 1.0, 1.0])
        # jitter draws UNDER the ceiling
        b2 = Backoff("t", base_s=0.1, cap_s=1.0, rng=lambda: 0.25,
                     sleep=lambda s: None)
        assert b2.next_delay() == pytest.approx(0.025)

    def test_reset_rearms_schedule_and_gauge(self):
        m = MetricsRegistry()
        b = Backoff("t", base_s=0.1, cap_s=1.0, metrics=m,
                    rng=lambda: 1.0, sleep=lambda s: None)
        b.next_delay()
        b.next_delay()
        assert m.snapshot()["reconnect_backoff_s"] == pytest.approx(0.2)
        b.reset()
        assert b.attempts == 0
        assert m.snapshot()["reconnect_backoff_s"] == 0.0
        assert b.next_delay() == pytest.approx(0.1)  # schedule restarted

    def test_give_up_event_once_per_streak(self):
        m = MetricsRegistry()
        b = Backoff("drill", base_s=0.001, max_attempts=3, metrics=m,
                    sleep=lambda s: None)
        for _ in range(6):
            b.sleep()
        assert b.exhausted
        give_ups = [
            e for e in flight.events() if e["kind"] == "retry_give_up"
            and e.get("what") == "drill"
        ]
        assert len(give_ups) == 1  # once per streak, not per retry
        assert m.snapshot()["retry_give_ups"] == 1.0

    def test_env_overrides(self, monkeypatch):
        monkeypatch.setenv("FJT_RETRY_BASE_S", "0.2")
        monkeypatch.setenv("FJT_RETRY_CAP_S", "0.5")
        monkeypatch.setenv("FJT_RETRY_MAX", "2")
        b = Backoff("t", base_s=0.01, cap_s=9.0, max_attempts=99)
        assert b.base_s == 0.2 and b.cap_s == 0.5 and b.max_attempts == 2


def _broker_and_source(metrics=None, rows=512):
    from flink_jpmml_tpu.runtime.kafka import (
        KafkaBlockSource, MiniKafkaBroker,
    )

    broker = MiniKafkaBroker(topic="faults")
    data = np.arange(rows * 4, dtype=np.float32).reshape(rows, 4)
    broker.append_rows(data)
    src = KafkaBlockSource(
        broker.host, broker.port, "faults", n_cols=4,
        max_wait_ms=10, reconnect_backoff_s=0.002, metrics=metrics,
        # small fetches: the stream must OUTLIVE the injected fault so
        # recovery has something left to resume
        max_bytes=2048,
    )
    return broker, src, data


class TestKafkaFaultDrills:
    def test_broker_death_recovers_through_backoff(self):
        """ISSUE 8 acceptance fault #1: injected broker death rides the
        real reconnect path — polls fail while the fault is active, the
        backoff streak grows, and when the 'broker' heals the stream
        resumes exactly where it left off (nothing lost, nothing
        duplicated)."""
        m = MetricsRegistry()
        broker, src, data = _broker_and_source(metrics=m)
        try:
            got = src.poll()
            assert got is not None and got[0] == 0
            consumed = got[1].shape[0]
            faults.inject("broker_death", n=4)
            dead_polls = 0
            while faults.stats().get("broker_death", 0) < 4:
                assert src.poll() is None  # the reconnect path, looping
                dead_polls += 1
                assert dead_polls < 50
            # the streak is visible while the broker is down...
            assert m.snapshot()["reconnect_backoff_s"] > 0.0
            reconnects = [
                e for e in flight.events()
                if e["kind"] == "kafka_reconnect"
            ]
            assert len(reconnects) >= 4
            assert reconnects[-1]["attempt"] >= 2  # a growing streak
            # ...and the fault budget exhausted = the broker healed
            healed = None
            for _ in range(50):
                healed = src.poll()
                if healed is not None:
                    break
            assert healed is not None
            assert healed[0] == consumed  # resume AT the cursor
            assert m.snapshot()["reconnect_backoff_s"] == 0.0  # reset
        finally:
            src.close()
            broker.close()

    def test_slow_fetch_lands_in_fetch_histogram(self):
        """ISSUE 8 acceptance fault #2: the injected delay is measured
        by the SAME kafka_fetch_s histogram a real slow broker would
        feed — the telemetry plane sees the fault, not a synthetic."""
        m = MetricsRegistry()
        broker, src, _ = _broker_and_source(metrics=m)
        try:
            faults.inject("slow_fetch", delay_ms=60, n=2)
            polls = 0
            while faults.stats().get("slow_fetch", 0) < 2 and polls < 50:
                src.poll()
                polls += 1
            h = m.histogram("kafka_fetch_s")
            state = h.state()
            assert state["max"] >= 0.06, state
        finally:
            src.close()
            broker.close()


class TestDispatchAndWedge:
    def test_dispatch_delay_injected_at_launch(self):
        from flink_jpmml_tpu.runtime.pipeline import OverlappedDispatcher

        class _Leaf:
            def block_until_ready(self):
                pass

        disp = OverlappedDispatcher(depth=1)
        faults.inject("dispatch_delay", delay_ms=40, n=1)
        t0 = time.monotonic()
        disp.launch(lambda: _Leaf())
        dt = time.monotonic() - t0
        disp.close()
        assert dt >= 0.04

    def test_worker_wedge_stalls_the_score_loop(self):
        """The wedge fires in the real block score loop: a wedged run
        takes visibly longer than a clean one over the same stream but
        still drains completely (the supervisor's wedge-kill plane is
        what would reap a longer one)."""
        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.pmml import parse_pmml
        from flink_jpmml_tpu.runtime.block import (
            BlockPipeline, FiniteBlockSource,
        )
        from tests.test_overload import _CONST_XML

        cm = compile_pmml(parse_pmml(_CONST_XML.format(c=1.0)),
                          batch_size=32)
        data = np.zeros((128, 1), np.float32)

        def run():
            sunk = [0]
            pipe = BlockPipeline(
                FiniteBlockSource(data, block_size=32), cm,
                lambda out, n, off: sunk.__setitem__(0, sunk[0] + n),
                in_flight=2, use_native=False,
            )
            t0 = time.monotonic()
            pipe.run_until_exhausted(timeout=60.0)
            return time.monotonic() - t0, sunk[0]

        clean_dt, clean_n = run()
        faults.inject("worker_wedge", wedge_s=0.4, n=1)
        wedged_dt, wedged_n = run()
        assert clean_n == wedged_n == 128  # the stream still drains
        # the wedge sleep sits on the score thread's critical path; the
        # bound is the wedge itself — a clean-vs-wedged comparison
        # would flake whenever the (first, cold) clean run pays more
        # than 0.4 s of compile/scheduling noise
        assert wedged_dt >= 0.35


class TestCheckpointFaultDrill:
    def test_transient_failures_retry_then_succeed(self, tmp_path,
                                                   monkeypatch):
        """ISSUE 8 acceptance fault #3: two injected mid-write failures
        ride the retry/backoff path and the snapshot still lands."""
        monkeypatch.setenv("FJT_RETRY_BASE_S", "0.001")
        from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager

        faults.inject("checkpoint_fail", n=2)
        mgr = CheckpointManager(str(tmp_path))
        mgr.save({"source_offset": 11})
        assert mgr.load_latest() == {"source_offset": 11}
        retries = [
            e for e in flight.events()
            if e["kind"] == "checkpoint_save_retry"
        ]
        assert len(retries) >= 2
        saves = [
            e for e in flight.events() if e["kind"] == "checkpoint_save"
        ]
        assert saves and saves[-1]["retries"] == 2

    def test_persistent_failure_exhausts_and_raises(self, tmp_path,
                                                    monkeypatch):
        monkeypatch.setenv("FJT_RETRY_BASE_S", "0.001")
        monkeypatch.setenv("FJT_RETRY_MAX", "3")
        from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager
        from flink_jpmml_tpu.utils.exceptions import CheckpointException

        faults.inject("checkpoint_fail")  # no budget: never heals
        mgr = CheckpointManager(str(tmp_path))
        with pytest.raises(CheckpointException, match="after 3 retries"):
            mgr.save({"source_offset": 1})
        assert any(
            e["kind"] == "checkpoint_save_failed"
            for e in flight.events()
        )
        assert not list(tmp_path.glob("ckpt-*.json"))
