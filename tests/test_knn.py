"""NearestNeighborModel family: top-k selection, voting/averaging
methods, inline training tables — compiled vs oracle vs hand-computed."""

import math

import numpy as np
import pytest

from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.pmml import parse_pmml
from flink_jpmml_tpu.pmml.interp import evaluate

ROWS = [
    # (u, v, cls, yval)
    (0.0, 0.0, "a", 1.0),
    (1.0, 0.0, "a", 2.0),
    (0.0, 1.0, "b", 3.0),
    (1.0, 1.0, "b", 4.0),
    (2.0, 2.0, "c", 10.0),
    (2.5, 2.5, "c", 12.0),
]


def _knn_xml(function="classification", k=3, attrs="", target="cls",
             measure='<ComparisonMeasure kind="distance">'
                     "<squaredEuclidean/></ComparisonMeasure>"):
    rows = "".join(
        f"<row><u>{u}</u><v>{v}</v><cls>{c}</cls><yv>{y}</yv></row>"
        for u, v, c, y in ROWS
    )
    return f"""<PMML version="4.3"><DataDictionary>
      <DataField name="u" optype="continuous" dataType="double"/>
      <DataField name="v" optype="continuous" dataType="double"/>
      <DataField name="cls" optype="categorical" dataType="string">
        <Value value="a"/><Value value="b"/><Value value="c"/></DataField>
      <DataField name="yv" optype="continuous" dataType="double"/>
      </DataDictionary>
      <NearestNeighborModel functionName="{function}"
          numberOfNeighbors="{k}" {attrs}>
      <MiningSchema><MiningField name="{target}" usageType="target"/>
        <MiningField name="u"/><MiningField name="v"/></MiningSchema>
      {measure}
      <KNNInputs><KNNInput field="u"/><KNNInput field="v"/></KNNInputs>
      <TrainingInstances>
        <InstanceFields>
          <InstanceField field="u" column="u"/>
          <InstanceField field="v" column="v"/>
          <InstanceField field="{target}" column="{target if target == 'cls' else 'yv'}"/>
        </InstanceFields>
        <InlineTable>{rows}</InlineTable>
      </TrainingInstances>
      </NearestNeighborModel></PMML>"""


def _parity(doc, n=150, seed=0, spread=1.5):
    cm = compile_pmml(doc)
    rng = np.random.default_rng(seed)
    recs = [
        {"u": float(a), "v": float(b)}
        for a, b in rng.normal(1.0, spread, size=(n, 2))
    ]
    for rec, p in zip(recs, cm.score_records(recs)):
        o = evaluate(doc, rec)
        assert not p.is_empty and not o.is_missing
        if o.label is not None:
            assert p.target.label == o.label, rec
        assert p.score.value == pytest.approx(o.value, rel=1e-4,
                                              abs=1e-6), rec
    return cm


class TestKnn:
    def test_majority_vote_hand_case(self):
        doc = parse_pmml(_knn_xml())
        _parity(doc)
        # query (0.1, 0.1): 3 nearest are rows 0 (a), 1 (a), 2 (b) → a
        o = evaluate(doc, {"u": 0.1, "v": 0.1})
        assert o.label == "a"
        assert o.probabilities["a"] == pytest.approx(2 / 3)

    def test_weighted_majority_vote(self):
        doc = parse_pmml(_knn_xml(
            attrs='categoricalScoringMethod="weightedMajorityVote"'
        ))
        _parity(doc)
        # query very near row 2 (b): its 1/d vote dominates two a's
        o = evaluate(doc, {"u": 0.05, "v": 0.95})
        assert o.label == "b"

    def test_regression_average_and_weighted(self):
        doc = parse_pmml(_knn_xml(function="regression", target="yv"))
        _parity(doc)
        # query (0,0): neighbors rows 0,1,2 → mean(1,2,3) = 2
        assert evaluate(doc, {"u": 0.0, "v": 0.0}).value == pytest.approx(2.0)

        doc_w = parse_pmml(_knn_xml(
            function="regression", target="yv",
            attrs='continuousScoringMethod="weightedAverage"',
        ))
        _parity(doc_w)
        # exactly on row 0: 1/(0+eps) weight pins the value to 1.0
        assert evaluate(doc_w, {"u": 0.0, "v": 0.0}).value == pytest.approx(
            1.0, abs=1e-5
        )

    def test_regression_median(self):
        doc = parse_pmml(_knn_xml(
            function="regression", target="yv",
            attrs='continuousScoringMethod="median"',
        ))
        _parity(doc)
        assert evaluate(doc, {"u": 0.0, "v": 0.0}).value == pytest.approx(2.0)

    def test_k1_exact_match_and_missing(self):
        doc = parse_pmml(_knn_xml(k=1))
        cm = _parity(doc)
        o = evaluate(doc, {"u": 2.5, "v": 2.5})
        assert o.label == "c" and o.probabilities["c"] == 1.0
        preds = cm.score_records([{"u": 1.0}])
        assert preds[0].is_empty
        assert evaluate(doc, {"u": 1.0}).is_missing

    def test_minkowski_measure_with_knn(self):
        doc = parse_pmml(_knn_xml(
            measure='<ComparisonMeasure kind="distance">'
                    '<minkowski p-parameter="3"/></ComparisonMeasure>'
        ))
        _parity(doc)

    def test_tie_prefers_earlier_training_row(self):
        # query equidistant from rows 1 (a) and 2 (b) with k=1: the
        # earlier row wins on both paths
        doc = parse_pmml(_knn_xml(k=1))
        cm = compile_pmml(doc)
        rec = {"u": 0.5, "v": 0.5}
        o = evaluate(doc, rec)
        p = cm.score_records([rec])[0]
        assert o.label == p.target.label == "a"  # row 0 is nearest... or
        # equidistant set {0,1,2,3} all at d=0.5 → row 0 (a) wins


class TestReviewRegressions:
    def test_similarity_kind_with_distance_metric_rejected(self):
        # similarity measures are now supported (TestBinarySimilarity);
        # what stays invalid is declaring kind="similarity" over a
        # distance metric — caught at parse, one error for both paths
        from flink_jpmml_tpu.utils.exceptions import ModelLoadingException

        with pytest.raises(ModelLoadingException, match="kind"):
            parse_pmml(_knn_xml(
                measure='<ComparisonMeasure kind="similarity">'
                        "<squaredEuclidean/></ComparisonMeasure>"
            ))

    def test_unknown_scoring_method_rejected_both_paths(self):
        from flink_jpmml_tpu.utils.exceptions import (
            ModelCompilationException,
        )

        doc = parse_pmml(_knn_xml(
            function="regression", target="yv",
            attrs='continuousScoringMethod="weightedMedian"',
        ))
        with pytest.raises(ModelCompilationException, match="weightedMedian"):
            compile_pmml(doc)
        with pytest.raises(ModelCompilationException, match="weightedMedian"):
            evaluate(doc, {"u": 0.0, "v": 0.0})

    def test_extension_before_metric_accepted(self):
        doc = parse_pmml(_knn_xml(
            measure='<ComparisonMeasure kind="distance">'
                    '<Extension extender="x" name="n" value="v"/>'
                    "<squaredEuclidean/></ComparisonMeasure>"
        ))
        assert doc.model.measure.metric == "squaredEuclidean"
        _parity(doc, n=40)

    def test_polynomial_kernel_fractional_degree_nan_not_complex(self):
        from tests.test_svm import _svm_xml, _PAIR_MACHINES

        xml = _svm_xml(
            '<PolynomialKernelType gamma="1" coef0="-5" degree="0.5"/>',
            _PAIR_MACHINES,
        )
        doc = parse_pmml(xml)
        o = evaluate(doc, {"x1": 0.0, "x2": 0.0})  # dot=0 → base −5 < 0
        assert not isinstance(o.value, complex)

    def test_regression_svm_multiple_machines_rejected_both_paths(self):
        from flink_jpmml_tpu.utils.exceptions import (
            ModelCompilationException,
        )
        from tests.test_svm import _svm_xml, _PAIR_MACHINES

        doc = parse_pmml(_svm_xml(
            "<LinearKernelType/>", _PAIR_MACHINES, function="regression"
        ))
        with pytest.raises(ModelCompilationException, match="exactly one"):
            compile_pmml(doc)
        with pytest.raises(ModelCompilationException, match="exactly one"):
            evaluate(doc, {"x1": 1.0, "x2": 1.0})


SIM_CLUSTER = """<PMML version="4.3"><DataDictionary>
  <DataField name="b0" optype="continuous" dataType="double"/>
  <DataField name="b1" optype="continuous" dataType="double"/>
  <DataField name="b2" optype="continuous" dataType="double"/>
  <DataField name="b3" optype="continuous" dataType="double"/>
  </DataDictionary>
  <ClusteringModel functionName="clustering" modelClass="centerBased"
      numberOfClusters="2">
  <MiningSchema>
    <MiningField name="b0"/><MiningField name="b1"/>
    <MiningField name="b2"/><MiningField name="b3"/>
  </MiningSchema>
  <ComparisonMeasure kind="similarity"><{metric}{params}/>
  </ComparisonMeasure>
  <ClusteringField field="b0"/><ClusteringField field="b1"/>
  <ClusteringField field="b2"/><ClusteringField field="b3"/>
  <Cluster id="c1"><Array n="4" type="real">1 1 0 0</Array></Cluster>
  <Cluster id="c2"><Array n="4" type="real">0 1 1 1</Array></Cluster>
  </ClusteringModel></PMML>"""


def _hand_sim(metric, x, z, params=None):
    a = sum(1 for xi, zi in zip(x, z) if xi > 0.5 and zi > 0.5)
    b = sum(1 for xi, zi in zip(x, z) if xi > 0.5 and zi <= 0.5)
    c = sum(1 for xi, zi in zip(x, z) if xi <= 0.5 and zi > 0.5)
    d = sum(1 for xi, zi in zip(x, z) if xi <= 0.5 and zi <= 0.5)
    if metric == "simpleMatching":
        return (a + d) / (a + b + c + d)
    if metric == "jaccard":
        return a / (a + b + c) if a + b + c else 0.0
    if metric == "tanimoto":
        return (a + d) / (a + 2 * (b + c) + d)
    c00, c01, c10, c11, d00, d01, d10, d11 = params
    num = c11 * a + c10 * b + c01 * c + c00 * d
    den = d11 * a + d10 * b + d01 * c + d00 * d
    return num / den if den else 0.0


class TestBinarySimilarity:
    @pytest.mark.parametrize(
        "metric,params",
        [
            ("simpleMatching", None),
            ("jaccard", None),
            ("tanimoto", None),
            ("binarySimilarity",
             (0.5, 0.0, 0.0, 2.0, 1.0, 1.0, 1.0, 1.0)),
        ],
    )
    def test_clustering_similarity_parity(self, metric, params):
        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.pmml import parse_pmml
        from flink_jpmml_tpu.pmml.interp import evaluate

        pstr = ""
        if params is not None:
            names = ["c00", "c01", "c10", "c11", "d00", "d01", "d10", "d11"]
            pstr = "".join(
                f' {n}-parameter="{v}"' for n, v in zip(names, params)
            )
        doc = parse_pmml(SIM_CLUSTER.format(metric=metric, params=pstr))
        cm = compile_pmml(doc)
        centers = [(1, 1, 0, 0), (0, 1, 1, 1)]
        for basket in ((1, 1, 0, 0), (0, 1, 1, 0), (1, 0, 1, 1), (0, 0, 0, 0)):
            rec = dict(zip(("b0", "b1", "b2", "b3"), map(float, basket)))
            hand = [_hand_sim(metric, basket, z, params) for z in centers]
            o = evaluate(doc, rec)
            p = cm.score_records([rec])[0]
            assert o.probabilities["c1"] == pytest.approx(hand[0])
            assert o.probabilities["c2"] == pytest.approx(hand[1])
            assert p.target.probabilities["c1"] == pytest.approx(
                hand[0], abs=1e-6
            )
            win = "c1" if hand[0] >= hand[1] else "c2"
            assert o.label == win and p.target.label == win, (metric, basket)

    def test_knn_similarity_votes(self):
        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.pmml import parse_pmml
        from flink_jpmml_tpu.pmml.interp import evaluate

        xml = _knn_xml(
            measure='<ComparisonMeasure kind="similarity"><jaccard/>'
                    "</ComparisonMeasure>"
        )
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        import numpy as np

        rng = np.random.default_rng(6)
        for _ in range(20):
            rec = {
                f: float(v)
                for f, v in zip(doc.active_fields, rng.integers(0, 2, size=2))
            }
            o = evaluate(doc, rec)
            p = cm.score_records([rec])[0]
            assert p.target.label == o.label, rec

    def test_kind_metric_mismatch_rejected(self):
        from flink_jpmml_tpu.pmml import parse_pmml
        from flink_jpmml_tpu.utils.exceptions import ModelLoadingException

        bad = SIM_CLUSTER.format(metric="euclidean", params="")
        with pytest.raises(ModelLoadingException, match="kind"):
            parse_pmml(bad)

    def test_zero_similarity_weighted_average_empty(self):
        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.pmml import parse_pmml
        from flink_jpmml_tpu.pmml.interp import evaluate

        xml = _knn_xml(
            function="regression", target="yv",
            attrs='continuousScoringMethod="weightedAverage"',
            measure='<ComparisonMeasure kind="similarity"><jaccard/>'
                    "</ComparisonMeasure>",
        )
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        # a record with no set bits shares nothing with any neighbor:
        # all similarities 0 -> undefined weighted average -> empty lane
        rec = {f: 0.0 for f in doc.active_fields}
        assert evaluate(doc, rec).value is None
        assert cm.score_records([rec])[0].is_empty


class TestInstanceIds:
    def _xml_with_ids(self, function="classification", target="cls",
                      attrs=""):
        xml = _knn_xml(function=function, target=target, attrs=attrs)
        # give every training row an id column and declare the variable
        rows = "".join(
            f"<row><u>{u}</u><v>{v}</v><cls>{c}</cls><yv>{y}</yv>"
            f"<rid>row{i}</rid></row>"
            for i, (u, v, c, y) in enumerate(ROWS)
        )
        import re

        xml = re.sub(r"<InlineTable>.*</InlineTable>",
                     f"<InlineTable>{rows}</InlineTable>", xml, flags=re.S)
        xml = xml.replace(
            "<InstanceFields>",
            '<InstanceFields><InstanceField field="rid" column="rid"/>',
        ).replace(
            "<NearestNeighborModel",
            '<NearestNeighborModel instanceIdVariable="rid"',
            1,
        )
        return xml

    def _with_output(self, xml, n_ranks=3):
        fields = "".join(
            f'<OutputField name="nb{r}" feature="entityId" rank="{r}"/>'
            for r in range(1, n_ranks + 1)
        )
        return xml.replace(
            "</MiningSchema>", f"</MiningSchema><Output>{fields}</Output>"
        )

    def test_rank_k_neighbor_ids_classification(self):
        doc = parse_pmml(self._with_output(self._xml_with_ids()))
        cm = compile_pmml(doc)
        # query (0.1, 0.1): nearest rows 0, 1, 2 in that order
        rec = {"u": 0.1, "v": 0.1}
        o = evaluate(doc, rec)
        p = cm.score_records([rec])[0]
        assert o.outputs == {"nb1": "row0", "nb2": "row1", "nb3": "row2"}
        assert p.outputs == o.outputs
        # near row 4 (2,2): nb1 = row4
        rec = {"u": 2.1, "v": 2.0}
        assert evaluate(doc, rec).outputs["nb1"] == "row4"
        assert cm.score_records([rec])[0].outputs["nb1"] == "row4"

    def test_rank_k_neighbor_ids_regression(self):
        doc = parse_pmml(self._with_output(
            self._xml_with_ids(function="regression", target="yv")
        ))
        cm = compile_pmml(doc)
        rec = {"u": 0.0, "v": 0.0}
        o = evaluate(doc, rec)
        p = cm.score_records([rec])[0]
        assert o.outputs["nb1"] == "row0" == p.outputs["nb1"]
        assert o.value == pytest.approx(2.0)
        assert p.score.value == pytest.approx(2.0, rel=1e-6)

    def test_rank_beyond_k_is_none(self):
        doc = parse_pmml(self._with_output(self._xml_with_ids(), n_ranks=5))
        cm = compile_pmml(doc)
        rec = {"u": 0.1, "v": 0.1}
        o = evaluate(doc, rec)
        p = cm.score_records([rec])[0]
        assert o.outputs["nb4"] is None and o.outputs["nb5"] is None
        assert p.outputs["nb4"] is None and p.outputs["nb5"] is None

    def test_missing_id_column_rejected(self):
        from flink_jpmml_tpu.utils.exceptions import ModelLoadingException

        xml = _knn_xml().replace(
            "<NearestNeighborModel",
            '<NearestNeighborModel instanceIdVariable="rid"',
            1,
        )
        with pytest.raises(ModelLoadingException, match="instanceIdVariable"):
            parse_pmml(xml)

    def test_clustering_rank_k_entity_ids(self):
        from tests.test_compile_golden import MVW_KMEANS

        xml = MVW_KMEANS.replace(
            "</MiningSchema>",
            "</MiningSchema><Output>"
            '<OutputField name="c1st" feature="entityId" rank="1"/>'
            '<OutputField name="c2nd" feature="entityId" rank="2"/>'
            "</Output>",
        )
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        rec = {"a": 1.0, "b": 0.5, "c": 0.5}  # closer to c1
        o = evaluate(doc, rec)
        p = cm.score_records([rec])[0]
        assert o.outputs == {"c1st": "c1", "c2nd": "c2"} == p.outputs

    def test_nested_knn_with_ids_in_select_first(self):
        """A KNN segment declaring instanceIdVariable inside a
        selectFirst ensemble must compile (uniform probs shapes) and
        agree with the oracle — entity outputs are top-level features,
        so both paths yield None for entityId here."""
        from flink_jpmml_tpu.pmml.interp import evaluate

        inner = self._xml_with_ids()
        model = inner[
            inner.index("<NearestNeighborModel"):
            inner.index("</NearestNeighborModel>")
            + len("</NearestNeighborModel>")
        ]
        xml = inner[: inner.index("<NearestNeighborModel")] + f"""
          <MiningModel functionName="classification">
          <MiningSchema><MiningField name="cls" usageType="target"/>
            <MiningField name="u"/><MiningField name="v"/></MiningSchema>
          <Output><OutputField name="nb1" feature="entityId" rank="1"/>
          </Output>
          <Segmentation multipleModelMethod="selectFirst">
            <Segment><True/>{model}</Segment>
          </Segmentation></MiningModel></PMML>"""
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)  # must not raise on probs shapes
        rec = {"u": 0.1, "v": 0.1}
        o = evaluate(doc, rec)
        p = cm.score_records([rec])[0]
        assert p.target.label == o.label
        assert o.outputs["nb1"] is None and p.outputs["nb1"] is None
