"""Learned kernel cost model + predict-then-verify search (ISSUE 11).

Pins: the ridge fit recovers a planted cost law and ranks candidates
by it; persistence is atomic and corrupt-tolerant; the search times at
most top-K of the candidate space and feeds the ledger per-variant
feature rows; a cached winner from an older search space reads as no
entry; concurrent ledger writers merge instead of clobbering; and the
live profiler's drift band invalidates a stale prediction (clears the
autotune entry, bumps the cost-model generation, sets
``kernel_pred_error``)."""

import json
import math
import os

import numpy as np
import pytest

from assets.generate import gen_gbm
from flink_jpmml_tpu.compile import autotune, costmodel, layouts
from flink_jpmml_tpu.compile.qtrees import build_quantized_scorer
from flink_jpmml_tpu.obs import profiler
from flink_jpmml_tpu.obs import recorder as flight
from flink_jpmml_tpu.pmml import parse_pmml_file
from flink_jpmml_tpu.utils.metrics import MetricsRegistry


@pytest.fixture
def doc(tmp_path):
    return parse_pmml_file(
        gen_gbm(str(tmp_path), n_trees=10, depth=3, n_features=4)
    )


def _X(n=64, f=4, seed=0):
    rng = np.random.default_rng(seed)
    return rng.normal(0.0, 1.5, size=(n, f)).astype(np.float32)


def _planted_rows(n=40, seed=0):
    """Synthetic (features, y) with y = exp(0.5·a − 0.3·b + c)."""
    rng = np.random.default_rng(seed)
    rows = []
    for _ in range(n):
        a, b, c = rng.normal(size=3)
        rows.append((
            {"a": a, "b": b, "c": c},
            math.exp(0.5 * a - 0.3 * b + c),
        ))
    return rows


class TestCostModel:
    def test_fit_recovers_planted_law(self):
        m = costmodel.CostModel.fit(_planted_rows(), l2=1e-6)
        assert m is not None and m.stats["rows"] == 40
        assert m.stats["r2"] > 0.99
        for f, y in _planted_rows(8, seed=1):
            pred = m.predict(f)
            assert pred is not None
            assert 0.8 < pred / y < 1.25  # within ~±25% out of sample

    def test_rank_orders_by_predicted_cost(self):
        m = costmodel.CostModel.fit(_planted_rows(), l2=1e-6)
        cands = {
            "cheap": {"a": -2.0, "b": 2.0, "c": -1.0},
            "mid": {"a": 0.0, "b": 0.0, "c": 0.0},
            "dear": {"a": 2.0, "b": -2.0, "c": 1.0},
        }
        assert [n for n, _ in m.rank(cands)] == ["cheap", "mid", "dear"]

    def test_fit_skips_garbage_rows(self):
        rows = _planted_rows(10) + [
            ({}, 1.0), (None, 1.0), ({"a": 1.0}, -1.0),
            ({"a": 1.0}, float("nan")), ({"a": 1.0}, "wat"),
        ]
        m = costmodel.CostModel.fit(rows)
        assert m is not None and m.stats["rows"] == 10

    def test_persistence_roundtrip_and_corrupt_tolerance(self, tmp_path):
        path = str(tmp_path / "cm.json")
        m = costmodel.CostModel.fit(_planted_rows())
        costmodel.save(m, path)
        m2 = costmodel.load(path)
        assert m2 is not None
        f = {"a": 0.3, "b": -0.2, "c": 0.1}
        assert m2.predict(f) == pytest.approx(m.predict(f))
        with open(path, "w") as fh:
            fh.write("\x00not json{{{")
        assert costmodel.load(path) is None  # silent refit contract

    def test_persisted_fit_is_platform_scoped(self, tmp_path):
        # a CPU-interpret fit must never rank a TPU search: load()
        # with a platform rejects a file stamped for another one
        path = str(tmp_path / "cm.json")
        costmodel.save(costmodel.CostModel.fit(_planted_rows()), path)
        here = costmodel._current_platform()
        assert costmodel.load(path, platform=here) is not None
        assert costmodel.load(path, platform="not-" + here) is None
        assert costmodel.load(path) is not None  # unscoped: accept

    def test_variant_features_cover_the_search_axes(self, doc):
        q = build_quantized_scorer(doc, batch_size=64)
        feats = costmodel.variant_features(
            costmodel.scorer_meta(q), "pallas", "mega_bfs", 512, 8,
            wire_bytes=4.0,
        )
        assert feats["layout_mega"] == 1.0 and feats["layout_bfs"] == 1.0
        assert feats["layout_wirepack"] == 0.0
        assert feats["gt"] == 8.0
        assert feats["log2_block_b"] == 9.0
        assert feats["depth"] == pytest.approx(
            math.log2(q._meta["splits"] + 1)
        )


class TestLedger:
    def test_per_variant_rows_carry_features(self, tmp_path):
        path = str(tmp_path / "kc.json")
        led = profiler.KernelCostLedger(path=path, flush_interval_s=0.0)
        led.update(
            "m1", "pallas", 0.5, 1000, 100.0, 6.0,
            variant="pallas_b512_gt4_mega",
            features={"depth": 3.0}, predicted=4e-4,
        )
        entries = profiler.read_ledger(path)
        (key,) = entries
        assert key == "m1|pallas|pallas_b512_gt4_mega"
        e = entries[key]
        assert e["features"] == {"depth": 3.0}
        assert e["predicted_s_per_record"] == 4e-4
        assert e["pred_err"] == pytest.approx(0.25)  # |5e-4−4e-4|/4e-4

    def test_concurrent_writers_merge_not_clobber(self, tmp_path):
        # the satellite: two sibling processes flushing must UNION
        # their entries, not last-writer-wins each other away
        path = str(tmp_path / "kc.json")
        a = profiler.KernelCostLedger(path=path, flush_interval_s=math.inf)
        b = profiler.KernelCostLedger(path=path, flush_interval_s=math.inf)
        a.update("m1", "pallas", 0.5, 1000, None, None, variant="v1")
        b.update("m2", "xla", 0.2, 1000, None, None, variant="v2")
        a.flush()
        b.flush()  # b never saw a's entry in memory
        entries = profiler.read_ledger(path)
        assert set(entries) == {"m1|pallas|v1", "m2|xla|v2"}

    def test_same_key_newest_ts_wins(self, tmp_path):
        path = str(tmp_path / "kc.json")
        a = profiler.KernelCostLedger(path=path, flush_interval_s=math.inf)
        b = profiler.KernelCostLedger(path=path, flush_interval_s=math.inf)
        a.update("m", "xla", 0.4, 1000, None, None)
        a.flush()
        b.update("m", "xla", 0.1, 1000, None, None)  # fresher ts
        b.flush()
        a.update("m", "xla", 0.4, 1000, None, None)
        # force a's in-memory ts older than b's on-disk entry
        with a._mu:
            a._entries["m|xla"]["ts"] -= 3600.0
            a._dirty = True
        a.flush()
        e = profiler.read_ledger(path)["m|xla"]
        assert e["device_s_per_record"] == pytest.approx(1e-4)

    def test_corrupt_ledger_reads_empty(self, tmp_path):
        path = str(tmp_path / "kc.json")
        with open(path, "w") as f:
            f.write("{broken")
        assert profiler.read_ledger(path) == {}
        # and a flush over the corrupt file rewrites it valid
        led = profiler.KernelCostLedger(path=path, flush_interval_s=0.0)
        led.update("m", "xla", 0.1, 100, None, None)
        assert json.load(open(path))["entries"]

    def test_fit_from_ledger(self, tmp_path):
        path = str(tmp_path / "kc.json")
        led = profiler.KernelCostLedger(path=path, flush_interval_s=math.inf)
        rng = np.random.default_rng(3)
        for i in range(10):
            a = float(rng.normal())
            led.update(
                "m", "xla", math.exp(a) * 1e-6 * 1000, 1000, None, None,
                variant=f"v{i}", features={"a": a},
            )
        led.flush()
        m = costmodel.fit_from_ledger(path=path, min_rows=5)
        assert m is not None and m.stats["rows"] == 10
        # legacy rows without features don't break the replay
        led.update("legacy", "xla", 0.1, 100, None, None)
        led.flush()
        assert costmodel.fit_from_ledger(path=path, min_rows=5) is not None


class TestSearch:
    def test_top_k_bounds_timing(self, doc):
        q = build_quantized_scorer(
            doc, batch_size=64, backend="pallas", pallas_interpret=True
        )
        cfg = autotune.sweep(q, _X(), repeats=1, top_k=2)
        s = cfg.search
        assert s is not None
        assert s["timed"] <= 2 < s["candidates_total"]
        assert s["space"] == layouts.SPACE_TAG
        # the timed candidates landed in the ledger as training rows
        rows = costmodel.training_rows()
        assert len(rows) >= s["timed"]

    def test_second_search_is_learned(self, doc):
        q = build_quantized_scorer(
            doc, batch_size=64, backend="pallas", pallas_interpret=True
        )
        autotune.sweep(q, _X(), repeats=1, top_k=8)
        q2 = build_quantized_scorer(
            doc, batch_size=64, backend="pallas", pallas_interpret=True
        )
        cfg2 = autotune.sweep(q2, _X(), repeats=1, top_k=3)
        assert cfg2.search["mode"] == "learned"
        assert cfg2.search["timed"] <= 3
        assert cfg2.search["predicted"]  # the whole space was ranked
        assert len(cfg2.search["predicted"]) == cfg2.search["candidates_total"]
        # the incumbent default is always among the verified set — a
        # mispredicting fit must never adopt a variant without having
        # measured the default it would replace
        assert "pallas_b1024_gt4" in cfg2.rates

    def test_disable_env_falls_back_to_legacy(self, doc, monkeypatch):
        monkeypatch.setenv("FJT_KERNEL_SEARCH_DISABLE", "1")
        q = build_quantized_scorer(
            doc, batch_size=64, backend="pallas", pallas_interpret=True
        )
        cfg = autotune.sweep(q, _X(), repeats=1, top_k=8)
        assert cfg.search["mode"] == "legacy"
        # legacy space = ref layout × tiles only
        assert cfg.search["candidates_total"] == 5
        assert cfg.layout == "ref"

    def test_stale_space_tag_reads_as_no_entry(self, doc):
        q = build_quantized_scorer(doc, batch_size=64)
        key = autotune.backend_key(q)
        cfg = autotune.TunedConfig(encode="fused", source="sweep")
        cfg.space = "space-v0:pre-layouts"
        autotune.store(q.model_hash, key, cfg)
        assert autotune.lookup(q.model_hash, key) is None
        # a current-space entry round-trips
        autotune.store(q.model_hash, key, autotune.TunedConfig())
        got = autotune.lookup(q.model_hash, key)
        assert got is not None and got.space == layouts.SPACE_TAG

    def test_pre_layout_entry_without_tag_is_stale(self, doc):
        # a cache written by the previous binary (no space field at
        # all) must silently re-search, not pin its winner
        q = build_quantized_scorer(doc, batch_size=64)
        key = autotune.backend_key(q)
        path = autotune.cache_path()
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(json.dumps({
            "version": 1,
            "entries": {
                f"{q.model_hash}|{key}": {
                    "encode": "fused", "block_b": 512, "gt": 8,
                    "rec_s": 1e6, "rates": {}, "source": "sweep",
                },
            },
        }))
        assert autotune.lookup(q.model_hash, key) is None
        q2 = build_quantized_scorer(doc, batch_size=64)
        assert q2.tuned is None and q2.encode_mode == "host"

    def test_xla_search_covers_layouts(self, doc):
        q = build_quantized_scorer(doc, batch_size=64, backend="xla")
        cfg = autotune.sweep(q, _X(), repeats=1, top_k=4)
        # uint8 wire: ref + bfs only (wirepack has nothing to pack)
        assert cfg.search["candidates_total"] == 2
        assert any(k.startswith("xla_") for k in cfg.rates)
        # whatever won still scores exactly like a fresh reference
        q_ref = build_quantized_scorer(doc, batch_size=64, backend="xla")
        X = _X(seed=9)
        np.testing.assert_array_equal(
            np.asarray(q.predict_wire(q.wire.encode(X)), np.float32),
            np.asarray(q_ref.predict_wire(q_ref.wire.encode(X)), np.float32),
        )


class TestDriftBandInvalidation:
    def _profile(self, q, predicted):
        from flink_jpmml_tpu.obs import attr

        p = attr.dispatch_profile(q, 64)
        p["predicted_s_per_record"] = predicted
        return p

    def test_sustained_drift_reopens_search(self, doc):
        q = build_quantized_scorer(doc, batch_size=64)
        key = autotune.backend_key(q)
        autotune.store(q.model_hash, key, autotune.TunedConfig())
        assert autotune.lookup(q.model_hash, key) is not None
        m = MetricsRegistry()
        prof = profiler.DeviceProfiler(m, interval_s=0.0)
        gen0 = costmodel.generation()
        # observed 64e-6/64 = 1e-6 s/rec vs predicted 1e-8: 100x out
        # of band, three strikes
        for _ in range(3):
            prof.record_sample(64e-6, self._profile(q, 1e-8))
        assert costmodel.generation() == gen0 + 1
        assert autotune.lookup(q.model_hash, key) is None
        assert (
            m.struct_snapshot()["gauges"]["kernel_pred_error"]["value"] > 0
        )
        kinds = [e.get("kind") for e in flight.events()]
        assert "kernel_search_stale" in kinds
        assert "costmodel_stale" in kinds

    def test_in_band_predictions_do_not_invalidate(self, doc):
        q = build_quantized_scorer(doc, batch_size=64)
        key = autotune.backend_key(q)
        autotune.store(q.model_hash, key, autotune.TunedConfig())
        m = MetricsRegistry()
        prof = profiler.DeviceProfiler(m, interval_s=0.0)
        gen0 = costmodel.generation()
        for _ in range(10):
            prof.record_sample(64e-6, self._profile(q, 1.2e-6))
        assert costmodel.generation() == gen0
        assert autotune.lookup(q.model_hash, key) is not None
        err = m.struct_snapshot()["gauges"]["kernel_pred_error"]["value"]
        assert 0 <= err < 0.5

    def test_stale_trigger_is_one_shot_per_prediction(self, doc):
        # a long-lived server with a permanently-out-of-band config
        # must fire ONCE: re-firing every 3 samples would keep wiping
        # the fit/cache a sibling's fresh re-search just wrote
        q = build_quantized_scorer(doc, batch_size=64)
        m = MetricsRegistry()
        prof = profiler.DeviceProfiler(m, interval_s=0.0)
        gen0 = costmodel.generation()
        for _ in range(12):
            prof.record_sample(64e-6, self._profile(q, 1e-8))
        assert costmodel.generation() == gen0 + 1  # exactly one firing
        # a NEW prediction (a re-search ran) re-arms the band
        for _ in range(3):
            prof.record_sample(64e-6, self._profile(q, 2e-8))
        assert costmodel.generation() == gen0 + 2

    def test_degraded_cached_variant_ships_no_prediction(self, doc):
        # a cached variant this build can't honour (block_b=32 is no
        # valid tile for batch 64) degrades to the built defaults —
        # and must NOT ship the unapplied variant's tiles/prediction
        # into the ledger or the live drift band
        from flink_jpmml_tpu.obs import attr

        qp = build_quantized_scorer(
            doc, batch_size=64, backend="pallas", pallas_interpret=True
        )
        autotune.apply(qp, autotune.TunedConfig(
            block_b=32, gt=2, predicted_s_per_record=1e-6, source="sweep",
        ))
        assert qp._pred_s_per_record is None
        p = attr.dispatch_profile(qp, 64)
        assert p["predicted_s_per_record"] is None
        assert p["model_hash"] == qp.model_hash
        assert p["variant"] == "pallas_b1024_gt4"  # what actually serves
        assert p["features"]["gt"] == 4.0

    def test_no_prediction_no_gauge(self, doc):
        q = build_quantized_scorer(doc, batch_size=64)
        m = MetricsRegistry()
        prof = profiler.DeviceProfiler(m, interval_s=0.0)
        from flink_jpmml_tpu.obs import attr

        prof.record_sample(64e-6, attr.dispatch_profile(q, 64))
        assert "kernel_pred_error" not in m.struct_snapshot()["gauges"]
