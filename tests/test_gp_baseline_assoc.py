"""GaussianProcessModel, BaselineModel, AssociationModel families:
compiled vs oracle vs hand-computed golden values."""

import dataclasses
import math

import numpy as np
import pytest

from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.pmml import parse_pmml
from flink_jpmml_tpu.pmml.interp import evaluate
from flink_jpmml_tpu.utils.exceptions import ModelLoadingException

# ---------------------------------------------------------------------------
# GaussianProcessModel
# ---------------------------------------------------------------------------

GP = """<PMML version="4.3"><DataDictionary>
  <DataField name="x1" optype="continuous" dataType="double"/>
  <DataField name="x2" optype="continuous" dataType="double"/>
  <DataField name="y" optype="continuous" dataType="double"/>
  </DataDictionary>
  <GaussianProcessModel functionName="regression">
  <MiningSchema><MiningField name="y" usageType="target"/>
    <MiningField name="x1"/><MiningField name="x2"/></MiningSchema>
  {kernel}
  <TrainingInstances recordCount="4">
    <InstanceFields>
      <InstanceField field="x1" column="x1"/>
      <InstanceField field="x2" column="x2"/>
      <InstanceField field="y" column="y"/>
    </InstanceFields>
    <InlineTable>
      <row><x1>0.0</x1><x2>0.0</x2><y>1.0</y></row>
      <row><x1>1.0</x1><x2>0.5</x2><y>-0.5</y></row>
      <row><x1>-0.5</x1><x2>1.5</x2><y>2.0</y></row>
      <row><x1>0.7</x1><x2>-1.0</x2><y>0.3</y></row>
    </InlineTable>
  </TrainingInstances>
  </GaussianProcessModel></PMML>"""

TRAIN_X = np.array(
    [[0.0, 0.0], [1.0, 0.5], [-0.5, 1.5], [0.7, -1.0]], np.float64
)
TRAIN_Y = np.array([1.0, -0.5, 2.0, 0.3], np.float64)


def _hand_kernel(kind, a, b, gamma, lam, degree=1.0):
    lam = np.asarray(lam, np.float64)
    d = np.asarray(a, np.float64) - np.asarray(b, np.float64)
    if kind == "sq":
        return gamma * math.exp(-0.5 * float(((d / lam) ** 2).sum()))
    if kind == "abs":
        return gamma * math.exp(-float((np.abs(d) / lam).sum()))
    return gamma * math.exp(-float(((np.abs(d) / lam) ** degree).sum()))


def _hand_gp(kind, x, gamma, noise, lam, degree=1.0):
    N = TRAIN_X.shape[0]
    K = np.array(
        [
            [
                _hand_kernel(kind, TRAIN_X[i], TRAIN_X[j], gamma, lam, degree)
                for j in range(N)
            ]
            for i in range(N)
        ]
    )
    alpha = np.linalg.solve(K + noise * np.eye(N), TRAIN_Y)
    ks = np.array(
        [_hand_kernel(kind, x, TRAIN_X[i], gamma, lam, degree) for i in range(N)]
    )
    return float(ks @ alpha)


class TestGaussianProcess:
    def _parity(self, kernel_xml, kind, gamma, noise, lam, degree=1.0, n=64):
        doc = parse_pmml(GP.format(kernel=kernel_xml))
        cm = compile_pmml(doc)
        rng = np.random.default_rng(3)
        X = rng.normal(0, 1, size=(n, 2))
        recs = [{"x1": float(a), "x2": float(b)} for a, b in X]
        preds = cm.score_records(recs)
        for rec, p, x in zip(recs, preds, X):
            o = evaluate(doc, rec)
            hand = _hand_gp(kind, x, gamma, noise, lam, degree)
            assert not p.is_empty
            assert o.value == pytest.approx(hand, rel=1e-9)
            assert p.score.value == pytest.approx(hand, rel=2e-4, abs=1e-5)

    def test_radial_basis(self):
        self._parity(
            '<RadialBasisKernel gamma="2.0" noiseVariance="0.1" '
            'lambda="1.3"/>',
            "sq", 2.0, 0.1, [1.3, 1.3],
        )

    def test_ard_squared_exponential(self):
        self._parity(
            '<ARDSquaredExponentialKernel gamma="1.5" noiseVariance="0.2">'
            '<Lambda><Array n="2" type="real">0.8 2.0</Array></Lambda>'
            "</ARDSquaredExponentialKernel>",
            "sq", 1.5, 0.2, [0.8, 2.0],
        )

    def test_absolute_exponential(self):
        self._parity(
            '<AbsoluteExponentialKernel gamma="1.0" noiseVariance="0.05">'
            '<Lambda><Array n="2" type="real">1.0 0.5</Array></Lambda>'
            "</AbsoluteExponentialKernel>",
            "abs", 1.0, 0.05, [1.0, 0.5],
        )

    def test_generalized_exponential(self):
        self._parity(
            '<GeneralizedExponentialKernel gamma="1.2" noiseVariance="0.1" '
            'degree="1.5"><Lambda><Array n="2" type="real">1.1 0.9</Array>'
            "</Lambda></GeneralizedExponentialKernel>",
            "gen", 1.2, 0.1, [1.1, 0.9], degree=1.5,
        )

    def test_missing_input_empty_lane(self):
        doc = parse_pmml(GP.format(
            kernel='<RadialBasisKernel gamma="1.0" noiseVariance="0.1" '
                   'lambda="1.0"/>'
        ))
        cm = compile_pmml(doc)
        p = cm.score_records([{"x1": 0.5, "x2": None}])[0]
        assert p.is_empty
        assert evaluate(doc, {"x1": 0.5, "x2": None}).value is None

    def test_bad_documents(self):
        with pytest.raises(ModelLoadingException):
            parse_pmml(GP.format(kernel=""))  # no kernel element
        with pytest.raises(ModelLoadingException):
            parse_pmml(GP.format(
                kernel='<RadialBasisKernel gamma="1" noiseVariance="0.1" '
                       'lambda="-2"/>'
            ))
        # the isotropic kernel must not accept a per-dimension Lambda
        # (compiled/oracle would disagree on which scale applies)
        with pytest.raises(ModelLoadingException):
            parse_pmml(GP.format(
                kernel='<RadialBasisKernel gamma="1" noiseVariance="0.1">'
                       '<Lambda><Array n="2" type="real">0.5 3.0</Array>'
                       "</Lambda></RadialBasisKernel>"
            ))
        # a typo'd InstanceField leaves an active field without a column:
        # rejected, never silently dropped from the kernel inputs
        with pytest.raises(ModelLoadingException):
            parse_pmml(GP.format(
                kernel='<RadialBasisKernel gamma="1" noiseVariance="0.1" '
                       'lambda="1"/>'
            ).replace('<InstanceField field="x1" column="x1"/>',
                      '<InstanceField field="x_1" column="x1"/>'))


# ---------------------------------------------------------------------------
# BaselineModel
# ---------------------------------------------------------------------------

BASELINE = """<PMML version="4.2"><DataDictionary>
  <DataField name="x" optype="continuous" dataType="double"/>
  </DataDictionary>
  <BaselineModel functionName="regression">
  <MiningSchema><MiningField name="x"/></MiningSchema>
  <TestDistributions field="x" testStatistic="zValue">
    <Baseline>{dist}</Baseline>
  </TestDistributions>
  </BaselineModel></PMML>"""


class TestBaseline:
    @pytest.mark.parametrize(
        "dist,mean,sd",
        [
            ('<GaussianDistribution mean="5.0" variance="4.0"/>', 5.0, 2.0),
            ('<PoissonDistribution mean="9.0"/>', 9.0, 3.0),
            (
                '<UniformDistribution lower="2.0" upper="8.0"/>',
                5.0,
                math.sqrt(36.0 / 12.0),
            ),
        ],
    )
    def test_zvalue(self, dist, mean, sd):
        doc = parse_pmml(BASELINE.format(dist=dist))
        cm = compile_pmml(doc)
        xs = [0.0, 3.5, 5.0, 11.25]
        preds = cm.score_records([{"x": v} for v in xs])
        for v, p in zip(xs, preds):
            hand = (v - mean) / sd
            assert p.score.value == pytest.approx(hand, rel=1e-6, abs=1e-6)
            assert evaluate(doc, {"x": v}).value == pytest.approx(hand)

    def test_missing_and_rejections(self):
        doc = parse_pmml(BASELINE.format(
            dist='<GaussianDistribution mean="0" variance="1"/>'
        ))
        cm = compile_pmml(doc)
        assert cm.score_records([{"x": None}])[0].is_empty
        with pytest.raises(ModelLoadingException):
            parse_pmml(BASELINE.format(dist="").replace(
                'testStatistic="zValue"', 'testStatistic="CUSUM"'
            ))
        with pytest.raises(ModelLoadingException):
            parse_pmml(BASELINE.format(
                dist='<GaussianDistribution mean="0" variance="0"/>'
            ))


# ---------------------------------------------------------------------------
# AssociationModel
# ---------------------------------------------------------------------------

ASSOC = """<PMML version="4.2"><DataDictionary>
  <DataField name="beer" optype="continuous" dataType="double"/>
  <DataField name="chips" optype="continuous" dataType="double"/>
  <DataField name="wine" optype="continuous" dataType="double"/>
  <DataField name="bread" optype="continuous" dataType="double"/>
  </DataDictionary>
  <AssociationModel functionName="associationRules"
      numberOfTransactions="1000" numberOfItems="4"
      minimumSupport="0.1" minimumConfidence="0.5"
      numberOfItemsets="5" numberOfRules="3">
  <MiningSchema>
    <MiningField name="beer"/><MiningField name="chips"/>
    <MiningField name="wine"/><MiningField name="bread"/>
  </MiningSchema>
  <Item id="1" value="beer"/><Item id="2" value="chips"/>
  <Item id="3" value="wine"/><Item id="4" value="bread"/>
  <Itemset id="s1"><ItemRef itemRef="1"/></Itemset>
  <Itemset id="s2"><ItemRef itemRef="2"/></Itemset>
  <Itemset id="s3"><ItemRef itemRef="1"/><ItemRef itemRef="2"/></Itemset>
  <Itemset id="s4"><ItemRef itemRef="3"/></Itemset>
  <Itemset id="s5"><ItemRef itemRef="4"/></Itemset>
  <AssociationRule id="r1" support="0.4" confidence="0.7"
      antecedent="s1" consequent="s2"/>
  <AssociationRule id="r2" support="0.2" confidence="0.9"
      antecedent="s3" consequent="s5"/>
  <AssociationRule id="r3" support="0.3" confidence="0.7"
      antecedent="s4" consequent="s1"/>
  </AssociationModel></PMML>"""


def _basket(**kw):
    rec = {"beer": 0.0, "chips": 0.0, "wine": 0.0, "bread": 0.0}
    rec.update({k: 1.0 for k in kw if kw[k]})
    return rec


class TestAssociation:
    def _one(self, cm, doc, rec):
        p = cm.score_records([rec])[0]
        o = evaluate(doc, rec)
        if p.is_empty:
            assert o.value is None
            return None
        assert p.score.value == pytest.approx(o.value, rel=1e-6)
        assert p.target.label == o.label
        if not doc.output_fields:
            # no <Output>: both paths surface the winner's rule metadata
            assert p.outputs == o.outputs
        return p

    def test_firing_and_ranking(self):
        # spec-default criterion: exclusiveRecommendation
        doc = parse_pmml(ASSOC)
        assert doc.model.criterion == "exclusiveRecommendation"
        cm = compile_pmml(doc)
        # {beer}: only r1 fires (chips not yet held) → chips @ 0.7
        p = self._one(cm, doc, _basket(beer=1))
        assert p.target.label == "chips" and p.score.value == pytest.approx(0.7)
        # {beer, chips}: r1 excluded (consequent already held), r2 fires
        p = self._one(cm, doc, _basket(beer=1, chips=1))
        assert p.target.label == "bread" and p.score.value == pytest.approx(0.9)
        # {wine}: r3 → beer
        p = self._one(cm, doc, _basket(wine=1))
        assert p.target.label == "beer"
        # {beer, wine}: r3 excluded (beer already held) → r1 → chips
        p = self._one(cm, doc, _basket(beer=1, wine=1))
        assert p.target.label == "chips"
        # empty basket: nothing fires → empty lane
        assert self._one(cm, doc, _basket()) is None

    def test_criteria(self):
        # JPMML-parity semantics per criterion on basket {beer, chips}:
        # r1 beer→chips: "rule" needs the whole rule in the basket (it
        # is) and r2's consequent bread is absent, so "rule" picks r1;
        # "recommendation" ignores consequents → highest-confidence r2;
        # "exclusiveRecommendation" drops r1 (consequent held) → r2
        doc = parse_pmml(ASSOC)
        basket = _basket(beer=1, chips=1)
        for criterion, expect, conf in (
            ("rule", "chips", 0.7),
            ("recommendation", "bread", 0.9),
            ("exclusiveRecommendation", "bread", 0.9),
        ):
            m = dataclasses.replace(doc.model, criterion=criterion)
            d = dataclasses.replace(doc, model=m)
            p = self._one(compile_pmml(d), d, basket)
            assert p.target.label == expect, criterion
            assert p.score.value == pytest.approx(conf), criterion
        # "rule" on {beer} alone: consequent chips missing → nothing fires
        m = dataclasses.replace(doc.model, criterion="rule")
        d = dataclasses.replace(doc, model=m)
        assert self._one(compile_pmml(d), d, _basket(beer=1)) is None

    def test_missing_columns_read_absent(self):
        doc = parse_pmml(ASSOC)
        cm = compile_pmml(doc)
        rec = {"beer": 1.0, "chips": None, "wine": None, "bread": None}
        p = self._one(cm, doc, rec)
        assert p is not None and p.target.label == "chips"

    def test_items_must_be_fields(self):
        bad = ASSOC.replace('<MiningField name="bread"/>', "")
        with pytest.raises(ModelLoadingException):
            parse_pmml(bad)

    def test_empty_consequent_rejected_at_parse(self):
        bad = ASSOC.replace(
            '<Itemset id="s5"><ItemRef itemRef="4"/></Itemset>',
            '<Itemset id="s5"/>',
        )
        with pytest.raises(ModelLoadingException):
            parse_pmml(bad)

    def test_criterion_from_output_algorithm(self):
        # the ranking criterion rides <Output><OutputField algorithm=…>
        xml = ASSOC.replace(
            "</AssociationModel>",
            '<Output><OutputField name="rec" feature="ruleValue" '
            'algorithm="exclusiveRecommendation" ruleFeature="consequent"/>'
            "</Output></AssociationModel>",
        )
        doc = parse_pmml(xml)
        assert doc.model.criterion == "exclusiveRecommendation"
        cm = compile_pmml(doc)
        # {beer, chips}: r1 excluded (consequent chips already in basket),
        # r2 fires → bread
        p = self._one(cm, doc, _basket(beer=1, chips=1))
        assert p.target.label == "bread"
        assert p.outputs["rec"] == "bread"

    def test_rule_value_outputs_parity(self):
        xml = ASSOC.replace(
            "</AssociationModel>",
            "<Output>"
            '<OutputField name="rid" feature="ruleValue" ruleFeature="ruleId"/>'
            '<OutputField name="sup" feature="ruleValue" ruleFeature="support"/>'
            '<OutputField name="ante" feature="ruleValue" ruleFeature="antecedent"/>'
            '<OutputField name="rl" feature="ruleValue" ruleFeature="rule"/>'
            "</Output></AssociationModel>",
        )
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        rec = _basket(beer=1, chips=1)  # r2 wins
        p = cm.score_records([rec])[0]
        o = evaluate(doc, rec)
        assert p.outputs == o.outputs
        assert p.outputs["rid"] == "r2"
        assert p.outputs["sup"] == pytest.approx(0.2)
        assert p.outputs["ante"] == "beer chips"
        assert p.outputs["rl"] == "{beer chips}->{bread}"
