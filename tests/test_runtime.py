"""Runtime tests: the streaming loop end-to-end with in-memory sources/sinks.

SURVEY.md §5 tier 3: "runtime tests driving the streaming loop with
in-memory sources/sinks, including control-stream add/del and
checkpoint/restore" — the MiniCluster-test equivalent.
"""

import time

import numpy as np
import pytest

from flink_jpmml_tpu.api import ModelReader, StreamEnvironment
from flink_jpmml_tpu.models.control import AddMessage, DelMessage
from flink_jpmml_tpu.pmml import parse_pmml_file
from flink_jpmml_tpu.pmml.interp import evaluate
from flink_jpmml_tpu.runtime.queues import BoundedQueue, Closed
from flink_jpmml_tpu.runtime.sources import ControlSource, InMemorySource
from flink_jpmml_tpu.utils.config import BatchConfig, RuntimeConfig


@pytest.fixture()
def iris_reader(assets_dir):
    return ModelReader(str(assets_dir / "iris_lr.pmml"))


def _iris_records(n, seed=0, fields=4):
    rng = np.random.default_rng(seed)
    return rng.normal(3.0, 2.0, size=(n, fields)).astype(np.float32).tolist()


def _small_batch_config():
    return RuntimeConfig(batch=BatchConfig(size=32, deadline_us=2000))


class TestBoundedQueue:
    def test_drain_fills_to_max(self):
        q = BoundedQueue(100)
        for i in range(50):
            q.put(i)
        out = q.drain(32, deadline_us=1000)
        assert out == list(range(32))

    def test_drain_deadline_partial(self):
        q = BoundedQueue(100)
        q.put(1)
        t0 = time.monotonic()
        out = q.drain(32, deadline_us=20000)
        assert out == [1]
        assert time.monotonic() - t0 < 0.5

    def test_close_raises_when_empty(self):
        q = BoundedQueue(4)
        q.put(1)
        q.close()
        assert q.drain(4, 1000) == [1]
        with pytest.raises(Closed):
            q.drain(4, 1000)


class TestStaticPipeline:
    def test_vectors_end_to_end(self, iris_reader, assets_dir):
        env = StreamEnvironment(_small_batch_config())
        vectors = _iris_records(101)  # not a multiple of batch size: pad path
        sink = env.from_collection(vectors).evaluate(iris_reader).collect()
        env.execute(timeout=30.0)
        preds = sink.items
        assert len(preds) == 101
        doc = parse_pmml_file(iris_reader.path)
        # order is preserved; spot-check golden parity through the runtime
        for v, p in zip(vectors[:10], preds[:10]):
            o = evaluate(doc, dict(zip(doc.active_fields, v)))
            assert p.target.label == o.label

    def test_quick_evaluate_pairs(self, iris_reader):
        env = StreamEnvironment(_small_batch_config())
        vectors = _iris_records(40)
        sink = env.from_collection(vectors).quick_evaluate(iris_reader).collect()
        env.execute(timeout=30.0)
        assert len(sink.items) == 40
        pred, vec = sink.items[0]
        assert not pred.is_empty
        assert vec == vectors[0]

    def test_dirty_lanes_are_empty_not_fatal(self, iris_reader):
        env = StreamEnvironment(_small_batch_config())
        vectors = _iris_records(10)
        vectors[3] = [float("nan")] * 4  # all-missing record
        sink = env.from_collection(vectors).evaluate(iris_reader).collect()
        env.execute(timeout=30.0)
        preds = sink.items
        assert len(preds) == 10
        assert preds[3].is_empty
        assert not preds[4].is_empty  # stream survived (C5)

    def test_metrics_populated(self, iris_reader):
        env = StreamEnvironment(_small_batch_config())
        sink = env.from_collection(_iris_records(64)).evaluate(iris_reader).collect()
        env.execute(timeout=30.0)
        snap = env.metrics.snapshot()
        assert snap["records_in"] == 64
        assert snap["records_out"] == 64
        assert snap["batches"] >= 2
        assert "record_latency_s_p50" in snap


class TestCheckpointResume:
    def test_offsets_resume(self, iris_reader, tmp_path):
        records = _iris_records(96)
        cfg = _small_batch_config()

        env1 = StreamEnvironment(cfg)
        src1 = InMemorySource(records)
        sink1 = (
            env1.from_source(src1)
            .evaluate(iris_reader)
            .with_checkpointing(str(tmp_path / "ckpt"))
            .collect()
        )
        env1.execute(timeout=30.0)
        assert len(sink1.items) == 96

        # "restart": a new pipeline over the same source data restores the
        # committed offset and rescores nothing
        env2 = StreamEnvironment(cfg)
        src2 = InMemorySource(records)
        sink2 = (
            env2.from_source(src2)
            .evaluate(iris_reader)
            .with_checkpointing(str(tmp_path / "ckpt"))
            .collect()
        )
        env2.execute(timeout=30.0, restore=True)
        assert len(sink2.items) == 0  # everything was already committed

    def test_replay_boundary_uncommitted_suffix_exactly_once(
        self, iris_reader, tmp_path
    ):
        """ISSUE 12 satellite: the at-least-once replay boundary. A
        restart whose checkpoint trails the dispatched range (the
        SIGKILL-between-dispatch-and-commit shape; the process-kill
        twin lives in tests/test_faults.py) replays EXACTLY the
        uncommitted suffix once — never skips a record, never replays
        below the committed offset — and books the replay volume in
        records_replayed."""
        import json
        import time as _time

        from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager
        from flink_jpmml_tpu.runtime.engine import (
            Pipeline, StaticScorer,
        )
        from flink_jpmml_tpu.runtime.sinks import CollectSink
        from flink_jpmml_tpu.runtime.sources import InMemorySource

        records = _iris_records(100)
        cfg = _small_batch_config()
        model = iris_reader.load(batch_size=cfg.batch.size)

        def run(restore):
            sink = CollectSink()
            pipe = Pipeline(
                InMemorySource(records),
                StaticScorer(
                    model,
                    emit=lambda recs, preds: list(
                        zip(recs, preds)
                    ),
                ),
                sink,
                cfg,
                checkpoint=CheckpointManager(str(tmp_path / "ck")),
            )
            if restore:
                assert pipe.restore()
            pipe.run_until_exhausted(timeout=30.0)
            return pipe, sink

        pipe1, sink1 = run(restore=False)
        assert len(sink1.items) == 100
        assert pipe1.committed_offset == 100
        # forge the mid-kill shape: committed trails the dispatched
        # range (offsets 41..70 were in flight, never committed)
        _time.sleep(0.002)
        CheckpointManager(str(tmp_path / "ck")).save(
            {"source_offset": 40, "inflight_hi": 70, "scorer": {}}
        )
        pipe2, sink2 = run(restore=True)
        assert pipe2.committed_offset == 100
        # the uncommitted suffix replays exactly once per restart:
        # records 41..100 once more, 1..40 never again
        replayed = [r for r, _ in sink2.items]
        assert replayed == records[40:]
        snap = pipe2.metrics.struct_snapshot()["counters"]
        assert snap["records_replayed"] == 70 - 40
        # and the union over both incarnations has no gaps
        emitted = [r for r, _ in sink1.items] + replayed
        assert sorted(
            json.dumps(r, sort_keys=True) for r in emitted
        ) == sorted(
            json.dumps(r, sort_keys=True)
            for r in records + records[40:]
        )


class TestDynamicServing:
    def test_add_score_del(self, assets_dir):
        env = StreamEnvironment(_small_batch_config())
        ctrl = ControlSource()
        iris_path = str(assets_dir / "iris_lr.pmml")
        ctrl.push(AddMessage("iris", 1, iris_path, timestamp=1.0))

        events = [("iris", v) for v in _iris_records(20)]
        events += [("unknown-model", v) for v in _iris_records(5, seed=9)]
        sink = (
            env.from_collection(events)
            .with_control_stream(ctrl)
            .evaluate(ModelReader(iris_path))
            .collect()
        )
        env.execute(timeout=30.0)
        out = sink.items
        assert len(out) == 25
        served = [p for p, e in out if e[0] == "iris"]
        unserved = [p for p, e in out if e[0] == "unknown-model"]
        assert all(not p.is_empty for p in served)
        assert all(p.is_empty for p in unserved)  # totality, not failure

    def test_del_takes_effect_between_batches(self, assets_dir):
        from flink_jpmml_tpu.runtime.engine import Pipeline
        from flink_jpmml_tpu.runtime.sinks import CollectSink
        from flink_jpmml_tpu.serving.scorer import DynamicScorer

        iris_path = str(assets_dir / "iris_lr.pmml")
        ctrl = ControlSource()
        scorer = DynamicScorer(control=ctrl, batch_size=32)
        ctrl.push(AddMessage("iris", 1, iris_path, timestamp=1.0))

        vec = _iris_records(4)
        t1 = scorer.submit([("iris", v) for v in vec])
        out1 = scorer.finish(t1)
        assert all(not p.is_empty for p, _ in out1)

        ctrl.push(DelMessage("iris", 1, timestamp=2.0))
        t2 = scorer.submit([("iris", v) for v in vec])
        out2 = scorer.finish(t2)
        assert all(p.is_empty for p, _ in out2)

    def test_version_routing_latest_wins(self, assets_dir, tmp_path):
        from assets.generate import gen_iris_lr
        from flink_jpmml_tpu.serving.scorer import DynamicScorer

        # two versions with different coefficients (different seed)
        v1_path = str(assets_dir / "iris_lr.pmml")
        v2_path = gen_iris_lr(str(tmp_path), seed=99)
        ctrl = ControlSource()
        scorer = DynamicScorer(control=ctrl, batch_size=8)
        ctrl.push(AddMessage("iris", 1, v1_path, timestamp=1.0))
        ctrl.push(AddMessage("iris", 2, v2_path, timestamp=2.0))

        vec = _iris_records(4)
        out = scorer.finish(scorer.submit([("iris", v) for v in vec]))
        doc2 = parse_pmml_file(v2_path)
        for (p, _), v in zip(out, vec):
            o = evaluate(doc2, dict(zip(doc2.active_fields, v)))
            assert p.target.label == o.label  # v2 (latest) answered

    def test_registry_state_checkpoint_roundtrip(self, assets_dir):
        from flink_jpmml_tpu.serving.registry import ModelRegistry

        reg = ModelRegistry(batch_size=8)
        reg.apply(AddMessage("m", 1, str(assets_dir / "iris_lr.pmml"), 1.0))
        reg.apply(AddMessage("m", 2, str(assets_dir / "iris_lr.pmml"), 2.0))
        reg.apply(DelMessage("m", 1, 3.0))
        state = reg.state()

        reg2 = ModelRegistry(batch_size=8)
        reg2.restore(state)
        assert reg2.resolve("m") is not None
        assert reg2.resolve("m").version == 2
        assert reg2.resolve("m", 1) is None

    def test_bad_path_lanes_empty_stream_alive(self):
        from flink_jpmml_tpu.serving.scorer import DynamicScorer

        ctrl = ControlSource()
        scorer = DynamicScorer(control=ctrl, batch_size=8)
        ctrl.push(AddMessage("ghost", 1, "/nonexistent/m.pmml", timestamp=1.0))
        out = scorer.finish(scorer.submit([("ghost", [1.0, 2.0])]))
        assert out[0][0].is_empty


class TestManagers:
    def test_add_idempotent_del_unknown_noop(self):
        from flink_jpmml_tpu.serving import managers
        from flink_jpmml_tpu.models.core import ModelId

        meta, ch = managers.apply_message({}, AddMessage("m", 1, "/p", 1.0))
        assert ch and ModelId("m", 1) in meta
        meta2, ch2 = managers.apply_message(meta, AddMessage("m", 1, "/p", 2.0))
        assert not ch2 and meta2 == meta
        meta3, ch3 = managers.apply_message(meta, DelMessage("x", 9, 3.0))
        assert not ch3
        meta4, ch4 = managers.apply_message(meta, DelMessage("m", 1, 4.0))
        assert ch4 and not meta4


class TestQuantizedScorerPath:
    def test_static_scorer_uses_rank_wire_for_gbm(self, tmp_path):
        import numpy as np
        from assets.generate import gen_gbm
        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.pmml import parse_pmml_file
        from flink_jpmml_tpu.runtime.engine import StaticScorer

        doc = parse_pmml_file(gen_gbm(str(tmp_path), n_trees=20, depth=4,
                                      n_features=6))
        cm = compile_pmml(doc, batch_size=32)
        s_q = StaticScorer(cm)
        s_f = StaticScorer(cm, use_quantized=False)
        assert s_q._q is not None and s_f._q is None
        rng = np.random.default_rng(0)
        records = [
            {f"f{j}": float(v) for j, v in enumerate(row) if j % 5 != 3}
            for row in rng.normal(size=(17, 6))
        ]
        got = s_q.finish(s_q.submit(records))
        exp = s_f.finish(s_f.submit(records))
        assert len(got) == len(exp) == 17
        for a, b in zip(got, exp):
            assert abs(a.score.value - b.score.value) < 1e-3


class TestFaultInjectionRecovery:
    def test_pipeline_surfaces_fault_and_resumes_from_checkpoint(
        self, iris_reader, tmp_path
    ):
        """SURVEY.md §6 failure-detection row: the first attempt dies
        mid-stream on an injected fault; a fresh pipeline restores the
        committed source offset and finishes the stream (at-least-once)."""
        import numpy as np
        import pytest as _pytest

        from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager
        from flink_jpmml_tpu.runtime.engine import Pipeline, StaticScorer
        from flink_jpmml_tpu.runtime.sinks import CollectSink
        from flink_jpmml_tpu.runtime.sources import (
            FaultInjectionSource,
            InMemorySource,
        )
        from flink_jpmml_tpu.utils.config import BatchConfig, RuntimeConfig

        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.pmml import parse_pmml_file as _ppf

        cm = compile_pmml(_ppf(iris_reader.path))
        rng = np.random.default_rng(0)
        records = [
            {f: float(v) for f, v in zip(cm.active_fields, row)}
            for row in rng.normal(3.0, 2.0, size=(200, 4))
        ]
        cfg = RuntimeConfig(
            batch=BatchConfig(size=32, deadline_us=1000, queue_capacity=48),
            checkpoint_interval_s=0.0,  # checkpoint every batch
        )
        ckpt = CheckpointManager(str(tmp_path / "ckpt"))

        flaky = FaultInjectionSource(InMemorySource(records), fail_after=100)
        sink1 = CollectSink()
        p1 = Pipeline(flaky, StaticScorer(cm), sink1, cfg, checkpoint=ckpt)
        p1.start()
        with _pytest.raises(RuntimeError, match="injected fault"):
            deadline = 30.0
            import time as _time

            t0 = _time.monotonic()
            while _time.monotonic() - t0 < deadline:
                try:
                    p1.join(timeout=0.2)
                except RuntimeError:
                    raise
                if p1._error is not None:
                    p1.join()
                if not p1._ingest_thread.is_alive():
                    p1.stop()
                    p1.join()
                    break
            else:
                raise AssertionError("fault never surfaced")

        done_first = len(sink1.items)
        assert done_first < len(records)  # the fault cut the stream short

        # recovery: fresh pipeline, restore offset, finish the rest
        src2 = InMemorySource(records)
        sink2 = CollectSink()
        p2 = Pipeline(
            src2, StaticScorer(cm), sink2, cfg, checkpoint=ckpt
        )
        assert p2.restore()
        p2.run_until_exhausted(timeout=60.0)
        assert done_first + len(sink2.items) >= len(records)
        snap = p2.metrics.snapshot()
        assert "stage_readback_s" in snap  # stage timers active


class TestDeterministicDrain:
    """VERDICT r2 weak #3: run_until_exhausted must lose zero records at
    shutdown even when the scorer/sink is much slower than ingestion —
    no sleep-based settle windows."""

    def _compiled_iris(self, iris_reader):
        from flink_jpmml_tpu.compile import compile_pmml

        return compile_pmml(parse_pmml_file(iris_reader.path))

    def test_engine_slow_scorer_loses_nothing(self, iris_reader):
        from flink_jpmml_tpu.runtime.engine import Pipeline, StaticScorer
        from flink_jpmml_tpu.runtime.sinks import CollectSink
        from flink_jpmml_tpu.runtime.sources import InMemorySource

        cm = self._compiled_iris(iris_reader)

        class SlowScorer(StaticScorer):
            def finish(self, ticket):
                time.sleep(0.03)  # scorer ~10x slower than ingest
                return super().finish(ticket)

        n = 500
        records = _iris_records(n)
        sink = CollectSink()
        pipe = Pipeline(
            InMemorySource(records),
            SlowScorer(cm),
            sink,
            RuntimeConfig(batch=BatchConfig(size=32, deadline_us=500)),
        )
        pipe.run_until_exhausted(timeout=60.0)
        assert len(sink.items) == n
        assert pipe.committed_offset == n

    def test_multi_chunk_dispatch_aggregates_backed_up_ring(
        self, iris_reader
    ):
        """A backed-up ring ships several full batches in ONE dispatch
        (RPC amortization on high-RTT links); offsets stay contiguous,
        every record exactly once, and sinks may see n > batch_size."""
        import numpy as np

        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.pmml.interp import evaluate as _ev
        from flink_jpmml_tpu.runtime.block import (
            BlockPipeline,
            FiniteBlockSource,
        )

        doc = parse_pmml_file(iris_reader.path)
        cm = compile_pmml(doc, batch_size=64)
        rng = np.random.default_rng(2)
        N = 2048
        data = rng.normal(3, 2, size=(N, 4)).astype(np.float32)
        rows = []
        decoded = []
        pipe_box = {}

        def sink(out, n, first_off):
            rows.append((first_off, n))
            if len(decoded) < 2:  # golden parity through the aggregate
                decoded.append(
                    (first_off, pipe_box["p"].decode(out, n))
                )

        pipe = BlockPipeline(
            FiniteBlockSource(data, block_size=256),
            cm,
            sink,
            use_native=False,
            max_dispatch_chunks=4,
        )
        pipe_box["p"] = pipe
        pipe.run_until_exhausted(timeout=60.0)
        assert pipe.committed_offset == N
        expect = 0
        for off, n in rows:
            assert off == expect
            expect = off + n
        assert expect == N
        # the flooding finite source backs the ring up: at least one
        # dispatch must have aggregated beyond one batch
        assert any(n > 64 for _, n in rows), rows
        for first_off, preds in decoded:
            for i in (0, len(preds) - 1):
                rec = dict(zip(doc.active_fields, data[first_off + i]))
                assert preds[i].target.label == _ev(doc, rec).label

    def test_multi_chunk_dispatch_disabled_is_single_batch(
        self, iris_reader
    ):
        import numpy as np

        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.runtime.block import (
            BlockPipeline,
            FiniteBlockSource,
        )

        cm = compile_pmml(parse_pmml_file(iris_reader.path), batch_size=64)
        data = np.random.default_rng(3).normal(
            3, 2, size=(512, 4)
        ).astype(np.float32)
        rows = []
        pipe = BlockPipeline(
            FiniteBlockSource(data, block_size=128),
            cm,
            lambda out, n, off: rows.append(n),
            use_native=False,
            max_dispatch_chunks=1,
        )
        pipe.run_until_exhausted(timeout=60.0)
        assert sum(rows) == 512
        assert all(n <= 64 for n in rows), rows

    def test_block_slow_sink_loses_nothing(self, iris_reader):
        import numpy as np

        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.runtime.block import (
            BlockPipeline,
            FiniteBlockSource,
        )

        cm = compile_pmml(parse_pmml_file(iris_reader.path), batch_size=64)
        rng = np.random.default_rng(1)
        data = rng.normal(3, 2, size=(800, 4)).astype(np.float32)
        seen = {"n": 0}

        def slow_sink(out, n, first_off):
            time.sleep(0.02)
            seen["n"] += n

        pipe = BlockPipeline(
            FiniteBlockSource(data, block_size=100),
            cm,
            slow_sink,
            use_native=False,
        )
        pipe.run_until_exhausted(timeout=60.0)
        assert seen["n"] == 800
        assert pipe.committed_offset == 800
