"""Keyed per-key state plane (ISSUE 19): the open-addressed
device-resident table (runtime/state.py) + the fused gather/fold stage
(compile/statekernel.py) behind ``dispatch_quantized(state=...)``.

Pins, in order: host slot routing under adversarial hash collisions
(probe windows, LRU eviction that never steals a slot touched this
batch, scratch overflow), the exactly-once replay guard, the fold
columns against hand-computed ground truth, armed-vs-stateless score
parity, checkpoint payload/sidecar roundtrips, degraded-mesh migration
parity on the conftest 8-device virtual mesh, and the never-delivered
contract extended to state: a DLQ'd batch must never leave folds in
the table (rollback-to-snapshot semantics, deterministic with no
checkpoint pinned)."""

import numpy as np
import pytest

from flink_jpmml_tpu.parallel.partitioner import stable_hash
from flink_jpmml_tpu.runtime import state as state_mod
from flink_jpmml_tpu.runtime.state import (
    COL_COUNT,
    COL_DCOUNT,
    COL_LAST_T,
    COL_MAX,
    COL_MIN,
    COL_SUM,
    KeyedStateTable,
    StateSpec,
)
from flink_jpmml_tpu.utils.exceptions import InputValidationException
from flink_jpmml_tpu.utils.metrics import MetricsRegistry


def _table(capacity=16, probe=4, **kw):
    m = MetricsRegistry()
    return KeyedStateTable(
        StateSpec(capacity=capacity, probe=probe, **kw), metrics=m
    ), m


def _colliding_keys(capacity, base, n, start=0):
    """n distinct int keys whose stable hashes all land on probe base
    ``base`` of a ``capacity``-slot table (brute-force: the adversarial
    suite the open addressing must survive)."""
    out, k = [], start
    while len(out) < n:
        t = KeyedStateTable(StateSpec(capacity=capacity))
        h = int(t.hash_keys(np.array([k]))[0])
        if h % capacity == base:
            out.append(k)
        k += 1
    return out


class TestSlotRouting:
    def test_hit_reuses_slot(self):
        t, m = _table()
        kh = t.hash_keys(np.array([5, 9, 5]))
        s1, r1, _, w1 = t.assign_slots(kh, np.arange(3))
        assert s1[0] == s1[2] != s1[1]
        assert r1.all()  # every key fresh this batch
        assert (w1 > 0).all()
        s2, r2, _, _ = t.assign_slots(kh, np.arange(3, 6))
        assert np.array_equal(s1, s2)
        assert not r2.any()
        c = m.struct_snapshot()["counters"]
        assert c["state_inserts"] == 2
        assert c["state_hits"] == 3
        assert t.resident == 2
        assert t.applied_hi == 6

    def test_spec_validation(self):
        with pytest.raises(InputValidationException):
            StateSpec(capacity=1)
        with pytest.raises(InputValidationException):
            StateSpec(capacity=8, decay=1.0)
        with pytest.raises(InputValidationException):
            StateSpec(capacity=8, probe=0)

    def test_collisions_probe_to_distinct_slots(self):
        cap = 32
        keys = _colliding_keys(cap, base=3, n=4)
        t, m = _table(capacity=cap, probe=8)
        kh = t.hash_keys(np.array(keys))
        slots, reset, _, _ = t.assign_slots(kh, np.arange(4))
        assert reset.all()
        assert len(set(slots.tolist())) == 4, slots
        # every slot inside the probe window off the shared base
        assert all((int(s) - 3) % cap < 8 for s in slots)
        c = m.struct_snapshot()["counters"]
        assert c["state_collisions"] == 3  # all but one pending at p=0

    def test_eviction_lru_never_this_batch(self):
        cap = 32
        a, b, c = _colliding_keys(cap, base=7, n=3)
        t, m = _table(capacity=cap, probe=2)
        t.assign_slots(t.hash_keys(np.array([a, b])), np.arange(2))
        slot_a = int(t.assign_slots(
            t.hash_keys(np.array([a])), np.array([2])
        )[0][0])  # refresh A: B becomes the LRU of the window
        slots_b1, _, _, _ = t.assign_slots(
            t.hash_keys(np.array([b])), np.array([3])
        )
        t.assign_slots(t.hash_keys(np.array([a])), np.array([4]))
        sc, rc, _, _ = t.assign_slots(
            t.hash_keys(np.array([c])), np.array([5])
        )
        # C landed by evicting LRU B — never A (fresher), never scratch
        assert int(sc[0]) == int(slots_b1[0]) != slot_a
        assert rc.all()
        assert m.struct_snapshot()["counters"]["state_evictions"] == 1
        # B returns as a fresh insert: its state was evicted with it
        sb, rb, _, _ = t.assign_slots(
            t.hash_keys(np.array([b])), np.array([6])
        )
        assert rb.all()

    def test_window_overflow_bypasses_to_scratch(self):
        cap = 32
        keys = _colliding_keys(cap, base=11, n=3)
        t, m = _table(capacity=cap, probe=2)
        kh = t.hash_keys(np.array(keys))
        slots, _, _, _ = t.assign_slots(kh, np.arange(3))
        # two claim the window; the third may not evict a slot touched
        # THIS batch — it overflows to the scratch row
        assert sorted(slots.tolist())[:2] != [t.scratch, t.scratch]
        assert int(slots.max()) == t.scratch
        c = m.struct_snapshot()["counters"]
        assert c["state_overflow"] == 1
        assert c["state_evictions"] == 0

    def test_replay_below_skip_until_bypasses(self):
        t, m = _table()
        kh = t.hash_keys(np.array([1, 2, 3]))
        t.assign_slots(kh, np.arange(3))
        assert t.applied_hi == 3
        t.skip_until = 3
        s2, r2, _, w2 = t.assign_slots(kh, np.arange(3))
        assert (s2 == t.scratch).all()
        assert not r2.any()
        assert (w2 == 0).all()
        assert t.applied_hi == 3
        c = m.struct_snapshot()["counters"]
        assert c["state_bypass_records"] == 3
        # fresh offsets past the guard fold again
        s3, _, _, w3 = t.assign_slots(kh, np.arange(3, 6))
        assert (s3 != t.scratch).all()
        assert (w3 > 0).all()

    def test_bypass_context(self):
        """``bypass()`` is a CALL-SITE contract: armed dispatch paths
        check ``table.bypassed`` and score stateless — the table never
        gates ``assign_slots`` itself.  Assert the flag's scoping and
        nesting, and that it survives an exception in the window."""
        t, _ = _table()
        assert not t.bypassed
        with t.bypass():
            assert t.bypassed
            with t.bypass():  # recovery ladder inside poison bisection
                assert t.bypassed
            assert t.bypassed
        assert not t.bypassed
        with pytest.raises(RuntimeError):
            with t.bypass():
                raise RuntimeError("redispatch blew up")
        assert not t.bypassed

    def test_hash_matches_scalar_stable_hash(self):
        t, _ = _table()
        for k in (-128, -1, 0, 1, 7, 2**40, -(2**40)):
            assert int(t.hash_keys(np.array([k]))[0]) == (
                stable_hash(k) & 0xFFFFFFFF
            ), k


@pytest.fixture(scope="module")
def gbm(tmp_path_factory):
    from flink_jpmml_tpu.assets_gen import gen_gbm
    from flink_jpmml_tpu.compile import compile_pmml
    from flink_jpmml_tpu.pmml import parse_pmml_file

    tmp = tmp_path_factory.mktemp("state_gbm")
    path = gen_gbm(str(tmp), n_trees=5, depth=3, n_features=4)
    return compile_pmml(parse_pmml_file(path), batch_size=32)


def _batches(n_batches, keys, seed=11, B=32, feats=4):
    rng = np.random.default_rng(seed)
    X = rng.normal(0.0, 1.0, size=(n_batches * B, feats)).astype(
        np.float32
    )
    X[:, 0] = rng.integers(0, keys, size=n_batches * B).astype(
        np.float32
    )
    return [
        (X[i * B: (i + 1) * B], np.arange(i * B, (i + 1) * B))
        for i in range(n_batches)
    ]


class TestFusedFold:
    def test_armed_scores_match_stateless(self, gbm):
        import jax

        from flink_jpmml_tpu.runtime.pipeline import dispatch_quantized

        q = gbm.quantized_scorer()
        t, _ = _table(capacity=64)
        (X, offs) = _batches(1, keys=8)[0]
        plain = np.asarray(dispatch_quantized(q, X))
        res = dispatch_quantized(q, X, state=t, offsets=offs)
        assert state_mod.is_state_output(res)
        out, derived = state_mod.split_output(res)
        jax.block_until_ready(out)
        assert np.array_equal(np.asarray(out), plain)
        d = np.asarray(derived)
        assert d.shape == (32, len(state_mod.DERIVED_FIELDS))
        # derived features gather PRE-update: a key's first record of
        # the stream sees count 0
        first_rows = [
            int(np.flatnonzero(X[:, 0] == k)[0])
            for k in np.unique(X[:, 0])
        ]
        assert all(d[r, 0] == 0.0 for r in first_rows)

    def test_fold_columns_ground_truth(self, gbm):
        import jax

        from flink_jpmml_tpu.runtime.pipeline import dispatch_quantized

        q = gbm.quantized_scorer()
        t, m = _table(capacity=64)
        batches = _batches(2, keys=1, seed=3)  # a single key: col 0
        for X, offs in batches:
            X[:, 0] = 7.0
        scores = np.concatenate([
            np.asarray(dispatch_quantized(q, X)).ravel()
            for X, _ in batches
        ])
        for X, offs in batches:
            dispatch_quantized(q, X, state=t, offsets=offs)
        jax.block_until_ready(t.values)
        kh = int(t.hash_keys(np.array([7]))[0])
        slot = int(np.flatnonzero(t._occ & (t._keys == kh))[0])
        v = np.asarray(t.values)[slot]
        assert v[COL_COUNT] == 64.0
        # offsets 0..63 sit inside stride 0: every product-form weight
        # is exactly 1, so the decayed count equals the plain count
        assert v[COL_DCOUNT] == 64.0
        assert v[COL_LAST_T] == 0.0
        assert v[COL_MIN] == scores.min()
        assert v[COL_MAX] == scores.max()
        np.testing.assert_allclose(
            v[COL_SUM], scores.sum(dtype=np.float64), rtol=1e-5
        )
        # scratch row stays zero: padding/bypass can never leak state
        assert not np.asarray(t.values)[t.scratch].any()
        assert m.struct_snapshot()["counters"]["state_records"] == 64

    def test_donate_matches_copy_fold(self, gbm):
        import jax

        from flink_jpmml_tpu.runtime.pipeline import dispatch_quantized

        q = gbm.quantized_scorer()
        batches = _batches(3, keys=16, seed=5)
        tables = []
        for donate in (False, True):
            t, _ = _table(capacity=64)
            for X, offs in batches:
                dispatch_quantized(
                    q, X.copy(), state=t, offsets=offs, donate=donate,
                )
            jax.block_until_ready(t.values)
            tables.append(np.asarray(t.values).copy())
        assert tables[0].tobytes() == tables[1].tobytes()


class TestCheckpointRoundtrip:
    def _folded(self, gbm, capacity=64):
        import jax

        from flink_jpmml_tpu.runtime.pipeline import dispatch_quantized

        q = gbm.quantized_scorer()
        t, _ = _table(capacity=capacity)
        for X, offs in _batches(2, keys=12, seed=9):
            dispatch_quantized(q, X, state=t, offsets=offs)
        jax.block_until_ready(t.values)
        return t

    def test_payload_roundtrip_byte_exact(self, gbm):
        t = self._folded(gbm)
        p = t.to_payload()
        t2, _ = _table(capacity=64)
        assert t2.from_payload(p)
        assert (
            np.asarray(t2.values).tobytes()
            == np.asarray(t.values).tobytes()
        )
        assert np.array_equal(t2._keys, t._keys)
        assert np.array_equal(t2._occ, t._occ)
        assert t2.resident == t.resident
        # restore arms the exactly-once replay guard
        assert t2.skip_until == t.applied_hi == 64

    def test_sidecar_roundtrip_byte_exact(self, gbm, tmp_path):
        t = self._folded(gbm)
        name = t.save_sidecar(str(tmp_path))
        assert name is not None and (tmp_path / name).exists()
        t2, _ = _table(capacity=64)
        assert t2.restore_sidecar(str(tmp_path), name)
        assert (
            np.asarray(t2.values).tobytes()
            == np.asarray(t.values).tobytes()
        )
        assert t2.skip_until == t.applied_hi
        # a second fold on the restored table must keep working
        from flink_jpmml_tpu.runtime.pipeline import dispatch_quantized

        q = gbm.quantized_scorer()
        X, offs = _batches(3, keys=12, seed=9)[2]
        dispatch_quantized(q, X, state=t2, offsets=offs)

    def test_capacity_mismatch_refused(self, gbm):
        t = self._folded(gbm)
        t2, _ = _table(capacity=128)
        assert not t2.from_payload(t.to_payload())


class TestMeshMigration:
    def test_degraded_migration_preserves_every_key(self, gbm):
        import jax

        from flink_jpmml_tpu.parallel.mesh import make_mesh
        from flink_jpmml_tpu.runtime.pipeline import dispatch_quantized
        from flink_jpmml_tpu.utils.config import MeshConfig

        q = gbm.quantized_scorer()
        t, _ = _table(capacity=256)
        for X, offs in _batches(2, keys=40, seed=21):
            dispatch_quantized(q, X, state=t, offsets=offs)
        jax.block_until_ready(t.values)
        before = np.asarray(t.values).copy()
        resident = t.resident
        t.shard(make_mesh(MeshConfig(data=4, model=2)))
        # chip loss: the rebuilt mesh spans half the data axis — every
        # surviving key's row re-places byte-identically (slot = hash %
        # capacity is mesh-independent)
        t.migrate(
            make_mesh(MeshConfig(data=2, model=2), allow_subset=True)
        )
        assert np.asarray(t.values).tobytes() == before.tobytes()
        assert t.resident == resident
        # and the fold keeps running on the migrated placement
        X, offs = _batches(3, keys=40, seed=21)[2]
        dispatch_quantized(q, X, state=t, offsets=offs)
        jax.block_until_ready(t.values)
        after = np.asarray(t.values)
        assert after[:, COL_COUNT].sum() > before[:, COL_COUNT].sum()


class TestNeverDelivered:
    def test_dlq_batch_never_folds(self, gbm, tmp_path, monkeypatch):
        """The PR 8/12 never-delivered contract extended to state: the
        poisoned record is quarantined to the DLQ, never delivered, and
        provably never folded (its unique key is absent from the
        table).  A rollback sheds the in-flight fold window back to the
        last snapshot (here the initial EMPTY table) and suspect-mode
        probation keeps trailing batches stateless, so we assert fold
        INVARIANTS — per-key ≤ stream ground truth, whole armed
        batches only — not an exact batch suffix, which would pin the
        probation-window tuning into the contract."""
        import jax

        from flink_jpmml_tpu.runtime import faults
        from flink_jpmml_tpu.runtime.block import (
            BlockPipeline, FiniteBlockSource,
        )
        from flink_jpmml_tpu.runtime.dlq import DeadLetterQueue

        monkeypatch.setenv("FJT_RETRY_BASE_S", "0.01")
        B, blocks, keys = 32, 5, 6
        rng = np.random.default_rng(17)
        data = rng.normal(0.0, 1.0, size=(B * blocks, 4)).astype(
            np.float32
        )
        data[:, 0] = rng.integers(0, keys, size=B * blocks).astype(
            np.float32
        )
        poison = 70  # batch 2 ([64, 96)): batches 0-1 roll back
        # the quarantined record gets a key NO other record has, so
        # "never folded" is checkable as key-absence from the table
        data[poison, 0] = 99.0
        seen = []
        m = MetricsRegistry()
        dlq = DeadLetterQueue(str(tmp_path / "dlq"), metrics=m)
        assert faults.install_from_env(
            f"poison_record:offset={poison}"
        )
        try:
            pipe = BlockPipeline(
                FiniteBlockSource(data, block_size=B), gbm,
                lambda out, n, first_off: seen.append((first_off, n)),
                metrics=m,
                use_native=False,
                in_flight=1,
                dlq=dlq,
                state=StateSpec(capacity=64, key_col=0),
            )
            pipe.run_until_exhausted(timeout=60.0)
        finally:
            faults.clear()
        assert sorted(set(dlq.offsets())) == [poison]
        covered = np.zeros(B * blocks, np.int64)
        for off, n in seen:
            covered[off: off + n] += 1
        assert sorted(np.flatnonzero(covered == 0).tolist()) == [poison]
        t = pipe._state
        jax.block_until_ready(t.values)
        folded_keys = t._keys[t._occ]
        vals = np.asarray(t.values)[: t.capacity]
        folded = dict(zip(
            folded_keys.tolist(),
            vals[t._occ, COL_COUNT].tolist(),
        ))
        # the quarantined record's key never reached the table
        poison_hash = int(t.hash_keys(np.array([99]))[0])
        assert poison_hash not in folded
        # per-key no-over-fold vs. stream ground truth
        kh = t.hash_keys(data[:, 0].astype(np.int64))
        uk, n = np.unique(kh, return_counts=True)
        true_counts = dict(zip(uk.tolist(), n.tolist()))
        for k, cnt in folded.items():
            assert cnt <= true_counts[k], (k, cnt, true_counts[k])
        # folds land as whole armed batches: at least one batch made
        # it through after recovery, and never a partial batch
        total = sum(folded.values())
        assert total >= B and total % B == 0, folded
        c = m.struct_snapshot()["counters"]
        assert c["state_rollbacks"] >= 1
