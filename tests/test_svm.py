"""SupportVectorMachineModel family: kernels, OneAgainstOne voting,
OneAgainstAll, regression, sparse vectors — compiled vs oracle vs
hand-computed decision functions."""

import math

import numpy as np
import pytest

from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.pmml import parse_pmml
from flink_jpmml_tpu.pmml.interp import evaluate


def _svm_xml(kernel, machines, function="classification", method=None,
             extra_attrs=""):
    m_attr = (
        f' classificationMethod="{method}"' if method is not None else ""
    )
    return f"""<PMML version="4.3"><DataDictionary>
      <DataField name="x1" optype="continuous" dataType="double"/>
      <DataField name="x2" optype="continuous" dataType="double"/>
      <DataField name="y" optype="categorical" dataType="string">
        <Value value="A"/><Value value="B"/><Value value="C"/></DataField>
      </DataDictionary>
      <SupportVectorMachineModel functionName="{function}"{m_attr}
          {extra_attrs}>
      <MiningSchema><MiningField name="y" usageType="target"/>
        <MiningField name="x1"/><MiningField name="x2"/></MiningSchema>
      {kernel}
      <VectorDictionary numberOfVectors="3">
        <VectorFields numberOfFields="2">
          <FieldRef field="x1"/><FieldRef field="x2"/></VectorFields>
        <VectorInstance id="v1"><Array n="2" type="real">1 0</Array>
        </VectorInstance>
        <VectorInstance id="v2"><Array n="2" type="real">0 1</Array>
        </VectorInstance>
        <VectorInstance id="v3">
          <REAL-SparseArray n="2"><Indices>1 2</Indices>
            <REAL-Entries>-1 -1</REAL-Entries></REAL-SparseArray>
        </VectorInstance>
      </VectorDictionary>
      {machines}
      </SupportVectorMachineModel></PMML>"""


_PAIR_MACHINES = """
  <SupportVectorMachine targetCategory="A" alternateTargetCategory="B">
    <SupportVectors numberOfSupportVectors="2">
      <SupportVector vectorId="v1"/><SupportVector vectorId="v2"/>
    </SupportVectors>
    <Coefficients absoluteValue="0.1">
      <Coefficient value="1.0"/><Coefficient value="-0.5"/>
    </Coefficients>
  </SupportVectorMachine>
  <SupportVectorMachine targetCategory="A" alternateTargetCategory="C">
    <SupportVectors numberOfSupportVectors="2">
      <SupportVector vectorId="v1"/><SupportVector vectorId="v3"/>
    </SupportVectors>
    <Coefficients absoluteValue="-0.2">
      <Coefficient value="0.7"/><Coefficient value="0.3"/>
    </Coefficients>
  </SupportVectorMachine>
  <SupportVectorMachine targetCategory="B" alternateTargetCategory="C">
    <SupportVectors numberOfSupportVectors="2">
      <SupportVector vectorId="v2"/><SupportVector vectorId="v3"/>
    </SupportVectors>
    <Coefficients absoluteValue="0.0">
      <Coefficient value="-0.8"/><Coefficient value="0.6"/>
    </Coefficients>
  </SupportVectorMachine>"""

KERNELS = {
    "linear": ("<LinearKernelType/>", lambda d, n2: d),
    "polynomial": (
        '<PolynomialKernelType gamma="0.5" coef0="1" degree="3"/>',
        lambda d, n2: (0.5 * d + 1.0) ** 3,
    ),
    "sigmoid": (
        '<SigmoidKernelType gamma="0.7" coef0="-0.2"/>',
        lambda d, n2: math.tanh(0.7 * d - 0.2),
    ),
    "radialBasis": (
        '<RadialBasisKernelType gamma="0.4"/>',
        lambda d, n2: math.exp(-0.4 * n2),
    ),
}

SVS = {"v1": (1.0, 0.0), "v2": (0.0, 1.0), "v3": (-1.0, -1.0)}


def _kval(kname, x, s):
    d = x[0] * s[0] + x[1] * s[1]
    n2 = (x[0] - s[0]) ** 2 + (x[1] - s[1]) ** 2
    return KERNELS[kname][1](d, n2)


class TestSvmKernelsVoting:
    @pytest.mark.parametrize("kname", list(KERNELS))
    def test_one_against_one_parity(self, kname):
        doc = parse_pmml(_svm_xml(KERNELS[kname][0], _PAIR_MACHINES))
        cm = compile_pmml(doc)
        rng = np.random.default_rng(0)
        recs = [
            {"x1": float(a), "x2": float(b)}
            for a, b in rng.normal(0, 1.5, size=(150, 2))
        ]
        for rec, p in zip(recs, cm.score_records(recs)):
            o = evaluate(doc, rec)
            assert not p.is_empty
            assert p.target.label == o.label, (kname, rec)
            assert p.score.value == pytest.approx(o.value, rel=1e-4), rec

    @pytest.mark.parametrize("kname", list(KERNELS))
    def test_hand_computed_decision(self, kname):
        doc = parse_pmml(_svm_xml(KERNELS[kname][0], _PAIR_MACHINES))
        x = (0.4, -0.9)
        # machine AB: f = 1.0·K(v1) − 0.5·K(v2) + 0.1
        f_ab = (
            1.0 * _kval(kname, x, SVS["v1"])
            - 0.5 * _kval(kname, x, SVS["v2"])
            + 0.1
        )
        f_ac = (
            0.7 * _kval(kname, x, SVS["v1"])
            + 0.3 * _kval(kname, x, SVS["v3"])
            - 0.2
        )
        f_bc = (
            -0.8 * _kval(kname, x, SVS["v2"])
            + 0.6 * _kval(kname, x, SVS["v3"])
        )
        votes = {"A": 0, "B": 0, "C": 0}
        votes["A" if f_ab < 0 else "B"] += 1
        votes["A" if f_ac < 0 else "C"] += 1
        votes["B" if f_bc < 0 else "C"] += 1
        want = max(("A", "B", "C"), key=lambda c: votes[c])
        o = evaluate(doc, {"x1": x[0], "x2": x[1]})
        assert o.label == want, (kname, votes)

    def test_one_against_all(self):
        machines = """
          <SupportVectorMachine targetCategory="A">
            <SupportVectors numberOfSupportVectors="1">
              <SupportVector vectorId="v1"/></SupportVectors>
            <Coefficients absoluteValue="0.0">
              <Coefficient value="1.0"/></Coefficients>
          </SupportVectorMachine>
          <SupportVectorMachine targetCategory="B">
            <SupportVectors numberOfSupportVectors="1">
              <SupportVector vectorId="v2"/></SupportVectors>
            <Coefficients absoluteValue="0.0">
              <Coefficient value="1.0"/></Coefficients>
          </SupportVectorMachine>
        """
        doc = parse_pmml(_svm_xml(
            "<LinearKernelType/>", machines, method="OneAgainstAll"
        ))
        cm = compile_pmml(doc)
        rng = np.random.default_rng(1)
        recs = [
            {"x1": float(a), "x2": float(b)}
            for a, b in rng.normal(0, 2, size=(100, 2))
        ]
        for rec, p in zip(recs, cm.score_records(recs)):
            o = evaluate(doc, rec)
            assert p.target.label == o.label, rec
        # smallest decision value wins: x=(5,0) → f_A=5, f_B=0 → B
        assert evaluate(doc, {"x1": 5.0, "x2": 0.0}).label == "B"

    def test_regression_svm(self):
        machines = """
          <SupportVectorMachine>
            <SupportVectors numberOfSupportVectors="3">
              <SupportVector vectorId="v1"/><SupportVector vectorId="v2"/>
              <SupportVector vectorId="v3"/></SupportVectors>
            <Coefficients absoluteValue="0.25">
              <Coefficient value="1.5"/><Coefficient value="-2.0"/>
              <Coefficient value="0.5"/></Coefficients>
          </SupportVectorMachine>
        """
        doc = parse_pmml(_svm_xml(
            '<RadialBasisKernelType gamma="0.3"/>', machines,
            function="regression",
        ))
        cm = compile_pmml(doc)
        x = (0.2, 0.7)
        want = 0.25 + sum(
            a * math.exp(
                -0.3 * ((x[0] - s[0]) ** 2 + (x[1] - s[1]) ** 2)
            )
            for a, s in zip(
                (1.5, -2.0, 0.5), (SVS["v1"], SVS["v2"], SVS["v3"])
            )
        )
        o = evaluate(doc, {"x1": x[0], "x2": x[1]})
        p = cm.score_records([{"x1": x[0], "x2": x[1]}])[0]
        assert o.value == pytest.approx(want, rel=1e-9)
        assert p.score.value == pytest.approx(want, rel=1e-5)

    def test_missing_vector_field_empty_lane(self):
        doc = parse_pmml(_svm_xml("<LinearKernelType/>", _PAIR_MACHINES))
        cm = compile_pmml(doc)
        preds = cm.score_records([{"x1": 1.0, "x2": 1.0}, {"x1": 1.0}])
        assert [p.is_empty for p in preds] == [False, True]
        assert evaluate(doc, {"x1": 1.0}).is_missing

    def test_machine_threshold_override(self):
        machines = _PAIR_MACHINES.replace(
            '<SupportVectorMachine targetCategory="A" '
            'alternateTargetCategory="B">',
            '<SupportVectorMachine targetCategory="A" '
            'alternateTargetCategory="B" threshold="0.5">',
            1,
        )
        doc = parse_pmml(_svm_xml(
            "<LinearKernelType/>", machines, extra_attrs='threshold="0.1"'
        ))
        cm = compile_pmml(doc)
        rng = np.random.default_rng(2)
        recs = [
            {"x1": float(a), "x2": float(b)}
            for a, b in rng.normal(0, 1, size=(80, 2))
        ]
        for rec, p in zip(recs, cm.score_records(recs)):
            o = evaluate(doc, rec)
            assert p.target.label == o.label, rec


class TestReviewRegressions:
    def test_power_link_negative_eta_nan_both_paths(self):
        from tests.test_glm_bayes import GLM

        xml = GLM.format(
            model_type="generalizedLinear",
            link_attr='linkFunction="power" linkParameter="2"',
        )
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        rec = {"x1": -5.0, "x2": 0.0, "color": "blue"}  # eta = 0.5-10 < 0
        o = evaluate(doc, rec)
        assert not isinstance(o.value, complex)
        assert o.value != o.value  # NaN
        p = cm.score_records([rec])[0]
        # NaN value collapses identically on the decode side
        assert p.is_empty == (o.value != o.value) or p.score.value != p.score.value

    def test_inverse_link_zero_eta_inf_not_crash(self):
        from tests.test_glm_bayes import GLM

        xml = GLM.format(
            model_type="generalizedLinear", link_attr='linkFunction="inverse"'
        ).replace('<PCell parameterName="p0" beta="0.5"/>',
                  '<PCell parameterName="p0" beta="0.0"/>')
        doc = parse_pmml(xml)
        o = evaluate(doc, {"x1": 0.0, "x2": 0.0, "color": "blue"})
        assert o.value == math.inf  # no ZeroDivisionError

    def test_one_against_one_missing_alternate_typed_error(self):
        from flink_jpmml_tpu.utils.exceptions import (
            ModelCompilationException,
        )

        machines = _PAIR_MACHINES.replace(
            ' alternateTargetCategory="B"', "", 1
        )
        doc = parse_pmml(_svm_xml("<LinearKernelType/>", machines))
        with pytest.raises(ModelCompilationException, match="OneAgainstOne"):
            compile_pmml(doc)
        with pytest.raises(ModelCompilationException, match="OneAgainstOne"):
            evaluate(doc, {"x1": 1.0, "x2": 1.0})

    def test_unknown_pcell_parameter_typed_error_both_paths(self):
        from flink_jpmml_tpu.utils.exceptions import (
            ModelCompilationException,
        )
        from tests.test_glm_bayes import GLM

        xml = GLM.format(model_type="generalLinear", link_attr="").replace(
            '<PCell parameterName="p1" beta="2.0"/>',
            '<PCell parameterName="typo" beta="2.0"/>',
        )
        doc = parse_pmml(xml)
        with pytest.raises(ModelCompilationException, match="typo"):
            compile_pmml(doc)
        with pytest.raises(ModelCompilationException, match="typo"):
            evaluate(doc, {"x1": 1.0, "x2": 1.0, "color": "red"})
