"""Multi-tenant packed scoring (ISSUE 17): the zoo's acceptance pins.

- **Byte-identical packed-vs-solo.** The same interleaved multi-tenant
  event stream through a ``zoo=True`` DynamicScorer and a packing-off
  twin must produce bit-equal predictions per (tenant, record) — across
  NaN lanes, ±inf cells, missing-key masks, mining-schema
  ``missingValueReplacement``, and a pack mixing uint8 and uint16
  wires in one shared buffer.
- **Eviction / re-admit identity.** Under a starvation-level
  ``FJT_ZOO_BYTES`` cap the LRU evicts packs between rounds; replaying
  the identical round must reproduce identical bytes, with
  ``zoo_evictions`` and ``warm_pool_hits`` proving the churn happened.
- **Layout invalidation by model-SET hash** (the autotune satellite):
  a tenant add/remove changes ``model_set_hash`` and therefore misses
  the adopted plan; restoring the set restores the cached winner.
- **Fairness quota.** ``FJT_TENANT_QUOTA_FRAC`` sheds a hog tenant's
  excess rows as explicit empties (``tenant_shed_records{model=*}``)
  without touching its neighbours.
- **Cold-start accounting** (the registry satellite): every full
  parse+compile+jit lands in ``cold_start_s``; ``resolve_warm`` books
  ``warm_pool_hits`` / ``warm_pool_misses``.
"""

import struct
import time

import numpy as np
import pytest

from flink_jpmml_tpu.models.control import AddMessage
from flink_jpmml_tpu.models.core import ModelId
from flink_jpmml_tpu.runtime.sources import ControlSource
from flink_jpmml_tpu.serving.scorer import DynamicScorer
from flink_jpmml_tpu.utils.metrics import MetricsRegistry

BATCH = 32


# ---------------------------------------------------------------------------
# Fixtures: a heterogeneous tenant mix (tree counts, field spaces,
# wire dtypes, missing-value semantics)
# ---------------------------------------------------------------------------

MVR_XML = """<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3">
  <Header/>
  <DataDictionary numberOfFields="3">
    <DataField name="y" optype="continuous" dataType="double"/>
    <DataField name="a" optype="continuous" dataType="double"/>
    <DataField name="b" optype="continuous" dataType="double"/>
  </DataDictionary>
  <TreeModel functionName="regression" missingValueStrategy="defaultChild"
             splitCharacteristic="binarySplit">
    <MiningSchema>
      <MiningField name="y" usageType="target"/>
      <MiningField name="a" missingValueReplacement="0.25"/>
      <MiningField name="b"/>
    </MiningSchema>
    <Node id="0" defaultChild="1"><True/>
      <Node id="1" score="1.5">
        <SimplePredicate field="a" operator="lessOrEqual" value="0.1"/>
      </Node>
      <Node id="2" score="-2.0">
        <SimplePredicate field="a" operator="greaterThan" value="0.1"/>
      </Node>
    </Node>
  </TreeModel>
</PMML>"""


def _stump_forest_xml(n_a=300, n_b=5):
    """Depth-1 stump sum-forest with >254 distinct thresholds on ``a``
    → the uint16 wire (the mixed-width pack member)."""
    segs = []
    i = 0
    for field, n in (("a", n_a), ("b", n_b)):
        for k in range(n):
            thr = round(-3.0 + 6.0 * (k + 1) / (n + 1), 6)
            i += 1
            segs.append(f"""
      <Segment><True/>
        <TreeModel functionName="regression"
                   missingValueStrategy="defaultChild"
                   splitCharacteristic="binarySplit">
          <MiningSchema><MiningField name="y" usageType="target"/>
            <MiningField name="a"/><MiningField name="b"/></MiningSchema>
          <Node id="r" defaultChild="l"><True/>
            <Node id="l" score="{0.01 * i}">
              <SimplePredicate field="{field}" operator="lessOrEqual"
                               value="{thr}"/></Node>
            <Node id="g" score="{-0.01 * i}">
              <SimplePredicate field="{field}" operator="greaterThan"
                               value="{thr}"/></Node>
          </Node>
        </TreeModel>
      </Segment>""")
    return f"""<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3">
  <Header/>
  <DataDictionary numberOfFields="3">
    <DataField name="y" optype="continuous" dataType="double"/>
    <DataField name="a" optype="continuous" dataType="double"/>
    <DataField name="b" optype="continuous" dataType="double"/>
  </DataDictionary>
  <MiningModel functionName="regression">
    <MiningSchema><MiningField name="y" usageType="target"/>
      <MiningField name="a"/><MiningField name="b"/></MiningSchema>
    <Segmentation multipleModelMethod="sum">{"".join(segs)}
    </Segmentation>
  </MiningModel>
</PMML>"""


def _tenant_docs(tmp_path):
    """name -> (path, fields): two GBM shapes, an MVR doc, a uint16
    stump forest — four tenants, three field spaces, two wire dtypes."""
    from flink_jpmml_tpu.assets_gen import gen_gbm

    g0 = gen_gbm(str(tmp_path), n_trees=3, depth=3, n_features=4,
                 seed=7, name="zg0")
    g1 = gen_gbm(str(tmp_path), n_trees=5, depth=2, n_features=4,
                 seed=8, name="zg1")
    mvr = tmp_path / "mvr.pmml"
    mvr.write_text(MVR_XML)
    wide = tmp_path / "wide.pmml"
    wide.write_text(_stump_forest_xml())
    gf = [f"f{j}" for j in range(4)]
    return {
        "gbm0": (g0, gf),
        "gbm1": (g1, gf),
        "mvr": (str(mvr), ["a", "b"]),
        "wide": (str(wide), ["a", "b"]),
    }


def _build(docs, zoo, batch=BATCH, timeout_s=300.0):
    ctrl = ControlSource()
    sc = DynamicScorer(control=ctrl, batch_size=batch,
                       auto_rollout=False, zoo=zoo)
    for name, (path, _) in docs.items():
        ctrl.push(AddMessage(name, 1, path, timestamp=time.time()))
    sc._drain_control()
    deadline = time.monotonic() + timeout_s
    for name in docs:
        mid = ModelId(name, 1)
        while sc.registry.model_if_warm(mid) is None:
            err = sc.registry.warm_error(mid)
            assert err is None, f"{name} warm failed: {err!r}"
            assert time.monotonic() < deadline, f"{name} never warmed"
            time.sleep(0.01)
    return sc


def _events(docs, rows=BATCH, seed=5):
    """One interleaved multi-tenant submit list with hostile lanes:
    NaN, +inf, -inf, and missing keys (the mask — and for the MVR
    tenant, the replacement path)."""
    rng = np.random.default_rng(seed)
    ev = []
    for t, (name, (_, fields)) in enumerate(docs.items()):
        for i in range(rows):
            vals = rng.normal(0.0, 1.5, size=len(fields))
            rec = dict(zip(fields, vals.tolist()))
            k = i % 5
            if k == 1:
                rec[fields[i % len(fields)]] = float("nan")
            elif k == 2:
                rec[fields[i % len(fields)]] = float("inf")
            elif k == 3:
                rec[fields[i % len(fields)]] = float("-inf")
            elif k == 4:
                del rec[fields[i % len(fields)]]  # mask / MVR lane
            rec["_key"] = f"{name}-{i}"
            ev.append((name, rec))
    # interleave tenants so every pack dispatch mixes them
    by_t = [ev[t * rows:(t + 1) * rows] for t in range(len(docs))]
    return [e for row in zip(*by_t) for e in row]


def _sig(p):
    """Bit-exact identity signature for one prediction."""
    if p.is_empty:
        return b"empty"
    t = p.target
    return (struct.pack("<d", float(p.score.value)),
            None if t is None else repr(t))


def _run(sc, ev):
    return [_sig(p) for p, _ in sc.finish(sc.submit(ev))]


# ---------------------------------------------------------------------------
# Packed-vs-solo byte identity
# ---------------------------------------------------------------------------

class TestPackedSoloParity:
    def test_byte_identity_hostile_lanes_mixed_wires(self, tmp_path):
        docs = _tenant_docs(tmp_path)
        sc_zoo = _build(docs, zoo=True)
        sc_solo = _build(docs, zoo=False)
        for rnd in range(3):
            ev = _events(docs, seed=5 + rnd)
            got = _run(sc_zoo, ev)
            want = _run(sc_solo, ev)
            assert got == want, f"packed-vs-solo divergence, round {rnd}"
        counters = sc_zoo.metrics.struct_snapshot()["counters"]
        assert counters.get("pack_dispatches", 0) > 0, (
            "zoo never packed — the parity above proved nothing"
        )
        # a delivered (non-empty) lane exists for every tenant: the
        # hostile lanes above must not have emptied a whole tenant
        for name in docs:
            n = counters.get(f'tenant_records{{model="{name}_1"}}', 0)
            assert n > 0, f"tenant {name} delivered no records"

    def test_pack_mixes_uint8_and_uint16_wires(self, tmp_path):
        docs = _tenant_docs(tmp_path)
        sc = _build(docs, zoo=True)
        _run(sc, _events(docs))
        packs_resident = list(sc._zoo._resident.values())
        assert packs_resident, "no resident pack after a packed round"
        dtypes = set()
        for pk in packs_resident:
            for info in pk._infos:
                dtypes.add(np.dtype(info["dtype"]).name)
        assert "uint16" in dtypes, "uint16 member never packed"
        assert "uint8" in dtypes, "uint8 member never packed"
        widened = [pk for pk in packs_resident
                   if pk.in_dtype is np.uint16
                   and any(i["dtype"] is np.uint8 for i in pk._infos)]
        assert widened, (
            "no pack actually shares a widened uint16 buffer across "
            "mixed-width members — the exact-narrowing path is untested"
        )


# ---------------------------------------------------------------------------
# Eviction / re-admit identity
# ---------------------------------------------------------------------------

class TestEvictionReadmit:
    def test_identity_across_eviction_churn(self, tmp_path, monkeypatch):
        monkeypatch.setenv("FJT_PACK_MAX", "2")
        monkeypatch.setenv("FJT_ZOO_BYTES", "1")  # nothing stays resident
        monkeypatch.setenv("FJT_AUTOTUNE_DISABLE", "1")
        docs = _tenant_docs(tmp_path)
        sc_zoo = _build(docs, zoo=True)
        sc_solo = _build(docs, zoo=False)
        ev = _events(docs, seed=9)
        want = _run(sc_solo, ev)
        first = _run(sc_zoo, ev)
        again = _run(sc_zoo, ev)  # replay after the LRU churned
        assert first == want
        assert again == want, "re-admitted pack broke byte identity"
        counters = sc_zoo.metrics.struct_snapshot()["counters"]
        assert counters.get("pack_dispatches", 0) > 0
        assert counters.get("zoo_evictions", 0) > 0, (
            "byte cap of 1 evicted nothing — the churn never happened"
        )
        assert counters.get("warm_pool_hits", 0) > 0, (
            "re-admit never hit the warm pool — every round paid a "
            "cold rebuild"
        )


# ---------------------------------------------------------------------------
# Layout invalidation: the model-SET hash (autotune satellite)
# ---------------------------------------------------------------------------

def _meta(trees, leaves=8, fields=4, dtype_rank=1.0):
    return {
        "trees": float(trees), "splits": float(trees * (leaves - 1)),
        "leaves": float(leaves), "fields": float(fields),
        "batch": float(BATCH), "dtype_rank": float(dtype_rank),
        "classification": 0.0,
    }


class TestPlanSetHashInvalidation:
    def test_set_hash_is_order_free_and_multiset_sensitive(self):
        from flink_jpmml_tpu.compile.packs import model_set_hash

        a = model_set_hash(["h1", "h2", "h3"])
        assert a == model_set_hash(["h3", "h1", "h2"])
        assert a != model_set_hash(["h1", "h2"])
        assert a != model_set_hash(["h1", "h2", "h3", "h3"]), (
            "two tenants sharing one document must change the set hash"
        )

    def test_tenant_add_remove_invalidates_adopted_plan(self):
        from flink_jpmml_tpu.compile import autotune

        metas4 = {f"m{i:02d}": _meta(3 + i) for i in range(4)}
        plan1 = autotune.ensure_pack_plan(metas4)
        assert plan1.source == "search"
        assert {h for g in plan1.groups for h in g} == set(metas4)

        # same set again: the adopted winner is served from the cache
        plan1b = autotune.ensure_pack_plan(metas4)
        assert plan1b.set_hash == plan1.set_hash
        assert plan1b.groups == plan1.groups
        assert plan1b.source != "search", (
            "unchanged model set re-searched — the adopted layout "
            "never persisted"
        )

        # tenant ADD: different set hash, fresh search over the union
        metas5 = dict(metas4, m99=_meta(11))
        plan2 = autotune.ensure_pack_plan(metas5)
        assert plan2.set_hash != plan1.set_hash
        assert {h for g in plan2.groups for h in g} == set(metas5)

        # tenant REMOVE back to the original set: the stale 5-member
        # winner must NOT serve — the original cached plan returns
        plan3 = autotune.ensure_pack_plan(metas4)
        assert plan3.set_hash == plan1.set_hash
        assert plan3.groups == plan1.groups
        assert "m99" not in {h for g in plan3.groups for h in g}


# ---------------------------------------------------------------------------
# Fairness quota
# ---------------------------------------------------------------------------

class TestQuotaShed:
    def test_hog_sheds_neighbours_unharmed(self, tmp_path, monkeypatch):
        monkeypatch.setenv("FJT_TENANT_QUOTA_FRAC", "0.25")
        docs = _tenant_docs(tmp_path)
        docs = {k: docs[k] for k in ("gbm0", "gbm1")}
        sc = _build(docs, zoo=True)
        quota = max(1, int(0.25 * BATCH))
        rng = np.random.default_rng(3)
        ev = []
        for i in range(BATCH):  # the hog: a full batch of rows
            rec = {f"f{j}": float(v)
                   for j, v in enumerate(rng.normal(size=4))}
            rec["_key"] = f"hog-{i}"
            ev.append(("gbm0", rec))
        for i in range(quota):  # the mouse: within quota
            rec = {f"f{j}": float(v)
                   for j, v in enumerate(rng.normal(size=4))}
            rec["_key"] = f"mouse-{i}"
            ev.append(("gbm1", rec))
        out = sc.finish(sc.submit(ev))
        assert len(out) == len(ev)
        hog = [p for p, (_, r) in out if r["_key"].startswith("hog")]
        mouse = [p for p, (_, r) in out if r["_key"].startswith("mouse")]
        assert sum(1 for p in hog if not p.is_empty) == quota
        assert sum(1 for p in hog if p.is_empty) == BATCH - quota, (
            "shed rows must surface as explicit empties (C5 totality)"
        )
        assert all(not p.is_empty for p in mouse), (
            "the quota shed a tenant that was inside its share"
        )
        counters = sc.metrics.struct_snapshot()["counters"]
        assert counters.get(
            'tenant_shed_records{model="gbm0_1"}', 0
        ) == BATCH - quota
        assert counters.get(
            'tenant_shed_records{model="gbm1_1"}', 0
        ) == 0


# ---------------------------------------------------------------------------
# Cold-start accounting (registry satellite)
# ---------------------------------------------------------------------------

class TestColdStartAccounting:
    def test_resolve_warm_books_hits_misses_and_cold_start(
        self, tmp_path
    ):
        from flink_jpmml_tpu.serving.registry import ModelRegistry

        path = tmp_path / "m.pmml"
        path.write_text(MVR_XML)
        metrics = MetricsRegistry()
        reg = ModelRegistry(batch_size=BATCH, metrics=metrics)
        reg.apply(AddMessage("m", 1, str(path), timestamp=1.0))

        assert reg.resolve_warm("m") is None  # served but still cold
        mid = ModelId("m", 1)
        deadline = time.monotonic() + 120.0
        while reg.model_if_warm(mid) is None:  # kicks the warm
            assert reg.warm_error(mid) is None
            assert time.monotonic() < deadline
            time.sleep(0.01)
        assert reg.resolve_warm("m") == mid

        snap = metrics.struct_snapshot()
        counters = snap["counters"]
        assert counters.get("warm_pool_misses", 0) >= 1
        assert counters.get("warm_pool_hits", 0) >= 1
        hist = (snap.get("histograms") or {}).get("cold_start_s")
        assert hist is not None, "cold start never hit cold_start_s"
        from flink_jpmml_tpu.utils.metrics import Histogram

        h = Histogram.from_state(hist)
        assert h.count() >= 1
        assert (h.quantile(0.5) or 0) > 0
