"""Property fuzz: random models, random dirty records — the compiled
engine and the oracle interpreter must agree lane by lane.

Deterministically seeded (no flakes). The generator stays inside the
documented support surface; the *records* are adversarial: NaNs,
missing keys, undeclared categories, exact-threshold hits.
"""

import numpy as np
import pytest

from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.pmml import ir
from flink_jpmml_tpu.pmml.interp import evaluate

FIELDS = ("f0", "f1", "f2")
CAT_VALUES = ("red", "green", "blue")


def _doc(model):
    dd = ir.DataDictionary(fields=tuple(
        [ir.DataField(name=f, optype="continuous", dtype="double")
         for f in FIELDS]
        + [ir.DataField(name="color", optype="categorical", dtype="string",
                        values=CAT_VALUES)]
    ))
    return ir.PmmlDocument(
        version="4.3",
        header=ir.Header(),
        data_dictionary=dd,
        transformations=ir.TransformationDictionary(),
        model=model,
    )


def _schema(target="y"):
    return ir.MiningSchema(fields=tuple(
        [ir.MiningField(name=target, usage_type="target")]
        + [ir.MiningField(name=f) for f in FIELDS]
        + [ir.MiningField(name="color")]
    ))


def _rand_predicate(rng, depth=0):
    roll = rng.random()
    if roll < 0.45 or depth >= 2:
        op = rng.choice([
            "lessThan", "lessOrEqual", "greaterThan", "greaterOrEqual",
            "equal", "notEqual", "isMissing", "isNotMissing",
        ])
        field = str(rng.choice(FIELDS))
        value = f"{rng.normal(0, 1):.3f}"
        return ir.SimplePredicate(field=field, operator=str(op), value=value)
    if roll < 0.6:
        vals = tuple(
            str(v) for v in rng.choice(
                CAT_VALUES, size=rng.integers(1, 3), replace=False
            )
        )
        return ir.SimpleSetPredicate(
            field="color",
            boolean_operator=str(rng.choice(["isIn", "isNotIn"])),
            values=vals,
        )
    if roll < 0.7:
        return ir.TruePredicate()
    return ir.CompoundPredicate(
        boolean_operator=str(rng.choice(["and", "or", "xor"])),
        predicates=tuple(
            _rand_predicate(rng, depth + 1)
            for _ in range(rng.integers(2, 4))
        ),
    )


def _rand_tree(rng, classification, depth=0, max_depth=3):
    node_id = f"n{rng.integers(0, 1 << 30)}"
    rc = float(rng.integers(1, 100))
    if depth >= max_depth or rng.random() < 0.3:
        if classification:
            counts = rng.integers(1, 50, size=2)
            dist = tuple(
                ir.ScoreDistribution(value=v, record_count=float(c))
                for v, c in zip(("pos", "neg"), counts)
            )
            score = ("pos", "neg")[int(np.argmax(counts))]
            return ir.TreeNode(
                predicate=_rand_predicate(rng, 1),
                score=score,
                node_id=node_id,
                record_count=rc,
                score_distribution=dist,
            )
        return ir.TreeNode(
            predicate=_rand_predicate(rng, 1),
            score=f"{rng.normal(0, 5):.4f}",
            node_id=node_id,
            record_count=rc,
        )
    kids = tuple(
        _rand_tree(rng, classification, depth + 1, max_depth)
        for _ in range(rng.integers(2, 4))
    )
    # defaultChild must reference a child id
    default_child = (
        kids[rng.integers(0, len(kids))].node_id
        if rng.random() < 0.8
        else None
    )
    return ir.TreeNode(
        predicate=ir.TruePredicate() if depth == 0 else _rand_predicate(rng, 1),
        node_id=node_id,
        record_count=rc,
        default_child=default_child,
        children=kids,
        score=f"{rng.normal(0, 5):.4f}" if not classification else "pos",
        score_distribution=(
            (
                ir.ScoreDistribution(value="pos", record_count=3.0),
                ir.ScoreDistribution(value="neg", record_count=2.0),
            )
            if classification
            else ()
        ),
    )


def _rand_tree_model(rng):
    classification = bool(rng.random() < 0.5)
    strategy = str(rng.choice([
        "none", "defaultChild", "lastPrediction", "nullPrediction",
        "weightedConfidence" if classification else "aggregateNodes",
    ]))
    return ir.TreeModelIR(
        function_name="classification" if classification else "regression",
        mining_schema=_schema(),
        root=_rand_tree(rng, classification),
        missing_value_strategy=strategy,
        no_true_child_strategy=str(rng.choice(
            ["returnNullPrediction", "returnLastPrediction"]
        )),
        split_characteristic="multiSplit",
    )


def _rand_records(rng, n):
    recs = []
    for _ in range(n):
        rec = {}
        for f in FIELDS:
            roll = rng.random()
            if roll < 0.15:
                continue  # absent key
            if roll < 0.25:
                rec[f] = None
            elif roll < 0.3:
                rec[f] = float("nan")
            else:
                rec[f] = float(np.round(rng.normal(0, 1), 3))
        roll = rng.random()
        if roll < 0.2:
            pass  # color absent
        elif roll < 0.3:
            rec["color"] = "mauve"  # undeclared → invalid treatment
        else:
            rec["color"] = str(rng.choice(CAT_VALUES))
        recs.append(rec)
    return recs


def _assert_parity(doc, recs, where):
    cm = compile_pmml(doc)
    preds = cm.score_records(recs)
    for i, (rec, p) in enumerate(zip(recs, preds)):
        o = evaluate(doc, rec)
        ctx = f"{where} record {i}: {rec!r}"
        if o.is_missing:
            assert p.is_empty, f"{ctx}: oracle empty, compiled {p!r}"
            continue
        assert not p.is_empty, f"{ctx}: compiled empty, oracle {o!r}"
        if o.label is not None:
            assert p.target.label == o.label, (
                f"{ctx}: label {p.target.label!r} != {o.label!r}"
            )
        if o.value is not None:
            if abs(o.value) > float(np.finfo(np.float32).max):
                # beyond float32 range: the compiled engine represents
                # it as same-signed inf (or the nearest huge finite
                # value when rounding kept it in range)
                assert (
                    np.isinf(p.score.value)
                    or abs(p.score.value) > 3.3e38
                ) and np.sign(p.score.value) == np.sign(o.value), (
                    f"{ctx}: f32-overflow {p.score.value!r} vs {o.value!r}"
                )
                continue
            assert p.score.value == pytest.approx(
                o.value, rel=2e-4, abs=2e-5
            ), f"{ctx}: value {p.score.value!r} != {o.value!r}"


class TestFuzzTrees:
    @pytest.mark.parametrize("seed", range(25))
    def test_random_tree_parity(self, seed):
        rng = np.random.default_rng(1000 + seed)
        model = _rand_tree_model(rng)
        doc = _doc(model)
        recs = _rand_records(rng, 48)
        _assert_parity(doc, recs, f"tree seed={seed}")


class TestFuzzMining:
    @pytest.mark.parametrize("seed", range(12))
    def test_random_regression_ensemble_parity(self, seed):
        rng = np.random.default_rng(2000 + seed)
        n_seg = int(rng.integers(2, 5))
        segments = tuple(
            ir.Segment(
                predicate=(
                    ir.TruePredicate()
                    if rng.random() < 0.5
                    else _rand_predicate(rng, 1)
                ),
                model=ir.TreeModelIR(
                    function_name="regression",
                    mining_schema=_schema(),
                    root=_rand_tree(rng, False, max_depth=2),
                    missing_value_strategy=str(rng.choice(
                        ["none", "defaultChild", "nullPrediction"]
                    )),
                    split_characteristic="multiSplit",
                ),
                segment_id=f"s{i}",
                weight=float(np.round(rng.uniform(0.5, 2.0), 2)),
            )
            for i in range(n_seg)
        )
        method = str(rng.choice(
            ["sum", "average", "weightedAverage", "max", "median",
             "selectFirst"]
        ))
        model = ir.MiningModelIR(
            function_name="regression",
            mining_schema=_schema(),
            segmentation=ir.Segmentation(
                multiple_model_method=method, segments=segments
            ),
        )
        doc = _doc(model)
        recs = _rand_records(rng, 32)
        _assert_parity(doc, recs, f"mining {method} seed={seed}")


def _rand_regression_model(rng):
    classification = bool(rng.random() < 0.4)

    def table(target_category=None):
        nps = tuple(
            ir.NumericPredictor(
                name=str(f),
                coefficient=float(np.round(rng.normal(0, 2), 3)),
                exponent=int(rng.choice([1, 1, 1, 2])),
            )
            for f in rng.choice(FIELDS, size=rng.integers(1, 4),
                                replace=False)
        )
        cps = tuple(
            ir.CategoricalPredictor(
                name="color",
                value=str(v),
                coefficient=float(np.round(rng.normal(0, 1), 3)),
            )
            for v in rng.choice(CAT_VALUES, size=rng.integers(0, 3),
                                replace=False)
        )
        return ir.RegressionTable(
            intercept=float(np.round(rng.normal(0, 1), 3)),
            numeric_predictors=nps,
            categorical_predictors=cps,
            target_category=target_category,
        )

    if classification:
        tables = tuple(table(c) for c in ("pos", "neg"))
        return ir.RegressionModelIR(
            function_name="classification",
            mining_schema=_schema(),
            tables=tables,
            normalization_method=str(rng.choice(["softmax", "simplemax", "none"])),
        )
    return ir.RegressionModelIR(
        function_name="regression",
        mining_schema=_schema(),
        tables=(table(),),
        normalization_method=str(rng.choice(["none", "logit", "exp"])),
    )


class TestFuzzRegression:
    @pytest.mark.parametrize("seed", range(15))
    def test_random_regression_parity(self, seed):
        rng = np.random.default_rng(3000 + seed)
        doc = _doc(_rand_regression_model(rng))
        recs = _rand_records(rng, 40)
        _assert_parity(doc, recs, f"regression seed={seed}")


class TestFuzzScorecard:
    @pytest.mark.parametrize("seed", range(10))
    def test_random_scorecard_parity(self, seed):
        rng = np.random.default_rng(4000 + seed)
        chars = []
        for ci in range(int(rng.integers(1, 4))):
            attrs = [
                ir.ScorecardAttribute(
                    predicate=_rand_predicate(rng, 1),
                    partial_score=float(np.round(rng.normal(0, 20), 1)),
                )
                for _ in range(int(rng.integers(1, 4)))
            ]
            # catch-all keeps most lanes valid; drop it sometimes to
            # exercise the no-match -> empty contract
            if rng.random() < 0.8:
                attrs.append(ir.ScorecardAttribute(
                    predicate=ir.TruePredicate(),
                    partial_score=float(np.round(rng.normal(0, 5), 1)),
                ))
            chars.append(ir.Characteristic(
                name=f"ch{ci}", attributes=tuple(attrs)
            ))
        model = ir.ScorecardIR(
            function_name="regression",
            mining_schema=_schema(),
            characteristics=tuple(chars),
            initial_score=float(np.round(rng.normal(100, 20), 1)),
            use_reason_codes=False,
        )
        doc = _doc(model)
        recs = _rand_records(rng, 40)
        _assert_parity(doc, recs, f"scorecard seed={seed}")


def _rand_nn_model(rng):
    """Random regression MLP: FieldRef inputs → 1-2 hidden layers →
    one output neuron mapped straight to the target."""
    acts = ["logistic", "tanh", "identity", "rectifier", "arctan",
            "cosine", "sine", "exponential", "reciprocal", "square"]
    inputs = tuple(
        ir.NeuralInput(
            neuron_id=f"in{i}",
            derived_field=ir.DerivedField(
                name=f"in{i}", optype="continuous", dtype="double",
                expression=ir.FieldRef(field=f),
            ),
        )
        for i, f in enumerate(FIELDS)
    )
    prev = [ni.neuron_id for ni in inputs]
    layers = []
    nid = 0
    for _ in range(int(rng.integers(1, 3))):
        width = int(rng.integers(2, 5))
        neurons = []
        for _ in range(width):
            neurons.append(ir.Neuron(
                neuron_id=f"h{nid}",
                bias=float(np.round(rng.normal(0, 0.5), 3)),
                weights=tuple(
                    (p, float(np.round(rng.normal(0, 1), 3)))
                    for p in prev
                ),
            ))
            nid += 1
        layers.append(ir.NeuralLayer(
            neurons=tuple(neurons),
            activation=str(rng.choice(acts)),
        ))
        prev = [n.neuron_id for n in neurons]
    out_neuron = ir.Neuron(
        neuron_id="out0",
        bias=float(np.round(rng.normal(0, 0.5), 3)),
        weights=tuple(
            (p, float(np.round(rng.normal(0, 1), 3))) for p in prev
        ),
    )
    layers.append(ir.NeuralLayer(
        neurons=(out_neuron,), activation="identity"
    ))
    outputs = (
        ir.NeuralOutput(
            output_neuron="out0",
            derived_field=ir.DerivedField(
                name="y", optype="continuous", dtype="double",
                expression=ir.FieldRef(field="y"),
            ),
        ),
    )
    return ir.NeuralNetworkIR(
        function_name="regression",
        mining_schema=_schema(),
        activation_function="logistic",
        inputs=inputs,
        layers=tuple(layers),
        outputs=outputs,
    )


class TestFuzzNeural:
    @pytest.mark.parametrize("seed", range(10))
    def test_random_mlp_parity(self, seed):
        rng = np.random.default_rng(5000 + seed)
        doc = _doc(_rand_nn_model(rng))
        recs = _rand_records(rng, 32)
        _assert_parity(doc, recs, f"nn seed={seed}")


def _rand_glm_model(rng):
    n_params = int(rng.integers(2, 5))
    params = tuple(f"p{i}" for i in range(n_params))
    pp = []
    for i, pname in enumerate(params[1:], 1):
        # each non-intercept parameter: 1-2 covariate cells and maybe a
        # factor indicator
        for f in rng.choice(FIELDS, size=rng.integers(1, 3), replace=False):
            pp.append(ir.PPCell(
                predictor=str(f), parameter=pname,
                value=str(int(rng.choice([1, 1, 2]))),
            ))
        if rng.random() < 0.4:
            pp.append(ir.PPCell(
                predictor="color", parameter=pname,
                value=str(rng.choice(CAT_VALUES)),
            ))
    p_cells = tuple(
        ir.PCell(parameter=p, beta=float(np.round(rng.normal(0, 1), 3)))
        for p in params
    )
    link = str(rng.choice(["identity", "log", "logit", "cloglog",
                           "probit", "cauchit"]))
    return ir.GeneralRegressionIR(
        function_name="regression",
        mining_schema=_schema(),
        model_type="generalizedLinear",
        parameters=params,
        factors=("color",),
        covariates=FIELDS,
        pp_cells=tuple(pp),
        p_cells=p_cells,
        link_function=link,
    )


class TestFuzzGlm:
    @pytest.mark.parametrize("seed", range(10))
    def test_random_glm_parity(self, seed):
        rng = np.random.default_rng(6000 + seed)
        doc = _doc(_rand_glm_model(rng))
        recs = _rand_records(rng, 32)
        _assert_parity(doc, recs, f"glm seed={seed}")


class TestFuzzArima:
    """Random SARIMA state through the FULL pipeline (XML → parser →
    compile vs oracle): the two implementations compose the differencing
    operators in opposite orders, so agreement here checks the algebra,
    not a shared routine."""

    @pytest.mark.parametrize("seed", range(12))
    def test_random_sarima_parity(self, seed):
        from flink_jpmml_tpu.pmml import parse_pmml
        from test_timeseries import _arima_xml, _ns, _sc

        rng = np.random.default_rng(9000 + seed)
        p = int(rng.integers(0, 3))
        d = int(rng.integers(0, 2))
        q = int(rng.integers(0, 3))
        s = int(rng.integers(2, 5)) if rng.random() < 0.6 else 0
        P = int(rng.integers(0, 2)) if s else 0
        D = int(rng.integers(0, 2)) if s else 0
        Q = int(rng.integers(0, 2)) if s else 0
        if s and not (P or D or Q):
            D = 1

        def coefs(n):
            return tuple(round(float(v), 3)
                         for v in rng.uniform(-0.65, 0.65, size=n))

        n_res = q + s * Q
        residuals = tuple(
            round(float(v), 3) for v in rng.normal(0, 0.4, size=n_res)
        )
        n_hist = d + s * D + (p + s * P) + int(rng.integers(8, 16))
        t = np.arange(n_hist)
        hist = tuple(
            round(float(v), 3)
            for v in 40
            + 0.8 * t
            + (4 * np.sin(2 * np.pi * t / s) if s else 0)
            + rng.normal(0, 1.0, size=n_hist)
        )
        transformation = str(
            rng.choice(("none", "none", "logarithmic", "squareroot"))
        )
        body = _ns(p, d, q, ar=coefs(p), ma=coefs(q),
                   residuals=residuals if n_res else ())
        if s:
            body += _sc(P, D, Q, s, sar=coefs(P), sma=coefs(Q))
        doc = parse_pmml(_arima_xml(
            body, hist,
            constant=round(float(rng.uniform(-0.5, 0.5)), 3),
            transformation=transformation,
        ))
        recs = []
        for _ in range(24):
            roll = rng.random()
            if roll < 0.1:
                recs.append({})
            elif roll < 0.2:
                recs.append({"h": None})
            elif roll < 0.3:
                recs.append({"h": float(rng.uniform(0.6, 20.0))})
            else:
                recs.append({"h": int(rng.integers(1, 31))})
        _assert_parity(doc, recs, f"sarima seed={seed}")
