"""TransformationDictionary derived fields: compiled path vs oracle.

The reference delegates preprocessing to JPMML-Evaluator's handling of
TransformationDictionary (SURVEY.md §8 step 1 lists DerivedFields as part
of the parser/IR scope); here derived fields lower to extra on-device
columns computed before the model body (compiler.py) and to record
extension in the oracle (interp.py)."""

import numpy as np

from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.pmml import parse_pmml
from flink_jpmml_tpu.pmml.interp import evaluate

_XML = """<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3">
  <Header/>
  <DataDictionary numberOfFields="3">
    <DataField name="a" optype="continuous" dataType="double"/>
    <DataField name="b" optype="continuous" dataType="double"/>
    <DataField name="y" optype="continuous" dataType="double"/>
  </DataDictionary>
  <TransformationDictionary>
    <DerivedField name="a_norm" optype="continuous" dataType="double">
      <NormContinuous field="a">
        <LinearNorm orig="-2" norm="0"/>
        <LinearNorm orig="2" norm="1"/>
      </NormContinuous>
    </DerivedField>
    <DerivedField name="ab_sum" optype="continuous" dataType="double">
      <Apply function="+">
        <FieldRef field="a_norm"/>
        <FieldRef field="b"/>
      </Apply>
    </DerivedField>
  </TransformationDictionary>
  <RegressionModel functionName="regression">
    <MiningSchema>
      <MiningField name="y" usageType="target"/>
      <MiningField name="a"/>
      <MiningField name="b"/>
    </MiningSchema>
    <RegressionTable intercept="0.25">
      <NumericPredictor name="ab_sum" coefficient="2.0"/>
      <NumericPredictor name="b" coefficient="-0.5"/>
    </RegressionTable>
  </RegressionModel>
</PMML>"""

_TREE_XML = """<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3">
  <Header/>
  <DataDictionary numberOfFields="2">
    <DataField name="a" optype="continuous" dataType="double"/>
    <DataField name="y" optype="continuous" dataType="double"/>
  </DataDictionary>
  <TransformationDictionary>
    <DerivedField name="abs_a" optype="continuous" dataType="double">
      <Apply function="abs"><FieldRef field="a"/></Apply>
    </DerivedField>
  </TransformationDictionary>
  <TreeModel functionName="regression" missingValueStrategy="defaultChild"
             splitCharacteristic="binarySplit">
    <MiningSchema>
      <MiningField name="y" usageType="target"/>
      <MiningField name="a"/>
    </MiningSchema>
    <Node id="0" defaultChild="1"><True/>
      <Node id="1" score="1.0">
        <SimplePredicate field="abs_a" operator="lessThan" value="1.0"/>
      </Node>
      <Node id="2" score="-1.0">
        <SimplePredicate field="abs_a" operator="greaterOrEqual" value="1.0"/>
      </Node>
    </Node>
  </TreeModel>
</PMML>"""


def _oracle_values(doc, records):
    out = []
    for r in records:
        res = evaluate(doc, r)
        out.append(np.nan if res.value is None else res.value)
    return np.asarray(out, np.float32)


class TestDerivedFields:
    def test_regression_with_chained_derivations(self):
        doc = parse_pmml(_XML)
        cm = compile_pmml(doc)
        assert cm.active_fields == ("a", "b")  # raw user contract
        rng = np.random.default_rng(0)
        records = [
            {"a": float(a), "b": float(b)}
            for a, b in rng.normal(0, 2, size=(64, 2))
        ]
        got = np.asarray(
            [p.score.value for p in cm.score_records(records)], np.float32
        )
        exp = _oracle_values(doc, records)
        np.testing.assert_allclose(got, exp, rtol=1e-5, atol=1e-6)

    def test_tree_predicate_on_derived_field(self):
        doc = parse_pmml(_TREE_XML)
        cm = compile_pmml(doc)
        records = [{"a": -2.0}, {"a": -0.5}, {"a": 0.5}, {"a": 2.0}, {}]
        got = [p.score.value if not p.is_empty else None
               for p in cm.score_records(records)]
        exp = []
        for r in records:
            res = evaluate(doc, r)
            exp.append(res.value)
        assert got == exp

    def test_missing_input_propagates_through_derivation(self):
        doc = parse_pmml(_XML)
        cm = compile_pmml(doc)
        # 'a' missing -> a_norm missing -> ab_sum missing -> empty score
        preds = cm.score_records([{"b": 1.0}])
        res = evaluate(doc, {"b": 1.0})
        assert preds[0].is_empty == (res.value is None)


import pytest

DEFINE_FN = """<PMML version="4.3"><DataDictionary>
  <DataField name="c" optype="continuous" dataType="double"/>
  <DataField name="y" optype="continuous" dataType="double"/>
  </DataDictionary>
  <TransformationDictionary>
    <DefineFunction name="c2f">
      <ParameterField name="t"/>
      <Apply function="+">
        <Apply function="*"><FieldRef field="t"/><Constant>1.8</Constant>
        </Apply><Constant>32</Constant></Apply>
    </DefineFunction>
    <DefineFunction name="f2k">
      <ParameterField name="t"/>
      <Apply function="*">
        <Apply function="+"><Apply function="c2f"><FieldRef field="t"/>
          </Apply><Constant>459.67</Constant></Apply>
        <Constant>0.5555555555555556</Constant></Apply>
    </DefineFunction>
    <DerivedField name="kelvin" optype="continuous" dataType="double">
      <Apply function="f2k"><FieldRef field="c"/></Apply>
    </DerivedField>
  </TransformationDictionary>
  <RegressionModel functionName="regression">
  <MiningSchema><MiningField name="y" usageType="target"/>
    <MiningField name="c"/></MiningSchema>
  <RegressionTable intercept="0.0">
    <NumericPredictor name="kelvin" coefficient="1.0"/>
  </RegressionTable></RegressionModel></PMML>"""

LOCAL_TX = """<PMML version="4.3"><DataDictionary>
  <DataField name="x" optype="continuous" dataType="double"/>
  <DataField name="y" optype="continuous" dataType="double"/>
  </DataDictionary>
  <TransformationDictionary>
    <DerivedField name="x2" optype="continuous" dataType="double">
      <Apply function="*"><FieldRef field="x"/><FieldRef field="x"/></Apply>
    </DerivedField>
  </TransformationDictionary>
  <RegressionModel functionName="regression">
  <MiningSchema><MiningField name="y" usageType="target"/>
    <MiningField name="x"/></MiningSchema>
  <LocalTransformations>
    <DerivedField name="lx" optype="continuous" dataType="double">
      <Apply function="+"><FieldRef field="x2"/><Constant>1</Constant>
      </Apply>
    </DerivedField>
  </LocalTransformations>
  <RegressionTable intercept="0.5">
    <NumericPredictor name="lx" coefficient="2.0"/>
  </RegressionTable></RegressionModel></PMML>"""


class TestDefineFunction:
    def test_nested_user_functions_inline(self):
        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.pmml import parse_pmml
        from flink_jpmml_tpu.pmml.interp import evaluate

        doc = parse_pmml(DEFINE_FN)
        cm = compile_pmml(doc)
        for c in (-40.0, 0.0, 25.0, 100.0):
            hand = ((c * 1.8 + 32) + 459.67) * 0.5555555555555556
            assert evaluate(doc, {"c": c}).value == pytest.approx(hand)
            assert cm.score_records([{"c": c}])[0].score.value == (
                pytest.approx(hand, rel=1e-5)
            )

    def test_arity_mismatch_rejected(self):
        from flink_jpmml_tpu.pmml import parse_pmml
        from flink_jpmml_tpu.utils.exceptions import ModelLoadingException

        bad = DEFINE_FN.replace(
            '<Apply function="c2f"><FieldRef field="t"/>\n          </Apply>',
            '<Apply function="c2f"><FieldRef field="t"/>'
            "<Constant>1</Constant></Apply>",
        )
        with pytest.raises(ModelLoadingException, match="argument"):
            parse_pmml(bad)


class TestLocalTransformations:
    def test_local_fields_see_dictionary_fields(self):
        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.pmml import parse_pmml
        from flink_jpmml_tpu.pmml.interp import evaluate

        doc = parse_pmml(LOCAL_TX)
        cm = compile_pmml(doc)
        for x in (0.0, 1.5, -2.0):
            hand = 0.5 + 2.0 * (x * x + 1)
            assert evaluate(doc, {"x": x}).value == pytest.approx(hand)
            assert cm.score_records([{"x": x}])[0].score.value == (
                pytest.approx(hand, rel=1e-6)
            )

    def test_segment_local_transformations_rejected(self):
        from flink_jpmml_tpu.pmml import parse_pmml
        from flink_jpmml_tpu.utils.exceptions import ModelLoadingException

        xml = """<PMML version="4.3"><DataDictionary>
          <DataField name="x" optype="continuous" dataType="double"/>
          <DataField name="y" optype="continuous" dataType="double"/>
          </DataDictionary>
          <MiningModel functionName="regression">
          <MiningSchema><MiningField name="y" usageType="target"/>
            <MiningField name="x"/></MiningSchema>
          <Segmentation multipleModelMethod="sum">
            <Segment><True/>
              <RegressionModel functionName="regression">
                <MiningSchema><MiningField name="y" usageType="target"/>
                  <MiningField name="x"/></MiningSchema>
                <LocalTransformations>
                  <DerivedField name="q" optype="continuous"
                      dataType="double"><FieldRef field="x"/></DerivedField>
                </LocalTransformations>
                <RegressionTable intercept="1.0"/>
              </RegressionModel></Segment>
          </Segmentation></MiningModel></PMML>"""
        with pytest.raises(ModelLoadingException, match="LocalTransformations"):
            parse_pmml(xml)


class TestBuiltinFunctionLibrary:
    """The PMML 4.4 numeric built-in library (round 5 widening):
    comparisons, booleans, isMissing/isNotMissing, rounding, residues,
    logs, trigonometry, and the standard-normal family — compiled vs
    oracle, including the domain-error → missing contract."""

    FN_XML = """<PMML xmlns="http://www.dmg.org/PMML-4_4" version="4.4">
      <Header/>
      <DataDictionary numberOfFields="3">
        <DataField name="a" optype="continuous" dataType="double"/>
        <DataField name="b" optype="continuous" dataType="double"/>
        <DataField name="y" optype="continuous" dataType="double"/>
      </DataDictionary>
      <TransformationDictionary>
        <DerivedField name="d" optype="continuous" dataType="double">
          <Apply function="{fn}">{args}</Apply>
        </DerivedField>
      </TransformationDictionary>
      <RegressionModel functionName="regression">
        <MiningSchema>
          <MiningField name="y" usageType="target"/>
          <MiningField name="a"/>
          <MiningField name="b"/>
        </MiningSchema>
        <RegressionTable intercept="0.0">
          <NumericPredictor name="d" coefficient="1.0"/>
        </RegressionTable>
      </RegressionModel>
    </PMML>"""

    A = '<FieldRef field="a"/>'
    AB = '<FieldRef field="a"/><FieldRef field="b"/>'

    def _diff(self, fn, args, records, rel=2e-4, abs_tol=2e-5):
        # the suite's standard f32 parity tolerance: TPU
        # transcendentals (tanh/sin/...) differ from libm by a
        # few e-5 relative — numerics, not semantics

        doc = parse_pmml(self.FN_XML.format(fn=fn, args=args))
        cm = compile_pmml(doc)
        got = cm.score_records(records)
        want = _oracle_values(doc, records)
        for i, (g, w) in enumerate(zip(got, want)):
            if np.isnan(w):
                assert g.is_empty, (fn, records[i], g)
            else:
                assert not g.is_empty, (fn, records[i], "compiled empty")
                assert abs(g.score.value - w) <= abs_tol + rel * abs(w), (
                    fn, records[i], g.score.value, w,
                )

    def test_unary_numeric_functions(self):
        recs = [{"a": v, "b": 0.0} for v in
                (-2.5, -1.5, -1.0, -0.5, 0.0, 0.3, 0.5, 1.0, 1.5, 2.5)]
        for fn in ("round", "rint", "expm1", "sin", "cos", "tan",
                   "atan", "sinh", "cosh", "tanh", "stdNormalCDF",
                   "stdNormalPDF", "not"):
            self._diff(fn, self.A, recs)

    def test_domain_errors_empty_the_lane(self):
        # out-of-domain inputs must MISS (both paths), not produce junk
        recs = [{"a": v, "b": 0.0} for v in (-2.0, -1.0, 0.0, 0.5, 2.0)]
        for fn in ("asin", "acos", "log10", "ln1p", "stdNormalIDF"):
            self._diff(fn, self.A, recs)

    def test_binary_functions(self):
        recs = [{"a": a, "b": b} for a in (-2.0, -0.5, 0.0, 1.0, 3.0)
                for b in (-1.5, 0.0, 0.5, 2.0)]
        for fn in ("equal", "notEqual", "lessThan", "lessOrEqual",
                   "greaterThan", "greaterOrEqual", "and", "or",
                   "modulo", "atan2", "hypot"):
            self._diff(fn, self.AB, recs)

    def test_round_is_half_up_and_rint_half_even(self):
        doc = parse_pmml(self.FN_XML.format(fn="round", args=self.A))
        cm = compile_pmml(doc)
        vals = [p.score.value for p in cm.score_records(
            [{"a": 0.5, "b": 0}, {"a": 1.5, "b": 0}, {"a": -0.5, "b": 0}]
        )]
        assert vals == [1.0, 2.0, 0.0]  # PMML round: 0.5 rounds UP
        doc = parse_pmml(self.FN_XML.format(fn="rint", args=self.A))
        cm = compile_pmml(doc)
        vals = [p.score.value for p in cm.score_records(
            [{"a": 0.5, "b": 0}, {"a": 1.5, "b": 0}, {"a": 2.5, "b": 0}]
        )]
        assert vals == [0.0, 2.0, 2.0]  # half-to-even

    def test_is_missing_consumes_missingness(self):
        # the any-arg-missing shortcut must not fire for isMissing
        for fn, on_missing, on_present in (
            ("isMissing", 1.0, 0.0), ("isNotMissing", 0.0, 1.0),
        ):
            doc = parse_pmml(self.FN_XML.format(fn=fn, args=self.A))
            cm = compile_pmml(doc)
            got = cm.score_records([{"a": None, "b": 0}, {"a": 3.0, "b": 0}])
            assert got[0].score.value == on_missing
            assert got[1].score.value == on_present
            assert evaluate(doc, {"a": None}).value == on_missing
            assert evaluate(doc, {"a": 3.0}).value == on_present

    def test_modulo_sign_follows_divisor(self):
        doc = parse_pmml(self.FN_XML.format(fn="modulo", args=self.AB))
        cm = compile_pmml(doc)
        recs = [{"a": 7.0, "b": 3.0}, {"a": -7.0, "b": 3.0},
                {"a": 7.0, "b": -3.0}, {"a": -7.0, "b": -3.0}]
        vals = [p.score.value for p in cm.score_records(recs)]
        assert vals == [1.0, 2.0, -2.0, -1.0]
        for r, v in zip(recs, vals):
            assert evaluate(doc, r).value == v
        # modulo by zero: missing, not a crash
        assert cm.score_records([{"a": 1.0, "b": 0.0}])[0].is_empty
        assert evaluate(doc, {"a": 1.0, "b": 0.0}).value is None

    def test_is_missing_on_present_categorical_string(self):
        # a present categorical value is NOT missing even though it
        # does not coerce to float (the compiled lane holds its codec
        # code) — both paths must agree on 0.0
        xml = """<PMML xmlns="http://www.dmg.org/PMML-4_4" version="4.4">
          <Header/>
          <DataDictionary numberOfFields="2">
            <DataField name="color" optype="categorical" dataType="string">
              <Value value="red"/><Value value="green"/>
            </DataField>
            <DataField name="y" optype="continuous" dataType="double"/>
          </DataDictionary>
          <TransformationDictionary>
            <DerivedField name="d" optype="continuous" dataType="double">
              <Apply function="isMissing"><FieldRef field="color"/></Apply>
            </DerivedField>
          </TransformationDictionary>
          <RegressionModel functionName="regression">
            <MiningSchema>
              <MiningField name="y" usageType="target"/>
              <MiningField name="color"/>
            </MiningSchema>
            <RegressionTable intercept="0.0">
              <NumericPredictor name="d" coefficient="1.0"/>
            </RegressionTable>
          </RegressionModel>
        </PMML>"""
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        got = cm.score_records([{"color": "red"}, {"color": None}])
        assert got[0].score.value == 0.0
        assert got[1].score.value == 1.0
        assert evaluate(doc, {"color": "red"}).value == 0.0
        assert evaluate(doc, {"color": None}).value == 1.0

    def test_kleene_and_or_dominators_beat_missing(self):
        # JPMML BinaryBooleanFunction three-valued (Kleene) logic:
        # and(false, missing) = false and or(true, missing) = true — a
        # definite dominator decides the lane; only an undecided lane
        # with a missing argument stays missing. Both lanes must agree.
        for fn, dom, other in (("and", 0.0, 1.0), ("or", 1.0, 0.0)):
            doc = parse_pmml(self.FN_XML.format(fn=fn, args=self.AB))
            cm = compile_pmml(doc)
            recs = [
                {"a": dom, "b": None},    # dominator + missing → decided
                {"a": None, "b": dom},    # (either side)
                {"a": other, "b": None},  # undecided + missing → missing
                {"a": None, "b": None},
                {"a": other, "b": other},  # no missing: plain logic
                {"a": dom, "b": other},
            ]
            expected = [dom, dom, None, None, other, dom]
            got = cm.score_records(recs)
            for r, g, w in zip(recs, got, expected):
                o = evaluate(doc, r).value
                assert o == w, (fn, r, o, w)
                if w is None:
                    assert g.is_empty, (fn, r, g)
                else:
                    assert not g.is_empty and g.score.value == w, (fn, r, g)

    def test_kleene_boolean_apply_chain_golden(self):
        # nested missing-value boolean chain, compiled vs oracle over
        # the full {0, 1, missing}^2 grid:
        #   or(and(greaterThan(a, 0), lessThan(b, 1)), isMissing(a))
        xml = self.FN_XML.format(
            fn="or",
            args=(
                '<Apply function="and">'
                '<Apply function="greaterThan">'
                '<FieldRef field="a"/><Constant>0</Constant></Apply>'
                '<Apply function="lessThan">'
                '<FieldRef field="b"/><Constant>1</Constant></Apply>'
                "</Apply>"
                '<Apply function="isMissing"><FieldRef field="a"/></Apply>'
            ),
        )
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        vals = (None, -1.0, 0.5, 2.0)
        recs = [{"a": a, "b": b} for a in vals for b in vals]
        got = cm.score_records(recs)
        for r, g in zip(recs, got):
            w = evaluate(doc, r).value
            if w is None:
                assert g.is_empty, (r, g)
            else:
                assert not g.is_empty and g.score.value == w, (r, g, w)
        # spot-check the Kleene-specific lanes: a missing with b known
        # decides via isMissing(a)=true through the or; a present but
        # chain-missing (b missing, a>0 undecided-and) stays missing
        by_rec = {(r["a"], r["b"]): g for r, g in zip(recs, got)}
        assert by_rec[(None, -1.0)].score.value == 1.0
        assert by_rec[(0.5, None)].is_empty
        assert by_rec[(-1.0, None)].score.value == 0.0  # and-dominated false

    def test_kleene_map_missing_to_applies_after_domination(self):
        # mapMissingTo fills only the lanes Kleene logic left missing —
        # dominated lanes keep their decided value
        xml = self.FN_XML.format(fn="or", args=self.AB).replace(
            '<Apply function="or">',
            '<Apply function="or" mapMissingTo="5">',
        )
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        recs = [
            {"a": 1.0, "b": None},  # or-dominated true: stays 1.0
            {"a": 0.0, "b": None},  # undecided-missing: mapped to 5
        ]
        got = cm.score_records(recs)
        assert got[0].score.value == 1.0
        assert got[1].score.value == 5.0
        assert evaluate(doc, recs[0]).value == 1.0
        assert evaluate(doc, recs[1]).value == 5.0

    def test_extreme_but_valid_idf_is_not_clipped(self):
        doc = parse_pmml(self.FN_XML.format(fn="stdNormalIDF", args=self.A))
        cm = compile_pmml(doc)
        got = cm.score_records([{"a": 1e-6, "b": 0}])[0].score.value
        want = _oracle_values(doc, [{"a": 1e-6}])[0]
        assert abs(got - want) < 1e-3, (got, want)  # ~-4.75, not -5.2-clip

    def test_hyperbolic_overflow_is_inf_on_both_paths(self):
        doc = parse_pmml(self.FN_XML.format(fn="sinh", args=self.A))
        cm = compile_pmml(doc)
        g = cm.score_records([{"a": 1000.0, "b": 0}, {"a": -1000.0, "b": 0}])
        assert np.isinf(g[0].score.value) and g[0].score.value > 0
        assert np.isinf(g[1].score.value) and g[1].score.value < 0
        assert evaluate(doc, {"a": 1000.0}).value == float("inf")
        assert evaluate(doc, {"a": -1000.0}).value == float("-inf")
