"""TransformationDictionary derived fields: compiled path vs oracle.

The reference delegates preprocessing to JPMML-Evaluator's handling of
TransformationDictionary (SURVEY.md §8 step 1 lists DerivedFields as part
of the parser/IR scope); here derived fields lower to extra on-device
columns computed before the model body (compiler.py) and to record
extension in the oracle (interp.py)."""

import numpy as np

from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.pmml import parse_pmml
from flink_jpmml_tpu.pmml.interp import evaluate

_XML = """<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3">
  <Header/>
  <DataDictionary numberOfFields="3">
    <DataField name="a" optype="continuous" dataType="double"/>
    <DataField name="b" optype="continuous" dataType="double"/>
    <DataField name="y" optype="continuous" dataType="double"/>
  </DataDictionary>
  <TransformationDictionary>
    <DerivedField name="a_norm" optype="continuous" dataType="double">
      <NormContinuous field="a">
        <LinearNorm orig="-2" norm="0"/>
        <LinearNorm orig="2" norm="1"/>
      </NormContinuous>
    </DerivedField>
    <DerivedField name="ab_sum" optype="continuous" dataType="double">
      <Apply function="+">
        <FieldRef field="a_norm"/>
        <FieldRef field="b"/>
      </Apply>
    </DerivedField>
  </TransformationDictionary>
  <RegressionModel functionName="regression">
    <MiningSchema>
      <MiningField name="y" usageType="target"/>
      <MiningField name="a"/>
      <MiningField name="b"/>
    </MiningSchema>
    <RegressionTable intercept="0.25">
      <NumericPredictor name="ab_sum" coefficient="2.0"/>
      <NumericPredictor name="b" coefficient="-0.5"/>
    </RegressionTable>
  </RegressionModel>
</PMML>"""

_TREE_XML = """<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3">
  <Header/>
  <DataDictionary numberOfFields="2">
    <DataField name="a" optype="continuous" dataType="double"/>
    <DataField name="y" optype="continuous" dataType="double"/>
  </DataDictionary>
  <TransformationDictionary>
    <DerivedField name="abs_a" optype="continuous" dataType="double">
      <Apply function="abs"><FieldRef field="a"/></Apply>
    </DerivedField>
  </TransformationDictionary>
  <TreeModel functionName="regression" missingValueStrategy="defaultChild"
             splitCharacteristic="binarySplit">
    <MiningSchema>
      <MiningField name="y" usageType="target"/>
      <MiningField name="a"/>
    </MiningSchema>
    <Node id="0" defaultChild="1"><True/>
      <Node id="1" score="1.0">
        <SimplePredicate field="abs_a" operator="lessThan" value="1.0"/>
      </Node>
      <Node id="2" score="-1.0">
        <SimplePredicate field="abs_a" operator="greaterOrEqual" value="1.0"/>
      </Node>
    </Node>
  </TreeModel>
</PMML>"""


def _oracle_values(doc, records):
    out = []
    for r in records:
        res = evaluate(doc, r)
        out.append(np.nan if res.value is None else res.value)
    return np.asarray(out, np.float32)


class TestDerivedFields:
    def test_regression_with_chained_derivations(self):
        doc = parse_pmml(_XML)
        cm = compile_pmml(doc)
        assert cm.active_fields == ("a", "b")  # raw user contract
        rng = np.random.default_rng(0)
        records = [
            {"a": float(a), "b": float(b)}
            for a, b in rng.normal(0, 2, size=(64, 2))
        ]
        got = np.asarray(
            [p.score.value for p in cm.score_records(records)], np.float32
        )
        exp = _oracle_values(doc, records)
        np.testing.assert_allclose(got, exp, rtol=1e-5, atol=1e-6)

    def test_tree_predicate_on_derived_field(self):
        doc = parse_pmml(_TREE_XML)
        cm = compile_pmml(doc)
        records = [{"a": -2.0}, {"a": -0.5}, {"a": 0.5}, {"a": 2.0}, {}]
        got = [p.score.value if not p.is_empty else None
               for p in cm.score_records(records)]
        exp = []
        for r in records:
            res = evaluate(doc, r)
            exp.append(res.value)
        assert got == exp

    def test_missing_input_propagates_through_derivation(self):
        doc = parse_pmml(_XML)
        cm = compile_pmml(doc)
        # 'a' missing -> a_norm missing -> ab_sum missing -> empty score
        preds = cm.score_records([{"b": 1.0}])
        res = evaluate(doc, {"b": 1.0})
        assert preds[0].is_empty == (res.value is None)


import pytest

DEFINE_FN = """<PMML version="4.3"><DataDictionary>
  <DataField name="c" optype="continuous" dataType="double"/>
  <DataField name="y" optype="continuous" dataType="double"/>
  </DataDictionary>
  <TransformationDictionary>
    <DefineFunction name="c2f">
      <ParameterField name="t"/>
      <Apply function="+">
        <Apply function="*"><FieldRef field="t"/><Constant>1.8</Constant>
        </Apply><Constant>32</Constant></Apply>
    </DefineFunction>
    <DefineFunction name="f2k">
      <ParameterField name="t"/>
      <Apply function="*">
        <Apply function="+"><Apply function="c2f"><FieldRef field="t"/>
          </Apply><Constant>459.67</Constant></Apply>
        <Constant>0.5555555555555556</Constant></Apply>
    </DefineFunction>
    <DerivedField name="kelvin" optype="continuous" dataType="double">
      <Apply function="f2k"><FieldRef field="c"/></Apply>
    </DerivedField>
  </TransformationDictionary>
  <RegressionModel functionName="regression">
  <MiningSchema><MiningField name="y" usageType="target"/>
    <MiningField name="c"/></MiningSchema>
  <RegressionTable intercept="0.0">
    <NumericPredictor name="kelvin" coefficient="1.0"/>
  </RegressionTable></RegressionModel></PMML>"""

LOCAL_TX = """<PMML version="4.3"><DataDictionary>
  <DataField name="x" optype="continuous" dataType="double"/>
  <DataField name="y" optype="continuous" dataType="double"/>
  </DataDictionary>
  <TransformationDictionary>
    <DerivedField name="x2" optype="continuous" dataType="double">
      <Apply function="*"><FieldRef field="x"/><FieldRef field="x"/></Apply>
    </DerivedField>
  </TransformationDictionary>
  <RegressionModel functionName="regression">
  <MiningSchema><MiningField name="y" usageType="target"/>
    <MiningField name="x"/></MiningSchema>
  <LocalTransformations>
    <DerivedField name="lx" optype="continuous" dataType="double">
      <Apply function="+"><FieldRef field="x2"/><Constant>1</Constant>
      </Apply>
    </DerivedField>
  </LocalTransformations>
  <RegressionTable intercept="0.5">
    <NumericPredictor name="lx" coefficient="2.0"/>
  </RegressionTable></RegressionModel></PMML>"""


class TestDefineFunction:
    def test_nested_user_functions_inline(self):
        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.pmml import parse_pmml
        from flink_jpmml_tpu.pmml.interp import evaluate

        doc = parse_pmml(DEFINE_FN)
        cm = compile_pmml(doc)
        for c in (-40.0, 0.0, 25.0, 100.0):
            hand = ((c * 1.8 + 32) + 459.67) * 0.5555555555555556
            assert evaluate(doc, {"c": c}).value == pytest.approx(hand)
            assert cm.score_records([{"c": c}])[0].score.value == (
                pytest.approx(hand, rel=1e-5)
            )

    def test_arity_mismatch_rejected(self):
        from flink_jpmml_tpu.pmml import parse_pmml
        from flink_jpmml_tpu.utils.exceptions import ModelLoadingException

        bad = DEFINE_FN.replace(
            '<Apply function="c2f"><FieldRef field="t"/>\n          </Apply>',
            '<Apply function="c2f"><FieldRef field="t"/>'
            "<Constant>1</Constant></Apply>",
        )
        with pytest.raises(ModelLoadingException, match="argument"):
            parse_pmml(bad)


class TestLocalTransformations:
    def test_local_fields_see_dictionary_fields(self):
        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.pmml import parse_pmml
        from flink_jpmml_tpu.pmml.interp import evaluate

        doc = parse_pmml(LOCAL_TX)
        cm = compile_pmml(doc)
        for x in (0.0, 1.5, -2.0):
            hand = 0.5 + 2.0 * (x * x + 1)
            assert evaluate(doc, {"x": x}).value == pytest.approx(hand)
            assert cm.score_records([{"x": x}])[0].score.value == (
                pytest.approx(hand, rel=1e-6)
            )

    def test_segment_local_transformations_rejected(self):
        from flink_jpmml_tpu.pmml import parse_pmml
        from flink_jpmml_tpu.utils.exceptions import ModelLoadingException

        xml = """<PMML version="4.3"><DataDictionary>
          <DataField name="x" optype="continuous" dataType="double"/>
          <DataField name="y" optype="continuous" dataType="double"/>
          </DataDictionary>
          <MiningModel functionName="regression">
          <MiningSchema><MiningField name="y" usageType="target"/>
            <MiningField name="x"/></MiningSchema>
          <Segmentation multipleModelMethod="sum">
            <Segment><True/>
              <RegressionModel functionName="regression">
                <MiningSchema><MiningField name="y" usageType="target"/>
                  <MiningField name="x"/></MiningSchema>
                <LocalTransformations>
                  <DerivedField name="q" optype="continuous"
                      dataType="double"><FieldRef field="x"/></DerivedField>
                </LocalTransformations>
                <RegressionTable intercept="1.0"/>
              </RegressionModel></Segment>
          </Segmentation></MiningModel></PMML>"""
        with pytest.raises(ModelLoadingException, match="LocalTransformations"):
            parse_pmml(xml)
