"""Device-fault resilience & degraded-mode serving (ISSUE 15).

The contracts under test:

- classification (runtime/devfault.py): injected device faults and
  XLA runtime errors classify into the OOM / transient / chip-loss
  taxonomy; record poison NEVER classifies as a device fault;
- the recovery ladder on both hot paths: transient errors re-dispatch
  the host-retained staging copy, OOM bisects the BATCH SIZE and feeds
  the AdaptiveBatcher cap, persistent streaks trip the circuit breaker
  onto the host fallback tier, chip loss escalates;
- the headline pin: a sick device never quarantines clean records —
  the DLQ stays empty under device faults, while genuine poison still
  lands there exactly;
- checkpoint ENOSPC degrade: a full disk suspends checkpointing
  (gauge + flight events) and serving continues; space returning
  resumes the cadence automatically;
- degraded mesh (parallel/): a data×model mesh minus one chip rebuilds
  over the survivors with identical predictions — testable in tier-1
  thanks to the conftest's 8-device virtual CPU mesh.
"""

import os
import time

import numpy as np
import pytest

from flink_jpmml_tpu.runtime import devfault, faults
from flink_jpmml_tpu.serving import failover as failover_mod
from flink_jpmml_tpu.utils.metrics import MetricsRegistry


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(autouse=True)
def _fast_ladder(monkeypatch):
    """Fast retry/breaker geometry: the ladders' sleeps must not
    dominate the tier-1 wall clock."""
    monkeypatch.setenv("FJT_RETRY_BASE_S", "0.005")
    monkeypatch.setenv("FJT_FAILOVER_COOLDOWN_S", "0.05")
    monkeypatch.setenv("FJT_FAILOVER_GREENS", "1")


@pytest.fixture(scope="module")
def gbm(tmp_path_factory):
    from flink_jpmml_tpu.assets_gen import gen_gbm
    from flink_jpmml_tpu.compile import compile_pmml
    from flink_jpmml_tpu.pmml import parse_pmml_file

    tmp = tmp_path_factory.mktemp("devfault-gbm")
    pmml = gen_gbm(str(tmp), n_trees=4, depth=3, n_features=5)
    return compile_pmml(parse_pmml_file(pmml), batch_size=32)


def _data(n, seed=0, cols=5):
    rng = np.random.default_rng(seed)
    return rng.normal(0, 1.0, size=(n, cols)).astype(np.float32)


def _block_pipe(gbm, sink, tmp_path, metrics=None, ckpt=True, **kw):
    from flink_jpmml_tpu.runtime.block import BlockPipeline
    from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager
    from flink_jpmml_tpu.utils.config import BatchConfig, RuntimeConfig

    return BlockPipeline(
        kw.pop("source"), gbm, sink,
        RuntimeConfig(
            batch=BatchConfig(size=32, deadline_us=500),
            checkpoint_interval_s=kw.pop("ckpt_interval", 0.05),
        ),
        metrics=metrics or MetricsRegistry(),
        checkpoint=(
            CheckpointManager(str(tmp_path / "ck")) if ckpt else None
        ),
        use_native=False,
        **kw,
    )


def _coverage(emitted, n):
    cov = np.zeros(n, np.int64)
    for off, cnt in emitted:
        cov[off: off + cnt] += 1
    return cov


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------


class TestClassify:
    def test_injected_kinds(self):
        assert devfault.classify(faults.InjectedDeviceOOM()) == (
            devfault.KIND_OOM
        )
        assert devfault.classify(faults.InjectedDeviceError()) == (
            devfault.KIND_ERROR
        )
        assert devfault.classify(faults.InjectedChipLoss()) == (
            devfault.KIND_LOST
        )

    def test_record_poison_never_classifies(self):
        assert devfault.classify(ValueError("bad record")) is None
        assert devfault.classify(
            faults.InjectedPoisonRecord([7])
        ) is None
        assert devfault.classify(KeyError("x")) is None
        # a host MemoryError is not a DEVICE fault
        assert devfault.classify(MemoryError()) is None

    def test_real_xla_runtime_errors(self):
        try:
            from jaxlib.xla_extension import XlaRuntimeError
        except Exception:
            pytest.skip("jaxlib layout exposes no XlaRuntimeError")
        assert devfault.classify(
            XlaRuntimeError(
                "RESOURCE_EXHAUSTED: Out of memory allocating "
                "1073741824 bytes"
            )
        ) == devfault.KIND_OOM
        assert devfault.classify(
            XlaRuntimeError("INTERNAL: Failed to execute XLA runtime")
        ) == devfault.KIND_ERROR
        assert devfault.classify(
            XlaRuntimeError("UNAVAILABLE: device lost: core halted")
        ) == devfault.KIND_LOST


# ---------------------------------------------------------------------------
# the circuit breaker
# ---------------------------------------------------------------------------


class TestCircuitBreaker:
    def test_lifecycle(self):
        clock = {"t": 0.0}
        m = MetricsRegistry()
        b = failover_mod.CircuitBreaker(
            m, key="m1", fail_threshold=3, cooldown_s=1.0,
            probe_greens=2, clock=lambda: clock["t"],
        )
        g = m.gauge('failover_state{model="m1"}')
        assert b.allow_dispatch()
        b.record_failure()
        b.record_failure()
        assert b.state == failover_mod.STATE_CLOSED
        b.record_failure()  # third consecutive: OPEN
        assert b.state == failover_mod.STATE_OPEN
        assert g.get() == failover_mod.STATE_OPEN
        assert not b.allow_dispatch()  # cooldown pending
        clock["t"] = 1.5
        assert b.allow_dispatch()  # flips half-open: this is a probe
        assert b.state == failover_mod.STATE_HALF_OPEN
        b.record_success()
        assert b.state == failover_mod.STATE_HALF_OPEN  # 1 of 2 greens
        b.record_success()
        assert b.state == failover_mod.STATE_CLOSED  # promoted back
        assert g.get() == failover_mod.STATE_CLOSED

    def test_probe_failure_reopens(self):
        clock = {"t": 0.0}
        b = failover_mod.CircuitBreaker(
            None, fail_threshold=1, cooldown_s=1.0, probe_greens=2,
            clock=lambda: clock["t"],
        )
        b.record_failure()
        assert b.state == failover_mod.STATE_OPEN
        clock["t"] = 1.5
        assert b.allow_dispatch()
        b.record_success()  # one green...
        b.record_failure()  # ...then the probe fails: re-open
        assert b.state == failover_mod.STATE_OPEN
        assert not b.allow_dispatch()  # cooldown restarted at t=1.5
        clock["t"] = 3.0
        assert b.allow_dispatch()
        b.record_success()
        b.record_success()
        assert b.state == failover_mod.STATE_CLOSED

    def test_success_streak_clears_strikes(self):
        b = failover_mod.CircuitBreaker(None, fail_threshold=2)
        b.record_failure()
        b.record_success()  # streak broken
        b.record_failure()
        assert b.state == failover_mod.STATE_CLOSED


class TestAdaptiveBatcherOOMCap:
    def test_cap_applies_without_deadline(self):
        from flink_jpmml_tpu.serving.overload import AdaptiveBatcher

        b = AdaptiveBatcher(metrics=MetricsRegistry(), min_records=16)
        assert b.max_records() is None  # no deadline, no cap
        assert b.note_oom_cap(128) == 128
        assert b.max_records() == 128
        # the cap only ever shrinks
        assert b.note_oom_cap(256) == 128
        assert b.note_oom_cap(64) == 64
        assert b.max_records() == 64
        # min_records floors it
        assert b.note_oom_cap(1) == 16


# ---------------------------------------------------------------------------
# the fallback tier
# ---------------------------------------------------------------------------


class TestFallbackTier:
    def test_rank_wire_parity(self, gbm):
        from flink_jpmml_tpu.runtime.block import BoundScorer

        bound = BoundScorer("static", gbm, use_quantized=True)
        assert bound.q is not None and bound.q.backend == "xla"
        tier = failover_mod.FallbackTier()
        assert tier.supports(bound)
        X = _data(32, seed=3)
        out_host = tier.score_bound(bound, X)
        device = bound.q.score(X)
        host = bound.q.decode(out_host, 32)
        assert [p.score.value for p in host] == pytest.approx(
            [p.score.value for p in device]
        )

    def test_f32_parity(self, gbm):
        from flink_jpmml_tpu.runtime.block import BoundScorer

        bound = BoundScorer("static", gbm, use_quantized=False)
        assert bound.q is None
        tier = failover_mod.FallbackTier()
        assert tier.supports(bound)
        X = _data(32, seed=4)
        out_host = tier.score_bound(bound, X)
        host = bound.decode(out_host, 32)
        M = np.zeros_like(X, bool)
        device = gbm.decode(gbm.predict(X, M), 32)
        assert [p.score.value for p in host] == pytest.approx(
            [p.score.value for p in device]
        )

    def test_pallas_unsupported(self, gbm):
        class FakePallasBound:
            class q:
                backend = "pallas"

        tier = failover_mod.FallbackTier()
        assert not tier.supports(FakePallasBound())
        with pytest.raises(failover_mod.FallbackUnavailable):
            tier.score_bound(FakePallasBound(), _data(4))


# ---------------------------------------------------------------------------
# block-path recovery ladder
# ---------------------------------------------------------------------------


class TestBlockLadder:
    def test_transient_error_redispatches_no_quarantine(
        self, gbm, tmp_path
    ):
        from flink_jpmml_tpu.runtime.block import FiniteBlockSource
        from flink_jpmml_tpu.runtime.dlq import DeadLetterQueue

        N = 640
        emitted = []
        faults.inject("device_error", site="device_readback", n=1)
        m = MetricsRegistry()
        pipe = _block_pipe(
            gbm, lambda o, n, f: emitted.append((f, n)), tmp_path,
            metrics=m, source=FiniteBlockSource(_data(N), 32),
            max_dispatch_chunks=1,
        )
        pipe.run_until_exhausted(timeout=60)
        cov = _coverage(emitted, N)
        assert (cov == 1).all()
        c = m.struct_snapshot()["counters"]
        assert c.get("redispatch_records", 0) >= 32
        assert c.get('device_fault_total{kind="device_error"}', 0) >= 1
        assert c.get("fallback_records", 0) == 0  # ladder step 1 won
        assert list(
            DeadLetterQueue(str(tmp_path / "ck" / "dlq")).offsets()
        ) == []

    def test_persistent_error_fails_over_then_recloses(
        self, gbm, tmp_path
    ):
        """The headline drill at test scale: a persistent device-error
        streak trips the breaker onto the fallback tier (serving
        continues), then green probes CLOSE the circuit again — pinned
        with an infinite source and deadline polling so CI load cannot
        race the breaker lifecycle."""
        from flink_jpmml_tpu.runtime.block import CyclingBlockSource
        from flink_jpmml_tpu.runtime.dlq import DeadLetterQueue

        emitted = []
        faults.inject("device_error", site="device_readback", n=7)
        faults.inject("dispatch_delay", delay_ms=2)
        m = MetricsRegistry()
        pipe = _block_pipe(
            gbm, lambda o, n, f: emitted.append((f, n)), tmp_path,
            metrics=m, source=CyclingBlockSource(_data(2048), 32),
            max_dispatch_chunks=1,
        )
        pipe.start()
        try:
            deadline = time.monotonic() + 30.0
            saw_open = False
            while time.monotonic() < deadline:
                if pipe._error is not None:
                    raise pipe._error
                g = m.struct_snapshot()["gauges"]
                state = g.get(
                    'failover_state{model="static"}', {}
                ).get("value")
                if state == failover_mod.STATE_OPEN:
                    saw_open = True
                if saw_open and state == failover_mod.STATE_CLOSED:
                    break
                time.sleep(0.01)
        finally:
            pipe.stop()
            pipe.join(timeout=30)
        assert saw_open, "circuit never opened"
        g = m.struct_snapshot()["gauges"]
        assert g['failover_state{model="static"}']["value"] == (
            failover_mod.STATE_CLOSED
        ), "circuit did not re-close after the outage"
        c = m.struct_snapshot()["counters"]
        assert c.get("fallback_records", 0) > 0
        # zero loss, in-order, no duplication across the whole window
        offs = [o for o, _ in emitted]
        assert offs == sorted(offs)
        cov = _coverage(emitted, int(pipe.committed_offset))
        assert (cov[: int(pipe.committed_offset)] == 1).all()
        assert list(
            DeadLetterQueue(str(tmp_path / "ck" / "dlq")).offsets()
        ) == []

    def test_oom_bisects_and_feeds_the_batcher(self, gbm, tmp_path):
        from flink_jpmml_tpu.runtime.block import FiniteBlockSource
        from flink_jpmml_tpu.runtime.dlq import DeadLetterQueue
        from flink_jpmml_tpu.serving.overload import AdaptiveBatcher

        N = 1280
        emitted = []
        # a 3-deep OOM streak: the full aggregate fails, the redispatch
        # fails, one half fails — the bisection must actually split
        faults.inject("device_oom", site="device_dispatch", n=3)
        m = MetricsRegistry()
        batcher = AdaptiveBatcher(metrics=m, min_records=16)
        pipe = _block_pipe(
            gbm, lambda o, n, f: emitted.append((f, n)), tmp_path,
            metrics=m, source=FiniteBlockSource(_data(N), 32),
            batcher=batcher, max_dispatch_chunks=4,
        )
        pipe.run_until_exhausted(timeout=60)
        cov = _coverage(emitted, N)
        assert (cov == 1).all()
        c = m.struct_snapshot()["counters"]
        assert c.get("oom_shrinks", 0) >= 1
        assert c.get('device_fault_total{kind="device_oom"}', 0) >= 1
        assert batcher.max_records() is not None  # standing cap
        assert list(
            DeadLetterQueue(str(tmp_path / "ck" / "dlq")).offsets()
        ) == []

    def test_chip_loss_escalates(self, gbm, tmp_path):
        from flink_jpmml_tpu.runtime.block import FiniteBlockSource

        faults.inject("chip_loss", n=1)
        pipe = _block_pipe(
            gbm, lambda o, n, f: None, tmp_path,
            source=FiniteBlockSource(_data(320), 32),
            max_dispatch_chunks=1,
        )
        with pytest.raises(faults.InjectedChipLoss):
            pipe.run_until_exhausted(timeout=60)

    def test_poison_still_quarantines_exactly_beside_device_faults(
        self, gbm, tmp_path
    ):
        """Composition pin: genuine record poison lands in the DLQ
        exactly while concurrent device errors land NOWHERE."""
        from flink_jpmml_tpu.runtime.block import FiniteBlockSource
        from flink_jpmml_tpu.runtime.dlq import DeadLetterQueue

        N = 640
        emitted = []
        faults.inject("poison_record", offset=100)
        faults.inject("device_error", site="device_readback", n=4)
        pipe = _block_pipe(
            gbm, lambda o, n, f: emitted.append((f, n)), tmp_path,
            source=FiniteBlockSource(_data(N), 32),
            max_dispatch_chunks=1,
        )
        pipe.run_until_exhausted(timeout=60)
        dlq = sorted(set(
            DeadLetterQueue(str(tmp_path / "ck" / "dlq")).offsets()
        ))
        assert dlq == [100]
        cov = _coverage(emitted, N)
        assert (cov[:100] == 1).all() and (cov[101:] == 1).all()
        assert cov[100] == 0  # quarantined, never sunk

    def test_poison_during_open_circuit_isolates_on_the_tier(
        self, gbm, tmp_path
    ):
        """An OPEN circuit must not exempt poison from the DLQ
        contract: the fallback tier fires the same score_batch site
        and the suspect scan bisects ON the tier."""
        from flink_jpmml_tpu.runtime.block import FiniteBlockSource
        from flink_jpmml_tpu.runtime.dlq import DeadLetterQueue

        N = 640
        emitted = []
        # enough fires that the circuit is open when offset 320's
        # batch arrives on the fallback path
        faults.inject("device_error", site="device_readback", n=50)
        faults.inject("poison_record", offset=320)
        pipe = _block_pipe(
            gbm, lambda o, n, f: emitted.append((f, n)), tmp_path,
            source=FiniteBlockSource(_data(N), 32),
            max_dispatch_chunks=1,
        )
        pipe.run_until_exhausted(timeout=60)
        dlq = sorted(set(
            DeadLetterQueue(str(tmp_path / "ck" / "dlq")).offsets()
        ))
        assert dlq == [320]
        cov = _coverage(emitted, N)
        assert cov[320] == 0
        assert (np.delete(cov, 320) == 1).all()

    def test_fail_fast_without_plane(self, gbm, tmp_path):
        """No DLQ, no FJT_FAILOVER: the historical contract — a device
        error kills the worker (the supervisor's jurisdiction)."""
        from flink_jpmml_tpu.runtime.block import FiniteBlockSource

        assert not os.environ.get("FJT_FAILOVER")
        faults.inject("device_error", site="device_readback", n=1)
        pipe = _block_pipe(
            gbm, lambda o, n, f: None, tmp_path, ckpt=False,
            source=FiniteBlockSource(_data(320), 32),
            max_dispatch_chunks=1,
        )
        assert pipe._failover is None
        with pytest.raises(faults.InjectedDeviceError):
            pipe.run_until_exhausted(timeout=60)


# ---------------------------------------------------------------------------
# record-path (engine) ladder
# ---------------------------------------------------------------------------


def _record_pipe(gbm, records, tmp_path=None, metrics=None):
    from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager
    from flink_jpmml_tpu.runtime.engine import Pipeline, StaticScorer
    from flink_jpmml_tpu.runtime.sinks import CollectSink
    from flink_jpmml_tpu.runtime.sources import InMemorySource
    from flink_jpmml_tpu.utils.config import BatchConfig, RuntimeConfig

    sink = CollectSink()
    pipe = Pipeline(
        InMemorySource(records),
        StaticScorer(gbm),
        sink,
        RuntimeConfig(
            batch=BatchConfig(size=16, deadline_us=500),
            checkpoint_interval_s=0.05,
        ),
        metrics=metrics or MetricsRegistry(),
        checkpoint=(
            CheckpointManager(str(tmp_path / "ck"))
            if tmp_path is not None else None
        ),
    )
    return pipe, sink


class TestEngineLadder:
    def test_transient_error_redispatches(self, gbm, tmp_path):
        records = [list(map(float, row)) for row in _data(96, seed=7)]
        faults.inject("device_error", site="device_readback", n=1)
        m = MetricsRegistry()
        pipe, sink = _record_pipe(
            gbm, records, tmp_path=tmp_path, metrics=m
        )
        pipe.run_until_exhausted(timeout=60)
        assert len(sink.items) == 96
        c = m.struct_snapshot()["counters"]
        assert c.get("redispatch_records", 0) >= 1
        assert c.get('device_fault_total{kind="device_error"}', 0) >= 1

    def test_unarmed_record_path_fails_fast(self, gbm):
        """No DLQ, no FJT_FAILOVER: the record path keeps the
        historical contract too — a device error kills the worker."""
        records = [list(map(float, row)) for row in _data(48, seed=15)]
        faults.inject("device_error", site="device_readback", n=1)
        pipe, _sink = _record_pipe(gbm, records)
        with pytest.raises(faults.InjectedDeviceError):
            pipe.run_until_exhausted(timeout=60)

    def test_device_error_never_quarantines(self, gbm, tmp_path):
        from flink_jpmml_tpu.runtime.dlq import DeadLetterQueue

        records = [list(map(float, row)) for row in _data(96, seed=8)]
        faults.inject("device_error", site="device_readback", n=1)
        pipe, sink = _record_pipe(gbm, records, tmp_path=tmp_path)
        pipe.run_until_exhausted(timeout=60)
        assert len(sink.items) == 96
        assert list(
            DeadLetterQueue(str(tmp_path / "ck" / "dlq")).offsets()
        ) == []

    def test_oom_bisects_below_half(self, gbm, tmp_path):
        """A device that only fits a QUARTER of the micro-batch must
        still converge (size halves per OOM seen, and halvings don't
        spend the transient-retry budget)."""
        records = [list(map(float, row)) for row in _data(64, seed=9)]
        # 3 OOMs: full batch, the half, the quarter — success at 1/8
        faults.inject("device_oom", site="device_readback", n=3)
        m = MetricsRegistry()
        pipe, sink = _record_pipe(
            gbm, records, tmp_path=tmp_path, metrics=m
        )
        pipe.run_until_exhausted(timeout=60)
        assert len(sink.items) == 64
        assert m.struct_snapshot()["counters"].get(
            'device_fault_total{kind="device_oom"}', 0
        ) >= 2

    def test_chip_loss_escalates(self, gbm, tmp_path):
        records = [list(map(float, row)) for row in _data(64, seed=10)]
        faults.inject("chip_loss", n=1)
        pipe, sink = _record_pipe(gbm, records, tmp_path=tmp_path)
        with pytest.raises(faults.InjectedChipLoss):
            pipe.run_until_exhausted(timeout=60)


class TestDynamicScorerRedispatch:
    def test_group_redispatch(self, tmp_path):
        import pathlib

        from flink_jpmml_tpu.models.control import AddMessage
        from flink_jpmml_tpu.runtime.sources import ControlSource
        from flink_jpmml_tpu.serving.scorer import DynamicScorer

        xml = """<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3">
  <Header/>
  <DataDictionary numberOfFields="2">
    <DataField name="a" optype="continuous" dataType="double"/>
    <DataField name="y" optype="continuous" dataType="double"/>
  </DataDictionary>
  <RegressionModel functionName="regression">
    <MiningSchema>
      <MiningField name="y" usageType="target"/>
      <MiningField name="a"/>
    </MiningSchema>
    <RegressionTable intercept="3.5"/>
  </RegressionModel></PMML>"""
        p = pathlib.Path(tmp_path, "c.pmml")
        p.write_text(xml)
        ctrl = ControlSource()
        m = MetricsRegistry()
        sc = DynamicScorer(control=ctrl, batch_size=4, metrics=m)
        ctrl.push(AddMessage("m", 1, str(p), timestamp=1.0))
        out = sc.finish(sc.submit([("m", {"a": 0.0})]))
        assert out[0][0].score.value == pytest.approx(3.5)
        # now a transient device fault on the NEXT batch's readback
        faults.inject("device_error", site="device_readback", n=1)
        out = sc.finish(
            sc.submit([("m", {"a": 0.0}), ("m", {"a": 1.0})])
        )
        assert [p_.score.value for p_, _ in out] == pytest.approx(
            [3.5, 3.5]
        )
        c = m.struct_snapshot()["counters"]
        assert c.get("redispatch_records", 0) >= 2
        assert c.get('device_fault_total{kind="device_error"}', 0) >= 1


# ---------------------------------------------------------------------------
# checkpoint ENOSPC degrade
# ---------------------------------------------------------------------------


class TestCheckpointEnospcDegrade:
    def test_suspends_then_resumes(self, gbm, tmp_path, monkeypatch):
        from flink_jpmml_tpu.obs import recorder as flight
        from flink_jpmml_tpu.runtime.block import FiniteBlockSource

        monkeypatch.setenv("FJT_RETRY_MAX", "2")
        monkeypatch.setenv("FJT_RETRY_BASE_S", "0.001")
        N = 960
        # errno=28 (ENOSPC), persistent for the first 8 save attempts,
        # then "space returns": the plane must suspend, keep serving,
        # and resume without intervention
        faults.inject("checkpoint_fail", errno=28, n=8)
        emitted = []
        m = MetricsRegistry()
        pipe = _block_pipe(
            gbm, lambda o, n, f: emitted.append((f, n)), tmp_path,
            metrics=m, source=FiniteBlockSource(_data(N), 32),
            ckpt_interval=0.0,  # save every batch: fast convergence
            max_dispatch_chunks=1,
        )
        pipe.run_until_exhausted(timeout=60)
        cov = _coverage(emitted, N)
        assert (cov == 1).all()  # serving never stopped
        kinds = [e["kind"] for e in flight.events()]
        assert "checkpoint_suspended" in kinds
        assert "checkpoint_resumed" in kinds
        g = m.struct_snapshot()["gauges"]
        assert g.get("checkpoint_suspended", {}).get("value") == 0.0
        # the cadence resumed: a checkpoint landed with the final offset
        from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager

        st = CheckpointManager(str(tmp_path / "ck")).load_latest()
        assert st is not None and int(st["source_offset"]) == N

    def test_non_enospc_still_raises(self, gbm, tmp_path, monkeypatch):
        from flink_jpmml_tpu.runtime.block import FiniteBlockSource
        from flink_jpmml_tpu.utils.exceptions import CheckpointException

        monkeypatch.setenv("FJT_RETRY_MAX", "2")
        monkeypatch.setenv("FJT_RETRY_BASE_S", "0.001")
        faults.inject("checkpoint_fail")  # persistent, no errno
        pipe = _block_pipe(
            gbm, lambda o, n, f: None, tmp_path,
            source=FiniteBlockSource(_data(320), 32),
            ckpt_interval=0.0, max_dispatch_chunks=1,
        )
        with pytest.raises(CheckpointException):
            pipe.run_until_exhausted(timeout=60)


# ---------------------------------------------------------------------------
# degraded mesh (the conftest's 8-device virtual CPU mesh)
# ---------------------------------------------------------------------------


class TestDegradedMesh:
    def test_dp_mesh_minus_one_chip(self, gbm):
        import jax

        from flink_jpmml_tpu.parallel.mesh import make_mesh
        from flink_jpmml_tpu.parallel.sharding import dp_sharded

        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device virtual CPU mesh")
        mesh = make_mesh()
        sm = dp_sharded(gbm, mesh)
        assert sm.batch_divisor == 8
        X = _data(28, seed=11)  # ≤ the compiled batch on both meshes
        want = [p.score.value for p in sm.score_dense(X)]
        degraded = sm.without_devices([mesh.devices.flat[3]])
        assert degraded.batch_divisor == 7
        lost_id = mesh.devices.flat[3].id
        assert all(
            d.id != lost_id for d in degraded.mesh.devices.flat
        )
        got = [p.score.value for p in degraded.score_dense(X)]
        assert got == pytest.approx(want)

    def test_tp_mesh_preserves_model_axis(self, gbm):
        import jax

        from flink_jpmml_tpu.parallel.mesh import MODEL_AXIS, make_mesh
        from flink_jpmml_tpu.parallel.sharding import (
            degraded_mesh, mesh_sharded,
        )
        from flink_jpmml_tpu.utils.config import MeshConfig

        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device virtual CPU mesh")
        mesh = make_mesh(MeshConfig(data=4, model=2))
        m2 = degraded_mesh(mesh, [mesh.devices.flat[0]])
        assert m2.shape[MODEL_AXIS] == 2
        assert m2.shape["data"] == 3  # 7 survivors // model 2
        sm = mesh_sharded(gbm, mesh)
        degraded = sm.without_devices([mesh.devices.flat[0]])
        assert degraded.mesh.shape["data"] == 3
        X = _data(24, seed=12)  # ≤ the compiled batch on both meshes
        want = [p.score.value for p in sm.score_dense(X)]
        got = [p.score.value for p in degraded.score_dense(X)]
        assert got == pytest.approx(want)

    def test_unsurvivable_mesh_raises(self):
        import jax

        from flink_jpmml_tpu.parallel.mesh import make_mesh
        from flink_jpmml_tpu.parallel.sharding import degraded_mesh
        from flink_jpmml_tpu.utils.exceptions import FlinkJpmmlTpuError

        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device virtual CPU mesh")
        mesh = make_mesh()
        with pytest.raises(FlinkJpmmlTpuError):
            degraded_mesh(mesh, list(mesh.devices.flat))

    def test_per_chip_metrics_merge_exactly(self):
        """The DrJAX discipline that makes degraded-mesh mode cheap:
        per-chip telemetry merges EXACTLY, so the fleet view of a
        7-chip mesh is just the merge over 7 structs — no
        rebaselining. Histogram buckets add bitwise."""
        from flink_jpmml_tpu.utils.metrics import merge_structs

        regs = [MetricsRegistry() for _ in range(8)]
        rng = np.random.default_rng(13)
        for r in regs:
            h = r.histogram("batch_latency_s")
            for v in rng.exponential(0.01, size=50):
                h.observe(float(v))
            r.counter("records_out").inc(100)
        full = merge_structs([r.struct_snapshot() for r in regs])
        minus_one = merge_structs(
            [r.struct_snapshot() for r in regs[:7]]
        )
        assert full["counters"]["records_out"] == 800
        assert minus_one["counters"]["records_out"] == 700
        # re-merging the lost chip's struct back restores the full
        # view bit-for-bit: merge is associative and lossless
        readded = merge_structs(
            [minus_one, regs[7].struct_snapshot()]
        )
        assert readded["histograms"]["batch_latency_s"] == (
            full["histograms"]["batch_latency_s"]
        )

    def test_device_health_transitions(self):
        import jax

        from flink_jpmml_tpu.parallel.health import DeviceHealth

        devs = jax.devices()
        lost_cb, rec_cb = [], []
        m = MetricsRegistry()
        dh = DeviceHealth(
            metrics=m, on_lost=lost_cb.append, on_recover=rec_cb.append
        ).watch(devs)
        assert dh.mark_lost(devs[0], error=faults.InjectedChipLoss())
        assert not dh.mark_lost(devs[0])  # idempotent transition
        assert lost_cb == [devs[0]]
        assert m.gauge("mesh_lost_devices").get() == 1.0
        assert devs[0] not in dh.alive()
        assert dh.survivors(devs) == list(devs[1:])
        assert dh.mark_recovered(devs[0])
        assert rec_cb == [devs[0]]
        assert m.gauge("mesh_lost_devices").get() == 0.0


# ---------------------------------------------------------------------------
# grammar + summary surfaces
# ---------------------------------------------------------------------------


class TestFaultGrammar:
    def test_device_kind_sites(self):
        fs = faults.parse_spec(
            "device_error:site=device_dispatch:n=2,"
            "device_oom:n=1,chip_loss:after_s=1"
        )
        assert [f.kind for f in fs] == [
            "device_error", "device_oom", "chip_loss",
        ]
        assert fs[0].site == "device_dispatch"
        assert fs[1].site == "device_readback"  # default: readback

    def test_device_kind_rejects_foreign_site(self):
        with pytest.raises(ValueError):
            faults.parse_spec("device_error:site=kafka_fetch")
        with pytest.raises(ValueError):
            faults.parse_spec("slow_fetch:site=device_readback")

    def test_checkpoint_fail_errno(self):
        (f,) = faults.parse_spec("checkpoint_fail:errno=28")
        with pytest.raises(faults.InjectedCheckpointFailure) as ei:
            f.act()
        assert ei.value.errno == 28

    def test_worker_crash_may_target_device_sites(self):
        (f,) = faults.parse_spec(
            "worker_crash:site=device_readback:n=0"
        )
        assert f.site == "device_readback"


class TestFailoverSummary:
    def test_summary_fields(self):
        m = MetricsRegistry()
        plane = failover_mod.FailoverPlane(m)
        plane.breaker_for("m1").record_failure()
        plane.note_fallback(64, "m1")
        plane.redispatch_records.inc(32)
        plane.oom_shrinks.inc()
        m.counter('device_fault_total{kind="device_error"}').inc(3)
        m.counter("records_out").inc(640)
        s = failover_mod.summary(m.struct_snapshot())
        assert s["states"] == {"m1": "closed"}
        assert s["fallback_records"] == 64
        assert s["redispatch_records"] == 32
        assert s["oom_shrinks"] == 1
        assert s["device_faults"] == {"device_error": 3.0}
        assert s["fallback_share"] == pytest.approx(0.1)

    def test_top_panel_renders(self, capsys):
        import io

        from flink_jpmml_tpu import cli

        m = MetricsRegistry()
        plane = failover_mod.FailoverPlane(m)
        b = plane.breaker_for("m1")
        b.record_failure()
        b.record_failure()
        b.record_failure()
        plane.note_fallback(100, "m1")
        m.counter("records_out").inc(1000)
        out = io.StringIO()
        cli._top_render_failover(
            "w0", m.struct_snapshot(), out, source="dump.json"
        )
        text = out.getvalue()
        assert "open" in text
        assert "fallback" in text
        assert "fjt-trace" in text

    def test_empty_panel_fallback_line(self):
        import io

        from flink_jpmml_tpu import cli

        out = io.StringIO()
        cli._top_render_failover("w0", {}, out)
        assert "no failover telemetry" in out.getvalue()
