"""Fused on-device featurization (ISSUE 2 tentpole) parity suite.

The fused path ships raw f32 batches and runs the threshold-rank
bucketize as an XLA pre-stage traced into the scoring jit; the host
bucketizer (``QuantizedWire.encode``) stays the byte-parity oracle.
These tests pin, on the CPU test backend (interpret mode for Pallas):

- BYTE parity of ``QuantizedScorer.encode_device`` against
  ``wire.encode`` — code for code, dtype for dtype — across golden
  models, NaN patterns, mining-schema ``missingValueReplacement``,
  explicit missing masks, ±inf cells, and the uint16 wire;
- end-to-end fused scoring parity (``predict_fused`` vs host-encoded
  ``predict_wire``) including pad-lane trimming on odd batch sizes and
  classification triples;
- the shared runtime dispatch helper
  (``runtime.pipeline.dispatch_quantized``) taking the fused path and
  accounting ``encode_s``/``h2d_bytes``.
"""

import numpy as np
import pytest

from assets.generate import gen_gbm
from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.compile.qtrees import build_quantized_scorer
from flink_jpmml_tpu.pmml import parse_pmml, parse_pmml_file
from flink_jpmml_tpu.runtime.pipeline import dispatch_quantized
from flink_jpmml_tpu.utils.metrics import MetricsRegistry

from test_qtrees import _forest_xml


def _doc(tmp_path, **kw):
    return parse_pmml_file(gen_gbm(str(tmp_path), **kw))


def _rand_X(rng, n, f, missing_rate=0.0):
    X = rng.normal(0.0, 1.5, size=(n, f)).astype(np.float32)
    if missing_rate:
        X[rng.random(size=X.shape) < missing_rate] = np.nan
    return X


_REPL_XML = """<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3">
  <Header/>
  <DataDictionary numberOfFields="3">
    <DataField name="a" optype="continuous" dataType="double"/>
    <DataField name="b" optype="continuous" dataType="double"/>
    <DataField name="y" optype="continuous" dataType="double"/>
  </DataDictionary>
  <TreeModel functionName="regression" missingValueStrategy="defaultChild"
             splitCharacteristic="binarySplit">
    <MiningSchema>
      <MiningField name="y" usageType="target"/>
      <MiningField name="a" missingValueReplacement="0.25"/>
      <MiningField name="b"/>
    </MiningSchema>
    <Node id="0" defaultChild="1"><True/>
      <Node id="1" defaultChild="3">
        <SimplePredicate field="a" operator="lessThan" value="0.1"/>
        <Node id="3" score="1.5">
          <SimplePredicate field="b" operator="lessOrEqual" value="-0.2"/>
        </Node>
        <Node id="4" score="-2.0">
          <SimplePredicate field="b" operator="greaterThan" value="-0.2"/>
        </Node>
      </Node>
      <Node id="2" score="3.0">
        <SimplePredicate field="a" operator="greaterOrEqual" value="0.1"/>
      </Node>
    </Node>
  </TreeModel></PMML>"""


class TestEncodeByteParity:
    def _assert_codes_equal(self, q, X, M=None):
        host = q.wire.encode(X, M)
        Xd = X if M is None else np.where(M, np.nan, X)
        dev = np.asarray(q.encode_device(Xd))
        assert dev.dtype == host.dtype
        np.testing.assert_array_equal(dev, host)

    def test_uint8_wire_with_nans(self, tmp_path):
        doc = _doc(tmp_path, n_trees=15, depth=4, n_features=8)
        q = build_quantized_scorer(doc, batch_size=64)
        assert q.supports_fused and q.wire.dtype is np.uint8
        rng = np.random.default_rng(0)
        self._assert_codes_equal(q, _rand_X(rng, 64, 8, missing_rate=0.3))

    def test_uint16_wire(self, tmp_path):
        # >254 cuts/feature → uint16 sentinel 65535; ranks must still be
        # exact through the on-device searchsorted (int32 → uint16 cast)
        doc = _doc(
            tmp_path, n_trees=300, depth=5, n_features=2, hist_bins=None
        )
        q = build_quantized_scorer(doc, batch_size=32)
        assert q.wire.dtype is np.uint16
        if not q.supports_fused:
            pytest.skip("device tables over budget for this model")
        rng = np.random.default_rng(1)
        self._assert_codes_equal(q, _rand_X(rng, 32, 2, missing_rate=0.2))

    def test_missing_value_replacement_folds_in(self):
        # NaN in a replaced column must take the mining-schema value
        # (NOT the sentinel); NaN in an unreplaced column stays missing
        doc = parse_pmml(_REPL_XML)
        q = build_quantized_scorer(doc, batch_size=8)
        assert q is not None and q.supports_fused
        X = np.array(
            [[np.nan, -0.5], [np.nan, 0.5], [0.0, np.nan], [2.0, -1.0]],
            np.float32,
        )
        host = q.wire.encode(X)
        dev = np.asarray(q.encode_device(X))
        np.testing.assert_array_equal(dev, host)
        # column a (replacement declared): no sentinel even for NaN
        assert (dev[:2, 0] != q.wire.sentinel).all()
        # column b (no replacement): NaN becomes the sentinel
        assert dev[2, 1] == q.wire.sentinel

    def test_explicit_mask_folds_as_nan(self, tmp_path):
        # the dynamic scorer's record path carries (X, M) with zeros at
        # masked cells; fused folds M in as NaN — codes must match the
        # host encoder given the same mask
        doc = _doc(tmp_path, n_trees=10, depth=3, n_features=4)
        q = build_quantized_scorer(doc, batch_size=16)
        rng = np.random.default_rng(2)
        X = _rand_X(rng, 16, 4)
        M = rng.random(size=X.shape) < 0.25
        Xz = np.where(M, 0.0, X).astype(np.float32)
        self._assert_codes_equal(q, Xz, M)

    def test_infinite_cells(self, tmp_path):
        # +inf ranks past every finite cut (== len(cuts), never the
        # sentinel and never perturbed by the +inf table pads); -inf
        # ranks 0 — bit-exact with numpy searchsorted either way
        doc = _doc(tmp_path, n_trees=10, depth=3, n_features=4)
        q = build_quantized_scorer(doc, batch_size=8)
        rng = np.random.default_rng(3)
        X = _rand_X(rng, 8, 4)
        X[0, 0] = np.inf
        X[1, 1] = -np.inf
        X[2, 2] = np.nan
        self._assert_codes_equal(q, X)

    def test_exact_cut_values_rank_left(self, tmp_path):
        # x exactly equal to a cut must rank strictly-less (#{c < x})
        # on both sides — the bit-exactness contract of the rank wire
        doc = _doc(tmp_path, n_trees=12, depth=4, n_features=4)
        q = build_quantized_scorer(doc, batch_size=None)
        cuts = q.wire.cuts
        rows = []
        for j, c in enumerate(cuts):
            if len(c):
                row = np.zeros((len(cuts),), np.float32)
                row[j] = c[len(c) // 2]
                rows.append(row)
        X = np.asarray(rows, np.float32)
        self._assert_codes_equal(q, X)


class TestFusedScoringParity:
    def test_xla_regression_all_lanes(self, tmp_path):
        doc = _doc(tmp_path, n_trees=21, depth=4, n_features=8)
        B = 64
        q = build_quantized_scorer(doc, batch_size=B, backend="xla")
        rng = np.random.default_rng(4)
        for n in (B, B - 9, 2 * B, 2 * B + 7):
            X = _rand_X(rng, n, 8, missing_rate=0.2)
            host = q.decode(q.predict_wire(q.wire.encode(X)), n)
            fused = q.decode(q.predict_fused(X), n)
            np.testing.assert_allclose(
                [p.score.value for p in fused],
                [p.score.value for p in host],
                rtol=0, atol=0,
            )

    def test_pallas_interpret_fused(self, tmp_path):
        doc = _doc(tmp_path, n_trees=13, depth=3, n_features=4)
        B = 32
        qp = build_quantized_scorer(
            doc, batch_size=B, backend="pallas", pallas_interpret=True
        )
        assert qp is not None and qp.backend == "pallas"
        assert qp.supports_fused
        rng = np.random.default_rng(5)
        for n in (B, 2 * B):  # exercises the fused scan (K > 1) too
            X = _rand_X(rng, n, 4, missing_rate=0.15)
            host = np.asarray(
                qp.predict_wire(qp.wire.encode(X)), np.float32
            )[:n]
            fused = np.asarray(qp.predict_fused(X), np.float32)[:n]
            np.testing.assert_allclose(fused, host, rtol=0, atol=0)

    def test_classification_triple_fused(self):
        doc = parse_pmml(_forest_xml("majorityVote", n_trees=8))
        B = 32
        q = build_quantized_scorer(doc, batch_size=B, backend="xla")
        assert q.is_classification and q.supports_fused
        rng = np.random.default_rng(6)
        X = _rand_X(rng, B, 4, missing_rate=0.2)
        hv, hp, hl = q.predict_wire(q.wire.encode(X))
        fv, fp, fl = q.predict_fused(X)
        np.testing.assert_array_equal(np.asarray(fl), np.asarray(hl))
        np.testing.assert_allclose(
            np.asarray(fp), np.asarray(hp), rtol=0, atol=0
        )
        np.testing.assert_allclose(
            np.asarray(fv), np.asarray(hv), rtol=0, atol=0
        )

    def test_f32_reference_agreement(self, tmp_path):
        doc = _doc(tmp_path, n_trees=15, depth=4, n_features=6)
        B = 64
        cm = compile_pmml(doc, batch_size=B)
        q = build_quantized_scorer(doc, batch_size=B)
        rng = np.random.default_rng(7)
        X = _rand_X(rng, B, 6, missing_rate=0.25)
        M = np.isnan(X)
        ref = np.asarray(
            cm.predict(np.nan_to_num(X, nan=0.0), M).value, np.float32
        )
        fused = np.asarray(q.predict_fused(X), np.float32)
        np.testing.assert_allclose(fused, ref, rtol=1e-4, atol=1e-5)


class TestDispatchHelper:
    def _scorer(self, tmp_path):
        doc = _doc(tmp_path, n_trees=10, depth=3, n_features=4)
        return build_quantized_scorer(doc, batch_size=32)

    def test_fused_vs_host_identical_scores(self, tmp_path):
        q = self._scorer(tmp_path)
        rng = np.random.default_rng(8)
        X = _rand_X(rng, 32, 4, missing_rate=0.2)
        q.encode_mode = "host"
        host = np.asarray(dispatch_quantized(q, X), np.float32)
        q.encode_mode = "fused"
        fused = np.asarray(dispatch_quantized(q, X), np.float32)
        np.testing.assert_allclose(fused, host, rtol=0, atol=0)

    def test_metrics_accounting(self, tmp_path):
        q = self._scorer(tmp_path)
        rng = np.random.default_rng(9)
        X = _rand_X(rng, 32, 4)
        m_host = MetricsRegistry()
        q.encode_mode = "host"
        dispatch_quantized(q, X, metrics=m_host)
        assert m_host.counter("encode_s").get() > 0
        # uint8 wire: one byte per feature per record
        assert m_host.counter("h2d_bytes").get() == 32 * 4
        m_fused = MetricsRegistry()
        q.encode_mode = "fused"
        dispatch_quantized(q, X, metrics=m_fused)
        # fused ships raw f32: 4 bytes per feature per record
        assert m_fused.counter("h2d_bytes").get() == 32 * 4 * 4

    def test_mask_path_through_helper(self, tmp_path):
        q = self._scorer(tmp_path)
        rng = np.random.default_rng(10)
        X = _rand_X(rng, 32, 4)
        M = rng.random(size=X.shape) < 0.3
        Xz = np.where(M, 0.0, X).astype(np.float32)
        q.encode_mode = "host"
        host = np.asarray(dispatch_quantized(q, Xz, M), np.float32)
        q.encode_mode = "fused"
        fused = np.asarray(dispatch_quantized(q, Xz, M), np.float32)
        np.testing.assert_allclose(fused, host, rtol=0, atol=0)

    def test_fused_falls_back_when_unsupported(self, tmp_path):
        # a stale "fused" mode on a scorer without device tables must
        # quietly take the host path, not raise
        q = self._scorer(tmp_path)
        q._fused_inner = None
        q.encode_mode = "fused"
        rng = np.random.default_rng(11)
        X = _rand_X(rng, 32, 4)
        out = np.asarray(dispatch_quantized(q, X), np.float32)
        assert out.shape == (32,)
