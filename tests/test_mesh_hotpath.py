"""Multichip hot-path serving (PR 16): ShardedModel promoted into the
streaming pipelines.

The contracts under test:

- key-stable splits (parallel/assignment.py): rendezvous-hashed chip
  ownership of kafka partitions and record keys moves ONLY the dead
  chip's work across a degraded-mesh resize — every healthy chip keeps
  exactly what it had, composed end-to-end with the producer-side
  HashPartitioner lanes;
- canary splits across shards (rollout/split.py): assign_candidate is
  a pure function of the key, so per-shard canary fractions match the
  global fraction and survive a resize untouched;
- ``ShardedModel.without_devices`` carries the dispatcher/window state
  and the partition assignment through the rebuild;
- chip loss ON the mesh hot path (runtime/block.py KIND_LOST rung):
  the pipeline rebuilds over the survivors in place — zero loss, zero
  duplication, EMPTY DLQ, per-chip telemetry flags the dead chip;
- the mesh chaos-soak profile (tools/fuzz_soak.py --chaos --mesh),
  slow-marked.

Runs on the virtual 8-CPU mesh (tests/conftest.py).
"""

import os
import pathlib
import subprocess
import sys

import numpy as np
import pytest

from flink_jpmml_tpu.parallel.assignment import (
    ChipAssignment, assignment_for, mesh_in_flight,
)
from flink_jpmml_tpu.parallel.partitioner import HashPartitioner
from flink_jpmml_tpu.rollout import split as rsplit
from flink_jpmml_tpu.runtime import faults
from flink_jpmml_tpu.utils.metrics import MetricsRegistry


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def gbm(tmp_path_factory):
    from flink_jpmml_tpu.assets_gen import gen_gbm
    from flink_jpmml_tpu.compile import compile_pmml
    from flink_jpmml_tpu.pmml import parse_pmml_file

    tmp = tmp_path_factory.mktemp("mesh-hotpath-gbm")
    pmml = gen_gbm(str(tmp), n_trees=4, depth=3, n_features=5)
    return compile_pmml(parse_pmml_file(pmml), batch_size=32)


def _data(n, seed=0, cols=5):
    rng = np.random.default_rng(seed)
    return rng.normal(0, 1.0, size=(n, cols)).astype(np.float32)


def _mesh_4x2():
    import jax

    from flink_jpmml_tpu.parallel.mesh import make_mesh
    from flink_jpmml_tpu.utils.config import MeshConfig

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    return make_mesh(MeshConfig(data=4, model=2))


KEYS = [f"user-{i}" for i in range(2000)]


# ---------------------------------------------------------------------------
# key-stable splits across a degraded-mesh resize
# ---------------------------------------------------------------------------


class TestKeyStability:
    def test_healthy_chips_keep_their_keys(self):
        a = ChipAssignment((0, 1, 2, 3))
        before = {k: a.chip_for_key(k) for k in KEYS}
        shrunk = a.without([2])
        for k, chip in before.items():
            if chip == 2:
                assert shrunk.chip_for_key(k) in (0, 1, 3)
            else:
                # the rendezvous property: survivors keep every key
                assert shrunk.chip_for_key(k) == chip

    def test_healthy_chips_keep_their_partitions(self):
        a = ChipAssignment((0, 1, 2, 3), partitions=range(16))
        shrunk = a.without([1])
        for p in range(16):
            owner = a.chip_for_partition(p)
            if owner != 1:
                assert shrunk.chip_for_partition(p) == owner
        # the dead chip's partitions all re-homed onto survivors
        orphans = a.partitions_for(1)
        assert orphans  # 16 partitions over 4 chips: never empty
        for p in orphans:
            assert shrunk.chip_for_partition(p) in (0, 2, 3)

    def test_producer_lane_to_chip_end_to_end(self):
        """Composed stability: producer-side HashPartitioner lanes
        (fixed partition count — the topic doesn't resize when a chip
        dies) plus rendezvous partition→chip ownership ⇒ a record key
        scored on a healthy chip stays on that chip across the
        resize."""
        n_parts = 16
        hp = HashPartitioner(n_parts)
        a = ChipAssignment((0, 1, 2, 3), partitions=range(n_parts))
        shrunk = a.without([3])
        for k in KEYS:
            part = hp.lane(k)
            before = a.chip_for_partition(part)
            if before != 3:
                assert shrunk.chip_for_partition(part) == before

    def test_split_groups_by_owner(self):
        a = ChipAssignment((0, 1, 2, 3))
        groups = a.split(KEYS)
        assert sorted(sum(groups.values(), [])) == sorted(KEYS)
        for chip, members in groups.items():
            for k in members:
                assert a.chip_for_key(k) == chip

    def test_mesh_row_ids_survive_resize(self):
        """for_mesh labels lanes by each data row's FIRST device id, so
        the surviving rows keep their identity (and weights) after
        degraded_mesh trims a row."""
        mesh = _mesh_4x2()
        a = assignment_for(mesh, partitions=range(8))
        row_ids = a.chips
        assert len(row_ids) == 4
        lost_row = list(mesh.devices.reshape(4, -1)[-1])
        shrunk = a.without(lost_row)
        assert shrunk.chips == tuple(
            c for c in row_ids
            if c not in {d.id for d in lost_row}
        )

    def test_in_flight_geometry(self):
        mesh = _mesh_4x2()
        assert mesh_in_flight(None, 2) == 2
        assert mesh_in_flight(mesh, 2) == 4
        assert mesh_in_flight(mesh, 6) == 6


# ---------------------------------------------------------------------------
# canary fractions per shard
# ---------------------------------------------------------------------------


class TestCanaryAcrossShards:
    def test_fraction_preserved_per_shard(self):
        """assign_candidate is a pure function of the key (chip-blind),
        so each shard's canary fraction tracks the global fraction and
        a degraded-mesh resize cannot change any key's canary side."""
        fraction = 0.2
        a = ChipAssignment((0, 1, 2, 3))
        flags = {
            k: rsplit.assign_candidate("m", 2, fraction, k)
            for k in KEYS
        }
        global_frac = sum(flags.values()) / len(KEYS)
        assert abs(global_frac - fraction) < 0.05
        for chip, members in a.split(KEYS).items():
            assert len(members) > 100  # rendezvous spreads the keys
            frac = sum(flags[k] for k in members) / len(members)
            assert abs(frac - global_frac) < 0.07, (
                f"chip {chip} canary fraction {frac:.3f} drifted from "
                f"global {global_frac:.3f}"
            )

    def test_resize_never_flips_a_canary_side(self):
        """Each surviving chip's canary population is IDENTICAL before
        and after the resize: keys neither re-home off survivors nor
        change canary side (assign_candidate is key-pure), so a mid-
        rollout chip loss cannot skew the canary comparison."""
        fraction = 0.3
        a = ChipAssignment((0, 1, 2, 3))
        shrunk = a.without([0])
        canary = {
            k for k in KEYS
            if rsplit.assign_candidate("m", 2, fraction, k)
        }
        before = {
            chip: {k for k in ks if k in canary}
            for chip, ks in a.split(KEYS).items()
        }
        after = {
            chip: {k for k in ks if k in canary}
            for chip, ks in shrunk.split(KEYS).items()
        }
        for chip in (1, 2, 3):
            # survivors keep their exact canary slice; the dead chip's
            # slice re-homes as a whole
            assert before[chip] <= after[chip]
            assert after[chip] - before[chip] <= before[0]


# ---------------------------------------------------------------------------
# without_devices carries serving state
# ---------------------------------------------------------------------------


class TestRebuildCarry:
    def test_dispatch_state_and_assignment_carry(self, gbm):
        from flink_jpmml_tpu.parallel.sharding import mesh_sharded

        mesh = _mesh_4x2()
        sm = mesh_sharded(gbm, mesh)
        sm.with_dispatch_state(in_flight=4, donate=False)
        sm.assignment = assignment_for(mesh, partitions=range(8))
        lost = list(mesh.devices.reshape(4, -1)[-1])
        rebuilt = sm.without_devices(lost)
        assert rebuilt.dispatch_state == sm.dispatch_state
        assert rebuilt.dispatch_state is not sm.dispatch_state
        assert rebuilt.assignment is not None
        assert rebuilt.assignment.chips == sm.assignment.without(
            lost
        ).chips
        assert rebuilt.assignment.partitions == (
            sm.assignment.partitions
        )
        assert rebuilt.in_flight_depth(2) == 4  # carried window depth


# ---------------------------------------------------------------------------
# chip loss on the mesh hot path
# ---------------------------------------------------------------------------


class TestMeshChipLoss:
    def test_pipeline_survives_chip_loss(self, gbm, tmp_path,
                                         monkeypatch):
        from flink_jpmml_tpu.obs import mesh as mesh_obs
        from flink_jpmml_tpu.runtime.block import (
            BlockPipeline, FiniteBlockSource,
        )
        from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager
        from flink_jpmml_tpu.runtime.dlq import DeadLetterQueue
        from flink_jpmml_tpu.utils.config import (
            BatchConfig, RuntimeConfig,
        )

        monkeypatch.setenv("FJT_RETRY_BASE_S", "0.005")
        mesh = _mesh_4x2()
        N = 640
        emitted = []
        m = MetricsRegistry()
        faults.inject("chip_loss", n=1)
        pipe = BlockPipeline(
            FiniteBlockSource(_data(N), 32), gbm,
            lambda o, n, f: emitted.append((f, n)),
            RuntimeConfig(
                batch=BatchConfig(size=32, deadline_us=500),
                checkpoint_interval_s=0.05,
            ),
            metrics=m,
            # the checkpoint auto-wires the DLQ beside it, which arms
            # the failover plane — the production shape of the ladder
            checkpoint=CheckpointManager(str(tmp_path / "ck")),
            use_native=False,
            max_dispatch_chunks=1,
            mesh=mesh,
        )
        pipe.run_until_exhausted(timeout=120)
        cov = np.zeros(N, np.int64)
        for off, n in emitted:
            cov[off: off + n] += 1
        assert (cov == 1).all()
        assert list(
            DeadLetterQueue(str(tmp_path / "ck" / "dlq")).offsets()
        ) == []
        snap = m.struct_snapshot()
        c, g = snap["counters"], snap["gauges"]
        assert c.get("mesh_rebuilds", 0) == 1
        assert g["mesh_data_width"]["value"] == 3.0
        assert g["mesh_lost_devices"]["value"] == 2.0  # one 4x2 row
        s = mesh_obs.summary(snap)
        assert s is not None and s["data_width"] == 3.0
        lost = [
            chip for chip, v in s["chips"].items()
            if v["state"] == "lost"
        ]
        assert len(lost) == 1

    def test_single_chip_still_escalates(self, gbm, tmp_path,
                                         monkeypatch):
        """The historical contract is untouched off the mesh: a
        single-chip model's chip loss raises to the supervisor."""
        from flink_jpmml_tpu.runtime.block import (
            BlockPipeline, FiniteBlockSource,
        )
        from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager
        from flink_jpmml_tpu.utils.config import (
            BatchConfig, RuntimeConfig,
        )

        monkeypatch.setenv("FJT_RETRY_BASE_S", "0.005")
        m = MetricsRegistry()
        faults.inject("chip_loss", n=1)
        pipe = BlockPipeline(
            FiniteBlockSource(_data(320), 32), gbm,
            lambda o, n, f: None,
            RuntimeConfig(batch=BatchConfig(size=32, deadline_us=500)),
            metrics=m,
            checkpoint=CheckpointManager(str(tmp_path / "ck")),
            use_native=False,
            max_dispatch_chunks=1,
        )
        with pytest.raises(faults.InjectedChipLoss):
            pipe.run_until_exhausted(timeout=60)


# ---------------------------------------------------------------------------
# chaos soak: mesh profile (slow)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_mesh_chaos_soak_profile():
    root = pathlib.Path(__file__).resolve().parent.parent
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("FJT_FAULTS", None)
    proc = subprocess.run(
        [
            sys.executable, str(root / "tools" / "fuzz_soak.py"),
            "--chaos", "--mesh", "--seeds", "3", "--start", "7",
        ],
        capture_output=True, text=True, timeout=600, env=env,
    )
    assert proc.returncode == 0, (
        f"mesh chaos soak rc={proc.returncode}\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr[-2000:]}"
    )
    assert "mesh-chaos: 3/3 seeds clean" in proc.stdout
