"""Causal record-journey tracing (obs/trace.py): trace contexts,
cross-process traceparent propagation through Kafka record headers, the
tail-sampled journey store, hot-path wiring on both pipelines, the
/trace endpoint, redrive continuity, and the fjt-trace CLI.

The kill-anywhere acceptance (journey reconstruction across SIGKILL
incarnations) lives in bench.py --recovery-drill with a smoke-scale
tripwire in tools/perf_smoke.py; this file pins the mechanisms one at
a time.
"""

import io
import json
import os
import tempfile

import numpy as np
import pytest

from flink_jpmml_tpu import cli as cli_mod
from flink_jpmml_tpu.obs import recorder as flight
from flink_jpmml_tpu.obs import trace as trace_mod
from flink_jpmml_tpu.runtime import faults
from flink_jpmml_tpu.runtime.dlq import DeadLetterQueue, payload_bytes
from flink_jpmml_tpu.utils.metrics import MetricsRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    monkeypatch.delenv("FJT_JOURNEY_DIR", raising=False)
    monkeypatch.delenv("FJT_JOURNEY_SYNC", raising=False)
    monkeypatch.delenv("FJT_RESTART_STREAK", raising=False)
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def small_gbm():
    from flink_jpmml_tpu.assets_gen import gen_gbm
    from flink_jpmml_tpu.compile import compile_pmml
    from flink_jpmml_tpu.pmml import parse_pmml_file

    tmp = tempfile.mkdtemp(prefix="fjt-trace-model-")
    return compile_pmml(
        parse_pmml_file(gen_gbm(tmp, n_trees=3, depth=3, n_features=4)),
        batch_size=32,
    )


class TestTraceContext:
    def test_traceparent_roundtrip(self):
        ctx = trace_mod.context_for(1374)
        tp = ctx.to_traceparent()
        assert tp.startswith("00-") and tp.endswith("-01")
        back = trace_mod.TraceContext.from_traceparent(tp)
        assert back is not None
        assert back.trace_id == ctx.trace_id
        assert back.span_id == ctx.span_id

    @pytest.mark.parametrize("bad", [
        "", "garbage", "00-zz-yy-01", "00-abc-def-01", None, 42,
        "00-" + "0" * 31 + "-" + "0" * 16 + "-01",  # short trace id
    ])
    def test_malformed_traceparent_is_none(self, bad):
        assert trace_mod.TraceContext.from_traceparent(bad) is None

    def test_trace_id_deterministic_across_processes(self):
        # the fleet-merge property: any process derives the SAME id
        # for the same offset with zero coordination
        import subprocess
        import sys

        out = subprocess.run(
            [sys.executable, "-c", (
                "import sys; sys.path.insert(0, %r); "
                "from flink_jpmml_tpu.obs.trace import trace_id_for; "
                "print(trace_id_for(1374))" % REPO
            )],
            capture_output=True, text=True, timeout=60,
        )
        assert out.stdout.strip() == trace_mod.trace_id_for(1374)
        assert trace_mod.trace_id_for(1374) != trace_mod.trace_id_for(1375)
        assert len(trace_mod.trace_id_for(0)) == 32

    def test_child_parenting_and_current(self):
        ctx = trace_mod.context_for(5)
        kid = ctx.child()
        assert kid.trace_id == ctx.trace_id
        assert kid.parent_id == ctx.span_id
        assert kid.span_id != ctx.span_id
        assert trace_mod.current() is None
        with trace_mod.use(ctx):
            assert trace_mod.current() is ctx
            with trace_mod.use(kid):
                assert trace_mod.current() is kid
            assert trace_mod.current() is ctx
        assert trace_mod.current() is None
        # None context is a no-op wrapper, not a clear
        with trace_mod.use(ctx):
            with trace_mod.use(None):
                assert trace_mod.current() is ctx


class TestJourneyStore:
    def _store(self, tmp_path, **kw):
        m = MetricsRegistry()
        kw.setdefault("head_n", 0)
        kw.setdefault("budget_frac", 1.0)
        return trace_mod.JourneyStore(
            str(tmp_path / "j"), metrics=m, **kw
        ), m

    def test_tail_sampling_keeps_marked_drops_rest(self, tmp_path):
        store, m = self._store(tmp_path)
        kept = trace_mod.context_for(0)
        store.hop("dispatch", kept, 0, 64)
        store.mark(kept.trace_id, "exemplar")
        store.finish(kept, 0, 64, latency_s=0.5)
        dropped = trace_mod.context_for(64)
        store.hop("dispatch", dropped, 64, 64)
        store.finish(dropped, 64, 64, latency_s=0.001)
        rows = trace_mod.read_rows(store.directory)
        ids = {r["trace_id"] for r in rows}
        assert ids == {kept.trace_id}
        sink = [r for r in rows if r["kind"] == "sink"][0]
        assert sink["sampled"] == "exemplar"
        assert sink["latency_s"] == pytest.approx(0.5)
        snap = m.struct_snapshot()["counters"]
        assert snap["journeys_sampled"] == 1
        assert snap['journeys_dropped{reason="unsampled"}'] == 1

    def test_head_sample_and_continuation(self, tmp_path):
        store, m = self._store(tmp_path, head_n=1)
        a = trace_mod.context_for(0)
        store.hop("dispatch", a, 0, 32)
        store.finish(a, 0, 32)  # head sample → kept
        # a later hop of a KEPT journey writes straight through
        store.hop("extra", a.child(), 0, 32)
        b = trace_mod.context_for(32)
        store.hop("dispatch", b, 32, 32)
        store.finish(b, 32, 32)  # head exhausted → dropped
        rows = trace_mod.read_rows(store.directory)
        kinds = sorted(r["kind"] for r in rows)
        assert kinds == ["dispatch", "extra", "sink"]
        assert all(r["trace_id"] == a.trace_id for r in rows)

    def test_terminal_always_durable_and_flushes_pending(self, tmp_path):
        store, m = self._store(tmp_path)
        ctx = trace_mod.context_for(7)
        store.hop("dispatch", ctx, 7, 1)
        store.terminal("dlq", ctx.child(), offset=7, reason="score")
        rows = trace_mod.read_rows(store.directory)
        assert sorted(r["kind"] for r in rows) == ["dispatch", "dlq"]
        assert m.struct_snapshot()["counters"]["journeys_sampled"] == 1

    def test_budget_drops_only_nonterminal(self, tmp_path):
        store, m = self._store(tmp_path, budget_frac=0.0)
        c1 = trace_mod.context_for(0)
        # the first hop finds zero accrued overhead (0 > 0 is false)
        # and buffers; it also accrues the overhead that trips the gate
        store.hop("dispatch", c1, 0, 32)
        c2 = trace_mod.context_for(32)
        store.hop("dispatch", c2, 32, 32)  # over budget → dropped
        store.terminal("dlq", c2, offset=32, reason="score")  # kept
        rows = trace_mod.read_rows(store.directory)
        assert [r["kind"] for r in rows] == ["dlq"]
        snap = m.struct_snapshot()["counters"]
        assert snap['journeys_dropped{reason="budget"}'] == 1

    def test_pending_eviction_bound(self, tmp_path):
        store, m = self._store(tmp_path)
        for i in range(trace_mod._PENDING_TRACES + 10):
            store.hop("dispatch", trace_mod.context_for(i), i, 1)
        snap = m.struct_snapshot()["counters"]
        assert snap['journeys_dropped{reason="evicted"}'] == 10

    def test_write_through_persists_everything(self, tmp_path):
        store, m = self._store(tmp_path)
        store.write_through = True
        ctx = trace_mod.context_for(0)
        store.hop("dispatch", ctx, 0, 32)
        rows = trace_mod.read_rows(store.directory)
        assert [r["kind"] for r in rows] == ["dispatch"]

    def test_faults_arm_write_through(self, tmp_path):
        faults.inject("slow_fetch", delay_ms=1, n=0)
        store, _ = self._store(tmp_path)
        assert store.write_through

    def test_ring_gc_bounds_bytes(self, tmp_path):
        store, m = self._store(
            tmp_path, max_bytes=4096, segment_bytes=512,
        )
        store.write_through = True
        for i in range(200):
            store.hop("dispatch", trace_mod.context_for(i), i, 1,
                      pad="x" * 64)
        total = sum(
            os.path.getsize(os.path.join(store.directory, nm))
            for nm in os.listdir(store.directory)
        )
        assert total <= 4096 + 1024  # one open segment of slack
        snap = m.struct_snapshot()["counters"]
        assert snap.get('journeys_dropped{reason="ring_gc"}', 0) > 0
        assert m.struct_snapshot()["gauges"][
            "journey_store_bytes"
        ]["value"] > 0

    def test_read_rows_orders_by_mtime_not_filename(self, tmp_path):
        # review fix: pid 100045's segment sorts lexically BEFORE pid
        # 99870's, but it is the NEWER incarnation — the newest-limit
        # deque must keep its rows, so segments read oldest-mtime-first
        d = tmp_path / "j"
        d.mkdir()
        old = d / "journeys-99870-00000000.jsonl"
        new = d / "journeys-100045-00000000.jsonl"
        old.write_text(json.dumps(
            {"t": 1.0, "pid": 99870, "kind": "old", "trace_id": "a",
             "span_id": "s"}
        ) + "\n")
        new.write_text(json.dumps(
            {"t": 2.0, "pid": 100045, "kind": "dlq", "trace_id": "b",
             "span_id": "s"}
        ) + "\n")
        os.utime(old, (1_000, 1_000))
        os.utime(new, (2_000, 2_000))
        rows = trace_mod.read_rows(str(d), limit=1)
        assert [r["kind"] for r in rows] == ["dlq"]

    def test_read_rows_skips_torn_lines(self, tmp_path):
        store, _ = self._store(tmp_path)
        store.terminal("dlq", trace_mod.context_for(1), offset=1)
        seg = [
            nm for nm in os.listdir(store.directory)
            if nm.startswith("journeys-")
        ][0]
        path = os.path.join(store.directory, seg)
        with open(path, "a") as f:
            f.write('{"torn')  # a SIGKILL mid-write
        rows = trace_mod.read_rows(store.directory)
        assert len(rows) == 1 and rows[0]["kind"] == "dlq"

    def test_mark_bound_evicts_oldest_keeps_sampling(self, tmp_path):
        # review fix: orphaned marks (journeys that never finish) must
        # not permanently exhaust the mark table — eviction, not refusal
        store, _ = self._store(tmp_path)
        for i in range(trace_mod._PENDING_TRACES * 2 + 5):
            store.mark(f"orphan-{i}", "exemplar")
        late = trace_mod.context_for(999)
        store.hop("dispatch", late, 999, 1)
        store.mark(late.trace_id, "exemplar")  # must still register
        store.finish(late, 999, 1)
        rows = trace_mod.read_rows(store.directory)
        assert any(r["trace_id"] == late.trace_id for r in rows)

    def test_ingest_hops_durable_but_uncounted(self, tmp_path):
        # review fix: per-fetch ingest hops persist WITHOUT a finish()
        # (nothing ever finishes a fetch-run id) and without inflating
        # journeys_sampled or adopting the run id as a kept journey
        store, m = self._store(tmp_path)
        store.ingest(0, 512, partition=0)
        rows = trace_mod.read_rows(store.directory)
        assert [r["kind"] for r in rows] == ["ingest"]
        snap = m.struct_snapshot()["counters"]
        assert snap.get("journeys_sampled", 0) == 0
        # the run id was NOT registered: a later same-id hop buffers
        ctx = trace_mod.context_for(0)
        store.hop("dispatch", ctx, 0, 64)
        assert len(trace_mod.read_rows(store.directory)) == 1

    def test_store_for_gate_and_install(self, tmp_path, monkeypatch):
        m = MetricsRegistry()
        assert trace_mod.store_for(m) is None  # env unset: nothing
        assert trace_mod.peek(m) is None
        monkeypatch.setenv("FJT_JOURNEY_DIR", str(tmp_path / "env"))
        s = trace_mod.store_for(m)
        assert s is not None and trace_mod.store_for(m) is s
        assert trace_mod.peek(m) is s
        m2 = MetricsRegistry()
        s2 = trace_mod.install(m2, str(tmp_path / "explicit"))
        assert s2.directory.endswith("explicit")


class TestKafkaHeaders:
    def test_encode_decode_roundtrip(self):
        from flink_jpmml_tpu.runtime.kafka import (
            decode_record_batches,
            decode_record_batches_h,
            encode_record_batch,
            record_batch_traceparents,
        )

        ctx = trace_mod.context_for(11)
        tp = ctx.to_traceparent().encode()
        hdrs = [
            None,
            [("traceparent", tp), ("other", b"\x00\x01")],
            [],
        ]
        blob = encode_record_batch(
            10, [b"a", b"bb", b"ccc"], timestamp_ms=123, headers=hdrs
        )
        # the fast decoder still skips headers correctly
        assert decode_record_batches(blob) == [
            (10, b"a"), (11, b"bb"), (12, b"ccc")
        ]
        got = decode_record_batches_h(blob)
        assert got[0][2] is None
        assert got[1][2] == [("traceparent", tp), ("other", b"\x00\x01")]
        assert got[2][2] is None
        assert record_batch_traceparents(blob) == {
            11: ctx.to_traceparent()
        }

    def test_broker_produce_fetch_preserves_headers(self):
        from flink_jpmml_tpu.runtime.kafka import (
            KafkaClient,
            MiniKafkaBroker,
            decode_record_batches_h,
        )

        broker = MiniKafkaBroker(topic="t")
        try:
            client = KafkaClient(broker.host, broker.port)
            tp = trace_mod.context_for(3).to_traceparent().encode()
            base = client.produce(
                "t", 0, [b"v0", b"v1"],
                headers=[None, [("traceparent", tp)]],
            )
            assert base == 0
            _, raw = client.fetch_raw("t", 0, 0)
            got = decode_record_batches_h(raw)
            assert [(o, v) for o, v, _ in got] == [(0, b"v0"), (1, b"v1")]
            assert got[0][2] is None
            assert got[1][2] == [("traceparent", tp)]
            client.close()
        finally:
            broker.close()

    def test_compaction_keeps_headers(self):
        from flink_jpmml_tpu.runtime.kafka import (
            MiniKafkaBroker,
            decode_record_batches_h,
            encode_record_batch,  # noqa: F401 (API under test above)
            KafkaClient,
        )

        broker = MiniKafkaBroker(topic="t")
        try:
            hx = [("traceparent", b"00-" + b"a" * 32 + b"-" + b"b" * 16
                   + b"-01")]
            broker.append(
                b"x", b"y", b"z", headers=[None, hx, None]
            )
            broker.compact(0, [0])
            client = KafkaClient(broker.host, broker.port)
            _, raw = client.fetch_raw("t", 0, 0)
            got = decode_record_batches_h(raw)
            assert [(o, v) for o, v, _ in got] == [(1, b"y"), (2, b"z")]
            assert got[0][2] == [
                (k, v) for k, v in hx
            ]
            client.close()
        finally:
            broker.close()


class TestTraceparentSurplus:
    def test_header_survives_poll_surplus_across_fetches(self, tmp_path):
        """Review fix: a traceparent whose record sits in the record
        source's fetch SURPLUS must survive the next fetch's header
        walk — pending headers are keyed persistently by offset, not
        clobbered per fetch."""
        from flink_jpmml_tpu.runtime.kafka import (
            KafkaRecordSource, MiniKafkaBroker,
        )

        broker = MiniKafkaBroker(topic="t")
        try:
            origin = trace_mod.context_for(12345)
            tp = origin.to_traceparent().encode()
            vals = [json.dumps({"i": i}).encode() for i in range(5)]
            broker.append(
                *vals,
                headers=[None, None, None, None, [("traceparent", tp)]],
            )
            m = MetricsRegistry()
            trace_mod.install(m, str(tmp_path / "j"))
            src = KafkaRecordSource(
                broker.host, broker.port, "t", max_wait_ms=20,
                metrics=m,
            )
            got = src.poll(3)  # fetches all 5, serves 3, 2 surplus
            assert len(got) == 3
            # a NEW fetch (header-free batch) lands before the header
            # record is served from the surplus
            broker.append(*[
                json.dumps({"i": i}).encode() for i in range(5, 10)
            ])
            got2 = src.poll(3)  # serves the surplus (incl. offset 4)
            assert [r["i"] for _, r in got2] == [3, 4, 5]
            src.close()
            rows = trace_mod.read_rows(str(tmp_path / "j"))
            redriven = [r for r in rows if r.get("redriven")]
            assert redriven and redriven[0]["offset"] == 4
            assert redriven[0]["trace_id"] == origin.trace_id
            assert redriven[0]["parent_id"] == origin.span_id
        finally:
            broker.close()


class TestSpanTraceArgs:
    def test_spans_carry_active_context(self, tmp_path, monkeypatch):
        from flink_jpmml_tpu.obs import spans
        from flink_jpmml_tpu.utils.profiling import StageTimer

        monkeypatch.setenv("FJT_TRACE_DIR", str(tmp_path))
        ctx = trace_mod.context_for(9)
        timer = StageTimer(MetricsRegistry())
        with trace_mod.use(ctx):
            with timer.stage("featurize"):
                pass
            spans.emit("manual", 0.0, 0.001)
        spans.emit("untraced", 0.0, 0.001)  # no active ctx
        w = spans.writer()
        assert w is not None
        w.flush()
        events = []
        with open(w.path) as f:
            for ln in f:
                ln = ln.strip().rstrip(",")
                if not ln or ln == "[":
                    continue
                events.append(json.loads(ln))
        by_name = {e["name"]: e for e in events}
        for name in ("featurize", "manual"):
            args = by_name[name].get("args") or {}
            assert args.get("trace_id") == ctx.trace_id
            assert args.get("span_id") == ctx.span_id
        assert "trace_id" not in (by_name["untraced"].get("args") or {})
        # explicit trace_id args win over the ambient context
        with trace_mod.use(ctx):
            spans.emit("explicit", 0.0, 0.001, trace_id="custom")
        w.flush()
        with open(w.path) as f:
            tail = [
                json.loads(ln.strip().rstrip(","))
                for ln in f
                if ln.strip().rstrip(",") not in ("", "[")
            ]
        ex = [e for e in tail if e["name"] == "explicit"][0]
        assert ex["args"]["trace_id"] == "custom"
        # cleanup: drop the module singleton so later tests (and other
        # files) don't inherit a writer bound to this tmp dir
        monkeypatch.delenv("FJT_TRACE_DIR")
        assert spans.writer() is None


class TestBlockPipelineJourneys:
    def _run(self, small_gbm, tmp_path, data, metrics, **pipe_kw):
        from flink_jpmml_tpu.runtime.block import (
            BlockPipeline, FiniteBlockSource,
        )
        from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager
        from flink_jpmml_tpu.utils.config import BatchConfig, RuntimeConfig

        emitted = []

        def sink(out, n, first_off):
            emitted.append((first_off, n))

        pipe = BlockPipeline(
            FiniteBlockSource(data, 64), small_gbm, sink,
            RuntimeConfig(
                batch=BatchConfig(size=32, deadline_us=1000),
                checkpoint_interval_s=0.05,
            ),
            metrics=metrics,
            checkpoint=CheckpointManager(str(tmp_path / "ck")),
            **pipe_kw,
        )
        pipe.run_until_exhausted(timeout=60)
        return pipe, emitted

    def test_complete_journeys_and_exemplar_linkage(
        self, small_gbm, tmp_path
    ):
        m = MetricsRegistry()
        store = trace_mod.install(m, str(tmp_path / "j"), head_n=2)
        rng = np.random.default_rng(0)
        data = rng.normal(0, 1, size=(256, 4)).astype(np.float32)
        self._run(small_gbm, tmp_path, data, m)
        rows = trace_mod.read_rows(store.directory)
        by_id = {}
        for r in rows:
            by_id.setdefault(r["trace_id"], set()).add(r["kind"])
        complete = {
            t for t, k in by_id.items() if {"dispatch", "sink"} <= k
        }
        assert complete, by_id
        # the exemplar path marks journeys: a first-batch exemplar's
        # trace id must name a persisted journey (the fjt-top pivot)
        ex = {
            e.get("trace_id") for e in flight.events()
            if e.get("kind") == "latency_exemplar"
        }
        assert complete & ex
        # the dispatch hop is batch-keyed: (first_off, n) present
        d = [r for r in rows if r["kind"] == "dispatch"][0]
        assert "first_off" in d and "n" in d

    def test_poison_isolation_leaves_trace_trail(
        self, small_gbm, tmp_path
    ):
        m = MetricsRegistry()
        store = trace_mod.install(m, str(tmp_path / "j"), head_n=0)
        rng = np.random.default_rng(1)
        data = rng.normal(0, 1, size=(200, 4)).astype(np.float32)
        faults.clear()  # install() precedes: keep buffering mode
        store.write_through = False
        faults.inject("poison_record", offset=97)
        self._run(small_gbm, tmp_path, data, m)
        rows = trace_mod.read_rows(store.directory)
        kinds = {r["kind"] for r in rows}
        assert "suspect_scan" in kinds and "dlq" in kinds
        dlq_row = [r for r in rows if r["kind"] == "dlq"][0]
        assert dlq_row["offset"] == 97
        assert dlq_row["trace_id"] == trace_mod.trace_id_for(97)
        # the envelope carries the SAME context (satellite: redrive
        # continuity rests on this)
        envs = {
            e["offset"]: e
            for e in DeadLetterQueue(str(tmp_path / "ck" / "dlq")).scan()
        }
        assert envs[97]["trace_id"] == dlq_row["trace_id"]
        assert envs[97]["span_id"] == dlq_row["span_id"]
        # isolated sink runs are durable and offset-labelled
        sinks = [r for r in rows if r["kind"] == "sink"]
        assert any(r.get("isolated") for r in sinks)

    def test_shed_terminal_hop(self, small_gbm, tmp_path):
        from flink_jpmml_tpu.serving.overload import AdmissionController

        m = MetricsRegistry()
        store = trace_mod.install(m, str(tmp_path / "j"), head_n=0)
        store.write_through = False
        admission = AdmissionController(m, lanes=("block",))
        admission._level = 1  # shed everything on the block lane
        rng = np.random.default_rng(2)
        data = rng.normal(0, 1, size=(64, 4)).astype(np.float32)
        pipe, emitted = self._run(
            small_gbm, tmp_path, data, m, admission=admission,
            shed_lane="block",
        )
        assert emitted == []  # everything shed
        rows = trace_mod.read_rows(store.directory)
        shed = [r for r in rows if r["kind"] == "shed"]
        assert shed and shed[0]["lane"] == "block"


class TestEnginePathJourneys:
    class _ListSource:
        def __init__(self, rows):
            self._rows = rows
            self._i = 0

        def poll(self, max_n):
            out = []
            while self._i < len(self._rows) and len(out) < max_n:
                out.append((self._i + 1, self._rows[self._i]))
                self._i += 1
            return out

        def seek(self, offset):
            self._i = offset

        @property
        def exhausted(self):
            return self._i >= len(self._rows)

    def test_engine_journeys_and_isolation(self, small_gbm, tmp_path):
        from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager
        from flink_jpmml_tpu.runtime.engine import Pipeline, StaticScorer
        from flink_jpmml_tpu.runtime.sinks import CollectSink
        from flink_jpmml_tpu.utils.config import BatchConfig, RuntimeConfig

        m = MetricsRegistry()
        store = trace_mod.install(m, str(tmp_path / "j"), head_n=2)
        store.write_through = False
        N = 100
        rng = np.random.default_rng(3)
        rows_in = [
            rng.normal(0, 1, size=4).astype(np.float32).tolist()
            for _ in range(N)
        ]
        faults.inject("poison_record", offset=56)
        sink = CollectSink()
        pipe = Pipeline(
            self._ListSource(rows_in), StaticScorer(small_gbm), sink,
            RuntimeConfig(
                batch=BatchConfig(size=32, deadline_us=1000),
                checkpoint_interval_s=0.05,
            ),
            metrics=m,
            checkpoint=CheckpointManager(str(tmp_path / "ck")),
        )
        pipe.run_until_exhausted(timeout=60)
        assert len(sink.items) == N - 1
        rows = trace_mod.read_rows(store.directory)
        kinds = {r["kind"] for r in rows}
        assert {"dispatch", "suspect_scan", "dlq"} <= kinds
        dlq_row = [r for r in rows if r["kind"] == "dlq"][0]
        assert dlq_row["offset"] == 56
        envs = list(
            DeadLetterQueue(str(tmp_path / "ck" / "dlq")).scan()
        )
        assert envs[0]["trace_id"] == trace_mod.trace_id_for(56)
        # surviving runs of the isolation get durable sink hops, like
        # the block path (review fix: both hot paths render the same
        # isolation timeline)
        iso_sinks = [
            r for r in rows
            if r["kind"] == "sink" and r.get("isolated")
        ]
        assert iso_sinks
        # head-sampled complete journeys exist on this path too
        by_id = {}
        for r in rows:
            by_id.setdefault(r["trace_id"], set()).add(r["kind"])
        assert any(
            {"dispatch", "sink"} <= k for k in by_id.values()
        ), by_id


class TestServerTraceEndpoint:
    def test_trace_endpoint_payload(self, tmp_path):
        import urllib.request

        from flink_jpmml_tpu.obs.server import ObsServer

        m = MetricsRegistry()
        store = trace_mod.install(m, str(tmp_path / "j"))
        store.terminal("dlq", trace_mod.context_for(4), offset=4,
                       reason="score")
        srv = ObsServer.for_registry(m)
        try:
            with urllib.request.urlopen(
                srv.url + "/trace", timeout=10
            ) as r:
                assert r.status == 200
                payload = json.loads(r.read().decode())
        finally:
            srv.close()
        assert payload["dir"] == store.directory
        assert any(
            row["kind"] == "dlq" and row["offset"] == 4
            for row in payload["journeys"]
        )
        assert isinstance(payload["flight"], list)

    def test_trace_endpoint_serves_spans_and_url_load(
        self, tmp_path, monkeypatch
    ):
        # review fix: the URL source must carry the trace-id'd span
        # timeline the dump-dir scan shows (docs parity)
        from flink_jpmml_tpu.obs import spans
        from flink_jpmml_tpu.obs.server import ObsServer

        monkeypatch.setenv("FJT_TRACE_DIR", str(tmp_path / "spans"))
        m = MetricsRegistry()
        store = trace_mod.install(m, str(tmp_path / "j"))
        ctx = trace_mod.context_for(8)
        store.terminal("dlq", ctx, offset=8, reason="score")
        with trace_mod.use(ctx):
            spans.emit("featurize", 0.0, 0.002, first_off=8, n=1)
        spans.emit("uncorrelated", 0.0, 0.001)
        srv = ObsServer.for_registry(m)
        try:
            rows = cli_mod._trace_load(srv.url)
        finally:
            srv.close()
        span_rows = [r for r in rows if r.get("src") == "span"]
        assert span_rows, "URL source carried no spans"
        assert span_rows[0]["trace_id"] == ctx.trace_id
        assert span_rows[0]["kind"] == "span:featurize"
        assert not any(
            r["kind"] == "span:uncorrelated" for r in rows
        )
        # and the selection joins them to the journey
        sel = cli_mod._trace_select(rows, trace_id=ctx.trace_id)
        assert {"dlq", "span:featurize"} <= {r["kind"] for r in sel}
        # cleanup the module-level span writer singleton
        monkeypatch.delenv("FJT_TRACE_DIR")
        assert spans.writer() is None


@pytest.mark.slow
class TestRedriveContinuity:
    def test_redrive_links_original_journey_live(
        self, small_gbm, tmp_path, capsys
    ):
        """The satellite pin: quarantine → envelope carries the trace
        context → fjt-dlq redrive stamps it as a traceparent header →
        the LIVE pipeline's re-consume opens a child ingest hop of the
        original journey and scores the record."""
        from flink_jpmml_tpu.runtime.block import BlockPipeline
        from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager
        from flink_jpmml_tpu.runtime.kafka import (
            KafkaBlockSource, MiniKafkaBroker,
        )
        from flink_jpmml_tpu.utils.config import BatchConfig, RuntimeConfig

        N, poison_off = 192, 70
        broker = MiniKafkaBroker(topic="t")
        try:
            rng = np.random.default_rng(4)
            data = rng.normal(0, 1, size=(N, 4)).astype(np.float32)
            broker.append_rows(data)

            def run_consumer(total, fault_offset=None):
                faults.clear()
                if fault_offset is not None:
                    faults.inject("poison_record", offset=fault_offset)
                m = MetricsRegistry()
                trace_mod.install(m, str(tmp_path / "j"))
                dlq = DeadLetterQueue(
                    str(tmp_path / "ck" / "dlq"), metrics=m
                )
                src = KafkaBlockSource(
                    broker.host, broker.port, "t", n_cols=4,
                    max_wait_ms=20, metrics=m, dlq=dlq,
                )
                emitted = []
                pipe = BlockPipeline(
                    src, small_gbm,
                    lambda out, n, first: emitted.append((first, n)),
                    RuntimeConfig(
                        batch=BatchConfig(size=32, deadline_us=1000),
                        checkpoint_interval_s=0.05,
                    ),
                    metrics=m,
                    checkpoint=CheckpointManager(str(tmp_path / "ck")),
                    dlq=dlq,
                )
                pipe.restore()
                pipe.start()
                import time as _t

                deadline = _t.monotonic() + 60
                while (
                    pipe.committed_offset < total
                    and pipe._error is None
                    and _t.monotonic() < deadline
                ):
                    _t.sleep(0.02)
                pipe.stop()
                pipe.join(timeout=30)
                src.close()
                return emitted

            emitted = run_consumer(N, fault_offset=poison_off)
            covered = np.zeros(N + 1, np.int64)
            for off, n in emitted:
                covered[off: off + n] += 1
            assert covered[poison_off] == 0
            dlq = DeadLetterQueue(str(tmp_path / "ck" / "dlq"))
            env = [
                e for e in dlq.scan() if e["offset"] == poison_off
            ][0]
            assert env.get("trace_id") and env.get("span_id")
            assert payload_bytes(env) == data[poison_off].tobytes()

            # redrive through the CLI: the traceparent header rides
            cli_mod.dlq_main([
                "redrive", str(tmp_path / "ck"),
                "--host", broker.host, "--port", str(broker.port),
                "--topic", "t", "--offset", str(poison_off),
            ])
            capsys.readouterr()

            # the corrected (fault-free) pipeline consumes the new
            # record through the real path
            emitted2 = run_consumer(N + 1)
            assert any(
                off <= N < off + n for off, n in emitted2
            ), "redriven record never reached the sink"
            rows = trace_mod.read_rows(str(tmp_path / "j"))
            redriven = [r for r in rows if r.get("redriven")]
            assert redriven, "no traceparent-linked ingest hop"
            hop = redriven[0]
            # same journey, child span of the envelope's quarantine hop
            assert hop["trace_id"] == env["trace_id"]
            assert hop["parent_id"] == env["span_id"]
            assert hop["offset"] == N  # the new log offset
            # and fjt-trace joins the whole story by the original offset
            sel = cli_mod._trace_select(
                rows + [cli_mod._trace_norm_dlq(env)],
                trace_id=env["trace_id"],
            )
            kinds = {r["kind"] for r in sel}
            assert {"dlq", "ingest", "dlq_envelope"} <= kinds
        finally:
            broker.close()


class TestTraceCLI:
    def _dump(self, tmp_path):
        m = MetricsRegistry()
        store = trace_mod.JourneyStore(
            str(tmp_path / "journeys"), metrics=m, head_n=100,
            budget_frac=1.0,
        )
        for i, off in enumerate((0, 64, 128)):
            ctx = trace_mod.context_for(off)
            store.hop("dispatch", ctx, off, 64)
            store.finish(ctx, off, 64, latency_s=0.01 * (i + 1))
        store.terminal(
            "dlq", trace_mod.context_for(70), offset=70, reason="score",
        )
        return store

    def test_summary_grep_slowest_id(self, tmp_path, capsys):
        self._dump(tmp_path)
        assert cli_mod.trace_main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "journey(s)" in out
        # --grep offset=K: the batch containing 70 AND its terminal hop
        assert cli_mod.trace_main(
            [str(tmp_path), "--grep", "offset=70"]
        ) == 0
        out = capsys.readouterr().out
        assert "dlq" in out and "[64..128)" in out
        assert cli_mod.trace_main([str(tmp_path), "--slowest", "2"]) == 0
        out = capsys.readouterr().out
        assert "30.000ms" in out.replace(" ", "") or "30.000" in out
        tid = trace_mod.trace_id_for(128)
        assert cli_mod.trace_main([str(tmp_path), "--id", tid]) == 0
        out = capsys.readouterr().out
        assert tid[:12] in out

    def test_id_selection_pulls_batch_terminal_hops(self, tmp_path):
        """Review fix: --id <batch-tid> (the fjt-top pivot) must pull
        in per-record terminal hops whose offset falls inside the
        batch's (first_off, n) range — a quarantine inside the slow
        batch must not vanish from the id-selected timeline."""
        store = self._dump(tmp_path)
        rows = cli_mod._trace_rows_from_dir(str(tmp_path))
        batch_tid = trace_mod.trace_id_for(64)
        sel = cli_mod._trace_select(rows, trace_id=batch_tid)
        kinds = {r["kind"] for r in sel}
        assert "dlq" in kinds, kinds  # offset 70 ∈ [64..128)
        # and by-offset selection agrees with by-id selection
        sel2 = cli_mod._trace_select(rows, offset=70)
        assert {r["kind"] for r in sel2} >= kinds

    def test_grep_rejects_unknown_key(self, tmp_path):
        self._dump(tmp_path)
        with pytest.raises(SystemExit):
            cli_mod.trace_main([str(tmp_path), "--grep", "pid=3"])
        with pytest.raises(SystemExit):
            cli_mod.trace_main([str(tmp_path), "--grep", "offset=x"])

    def test_no_match_exits(self, tmp_path):
        self._dump(tmp_path)
        with pytest.raises(SystemExit):
            cli_mod.trace_main(
                [str(tmp_path), "--grep", "offset=99999"]
            )

    def test_artifact_source(self, tmp_path, capsys):
        store = self._dump(tmp_path)
        rows = trace_mod.read_rows(store.directory)
        art = tmp_path / "BENCH_x.json"
        art.write_text(json.dumps({
            "metric": "recovery_drill", "journeys": rows,
        }))
        assert cli_mod.trace_main(
            [str(art), "--grep", "offset=70"]
        ) == 0
        out = capsys.readouterr().out
        assert "dlq" in out

    def test_incarnation_boundary_render(self, capsys):
        rows = [
            {"t": 1.0, "pid": 10, "kind": "dispatch",
             "trace_id": "aa", "span_id": "s1", "first_off": 0, "n": 8},
            {"t": 2.0, "pid": 20, "kind": "restore",
             "trace_id": "aa", "span_id": "s2", "first_off": 0},
        ]
        buf = io.StringIO()
        cli_mod._trace_render(rows, buf)
        out = buf.getvalue()
        assert "incarnation boundary: pid 10 → pid 20" in out

    def test_flight_and_dlq_normalization(self, tmp_path, capsys):
        # flight dumps + DLQ segments in the scanned tree join the
        # journey rows (the recovery-drill reconstruction path)
        store = self._dump(tmp_path)
        flight_path = tmp_path / "flight-1-2.jsonl"
        flight_path.write_text(json.dumps({
            "t": 5.0, "kind": "poison_suspect_mode", "lo": 64,
            "hi": 128, "restarts": 3, "pid": 99,
        }) + "\n" + json.dumps({
            "t": 5.1, "kind": "kafka_reconnect",  # not journey-relevant
        }) + "\n")
        q = DeadLetterQueue(str(tmp_path / "dlq"))
        q.quarantine(b"\x00" * 16, offset=70, reason="score",
                     trace_id="tt", span_id="ss")
        rows = cli_mod._trace_rows_from_dir(str(tmp_path))
        kinds = {r["kind"] for r in rows}
        assert "poison_suspect_mode" in kinds
        assert "dlq_envelope" in kinds
        assert "kafka_reconnect" not in kinds
        sus = [r for r in rows if r["kind"] == "poison_suspect_mode"][0]
        assert sus["first_off"] == 64 and sus["n"] == 64
        sel = cli_mod._trace_select(rows, offset=70)
        sel_kinds = {r["kind"] for r in sel}
        assert {"poison_suspect_mode", "dlq_envelope", "dlq"} <= sel_kinds

    def test_fjt_top_exemplar_pivot_hint(self, tmp_path, capsys):
        # an exemplar row renders the fjt-trace invocation (satellite)
        m = MetricsRegistry()
        h = m.histogram("stage_seconds{stage=\"sink\"}")
        h.observe(0.5, exemplar="abcd1234")
        struct = m.struct_snapshot()
        dump = tmp_path / "varz.json"
        dump.write_text(json.dumps(struct))
        assert cli_mod.top_main([str(dump)]) == 0
        out = capsys.readouterr().out
        assert "fjt-trace" in out and "--id abcd1234" in out
        assert str(dump) in out
