"""Network streaming source (runtime/net.py): the BASELINE config-2
"tabular stream over the network" path — frames, offsets, engine
integration, kill/resume exactness, and server-restart reconnect."""

import numpy as np
import pytest

from assets.generate import gen_gbm
from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.pmml import parse_pmml_file
from flink_jpmml_tpu.runtime.block import BlockPipeline
from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager
from flink_jpmml_tpu.runtime.engine import Pipeline, StaticScorer
from flink_jpmml_tpu.runtime.net import (
    BlockFrameServer,
    TcpBlockSource,
    TcpRecordSource,
)
from flink_jpmml_tpu.runtime.sinks import CollectSink
from flink_jpmml_tpu.utils.config import BatchConfig, RuntimeConfig


def _drain_blocks(src, n_total, timeout=10.0):
    import time

    got = []
    deadline = time.monotonic() + timeout
    count = 0
    while count < n_total and time.monotonic() < deadline:
        polled = src.poll()
        if polled is None:
            if src.exhausted:
                break
            time.sleep(0.001)
            continue
        off, blk = polled
        got.append((off, np.array(blk)))
        count += blk.shape[0]
    return got


class TestFrames:
    def test_block_roundtrip_offsets_contiguous(self):
        rng = np.random.default_rng(0)
        data = rng.normal(size=(1000, 6)).astype(np.float32)
        srv = BlockFrameServer(data, block_size=128)
        try:
            src = TcpBlockSource("127.0.0.1", srv.port, arity=6)
            got = _drain_blocks(src, 1000)
            # offsets are contiguous and the payload is bit-exact
            pos = 0
            for off, blk in got:
                assert off == pos
                np.testing.assert_array_equal(blk, data[off : off + len(blk)])
                pos += len(blk)
            assert pos == 1000
            # EOS surfaced
            assert src.poll() is None and src.exhausted
            src.close()
        finally:
            srv.stop()

    def test_seek_replays_from_offset(self):
        data = np.arange(200 * 2, dtype=np.float32).reshape(200, 2)
        srv = BlockFrameServer(data, block_size=64)
        try:
            src = TcpBlockSource("127.0.0.1", srv.port)
            _drain_blocks(src, 200)
            assert src.poll() is None  # consumes the EOS frame
            assert src.exhausted
            src.seek(150)  # replayable log: fetch again from offset 150
            got = _drain_blocks(src, 50)
            assert got[0][0] == 150
            assert sum(len(b) for _, b in got) == 50
            src.close()
        finally:
            srv.stop()


class TestEngineIntegration:
    def test_record_stream_through_pipeline(self, assets_dir):
        doc = parse_pmml_file(str(assets_dir / "iris_lr.pmml"))
        cm = compile_pmml(doc, batch_size=32)
        rng = np.random.default_rng(1)
        recs = [
            {f: float(v) for f, v in zip(doc.active_fields, row)}
            for row in rng.normal(3, 2, size=(150, 4))
        ]
        srv = BlockFrameServer(recs, block_size=40)
        try:
            src = TcpRecordSource("127.0.0.1", srv.port)
            sink = CollectSink()
            pipe = Pipeline(
                src, StaticScorer(cm), sink,
                RuntimeConfig(batch=BatchConfig(size=32)),
            )
            pipe.run_until_exhausted(timeout=30.0)
            assert len(sink.items) == 150
            # parity with direct scoring
            direct = cm.score_records(recs[:5])
            for got, exp in zip(sink.items[:5], direct):
                assert got.score.value == pytest.approx(
                    exp.score.value, rel=1e-6
                )
            src.close()
        finally:
            srv.stop()


@pytest.mark.slow
class TestKillResume:
    def test_block_pipeline_resumes_exactly(self, tmp_path):
        # VERDICT r1 #3 'Done': BlockPipeline scores a socket-fed GBM
        # stream and resumes exactly after restart — every offset sunk
        # exactly once across the two runs.
        doc = parse_pmml_file(
            gen_gbm(str(tmp_path), n_trees=10, depth=3, n_features=5)
        )
        cm = compile_pmml(doc, batch_size=64)
        rng = np.random.default_rng(2)
        N = 4000
        data = rng.normal(0, 1.5, size=(N, 5)).astype(np.float32)
        ckdir = str(tmp_path / "ck")
        cfg = RuntimeConfig(
            batch=BatchConfig(size=64, deadline_us=2000),
            checkpoint_interval_s=0.05,
        )

        seen = []  # (first_off, n) across both runs

        def sink(out, n, first_off):
            seen.append((first_off, n))

        srv = BlockFrameServer(data, block_size=100)
        try:
            src = TcpBlockSource("127.0.0.1", srv.port, arity=5)
            pipe = BlockPipeline(
                src, cm, sink, cfg,
                checkpoint=CheckpointManager(ckdir),
            )
            pipe.start()
            import time

            # let it score some — but not all — of the stream, then stop
            deadline = time.monotonic() + 10.0
            while pipe.committed_offset < 500 and time.monotonic() < deadline:
                time.sleep(0.005)
            pipe.stop()
            pipe.join(timeout=30.0)
            first_run_committed = pipe.committed_offset
            assert 0 < first_run_committed, "first run scored nothing"
            src.close()

            # "restart": fresh source + pipeline, resume from checkpoint
            src2 = TcpBlockSource("127.0.0.1", srv.port, arity=5)
            pipe2 = BlockPipeline(
                src2, cm, sink, cfg,
                checkpoint=CheckpointManager(ckdir),
            )
            assert pipe2.restore()
            assert pipe2.committed_offset == first_run_committed
            pipe2.run_until_exhausted(timeout=60.0)
            src2.close()
        finally:
            srv.stop()

        covered = np.zeros(N, np.int32)
        for off, n in seen:
            covered[off : off + n] += 1
        assert (covered == 1).all(), (
            f"gaps={np.flatnonzero(covered == 0)[:5]} "
            f"dups={np.flatnonzero(covered > 1)[:5]}"
        )

    def test_source_survives_server_restart(self):
        data = np.arange(600 * 3, dtype=np.float32).reshape(600, 3)
        # paced sends so the stop() lands mid-stream regardless of socket
        # buffer sizes — otherwise the whole 7KB log buffers instantly
        srv = BlockFrameServer(data, block_size=50, throttle_s=0.02)
        port = srv.port
        src = TcpBlockSource("127.0.0.1", port)
        got = _drain_blocks(src, 200)
        srv.stop()  # network blip: server dies mid-stream
        # frames already buffered client-side still drain; after that,
        # reads during the outage yield None, never raise
        while True:
            polled = src.poll()
            if polled is None:
                break
            got.append((polled[0], np.array(polled[1])))
        n_before = sum(len(b) for _, b in got)
        assert 200 <= n_before < 600
        assert src.poll() is None
        srv2 = BlockFrameServer(data, block_size=50, port=port)
        try:
            got2 = _drain_blocks(src, 600 - n_before)
            # reconnected at exactly the next offset: no gap, no dup
            assert got2[0][0] == n_before
            covered = np.zeros(600, np.int32)
            for off, blk in got + got2:
                covered[off : off + len(blk)] += 1
            assert (covered == 1).all()
            src.close()
        finally:
            srv2.stop()


class TestOffsetDomain:
    """VERDICT r2 weak #5: one offset domain across frames, sources and
    checkpoints — a checkpointed engine offset k resumes at record index
    k with no bridging, including mid-frame."""

    def test_record_source_resumes_mid_frame(self):
        import time

        records = [{"f0": float(i), "f1": float(-i)} for i in range(100)]
        srv = BlockFrameServer(records, block_size=7)  # frames of 7
        try:
            # first consumer polls whole frames (28 records = 4 frames)
            # but the engine only *commits* through record 24 — so the
            # checkpointed offset k=24 lands mid-frame (24 % 7 != 0)
            src1 = TcpRecordSource("127.0.0.1", srv.port)
            got1 = []
            deadline = time.monotonic() + 10.0
            while len(got1) < 28 and time.monotonic() < deadline:
                got1.extend(src1.poll(28 - len(got1)))
            src1.close()
            assert len(got1) == 28
            k = got1[23][0]  # committed offset: 24 records consumed
            assert k == 24
            got1 = got1[:24]  # records past the commit point are replayed

            # recovery: fresh source, seek(k) verbatim — the next record
            # must be records[k], offsets continuing at k+1
            src2 = TcpRecordSource("127.0.0.1", srv.port)
            src2.seek(k)
            got2 = []
            deadline = time.monotonic() + 10.0
            while not src2.exhausted and time.monotonic() < deadline:
                got2.extend(src2.poll(1024))
            src2.close()
            assert got2[0][0] == k + 1
            assert got2[0][1] == records[k]
            assert [r for _, r in got1] + [r for _, r in got2] == records
            offs = [o for o, _ in got1] + [o for o, _ in got2]
            assert offs == list(range(1, 101))
        finally:
            srv.stop()

    def test_frame_client_idle_backoff_caps(self):
        from flink_jpmml_tpu.runtime.net import _FrameClient

        records = [{"a": 1.0}]
        srv = BlockFrameServer(records, block_size=1, cycle=True,
                               throttle_s=0.5)
        try:
            c = _FrameClient("127.0.0.1", srv.port)
            # burn through the idle window: repeated empty reads must
            # escalate the socket timeout to the cap, then data resets it
            for _ in range(40):
                if c.read_frame() is not None:
                    break
            import time

            deadline = time.monotonic() + 5.0
            while c._idle_timeout < c._IDLE_TIMEOUT_MAX:
                if time.monotonic() > deadline:
                    break
                c.read_frame()
            assert c._idle_timeout == c._IDLE_TIMEOUT_MAX
            # wait for the throttled server to emit; timeout resets
            deadline = time.monotonic() + 5.0
            body = None
            while body is None and time.monotonic() < deadline:
                body = c.read_frame()
            assert body is not None
            assert c._idle_timeout == c._poll_timeout
            c.close()
        finally:
            srv.stop()
