"""Kafka wire-protocol path (runtime/kafka.py): protocol bytes (CRC32C,
varints, magic-2 record batches), client↔broker calls, engine
integration, kill/resume exactness, broker-restart reconnect — the real
wire-format counterpart of test_net.py's FJT1 drills."""

import json
import time

import numpy as np
import pytest

from assets.generate import gen_gbm
from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.pmml import parse_pmml_file
from flink_jpmml_tpu.runtime.block import BlockPipeline
from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager
from flink_jpmml_tpu.runtime.engine import Pipeline, StaticScorer
from flink_jpmml_tpu.runtime.kafka import (
    KafkaBlockSource,
    KafkaClient,
    KafkaProtocolError,
    KafkaRecordSource,
    MiniKafkaBroker,
    crc32c,
    decode_record_batches,
    decode_record_batches_rows,
    encode_record_batch,
)
from flink_jpmml_tpu.runtime.sinks import CollectSink
from flink_jpmml_tpu.utils.config import BatchConfig, RuntimeConfig


class TestProtocolBytes:
    def test_crc32c_known_vectors(self):
        # RFC 3720 / kernel test vectors for Castagnoli
        assert crc32c(b"123456789") == 0xE3069283
        assert crc32c(b"") == 0
        assert crc32c(b"\x00" * 32) == 0x8A9136AA

    def test_record_batch_roundtrip(self):
        values = [f"record-{i}".encode() for i in range(7)]
        raw = encode_record_batch(100, values)
        got = decode_record_batches(raw)
        assert got == [(100 + i, v) for i, v in enumerate(values)]

    def test_multiple_batches_and_partial_tail(self):
        b1 = encode_record_batch(0, [b"a", b"b"])
        b2 = encode_record_batch(2, [b"c"])
        got = decode_record_batches(b1 + b2)
        assert got == [(0, b"a"), (1, b"b"), (2, b"c")]
        # Kafka truncates record sets at max_bytes: a partial trailing
        # batch decodes to the complete prefix, no exception
        got = decode_record_batches(b1 + b2[: len(b2) // 2])
        assert got == [(0, b"a"), (1, b"b")]

    def test_tiny_batch_len_tail_is_partial(self):
        import struct

        b1 = encode_record_batch(0, [b"a", b"b"])
        # a corrupt/truncated trailer whose batch_len (4) fits inside the
        # buffer but is too short to hold the v2 header: must be treated
        # as a partial tail, not indexed into
        tail = struct.pack(">q", 2) + struct.pack(">i", 4) + b"\x00" * 4
        got = decode_record_batches(b1 + tail)
        assert got == [(0, b"a"), (1, b"b")]

    def test_crc_corruption_detected(self):
        raw = bytearray(encode_record_batch(0, [b"payload"]))
        raw[-1] ^= 0xFF
        with pytest.raises(ValueError, match="CRC32C"):
            decode_record_batches(bytes(raw))


class TestNativeRowDecode:
    """decode_record_batches_rows: the C++ fixed-length fast path must be
    byte-identical with the Python walk (and fall back when the tabular
    contract doesn't hold)."""

    def test_rows_match_python_decode(self):
        rng = np.random.default_rng(21)
        rows = rng.normal(size=(700, 6)).astype(np.float32)
        raw = encode_record_batch(40, [rows[i].tobytes() for i in range(700)])
        raw += encode_record_batch(
            740, [rows[i].tobytes() for i in range(100)]
        )
        offs, got = decode_record_batches_rows(raw, 6)
        ref = decode_record_batches(raw)
        assert offs.tolist() == [o for o, _ in ref]
        np.testing.assert_array_equal(got[:700], rows)
        np.testing.assert_array_equal(got[700:], rows[:100])

    def test_native_falls_back_on_variable_lengths(self):
        from flink_jpmml_tpu.runtime import native

        raw = encode_record_batch(0, [b"12345678", b"1234"])
        if native.available():
            assert native.kafka_decode_fixed(raw, 8) is None
        # the general path still serves them (here as a length error at
        # row construction, same as the pre-native behavior)
        with pytest.raises(ValueError):
            decode_record_batches_rows(raw, 2)

    def test_native_encode_byte_exact(self):
        from flink_jpmml_tpu.runtime import native

        if not native.available():
            pytest.skip("native library unavailable")
        rows = np.random.default_rng(31).normal(size=(300, 5)).astype(
            np.float32
        )
        raw8 = rows.view(np.uint8).reshape(300, -1)
        got = native.kafka_encode_fixed(raw8, 777)
        ref = encode_record_batch(
            777, [rows[i].tobytes() for i in range(300)]
        )
        assert got == ref  # the C++ producer path IS the wire format

    def test_partial_tail_and_crc_parity(self):
        rows = np.arange(24, dtype=np.float32).reshape(6, 4)
        b1 = encode_record_batch(0, [rows[i].tobytes() for i in range(6)])
        offs, got = decode_record_batches_rows(b1 + b1[: len(b1) // 2], 4)
        assert offs.tolist() == list(range(6))
        np.testing.assert_array_equal(got, rows)
        bad = bytearray(b1)
        bad[-1] ^= 0xFF
        with pytest.raises(ValueError, match="CRC32C"):
            decode_record_batches_rows(bytes(bad), 4)


class TestProtocolFuzz:
    """Random batches, truncations, and corruptions through both
    decoders: every outcome must be a correct prefix or a typed
    ValueError — never an IndexError/struct.error escape."""

    @pytest.mark.parametrize("seed", range(10))
    def test_roundtrip_truncate_corrupt(self, seed):
        rng = np.random.default_rng(7000 + seed)
        n = int(rng.integers(1, 120))
        fixed = bool(rng.integers(0, 2))
        vlen = int(rng.integers(0, 64))
        values = [
            rng.bytes(vlen if fixed else int(rng.integers(0, 64)))
            for _ in range(n)
        ]
        base = int(rng.integers(0, 10_000))
        raw = encode_record_batch(base, values)

        ref = decode_record_batches(raw)
        assert ref == [(base + i, v) for i, v in enumerate(values)]
        if fixed and vlen > 0:
            from flink_jpmml_tpu.runtime import native

            if native.available():
                # a fixed-length batch MUST take the native fast path —
                # a spurious None here would be a silent fallback bug
                dec = native.kafka_decode_fixed(raw, vlen)
                assert dec is not None
                offs, vals = dec
                assert offs.tolist() == [o for o, _ in ref]
                assert [vals[i].tobytes() for i in range(len(ref))] == values

        # truncations: every strict prefix of the single batch decodes
        # to [] — the decoder must never fabricate records
        for _ in range(6):
            k = int(rng.integers(0, len(raw)))
            assert decode_record_batches(raw[:k]) == []

        # corruptions: a flipped byte is caught typed (CRC/magic/framing)
        # or yields a clean prefix. The v2 CRC deliberately does NOT
        # cover the first 21 header bytes (base_offset/batch_len/epoch),
        # so flips there can decode successfully with shifted offsets —
        # the VALUES must still be intact (they are CRC-covered).
        for _ in range(5):
            j = int(rng.integers(0, len(raw)))
            bad = bytearray(raw)
            bad[j] ^= 0xFF
            try:
                got = decode_record_batches(bytes(bad))
                assert got == [] or [v for _, v in got] == [
                    v for _, v in ref
                ]
            except ValueError:
                pass  # typed rejection is the expected outcome
            if fixed and vlen > 0:
                from flink_jpmml_tpu.runtime import native

                if native.available():
                    try:
                        dec = native.kafka_decode_fixed(bytes(bad), vlen)
                        assert dec is None or len(dec[0]) in (0, n)
                    except ValueError:
                        pass


class TestClientBroker:
    def test_api_versions_metadata_offsets(self):
        broker = MiniKafkaBroker(topic="t")
        try:
            c = KafkaClient(broker.host, broker.port)
            vers = c.api_versions()
            # the broker answers in fixed response shapes; it must only
            # advertise the versions those shapes are valid for
            assert vers[1] == (4, 4)  # Fetch: v4 only
            brokers, parts = c.metadata("t")
            assert parts == {0: 0}
            assert list(brokers.values())[0][1] == broker.port
            assert c.list_offset("t", 0, -2) == 0  # earliest
            broker.append(b"x", b"y")
            assert c.list_offset("t", 0, -1) == 2  # latest
            c.close()
        finally:
            broker.close()

    def test_fetch_from_offset_and_wait(self):
        broker = MiniKafkaBroker()
        try:
            broker.append(*(f"v{i}".encode() for i in range(10)))
            c = KafkaClient(broker.host, broker.port)
            hw, recs = c.fetch(broker.topic, 0, 4)
            assert hw == 10
            assert recs == [(i, f"v{i}".encode()) for i in range(4, 10)]
            # empty fetch respects max_wait and returns no records
            t0 = time.monotonic()
            hw, recs = c.fetch(broker.topic, 0, 10, max_wait_ms=80)
            assert recs == [] and time.monotonic() - t0 >= 0.05
            c.close()
        finally:
            broker.close()

    def test_out_of_range_partition_fails_fast(self):
        # err 3 (UNKNOWN_TOPIC_OR_PARTITION), not an empty err-0 log: a
        # consumer misconfigured with a bad partition id must fail, not
        # poll a phantom partition forever
        broker = MiniKafkaBroker(topic="t")
        try:
            broker.append(b"x")
            c = KafkaClient(broker.host, broker.port)
            with pytest.raises(KafkaProtocolError, match="error 3"):
                c.list_offset("t", 7, -1)
            t0 = time.monotonic()
            with pytest.raises(KafkaProtocolError, match="error 3"):
                c.fetch("t", 7, 0, max_wait_ms=5000)
            # and the error is immediate — no long-poll on a bad index
            assert time.monotonic() - t0 < 2.0
            c.close()
        finally:
            broker.close()

    def test_fetch_respects_max_bytes(self):
        broker = MiniKafkaBroker()
        try:
            broker.append(*(bytes(1000) for _ in range(100)))
            c = KafkaClient(broker.host, broker.port)
            _, recs = c.fetch(broker.topic, 0, 0, max_bytes=10_000)
            assert 0 < len(recs) < 100  # bounded, not the whole log
            # and the stream continues from where it stopped
            _, recs2 = c.fetch(
                broker.topic, 0, recs[-1][0] + 1, max_bytes=10_000
            )
            assert recs2[0][0] == recs[-1][0] + 1
            c.close()
        finally:
            broker.close()


@pytest.mark.slow
class TestEngineIntegration:
    def test_json_records_through_pipeline(self, assets_dir):
        doc = parse_pmml_file(str(assets_dir / "iris_lr.pmml"))
        cm = compile_pmml(doc, batch_size=32)
        rng = np.random.default_rng(1)
        recs = [
            {f: float(v) for f, v in zip(doc.active_fields, row)}
            for row in rng.normal(3, 2, size=(150, 4))
        ]
        broker = MiniKafkaBroker(topic="iris")
        try:
            broker.append(*(json.dumps(r).encode() for r in recs))
            src = KafkaRecordSource(
                broker.host, broker.port, "iris", max_wait_ms=20
            )
            sink = CollectSink()
            pipe = Pipeline(
                src, StaticScorer(cm), sink,
                RuntimeConfig(batch=BatchConfig(size=32, deadline_us=2000)),
            )
            pipe.start()
            deadline = time.monotonic() + 30.0
            while len(sink.items) < 150 and time.monotonic() < deadline:
                time.sleep(0.01)
            pipe.stop()
            pipe.join(timeout=30.0)
            assert len(sink.items) >= 150
            direct = cm.score_records(recs[:5])
            for got, exp in zip(sink.items[:5], direct):
                assert got.score.value == pytest.approx(
                    exp.score.value, rel=1e-6
                )
            src.close()
        finally:
            broker.close()

    def test_block_source_contiguous(self):
        rng = np.random.default_rng(3)
        data = rng.normal(size=(512, 6)).astype(np.float32)
        broker = MiniKafkaBroker(topic="blocks")
        try:
            broker.append_rows(data)
            src = KafkaBlockSource(
                broker.host, broker.port, "blocks",
                n_cols=6, max_wait_ms=20,
            )
            pos = 0
            deadline = time.monotonic() + 15.0
            while pos < 512 and time.monotonic() < deadline:
                polled = src.poll()
                if polled is None:
                    continue
                off, blk = polled
                assert off == pos
                np.testing.assert_array_equal(
                    blk, data[off : off + blk.shape[0]]
                )
                pos += blk.shape[0]
            assert pos == 512
            # seek replays the Kafka log from the requested offset
            src.seek(500)
            off, blk = src.poll()
            assert off == 500 and blk.shape[0] == 12
            src.close()
        finally:
            broker.close()


@pytest.mark.slow
class TestKillResume:
    def test_block_pipeline_resumes_exactly(self, tmp_path):
        doc = parse_pmml_file(
            gen_gbm(str(tmp_path), n_trees=10, depth=3, n_features=5)
        )
        cm = compile_pmml(doc, batch_size=64)
        rng = np.random.default_rng(2)
        N = 3000
        data = rng.normal(0, 1.5, size=(N, 5)).astype(np.float32)
        ckdir = str(tmp_path / "ck")
        cfg = RuntimeConfig(
            batch=BatchConfig(size=64, deadline_us=2000),
            checkpoint_interval_s=0.05,
        )
        seen = []

        def sink(out, n, first_off):
            seen.append((first_off, n))

        broker = MiniKafkaBroker(topic="gbm")
        try:
            broker.append_rows(data)
            src = KafkaBlockSource(
                broker.host, broker.port, "gbm", n_cols=5, max_wait_ms=20
            )
            pipe = BlockPipeline(
                src, cm, sink, cfg, checkpoint=CheckpointManager(ckdir)
            )
            pipe.start()
            deadline = time.monotonic() + 10.0
            while pipe.committed_offset < 500 and time.monotonic() < deadline:
                time.sleep(0.005)
            pipe.stop()
            pipe.join(timeout=30.0)
            committed = pipe.committed_offset
            assert 0 < committed
            src.close()

            src2 = KafkaBlockSource(
                broker.host, broker.port, "gbm", n_cols=5, max_wait_ms=20
            )
            pipe2 = BlockPipeline(
                src2, cm, sink, cfg, checkpoint=CheckpointManager(ckdir)
            )
            assert pipe2.restore()
            assert pipe2.committed_offset == committed
            pipe2.start()
            deadline = time.monotonic() + 30.0
            while pipe2.committed_offset < N and time.monotonic() < deadline:
                time.sleep(0.01)
            pipe2.stop()
            pipe2.join(timeout=30.0)
            src2.close()
        finally:
            broker.close()

        covered = np.zeros(N, np.int32)
        for off, n in seen:
            covered[off : off + n] += 1
        assert (covered == 1).all(), (
            f"gaps={np.flatnonzero(covered == 0)[:5]} "
            f"dups={np.flatnonzero(covered > 1)[:5]}"
        )

    def test_multi_partition_interleave_restores_order(self):
        # producer round-robins rows over 2 partitions; the interleaved
        # consumer reconstructs the original global order exactly
        rng = np.random.default_rng(11)
        data = rng.normal(size=(300, 4)).astype(np.float32)
        broker = MiniKafkaBroker(topic="mp", n_partitions=2)
        try:
            broker.append_rows_round_robin(data)
            src = KafkaBlockSource(
                broker.host, broker.port, "mp", partitions=[0, 1],
                n_cols=4, max_wait_ms=20, interleave="strict",
            )
            pos = 0
            deadline = time.monotonic() + 15.0
            while pos < 300 and time.monotonic() < deadline:
                polled = src.poll()
                if polled is None:
                    continue
                off, blk = polled
                assert off == pos
                np.testing.assert_array_equal(
                    blk, data[off : off + blk.shape[0]]
                )
                pos += blk.shape[0]
            assert pos == 300
            # seek: one scalar offset restores BOTH partition cursors
            src.seek(151)
            off, blk = src.poll()
            assert off == 151
            np.testing.assert_array_equal(blk[0], data[151])
            src.close()
        finally:
            broker.close()

    def test_multi_partition_staggered_producers(self):
        # partitions fill at different rates: the strict interleave must
        # stall at the slowest partition's cursor (never reorder or skip)
        # and drain the backlog once it catches up
        rows = np.arange(400 * 2, dtype=np.float32).reshape(400, 2)
        broker = MiniKafkaBroker(topic="st", n_partitions=2)
        try:
            # partition 0 far ahead of partition 1
            broker.append_rows(rows[0::2], partition=0)
            broker.append_rows(rows[1::2][:3], partition=1)
            src = KafkaBlockSource(
                broker.host, broker.port, "st", partitions=[0, 1],
                n_cols=2, max_wait_ms=20, interleave="strict",
            )
            got = []
            pos = 0
            deadline = time.monotonic() + 10.0
            while pos < 7 and time.monotonic() < deadline:
                p = src.poll()
                if p:
                    got.append(p[1])
                    pos += p[1].shape[0]
            # 3 full strides + the head record of the incomplete one
            # (global 6 lands on partition 0); global 7 needs partition
            # 1's 4th record, which doesn't exist yet
            assert pos == 7
            assert src.poll() is None  # stalled, not reordered
            # catch-up: the rest of partition 1 arrives
            broker.append_rows(rows[1::2][3:], partition=1)
            deadline = time.monotonic() + 15.0
            while pos < 400 and time.monotonic() < deadline:
                p = src.poll()
                if p:
                    got.append(p[1])
                    pos += p[1].shape[0]
            assert pos == 400
            np.testing.assert_array_equal(np.concatenate(got), rows)
            src.close()
        finally:
            broker.close()

    def test_multi_partition_record_source(self):
        broker = MiniKafkaBroker(topic="mpr", n_partitions=3)
        try:
            for i in range(30):
                broker.append(
                    json.dumps({"i": i}).encode(), partition=i % 3
                )
            src = KafkaRecordSource(
                broker.host, broker.port, "mpr", partitions=[0, 1, 2],
                max_wait_ms=20, interleave="strict",
            )
            got = []
            deadline = time.monotonic() + 15.0
            while len(got) < 30 and time.monotonic() < deadline:
                got.extend(src.poll(max_n=7))
            # engine offsets are global-index+1; records in global order
            assert [r["i"] for _, r in got] == list(range(30))
            assert [o for o, _ in got] == list(range(1, 31))
            src.close()
        finally:
            broker.close()

    def test_concurrent_produce_consume_ordered(self):
        # live producer racing the consumer: the segment cache grows
        # under the lock while fetches serve from it — order and
        # completeness must hold (the round-4 broker stores encoded
        # segments, so this is the write/read race that rework created)
        import threading

        rows = np.arange(2000 * 3, dtype=np.float32).reshape(2000, 3)
        broker = MiniKafkaBroker(topic="live")
        try:
            def produce():
                for i in range(0, 2000, 100):
                    broker.append_rows(rows[i : i + 100])
                    time.sleep(0.002)

            t = threading.Thread(target=produce)
            t.start()
            src = KafkaBlockSource(
                broker.host, broker.port, "live", n_cols=3, max_wait_ms=20
            )
            got = []
            pos = 0
            deadline = time.monotonic() + 30.0
            while pos < 2000 and time.monotonic() < deadline:
                polled = src.poll()
                if polled is None:
                    continue
                off, blk = polled
                assert off == pos
                got.append(blk)
                pos += blk.shape[0]
            t.join()
            assert pos == 2000
            np.testing.assert_array_equal(np.concatenate(got), rows)
            src.close()
        finally:
            broker.close()

    def test_source_survives_broker_restart(self):
        data = np.arange(400 * 3, dtype=np.float32).reshape(400, 3)
        broker = MiniKafkaBroker(topic="r")
        port = broker.port
        src = KafkaBlockSource(
            broker.host, port, "r", n_cols=3, max_wait_ms=20
        )
        broker.append_rows(data[:250])
        got = []
        pos = 0
        deadline = time.monotonic() + 30.0
        while pos < 250 and time.monotonic() < deadline:
            polled = src.poll()
            if polled is None:
                time.sleep(0.005)
                continue
            got.append(polled)
            pos += polled[1].shape[0]
        assert pos == 250
        broker.close()  # broker dies
        # outage: polls yield None (reconnect with backoff), never raise
        assert src.poll() is None
        # restart on the same port with the full log (a real broker's
        # log is durable; the mini broker models that by re-serving it)
        broker2 = MiniKafkaBroker(topic="r", port=port)
        try:
            broker2.append_rows(data)
            deadline = time.monotonic() + 30.0
            while pos < 400 and time.monotonic() < deadline:
                polled = src.poll()
                if polled is None:
                    time.sleep(0.005)
                    continue
                off, blk = polled
                assert off == pos  # resumed at exactly the next offset
                got.append(polled)
                pos += blk.shape[0]
            assert pos == 400
            covered = np.zeros(400, np.int32)
            for off, blk in got:
                covered[off : off + blk.shape[0]] += 1
            assert (covered == 1).all()
            src.close()
        finally:
            broker2.close()


@pytest.mark.slow
class TestMultiPartitionResume:
    def test_block_pipeline_resumes_exactly_across_two_partitions(
        self, tmp_path
    ):
        """VERDICT r3 #10: the kill/resume drill over a 2-partition topic —
        the single checkpointed offset must restore both partition cursors
        and replay every record exactly once."""
        doc = parse_pmml_file(
            gen_gbm(str(tmp_path), n_trees=8, depth=3, n_features=5)
        )
        cm = compile_pmml(doc, batch_size=64)
        rng = np.random.default_rng(13)
        N = 2000
        data = rng.normal(0, 1.5, size=(N, 5)).astype(np.float32)
        ckdir = str(tmp_path / "ck")
        cfg = RuntimeConfig(
            batch=BatchConfig(size=64, deadline_us=2000),
            checkpoint_interval_s=0.05,
        )
        seen = []

        def sink(out, n, first_off):
            seen.append((first_off, n))

        def mk_src():
            return KafkaBlockSource(
                broker.host, broker.port, "mp2", partitions=[0, 1],
                n_cols=5, max_wait_ms=20, interleave="strict",
            )

        broker = MiniKafkaBroker(topic="mp2", n_partitions=2)
        try:
            broker.append_rows_round_robin(data)
            src = mk_src()
            pipe = BlockPipeline(
                src, cm, sink, cfg, checkpoint=CheckpointManager(ckdir)
            )
            pipe.start()
            deadline = time.monotonic() + 10.0
            while pipe.committed_offset < 400 and time.monotonic() < deadline:
                time.sleep(0.005)
            pipe.stop()
            pipe.join(timeout=30.0)
            committed = pipe.committed_offset
            assert 0 < committed
            src.close()

            src2 = mk_src()
            pipe2 = BlockPipeline(
                src2, cm, sink, cfg, checkpoint=CheckpointManager(ckdir)
            )
            assert pipe2.restore()
            assert pipe2.committed_offset == committed
            pipe2.start()
            deadline = time.monotonic() + 30.0
            while pipe2.committed_offset < N and time.monotonic() < deadline:
                time.sleep(0.01)
            pipe2.stop()
            pipe2.join(timeout=30.0)
            src2.close()
        finally:
            broker.close()

        covered = np.zeros(N, np.int32)
        for off, n in seen:
            covered[off : off + n] += 1
        assert (covered == 1).all(), (
            f"gaps={np.flatnonzero(covered == 0)[:5]} "
            f"dups={np.flatnonzero(covered > 1)[:5]}"
        )


class TestInterleaveMigration:
    """The strict-resume migration path (docs/migration.md, 'Kafka
    multi-partition interleave and checkpoint migration'): a legacy
    scalar-only checkpoint written by the pre-vector strict bijection
    (a) is REFUSED by a default-constructed (auto) source with a pointer
    to the migration notes, and (b) resumes exactly when the source is
    constructed with interleave='strict' as the notes prescribe.
    Fast-loop on purpose: tier-1 guards the migration contract."""

    def test_legacy_scalar_checkpoint_migration_path(self):
        rng = np.random.default_rng(9)
        data = rng.normal(size=(40, 4)).astype(np.float32)
        broker = MiniKafkaBroker(topic="vec", n_partitions=2)
        try:
            # round-robin producer: global index g lives at partition
            # g % 2, offset g // 2 — the strict bijection's layout
            broker.append_rows(data[0::2], partition=0)
            broker.append_rows(data[1::2], partition=1)

            # (a) the post-default-change constructor (auto) cannot
            # expand a scalar offset; the error routes to the docs
            src_auto = KafkaBlockSource(
                broker.host, broker.port, "vec",
                partitions=[0, 1], n_cols=4, max_wait_ms=20,
            )
            with pytest.raises(
                KafkaProtocolError, match="docs/migration.md"
            ):
                src_auto.seek(10)  # the legacy checkpoint's scalar
            src_auto.close()

            # (b) the documented migration: interleave='strict' resumes
            # the same scalar exactly, records in producer order
            src = KafkaBlockSource(
                broker.host, broker.port, "vec",
                partitions=[0, 1], n_cols=4, max_wait_ms=20,
                interleave="strict",
            )
            src.seek(10)
            got, pos = [], 10
            deadline = time.monotonic() + 10.0
            while len(got) < 30 and time.monotonic() < deadline:
                polled = src.poll()
                if polled is None:
                    time.sleep(0.01)
                    continue
                off, blk = polled
                assert off == pos
                pos += blk.shape[0]
                got.extend(np.asarray(blk))
            src.close()
            np.testing.assert_array_equal(
                np.asarray(got), data[10:40]
            )
        finally:
            broker.close()


@pytest.mark.slow
class TestVectorOffsets:
    """Multi-partition ``interleave="auto"`` (the default): keyed
    producers (no round-robin bijection), compaction gaps, and resume
    from a checkpointed per-partition offset vector (VERDICT r4 #5)."""

    def _keyed_gapped_broker(self, data, n_partitions=3):
        """Keyed producer over ``n_partitions`` + compaction gaps in
        every partition → (broker, surviving row multiset)."""
        broker = MiniKafkaBroker(topic="vec", n_partitions=n_partitions)
        keys = [f"user-{i % 17}" for i in range(data.shape[0])]
        broker.append_rows_keyed(data, keys)
        # compact away a slice of each partition's middle (real gaps)
        survivors = []
        with broker._mu:
            sizes = [len(v) for v in broker._vals]
        for p in range(n_partitions):
            drop = list(range(5, min(25, sizes[p])))
            broker.compact(p, drop)
        with broker._mu:
            for p in range(n_partitions):
                survivors.extend(broker._vals[p])
        return broker, survivors

    def test_keyed_uneven_fill_consumes_everything(self):
        rng = np.random.default_rng(3)
        data = rng.normal(size=(300, 4)).astype(np.float32)
        broker = MiniKafkaBroker(topic="vec", n_partitions=3)
        try:
            broker.append_rows_keyed(
                data, [f"k{i % 11}" for i in range(300)]
            )
            src = KafkaBlockSource(
                broker.host, broker.port, "vec",
                partitions=[0, 1, 2], n_cols=4, max_wait_ms=20,
            )
            rows, pos = [], 0
            deadline = time.monotonic() + 15.0
            while len(rows) < 300 and time.monotonic() < deadline:
                polled = src.poll()
                if polled is None:
                    continue
                off, blk = polled
                assert off == pos  # global indices stay contiguous
                pos += blk.shape[0]
                rows.extend(blk.tobytes(order="C")[i * 16 : (i + 1) * 16]
                            for i in range(blk.shape[0]))
            src.close()
        finally:
            broker.close()
        # every produced row consumed exactly once (content multiset)
        want = sorted(data[i].tobytes() for i in range(300))
        assert sorted(rows) == want

    def test_compaction_gaps_are_data_not_errors(self):
        rng = np.random.default_rng(4)
        data = rng.normal(size=(240, 4)).astype(np.float32)
        broker, survivors = self._keyed_gapped_broker(data)
        try:
            src = KafkaBlockSource(
                broker.host, broker.port, "vec",
                partitions=[0, 1, 2], n_cols=4, max_wait_ms=20,
            )
            rows = []
            deadline = time.monotonic() + 15.0
            while len(rows) < len(survivors) and (
                time.monotonic() < deadline
            ):
                polled = src.poll()
                if polled is None:
                    continue
                _, blk = polled
                rows.extend(
                    blk[i].tobytes() for i in range(blk.shape[0])
                )
            src.close()
        finally:
            broker.close()
        assert sorted(rows) == sorted(survivors)

    def test_vector_state_resume_is_content_exact(self):
        """checkpoint_state/restore_state round trip: rows below the
        resume boundary never refetch; the union of pre-boundary and
        post-restore emissions is EXACTLY the surviving log."""
        rng = np.random.default_rng(5)
        data = rng.normal(size=(240, 4)).astype(np.float32)
        broker, survivors = self._keyed_gapped_broker(data)
        try:
            src = KafkaBlockSource(
                broker.host, broker.port, "vec",
                partitions=[0, 1, 2], n_cols=4, max_wait_ms=20,
            )
            run1 = []  # (global_idx, row bytes)
            while len(run1) < len(survivors) // 2:
                polled = src.poll()
                if polled is None:
                    continue
                off, blk = polled
                run1.extend(
                    (off + i, blk[i].tobytes())
                    for i in range(blk.shape[0])
                )
            committed = len(run1) - 3  # a commit mid-emission
            state = src.checkpoint_state(committed)
            assert state is not None and state["offset"] <= committed
            src.close()

            src2 = KafkaBlockSource(
                broker.host, broker.port, "vec",
                partitions=[0, 1, 2], n_cols=4, max_wait_ms=20,
            )
            resume = src2.restore_state(state)
            assert resume == state["offset"]
            run2 = []
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline:
                polled = src2.poll()
                if polled is None:
                    if len(run2) + resume >= len(survivors):
                        break
                    continue
                off, blk = polled
                assert off == resume + len(run2)  # contiguous from k'
                run2.extend(
                    blk[i].tobytes() for i in range(blk.shape[0])
                )
            src2.close()
        finally:
            broker.close()
        kept = [row for g, row in run1 if g < resume]
        assert sorted(kept + run2) == sorted(survivors), (
            len(kept), len(run2), len(survivors), resume,
        )

    def test_source_fails_fast_on_unknown_partition(self):
        # err 3 must propagate THROUGH the source's reconnect shield:
        # the fetch loop normally swallows KafkaProtocolError and
        # retries, which would turn a misconfigured partition list into
        # an infinite silent poll
        from flink_jpmml_tpu.runtime.kafka import KafkaPartitionError

        broker = MiniKafkaBroker(topic="vec", n_partitions=2)
        try:
            broker.append(b"\x00" * 16, partition=0)
            src = KafkaBlockSource(
                broker.host, broker.port, "vec",
                partitions=[0, 7], n_cols=4, max_wait_ms=20,
            )
            with pytest.raises(KafkaPartitionError, match="partition 7"):
                deadline = time.monotonic() + 5.0
                while time.monotonic() < deadline:
                    src.poll()
            src.close()
        finally:
            broker.close()

    def test_auto_mode_rejects_scalar_start_offset(self):
        with pytest.raises(ValueError, match="strict"):
            KafkaBlockSource(
                "127.0.0.1", 1, "t", partitions=[0, 1], n_cols=4,
                start_offset=100,
            )

    def test_vector_checkpoint_refused_by_strict_source(self):
        # auto-era cursor-vector state restored into a strict source
        # must refuse loudly (the bijection would misread the offsets)
        broker = MiniKafkaBroker(topic="vec", n_partitions=2)
        try:
            src = KafkaBlockSource(
                broker.host, broker.port, "vec",
                partitions=[0, 1], n_cols=4, interleave="strict",
            )
            with pytest.raises(KafkaProtocolError, match="strict"):
                src.restore_state(
                    {"offset": 10, "cursors": {"0": 6, "1": 4}}
                )
            src.close()
        finally:
            broker.close()

    def test_strict_mode_rejects_keyed_layout(self):
        # the fast path must fail loudly, not mis-align lanes, when the
        # producer was not round-robin
        rng = np.random.default_rng(6)
        data = rng.normal(size=(90, 4)).astype(np.float32)
        broker = MiniKafkaBroker(topic="vec", n_partitions=3)
        try:
            # partition fill 45/30/15 — no bijection exists
            broker.append_rows(data[:45], partition=0)
            broker.append_rows(data[45:75], partition=1)
            broker.append_rows(data[75:], partition=2)
            src = KafkaBlockSource(
                broker.host, broker.port, "vec",
                partitions=[0, 1, 2], n_cols=4, max_wait_ms=20,
                interleave="strict",
            )
            got = 0
            last_progress = time.monotonic()
            # strict mode serves the bijection prefix — global indices
            # up to the first one whose slot has run dry (partition 2
            # holds 15 records: indices 0..46 are servable, 47 maps to
            # slot 2 offset 15 which never arrives) — then stalls; it
            # must never emit beyond it
            while time.monotonic() - last_progress < 1.0:
                polled = src.poll()
                if polled is None:
                    time.sleep(0.01)
                    continue
                got += polled[1].shape[0]
                last_progress = time.monotonic()
            assert got == 47, got
            src.close()
        finally:
            broker.close()

    def test_pipeline_kill_resume_keyed_gapped(self, tmp_path):
        """The VERDICT drill: kill/resume over a keyed (non-round-robin)
        producer and a gap-containing log — exact offset accounting
        below the restore point, duplicates confined to the replay
        window, final commit == surviving record count."""
        doc = parse_pmml_file(
            gen_gbm(str(tmp_path), n_trees=6, depth=3, n_features=4)
        )
        cm = compile_pmml(doc, batch_size=32)
        rng = np.random.default_rng(7)
        data = rng.normal(0, 1.5, size=(1500, 4)).astype(np.float32)
        broker, survivors = self._keyed_gapped_broker(data)
        total = len(survivors)
        ckdir = str(tmp_path / "ck")
        cfg = RuntimeConfig(
            batch=BatchConfig(size=32, deadline_us=2000),
            checkpoint_interval_s=0.02,
        )
        seen = []

        def sink(out, n, first_off):
            seen.append((first_off, n))

        def mk_src():
            return KafkaBlockSource(
                broker.host, broker.port, "vec",
                partitions=[0, 1, 2], n_cols=4, max_wait_ms=20,
            )

        try:
            src = mk_src()
            pipe = BlockPipeline(
                src, cm, sink, cfg, checkpoint=CheckpointManager(ckdir)
            )
            pipe.start()
            deadline = time.monotonic() + 15.0
            while pipe.committed_offset < total // 3 and (
                time.monotonic() < deadline
            ):
                time.sleep(0.005)
            pipe.stop()  # mid-stream: uncommitted backlog discarded
            pipe.join(timeout=30.0)
            src.close()

            src2 = mk_src()
            pipe2 = BlockPipeline(
                src2, cm, sink, cfg, checkpoint=CheckpointManager(ckdir)
            )
            assert pipe2.restore()
            resume = pipe2.committed_offset
            assert 0 < resume <= total
            pipe2.start()
            deadline = time.monotonic() + 30.0
            while pipe2.committed_offset < total and (
                time.monotonic() < deadline
            ):
                time.sleep(0.01)
            pipe2.stop()
            pipe2.join(timeout=30.0)
            src2.close()
            assert pipe2.committed_offset == total
        finally:
            broker.close()

        covered = np.zeros(total, np.int64)
        for off, n in seen:
            covered[off : off + n] += 1
        assert (covered >= 1).all(), (
            f"gaps={np.flatnonzero(covered == 0)[:5]}"
        )
        assert (covered[:resume] == 1).all(), (
            f"dups below resume at "
            f"{np.flatnonzero(covered[:resume] > 1)[:5]}"
        )


class TestIdleCommit:
    def test_paused_feed_commits_tail_batch(self, tmp_path):
        """A feed that stops mid-stream must not pin the final partial
        batch uncommitted in the in-flight window: committed_offset has
        to reach the high watermark WITHOUT stop() being called."""
        doc = parse_pmml_file(
            gen_gbm(str(tmp_path), n_trees=5, depth=3, n_features=4)
        )
        cm = compile_pmml(doc, batch_size=64)
        N = 200  # 3 full batches of 64 + a 8-record tail
        data = np.random.default_rng(9).normal(size=(N, 4)).astype(np.float32)
        broker = MiniKafkaBroker(topic="pause")
        try:
            broker.append_rows(data)
            src = KafkaBlockSource(
                broker.host, broker.port, "pause", n_cols=4, max_wait_ms=10
            )
            done = []
            pipe = BlockPipeline(
                src, cm, lambda out, n, off: done.append(n),
                RuntimeConfig(batch=BatchConfig(size=64, deadline_us=2000)),
            )
            pipe.start()
            deadline = time.monotonic() + 15.0
            while pipe.committed_offset < N and time.monotonic() < deadline:
                time.sleep(0.01)
            committed = pipe.committed_offset  # BEFORE stop
            pipe.stop()
            pipe.join(timeout=10.0)
            src.close()
            assert committed == N, (
                f"paused feed left offset at {committed} (<{N}); the "
                "in-flight window was not flushed on idle"
            )
            assert sum(done) == N
        finally:
            broker.close()


class TestEventTimeFreshness:
    """The freshness plane's kafka side (ISSUE 7): event time rides the
    record-batch headers, fetch advances per-partition watermarks, and
    the ingest→sink stamp channel books record staleness."""

    def test_record_batch_time_range_header_walk(self):
        from flink_jpmml_tpu.runtime.kafka import record_batch_time_range

        b1 = encode_record_batch(0, [b"a", b"b"], timestamp_ms=5_000)
        b2 = encode_record_batch(2, [b"c"], timestamp_ms=9_000)
        assert record_batch_time_range(b1) == (5.0, 5.0)
        assert record_batch_time_range(b1 + b2) == (5.0, 9.0)
        # timestamp 0 (the native encoder's default) = no event time
        b0 = encode_record_batch(3, [b"d"])
        assert record_batch_time_range(b0) is None
        assert record_batch_time_range(b0 + b2) == (9.0, 9.0)
        # truncated tail: the whole-batch prefix still reads
        assert record_batch_time_range(b1 + b2[: len(b2) // 2]) == (5.0, 5.0)
        assert record_batch_time_range(b"") is None

    def test_timestamped_append_rows_roundtrip(self):
        """A timestamped append_rows takes the Python encoder (the
        native one writes ts 0) and stays byte-decodable with the same
        offsets and payloads."""
        from flink_jpmml_tpu.runtime.kafka import record_batch_time_range

        rng = np.random.default_rng(5)
        data = rng.normal(size=(700, 4)).astype(np.float32)
        broker = MiniKafkaBroker(topic="ts")
        try:
            broker.append_rows(data, timestamp_ms=42_000)
            client = KafkaClient(broker.host, broker.port)
            try:
                hw, raw = client.fetch_raw("ts", 0, 0, max_wait_ms=20)
                assert hw == 700
                recs = decode_record_batches(raw)
                assert recs[0] == (0, data[0].tobytes())
                tr = record_batch_time_range(raw)
                assert tr == (42.0, 42.0)
            finally:
                client.close()
        finally:
            broker.close()

    def test_block_source_advances_watermark_and_books_staleness(self):
        from flink_jpmml_tpu.obs.freshness import freshness_for
        from flink_jpmml_tpu.utils.metrics import MetricsRegistry

        rng = np.random.default_rng(6)
        data = rng.normal(size=(256, 4)).astype(np.float32)
        broker = MiniKafkaBroker(topic="fresh")
        m = MetricsRegistry()
        try:
            now_ms = int(time.time() * 1000)
            broker.append_rows(data, timestamp_ms=now_ms - 3_000)
            src = KafkaBlockSource(
                broker.host, broker.port, "fresh",
                n_cols=4, max_wait_ms=20, metrics=m,
            )
            try:
                pos = 0
                deadline = time.monotonic() + 15.0
                while pos < 256 and time.monotonic() < deadline:
                    polled = src.poll()
                    if polled is None:
                        continue
                    off, blk = polled
                    pos = off + blk.shape[0]
                assert pos == 256
                g = m.struct_snapshot()["gauges"]
                wm_lag = g.get('watermark_lag_s{partition="0"}')
                assert wm_lag is not None
                # the records were stamped ~3 s ago: end-to-end event-
                # time lag reads it (bounded well above by test slop)
                assert 2.5 <= wm_lag["value"] < 60.0
                # the fetch path fed the forecaster: lag + age gauges
                assert 'kafka_lag{partition="0"}' in g
                assert 'kafka_lag_age_s{partition="0"}' in g
                # the sink side consumes the ingest stamps
                fr = freshness_for(m)
                fr.observe_sink(0, 256)
                h = m.histogram("record_staleness_s")
                assert h.count() >= 2
                assert h.quantile(0.5) == pytest.approx(3.0, abs=2.0)
                assert g_val(m, "watermark_ts") == pytest.approx(
                    (now_ms - 3_000) / 1000.0, abs=1.0
                )
            finally:
                src.close()
        finally:
            broker.close()

    def test_fetch_failure_still_sweeps_lag_age(self, monkeypatch):
        """A dead broker must not freeze kafka_lag_age_s at its last
        fresh-looking value: every fetch skips _observe_fetch on the
        reconnect path, so the sweep has to ride that path too or the
        FJT_LAG_STALE_S crossing never fires (review finding, pinned)."""
        from flink_jpmml_tpu.utils.metrics import MetricsRegistry

        rng = np.random.default_rng(13)
        data = rng.normal(size=(32, 4)).astype(np.float32)
        broker = MiniKafkaBroker(topic="dead")
        m = MetricsRegistry()
        src = None
        try:
            broker.append_rows(
                data, timestamp_ms=int(time.time() * 1000)
            )
            src = KafkaBlockSource(
                broker.host, broker.port, "dead",
                n_cols=4, max_wait_ms=20, metrics=m,
                reconnect_backoff_s=0.01,
            )
            pos = 0
            deadline = time.monotonic() + 15.0
            while pos < 32 and time.monotonic() < deadline:
                polled = src.poll()
                if polled is None:
                    continue
                off, blk = polled
                pos = off + blk.shape[0]
            assert pos == 32
            broker.close()
            broker = None
            sweeps = []
            monkeypatch.setattr(
                src._forecaster, "sweep",
                lambda now=None: sweeps.append(1),
            )
            src.poll()  # fetch fails → reconnect → sweep still runs
            assert sweeps
        finally:
            if src is not None:
                src.close()
            if broker is not None:
                broker.close()

    def test_unstamped_log_stays_out_of_the_freshness_plane(self):
        from flink_jpmml_tpu.obs.freshness import freshness_for
        from flink_jpmml_tpu.utils.metrics import MetricsRegistry

        rng = np.random.default_rng(7)
        data = rng.normal(size=(128, 4)).astype(np.float32)
        broker = MiniKafkaBroker(topic="nots")
        m = MetricsRegistry()
        try:
            broker.append_rows(data)  # native path: timestamp 0
            src = KafkaBlockSource(
                broker.host, broker.port, "nots",
                n_cols=4, max_wait_ms=20, metrics=m,
            )
            try:
                pos = 0
                deadline = time.monotonic() + 15.0
                while pos < 128 and time.monotonic() < deadline:
                    polled = src.poll()
                    if polled is None:
                        continue
                    off, blk = polled
                    pos = off + blk.shape[0]
                assert pos == 128
                fr = freshness_for(m)
                assert fr.low_watermark() is None
                fr.observe_sink(0, 128)
                assert m.histogram("record_staleness_s").count() == 0
                g = m.struct_snapshot()["gauges"]
                assert 'watermark_lag_s{partition="0"}' not in g
                # a 1970 watermark would have read as ~56 years of lag
            finally:
                src.close()
        finally:
            broker.close()

    def test_seek_resets_stamps_but_not_watermarks(self):
        from flink_jpmml_tpu.obs.freshness import freshness_for
        from flink_jpmml_tpu.utils.metrics import MetricsRegistry

        rng = np.random.default_rng(8)
        data = rng.normal(size=(64, 4)).astype(np.float32)
        broker = MiniKafkaBroker(topic="seek")
        m = MetricsRegistry()
        try:
            broker.append_rows(
                data, timestamp_ms=int(time.time() * 1000)
            )
            src = KafkaBlockSource(
                broker.host, broker.port, "seek",
                n_cols=4, max_wait_ms=20, metrics=m,
            )
            try:
                deadline = time.monotonic() + 15.0
                pos = 0
                while pos < 64 and time.monotonic() < deadline:
                    polled = src.poll()
                    if polled is None:
                        continue
                    off, blk = polled
                    pos = off + blk.shape[0]
                fr = freshness_for(m)
                wm = fr.low_watermark()
                assert wm is not None
                src.seek(0)  # replay: offset domain restarted
                fr.observe_sink(0, 64)
                # the pre-seek stamps were dropped, not mis-keyed
                assert m.histogram("record_staleness_s").count() == 0
                assert fr.low_watermark() == wm  # time never regresses
            finally:
                src.close()
        finally:
            broker.close()


def g_val(m, name):
    v = m.struct_snapshot()["gauges"].get(name)
    return v["value"] if isinstance(v, dict) else None


class TestEventTimeStrictInterleave:
    def test_strict_interleave_stamps_ingest(self):
        """The strict round-robin path buffers rows across fetches; its
        emitted runs must still carry ingest stamps so the sink books
        staleness (review finding: the plane was dark on
        interleave='strict' topologies, pinned)."""
        from flink_jpmml_tpu.obs.freshness import freshness_for
        from flink_jpmml_tpu.utils.metrics import MetricsRegistry

        rng = np.random.default_rng(9)
        data = rng.normal(size=(300, 4)).astype(np.float32)
        broker = MiniKafkaBroker(topic="mpts", n_partitions=2)
        m = MetricsRegistry()
        try:
            now_ms = int(time.time() * 1000)
            broker.append_rows_round_robin(
                data, timestamp_ms=now_ms - 4_000
            )
            src = KafkaBlockSource(
                broker.host, broker.port, "mpts", partitions=[0, 1],
                n_cols=4, max_wait_ms=20, interleave="strict",
                metrics=m,
            )
            try:
                pos = 0
                deadline = time.monotonic() + 15.0
                while pos < 300 and time.monotonic() < deadline:
                    polled = src.poll()
                    if polled is None:
                        continue
                    off, blk = polled
                    np.testing.assert_array_equal(
                        blk, data[off : off + blk.shape[0]]
                    )
                    pos = off + blk.shape[0]
                assert pos == 300
                g = m.struct_snapshot()["gauges"]
                assert 'watermark_lag_s{partition="0"}' in g
                assert 'watermark_lag_s{partition="1"}' in g
                fr = freshness_for(m)
                fr.observe_sink(0, 300)
                h = m.histogram("record_staleness_s")
                assert h.count() >= 2
                assert h.quantile(0.5) == pytest.approx(4.0, abs=2.0)
                assert g_val(m, "watermark_ts") == pytest.approx(
                    (now_ms - 4_000) / 1000.0, abs=1.0
                )
            finally:
                src.close()
        finally:
            broker.close()

    def test_explicit_none_trange_never_borrows_last_fetch(self):
        """An interleaved run whose consumed slots carried NO event
        times merges to trange=None; the stamp must be a no-op — not
        fall back to the previous (possibly foreign-partition) fetch's
        range and book unstamped rows with borrowed event times
        (review finding, pinned)."""
        from flink_jpmml_tpu.obs.freshness import freshness_for
        from flink_jpmml_tpu.utils.metrics import MetricsRegistry

        rng = np.random.default_rng(11)
        data = rng.normal(size=(64, 4)).astype(np.float32)
        broker = MiniKafkaBroker(topic="mixed")
        m = MetricsRegistry()
        try:
            now_ms = int(time.time() * 1000)
            broker.append_rows(data, timestamp_ms=now_ms - 5_000)
            src = KafkaBlockSource(
                broker.host, broker.port, "mixed",
                n_cols=4, max_wait_ms=20, metrics=m,
            )
            try:
                pos = 0
                deadline = time.monotonic() + 15.0
                while pos < 64 and time.monotonic() < deadline:
                    polled = src.poll()
                    if polled is None:
                        continue
                    off, blk = polled
                    pos = off + blk.shape[0]
                assert pos == 64
                assert src._last_trange is not None  # a stamped fetch
                fr = freshness_for(m)
                fr.observe_sink(0, 64)
                h = m.histogram("record_staleness_s")
                booked = h.count()
                assert booked >= 2
                # an unstamped run: explicit None, NOT the default
                src._stamp_ingest(1_000, 8, trange=None)
                fr.observe_sink(1_000, 8)
                assert h.count() == booked
            finally:
                src.close()
        finally:
            broker.close()


from flink_jpmml_tpu.utils.metrics import MetricsRegistry as _MReg


class TestDecodePoisonRouting:
    """ISSUE 12 satellite: decode errors stop being silently filtered —
    counted per partition, raw bytes to the DLQ, record skipped exactly
    once (never refetched forever, never fatal with a DLQ installed)."""

    def _broker_with_poison(self):
        broker = MiniKafkaBroker(topic="p")
        rows = np.arange(20, dtype=np.float32).reshape(5, 4)
        broker.append_rows(rows[:2])
        broker.append(b"short")        # 5 bytes: undecodable
        broker.append_rows(rows[2:4])
        broker.append(b"x" * 20)       # 20 bytes: over-long is ALSO
        # poison — np.frombuffer would silently truncate it into a
        # plausible row (pinned strict in decode_record_batches_rows)
        broker.append_rows(rows[4:])
        return broker, rows

    def test_block_source_skips_counts_and_quarantines(self, tmp_path):
        from flink_jpmml_tpu.runtime.dlq import DeadLetterQueue

        broker, rows = self._broker_with_poison()
        try:
            m = _MReg()
            dlq = DeadLetterQueue(str(tmp_path / "dlq"), metrics=m)
            src = KafkaBlockSource(
                broker.host, broker.port, "p", n_cols=4,
                metrics=m, dlq=dlq, max_wait_ms=10,
            )
            try:
                got = []
                for _ in range(30):
                    p = src.poll()
                    if p is None:
                        if len(got) >= 5:
                            break
                        continue
                    off, blk = p
                    for i in range(blk.shape[0]):
                        got.append((off + i, blk[i].tolist()))
                assert [o for o, _ in got] == [0, 1, 3, 4, 6]
                # rows decode under their TRUE offsets (no shift)
                assert got[2][1] == rows[2].tolist()
                assert sorted(set(dlq.offsets())) == [2, 5]
                assert all(
                    e["reason"] == "decode" for e in dlq.scan()
                )
                snap = m.struct_snapshot()["counters"]
                # ≥2: a gap-truncated refetch may see (and re-count) a
                # trailing poison value once more — the counter is per
                # rejection EVENT; the DLQ offset set stays exact
                assert snap['decode_errors{partition="0"}'] >= 2
            finally:
                src.close()
        finally:
            broker.close()

    def test_block_source_without_dlq_or_metrics_raises(self):
        broker, _ = self._broker_with_poison()
        try:
            src = KafkaBlockSource(
                broker.host, broker.port, "p", n_cols=4, max_wait_ms=10,
            )
            try:
                with pytest.raises(ValueError, match="value length"):
                    for _ in range(10):
                        src.poll()
            finally:
                src.close()
        finally:
            broker.close()

    def test_record_source_skips_bad_json(self, tmp_path):
        import json as _json

        from flink_jpmml_tpu.runtime.dlq import (
            DeadLetterQueue, payload_bytes,
        )

        broker = MiniKafkaBroker(topic="r")
        try:
            broker.append(
                _json.dumps({"a": 1}).encode(),
                b"not json {{",
                _json.dumps({"a": 2}).encode(),
            )
            m = _MReg()
            dlq = DeadLetterQueue(str(tmp_path / "dlq"), metrics=m)
            src = KafkaRecordSource(
                broker.host, broker.port, "r",
                metrics=m, dlq=dlq, max_wait_ms=10,
            )
            try:
                recs = src.poll(10)
                assert [r for _, r in recs] == [{"a": 1}, {"a": 2}]
                envs = list(dlq.scan())
                assert [e["offset"] for e in envs] == [1]
                assert payload_bytes(envs[0]) == b"not json {{"
            finally:
                src.close()
        finally:
            broker.close()

    def test_all_poison_fetch_advances_cursor_once(self, tmp_path):
        # a fetch containing ONLY undecodable values must advance the
        # cursor past them — otherwise the next poll refetches and
        # re-quarantines the same bytes forever
        from flink_jpmml_tpu.runtime.dlq import DeadLetterQueue

        broker = MiniKafkaBroker(topic="ap")
        try:
            broker.append(b"junk1", b"junk2")
            rows = np.arange(8, dtype=np.float32).reshape(2, 4)
            broker.append_rows(rows)
            m = _MReg()
            dlq = DeadLetterQueue(str(tmp_path / "dlq"), metrics=m)
            src = KafkaBlockSource(
                broker.host, broker.port, "ap", n_cols=4,
                metrics=m, dlq=dlq, max_wait_ms=10,
            )
            try:
                got = []
                for _ in range(20):
                    p = src.poll()
                    if p is not None:
                        got.append(p[0])
                        if sum(1 for _ in got) >= 1:
                            break
                assert got and got[0] == 2
                assert sorted(set(dlq.offsets())) == [0, 1]
                # quarantined exactly once each, not per refetch
                assert len(dlq.offsets()) == 2
            finally:
                src.close()
        finally:
            broker.close()

    def test_strict_interleave_still_raises(self, tmp_path):
        # the round-robin bijection cannot drop a lane: decode poison
        # under interleave="strict" stays fatal (use auto mode)
        from flink_jpmml_tpu.runtime.dlq import DeadLetterQueue

        broker = MiniKafkaBroker(topic="s", n_partitions=2)
        try:
            rows = np.arange(16, dtype=np.float32).reshape(4, 4)
            broker.append_rows_round_robin(rows)
            broker.append(b"bad", partition=0)
            broker.append_rows(rows[:1], partition=1)
            m = _MReg()
            dlq = DeadLetterQueue(str(tmp_path / "dlq"), metrics=m)
            src = KafkaBlockSource(
                broker.host, broker.port, "s", n_cols=4,
                partitions=[0, 1], interleave="strict",
                metrics=m, dlq=dlq, max_wait_ms=10,
            )
            try:
                with pytest.raises(ValueError, match="value length"):
                    for _ in range(10):
                        src.poll()
                assert dlq.count() == 0
            finally:
                src.close()
        finally:
            broker.close()
