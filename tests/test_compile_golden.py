"""Golden tests: the JAX lowering vs the reference interpreter (oracle).

This is the SURVEY.md §5 tier-1 strategy: "pure unit tests of the PMML→JAX
compiler per model class against golden outputs". Every family is diffed
against the oracle over randomized record batches, including lanes with
missing values, so both value semantics and totality semantics are pinned.
"""

import math

import numpy as np
import pytest

from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.pmml import parse_pmml, parse_pmml_file
from flink_jpmml_tpu.pmml.interp import evaluate
from flink_jpmml_tpu.utils.exceptions import (
    InputValidationException,
    ModelCompilationException,
)

RTOL = 2e-4  # bf16 match einsum is exact; float32 math differs from float64


def _random_records(fields, n, rng, missing_rate=0.0, scale=2.0, loc=0.0):
    X = rng.normal(loc, scale, size=(n, len(fields)))
    recs = []
    for b in range(n):
        rec = {}
        for j, f in enumerate(fields):
            if missing_rate and rng.random() < missing_rate:
                rec[f] = None
            else:
                rec[f] = float(X[b, j])
        recs.append(rec)
    return recs


def _assert_match(cm, doc, records, check_label=True):
    preds = cm.score_records(records)
    for rec, p in zip(records, preds):
        o = evaluate(doc, rec)
        if o.is_missing:
            assert p.is_empty, f"oracle empty but compiled gave {p} for {rec}"
            continue
        assert not p.is_empty, f"compiled empty but oracle gave {o} for {rec}"
        if o.value is not None:
            assert p.score.value == pytest.approx(o.value, rel=RTOL, abs=1e-5), rec
        if check_label and o.label is not None:
            assert p.target is not None and p.target.label == o.label, (
                rec, p.target, o.label, o.probabilities,
            )


class TestRegressionGolden:
    def test_iris_lr(self, assets_dir):
        doc = parse_pmml_file(str(assets_dir / "iris_lr.pmml"))
        cm = compile_pmml(doc)
        rng = np.random.default_rng(1)
        recs = _random_records(doc.active_fields, 64, rng, loc=4.0)
        _assert_match(cm, doc, recs)

    def test_iris_lr_with_missing(self, assets_dir):
        doc = parse_pmml_file(str(assets_dir / "iris_lr.pmml"))
        cm = compile_pmml(doc)
        rng = np.random.default_rng(2)
        recs = _random_records(doc.active_fields, 64, rng, missing_rate=0.3)
        _assert_match(cm, doc, recs)

    def test_probabilities_match(self, assets_dir):
        doc = parse_pmml_file(str(assets_dir / "iris_lr.pmml"))
        cm = compile_pmml(doc)
        rng = np.random.default_rng(3)
        recs = _random_records(doc.active_fields, 8, rng, loc=4.0)
        preds = cm.score_records(recs)
        for rec, p in zip(recs, preds):
            o = evaluate(doc, rec)
            for lbl, prob in o.probabilities.items():
                assert p.target.probabilities[lbl] == pytest.approx(
                    prob, rel=RTOL, abs=1e-6
                )

    def test_categorical_predictor_with_codec(self):
        doc = parse_pmml(
            '<PMML version="4.3"><DataDictionary>'
            '<DataField name="color" optype="categorical" dataType="string">'
            '<Value value="red"/><Value value="blue"/></DataField>'
            '<DataField name="x" optype="continuous" dataType="double"/>'
            "</DataDictionary>"
            '<RegressionModel functionName="regression">'
            '<MiningSchema><MiningField name="color"/><MiningField name="x"/>'
            "</MiningSchema>"
            '<RegressionTable intercept="1.0">'
            '<NumericPredictor name="x" coefficient="2.0"/>'
            '<CategoricalPredictor name="color" value="red" coefficient="5.0"/>'
            "</RegressionTable></RegressionModel></PMML>"
        )
        cm = compile_pmml(doc)
        recs = [
            {"color": "red", "x": 1.0},
            {"color": "blue", "x": 1.0},
            {"color": None, "x": 1.0},
            {"color": "green", "x": 1.0},  # undeclared category → missing cat
        ]
        _assert_match(cm, doc, recs)

    def test_exponent(self):
        doc = parse_pmml(
            '<PMML version="4.3"><DataDictionary>'
            '<DataField name="x" optype="continuous" dataType="double"/>'
            "</DataDictionary>"
            '<RegressionModel functionName="regression" '
            'normalizationMethod="exp">'
            '<MiningSchema><MiningField name="x"/></MiningSchema>'
            '<RegressionTable intercept="0.5">'
            '<NumericPredictor name="x" coefficient="1.5" exponent="3"/>'
            "</RegressionTable></RegressionModel></PMML>"
        )
        cm = compile_pmml(doc)
        _assert_match(cm, doc, [{"x": 0.7}, {"x": -1.2}, {"x": 2.0}])


class TestTreeGolden:
    def test_gbm_sum(self, assets_dir):
        doc = parse_pmml_file(str(assets_dir / "gbm_small.pmml"))
        cm = compile_pmml(doc)
        rng = np.random.default_rng(4)
        recs = _random_records(doc.active_fields, 128, rng)
        _assert_match(cm, doc, recs)

    def test_gbm_with_missing_default_child(self, assets_dir):
        doc = parse_pmml_file(str(assets_dir / "gbm_small.pmml"))
        cm = compile_pmml(doc)
        rng = np.random.default_rng(5)
        recs = _random_records(doc.active_fields, 128, rng, missing_rate=0.25)
        _assert_match(cm, doc, recs)

    def test_single_tree_none_strategy_missing_is_empty(self):
        xml = (
            '<PMML version="4.3"><DataDictionary>'
            '<DataField name="a" optype="continuous" dataType="double"/>'
            "</DataDictionary>"
            '<TreeModel functionName="regression" missingValueStrategy="none">'
            '<MiningSchema><MiningField name="a"/></MiningSchema>'
            '<Node id="r"><True/>'
            '<Node id="l" score="1"><SimplePredicate field="a" '
            'operator="lessThan" value="0"/></Node>'
            '<Node id="rr" score="2"><SimplePredicate field="a" '
            'operator="greaterOrEqual" value="0"/></Node>'
            "</Node></TreeModel></PMML>"
        )
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        _assert_match(cm, doc, [{"a": -1.0}, {"a": 1.0}, {"a": None}])

    def test_classification_tree(self):
        xml = (
            '<PMML version="4.3"><DataDictionary>'
            '<DataField name="a" optype="continuous" dataType="double"/>'
            '<DataField name="b" optype="continuous" dataType="double"/>'
            "</DataDictionary>"
            '<TreeModel functionName="classification">'
            '<MiningSchema><MiningField name="a"/><MiningField name="b"/>'
            "</MiningSchema>"
            '<Node id="r"><True/>'
            '<Node id="l"><SimplePredicate field="a" operator="lessThan" '
            'value="0"/>'
            '<Node id="ll" score="cat"><SimplePredicate field="b" '
            'operator="lessThan" value="1"/>'
            '<ScoreDistribution value="cat" recordCount="8"/>'
            '<ScoreDistribution value="dog" recordCount="2"/></Node>'
            '<Node id="lr" score="dog"><SimplePredicate field="b" '
            'operator="greaterOrEqual" value="1"/>'
            '<ScoreDistribution value="cat" recordCount="1"/>'
            '<ScoreDistribution value="dog" recordCount="9"/></Node>'
            "</Node>"
            '<Node id="rr" score="bird"><SimplePredicate field="a" '
            'operator="greaterOrEqual" value="0"/>'
            '<ScoreDistribution value="bird" recordCount="10"/></Node>'
            "</Node></TreeModel></PMML>"
        )
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        rng = np.random.default_rng(6)
        recs = _random_records(("a", "b"), 64, rng, scale=1.5)
        _assert_match(cm, doc, recs)
        preds = cm.score_records(recs)
        for rec, p in zip(recs, preds):
            o = evaluate(doc, rec)
            for lbl, pr in o.probabilities.items():
                assert p.target.probabilities[lbl] == pytest.approx(
                    pr, rel=RTOL, abs=1e-6
                )

    def test_majority_vote_forest(self):
        trees = "".join(
            f'<Segment id="{i}"><True/>'
            '<TreeModel functionName="classification">'
            '<MiningSchema><MiningField name="a"/></MiningSchema>'
            f'<Node id="r"><True/>'
            f'<Node id="l" score="{l1}"><SimplePredicate field="a" '
            f'operator="lessThan" value="{thr}"/></Node>'
            f'<Node id="rr" score="{l2}"><SimplePredicate field="a" '
            f'operator="greaterOrEqual" value="{thr}"/></Node>'
            "</Node></TreeModel></Segment>"
            for i, (thr, l1, l2) in enumerate(
                [(0.0, "x", "y"), (0.5, "x", "y"), (-0.5, "y", "x")]
            )
        )
        xml = (
            '<PMML version="4.3"><DataDictionary>'
            '<DataField name="a" optype="continuous" dataType="double"/>'
            "</DataDictionary>"
            '<MiningModel functionName="classification">'
            '<MiningSchema><MiningField name="a"/></MiningSchema>'
            f'<Segmentation multipleModelMethod="majorityVote">{trees}'
            "</Segmentation></MiningModel></PMML>"
        )
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        rng = np.random.default_rng(7)
        recs = _random_records(("a",), 64, rng, scale=1.0)
        _assert_match(cm, doc, recs)
        # a missing split field makes trees abstain (strategy 'none'), but
        # the remaining votes still elect a winner — lane must stay valid
        _assert_match(cm, doc, [{"a": None}])

    def test_classification_average_uses_numeric_path(self):
        # sum/average over classification trees aggregates winning
        # probabilities (no label) — must match the oracle via the generic
        # per-segment path, not the vote-based fused path
        trees = "".join(
            f'<Segment id="{i}"><True/>'
            '<TreeModel functionName="classification">'
            '<MiningSchema><MiningField name="a"/></MiningSchema>'
            f'<Node id="r"><True/>'
            f'<Node id="l" score="x"><SimplePredicate field="a" '
            f'operator="lessThan" value="{thr}"/>'
            '<ScoreDistribution value="x" recordCount="7"/>'
            '<ScoreDistribution value="y" recordCount="3"/></Node>'
            f'<Node id="rr" score="y"><SimplePredicate field="a" '
            f'operator="greaterOrEqual" value="{thr}"/>'
            '<ScoreDistribution value="x" recordCount="2"/>'
            '<ScoreDistribution value="y" recordCount="8"/></Node>'
            "</Node></TreeModel></Segment>"
            for i, thr in enumerate([0.0, 0.5])
        )
        xml = (
            '<PMML version="4.3"><DataDictionary>'
            '<DataField name="a" optype="continuous" dataType="double"/>'
            "</DataDictionary>"
            '<MiningModel functionName="classification">'
            '<MiningSchema><MiningField name="a"/></MiningSchema>'
            f'<Segmentation multipleModelMethod="average">{trees}'
            "</Segmentation></MiningModel></PMML>"
        )
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        _assert_match(
            cm, doc, [{"a": -1.0}, {"a": 0.2}, {"a": 1.0}], check_label=False
        )

    def test_non_binary_tree_takes_general_backend(self):
        # non-binary nodes route to the general first-match scan backend
        # (gtrees.py) instead of erroring — diffed against the oracle
        xml = (
            '<PMML version="4.3"><DataDictionary>'
            '<DataField name="a" optype="continuous" dataType="double"/>'
            "</DataDictionary>"
            '<TreeModel functionName="regression">'
            '<MiningSchema><MiningField name="a"/></MiningSchema>'
            '<Node id="r"><True/>'
            '<Node id="1" score="1"><SimplePredicate field="a" '
            'operator="lessThan" value="0"/></Node>'
            '<Node id="2" score="2"><SimplePredicate field="a" '
            'operator="lessThan" value="1"/></Node>'
            '<Node id="3" score="3"><True/></Node>'
            "</Node></TreeModel></PMML>"
        )
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        for a, want in ((-0.5, 1.0), (0.5, 2.0), (1.5, 3.0)):
            [pred] = cm.score_records([{"a": a}])
            assert pred.score.value == want
            assert evaluate(doc, {"a": a}).value == want


class TestNeuralGolden:
    def test_mlp(self, assets_dir):
        doc = parse_pmml_file(str(assets_dir / "mlp_small.pmml"))
        cm = compile_pmml(doc)
        rng = np.random.default_rng(8)
        recs = _random_records(doc.active_fields, 64, rng, scale=1.0)
        _assert_match(cm, doc, recs)

    def test_mlp_missing_input_is_empty(self, assets_dir):
        doc = parse_pmml_file(str(assets_dir / "mlp_small.pmml"))
        cm = compile_pmml(doc)
        recs = [{f: (None if f == "x3" else 0.5) for f in doc.active_fields}]
        _assert_match(cm, doc, recs)

    def test_regression_nn_with_denorm(self):
        xml = (
            '<PMML version="4.3"><DataDictionary>'
            '<DataField name="a" optype="continuous" dataType="double"/>'
            "</DataDictionary>"
            '<NeuralNetwork functionName="regression" '
            'activationFunction="tanh">'
            '<MiningSchema><MiningField name="a"/></MiningSchema>'
            '<NeuralInputs><NeuralInput id="i0">'
            '<DerivedField optype="continuous" dataType="double">'
            '<NormContinuous field="a">'
            '<LinearNorm orig="0" norm="0"/><LinearNorm orig="10" norm="1"/>'
            "</NormContinuous></DerivedField></NeuralInput></NeuralInputs>"
            '<NeuralLayer><Neuron id="h" bias="0.1">'
            '<Con from="i0" weight="1.3"/></Neuron></NeuralLayer>'
            '<NeuralLayer activationFunction="identity">'
            '<Neuron id="o" bias="0.0"><Con from="h" weight="2.0"/></Neuron>'
            "</NeuralLayer>"
            '<NeuralOutputs><NeuralOutput outputNeuron="o">'
            '<DerivedField optype="continuous" dataType="double">'
            '<NormContinuous field="t">'
            '<LinearNorm orig="100" norm="0"/><LinearNorm orig="200" norm="1"/>'
            "</NormContinuous></DerivedField></NeuralOutput></NeuralOutputs>"
            "</NeuralNetwork></PMML>"
        )
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        recs = [{"a": v} for v in (-3.0, 0.0, 5.0, 12.0)]
        _assert_match(cm, doc, recs)


class TestClusteringGolden:
    def test_kmeans(self, assets_dir):
        doc = parse_pmml_file(str(assets_dir / "kmeans.pmml"))
        cm = compile_pmml(doc)
        rng = np.random.default_rng(9)
        recs = _random_records(doc.active_fields, 128, rng, scale=3.0)
        _assert_match(cm, doc, recs)
        # winning distance matches the oracle's
        from flink_jpmml_tpu.compile import prepare

        preds_out = cm.predict(*prepare.from_records(cm.field_space, recs))
        D = np.asarray(preds_out.probs)
        for i, rec in enumerate(recs):
            o = evaluate(doc, rec)
            assert D[i].min() == pytest.approx(
                o.probabilities[o.label], rel=1e-4
            )

    def test_kmeans_missing(self, assets_dir):
        doc = parse_pmml_file(str(assets_dir / "kmeans.pmml"))
        cm = compile_pmml(doc)
        rng = np.random.default_rng(10)
        recs = _random_records(doc.active_fields, 32, rng, missing_rate=0.2)
        _assert_match(cm, doc, recs)


class TestChainGolden:
    def test_stacked(self, assets_dir):
        doc = parse_pmml_file(str(assets_dir / "stacked.pmml"))
        cm = compile_pmml(doc)
        rng = np.random.default_rng(11)
        recs = _random_records(doc.active_fields, 128, rng)
        _assert_match(cm, doc, recs)

    def test_stacked_with_missing(self, assets_dir):
        doc = parse_pmml_file(str(assets_dir / "stacked.pmml"))
        cm = compile_pmml(doc)
        rng = np.random.default_rng(12)
        recs = _random_records(doc.active_fields, 64, rng, missing_rate=0.2)
        _assert_match(cm, doc, recs)


class TestInputContract:
    def test_arity_mismatch_raises(self, assets_dir):
        doc = parse_pmml_file(str(assets_dir / "iris_lr.pmml"))
        cm = compile_pmml(doc)
        with pytest.raises(InputValidationException, match="arity"):
            cm.score_dense(np.zeros((4, 3), np.float32))

    def test_replace_nan(self, assets_dir):
        doc = parse_pmml_file(str(assets_dir / "iris_lr.pmml"))
        cm = compile_pmml(doc)
        X = np.full((2, 4), np.nan, np.float32)
        # without replacement: missing numeric → empty
        assert all(p.is_empty for p in cm.score_dense(X))
        # with replaceNan: scores as if all-zero input
        preds = cm.score_dense(X, replace_nan=0.0)
        assert not any(p.is_empty for p in preds)
        o = evaluate(doc, {f: 0.0 for f in doc.active_fields})
        assert preds[0].score.value == pytest.approx(o.value, rel=RTOL)

    def test_padded_batch(self, assets_dir):
        doc = parse_pmml_file(str(assets_dir / "iris_lr.pmml"))
        cm = compile_pmml(doc, batch_size=32)
        X = np.ones((5, 4), np.float32)
        preds = cm.score_dense(X)
        assert len(preds) == 5
        assert not any(p.is_empty for p in preds)


class TestLinkFunctions:
    def test_regression_normalizations_match_oracle(self):
        for nm in ("cauchit", "cloglog", "loglog", "probit", "exp", "logit"):
            xml = f"""<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3">
              <Header/>
              <DataDictionary numberOfFields="2">
                <DataField name="a" optype="continuous" dataType="double"/>
                <DataField name="y" optype="continuous" dataType="double"/>
              </DataDictionary>
              <RegressionModel functionName="regression" normalizationMethod="{nm}">
                <MiningSchema>
                  <MiningField name="y" usageType="target"/>
                  <MiningField name="a"/>
                </MiningSchema>
                <RegressionTable intercept="0.1">
                  <NumericPredictor name="a" coefficient="0.8"/>
                </RegressionTable>
              </RegressionModel></PMML>"""
            doc = parse_pmml(xml)
            cm = compile_pmml(doc)
            for a in (-2.0, -0.3, 0.0, 0.7, 2.5):
                [pred] = cm.score_records([{"a": a}])
                exp = evaluate(doc, {"a": a})
                assert pred.score.value == pytest.approx(
                    exp.value, rel=1e-5, abs=1e-6
                ), (nm, a)


class TestNeuralActivations:
    def test_spec_defined_activation_values(self):
        """Golden values straight from the PMML 4.x spec formulas (not
        oracle parity — the oracle shares the table, so parity alone could
        not catch a spec divergence like plain atan vs 2*atan(z)/pi)."""
        import math

        spec = {
            "arctan": lambda z: 2.0 * math.atan(z) / math.pi,
            "Elliott": lambda z: z / (1.0 + abs(z)),
            "logistic": lambda z: 1.0 / (1.0 + math.exp(-z)),
            "tanh": math.tanh,
            "rectifier": lambda z: max(0.0, z),
        }
        from flink_jpmml_tpu.compile.neural import _ACTIVATIONS as C_ACT
        from flink_jpmml_tpu.pmml.interp import _ACTIVATIONS as O_ACT

        for name, fn in spec.items():
            for z in (-3.0, -0.7, 0.0, 0.4, 2.2):
                exp = fn(z)
                # abs=5e-5: the TPU VPU's transcendental approximations (tanh
                # at the tails especially) sit a few e-5 off the exact
                # values; CPU matches to ~1e-7
                assert float(C_ACT[name](z)) == pytest.approx(
                    exp, abs=5e-5
                ), name
                assert float(O_ACT[name](z)) == pytest.approx(exp, abs=1e-9), name

    def test_extended_activations_match_oracle(self):
        for act in ("arctan", "cosine", "sine", "square", "Gauss",
                    "reciprocal", "exponential", "Elliott", "elliott",
                    "tanh"):
            xml = f"""<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3">
              <Header/>
              <DataDictionary numberOfFields="2">
                <DataField name="a" optype="continuous" dataType="double"/>
                <DataField name="y" optype="continuous" dataType="double"/>
              </DataDictionary>
              <NeuralNetwork functionName="regression" activationFunction="{act}">
                <MiningSchema>
                  <MiningField name="y" usageType="target"/>
                  <MiningField name="a"/>
                </MiningSchema>
                <NeuralInputs>
                  <NeuralInput id="in0">
                    <DerivedField optype="continuous" dataType="double">
                      <FieldRef field="a"/>
                    </DerivedField>
                  </NeuralInput>
                </NeuralInputs>
                <NeuralLayer>
                  <Neuron id="h0" bias="0.2">
                    <Con from="in0" weight="1.3"/>
                  </Neuron>
                </NeuralLayer>
                <NeuralLayer activationFunction="identity">
                  <Neuron id="out0" bias="-0.1">
                    <Con from="h0" weight="0.9"/>
                  </Neuron>
                </NeuralLayer>
                <NeuralOutputs>
                  <NeuralOutput outputNeuron="out0">
                    <DerivedField optype="continuous" dataType="double">
                      <FieldRef field="y"/>
                    </DerivedField>
                  </NeuralOutput>
                </NeuralOutputs>
              </NeuralNetwork></PMML>"""
            doc = parse_pmml(xml)
            cm = compile_pmml(doc)
            for a in (-1.5, -0.2, 0.4, 1.1):
                [pred] = cm.score_records([{"a": a}])
                exp = evaluate(doc, {"a": a})
                # 5e-5: TPU transcendentals (exp/erf chains) carry a couple
                # extra ulps vs the CPU backend
                assert abs(pred.score.value - exp.value) < 5e-5, (act, a)


MVW_KMEANS = """<PMML version="4.3"><DataDictionary>
  <DataField name="a" optype="continuous" dataType="double"/>
  <DataField name="b" optype="continuous" dataType="double"/>
  <DataField name="c" optype="continuous" dataType="double"/>
  </DataDictionary>
  <ClusteringModel functionName="clustering" modelClass="centerBased"
      numberOfClusters="2">
  <MiningSchema><MiningField name="a"/><MiningField name="b"/>
    <MiningField name="c"/></MiningSchema>
  <ComparisonMeasure kind="distance"><squaredEuclidean/>
  </ComparisonMeasure>
  <ClusteringField field="a"/><ClusteringField field="b"/>
  <ClusteringField field="c"/>
  <MissingValueWeights><Array n="3" type="real">1 2 1</Array>
  </MissingValueWeights>
  <Cluster id="c1"><Array n="3" type="real">0 0 0</Array></Cluster>
  <Cluster id="c2"><Array n="3" type="real">4 4 4</Array></Cluster>
  </ClusteringModel></PMML>"""


class TestMissingValueWeights:
    def test_adjusted_distance_parity(self):
        from flink_jpmml_tpu.pmml.interp import evaluate

        doc = parse_pmml(MVW_KMEANS)
        cm = compile_pmml(doc)
        # b missing: terms over (a, c); adjust = (1+2+1)/(1+1) = 2
        rec = {"a": 1.0, "b": None, "c": 2.0}
        hand = {
            "c1": 2.0 * (1.0 + 4.0),
            "c2": 2.0 * (9.0 + 4.0),
        }
        o = evaluate(doc, rec)
        p = cm.score_records([rec])[0]
        assert o.probabilities["c1"] == pytest.approx(hand["c1"])
        assert o.probabilities["c2"] == pytest.approx(hand["c2"])
        assert o.label == "c1" == p.target.label
        assert p.target.probabilities["c2"] == pytest.approx(
            hand["c2"], rel=1e-6
        )
        # fully observed: no adjustment
        rec = {"a": 3.0, "b": 3.0, "c": 3.0}
        o = evaluate(doc, rec)
        p = cm.score_records([rec])[0]
        assert o.label == "c2" == p.target.label
        assert o.probabilities["c1"] == pytest.approx(27.0)
        # all missing: still an empty lane
        rec = {"a": None, "b": None, "c": None}
        assert evaluate(doc, rec).value is None
        assert cm.score_records([rec])[0].is_empty

    def test_without_weights_stays_strict(self):
        from flink_jpmml_tpu.pmml.interp import evaluate

        xml = MVW_KMEANS.replace(
            "<MissingValueWeights><Array n=\"3\" type=\"real\">1 2 1"
            "</Array>\n  </MissingValueWeights>", ""
        )
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        rec = {"a": 1.0, "b": None, "c": 2.0}
        assert evaluate(doc, rec).value is None
        assert cm.score_records([rec])[0].is_empty

    def test_bad_weights_rejected(self):
        from flink_jpmml_tpu.utils.exceptions import ModelLoadingException

        with pytest.raises(ModelLoadingException, match="length"):
            parse_pmml(MVW_KMEANS.replace(
                '<Array n="3" type="real">1 2 1</Array>',
                '<Array n="2" type="real">1 2</Array>',
            ))

    def test_zero_weight_evidence_empty_both_paths(self):
        from flink_jpmml_tpu.pmml.interp import evaluate

        # field b carries ALL the weight; with b missing the remaining
        # evidence is weightless -> empty on both engines
        xml = MVW_KMEANS.replace(
            '<Array n="3" type="real">1 2 1</Array>',
            '<Array n="3" type="real">0 2 0</Array>',
        )
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        rec = {"a": 1.0, "b": None, "c": 2.0}
        assert evaluate(doc, rec).value is None
        assert cm.score_records([rec])[0].is_empty
        # with b present everything scores
        assert not cm.score_records([{"a": 1.0, "b": 0.0, "c": 2.0}])[0].is_empty

    def test_negative_or_zero_sum_weights_rejected(self):
        from flink_jpmml_tpu.utils.exceptions import ModelLoadingException

        for arr in ("-1 2 1", "0 0 0"):
            with pytest.raises(ModelLoadingException, match="negative|positive"):
                parse_pmml(MVW_KMEANS.replace(
                    '<Array n="3" type="real">1 2 1</Array>',
                    f'<Array n="3" type="real">{arr}</Array>',
                ))


class TestEntityOutputs:
    def test_cluster_entity_id_and_affinity(self):
        from flink_jpmml_tpu.pmml.interp import evaluate

        xml = MVW_KMEANS.replace(
            "</MiningSchema>",
            "</MiningSchema>"
            '<Output><OutputField name="cluster" feature="entityId"/>'
            '<OutputField name="dist" feature="affinity"/></Output>',
        )
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        rec = {"a": 1.0, "b": 0.5, "c": 0.5}
        o = evaluate(doc, rec)
        p = cm.score_records([rec])[0]
        assert o.outputs["cluster"] == "c1" == p.outputs["cluster"]
        hand = 1.0 + 0.25 + 0.25  # squaredEuclidean to (0,0,0)
        assert o.outputs["dist"] == pytest.approx(hand)
        assert p.outputs["dist"] == pytest.approx(hand, rel=1e-6)

    def test_affinity_value_attribute_picks_entity(self):
        from flink_jpmml_tpu.pmml.interp import evaluate

        xml = MVW_KMEANS.replace(
            "</MiningSchema>",
            "</MiningSchema>"
            '<Output><OutputField name="d2" feature="affinity" value="c2"/>'
            "</Output>",
        )
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        rec = {"a": 1.0, "b": 0.5, "c": 0.5}  # winner c1; ask for c2
        hand = (1 - 4) ** 2 + (0.5 - 4) ** 2 + (0.5 - 4) ** 2
        assert evaluate(doc, rec).outputs["d2"] == pytest.approx(hand)
        assert cm.score_records([rec])[0].outputs["d2"] == pytest.approx(
            hand, rel=1e-6
        )
