"""Oracle (reference interpreter) tests against hand-computed expectations.

The interpreter is the semantic anchor for all golden tests of the JAX
lowering, so its own behavior is pinned here on tiny hand-written PMML
documents where the expected output is computed by hand (SURVEY.md §5:
"golden outputs (JPMML-computed or hand-derived)").
"""

import math

import pytest

from flink_jpmml_tpu.pmml import parse_pmml, parse_pmml_file
from flink_jpmml_tpu.pmml.interp import evaluate


def _wrap(model_xml: str, fields=("a", "b")) -> str:
    dd = "".join(
        f'<DataField name="{f}" optype="continuous" dataType="double"/>'
        for f in fields
    )
    return (
        f'<PMML version="4.3"><DataDictionary>{dd}</DataDictionary>'
        f"{model_xml}</PMML>"
    )


MS = '<MiningSchema><MiningField name="a"/><MiningField name="b"/></MiningSchema>'


class TestRegression:
    def test_linear(self):
        doc = parse_pmml(
            _wrap(
                '<RegressionModel functionName="regression">'
                + MS
                + '<RegressionTable intercept="1.5">'
                '<NumericPredictor name="a" coefficient="2.0"/>'
                '<NumericPredictor name="b" coefficient="-1.0" exponent="2"/>'
                "</RegressionTable></RegressionModel>"
            )
        )
        r = evaluate(doc, {"a": 3.0, "b": 2.0})
        assert r.value == pytest.approx(1.5 + 6.0 - 4.0)

    def test_missing_numeric_gives_empty(self):
        doc = parse_pmml(
            _wrap(
                '<RegressionModel functionName="regression">'
                + MS
                + '<RegressionTable intercept="0">'
                '<NumericPredictor name="a" coefficient="1"/>'
                "</RegressionTable></RegressionModel>"
            )
        )
        assert evaluate(doc, {"a": None, "b": 1.0}).is_missing
        assert evaluate(doc, {"a": float("nan"), "b": 1.0}).is_missing

    def test_missing_value_replacement(self):
        doc = parse_pmml(
            _wrap(
                '<RegressionModel functionName="regression">'
                "<MiningSchema>"
                '<MiningField name="a" missingValueReplacement="10"/>'
                '<MiningField name="b"/>'
                "</MiningSchema>"
                '<RegressionTable intercept="0">'
                '<NumericPredictor name="a" coefficient="1"/>'
                "</RegressionTable></RegressionModel>"
            )
        )
        assert evaluate(doc, {"a": None, "b": 0.0}).value == pytest.approx(10.0)

    def test_logit_regression(self):
        doc = parse_pmml(
            _wrap(
                '<RegressionModel functionName="regression" '
                'normalizationMethod="logit">'
                + MS
                + '<RegressionTable intercept="0.0">'
                '<NumericPredictor name="a" coefficient="1.0"/>'
                "</RegressionTable></RegressionModel>"
            )
        )
        r = evaluate(doc, {"a": 0.0, "b": 0.0})
        assert r.value == pytest.approx(0.5)

    def test_softmax_classification(self):
        doc = parse_pmml(
            _wrap(
                '<RegressionModel functionName="classification" '
                'normalizationMethod="softmax">'
                + MS
                + '<RegressionTable intercept="1.0" targetCategory="yes"/>'
                '<RegressionTable intercept="0.0" targetCategory="no"/>'
                "</RegressionModel>"
            )
        )
        r = evaluate(doc, {"a": 0.0, "b": 0.0})
        p_yes = math.exp(1.0) / (math.exp(1.0) + 1.0)
        assert r.label == "yes"
        assert r.probabilities["yes"] == pytest.approx(p_yes)
        assert r.probabilities["no"] == pytest.approx(1 - p_yes)

    def test_categorical_predictor(self):
        doc = parse_pmml(
            '<PMML version="4.3"><DataDictionary>'
            '<DataField name="color" optype="categorical" dataType="string">'
            '<Value value="red"/><Value value="blue"/></DataField>'
            "</DataDictionary>"
            '<RegressionModel functionName="regression">'
            '<MiningSchema><MiningField name="color"/></MiningSchema>'
            '<RegressionTable intercept="1.0">'
            '<CategoricalPredictor name="color" value="red" coefficient="5.0"/>'
            "</RegressionTable></RegressionModel></PMML>"
        )
        assert evaluate(doc, {"color": "red"}).value == pytest.approx(6.0)
        assert evaluate(doc, {"color": "blue"}).value == pytest.approx(1.0)
        # missing categorical contributes 0, does not kill the table
        assert evaluate(doc, {"color": None}).value == pytest.approx(1.0)


TREE = (
    '<TreeModel functionName="regression" missingValueStrategy="defaultChild">'
    + MS
    + '<Node id="root" defaultChild="L"><True/>'
    '<Node id="L" score="10"><SimplePredicate field="a" operator="lessThan" '
    'value="2.0"/></Node>'
    '<Node id="R"><SimplePredicate field="a" operator="greaterOrEqual" '
    'value="2.0"/>'
    '<Node id="RL" score="20"><SimplePredicate field="b" operator="lessThan" '
    'value="0.0"/></Node>'
    '<Node id="RR" score="30"><SimplePredicate field="b" '
    'operator="greaterOrEqual" value="0.0"/></Node>'
    "</Node></Node></TreeModel>"
)


class TestTree:
    def test_paths(self):
        doc = parse_pmml(_wrap(TREE))
        assert evaluate(doc, {"a": 1.0, "b": 0.0}).value == 10.0
        assert evaluate(doc, {"a": 5.0, "b": -1.0}).value == 20.0
        assert evaluate(doc, {"a": 5.0, "b": 1.0}).value == 30.0

    def test_missing_goes_default_child(self):
        doc = parse_pmml(_wrap(TREE))
        # a missing at root split -> defaultChild L -> score 10
        assert evaluate(doc, {"a": None, "b": 1.0}).value == 10.0
        # b missing at inner node: R's defaultChild is unset -> empty
        # (inner node R has no defaultChild attribute)
        assert evaluate(doc, {"a": 5.0, "b": None}).is_missing

    def test_null_prediction_strategy(self):
        doc = parse_pmml(_wrap(TREE.replace("defaultChild", "nullPrediction", 1)))
        assert evaluate(doc, {"a": None, "b": 1.0}).is_missing

    def test_last_prediction_strategy(self):
        xml = TREE.replace(
            'missingValueStrategy="defaultChild"',
            'missingValueStrategy="lastPrediction"',
        ).replace('<Node id="root" defaultChild="L">', '<Node id="root" score="7">')
        doc = parse_pmml(_wrap(xml))
        assert evaluate(doc, {"a": None, "b": 1.0}).value == 7.0

    def test_classification_distribution(self):
        xml = (
            '<TreeModel functionName="classification">'
            + MS
            + '<Node id="r"><True/>'
            '<Node id="l" score="cat"><SimplePredicate field="a" '
            'operator="lessThan" value="0"/>'
            '<ScoreDistribution value="cat" recordCount="30"/>'
            '<ScoreDistribution value="dog" recordCount="10"/>'
            "</Node>"
            '<Node id="rr" score="dog"><True/>'
            '<ScoreDistribution value="cat" recordCount="5"/>'
            '<ScoreDistribution value="dog" recordCount="15"/>'
            "</Node></Node></TreeModel>"
        )
        doc = parse_pmml(_wrap(xml))
        r = evaluate(doc, {"a": -1.0, "b": 0.0})
        assert r.label == "cat"
        assert r.probabilities == {"cat": 0.75, "dog": 0.25}
        r2 = evaluate(doc, {"a": 1.0, "b": 0.0})
        assert r2.label == "dog"
        assert r2.probabilities["dog"] == pytest.approx(0.75)


class TestMining:
    def test_sum_with_rescale(self, assets_dir):
        doc = parse_pmml_file(str(assets_dir / "gbm_small.pmml"))
        rec = {f"f{i}": 0.25 * i - 1.0 for i in range(8)}
        r = evaluate(doc, rec)
        assert r.value is not None
        # sum of 16 trees + rescaleConstant 0.5: recompute by summing each
        # tree independently
        total = 0.5
        for seg in doc.model.segmentation.segments:
            from flink_jpmml_tpu.pmml.interp import _eval_model

            total += _eval_model(seg.model, rec).value
        assert r.value == pytest.approx(total)

    def test_majority_vote(self):
        votes = (
            '<MiningModel functionName="classification">'
            + MS
            + '<Segmentation multipleModelMethod="majorityVote">'
            + "".join(
                f'<Segment id="{i}"><True/>'
                '<TreeModel functionName="classification">'
                + MS
                + f'<Node id="r" score="{lbl}"><True/></Node>'
                "</TreeModel></Segment>"
                for i, lbl in enumerate(["x", "x", "y"])
            )
            + "</Segmentation></MiningModel>"
        )
        doc = parse_pmml(_wrap(votes))
        r = evaluate(doc, {"a": 0.0, "b": 0.0})
        assert r.label == "x"
        assert r.probabilities["x"] == pytest.approx(2 / 3)

    def test_select_first(self):
        xml = (
            '<MiningModel functionName="regression">'
            + MS
            + '<Segmentation multipleModelMethod="selectFirst">'
            '<Segment id="0"><SimplePredicate field="a" operator="lessThan" '
            'value="0"/>'
            '<TreeModel functionName="regression">' + MS +
            '<Node id="r" score="1"><True/></Node></TreeModel></Segment>'
            '<Segment id="1"><True/>'
            '<TreeModel functionName="regression">' + MS +
            '<Node id="r" score="2"><True/></Node></TreeModel></Segment>'
            "</Segmentation></MiningModel>"
        )
        doc = parse_pmml(_wrap(xml))
        assert evaluate(doc, {"a": -1.0, "b": 0.0}).value == 1.0
        assert evaluate(doc, {"a": 1.0, "b": 0.0}).value == 2.0

    def test_model_chain(self, assets_dir):
        doc = parse_pmml_file(str(assets_dir / "stacked.pmml"))
        rec = {f"f{i}": 0.1 * i for i in range(12)}
        r = evaluate(doc, rec)
        # manually: inner gbm sum -> logit(1.7*s - 0.3)
        from flink_jpmml_tpu.pmml.interp import _eval_model

        inner = doc.model.segmentation.segments[0].model
        s = _eval_model(inner, rec).value
        expected = 1.0 / (1.0 + math.exp(-(1.7 * s - 0.3)))
        assert r.value == pytest.approx(expected)


class TestClustering:
    def test_nearest_center(self, assets_dir):
        doc = parse_pmml_file(str(assets_dir / "kmeans.pmml"))
        c0 = doc.model.clusters[2].center
        r = evaluate(doc, {f"f{i}": v for i, v in enumerate(c0)})
        assert r.value == 2.0
        assert r.label == "3"
        assert r.probabilities[r.label] == pytest.approx(0.0)

    def test_missing_field_empty(self, assets_dir):
        doc = parse_pmml_file(str(assets_dir / "kmeans.pmml"))
        assert evaluate(doc, {"f0": None, "f1": 0, "f2": 0, "f3": 0}).is_missing


class TestNeuralNetwork:
    def test_tiny_manual(self):
        # 1 input, 1 hidden logistic neuron, identity output
        xml = (
            '<NeuralNetwork functionName="regression" '
            'activationFunction="logistic">'
            '<MiningSchema><MiningField name="a"/></MiningSchema>'
            "<NeuralInputs>"
            '<NeuralInput id="i0"><DerivedField optype="continuous" '
            'dataType="double"><FieldRef field="a"/></DerivedField>'
            "</NeuralInput></NeuralInputs>"
            '<NeuralLayer><Neuron id="h0" bias="0.5">'
            '<Con from="i0" weight="2.0"/></Neuron></NeuralLayer>'
            '<NeuralLayer activationFunction="identity">'
            '<Neuron id="o0" bias="1.0"><Con from="h0" weight="3.0"/>'
            "</Neuron></NeuralLayer>"
            "<NeuralOutputs>"
            '<NeuralOutput outputNeuron="o0"><DerivedField '
            'optype="continuous" dataType="double">'
            '<FieldRef field="target"/></DerivedField></NeuralOutput>'
            "</NeuralOutputs></NeuralNetwork>"
        )
        doc = parse_pmml(_wrap(xml, fields=("a",)))
        h = 1.0 / (1.0 + math.exp(-(0.5 + 2.0 * 1.0)))
        assert evaluate(doc, {"a": 1.0}).value == pytest.approx(1.0 + 3.0 * h)

    def test_mlp_classification_probs_sum_to_one(self, assets_dir):
        doc = parse_pmml_file(str(assets_dir / "mlp_small.pmml"))
        r = evaluate(doc, {f"x{i}": 0.1 * i for i in range(8)})
        assert r.label in {"0", "1", "2"}
        assert sum(r.probabilities.values()) == pytest.approx(1.0)
