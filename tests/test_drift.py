"""Data-drift plane (obs/drift.py + utils/metrics.QuantileSketch).

Pins the tentpole contracts of the fourth sensor plane:

- the sketch's merge EXACTNESS discipline (associativity under
  adversarial orderings, state-roundtrip fidelity, fleet merge ==
  per-worker state merge) and its quantile error bound;
- baseline save/load/corruption (silent re-snapshot, like the
  autotune cache);
- DriftMonitor alarm/clear hysteresis under a fake clock;
- ZERO drift-plane records when FJT_DRIFT_SAMPLE is unset;
- the dispatch/sink integrations and the rollout prediction-PSI
  guardrail (hold promotion / roll back).
"""

import json
import math
import os
import time

import numpy as np
import pytest

from flink_jpmml_tpu.obs import drift
from flink_jpmml_tpu.obs import recorder as flight
from flink_jpmml_tpu.utils.metrics import (
    MetricsRegistry,
    QuantileSketch,
    Reservoir,
    merge_structs,
)


class _FakeWire:
    def __init__(self, fields, cuts):
        self.fields = tuple(fields)
        self.cuts = tuple(np.asarray(c, np.float32) for c in cuts)


class _FakeScorer:
    def __init__(self, fields=("a", "b", "c"), cuts=None, model_hash="m01"):
        if cuts is None:
            cuts = [np.array([-1.0, 0.0, 1.0])] * len(fields)
        self.wire = _FakeWire(fields, cuts)
        self.model_hash = model_hash


def _plane(reg, store=None, **kw):
    kw.setdefault("interval_s", 0.0)
    kw.setdefault("budget_frac", 0)  # drills/tests want determinism
    return drift.install(reg, store=store, **kw)


# ---------------------------------------------------------------------------
# QuantileSketch
# ---------------------------------------------------------------------------


class TestQuantileSketch:
    def _adversarial_orderings(self, vals):
        asc = np.sort(vals)
        return [
            vals,
            asc,
            asc[::-1],
            # extremes-first interleave: worst case for compaction-
            # scheduled sketches, a no-op for value-partition ones
            np.concatenate([asc[::2], asc[1::2][::-1]]),
        ]

    def test_merge_associativity_exact_under_orderings(self):
        rng = np.random.default_rng(0)
        base = np.concatenate([
            rng.normal(0, 1, 3000),
            rng.normal(50, 5, 2000),
            -rng.lognormal(0, 2, 1000),
            np.zeros(100),
        ])
        for order in self._adversarial_orderings(base):
            thirds = np.array_split(order, 3)
            parts = []
            for t in thirds:
                s = QuantileSketch()
                s.observe_many(t)
                parts.append(s.state())

            def sk(state):
                return QuantileSketch.from_state(state)

            ab_c = sk(parts[0]).merge(sk(parts[1])).merge(sk(parts[2]))
            a_bc = sk(parts[0]).merge(sk(parts[1]).merge(sk(parts[2])))
            c_ab = sk(parts[2]).merge(sk(parts[0]).merge(sk(parts[1])))
            s1, s2, s3 = ab_c.state(), a_bc.state(), c_ab.state()
            for key in ("pos", "neg", "zero", "n"):
                assert s1[key] == s2[key] == s3[key], key
            for q in (0.001, 0.01, 0.1, 0.5, 0.9, 0.99, 0.999):
                assert (
                    ab_c.quantile(q) == a_bc.quantile(q) == c_ab.quantile(q)
                ), q

    def test_order_independence(self):
        # bucket membership is a pure function of the value, so the
        # SAME multiset in any order yields the identical state — the
        # property that makes fleet merge exact
        rng = np.random.default_rng(1)
        vals = rng.normal(2.0, 3.0, 5000)
        states = []
        for order in self._adversarial_orderings(vals):
            s = QuantileSketch()
            s.observe_many(order)
            st = s.state()
            states.append((st["pos"], st["neg"], st["zero"], st["n"]))
        assert all(st == states[0] for st in states[1:])

    def test_quantile_error_bound(self):
        # the estimate is the nearest-rank observation's bucket upper
        # edge: true(q) <= est(q) <= true(q) * gamma for positive data
        rng = np.random.default_rng(2)
        vals = rng.lognormal(0.0, 2.0, 20000)
        s = QuantileSketch()
        s.observe_many(vals)
        gamma = 10.0 ** (1.0 / QuantileSketch.DEFAULT_BPD)
        srt = np.sort(vals)
        for q in (0.01, 0.1, 0.5, 0.9, 0.99, 0.999):
            rank = min(max(math.ceil(q * len(srt)) - 1, 0), len(srt) - 1)
            true = srt[rank]
            est = s.quantile(q)
            assert true * (1 - 1e-9) <= est <= true * gamma * (1 + 1e-9), (
                q, true, est,
            )

    def test_state_roundtrip_exact(self):
        rng = np.random.default_rng(3)
        s = QuantileSketch()
        s.observe_many(rng.normal(0, 1, 1000))
        s.observe_many(-rng.lognormal(0, 1, 500))
        st = s.state()
        assert QuantileSketch.from_state(st).state() == st
        # ...and through a JSON wire hop (the heartbeat piggyback)
        st2 = json.loads(json.dumps(st))
        assert QuantileSketch.from_state(st2).state() == st

    def test_moments_welford_and_chan_merge(self):
        rng = np.random.default_rng(4)
        vals = rng.normal(3.0, 2.0, 10000)
        whole = QuantileSketch()
        whole.observe_many(vals)
        assert whole.mean() == pytest.approx(vals.mean(), rel=1e-9)
        assert whole.variance() == pytest.approx(vals.var(), rel=1e-9)
        parts = [QuantileSketch() for _ in range(4)]
        for p, chunk in zip(parts, np.array_split(vals, 4)):
            p.observe_many(chunk)
        merged = parts[0]
        for p in parts[1:]:
            merged.merge(p)
        assert merged.mean() == pytest.approx(vals.mean(), rel=1e-9)
        assert merged.variance() == pytest.approx(vals.var(), rel=1e-7)
        assert merged.count() == 10000
        assert merged.sum() == pytest.approx(vals.sum(), rel=1e-9)

    def test_nonfinite_dropped_and_zero_bucket(self):
        s = QuantileSketch()
        n = s.observe_many([1.0, np.nan, np.inf, -np.inf, 0.0, 1e-12])
        assert n == 3  # 1.0, 0.0, 1e-12 — the tiny ones in the zero bucket
        assert s.count() == 3
        assert s.state()["zero"] == 2

    def test_budget_compaction_preserves_counts(self):
        s = QuantileSketch(budget=16)
        s.observe_many(np.logspace(-6, 6, 500))
        st = s.state()
        assert len(st["pos"]) <= 16
        assert s.count() == 500
        # compaction folds toward LARGER magnitude / the zero bucket:
        # the top quantile is untouched
        assert s.quantile(0.99) >= np.logspace(-6, 6, 500)[494] * 0.9

    def test_merge_layout_mismatch_raises(self):
        with pytest.raises(ValueError):
            QuantileSketch(buckets_per_decade=8).merge(
                QuantileSketch(buckets_per_decade=4)
            )

    def test_registry_struct_and_fleet_merge_exact(self):
        rng = np.random.default_rng(5)
        regs = [MetricsRegistry(), MetricsRegistry()]
        chunks = [rng.normal(0, 1, 4000), rng.normal(1, 2, 4000)]
        for reg, chunk in zip(regs, chunks):
            reg.sketch("s").observe_many(chunk)
        fleet = merge_structs([r.struct_snapshot() for r in regs])
        direct = QuantileSketch.from_state(regs[0].sketch("s").state())
        direct.merge(QuantileSketch.from_state(regs[1].sketch("s").state()))
        merged = QuantileSketch.from_state(fleet["sketches"]["s"])
        assert merged.count() == direct.count() == 8000
        for q in (0.01, 0.1, 0.5, 0.9, 0.99):
            assert merged.quantile(q) == direct.quantile(q)
        # garbage sketch entries are skipped, never raised
        ok = merge_structs([
            {"sketches": {"s": "garbage"}},
            regs[0].struct_snapshot(),
        ])
        assert ok["sketches"]["s"]["n"] == 4000

    def test_struct_snapshot_has_no_sketch_key_when_empty(self):
        # pre-drift consumers (and equality-pinned fleet tests) must
        # see byte-identical struct shapes
        assert "sketches" not in MetricsRegistry().struct_snapshot()


class TestReservoirRoundtrip:
    def test_state_roundtrip(self):
        r = Reservoir(capacity=4)
        for v in (1.0, 2.0, 3.0, 4.0, 5.0):  # wraps: ring keeps recent
            r.observe(v)
        st = r.state()
        r2 = Reservoir.from_state(json.loads(json.dumps(st)))
        assert r2.state() == st
        assert r2.quantile(0.5) == r.quantile(0.5)
        # continued observation honours the restored ring cursor
        r.observe(6.0)
        r2.observe(6.0)
        assert r2.state() == r.state()

    def test_deliberately_not_mergeable(self):
        assert not hasattr(Reservoir(), "merge")

    def test_still_absent_from_fleet_wire(self):
        m = MetricsRegistry()
        m.reservoir("res").observe(1.0)
        snap = m.struct_snapshot()
        assert "res" not in str(snap)


# ---------------------------------------------------------------------------
# PSI / JS / windows
# ---------------------------------------------------------------------------


class TestDivergence:
    def _sk(self, vals):
        s = QuantileSketch()
        s.observe_many(vals)
        return s

    def test_identical_distributions_near_zero(self):
        rng = np.random.default_rng(6)
        a = self._sk(rng.normal(0, 1, 8000))
        b = self._sk(rng.normal(0, 1, 8000))
        assert drift.psi(a, b) < 0.02
        assert drift.js_divergence(a, b) < 0.01

    def test_shifted_distribution_scores_high(self):
        rng = np.random.default_rng(7)
        a = self._sk(rng.normal(0, 1, 8000))
        b = self._sk(rng.normal(3, 1, 8000))
        assert drift.psi(a, b) > 1.0
        js = drift.js_divergence(a, b)
        assert 0.1 < js <= math.log(2) + 1e-9

    def test_empty_side_is_none_and_smoothing_is_finite(self):
        rng = np.random.default_rng(8)
        a = self._sk(rng.normal(0, 1, 1000))
        assert drift.psi(a, QuantileSketch()) is None
        assert drift.psi(QuantileSketch(), a) is None
        # fully disjoint supports: smoothing keeps PSI finite
        b = self._sk(rng.normal(1000, 1, 1000))
        v = drift.psi(a, b)
        assert v is not None and math.isfinite(v) and v > 1.0

    def test_constant_feature_baseline(self):
        a = self._sk(np.full(500, 2.5))
        same = self._sk(np.full(400, 2.5))
        moved = self._sk(np.full(400, 9.0))
        assert drift.psi(a, same) < 0.02
        assert drift.psi(a, moved) > 0.5

    def test_sketch_window_delta_and_fallbacks(self):
        rng = np.random.default_rng(9)
        s = QuantileSketch()
        s.observe_many(rng.normal(0, 1, 1000))
        old = s.state()
        s.observe_many(rng.normal(5, 1, 500))
        new = s.state()
        w = drift.sketch_window(new, old)
        assert w.count() == 500
        assert w.quantile(0.5) > 2.0  # the window is the NEW data only
        # no delta → None
        assert drift.sketch_window(new, new) is None
        # counts going backwards (worker restart) → cumulative fallback
        w2 = drift.sketch_window(old, new)
        assert w2 is not None and w2.count() == 1000
        # no old frame → cumulative
        assert drift.sketch_window(new, None).count() == 1500
        assert drift.sketch_window(None, old) is None


# ---------------------------------------------------------------------------
# Baseline store
# ---------------------------------------------------------------------------


class TestBaselineStore:
    def _payload(self):
        s = QuantileSketch()
        s.observe_many(np.arange(100, dtype=np.float64))
        return {"features": {"a": s.state()}, "stats": {}, "predictions": None}

    def test_save_load_roundtrip(self, tmp_path):
        store = drift.BaselineStore(tmp_path)
        store.save("m01", self._payload())
        loaded = store.load("m01")
        assert loaded is not None
        assert loaded["model"] == "m01"
        assert "a" in loaded["features"]
        assert store.models() == ["m01"]

    def test_corruption_reads_as_absent(self, tmp_path):
        store = drift.BaselineStore(tmp_path)
        path = store.save("m01", self._payload())
        good = path.read_bytes()
        for garbage in (b"\x00garbage{{{", b"[]", b"{}"):
            path.write_bytes(garbage)
            assert store.load("m01") is None  # silent re-snapshot
        # a hand-edited payload fails the content hash too
        doc = json.loads(good)
        doc["features"]["a"]["n"] = 999999
        path.write_text(json.dumps(doc))
        assert store.load("m01") is None
        path.write_bytes(good)
        assert store.load("m01") is not None

    def test_missing_and_unreadable(self, tmp_path):
        store = drift.BaselineStore(tmp_path / "nonexistent")
        assert store.load("nope") is None
        assert store.models() == []

    def test_save_failure_raises(self, tmp_path):
        # UNLIKE load, save must fail loudly: a silently-dropped
        # snapshot leaves the drift plane dark while the operator
        # believes it is armed
        # a regular FILE where the directory chain must go: mkdir
        # raises NotADirectoryError on any uid (chmod-based denial
        # would be bypassed by a root test runner)
        blocker = tmp_path / "blocker"
        blocker.write_text("")
        store = drift.BaselineStore(blocker / "bl")
        with pytest.raises(OSError):
            store.save("m01", self._payload())

    def test_monitor_adopts_a_resnapshotted_baseline(self, tmp_path):
        # the accept-the-new-regime remedy: fjt-drift re-snapshot over
        # HTTP must reach a live monitor via the periodic store
        # re-probe, not only at process start
        rng = np.random.default_rng(30)
        store = drift.BaselineStore(tmp_path)
        t = [0.0]
        sk = QuantileSketch()  # the live cumulative stream (N(0,1))
        sk.observe_many(rng.normal(0, 1, 4000))

        def struct():
            return {
                "sketches": {
                    drift.feature_sketch_name("m", "x"): sk.state()
                },
                "counters": {},
            }

        mon = drift.DriftMonitor(
            struct_fn=struct, store=store,
            psi_alarm=0.25, psi_clear=0.1, min_n=50,
            window_s=0.5, dwell_s=0.0, interval_s=0.0,
            clock=lambda: t[0],
        )
        old_base = QuantileSketch()
        old_base.observe_many(rng.normal(5, 1, 4000))
        store.save("m", {"features": {"x": old_base.state()},
                         "stats": {}, "predictions": None})
        assert [tr["transition"] for tr in mon.tick()] == ["alarm"]
        # operator re-baselines onto the CURRENT (N(0,1)) regime; the
        # stream keeps flowing in-regime
        store.save("m", {"features": {"x": sk.state()},
                         "stats": {}, "predictions": None})
        sk.observe_many(rng.normal(0, 1, 4000))
        t[0] = drift._BASELINE_REPROBE_S + 1.0
        # the re-probe adopts the new file within the SAME tick, and
        # with dwell 0 the alarm clears right there
        assert [tr["transition"] for tr in mon.tick()] == ["clear"]
        # ...and a DELETED file never disarms a held baseline
        store.path("m").unlink()
        sk.observe_many(rng.normal(0, 1, 4000))
        t[0] = 2 * (drift._BASELINE_REPROBE_S + 1.0)
        mon.tick()
        assert mon.scores()[("m", "x")] is not None

    def test_snapshot_from_struct_shapes(self):
        reg = MetricsRegistry()
        plane = _plane(reg)
        plane.record_features(
            _FakeScorer(), np.zeros((64, 3), np.float32)
        )
        plane.record_predictions("m01", np.arange(32, dtype=np.float32))
        payloads = drift.snapshot_from_struct(reg.struct_snapshot())
        assert set(payloads) == {"m01"}
        p = payloads["m01"]
        assert set(p["features"]) == {"a", "b", "c"}
        assert p["predictions"] is not None
        assert p["stats"]["a"]["records"] == 64


# ---------------------------------------------------------------------------
# DriftPlane (the hot-path recorder)
# ---------------------------------------------------------------------------


class TestDriftPlane:
    def test_zero_records_when_env_unset(self, monkeypatch):
        monkeypatch.delenv("FJT_DRIFT_SAMPLE", raising=False)
        reg = MetricsRegistry()
        assert drift.plane_for(reg) is None
        # the real dispatch gate: nothing lands in the registry
        from flink_jpmml_tpu.runtime.pipeline import dispatch_quantized  # noqa: F401

        snap = reg.struct_snapshot()
        assert "sketches" not in snap
        assert not any(
            k.startswith("drift_") for k in snap["counters"]
        )

    def test_env_arms_the_plane(self, monkeypatch):
        monkeypatch.setenv("FJT_DRIFT_SAMPLE", "0")
        reg = MetricsRegistry()
        plane = drift.plane_for(reg)
        assert plane is not None
        assert drift.plane_for(reg) is plane  # cached

    def test_records_profiles_missing_and_unseen(self):
        reg = MetricsRegistry()
        plane = _plane(reg)
        q = _FakeScorer(
            fields=("a", "b"),
            cuts=[np.array([-1.0, 1.0]), np.empty((0,))],
        )
        X = np.array(
            [[0.0, 5.0], [2.0, 5.0], [np.nan, 5.0], [-3.0, np.nan]],
            np.float32,
        )
        assert plane.record_features(q, X)
        c = reg.struct_snapshot()["counters"]
        assert c['drift_feature_records{model="m01",feature="a"}'] == 4
        assert c['drift_feature_missing{model="m01",feature="a"}'] == 1
        # 2.0 and -3.0 sit beyond [-1, 1]; NaN is missing, not unseen
        assert c['drift_feature_unseen{model="m01",feature="a"}'] == 2
        # feature b has no cuts: never out-of-domain
        assert c['drift_feature_unseen{model="m01",feature="b"}'] == 0
        assert c['drift_feature_missing{model="m01",feature="b"}'] == 1
        sk = reg.sketches()['feature_values{model="m01",feature="a"}']
        assert sk.count() == 3  # missing excluded from the value sketch

    def test_explicit_mask_folds_into_missing(self):
        reg = MetricsRegistry()
        plane = _plane(reg)
        q = _FakeScorer(fields=("a",), cuts=[np.array([0.0])])
        X = np.array([[1.0], [2.0]], np.float32)
        M = np.array([[True], [False]])
        plane.record_features(q, X, M)
        c = reg.struct_snapshot()["counters"]
        assert c['drift_feature_missing{model="m01",feature="a"}'] == 1
        assert reg.sketches()[
            'feature_values{model="m01",feature="a"}'
        ].count() == 1

    def test_interval_rate_limit_fake_clock(self):
        t = [0.0]
        reg = MetricsRegistry()
        plane = drift.DriftPlane(
            reg, interval_s=1.0, budget_frac=None, clock=lambda: t[0],
        )
        q = _FakeScorer()
        X = np.zeros((8, 3), np.float32)
        assert plane.record_features(q, X)
        assert not plane.record_features(q, X)  # inside the interval
        t[0] = 1.5
        assert plane.record_features(q, X)
        # the two families rate-limit independently
        assert plane.record_predictions("m01", np.ones(4))
        assert not plane.record_predictions("m01", np.ones(4))

    def test_row_cap(self):
        reg = MetricsRegistry()
        plane = _plane(reg, max_rows=16)
        plane.record_features(_FakeScorer(), np.zeros((1000, 3), np.float32))
        c = reg.struct_snapshot()["counters"]
        assert c['drift_feature_records{model="m01",feature="a"}'] <= 16

    def test_row_subsample_spans_the_whole_batch(self):
        # ceil stride: drift clustered in a drain's TAIL must still be
        # sampled (floor division truncated to the leading rows)
        reg = MetricsRegistry()
        plane = _plane(reg, max_rows=512)
        X = np.zeros((1000, 1), np.float32)
        X[500:, 0] = np.nan  # the entire second half is missing
        q = _FakeScorer(fields=("a",), cuts=[np.array([0.0])])
        plane.record_features(q, X)
        c = reg.struct_snapshot()["counters"]
        miss = c['drift_feature_missing{model="m01",feature="a"}']
        rec = c['drift_feature_records{model="m01",feature="a"}']
        assert rec <= 512
        assert 0.4 <= miss / rec <= 0.6, (miss, rec)

    def test_budget_gate_skips(self):
        t = [0.0]
        reg = MetricsRegistry()
        plane = drift.DriftPlane(
            reg, interval_s=0.0, budget_frac=0.02, clock=lambda: t[0],
        )
        q = _FakeScorer()
        X = np.zeros((64, 3), np.float32)
        t[0] = 0.001
        assert plane.record_features(q, X)  # first sample goes through
        # pretend that sample was expensive relative to elapsed wall
        with plane._mu:
            plane._spent = 1.0
        t[0] = 0.002
        assert not plane.record_features(q, X)
        assert plane.stats()["skipped"] >= 1
        # wall clock catches up past spent/budget → sampling resumes
        t[0] = 100.0
        assert plane.record_features(q, X)

    def test_prediction_extraction_shapes(self):
        reg = MetricsRegistry()
        plane = _plane(reg)
        # tuple (classification) → the value plane
        plane.record_predictions("m01", (np.arange(8.0), None, None), 8)
        sk = reg.sketches()['prediction_values{model="m01"}']
        assert sk.count() == 8
        # unrecognizable input records nothing, never raises
        t = [10.0]
        plane._clock = lambda: t[0]
        assert not plane.record_predictions("m01", object())

    def test_dispatch_quantized_integration(self, tmp_path):
        # the REAL dispatch path on a real compiled scorer
        from assets.generate import gen_gbm
        from flink_jpmml_tpu.compile.qtrees import build_quantized_scorer
        from flink_jpmml_tpu.pmml import parse_pmml_file
        from flink_jpmml_tpu.runtime.pipeline import dispatch_quantized

        doc = parse_pmml_file(
            gen_gbm(str(tmp_path), n_trees=5, depth=2, n_features=3)
        )
        q = build_quantized_scorer(doc, batch_size=32)
        rng = np.random.default_rng(0)
        X = rng.normal(0, 1, (32, 3)).astype(np.float32)

        # unarmed registry: the dispatch records nothing
        cold = MetricsRegistry()
        import jax

        jax.block_until_ready(dispatch_quantized(q, X, metrics=cold))
        assert "sketches" not in cold.struct_snapshot()

        # armed registry: profiles land, labelled by model_hash
        reg = MetricsRegistry()
        _plane(reg)
        jax.block_until_ready(dispatch_quantized(q, X, metrics=reg))
        snap = reg.struct_snapshot()
        key = f'feature_values{{model="{q.model_hash}",feature="f0"}}'
        assert snap["sketches"][key]["n"] == 32


# ---------------------------------------------------------------------------
# DriftMonitor (hysteresis under a fake clock)
# ---------------------------------------------------------------------------


class _CumFeed:
    """A worker's CUMULATIVE drift state (what a live registry holds);
    the monitor windows over deltas of successive ``struct()`` frames,
    exactly as it does against a real registry or fleet merge."""

    def __init__(self, label="m"):
        self.label = label
        self.sk = QuantileSketch()
        self.pred = QuantileSketch()

    def add(self, vals=None, pred=None):
        if vals is not None:
            self.sk.observe_many(vals)
        if pred is not None:
            self.pred.observe_many(pred)

    def struct(self):
        sketches = {
            drift.feature_sketch_name(self.label, "x"): self.sk.state()
        }
        if self.pred.count():
            sketches[
                drift.prediction_sketch_name(self.label)
            ] = self.pred.state()
        return {"sketches": sketches, "counters": {}}


class TestDriftMonitorHysteresis:
    def _monitor(self, feed, t, **kw):
        gauges = MetricsRegistry()
        kw.setdefault("psi_alarm", 0.25)
        kw.setdefault("psi_clear", 0.1)
        kw.setdefault("min_n", 50)
        kw.setdefault("window_s", 1e9)
        kw.setdefault("dwell_s", 5.0)
        mon = drift.DriftMonitor(
            struct_fn=feed.struct,
            store=drift.BaselineStore("/nonexistent-drift-dir"),
            interval_s=0.0,
            clock=lambda: t[0],
            gauge_metrics=gauges,
            **kw,
        )
        return mon, gauges

    def test_alarm_requires_dwell_then_fires_once(self):
        rng = np.random.default_rng(10)
        t = [0.0]
        feed = _CumFeed()
        feed.add(rng.normal(0, 1, 4000))
        mon, gauges = self._monitor(feed, t)
        mon.set_baseline(
            "m", drift.snapshot_from_struct(feed.struct())["m"]
        )
        assert mon.tick() == []          # baseline frame: psi ≈ 0
        feed.add(rng.normal(4, 1, 4000))  # the drift arrives
        t[0] = 1.0
        assert mon.tick() == []          # above threshold, dwell starts
        t[0] = 3.0
        assert mon.tick() == []          # still inside the dwell
        t[0] = 6.5
        trans = mon.tick()
        assert [tr["transition"] for tr in trans] == ["alarm"]
        assert trans[0]["feature"] == "x"
        t[0] = 7.0
        assert mon.tick() == []          # no re-fire while alarmed
        assert mon.alarms() and not mon.health()["drift"]["ok"]
        g = gauges.struct_snapshot()["gauges"]
        assert g['drift_alarmed{model="m",feature="x"}']["value"] == 1.0
        assert g['drift_score{model="m",feature="x"}']["value"] > 0.25
        assert gauges.struct_snapshot()["counters"]["drift_alarms"] == 1

    def test_band_wobble_neither_clears_nor_realarms(self):
        # hysteresis: a score inside (clear, alarm) accrues progress in
        # NEITHER direction. window_s below the 1s tick spacing makes
        # each tick's window the delta since the previous tick, so each
        # phase's distribution is under test control.
        rng = np.random.default_rng(11)
        t = [0.0]
        feed = _CumFeed()
        feed.add(rng.normal(0, 1, 4000))
        mon, _ = self._monitor(feed, t, dwell_s=0.0, window_s=0.9)
        mon.set_baseline(
            "m", drift.snapshot_from_struct(feed.struct())["m"]
        )
        mon.tick()                       # baseline frame
        feed.add(rng.normal(4, 1, 4000))
        t[0] = 1.0
        assert [tr["transition"] for tr in mon.tick()] == ["alarm"]
        # the next window lands INSIDE the band: psi(N(0,1), N(.35,1))
        # ≈ 0.14 ∈ (0.1, 0.25)
        feed.add(rng.normal(0.35, 1.0, 4000))
        t[0] = 2.0
        trans = mon.tick()
        assert trans == [], (trans, mon.scores())
        score = mon.scores()[("m", "x")]
        assert 0.1 < score < 0.25, score  # genuinely in the band
        assert mon.alarms()  # still alarmed: the band held the state

    def test_clear_requires_sustained_below_clear(self):
        rng = np.random.default_rng(12)
        t = [0.0]
        feed = _CumFeed()
        feed.add(rng.normal(0, 1, 4000))
        mon, _ = self._monitor(feed, t, dwell_s=2.0, window_s=0.9)
        mon.set_baseline(
            "m", drift.snapshot_from_struct(feed.struct())["m"]
        )
        mon.tick()                       # baseline frame
        feed.add(rng.normal(4, 1, 4000))
        t[0] = 1.0
        assert mon.tick() == []          # drifted, dwell starts
        feed.add(rng.normal(4, 1, 4000))
        t[0] = 3.2
        assert [tr["transition"] for tr in mon.tick()] == ["alarm"]
        # recovery: subsequent windows match the baseline again (the
        # retained baseline frame can be up to window+tick old, so the
        # first recovered tick still sees the drifted chunk)
        feed.add(rng.normal(0, 1, 4000))
        t[0] = 4.0
        assert mon.tick() == []          # window still spans the drift
        feed.add(rng.normal(0, 1, 4000))
        t[0] = 5.0
        assert mon.tick() == []          # below clear, dwell starts
        feed.add(rng.normal(0, 1, 4000))
        t[0] = 6.2
        assert mon.tick() == []          # 1.2s below < the 2s dwell
        feed.add(rng.normal(0, 1, 4000))
        t[0] = 7.3
        trans = mon.tick()
        assert [tr["transition"] for tr in trans] == ["clear"]
        assert not mon.alarms() and mon.health()["drift"]["ok"]
        ev = [e for e in flight.events() if e.get("kind") == "drift_clear"]
        assert ev and ev[-1]["model"] == "m"

    def test_prediction_series_alarm(self):
        rng = np.random.default_rng(13)
        t = [0.0]
        feed = _CumFeed()
        feed.add(rng.normal(0, 1, 4000), pred=rng.normal(2, 1, 4000))
        mon, gauges = self._monitor(feed, t, dwell_s=0.0)
        mon.set_baseline(
            "m", drift.snapshot_from_struct(feed.struct())["m"]
        )
        mon.tick()                       # baseline frame
        # predictions shift; the feature stream stays steady
        feed.add(rng.normal(0, 1, 4000), pred=rng.normal(9, 1, 4000))
        t[0] = 1.0
        trans = mon.tick()
        kinds = {(tr["feature"], tr["transition"]) for tr in trans}
        assert (None, "alarm") in kinds  # the prediction series
        assert ("x", "alarm") not in kinds  # features stayed quiet
        g = gauges.struct_snapshot()["gauges"]
        assert g['prediction_drift{model="m"}']["value"] > 0.25

    def test_min_n_floor_blocks_verdicts(self):
        rng = np.random.default_rng(14)
        t = [0.0]
        feed = _CumFeed()
        feed.add(rng.normal(0, 1, 4000))
        mon, _ = self._monitor(feed, t, dwell_s=0.0, min_n=10_000)
        mon.set_baseline(
            "m", drift.snapshot_from_struct(feed.struct())["m"]
        )
        mon.tick()
        feed.add(rng.normal(9, 1, 4000))
        t[0] = 1.0
        assert mon.tick() == []  # window below the sample floor
        assert mon.scores() == {}

    def test_health_fn_composes(self):
        t = [0.0]
        mon, _ = self._monitor(_CumFeed(), t)
        h = mon.health_fn(lambda: {"ok": True, "base": 1})()
        assert h["ok"] and h["base"] == 1 and h["drift"]["ok"]

    def test_scrape_hook_ticks_registry_monitor(self):
        # registry mode: a /metrics scrape (struct_snapshot) must tick
        # the monitor even when no batch loop is running — the wedged-
        # consumer guarantee
        reg = MetricsRegistry()
        t = [0.0]
        mon = drift.monitor_for(reg)
        mon._clock = lambda: t[0]
        mon._interval = 0.0
        mon.dwell_s = 0.0
        mon.min_n = 50
        rng = np.random.default_rng(15)
        sk = reg.sketch(drift.feature_sketch_name("m", "x"))
        sk.observe_many(rng.normal(0, 1, 4000))
        mon.set_baseline(
            "m", drift.snapshot_from_struct(reg.struct_snapshot())["m"]
        )
        sk.observe_many(rng.normal(8, 1, 4000))
        t[0] = 1.0
        reg.struct_snapshot()  # the scrape IS the tick
        assert mon.alarms(), mon.scores()


# ---------------------------------------------------------------------------
# Rollout prediction-PSI guardrail
# ---------------------------------------------------------------------------


class TestPredictionPsiGuardrail:
    def _controller(self, spec, structs, t):
        from flink_jpmml_tpu.rollout.controller import RolloutController
        from flink_jpmml_tpu.rollout.state import RolloutState

        applied = []

        class _Book:
            def rollouts(self):
                return {
                    "m": RolloutState(
                        name="m", candidate_version=2, stage="canary",
                        fraction=0.2, spec=spec, stage_since=0.0,
                    )
                }

            def apply(self, msg):
                applied.append(msg)
                return True

        ctl = RolloutController(
            book=_Book(), struct_fn=lambda: structs[0],
            metrics=MetricsRegistry(), interval_s=0.0,
            clock=lambda: t[0],
        )
        return ctl, applied

    def _struct(self, cand_vals, inc_vals, records):
        ca, ia = QuantileSketch(), QuantileSketch()
        ca.observe_many(cand_vals)
        ia.observe_many(inc_vals)
        return {
            "counters": {
                'rollout_candidate_records{model="m"}': records,
                'rollout_incumbent_records{model="m"}': records,
            },
            "gauges": {},
            "histograms": {},
            "sketches": {
                'rollout_score_dist{model="m",role="candidate"}': ca.state(),
                'rollout_score_dist{model="m",role="incumbent"}': ia.state(),
            },
        }

    def test_rollback_on_prediction_psi(self):
        from flink_jpmml_tpu.rollout.state import GuardrailSpec

        rng = np.random.default_rng(16)
        spec = GuardrailSpec(
            max_prediction_psi=0.25, min_samples=100,
            promote_after_s=1e9,
        )
        inc = rng.normal(0, 1, 2000)
        structs = [self._struct(rng.normal(0, 1, 2000), inc, 2000)]
        t = [0.0]
        ctl, applied = self._controller(spec, structs, t)
        assert ctl.tick() == []  # healthy: same distribution
        # candidate's score distribution shifts hard
        structs[0] = self._struct(
            np.concatenate([rng.normal(0, 1, 2000), rng.normal(5, 1, 2000)]),
            np.concatenate([inc, rng.normal(0, 1, 2000)]),
            4000,
        )
        t[0] = 1.0
        decisions = ctl.tick()
        assert len(decisions) == 1 and decisions[0]["action"] == "rollback"
        assert "prediction PSI" in decisions[0]["reason"]
        assert decisions[0]["prediction_psi"] > 0.25
        assert applied and applied[0].stage == "rollback"
        g = ctl.metrics.struct_snapshot()["gauges"]
        assert g['rollout_prediction_psi{model="m"}']["value"] > 0.25

    def test_hold_promotion_below_max_above_hold(self):
        from flink_jpmml_tpu.rollout.state import GuardrailSpec

        rng = np.random.default_rng(17)
        # window_s below the tick spacing: each tick evaluates the
        # delta since the previous tick, so each phase's distribution
        # is under test control
        spec = GuardrailSpec(
            max_prediction_psi=50.0, hold_prediction_psi=0.05,
            min_samples=100, promote_after_s=0.0, window_s=0.9,
        )
        inc = rng.normal(0, 1, 2000)
        structs = [self._struct(rng.normal(0, 1, 2000), inc, 2000)]
        t = [0.0]
        ctl, applied = self._controller(spec, structs, t)
        ctl.tick()  # baseline frame (cumulative window: psi ≈ 0 BUT
        # promotion also needs the dwell evaluation below — accept
        # either a promote here or not, then reset for the hold phase
        applied.clear()
        # moderate shift: psi above hold, far below max → promotion HELD
        structs[0] = self._struct(
            np.concatenate([rng.normal(0, 1, 2000), rng.normal(2, 1, 2000)]),
            np.concatenate([inc, rng.normal(0, 1, 2000)]),
            4000,
        )
        t[0] = 1.0
        assert ctl.tick() == []
        assert not applied  # neither promoted nor rolled back
        held = [
            e for e in flight.events()
            if e.get("kind") == "rollout_promotion_held"
        ]
        assert held and held[-1]["model"] == "m"
        # the drift subsides → the same dwell now promotes
        structs[0] = self._struct(
            np.concatenate([
                rng.normal(0, 1, 2000), rng.normal(2, 1, 2000),
                rng.normal(0, 1, 20000),
            ]),
            np.concatenate([inc, rng.normal(0, 1, 22000)]),
            24000,
        )
        t[0] = 2.0
        decisions = ctl.tick()
        assert len(decisions) == 1 and decisions[0]["action"] == "promote"

    def test_spec_wire_roundtrip_and_validation(self):
        from flink_jpmml_tpu.rollout.state import GuardrailSpec

        spec = GuardrailSpec(
            max_prediction_psi=0.3, hold_prediction_psi=0.2
        )
        d = spec.as_dict()
        assert d["max_prediction_psi"] == 0.3
        assert GuardrailSpec.from_dict(json.loads(json.dumps(d))) == spec
        # unset fields stay OFF the wire (pre-drift readers see the
        # byte-compatible form) and default to disabled
        d2 = GuardrailSpec().as_dict()
        assert "max_prediction_psi" not in d2
        assert GuardrailSpec.from_dict(d2).effective_hold_psi is None
        assert GuardrailSpec(
            max_prediction_psi=0.4
        ).effective_hold_psi == pytest.approx(0.2)
        with pytest.raises(ValueError):
            GuardrailSpec(max_prediction_psi=-1.0)
        with pytest.raises(ValueError):
            GuardrailSpec(
                max_prediction_psi=0.1, hold_prediction_psi=0.2
            )

    def test_scorer_records_score_dists(self, tmp_path):
        # the live signal source: a rolled-out DynamicScorer sketches
        # both roles' score distributions
        from assets.generate import gen_gbm
        from flink_jpmml_tpu.models.control import (
            AddMessage, RolloutMessage,
        )
        from flink_jpmml_tpu.models.core import ModelId
        from flink_jpmml_tpu.runtime.sources import ControlSource
        from flink_jpmml_tpu.serving.scorer import DynamicScorer

        pmml = gen_gbm(str(tmp_path), n_trees=5, depth=2, n_features=3)
        # the candidate must be a byte-identical COPY at a different
        # path: registering the SAME path re-attributes the incumbent's
        # ModelInfo identity (the registry's re-warm optimization) and
        # every group would count as "candidate"
        pmml_v2 = str(tmp_path / "v2.pmml")
        with open(pmml, "rb") as f:
            doc_bytes = f.read()
        with open(pmml_v2, "wb") as f:
            f.write(doc_bytes)
        ctrl = ControlSource()
        sc = DynamicScorer(control=ctrl, batch_size=64, auto_rollout=False)
        ctrl.push(AddMessage("m", 1, pmml, timestamp=time.time()))
        sc._drain_control()
        deadline = time.monotonic() + 60.0
        while sc.registry.model_if_warm(ModelId("m", 1)) is None:
            assert time.monotonic() < deadline
            time.sleep(0.02)
        ctrl.push(RolloutMessage("m", 2, "shadow", time.time(), path=pmml_v2))
        sc._drain_control()
        while sc.registry.model_if_warm(ModelId("m", 2)) is None:
            assert time.monotonic() < deadline
            time.sleep(0.02)
        rng = np.random.default_rng(18)
        fields = ["f0", "f1", "f2"]
        for _ in range(4):
            events = [
                ("m", dict(zip(fields, rng.normal(0, 1, 3).tolist())))
                for _ in range(64)
            ]
            sc.finish(sc.submit(events))
        sk = sc.metrics.sketches()
        cand = sk['rollout_score_dist{model="m",role="candidate"}']
        inc = sk['rollout_score_dist{model="m",role="incumbent"}']
        assert inc.count() >= 64 and cand.count() >= 1
        # byte-identical candidate: distributions agree
        assert drift.psi(inc, cand) < 0.1


# ---------------------------------------------------------------------------
# Surfaces: summary / fjt-top --drift / fjt-drift CLI
# ---------------------------------------------------------------------------


class TestSurfaces:
    def _drifted_registry(self, tmp_path):
        reg = MetricsRegistry()
        store = drift.BaselineStore(tmp_path / "bl")
        plane = _plane(reg, store=store)
        mon = plane.monitor
        mon.min_n = 50
        mon.dwell_s = 0.0
        mon._interval = 0.0
        rng = np.random.default_rng(19)
        q = _FakeScorer(fields=("a", "b"), cuts=[
            np.array([-1.0, 1.0]), np.array([-1.0, 1.0]),
        ])
        for _ in range(8):
            plane.record_features(
                q, rng.normal(0, 1, (128, 2)).astype(np.float32)
            )
        drift.snapshot_registry(reg, store=store)
        for _ in range(8):
            X = rng.normal(0, 1, (128, 2)).astype(np.float32)
            X[:, 1] += 5.0
            plane.record_features(q, X)
        return reg, store

    def test_summary_and_artifact_fields(self, tmp_path):
        reg, _ = self._drifted_registry(tmp_path)
        s = drift.summary(reg)
        feats = s["m01"]["features"]
        assert feats["b"]["psi"] > 0.25 and feats["b"]["alarmed"]
        assert feats["a"]["psi"] < 0.25 and not feats["a"]["alarmed"]
        assert feats["b"]["n"] > 0
        art = drift.artifact_fields(reg)
        assert art["m01"]["worst_feature"] == "b"
        assert art["m01"]["alarmed_features"] == ["b"]
        assert drift.summary({}) is None
        assert drift.artifact_fields({}) is None

    def test_top_render_drift_panel(self, tmp_path):
        import io

        from flink_jpmml_tpu import cli

        reg, _ = self._drifted_registry(tmp_path)
        out = io.StringIO()
        cli._top_render_drift("w0", reg.struct_snapshot(), out)
        text = out.getvalue()
        assert "w0 · drift" in text
        assert "ALARM" in text
        # ranked worst-first: the drifted feature's row precedes the
        # quiet one's
        assert text.index("\nb ") < text.index("\na ")
        # an empty struct renders the honest fallback
        out2 = io.StringIO()
        cli._top_render_drift("", {}, out2)
        assert "no drift telemetry" in out2.getvalue()

    def test_fjt_drift_cli_roundtrip(self, tmp_path, capsys):
        from flink_jpmml_tpu import cli

        reg, _ = self._drifted_registry(tmp_path)
        dump = tmp_path / "varz.json"
        dump.write_text(json.dumps(reg.struct_snapshot()))
        bl = str(tmp_path / "cli-bl")
        assert cli.drift_main(
            ["snapshot", str(dump), "--dir", bl]
        ) == 0
        assert cli.drift_main(["list", "--dir", bl]) == 0
        assert "m01" in capsys.readouterr().out
        # checking the SAME data against its own snapshot: stable
        assert cli.drift_main(["check", str(dump), "--dir", bl]) == 0
        # a shifted source fails the check with exit 1
        rng = np.random.default_rng(20)
        plane = drift.plane_for(reg)
        qsc = _FakeScorer(fields=("a", "b"), cuts=[
            np.array([-1.0, 1.0]), np.array([-1.0, 1.0]),
        ])
        for _ in range(20):
            X = rng.normal(0, 1, (256, 2)).astype(np.float32)
            X[:, 0] += 8.0
            plane.record_features(qsc, X)
        dump.write_text(json.dumps(reg.struct_snapshot()))
        assert cli.drift_main(["check", str(dump), "--dir", bl]) == 1
        out = capsys.readouterr().out
        assert "DRIFTED" in out

    def test_fjt_rollout_cli_psi_flags(self, tmp_path, capsys):
        from flink_jpmml_tpu import cli
        from flink_jpmml_tpu.models.control import from_wire

        ctrl = tmp_path / "ctrl.jsonl"
        rc = cli.rollout_main([
            str(ctrl), "canary", "--name", "m", "--version", "2",
            "--max-prediction-psi", "0.25",
            "--hold-prediction-psi", "0.1",
        ])
        assert rc == 0
        msg = from_wire(json.loads(ctrl.read_text().strip()))
        assert msg.guardrails.max_prediction_psi == 0.25
        assert msg.guardrails.hold_prediction_psi == 0.1


# ---------------------------------------------------------------------------
# The drill (smoke-scale) — the acceptance surface
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestDriftDrill:
    def test_drill_passes(self):
        from flink_jpmml_tpu.bench import run_drift_drill

        line = run_drift_drill(records_per_phase=4096, batch=256)
        assert line["ok"] and line["merge_exact"]
        assert line["perturbed_feature"] == "f1"
        assert line["psi_control"] < 0.25 < line["psi_perturbed"]
        assert line["varz"]["sketches"]
