"""Async compile + double-buffered model swap in dynamic serving
(SURVEY.md §8 hard part (d); VERDICT r1 #4).

The contract under test: an AddMessage triggers a *background* parse +
compile + jit; while the new version warms, unpinned events keep scoring
the newest warm version (and pinned-cold events go empty) — the batch
loop never stalls on a compile. Only the first deployment of a name
blocks, joining the in-flight warm rather than compiling twice.
"""

import pathlib
import time

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # serving swap/SLO drills (-m 'not slow' = fast inner loop)

from flink_jpmml_tpu.models.control import AddMessage
from flink_jpmml_tpu.models.core import ModelId
from flink_jpmml_tpu.runtime.sources import ControlSource
from flink_jpmml_tpu.serving.registry import ModelRegistry
from flink_jpmml_tpu.serving.scorer import DynamicScorer

_CONST_XML = """<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3">
  <Header/>
  <DataDictionary numberOfFields="2">
    <DataField name="a" optype="continuous" dataType="double"/>
    <DataField name="y" optype="continuous" dataType="double"/>
  </DataDictionary>
  <RegressionModel functionName="regression">
    <MiningSchema>
      <MiningField name="y" usageType="target"/>
      <MiningField name="a"/>
    </MiningSchema>
    <RegressionTable intercept="{c}"/>
  </RegressionModel></PMML>"""


def _write_const(tmp_path, name, c):
    p = pathlib.Path(tmp_path, name)
    p.write_text(_CONST_XML.format(c=c))
    return str(p)


def _slow_loader(reg, slow_substr, delay_s, counter=None):
    """Instance-patch the registry's loader: paths containing
    ``slow_substr`` sleep ``delay_s`` before compiling."""
    orig = reg._load

    def load(info):
        if counter is not None:
            counter[info.path] = counter.get(info.path, 0) + 1
        if slow_substr in info.path:
            time.sleep(delay_s)
        return orig(info)

    reg._load = load


def _wait_warm(reg, mid, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if reg.model_if_warm(mid) is not None or reg.warm_error(mid):
            return
        time.sleep(0.01)
    raise AssertionError(f"{mid} never warmed")


def _values(results):
    return [p.score.value if p.score else None for (p, _e) in results]


class TestDoubleBufferedSwap:
    def test_unpinned_events_stay_on_previous_while_new_warms(self, tmp_path):
        v1 = _write_const(tmp_path, "v1.pmml", 1.0)
        v2 = _write_const(tmp_path, "v2.pmml", 2.0)
        ctrl = ControlSource()
        sc = DynamicScorer(control=ctrl, batch_size=4)
        _slow_loader(sc.registry, "v2", 0.8)

        ctrl.push(AddMessage("m", 1, v1, timestamp=1.0))
        out = sc.finish(sc.submit([("m", {"a": 0.0})]))
        assert _values(out) == [1.0]  # v1 warm and serving

        ctrl.push(AddMessage("m", 2, v2, timestamp=2.0))
        t0 = time.monotonic()
        out = sc.finish(sc.submit([("m", {"a": 0.0}), ("m", {"a": 1.0})]))
        dt = time.monotonic() - t0
        # served by v1 — and without waiting for v2's 0.8s compile
        assert _values(out) == [1.0, 1.0]
        assert dt < 0.5, f"batch stalled {dt:.2f}s on a background compile"

        _wait_warm(sc.registry, ModelId("m", 2))
        out = sc.finish(sc.submit([("m", {"a": 0.0})]))
        assert _values(out) == [2.0]  # swap complete

    def test_pinned_cold_version_goes_empty_without_stall(self, tmp_path):
        v1 = _write_const(tmp_path, "v1.pmml", 1.0)
        v2 = _write_const(tmp_path, "v2.pmml", 2.0)
        ctrl = ControlSource()
        sc = DynamicScorer(control=ctrl, batch_size=4)
        _slow_loader(sc.registry, "v2", 0.8)
        ctrl.push(AddMessage("m", 1, v1, timestamp=1.0))
        sc.finish(sc.submit([("m", {"a": 0.0})]))

        ctrl.push(AddMessage("m", 2, v2, timestamp=2.0))
        t0 = time.monotonic()
        out = sc.finish(
            sc.submit([{"_model": "m", "_version": 2, "a": 0.0}])
        )
        dt = time.monotonic() - t0
        (p, _e) = out[0]
        assert p.is_empty  # pinned to the cold version → empty lane
        assert dt < 0.5

        _wait_warm(sc.registry, ModelId("m", 2))
        out = sc.finish(
            sc.submit([{"_model": "m", "_version": 2, "a": 0.0}])
        )
        assert _values(out) == [2.0]

    def test_first_deploy_joins_inflight_warm_one_compile(self, tmp_path):
        v1 = _write_const(tmp_path, "v1.pmml", 1.0)
        ctrl = ControlSource()
        sc = DynamicScorer(control=ctrl, batch_size=4)
        loads = {}
        _slow_loader(sc.registry, "v1", 0.3, counter=loads)
        ctrl.push(AddMessage("m", 1, v1, timestamp=1.0))
        # first deployment: nothing warm to fall back to — the submit
        # blocks, joining the background warm (correctness over liveness)
        out = sc.finish(sc.submit([("m", {"a": 0.0})]))
        assert _values(out) == [1.0]
        assert loads.get(v1) == 1, f"duplicate compile: {loads}"

    def test_background_failure_quarantines_and_falls_back(self, tmp_path):
        v1 = _write_const(tmp_path, "v1.pmml", 1.0)
        ctrl = ControlSource()
        sc = DynamicScorer(control=ctrl, batch_size=4)
        ctrl.push(AddMessage("m", 1, v1, timestamp=1.0))
        sc.finish(sc.submit([("m", {"a": 0.0})]))

        ctrl.push(AddMessage("m", 2, "/nonexistent/v2.pmml", timestamp=2.0))
        sc.submit([("m", {"a": 0.0})])  # drains control, starts the warm
        deadline = time.monotonic() + 10.0
        while (
            sc.registry.warm_error(ModelId("m", 2)) is None
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        assert sc.registry.warm_error(ModelId("m", 2)) is not None
        # unpinned traffic falls back to the warm v1; the stream lives
        out = sc.finish(sc.submit([("m", {"a": 0.0})]))
        assert _values(out) == [1.0]


class TestRegistryWarmup:
    def test_restore_prewarms_served_models(self, tmp_path):
        v1 = _write_const(tmp_path, "v1.pmml", 3.0)
        reg = ModelRegistry(batch_size=4)
        reg.apply(AddMessage("m", 1, v1, timestamp=1.0))
        state = reg.state()

        reg2 = ModelRegistry(batch_size=4)
        reg2.restore(state)
        mid = ModelId("m", 1)
        _wait_warm(reg2, mid)
        # ready without ever calling the blocking model() path
        assert reg2.model_if_warm(mid) is not None

    def test_readd_with_new_path_not_served_by_stale_warm(self, tmp_path):
        """Del + re-Add of the same (name, version) with a different path
        while the old path's warm is in flight: the stale warm's result
        must not be attributed to the new registration."""
        from flink_jpmml_tpu.models.control import DelMessage

        old = _write_const(tmp_path, "old.pmml", 1.0)
        new = _write_const(tmp_path, "new.pmml", 2.0)
        reg = ModelRegistry(batch_size=4)
        _slow_loader(reg, "old", 0.4)
        mid = ModelId("m", 1)

        reg.apply(AddMessage("m", 1, old, timestamp=1.0))
        assert reg.is_warming(mid)
        reg.apply(DelMessage("m", 1, timestamp=2.0))
        reg.apply(AddMessage("m", 1, new, timestamp=3.0))  # same id, new path
        _wait_warm(reg, mid)
        deadline = time.monotonic() + 10.0
        # let the stale old-path warm finish too, then check attribution
        while reg.is_warming(mid) and time.monotonic() < deadline:
            time.sleep(0.01)
        time.sleep(0.5)  # past the old warm's sleep
        model = reg.model(mid)
        [pred] = model.score_records([{"a": 0.0}])
        assert pred.score.value == pytest.approx(2.0), (
            "stale warm's artifact served for the re-added registration"
        )

    def test_delete_during_warm_does_not_resurrect(self, tmp_path):
        from flink_jpmml_tpu.models.control import DelMessage

        v1 = _write_const(tmp_path, "v1.pmml", 1.0)
        reg = ModelRegistry(batch_size=4)
        _slow_loader(reg, "v1", 0.3)
        reg.apply(AddMessage("m", 1, v1, timestamp=1.0))
        mid = ModelId("m", 1)
        assert reg.is_warming(mid)
        reg.apply(DelMessage("m", 1, timestamp=2.0))
        deadline = time.monotonic() + 10.0
        while reg.is_warming(mid) and time.monotonic() < deadline:
            time.sleep(0.01)
        assert reg.model_if_warm(mid) is None
        assert reg.resolve("m") is None


class TestLatencySLO:
    """VERDICT r2 weak #4 / r1 #4: batch p99 stays bounded while a
    genuinely expensive model (a real GBM parse+compile+jit, plus a
    simulated 1.5s fetch) warms in the background — and the same
    scenario with async_warmup=False violates the bound, proving the
    feature rather than the machine."""

    BATCH = 32
    FETCH_DELAY = 1.5

    def _models(self, tmp_path, sub):
        from assets.generate import gen_gbm

        d = pathlib.Path(tmp_path, sub)
        (d / "v1").mkdir(parents=True)
        (d / "v2").mkdir(parents=True)
        small = gen_gbm(str(d / "v1"), n_trees=2, depth=3, n_features=4)
        big = gen_gbm(str(d / "v2"), n_trees=60, depth=4, n_features=4)
        return small, big

    def _scenario(self, tmp_path, sub, async_warmup):
        v1, v2 = self._models(tmp_path, sub)
        ctrl = ControlSource()
        sc = DynamicScorer(
            control=ctrl, batch_size=self.BATCH, async_warmup=async_warmup
        )
        _slow_loader(sc.registry, "v2", self.FETCH_DELAY)
        rng = np.random.default_rng(11)
        batch = [
            ("m", {f"f{j}": float(v) for j, v in enumerate(row)})
            for row in rng.normal(size=(self.BATCH, 4))
        ]
        ctrl.push(AddMessage("m", 1, v1, timestamp=1.0))
        sc.finish(sc.submit(batch))  # first deploy: v1 warm and serving
        ctrl.push(AddMessage("m", 2, v2, timestamp=2.0))
        lats = []
        mid2 = ModelId("m", 2)
        deadline = time.monotonic() + 60.0
        # drive the batch loop continuously through the entire warm
        while time.monotonic() < deadline:
            t0 = time.monotonic()
            out = sc.finish(sc.submit(batch))
            lats.append(time.monotonic() - t0)
            assert len(out) == self.BATCH
            if sc.registry.model_if_warm(mid2) is not None and len(lats) > 4:
                break
        assert sc.registry.model_if_warm(mid2) is not None, "v2 never warmed"
        return lats

    def test_async_keeps_p99_bounded_sync_stalls(self, tmp_path):
        lats_async = self._scenario(tmp_path, "on", async_warmup=True)
        lats_sync = self._scenario(tmp_path, "off", async_warmup=False)
        p99 = sorted(lats_async)[max(0, int(0.99 * len(lats_async)) - 1)]
        stall = max(lats_sync)
        # the warm takes >= FETCH_DELAY + a real GBM compile (seconds);
        # with async warming no batch ever sees it
        assert p99 < 0.5, f"async p99 {p99:.2f}s breached the SLO"
        assert stall >= self.FETCH_DELAY, (
            f"sync scenario never stalled (max {stall:.2f}s) — "
            "the contrast no longer proves the feature"
        )
        assert stall > 4 * p99
