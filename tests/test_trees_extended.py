"""Extended tree-lowering coverage: set-predicate splits and the iterative
deep-tree backend, golden-diffed against the oracle."""

import dataclasses

import numpy as np
import pytest

from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.pmml import parse_pmml
from flink_jpmml_tpu.pmml.interp import evaluate
from flink_jpmml_tpu.utils.config import CompileConfig

RTOL = 2e-4


def _assert_match(cm, doc, records):
    preds = cm.score_records(records)
    for rec, p in zip(records, preds):
        o = evaluate(doc, rec)
        assert o.is_missing == p.is_empty, (rec, o, p)
        if o.is_missing:
            continue
        if o.value is not None:
            assert p.score.value == pytest.approx(o.value, rel=RTOL, abs=1e-5), rec
        if o.label is not None:
            assert p.target is not None and p.target.label == o.label, (rec, o)


SET_TREE = (
    '<PMML version="4.3"><DataDictionary>'
    '<DataField name="color" optype="categorical" dataType="string">'
    '<Value value="red"/><Value value="green"/><Value value="blue"/>'
    '<Value value="black"/></DataField>'
    '<DataField name="x" optype="continuous" dataType="double"/>'
    "</DataDictionary>"
    '<TreeModel functionName="regression" missingValueStrategy="none">'
    '<MiningSchema><MiningField name="color"/><MiningField name="x"/>'
    "</MiningSchema>"
    '<Node id="r"><True/>'
    '<Node id="l"><SimpleSetPredicate field="color" booleanOperator="isIn">'
    '<Array n="2" type="string">red blue</Array></SimpleSetPredicate>'
    '<Node id="ll" score="1"><SimplePredicate field="x" operator="lessThan" '
    'value="0"/></Node>'
    '<Node id="lr" score="2"><True/></Node>'
    "</Node>"
    '<Node id="rr" score="3"><SimpleSetPredicate field="color" '
    'booleanOperator="isNotIn">'
    '<Array n="2" type="string">red blue</Array></SimpleSetPredicate></Node>'
    "</Node></TreeModel></PMML>"
)


class TestSetPredicateSplits:
    def test_membership_routing(self):
        doc = parse_pmml(SET_TREE)
        cm = compile_pmml(doc)
        recs = [
            {"color": "red", "x": -1.0},
            {"color": "red", "x": 1.0},
            {"color": "blue", "x": 5.0},
            {"color": "green", "x": 0.0},
            {"color": "black", "x": 0.0},
            {"color": "purple", "x": 0.0},  # undeclared → missing → null
            {"color": None, "x": 0.0},
        ]
        _assert_match(cm, doc, recs)

    def test_set_split_in_ensemble(self):
        # set split mixed with comparison splits in a summed ensemble
        seg = (
            '<Segment id="0"><True/>'
            '<TreeModel functionName="regression" missingValueStrategy="none">'
            '<MiningSchema><MiningField name="color"/><MiningField name="x"/>'
            "</MiningSchema>"
            '<Node id="r"><True/>'
            '<Node id="a" score="10"><SimpleSetPredicate field="color" '
            'booleanOperator="isIn"><Array n="1" type="string">green</Array>'
            "</SimpleSetPredicate></Node>"
            '<Node id="b" score="20"><True/></Node>'
            "</Node></TreeModel></Segment>"
            '<Segment id="1"><True/>'
            '<TreeModel functionName="regression" missingValueStrategy="none">'
            '<MiningSchema><MiningField name="color"/><MiningField name="x"/>'
            "</MiningSchema>"
            '<Node id="r"><True/>'
            '<Node id="c" score="1"><SimplePredicate field="x" '
            'operator="lessThan" value="0.5"/></Node>'
            '<Node id="d" score="2"><SimplePredicate field="x" '
            'operator="greaterOrEqual" value="0.5"/></Node>'
            "</Node></TreeModel></Segment>"
        )
        xml = (
            '<PMML version="4.3"><DataDictionary>'
            '<DataField name="color" optype="categorical" dataType="string">'
            '<Value value="red"/><Value value="green"/></DataField>'
            '<DataField name="x" optype="continuous" dataType="double"/>'
            "</DataDictionary>"
            '<MiningModel functionName="regression">'
            '<MiningSchema><MiningField name="color"/><MiningField name="x"/>'
            "</MiningSchema>"
            f'<Segmentation multipleModelMethod="sum">{seg}</Segmentation>'
            "</MiningModel></PMML>"
        )
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        recs = [
            {"color": "green", "x": 0.0},
            {"color": "red", "x": 1.0},
            {"color": "red", "x": 0.0},
        ]
        _assert_match(cm, doc, recs)


def _deep_tree_xml(depth: int) -> str:
    """A strictly deeper-than-dense-cap chain tree: at level i splits on
    f_{i % 3} with threshold i/depth; left leaf carries a score, right
    recurses."""

    def node(i):
        thr = i / depth
        left = (
            f'<Node id="L{i}" score="{i + 0.25}">'
            f'<SimplePredicate field="f{i % 3}" operator="lessThan" '
            f'value="{thr}"/></Node>'
        )
        if i == depth - 1:
            right = (
                f'<Node id="R{i}" score="{depth * 1.5}">'
                f'<SimplePredicate field="f{i % 3}" '
                f'operator="greaterOrEqual" value="{thr}"/></Node>'
            )
        else:
            right = (
                f'<Node id="R{i}"><SimplePredicate field="f{i % 3}" '
                f'operator="greaterOrEqual" value="{thr}"/>{node(i + 1)}</Node>'
            )
        return left + right

    return (
        '<PMML version="4.3"><DataDictionary>'
        + "".join(
            f'<DataField name="f{j}" optype="continuous" dataType="double"/>'
            for j in range(3)
        )
        + "</DataDictionary>"
        '<TreeModel functionName="regression" missingValueStrategy="none">'
        "<MiningSchema>"
        + "".join(f'<MiningField name="f{j}"/>' for j in range(3))
        + "</MiningSchema>"
        f'<Node id="root"><True/>{node(0)}</Node>'
        "</TreeModel></PMML>"
    )


class TestIterativeBackend:
    def test_deep_tree_uses_iterative_and_matches_oracle(self):
        doc = parse_pmml(_deep_tree_xml(depth=14))
        cm = compile_pmml(doc)  # default max_dense_depth=10 → iterative
        rng = np.random.default_rng(0)
        recs = [
            {f"f{j}": float(rng.uniform(-0.2, 1.2)) for j in range(3)}
            for _ in range(128)
        ]
        _assert_match(cm, doc, recs)

    def test_dense_and_iterative_agree(self, assets_dir):
        from flink_jpmml_tpu.pmml import parse_pmml_file

        doc = parse_pmml_file(str(assets_dir / "gbm_small.pmml"))
        dense = compile_pmml(doc)
        iterative = compile_pmml(
            doc, config=CompileConfig(max_dense_depth=1)
        )
        rng = np.random.default_rng(1)
        X = rng.normal(0, 1, size=(64, 8)).astype(np.float32)
        X[X < -1.2] = np.nan  # some missing lanes (defaultChild path)
        pd = dense.score_dense(X)
        pi = iterative.score_dense(X)
        for a, b in zip(pd, pi):
            assert a.is_empty == b.is_empty
            if not a.is_empty:
                assert a.score.value == pytest.approx(b.score.value, rel=1e-6)

    def test_iterative_classification(self):
        xml = _deep_tree_xml(depth=12).replace(
            'functionName="regression"', 'functionName="classification"'
        )
        # chain-tree leaves carry numeric-string scores → usable as labels
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        rng = np.random.default_rng(2)
        recs = [
            {f"f{j}": float(rng.uniform(-0.2, 1.2)) for j in range(3)}
            for _ in range(64)
        ]
        _assert_match(cm, doc, recs)

    def test_iterative_set_splits(self):
        doc = parse_pmml(SET_TREE)
        cm = compile_pmml(doc, config=CompileConfig(max_dense_depth=1))
        recs = [
            {"color": "red", "x": -1.0},
            {"color": "green", "x": 0.0},
            {"color": None, "x": 0.0},
        ]
        _assert_match(cm, doc, recs)


def _nested_tree_xml(pred_xml: str) -> str:
    """A 3-field regression tree whose left-child predicate is pred_xml."""
    return (
        '<PMML version="4.3"><DataDictionary>'
        '<DataField name="a" optype="continuous" dataType="double"/>'
        '<DataField name="b" optype="continuous" dataType="double"/>'
        '<DataField name="c" optype="continuous" dataType="double"/>'
        "</DataDictionary>"
        '<TreeModel functionName="regression" missingValueStrategy="none">'
        '<MiningSchema><MiningField name="a"/><MiningField name="b"/>'
        '<MiningField name="c"/></MiningSchema>'
        '<Node id="r"><True/>'
        f'<Node id="l" score="1.5">{pred_xml}</Node>'
        '<Node id="rr" score="-2.5"><True/></Node>'
        "</Node></TreeModel></PMML>"
    )


def _sp(f, op, v):
    return f'<SimplePredicate field="{f}" operator="{op}" value="{v}"/>'


def _comp(op, *kids):
    return (
        f'<CompoundPredicate booleanOperator="{op}">'
        + "".join(kids)
        + "</CompoundPredicate>"
    )


def _nested_records(seed, n=200, missing_rate=0.25):
    rng = np.random.default_rng(seed)
    recs = []
    for _ in range(n):
        rec = {}
        for f in ("a", "b", "c"):
            if rng.random() >= missing_rate:
                rec[f] = float(rng.normal())
        recs.append(rec)
    return recs


class TestNestedCompoundPredicates:
    """Nested and/or/xor compounds lower exactly via the strong-Kleene
    DNF expansion (VERDICT r2 missing #3); golden-diffed vs the oracle
    over randomized records with missing values (U-propagation)."""

    @pytest.mark.parametrize("pred", [
        _comp("and", _comp("or", _sp("a", "lessThan", 0),
                           _sp("b", "greaterThan", 1)),
              _sp("c", "lessOrEqual", 0.5)),
        _comp("or", _comp("and", _sp("a", "greaterOrEqual", 0),
                          _sp("b", "lessThan", 0)),
              _comp("xor", _sp("b", "greaterThan", 0),
                    _sp("c", "greaterThan", 0))),
        _comp("xor", _comp("or", _sp("a", "lessThan", 0),
                           _sp("b", "lessThan", 0)),
              _sp("c", "greaterThan", 0)),
        _comp("and",
              _comp("or", _comp("and", _sp("a", "greaterThan", -1),
                                _sp("a", "lessThan", 1)),
                    _sp("b", "equal", 0)),
              _comp("or", _sp("c", "isMissing", 0),
                    _sp("c", "greaterThan", -0.5))),
        _comp("or", _comp("and", _sp("a", "notEqual", 0),
                          _comp("or", _sp("b", "lessThan", -0.3),
                                _sp("b", "greaterThan", 0.3))),
              _comp("and", _sp("c", "isNotMissing", 0), _sp("c", "lessThan", 0))),
    ])
    def test_nested_matches_oracle(self, pred):
        doc = parse_pmml(_nested_tree_xml(pred))
        cm = compile_pmml(doc)
        _assert_match(cm, doc, _nested_records(3))

    def test_nested_with_sets_and_missing_ops(self):
        xml = (
            '<PMML version="4.3"><DataDictionary>'
            '<DataField name="color" optype="categorical" dataType="string">'
            '<Value value="red"/><Value value="green"/><Value value="blue"/>'
            "</DataField>"
            '<DataField name="x" optype="continuous" dataType="double"/>'
            "</DataDictionary>"
            '<TreeModel functionName="regression" missingValueStrategy="none">'
            '<MiningSchema><MiningField name="color"/><MiningField name="x"/>'
            "</MiningSchema>"
            '<Node id="r"><True/>'
            '<Node id="l" score="7">'
            '<CompoundPredicate booleanOperator="or">'
            '<CompoundPredicate booleanOperator="and">'
            '<SimpleSetPredicate field="color" booleanOperator="isIn">'
            '<Array n="2" type="string">red blue</Array></SimpleSetPredicate>'
            '<SimplePredicate field="x" operator="greaterThan" value="0"/>'
            "</CompoundPredicate>"
            '<SimplePredicate field="x" operator="isMissing"/>'
            "</CompoundPredicate></Node>"
            '<Node id="rr" score="-7"><True/></Node>'
            "</Node></TreeModel></PMML>"
        )
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        rng = np.random.default_rng(11)
        recs = []
        for _ in range(150):
            rec = {}
            if rng.random() > 0.3:
                rec["color"] = str(rng.choice(["red", "green", "blue", "violet"]))
            if rng.random() > 0.3:
                rec["x"] = float(rng.normal())
            recs.append(rec)
        _assert_match(cm, doc, recs)

    def test_nested_surrogate_rejected_with_clear_error(self):
        from flink_jpmml_tpu.utils.exceptions import ModelCompilationException

        pred = _comp("and", _comp("surrogate", _sp("a", "lessThan", 0),
                                  _sp("b", "lessThan", 0)),
                     _sp("c", "greaterThan", 0))
        doc = parse_pmml(_nested_tree_xml(pred))
        with pytest.raises(ModelCompilationException, match="surrogate"):
            compile_pmml(doc)

    def test_flat_surrogate_still_works(self):
        pred = _comp("surrogate", _sp("a", "lessThan", 0),
                     _sp("b", "lessThan", 0), _sp("c", "lessThan", 0))
        doc = parse_pmml(_nested_tree_xml(pred))
        cm = compile_pmml(doc)
        _assert_match(cm, doc, _nested_records(5))


SELECT_ALL = """<PMML version="4.3"><DataDictionary>
  <DataField name="x" optype="continuous" dataType="double"/>
  <DataField name="y" optype="continuous" dataType="double"/>
  </DataDictionary>
  <MiningModel functionName="regression">
  <MiningSchema><MiningField name="y" usageType="target"/>
    <MiningField name="x"/></MiningSchema>
  <Segmentation multipleModelMethod="selectAll">
    <Segment id="lo"><SimplePredicate field="x" operator="lessThan"
        value="5"/>
      <TreeModel functionName="regression">
        <MiningSchema><MiningField name="y" usageType="target"/>
          <MiningField name="x"/></MiningSchema>
        <Node id="0" score="1.5"><True/></Node></TreeModel></Segment>
    <Segment id="hi"><SimplePredicate field="x" operator="greaterOrEqual"
        value="2"/>
      <TreeModel functionName="regression">
        <MiningSchema><MiningField name="y" usageType="target"/>
          <MiningField name="x"/></MiningSchema>
        <Node id="0" score="7.25"><True/></Node></TreeModel></Segment>
  </Segmentation></MiningModel></PMML>"""


class TestSelectAll:
    def test_per_segment_results(self):
        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.pmml import parse_pmml
        from flink_jpmml_tpu.pmml.interp import evaluate

        doc = parse_pmml(SELECT_ALL)
        cm = compile_pmml(doc)
        cases = {
            1.0: {"lo": 1.5, "hi": None},   # only lo active
            3.0: {"lo": 1.5, "hi": 7.25},   # both
            9.0: {"lo": None, "hi": 7.25},  # only hi
        }
        for x, segs in cases.items():
            rec = {"x": x}
            o = evaluate(doc, rec)
            p = cm.score_records([rec])[0]
            first = next(v for v in segs.values() if v is not None)
            assert o.value == pytest.approx(first)
            assert p.score.value == pytest.approx(first, rel=1e-6)
            assert o.outputs["segments"] == segs
            got = p.outputs["segments"]
            for sid, exp in segs.items():
                if exp is None:
                    assert got[sid] is None
                else:
                    assert got[sid] == pytest.approx(exp, rel=1e-6)

    def test_none_active_is_empty(self):
        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.pmml import parse_pmml
        from flink_jpmml_tpu.pmml.interp import evaluate

        bad = SELECT_ALL.replace('value="5"', 'value="-99"').replace(
            'value="2"', 'value="100"'
        )
        doc = parse_pmml(bad)
        cm = compile_pmml(doc)
        assert evaluate(doc, {"x": 0.0}).value is None
        assert cm.score_records([{"x": 0.0}])[0].is_empty

    def test_classification_segments_rejected(self):
        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.pmml import parse_pmml
        from flink_jpmml_tpu.utils.exceptions import (
            ModelCompilationException,
        )

        xml = SELECT_ALL.replace(
            '<TreeModel functionName="regression">',
            '<TreeModel functionName="classification">',
        )
        with pytest.raises(ModelCompilationException, match="regression"):
            compile_pmml(parse_pmml(xml))


class TestGatedMedian:
    def test_median_over_predicated_segments(self):
        """median with predicate-gated segments: the compiled path sorts
        the active subset with +inf pads and indexes by the active
        count — parity with the oracle across subset sizes."""
        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.pmml import parse_pmml
        from flink_jpmml_tpu.pmml.interp import evaluate

        seg = """<Segment><SimplePredicate field="x" operator="{op}"
            value="{v}"/>
          <TreeModel functionName="regression">
            <MiningSchema><MiningField name="y" usageType="target"/>
              <MiningField name="x"/></MiningSchema>
            <Node id="0" score="{s}"><True/></Node></TreeModel></Segment>"""
        xml = """<PMML version="4.3"><DataDictionary>
          <DataField name="x" optype="continuous" dataType="double"/>
          <DataField name="y" optype="continuous" dataType="double"/>
          </DataDictionary>
          <MiningModel functionName="regression">
          <MiningSchema><MiningField name="y" usageType="target"/>
            <MiningField name="x"/></MiningSchema>
          <Segmentation multipleModelMethod="median">
        """ + "".join([
            seg.format(op="greaterThan", v=0, s=1.0),
            seg.format(op="greaterThan", v=1, s=5.0),
            seg.format(op="greaterThan", v=2, s=9.0),
            seg.format(op="greaterThan", v=3, s=20.0),
        ]) + "</Segmentation></MiningModel></PMML>"
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        cases = {
            0.5: 1.0,                 # 1 active → itself
            1.5: 0.5 * (1.0 + 5.0),  # 2 active → mean of both
            2.5: 5.0,                 # 3 active → middle
            3.5: 0.5 * (5.0 + 9.0),  # 4 active → mean of middle two
        }
        for x, exp in cases.items():
            assert evaluate(doc, {"x": x}).value == pytest.approx(exp), x
            assert cm.score_records([{"x": x}])[0].score.value == (
                pytest.approx(exp, rel=1e-6)
            ), x
        # none active → empty on both paths
        assert evaluate(doc, {"x": -1.0}).value is None
        assert cm.score_records([{"x": -1.0}])[0].is_empty
