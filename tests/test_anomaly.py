"""AnomalyDetectionModel (PMML 4.4): the sklearn IsolationForest export
shape — inner path-length forest + 2^(−s/c(n)) normalization."""

import math

import numpy as np
import pytest

from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.compile.anomaly import iforest_c
from flink_jpmml_tpu.pmml import parse_pmml
from flink_jpmml_tpu.pmml.interp import evaluate


def _iforest_xml(algo='algorithmType="iforest" sampleDataSize="256"'):
    # two path-length "trees" averaged by the inner MiningModel
    def tree(thr, short, long_):
        return f"""<Segment><True/>
          <TreeModel functionName="regression">
            <MiningSchema><MiningField name="s" usageType="target"/>
              <MiningField name="x"/></MiningSchema>
            <Node id="0"><True/>
              <Node id="1" score="{short}">
                <SimplePredicate field="x" operator="greaterThan"
                  value="{thr}"/></Node>
              <Node id="2" score="{long_}"><True/></Node>
            </Node></TreeModel></Segment>"""
    return f"""<PMML version="4.4"><DataDictionary>
      <DataField name="x" optype="continuous" dataType="double"/>
      <DataField name="s" optype="continuous" dataType="double"/>
      </DataDictionary>
      <AnomalyDetectionModel functionName="regression" {algo}>
      <MiningSchema><MiningField name="s" usageType="target"/>
        <MiningField name="x"/></MiningSchema>
      <MiningModel functionName="regression">
        <MiningSchema><MiningField name="s" usageType="target"/>
          <MiningField name="x"/></MiningSchema>
        <Segmentation multipleModelMethod="average">
          {tree(3.0, 2.0, 9.0)}{tree(2.5, 3.0, 8.0)}
        </Segmentation></MiningModel>
      </AnomalyDetectionModel></PMML>"""


class TestAnomalyDetection:
    def test_iforest_normalization_hand_computed(self):
        doc = parse_pmml(_iforest_xml())
        cm = compile_pmml(doc)
        c = iforest_c(256)
        cases = [
            (5.0, (2.0 + 3.0) / 2),   # short paths → anomalous
            (0.0, (9.0 + 8.0) / 2),   # long paths → normal
            (2.7, (9.0 + 3.0) / 2),
        ]
        recs = [{"x": x} for x, _ in cases]
        for (x, mean_path), p in zip(cases, cm.score_records(recs)):
            want = 2.0 ** (-mean_path / c)
            o = evaluate(doc, {"x": x})
            assert o.value == pytest.approx(want, rel=1e-9)
            assert p.score.value == pytest.approx(want, rel=1e-5)
        # shorter mean path ⇒ more anomalous ⇒ higher score
        scores = [evaluate(doc, {"x": x}).value for x, _ in cases]
        assert scores[0] > scores[2] > scores[1]

    def test_other_algorithm_passes_through(self):
        doc = parse_pmml(_iforest_xml(algo='algorithmType="other"'))
        cm = compile_pmml(doc)
        o = evaluate(doc, {"x": 5.0})
        assert o.value == pytest.approx(2.5)  # raw inner average
        p = cm.score_records([{"x": 5.0}])[0]
        assert p.score.value == pytest.approx(2.5, rel=1e-5)

    def test_iforest_requires_sample_data_size(self):
        from flink_jpmml_tpu.utils.exceptions import ModelLoadingException

        with pytest.raises(ModelLoadingException, match="sampleDataSize"):
            parse_pmml(_iforest_xml(algo='algorithmType="iforest"'))

    def test_parity_randomized(self):
        doc = parse_pmml(_iforest_xml())
        cm = compile_pmml(doc)
        rng = np.random.default_rng(0)
        recs = [{"x": float(v)} for v in rng.normal(2.5, 2.0, size=120)]
        for rec, p in zip(recs, cm.score_records(recs)):
            o = evaluate(doc, rec)
            assert p.score.value == pytest.approx(o.value, rel=1e-5), rec


class TestTypedErrors:
    def test_garbage_numeric_attributes_are_loading_errors(self):
        from flink_jpmml_tpu.utils.exceptions import ModelLoadingException

        with pytest.raises(ModelLoadingException, match="not a number"):
            parse_pmml(_iforest_xml(
                algo='algorithmType="iforest" sampleDataSize="lots"'
            ))
        from tests.test_knn import _knn_xml

        bad_k = _knn_xml().replace(
            'numberOfNeighbors="3"', 'numberOfNeighbors="few"'
        )
        with pytest.raises(ModelLoadingException, match="not a number"):
            parse_pmml(bad_k)

    def test_minkowski_nonpositive_p_typed_both_paths(self):
        from flink_jpmml_tpu.utils.exceptions import (
            ModelCompilationException,
        )
        from tests.test_knn import _knn_xml

        doc = parse_pmml(_knn_xml(
            measure='<ComparisonMeasure kind="distance">'
                    '<minkowski p-parameter="0"/></ComparisonMeasure>'
        ))
        with pytest.raises(ModelCompilationException, match="p-parameter"):
            compile_pmml(doc)
        with pytest.raises(ModelCompilationException, match="p-parameter"):
            evaluate(doc, {"u": 0.0, "v": 0.0})

    def test_non_numeric_regression_targets_typed_both_paths(self):
        from flink_jpmml_tpu.utils.exceptions import (
            ModelCompilationException,
        )
        from tests.test_knn import _knn_xml

        xml = _knn_xml(function="regression", target="yv").replace(
            "<yv>1.0</yv>", "<yv>oops</yv>", 1
        )
        doc = parse_pmml(xml)
        with pytest.raises(ModelCompilationException, match="numeric"):
            compile_pmml(doc)
        with pytest.raises(ModelCompilationException, match="numeric"):
            evaluate(doc, {"u": 0.0, "v": 0.0})

    def test_nan_inf_and_fractional_int_attributes_rejected(self):
        from flink_jpmml_tpu.utils.exceptions import ModelLoadingException
        from tests.test_knn import _knn_xml

        for bad in ("NaN", "Infinity", "3.9"):
            with pytest.raises(ModelLoadingException, match="integer"):
                parse_pmml(_iforest_xml(
                    algo=f'algorithmType="iforest" sampleDataSize="{bad}"'
                ))
            with pytest.raises(ModelLoadingException, match="integer"):
                parse_pmml(_knn_xml().replace(
                    'numberOfNeighbors="3"', f'numberOfNeighbors="{bad}"'
                ))
