"""TimeSeriesModel (ExponentialSmoothing, ARIMA): compiled vs oracle vs
hand-computed forecasts."""

import numpy as np
import pytest

from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.pmml import parse_pmml
from flink_jpmml_tpu.pmml.interp import evaluate
from flink_jpmml_tpu.utils.exceptions import ModelLoadingException

TS = """<PMML version="4.3"><DataDictionary>
  <DataField name="h" optype="continuous" dataType="integer"/>
  <DataField name="sales" optype="continuous" dataType="double"/>
  </DataDictionary>
  <TimeSeriesModel functionName="timeSeries" bestFit="ExponentialSmoothing">
  <MiningSchema><MiningField name="sales" usageType="target"/>
    <MiningField name="h"/></MiningSchema>
  <ExponentialSmoothing>
    <Level alpha="0.3" smoothedValue="120.5"/>
    {trend}
    {seasonal}
  </ExponentialSmoothing>
  </TimeSeriesModel></PMML>"""

TREND_ADD = '<Trend_ExpoSmooth trend="additive" gamma="0.1" smoothedValue="2.5"/>'
TREND_DAMPED = (
    '<Trend_ExpoSmooth trend="damped_additive" gamma="0.1" '
    'smoothedValue="2.5" phi="0.8"/>'
)
TREND_MUL = (
    '<Trend_ExpoSmooth trend="multiplicative" gamma="0.1" '
    'smoothedValue="1.03"/>'
)
TREND_DAMPED_MUL = (
    '<Trend_ExpoSmooth trend="damped_multiplicative" gamma="0.1" '
    'smoothedValue="1.03" phi="0.8"/>'
)
SEASONAL_ADD = (
    '<Seasonality_ExpoSmooth type="additive" period="4" gamma="0.2">'
    '<Array n="4" type="real">5.0 -3.0 1.5 -3.5</Array>'
    "</Seasonality_ExpoSmooth>"
)
SEASONAL_MUL = (
    '<Seasonality_ExpoSmooth type="multiplicative" period="4" gamma="0.2">'
    '<Array n="4" type="real">1.1 0.9 1.05 0.95</Array>'
    "</Seasonality_ExpoSmooth>"
)


def _hand(h, trend="none", seasonal="none"):
    y = 120.5
    if trend == "additive":
        y += h * 2.5
    elif trend == "damped":
        y += 2.5 * sum(0.8 ** i for i in range(1, h + 1))
    elif trend == "mul":
        y *= 1.03 ** h
    elif trend == "damped_mul":
        y *= 1.03 ** sum(0.8 ** i for i in range(1, h + 1))
    if seasonal == "add":
        y += [5.0, -3.0, 1.5, -3.5][(h - 1) % 4]
    elif seasonal == "mul":
        y *= [1.1, 0.9, 1.05, 0.95][(h - 1) % 4]
    return y


class TestExponentialSmoothing:
    @pytest.mark.parametrize(
        "trend_xml,seasonal_xml,trend,seasonal",
        [
            ("", "", "none", "none"),
            (TREND_ADD, "", "additive", "none"),
            (TREND_DAMPED, "", "damped", "none"),
            (TREND_ADD, SEASONAL_ADD, "additive", "add"),
            (TREND_DAMPED, SEASONAL_MUL, "damped", "mul"),
            (TREND_MUL, "", "mul", "none"),
            (TREND_MUL, SEASONAL_ADD, "mul", "add"),
            (TREND_DAMPED_MUL, SEASONAL_MUL, "damped_mul", "mul"),
        ],
    )
    def test_forecast_parity(self, trend_xml, seasonal_xml, trend, seasonal):
        doc = parse_pmml(TS.format(trend=trend_xml, seasonal=seasonal_xml))
        cm = compile_pmml(doc)
        hs = [1, 2, 3, 4, 5, 9, 13]
        preds = cm.score_records([{"h": h} for h in hs])
        for h, p in zip(hs, preds):
            hand = _hand(h, trend, seasonal)
            o = evaluate(doc, {"h": h})
            assert o.value == pytest.approx(hand, rel=1e-12)
            assert p.score.value == pytest.approx(hand, rel=1e-5)

    def test_horizon_rounding_and_floor(self):
        doc = parse_pmml(TS.format(trend=TREND_ADD, seasonal=""))
        cm = compile_pmml(doc)
        # fractional horizons round; nonpositive clamp to 1
        for hv, h in ((2.4, 2), (2.6, 3), (0.0, 1), (-5.0, 1)):
            p = cm.score_records([{"h": hv}])[0]
            assert p.score.value == pytest.approx(_hand(h, "additive"))
            assert evaluate(doc, {"h": hv}).value == pytest.approx(
                _hand(h, "additive")
            )

    def test_missing_horizon_empty(self):
        doc = parse_pmml(TS.format(trend="", seasonal=""))
        cm = compile_pmml(doc)
        assert cm.score_records([{"h": None}])[0].is_empty
        assert evaluate(doc, {"h": None}).value is None

    def test_multiplicative_trend_huge_horizon_total(self):
        # 1.03^30000 overflows float: the oracle must agree with the
        # compiled f32 inf instead of raising out of the hot path
        doc = parse_pmml(TS.format(trend=TREND_MUL, seasonal=""))
        cm = compile_pmml(doc)
        o = evaluate(doc, {"h": 30000}).value
        g = cm.score_records([{"h": 30000}])[0].score.value
        assert o == float("inf") and np.isinf(g) and g > 0

    def test_multiplicative_trend_zero_level_total(self):
        # level == 0: the forecast is 0 for every horizon, including
        # horizons where trend^h overflows — the compiled path must not
        # produce 0 · inf = NaN where the oracle keeps 0 (backend
        # parity in the exact corner the overflow handling covers)
        xml = TS.format(trend=TREND_MUL, seasonal="").replace(
            'smoothedValue="120.5"', 'smoothedValue="0.0"'
        )
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        for h in (3, 30000):
            o = evaluate(doc, {"h": h}).value
            g = cm.score_records([{"h": h}])[0].score.value
            assert o == 0.0
            assert g == 0.0

    def test_damped_multiplicative_zero_level_total(self):
        xml = TS.format(trend=TREND_DAMPED_MUL, seasonal="").replace(
            'smoothedValue="120.5"', 'smoothedValue="0.0"'
        )
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        o = evaluate(doc, {"h": 5}).value
        g = cm.score_records([{"h": 5}])[0].score.value
        assert o == 0.0 and g == 0.0

    def test_legacy_damped_trend_alias(self):
        # pre-spec spelling accepted and normalized to damped_additive
        legacy = TREND_DAMPED.replace("damped_additive", "damped_trend")
        doc = parse_pmml(TS.format(trend=legacy, seasonal=""))
        assert doc.model.smoothing.trend_type == "damped_additive"
        assert evaluate(doc, {"h": 3}).value == pytest.approx(
            _hand(3, "damped")
        )

    def test_rejections(self):
        with pytest.raises(ModelLoadingException):
            parse_pmml(TS.format(trend="", seasonal="").replace(
                'bestFit="ExponentialSmoothing"', 'bestFit="ARIMA"'
            ))
        # polynomial_exponential is not supported
        with pytest.raises(ModelLoadingException, match="trend"):
            parse_pmml(TS.format(
                trend=TREND_ADD.replace("additive", "polynomial_exponential"),
                seasonal="",
            ))
        # multiplicative trends need a positive base
        with pytest.raises(ModelLoadingException, match="smoothedValue > 0"):
            parse_pmml(TS.format(
                trend=TREND_MUL.replace('smoothedValue="1.03"',
                                        'smoothedValue="-1.0"'),
                seasonal="",
            ))
        with pytest.raises(ModelLoadingException):
            parse_pmml(TS.format(
                trend=TREND_DAMPED.replace('phi="0.8"', 'phi="1.5"'),
                seasonal="",
            ))
        with pytest.raises(ModelLoadingException):
            parse_pmml(TS.format(
                trend="",
                seasonal=SEASONAL_ADD.replace('period="4"', 'period="3"'),
            ))


# ---------------------------------------------------------------------------
# ARIMA (PMML 4.4 <ARIMA>, conditionalLeastSquares)
# ---------------------------------------------------------------------------


def _arima_xml(body, history, constant=0.0, transformation="none",
               extra_attrs=""):
    tv = "".join(
        f'<TimeValue index="{i + 1}" value="{v}"/>'
        for i, v in enumerate(history)
    )
    return f"""<PMML version="4.4"><DataDictionary>
  <DataField name="h" optype="continuous" dataType="integer"/>
  <DataField name="y" optype="continuous" dataType="double"/>
  </DataDictionary>
  <TimeSeriesModel functionName="timeSeries" bestFit="ARIMA">
  <MiningSchema><MiningField name="y" usageType="target"/>
    <MiningField name="h"/></MiningSchema>
  <TimeSeries usage="original">{tv}</TimeSeries>
  <ARIMA constantTerm="{constant}" transformation="{transformation}"
      predictionMethod="conditionalLeastSquares"{extra_attrs}>
  {body}
  </ARIMA>
  </TimeSeriesModel></PMML>"""


def _ns(p, d, q, ar=(), ma=(), residuals=()):
    parts = [f'<NonseasonalComponent p="{p}" d="{d}" q="{q}">']
    if ar:
        parts.append(
            f'<AR><Array type="real" n="{len(ar)}">'
            + " ".join(map(str, ar)) + "</Array></AR>"
        )
    if ma or residuals:
        parts.append("<MA>")
        if ma:
            parts.append(
                f'<MACoefficients><Array type="real" n="{len(ma)}">'
                + " ".join(map(str, ma)) + "</Array></MACoefficients>"
            )
        if residuals:
            parts.append(
                f'<Residuals><Array type="real" n="{len(residuals)}">'
                + " ".join(map(str, residuals)) + "</Array></Residuals>"
            )
        parts.append("</MA>")
    parts.append("</NonseasonalComponent>")
    return "".join(parts)


def _sc(P, D, Q, period, sar=(), sma=(), residuals=()):
    parts = [
        f'<SeasonalComponent P="{P}" D="{D}" Q="{Q}" period="{period}">'
    ]
    if sar:
        parts.append(
            f'<AR><Array type="real" n="{len(sar)}">'
            + " ".join(map(str, sar)) + "</Array></AR>"
        )
    if sma or residuals:
        parts.append("<MA>")
        if sma:
            parts.append(
                f'<MACoefficients><Array type="real" n="{len(sma)}">'
                + " ".join(map(str, sma)) + "</Array></MACoefficients>"
            )
        if residuals:
            parts.append(
                f'<Residuals><Array type="real" n="{len(residuals)}">'
                + " ".join(map(str, residuals)) + "</Array></Residuals>"
            )
        parts.append("</MA>")
    parts.append("</SeasonalComponent>")
    return "".join(parts)


HIST8 = (10.0, 11.0, 9.5, 12.0, 11.5, 10.5, 12.5, 13.0)


def _both(doc, cm, h):
    """(oracle value, compiled value) at horizon h."""
    o = evaluate(doc, {"h": h}).value
    c = cm.score_records([{"h": h}])[0].score.value
    return o, c


class TestArima:
    def test_ar1_closed_form(self):
        phi, c, yT = 0.6, 0.5, HIST8[-1]
        doc = parse_pmml(_arima_xml(
            _ns(1, 0, 0, ar=(phi,)), HIST8, constant=c
        ))
        cm = compile_pmml(doc)
        for h in (1, 2, 3, 7):
            hand = c * sum(phi ** i for i in range(h)) + phi ** h * yT
            o, g = _both(doc, cm, h)
            assert o == pytest.approx(hand, rel=1e-12)
            assert g == pytest.approx(hand, rel=1e-5)

    def test_ma1_closed_form(self):
        theta, c, aT = 0.4, 2.0, 0.8
        doc = parse_pmml(_arima_xml(
            _ns(0, 0, 1, ma=(theta,), residuals=(0.1, aT)), HIST8,
            constant=c,
        ))
        cm = compile_pmml(doc)
        o, g = _both(doc, cm, 1)
        # spec sign convention: θ(B) = 1 − θB ⇒ MA terms subtract
        assert o == pytest.approx(c - theta * aT, rel=1e-12)
        assert g == pytest.approx(c - theta * aT, rel=1e-5)
        for h in (2, 3, 9):
            o, g = _both(doc, cm, h)
            assert o == pytest.approx(c, rel=1e-12)
            assert g == pytest.approx(c, rel=1e-5)

    def test_arima_011_drift_closed_form(self):
        theta, c, aT = 0.3, 0.25, -0.6
        yT = HIST8[-1]
        doc = parse_pmml(_arima_xml(
            _ns(0, 1, 1, ma=(theta,), residuals=(aT,)), HIST8, constant=c
        ))
        cm = compile_pmml(doc)
        for h in (1, 2, 5):
            hand = yT + (c - theta * aT) + (h - 1) * c
            o, g = _both(doc, cm, h)
            assert o == pytest.approx(hand, rel=1e-12)
            assert g == pytest.approx(hand, rel=1e-4)

    def test_seasonal_ar_closed_form(self):
        # SARIMA(0,0,0)(1,0,0)_4: ŷ(h) = c + Φ·ỹ(T+h−4)
        big_phi, c = 0.5, 1.0
        doc = parse_pmml(_arima_xml(
            _sc(1, 0, 0, 4, sar=(big_phi,)), HIST8, constant=c
        ))
        cm = compile_pmml(doc)
        expect = list(HIST8)
        for _ in range(6):
            expect.append(c + big_phi * expect[-4])
        for h in (1, 2, 4, 5, 6):
            hand = expect[len(HIST8) + h - 1]
            o, g = _both(doc, cm, h)
            assert o == pytest.approx(hand, rel=1e-12)
            assert g == pytest.approx(hand, rel=1e-5)

    def test_seasonal_difference_drift(self):
        # (0,0,0)(0,1,0)_4 with constant: ŷ(h) = ỹ(T+h−4) + c
        c = 0.75
        doc = parse_pmml(_arima_xml(
            _sc(0, 1, 0, 4), HIST8, constant=c
        ))
        cm = compile_pmml(doc)
        expect = list(HIST8)
        for _ in range(9):
            expect.append(expect[-4] + c)
        for h in (1, 3, 4, 5, 8, 9):
            hand = expect[len(HIST8) + h - 1]
            o, g = _both(doc, cm, h)
            assert o == pytest.approx(hand, rel=1e-12)
            assert g == pytest.approx(hand, rel=1e-4)

    def test_log_transformation(self):
        import math

        phi = 0.7
        doc = parse_pmml(_arima_xml(
            _ns(1, 0, 0, ar=(phi,)), HIST8, transformation="logarithmic"
        ))
        cm = compile_pmml(doc)
        zT = math.log(HIST8[-1])
        for h in (1, 2, 4):
            hand = math.exp(phi ** h * zT)
            o, g = _both(doc, cm, h)
            assert o == pytest.approx(hand, rel=1e-12)
            assert g == pytest.approx(hand, rel=1e-4)

    def test_full_sarima_oracle_vs_compiled(self):
        # SARIMA(2,1,1)(1,1,1)_4 — no closed form; the two independent
        # implementations (opposite differencing composition orders)
        # must agree over a horizon sweep
        rng = np.random.default_rng(7)
        hist = tuple(
            round(50 + 2 * t + 5 * np.sin(t * np.pi / 2) + v, 3)
            for t, v in enumerate(rng.normal(0, 0.5, size=24))
        )
        doc = parse_pmml(_arima_xml(
            _ns(2, 1, 1, ar=(0.45, -0.2), ma=(0.3,), residuals=(0.2, -0.1))
            + _sc(1, 1, 1, 4, sar=(0.35,), sma=(0.25,),
                  residuals=(0.1, -0.2, 0.15, 0.05, 0.2, -0.1)),
            hist, constant=0.1,
        ))
        cm = compile_pmml(doc)
        hs = list(range(1, 41))
        preds = cm.score_records([{"h": h} for h in hs])
        for h, p in zip(hs, preds):
            o = evaluate(doc, {"h": h}).value
            assert p.score.value == pytest.approx(o, rel=2e-4, abs=1e-3)

    def test_horizon_clamp_and_missing(self):
        from flink_jpmml_tpu.pmml.ir import ARIMA_H_MAX

        doc = parse_pmml(_arima_xml(_ns(1, 0, 0, ar=(0.9,)), HIST8))
        cm = compile_pmml(doc)
        o_big = evaluate(doc, {"h": ARIMA_H_MAX + 50}).value
        o_max = evaluate(doc, {"h": ARIMA_H_MAX}).value
        assert o_big == o_max
        g = cm.score_records([{"h": ARIMA_H_MAX + 50}])[0].score.value
        assert g == pytest.approx(o_max, abs=1e-6)
        assert cm.score_records([{"h": None}])[0].is_empty
        assert evaluate(doc, {"h": None}).value is None

    def test_explosive_log_forecast_is_total(self):
        # an AR polynomial outside the unit circle on the log scale
        # overflows exp at deep horizons: both paths must stay total and
        # agree on +inf — the hot path never raises (C5)
        doc = parse_pmml(_arima_xml(
            _ns(1, 0, 0, ar=(1.5,)), HIST8, transformation="logarithmic"
        ))
        cm = compile_pmml(doc)
        o = evaluate(doc, {"h": 60}).value
        g = cm.score_records([{"h": 60}])[0].score.value
        assert o == float("inf") and np.isinf(g) and g > 0

    def test_rejections(self):
        # exactLeastSquares is out of scope (documented)
        with pytest.raises(ModelLoadingException, match="predictionMethod"):
            parse_pmml(_arima_xml(
                _ns(1, 0, 0, ar=(0.5,)), HIST8
            ).replace("conditionalLeastSquares", "exactLeastSquares"))
        # AR terms but no history
        with pytest.raises(ModelLoadingException, match="observed series"):
            parse_pmml(_arima_xml(_ns(1, 0, 0, ar=(0.5,)), ()))
        # MA reach exceeds residuals
        with pytest.raises(ModelLoadingException, match="residuals"):
            parse_pmml(_arima_xml(
                _ns(0, 0, 2, ma=(0.3, 0.2), residuals=(0.5,)), HIST8
            ))
        # coefficient count must match declared order
        with pytest.raises(ModelLoadingException, match="coefficients"):
            parse_pmml(_arima_xml(_ns(2, 0, 0, ar=(0.5,)), HIST8))
        # log transform needs a positive series
        with pytest.raises(ModelLoadingException, match="positive"):
            parse_pmml(_arima_xml(
                _ns(1, 0, 0, ar=(0.5,)), (1.0, -2.0, 3.0, 4.0),
                transformation="logarithmic",
            ))
        # DynamicRegressor terms are rejected, not ignored
        with pytest.raises(ModelLoadingException, match="DynamicRegressor"):
            parse_pmml(_arima_xml(
                '<DynamicRegressor field="x"/>' + _ns(1, 0, 0, ar=(0.5,)),
                HIST8,
            ))

    def test_conflicting_residuals_rejected(self):
        # NonseasonalComponent.MA and SeasonalComponent.MA both carrying
        # <Residuals> that disagree on their overlap is ambiguous:
        # forecasting from an arbitrarily-chosen history would be
        # silent corruption (the shorter must be a trailing window —
        # a suffix — of the longer)
        with pytest.raises(ModelLoadingException, match="ambiguous"):
            parse_pmml(_arima_xml(
                _ns(0, 0, 1, ma=(0.3,), residuals=(0.5, 0.6))
                + _sc(0, 0, 1, 4, sma=(0.2,),
                      residuals=(0.1, 0.2, 0.3, 0.4, 0.5)),
                HIST8,
            ))

    def test_suffix_residuals_accepted(self):
        # each component carrying a trailing window of the ONE residual
        # history (sized to its own MA reach) is consistent: the longer
        # window wins, the shorter must be its suffix
        long_res = (0.1, 0.2, 0.3, 0.4, 0.5)
        doc = parse_pmml(_arima_xml(
            _ns(0, 0, 1, ma=(0.3,), residuals=long_res[-2:])
            + _sc(0, 0, 1, 4, sma=(0.2,), residuals=long_res),
            HIST8,
        ))
        assert tuple(doc.model.arima.residuals) == long_res
