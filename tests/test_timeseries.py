"""TimeSeriesModel (ExponentialSmoothing): compiled vs oracle vs
hand-computed Holt-Winters forecasts."""

import numpy as np
import pytest

from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.pmml import parse_pmml
from flink_jpmml_tpu.pmml.interp import evaluate
from flink_jpmml_tpu.utils.exceptions import ModelLoadingException

TS = """<PMML version="4.3"><DataDictionary>
  <DataField name="h" optype="continuous" dataType="integer"/>
  <DataField name="sales" optype="continuous" dataType="double"/>
  </DataDictionary>
  <TimeSeriesModel functionName="timeSeries" bestFit="ExponentialSmoothing">
  <MiningSchema><MiningField name="sales" usageType="target"/>
    <MiningField name="h"/></MiningSchema>
  <ExponentialSmoothing>
    <Level alpha="0.3" smoothedValue="120.5"/>
    {trend}
    {seasonal}
  </ExponentialSmoothing>
  </TimeSeriesModel></PMML>"""

TREND_ADD = '<Trend_ExpoSmooth trend="additive" gamma="0.1" smoothedValue="2.5"/>'
TREND_DAMPED = (
    '<Trend_ExpoSmooth trend="damped_trend" gamma="0.1" smoothedValue="2.5" '
    'phi="0.8"/>'
)
SEASONAL_ADD = (
    '<Seasonality_ExpoSmooth type="additive" period="4" gamma="0.2">'
    '<Array n="4" type="real">5.0 -3.0 1.5 -3.5</Array>'
    "</Seasonality_ExpoSmooth>"
)
SEASONAL_MUL = (
    '<Seasonality_ExpoSmooth type="multiplicative" period="4" gamma="0.2">'
    '<Array n="4" type="real">1.1 0.9 1.05 0.95</Array>'
    "</Seasonality_ExpoSmooth>"
)


def _hand(h, trend="none", seasonal="none"):
    y = 120.5
    if trend == "additive":
        y += h * 2.5
    elif trend == "damped":
        y += 2.5 * sum(0.8 ** i for i in range(1, h + 1))
    if seasonal == "add":
        y += [5.0, -3.0, 1.5, -3.5][(h - 1) % 4]
    elif seasonal == "mul":
        y *= [1.1, 0.9, 1.05, 0.95][(h - 1) % 4]
    return y


class TestExponentialSmoothing:
    @pytest.mark.parametrize(
        "trend_xml,seasonal_xml,trend,seasonal",
        [
            ("", "", "none", "none"),
            (TREND_ADD, "", "additive", "none"),
            (TREND_DAMPED, "", "damped", "none"),
            (TREND_ADD, SEASONAL_ADD, "additive", "add"),
            (TREND_DAMPED, SEASONAL_MUL, "damped", "mul"),
        ],
    )
    def test_forecast_parity(self, trend_xml, seasonal_xml, trend, seasonal):
        doc = parse_pmml(TS.format(trend=trend_xml, seasonal=seasonal_xml))
        cm = compile_pmml(doc)
        hs = [1, 2, 3, 4, 5, 9, 13]
        preds = cm.score_records([{"h": h} for h in hs])
        for h, p in zip(hs, preds):
            hand = _hand(h, trend, seasonal)
            o = evaluate(doc, {"h": h})
            assert o.value == pytest.approx(hand, rel=1e-12)
            assert p.score.value == pytest.approx(hand, rel=1e-5)

    def test_horizon_rounding_and_floor(self):
        doc = parse_pmml(TS.format(trend=TREND_ADD, seasonal=""))
        cm = compile_pmml(doc)
        # fractional horizons round; nonpositive clamp to 1
        for hv, h in ((2.4, 2), (2.6, 3), (0.0, 1), (-5.0, 1)):
            p = cm.score_records([{"h": hv}])[0]
            assert p.score.value == pytest.approx(_hand(h, "additive"))
            assert evaluate(doc, {"h": hv}).value == pytest.approx(
                _hand(h, "additive")
            )

    def test_missing_horizon_empty(self):
        doc = parse_pmml(TS.format(trend="", seasonal=""))
        cm = compile_pmml(doc)
        assert cm.score_records([{"h": None}])[0].is_empty
        assert evaluate(doc, {"h": None}).value is None

    def test_rejections(self):
        with pytest.raises(ModelLoadingException):
            parse_pmml(TS.format(trend="", seasonal="").replace(
                'bestFit="ExponentialSmoothing"', 'bestFit="ARIMA"'
            ))
        with pytest.raises(ModelLoadingException):
            parse_pmml(TS.format(
                trend=TREND_DAMPED.replace('phi="0.8"', 'phi="1.5"'),
                seasonal="",
            ))
        with pytest.raises(ModelLoadingException):
            parse_pmml(TS.format(
                trend="",
                seasonal=SEASONAL_ADD.replace('period="4"', 'period="3"'),
            ))
