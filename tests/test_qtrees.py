"""Quantized rank-wire fast path (qtrees.py) vs the f32 path and oracle.

The wire must be *bit-exact* on split decisions (integer rank compares
reproduce the float compares) — only the final leaf-value contraction uses
a bf16 hi+lo split, so values match the f32 path to ~1e-4 relative.
"""

import tempfile
import xml.etree.ElementTree as ET

import numpy as np
import pytest

from assets.generate import gen_gbm
from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.compile.qtrees import build_quantized_scorer
from flink_jpmml_tpu.pmml import parse_pmml, parse_pmml_file
from flink_jpmml_tpu.pmml.interp import evaluate


def _gbm(tmp_path, **kw):
    path = gen_gbm(str(tmp_path), n_trees=kw.pop("n_trees", 40),
                   depth=kw.pop("depth", 4), n_features=kw.pop("n_features", 8),
                   **kw)
    return parse_pmml_file(path)


def _rand_X(rng, n, F, missing_rate=0.0):
    X = rng.normal(0.0, 1.5, size=(n, F)).astype(np.float32)
    if missing_rate:
        X[rng.random(size=X.shape) < missing_rate] = np.nan
    return X


def _parity(doc, X, rtol=1e-4, atol=1e-5):
    cm = compile_pmml(doc)
    q = cm.quantized_scorer()
    assert q is not None
    M = np.isnan(X)
    Xf = np.nan_to_num(X, nan=0.0)
    ref = np.asarray(cm.predict(Xf, M).value, np.float32)
    got = np.asarray(q.predict_wire(q.wire.encode(X)), np.float32)
    np.testing.assert_allclose(got, ref, rtol=rtol, atol=atol)
    return cm, q


class TestEligibility:
    def test_hist_gbm_gets_u8_wire(self, tmp_path):
        doc = _gbm(tmp_path)
        q = build_quantized_scorer(doc)
        assert q is not None
        assert q.wire.dtype is np.uint8
        assert q.wire.bytes_per_record == 8  # 8 features x u8

    def test_continuous_thresholds_still_eligible(self, tmp_path):
        # 40 trees x 15 splits over 8 features ≈ 75 cuts/feature < 254
        doc = _gbm(tmp_path, hist_bins=None)
        q = build_quantized_scorer(doc)
        assert q is not None and q.wire.dtype is np.uint8

    def test_u16_fallback_when_over_254_cuts(self, tmp_path):
        # 300 deep trees on 2 features → >254 distinct cuts per feature
        doc = _gbm(tmp_path, n_trees=300, depth=5, n_features=2,
                   hist_bins=None)
        q = build_quantized_scorer(doc)
        assert q is not None
        assert q.wire.dtype is np.uint16
        rng = np.random.default_rng(3)
        _parity(doc, _rand_X(rng, 64, 2, missing_rate=0.1))

    def test_halting_strategy_probe_returns_none(self):
        # missingValueStrategy=lastPrediction needs the iterative f32
        # backend; the probe must degrade to None, never raise (a raise
        # here used to crash StaticScorer/DynamicScorer pipelines)
        xml = """<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3">
          <Header/>
          <DataDictionary numberOfFields="2">
            <DataField name="a" optype="continuous" dataType="double"/>
            <DataField name="y" optype="continuous" dataType="double"/>
          </DataDictionary>
          <MiningModel functionName="regression">
            <MiningSchema>
              <MiningField name="y" usageType="target"/>
              <MiningField name="a"/>
            </MiningSchema>
            <Segmentation multipleModelMethod="sum">
              <Segment><True/>
                <TreeModel functionName="regression"
                           missingValueStrategy="lastPrediction">
                  <MiningSchema>
                    <MiningField name="y" usageType="target"/>
                    <MiningField name="a"/>
                  </MiningSchema>
                  <Node score="0.5"><True/>
                    <Node score="1.0">
                      <SimplePredicate field="a" operator="lessThan" value="0"/>
                    </Node>
                    <Node score="2.0">
                      <SimplePredicate field="a" operator="greaterOrEqual" value="0"/>
                    </Node>
                  </Node>
                </TreeModel>
              </Segment>
            </Segmentation>
          </MiningModel></PMML>"""
        doc = parse_pmml(xml)
        assert build_quantized_scorer(doc) is None
        cm = compile_pmml(doc)
        assert cm.quantized_scorer() is None  # guarded probe, no raise
        # and the f32 path still scores it (incl. the halt semantics)
        [pred] = cm.score_records([{"a": 1.0}])
        assert pred.score.value == pytest.approx(2.0)
        [pred] = cm.score_records([{}])
        assert pred.score.value == pytest.approx(0.5)

    def test_classification_not_eligible(self):
        xml = """<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3">
          <Header/>
          <DataDictionary numberOfFields="2">
            <DataField name="a" optype="continuous" dataType="double"/>
            <DataField name="y" optype="categorical" dataType="string">
              <Value value="p"/><Value value="q"/></DataField>
          </DataDictionary>
          <TreeModel functionName="classification" splitCharacteristic="binarySplit">
            <MiningSchema>
              <MiningField name="y" usageType="target"/>
              <MiningField name="a"/>
            </MiningSchema>
            <Node id="0"><True/>
              <Node id="1" score="p"><SimplePredicate field="a" operator="lessThan" value="0"/></Node>
              <Node id="2" score="q"><SimplePredicate field="a" operator="greaterOrEqual" value="0"/></Node>
            </Node>
          </TreeModel></PMML>"""
        assert build_quantized_scorer(parse_pmml(xml)) is None


class TestParity:
    def test_clean_batch_matches_f32_path(self, tmp_path):
        doc = _gbm(tmp_path, n_trees=60, depth=6, n_features=16)
        rng = np.random.default_rng(0)
        _parity(doc, _rand_X(rng, 256, 16))

    def test_missing_values_follow_default_child(self, tmp_path):
        doc = _gbm(tmp_path)
        rng = np.random.default_rng(1)
        _parity(doc, _rand_X(rng, 256, 8, missing_rate=0.25))

    def test_values_on_exact_thresholds(self, tmp_path):
        # records sitting exactly on cut values — the strict/inclusive
        # boundary handling must match the float comparisons bit-for-bit
        doc = _gbm(tmp_path, n_trees=30)
        cm = compile_pmml(doc)
        q = cm.quantized_scorer()
        cuts = np.concatenate([c for c in q.wire.cuts if len(c)])
        rng = np.random.default_rng(2)
        X = rng.choice(cuts, size=(512, 8)).astype(np.float32)
        M = np.zeros(X.shape, bool)
        ref = np.asarray(cm.predict(X, M).value, np.float32)
        got = np.asarray(q.predict_wire(q.wire.encode(X)), np.float32)
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

    def test_matches_oracle_interpreter(self, tmp_path):
        doc = _gbm(tmp_path, n_trees=12, depth=3, n_features=4)
        q = build_quantized_scorer(doc)
        rng = np.random.default_rng(4)
        X = _rand_X(rng, 16, 4, missing_rate=0.2)
        got = np.asarray(q.predict_wire(q.wire.encode(X)), np.float32)
        fields = doc.active_fields
        for i in range(X.shape[0]):
            rec = {
                f: float(X[i, j])
                for j, f in enumerate(fields)
                if not np.isnan(X[i, j])
            }
            exp = evaluate(doc, rec)
            np.testing.assert_allclose(
                got[i], float(exp.value), rtol=1e-4, atol=1e-5
            )

    def test_all_four_operators(self):
        # one tree per comparison operator, summed
        def tree(op, thr):
            return f"""<Segment><True/>
              <TreeModel functionName="regression" missingValueStrategy="defaultChild" splitCharacteristic="binarySplit">
                <MiningSchema><MiningField name="y" usageType="target"/><MiningField name="a"/></MiningSchema>
                <Node id="0" defaultChild="1"><True/>
                  <Node id="1" score="1.5"><SimplePredicate field="a" operator="{op}" value="{thr}"/></Node>
                  <Node id="2" score="-2.5"><True/></Node>
                </Node>
              </TreeModel></Segment>"""

        xml = f"""<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3">
          <Header/>
          <DataDictionary numberOfFields="2">
            <DataField name="a" optype="continuous" dataType="double"/>
            <DataField name="y" optype="continuous" dataType="double"/>
          </DataDictionary>
          <MiningModel functionName="regression">
            <MiningSchema>
              <MiningField name="y" usageType="target"/>
              <MiningField name="a"/>
            </MiningSchema>
            <Segmentation multipleModelMethod="sum">
              {tree('lessThan', 0.5)}{tree('lessOrEqual', 0.5)}
              {tree('greaterThan', -0.25)}{tree('greaterOrEqual', -0.25)}
            </Segmentation>
          </MiningModel></PMML>"""
        doc = parse_pmml(xml)
        X = np.array(
            [[0.5], [0.49999997], [0.50000006], [-0.25], [-0.2500001],
             [-0.24999999], [0.0], [np.nan]],
            np.float32,
        )
        _parity(doc, X)

    def test_weighted_average_and_average(self, tmp_path):
        for method, wattr in (("average", ""), ("weightedAverage", "")):
            doc = _gbm(tmp_path, n_trees=10, name=f"m_{method}.pmml")
            # rewrite the segmentation method (+ weights for weightedAverage)
            import xml.etree.ElementTree as ET  # noqa: PLC0415

            ns = "http://www.dmg.org/PMML-4_3"
            t = ET.parse(f"{tmp_path}/m_{method}.pmml")
            seg = t.getroot().find(f".//{{{ns}}}Segmentation")
            seg.set("multipleModelMethod", method)
            if method == "weightedAverage":
                for k, s in enumerate(seg.findall(f"{{{ns}}}Segment")):
                    s.set("weight", str(0.5 + 0.1 * k))
            out = f"{tmp_path}/m2_{method}.pmml"
            t.write(out)
            doc = parse_pmml_file(out)
            rng = np.random.default_rng(5)
            _parity(doc, _rand_X(rng, 128, 8, missing_rate=0.1))


class TestWireFormat:
    def test_sentinel_reserved(self, tmp_path):
        doc = _gbm(tmp_path)
        q = build_quantized_scorer(doc)
        X = _rand_X(np.random.default_rng(6), 64, 8, missing_rate=0.3)
        Xq = q.wire.encode(X)
        assert Xq[np.isnan(X)].min() == q.wire.sentinel
        assert (Xq[~np.isnan(X)] < q.wire.sentinel).all()

    def test_explicit_mask_marks_missing(self, tmp_path):
        doc = _gbm(tmp_path)
        q = build_quantized_scorer(doc)
        X = np.zeros((4, 8), np.float32)
        M = np.zeros((4, 8), bool)
        M[0, 0] = True
        Xq = q.wire.encode(X, M)
        assert Xq[0, 0] == q.wire.sentinel and Xq[1, 0] != q.wire.sentinel

    def test_score_decodes_predictions(self, tmp_path):
        doc = _gbm(tmp_path)
        cm = compile_pmml(doc)
        q = cm.quantized_scorer()
        X = _rand_X(np.random.default_rng(7), 10, 8)
        preds = q.score(X)
        assert len(preds) == 10
        ref = cm.score_dense(X)
        for a, b in zip(preds, ref):
            assert abs(a.score.value - b.score.value) < 1e-3


class TestNativeBucketizer:
    """The lockstep pow2 kernel (the ONE native encode path) vs the numpy
    searchsorted reference — the same parity the fallback in
    QuantizedWire.encode guarantees."""

    @staticmethod
    def _numpy_ref(w, X, M=None):
        Xr = np.asarray(X, np.float32)
        miss = np.isnan(Xr)
        if M is not None:
            miss = miss | M
        if w.has_repl.any():
            use = miss & w.has_repl[None, :]
            Xr = np.where(use, w.repl[None, :], Xr)
            miss = miss & ~w.has_repl[None, :]
        ref = np.empty(Xr.shape, w.dtype)
        for j, cuts in enumerate(w.cuts):
            ref[:, j] = np.searchsorted(cuts, Xr[:, j], side="left")
        ref[miss] = w.sentinel
        return ref

    def test_native_matches_numpy(self, tmp_path):
        from flink_jpmml_tpu.runtime import native

        if not native.available():
            pytest.skip(f"native plane unavailable: {native.build_error()}")
        doc = _gbm(tmp_path, n_trees=30, depth=5, n_features=12)
        q = build_quantized_scorer(doc)
        w = q.wire
        rng = np.random.default_rng(8)
        X = _rand_X(rng, 4096, 12, missing_rate=0.15)
        # edge rows: exact cut hits, +/-inf, all-NaN
        X[0, :] = [w.cuts[j][0] if len(w.cuts[j]) else 0.0 for j in range(12)]
        X[1, :] = np.inf
        X[2, :] = -np.inf
        X[3, :] = np.nan
        padded, L = w._pow2_tables()
        assert L & (L - 1) == 0  # power of two
        got = native.bucketize_pow2(
            X, padded, L, w.repl, w.has_repl.astype(np.uint8), w.dtype
        )
        np.testing.assert_array_equal(got, self._numpy_ref(w, X))

    def test_native_randomized_table_shapes(self, tmp_path):
        """Sweep ensemble shapes so L covers several powers of two."""
        from flink_jpmml_tpu.runtime import native

        if not native.available():
            pytest.skip("native plane unavailable")
        rng = np.random.default_rng(11)
        for trees, depth, f in ((1, 2, 3), (5, 3, 4), (60, 6, 6)):
            doc = _gbm(tmp_path, n_trees=trees, depth=depth, n_features=f)
            w = build_quantized_scorer(doc).wire
            X = _rand_X(rng, 512, f, missing_rate=0.2)
            padded, L = w._pow2_tables()
            got = native.bucketize_pow2(
                X, padded, L, w.repl, w.has_repl.astype(np.uint8), w.dtype
            )
            np.testing.assert_array_equal(got, self._numpy_ref(w, X))

    def test_skewed_tables_take_ragged_path(self):
        """One huge cut table among tiny ones: the pow2 dispatch bails
        (padding blowup) and the ragged kernel produces identical ranks."""
        from flink_jpmml_tpu.compile.qtrees import QuantizedWire
        from flink_jpmml_tpu.runtime import native

        if not native.available():
            pytest.skip("native plane unavailable")
        rng = np.random.default_rng(4)
        F = 8
        cuts = (np.sort(rng.normal(0, 5, size=900)).astype(np.float32),) + tuple(
            np.sort(rng.normal(0, 5, size=int(k))).astype(np.float32)
            for k in rng.integers(1, 4, size=F - 1)
        )
        w = QuantizedWire(
            fields=tuple(f"f{i}" for i in range(F)),
            cuts=cuts,
            dtype=np.uint16,
            sentinel=65535,
            repl=np.zeros(F, np.float32),
            has_repl=np.zeros(F, bool),
        )
        padded, L = w._pow2_tables()
        assert padded is None  # skew heuristic chose ragged
        X = rng.normal(0, 5, size=(2048, F)).astype(np.float32)
        X[0, 0] = np.nan
        got = w.encode(X)
        np.testing.assert_array_equal(got, self._numpy_ref(w, X))

    def test_native_mask_and_single_thread(self, tmp_path):
        from flink_jpmml_tpu.runtime import native

        if not native.available():
            pytest.skip("native plane unavailable")
        doc = _gbm(tmp_path)
        q = build_quantized_scorer(doc)
        w = q.wire
        X = np.zeros((8, 8), np.float32)
        M = np.zeros((8, 8), bool)
        M[2, 3] = True
        padded, L = w._pow2_tables()
        got = native.bucketize_pow2(
            X, padded, L, w.repl, w.has_repl.astype(np.uint8), w.dtype,
            mask=M, n_threads=1,
        )
        assert got[2, 3] == w.sentinel
        assert (got[0] != w.sentinel).all()
        np.testing.assert_array_equal(got, self._numpy_ref(w, X, M))


def _forest_xml(method="majorityVote", weighted=False, n_trees=7, seed=21):
    rng = np.random.default_rng(seed)
    segs = []
    for t in range(n_trees):
        w = f' weight="{0.5 + 0.25 * t}"' if weighted else ""
        f1, f2 = rng.integers(0, 4, size=2)
        t1, t2 = rng.normal(0, 1, size=2)
        labs = rng.choice(["p", "q", "r"], size=3)
        segs.append(f"""<Segment{w}><True/>
          <TreeModel functionName="classification" missingValueStrategy="defaultChild" splitCharacteristic="binarySplit">
            <MiningSchema><MiningField name="y" usageType="target"/>
              <MiningField name="f0"/><MiningField name="f1"/>
              <MiningField name="f2"/><MiningField name="f3"/></MiningSchema>
            <Node id="0" defaultChild="1"><True/>
              <Node id="1" defaultChild="3">
                <SimplePredicate field="f{f1}" operator="lessThan" value="{t1:.6f}"/>
                <Node id="3" score="{labs[0]}"><SimplePredicate field="f{f2}" operator="lessThan" value="{t2:.6f}"/></Node>
                <Node id="4" score="{labs[1]}"><SimplePredicate field="f{f2}" operator="greaterOrEqual" value="{t2:.6f}"/></Node>
              </Node>
              <Node id="2" score="{labs[2]}"><SimplePredicate field="f{f1}" operator="greaterOrEqual" value="{t1:.6f}"/></Node>
            </Node>
          </TreeModel></Segment>""")
    return f"""<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3">
      <Header/>
      <DataDictionary numberOfFields="5">
        <DataField name="f0" optype="continuous" dataType="double"/>
        <DataField name="f1" optype="continuous" dataType="double"/>
        <DataField name="f2" optype="continuous" dataType="double"/>
        <DataField name="f3" optype="continuous" dataType="double"/>
        <DataField name="y" optype="categorical" dataType="string">
          <Value value="p"/><Value value="q"/><Value value="r"/></DataField>
      </DataDictionary>
      <MiningModel functionName="classification">
        <MiningSchema><MiningField name="y" usageType="target"/>
          <MiningField name="f0"/><MiningField name="f1"/>
          <MiningField name="f2"/><MiningField name="f3"/></MiningSchema>
        <Segmentation multipleModelMethod="{method}">{''.join(segs)}</Segmentation>
      </MiningModel></PMML>"""


class TestClassificationWire:
    def _parity_cls(self, xml, n=256, missing_rate=0.15, seed=5):
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        q = cm.quantized_scorer()
        assert q is not None and q.is_classification
        rng = np.random.default_rng(seed)
        X = _rand_X(rng, n, 4, missing_rate=missing_rate)
        M = np.isnan(X)
        ref = cm.predict(np.nan_to_num(X, nan=0.0), M)
        got_v, got_p, got_l = q.predict_wire(q.wire.encode(X))
        np.testing.assert_array_equal(
            np.asarray(got_l), np.asarray(ref.label_idx)
        )
        np.testing.assert_allclose(
            np.asarray(got_p), np.asarray(ref.probs), rtol=1e-3, atol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(got_v), np.asarray(ref.value), rtol=1e-3, atol=1e-4
        )

    def test_majority_vote_forest(self):
        self._parity_cls(_forest_xml("majorityVote"))

    def test_weighted_majority_vote(self):
        self._parity_cls(_forest_xml("weightedMajorityVote", weighted=True))

    def test_scorer_decode_labels(self):
        doc = parse_pmml(_forest_xml("majorityVote"))
        q = build_quantized_scorer(doc)
        rng = np.random.default_rng(9)
        X = _rand_X(rng, 16, 4)
        preds = q.score(X)
        cm = compile_pmml(doc)
        exp = cm.score_dense(X)
        for a, b in zip(preds, exp):
            assert a.target.label == b.target.label
            assert abs(a.score.value - b.score.value) < 1e-3
