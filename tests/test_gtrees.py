"""General tree backend (gtrees.py): compound predicates, n-ary nodes,
surrogates, isMissing, non-True roots — all diffed against the oracle.

These are the tree shapes the canonical path-matrix backends reject; the
reference scores them through JPMML-Evaluator's general traversal, so
parity here closes the "real-world R/rpart export" gap.
"""

import itertools

import numpy as np
import pytest

from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.pmml import parse_pmml
from flink_jpmml_tpu.pmml.interp import evaluate

_HDR = """<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3">
  <Header/>
  <DataDictionary numberOfFields="4">
    <DataField name="a" optype="continuous" dataType="double"/>
    <DataField name="b" optype="continuous" dataType="double"/>
    <DataField name="c" optype="continuous" dataType="double"/>
    <DataField name="y" optype="continuous" dataType="double"/>
  </DataDictionary>"""

_SCHEMA = """<MiningSchema>
      <MiningField name="y" usageType="target"/>
      <MiningField name="a"/><MiningField name="b"/><MiningField name="c"/>
    </MiningSchema>"""


def _doc(tree_body, strategy="none", ntc=None):
    ntc_attr = f' noTrueChildStrategy="{ntc}"' if ntc else ""
    return parse_pmml(f"""{_HDR}
  <TreeModel functionName="regression" missingValueStrategy="{strategy}"{ntc_attr}>
    {_SCHEMA}
    {tree_body}
  </TreeModel></PMML>""")


def _grid(missing_too=True):
    vals = [-1.5, -0.25, 0.0, 0.25, 1.5] + ([None] if missing_too else [])
    recs = []
    for a, b, c in itertools.product(vals, vals, vals):
        r = {}
        if a is not None:
            r["a"] = a
        if b is not None:
            r["b"] = b
        if c is not None:
            r["c"] = c
        recs.append(r)
    return recs


def _check(doc, records):
    cm = compile_pmml(doc)
    got = cm.score_records(records)
    for rec, pred in zip(records, got):
        exp = evaluate(doc, rec)
        if exp.value is None:
            assert pred.is_empty, f"{rec}: expected empty, got {pred}"
        else:
            assert not pred.is_empty, f"{rec}: expected {exp.value}, got empty"
            assert abs(pred.score.value - exp.value) < 1e-6, (
                f"{rec}: {pred.score.value} != {exp.value}"
            )


class TestCompoundPredicates:
    def test_and_or_children(self):
        body = """<Node id="0"><True/>
          <Node id="1" score="1.0">
            <CompoundPredicate booleanOperator="and">
              <SimplePredicate field="a" operator="lessThan" value="0"/>
              <SimplePredicate field="b" operator="greaterOrEqual" value="0"/>
            </CompoundPredicate>
          </Node>
          <Node id="2" score="2.0">
            <CompoundPredicate booleanOperator="or">
              <SimplePredicate field="a" operator="greaterOrEqual" value="1"/>
              <SimplePredicate field="c" operator="lessThan" value="0"/>
            </CompoundPredicate>
          </Node>
          <Node id="3" score="3.0"><True/></Node>
        </Node>"""
        _check(_doc(body), _grid())

    def test_xor(self):
        body = """<Node id="0"><True/>
          <Node id="1" score="1.0">
            <CompoundPredicate booleanOperator="xor">
              <SimplePredicate field="a" operator="lessThan" value="0"/>
              <SimplePredicate field="b" operator="lessThan" value="0"/>
            </CompoundPredicate>
          </Node>
          <Node id="2" score="2.0"><True/></Node>
        </Node>"""
        _check(_doc(body), _grid())

    def test_surrogate_split(self):
        # rpart-style: primary on a, surrogate on b, final fallback constant
        body = """<Node id="0"><True/>
          <Node id="1" score="1.0">
            <CompoundPredicate booleanOperator="surrogate">
              <SimplePredicate field="a" operator="lessThan" value="0"/>
              <SimplePredicate field="b" operator="lessThan" value="0.25"/>
            </CompoundPredicate>
          </Node>
          <Node id="2" score="2.0"><True/></Node>
        </Node>"""
        _check(_doc(body), _grid())

    def test_surrogate_all_unknown_uses_strategy(self):
        body = """<Node id="0" score="9.0"><True/>
          <Node id="1" score="1.0">
            <CompoundPredicate booleanOperator="surrogate">
              <SimplePredicate field="a" operator="lessThan" value="0"/>
              <SimplePredicate field="b" operator="lessThan" value="0"/>
            </CompoundPredicate>
          </Node>
          <Node id="2" score="2.0"><True/></Node>
        </Node>"""
        for strategy in ("none", "nullPrediction", "lastPrediction"):
            _check(_doc(body, strategy=strategy), _grid())


class TestGeneralShapes:
    def test_three_way_split(self):
        body = """<Node id="0"><True/>
          <Node id="1" score="1.0">
            <SimplePredicate field="a" operator="lessThan" value="-0.5"/>
          </Node>
          <Node id="2" score="2.0">
            <SimplePredicate field="a" operator="lessThan" value="0.5"/>
          </Node>
          <Node id="3" score="3.0"><True/></Node>
        </Node>"""
        _check(_doc(body), _grid())

    def test_is_missing_routing(self):
        body = """<Node id="0"><True/>
          <Node id="1" score="1.0">
            <SimplePredicate field="a" operator="isMissing"/>
          </Node>
          <Node id="2" score="2.0">
            <SimplePredicate field="a" operator="lessThan" value="0"/>
          </Node>
          <Node id="3" score="3.0"><True/></Node>
        </Node>"""
        _check(_doc(body), _grid())

    def test_non_true_root_predicate(self):
        body = """<Node id="0">
          <SimplePredicate field="c" operator="greaterOrEqual" value="0"/>
          <Node id="1" score="1.0">
            <SimplePredicate field="a" operator="lessThan" value="0"/>
          </Node>
          <Node id="2" score="2.0"><True/></Node>
        </Node>"""
        _check(_doc(body), _grid())

    def test_default_child_with_compound(self):
        body = """<Node id="0" defaultChild="n2"><True/>
          <Node id="n1" score="1.0">
            <CompoundPredicate booleanOperator="and">
              <SimplePredicate field="a" operator="lessThan" value="0"/>
              <SimplePredicate field="b" operator="lessThan" value="0"/>
            </CompoundPredicate>
          </Node>
          <Node id="n2" score="2.0"><True/></Node>
        </Node>"""
        _check(_doc(body, strategy="defaultChild"), _grid())

    def test_deeper_mixed_tree(self):
        body = """<Node id="0"><True/>
          <Node id="1">
            <SimplePredicate field="a" operator="lessThan" value="0"/>
            <Node id="3" score="1.0">
              <CompoundPredicate booleanOperator="or">
                <SimplePredicate field="b" operator="lessThan" value="0"/>
                <SimplePredicate field="c" operator="greaterThan" value="1"/>
              </CompoundPredicate>
            </Node>
            <Node id="4" score="2.0"><True/></Node>
          </Node>
          <Node id="2">
            <True/>
            <Node id="5" score="3.0">
              <SimplePredicate field="b" operator="isNotMissing"/>
            </Node>
            <Node id="6" score="4.0"><True/></Node>
          </Node>
        </Node>"""
        for strategy in ("none", "nullPrediction"):
            _check(_doc(body, strategy=strategy), _grid())

    def test_nested_compound_compiles_and_matches_oracle(self):
        # r2 rejected these; r3 lowers nested and/or/xor exactly via the
        # strong-Kleene DNF expansion (full coverage in
        # test_trees_extended.TestNestedCompoundPredicates)
        body = """<Node id="0"><True/>
          <Node id="1" score="1.0">
            <CompoundPredicate booleanOperator="and">
              <CompoundPredicate booleanOperator="or">
                <SimplePredicate field="a" operator="lessThan" value="0"/>
                <SimplePredicate field="b" operator="lessThan" value="0"/>
              </CompoundPredicate>
              <SimplePredicate field="c" operator="lessThan" value="0"/>
            </CompoundPredicate>
          </Node>
          <Node id="2" score="2.0"><True/></Node>
        </Node>"""
        from flink_jpmml_tpu.pmml.interp import evaluate as _oeval

        doc = _doc(body)
        cm = compile_pmml(doc)
        rng = np.random.default_rng(4)
        recs = []
        for _ in range(100):
            rec = {}
            for f in ("a", "b", "c"):
                if rng.random() > 0.25:
                    rec[f] = float(rng.normal())
            recs.append(rec)
        for rec, p in zip(recs, cm.score_records(recs)):
            o = _oeval(doc, rec)
            assert o.is_missing == p.is_empty, rec
            if not o.is_missing:
                assert p.score.value == pytest.approx(o.value), rec


class TestGeneralClassification:
    def test_classification_compound(self):
        xml = """<PMML xmlns="http://www.dmg.org/PMML-4_3" version="4.3">
          <Header/>
          <DataDictionary numberOfFields="3">
            <DataField name="a" optype="continuous" dataType="double"/>
            <DataField name="b" optype="continuous" dataType="double"/>
            <DataField name="y" optype="categorical" dataType="string">
              <Value value="p"/><Value value="q"/><Value value="r"/>
            </DataField>
          </DataDictionary>
          <TreeModel functionName="classification" missingValueStrategy="none">
            <MiningSchema>
              <MiningField name="y" usageType="target"/>
              <MiningField name="a"/><MiningField name="b"/>
            </MiningSchema>
            <Node id="0"><True/>
              <Node id="1" score="p">
                <CompoundPredicate booleanOperator="and">
                  <SimplePredicate field="a" operator="lessThan" value="0"/>
                  <SimplePredicate field="b" operator="lessThan" value="0"/>
                </CompoundPredicate>
              </Node>
              <Node id="2" score="q">
                <SimplePredicate field="a" operator="lessThan" value="0"/>
              </Node>
              <Node id="3" score="r"><True/></Node>
            </Node>
          </TreeModel></PMML>"""
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        recs = [
            {"a": -1.0, "b": -1.0}, {"a": -1.0, "b": 1.0},
            {"a": 1.0, "b": -1.0}, {"a": 1.0}, {"b": 0.0}, {},
        ]
        got = cm.score_records(recs)
        for rec, pred in zip(recs, got):
            exp = evaluate(doc, rec)
            if exp.label is None:
                assert pred.is_empty, f"{rec}: expected empty, got {pred}"
            else:
                assert pred.target.label == exp.label, (
                    f"{rec}: {pred.target.label} != {exp.label}"
                )


class TestPaddedChildSlots:
    def test_no_match_node_with_fewer_children_than_max(self):
        """Review regression: a 2-child node in a tree whose max fan-out is
        3 gets a padded child slot; that slot must evaluate FALSE so the
        no-true-child path still fires (empty result), not a bogus hit."""
        body = """<Node id="0"><True/>
          <Node id="t3">
            <SimplePredicate field="a" operator="lessThan" value="0"/>
            <Node id="x1" score="1.0">
              <SimplePredicate field="b" operator="lessThan" value="-0.5"/>
            </Node>
            <Node id="x2" score="2.0">
              <SimplePredicate field="b" operator="lessThan" value="0.5"/>
            </Node>
            <Node id="x3" score="3.0"><True/></Node>
          </Node>
          <Node id="t2">
            <True/>
            <Node id="y1" score="4.0">
              <SimplePredicate field="b" operator="lessThan" value="0"/>
            </Node>
            <Node id="y2" score="5.0">
              <SimplePredicate field="b" operator="greaterOrEqual" value="1"/>
            </Node>
          </Node>
        </Node>"""
        # record a>=0, 0 <= b < 1: reaches node t2, neither child matches →
        # noTrueChildStrategy (returnNullPrediction default) → empty
        doc = _doc(body)
        _check(doc, _grid())
        [pred] = compile_pmml(doc).score_records([{"a": 1.0, "b": 0.5}])
        assert pred.is_empty
