"""C++ data plane + block pipeline tests (SURVEY.md §6 'stress tests for the
host-side queue/partitioner')."""

import threading
import time

import numpy as np
import pytest

from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.pmml import parse_pmml_file
from flink_jpmml_tpu.runtime import native
from flink_jpmml_tpu.runtime.block import (
    BlockPipeline,
    CyclingBlockSource,
    FiniteBlockSource,
    _PyRing,
    make_ring,
)

needs_native = pytest.mark.skipif(
    not native.available(), reason=f"native plane unavailable: {native.build_error()}"
)


class TestNativeRing:
    @needs_native
    def test_roundtrip_order_and_offsets(self):
        ring = native.NativeRing(capacity=1024, arity=4, batch_size=256)
        blk = np.arange(32, dtype=np.float32).reshape(8, 4)
        assert ring.push_block(blk, first_offset=100) == 8
        out, offs = ring.drain(deadline_us=1000)
        np.testing.assert_array_equal(out, blk)
        assert offs.tolist() == list(range(100, 108))

    @needs_native
    def test_fill_or_deadline(self):
        ring = native.NativeRing(capacity=1024, arity=2, batch_size=64)
        ring.push_block(np.ones((10, 2), np.float32), 0)
        t0 = time.monotonic()
        out, _ = ring.drain(deadline_us=30_000)
        assert out.shape[0] == 10  # partial batch after deadline
        assert time.monotonic() - t0 < 1.0

    @needs_native
    def test_backpressure_blocks_producer(self):
        ring = native.NativeRing(capacity=8, arity=1, batch_size=8)
        assert ring.push_block(np.ones((8, 1), np.float32), 0) == 8
        # ring full: timed push returns short
        pushed = ring.push_block(np.ones((4, 1), np.float32), 8, timeout_us=50_000)
        assert pushed == 0
        ring.drain(deadline_us=100)
        assert ring.push_block(np.ones((4, 1), np.float32), 8, timeout_us=50_000) == 4

    @needs_native
    def test_threaded_producer_consumer_conserves_records(self):
        ring = native.NativeRing(capacity=4096, arity=3, batch_size=512)
        N, BLK = 100_000, 1000
        total = [0]

        def produce():
            sent = 0
            while sent < N:
                blk = np.full((BLK, 3), sent, np.float32)
                got = 0
                while got < BLK:
                    got += ring.push_block(blk[got:], sent + got, timeout_us=1_000_000)
                sent += BLK
            ring.close()

        t = threading.Thread(target=produce)
        t.start()
        offsets_seen = []
        while True:
            out, offs = ring.drain(deadline_us=2000)
            if out.shape[0] == 0:
                break
            total[0] += out.shape[0]
            offsets_seen.append(offs.copy())
        t.join()
        assert total[0] == N
        all_offs = np.concatenate(offsets_seen)
        assert all_offs.shape[0] == N
        assert np.array_equal(np.sort(all_offs), np.arange(N, dtype=np.uint64))

    def test_python_fallback_same_interface(self):
        ring = _PyRing(capacity=64, arity=2, batch_size=16)
        ring.push_block(np.ones((20, 2), np.float32) * 7, 5)
        out, offs = ring.drain(deadline_us=1000)
        assert out.shape == (16, 2)
        assert offs.tolist() == list(range(5, 21))
        out2, offs2 = ring.drain(deadline_us=1000)
        assert out2.shape[0] == 4
        assert offs2.tolist() == [21, 22, 23, 24]

    def test_make_ring_falls_back(self):
        r = make_ring(16, 2, 8, native=False)
        assert isinstance(r, _PyRing)


class TestBlockPipeline:
    @pytest.fixture()
    def iris_model(self, assets_dir):
        doc = parse_pmml_file(str(assets_dir / "iris_lr.pmml"))
        return compile_pmml(doc, batch_size=64)

    @pytest.mark.parametrize("use_native", [False, True])
    def test_end_to_end_counts_and_validity(self, iris_model, use_native):
        if use_native and not native.available():
            pytest.skip("no native plane")
        rng = np.random.default_rng(0)
        data = rng.normal(3, 2, size=(1000, 4)).astype(np.float32)
        data[17, :] = np.nan  # one dirty record
        seen = {"n": 0, "invalid": 0}

        def sink(out, n, first_off):
            seen["n"] += n
            valid = np.asarray(out.valid)[:n]
            seen["invalid"] += int((~valid).sum())

        pipe = BlockPipeline(
            FiniteBlockSource(data, block_size=100),
            iris_model,
            sink,
            use_native=use_native,
        )
        pipe.run_until_exhausted(timeout=30.0)
        assert seen["n"] == 1000
        assert seen["invalid"] == 1
        assert pipe.native == (use_native and native.available())
        snap = pipe.metrics.snapshot()
        assert snap["records_out"] == 1000

    def test_gbm_block_path_takes_rank_wire(self, tmp_path):
        # the production block path must engage the quantized wire for the
        # north-star GBM (VERDICT r1 #2: it used to ship f32 via predict)
        from assets.generate import gen_gbm

        doc = parse_pmml_file(
            gen_gbm(str(tmp_path), n_trees=20, depth=4, n_features=6)
        )
        cm = compile_pmml(doc, batch_size=128)
        rng = np.random.default_rng(5)
        data = rng.normal(0.0, 1.5, size=(500, 6)).astype(np.float32)
        data[rng.random(size=data.shape) < 0.1] = np.nan
        got = np.full((500,), np.nan, np.float32)

        collected = []

        def sink(out, n, first_off):
            collected.append((out, n, first_off))

        pipe = BlockPipeline(
            FiniteBlockSource(data, block_size=100),
            cm,
            sink,
            use_native=native.available(),
        )
        assert pipe.backend.startswith("rank_wire_")
        pipe.run_until_exhausted(timeout=30.0)
        for out, n, first_off in collected:
            preds = pipe.decode(out, n)
            got[first_off : first_off + n] = [p.score.value for p in preds]
        assert not np.isnan(got).any()
        M = np.isnan(data)
        ref = np.asarray(
            cm.predict(np.nan_to_num(data, nan=0.0), M).value, np.float32
        )[:500]
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
        snap = pipe.metrics.snapshot()
        assert snap[f"scorer_backend_{pipe.backend}"] == 1
        assert snap["records_out"] == 500

    def test_throughput_smoke_cpu(self, iris_model):
        # not a perf assertion — just that the loop sustains block flow
        rng = np.random.default_rng(1)
        data = rng.normal(3, 2, size=(4096, 4)).astype(np.float32)
        count = [0]

        def sink(out, n, first_off):
            count[0] += n

        pipe = BlockPipeline(
            CyclingBlockSource(data, block_size=512),
            iris_model,
            sink,
            use_native=native.available(),
        )
        pipe.run_for(seconds=0.5)
        assert count[0] > 0
