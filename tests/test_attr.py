"""Latency attribution & continuous device-profiling plane (ISSUE 6):
the per-batch stage ledger (obs/attr.py), the sampled device profiler
and kernel cost ledger (obs/profiler.py), the SLO burn-rate tracker
(obs/slo.py), the buffered span writer's bounded-loss contract
(obs/spans.py), and the fjt-top renderer (cli.py).

Everything here runs jax-free and in milliseconds: the profiler and
SLO tracker take injectable clocks, the ledger is plain dict+histogram
work, and fjt-top consumes struct dumps.
"""

import json
import os
import re

import pytest

from flink_jpmml_tpu.obs import attr, profiler, recorder, slo, spans
from flink_jpmml_tpu.obs.server import prometheus_text
from flink_jpmml_tpu.utils.metrics import (
    Histogram,
    MetricsRegistry,
    merge_structs,
)


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ---------------------------------------------------------------------------
# Stage ledger
# ---------------------------------------------------------------------------


class TestStageLedger:
    def test_observe_lands_in_stage_family(self):
        m = MetricsRegistry()
        led = attr.StageLedger(m)
        led.observe("sink", 0.002)
        led.observe("sink", 0.004)
        led.observe("encode", 0.001)
        snap = m.struct_snapshot()
        h = Histogram.from_state(
            snap["histograms"][attr.stage_metric_name("sink")]
        )
        assert h.count() == 2
        assert attr.stage_metric_name("encode") in snap["histograms"]

    def test_ledger_for_is_per_registry_singleton(self):
        a, b = MetricsRegistry(), MetricsRegistry()
        assert attr.ledger_for(a) is attr.ledger_for(a)
        assert attr.ledger_for(a) is not attr.ledger_for(b)
        assert attr.ledger_for(None) is None

    def test_merge_associativity(self):
        """Fleet aggregation of stage_seconds must associate exactly —
        (a ⊕ b) ⊕ c == a ⊕ (b ⊕ c) per stage histogram — or two
        supervisors merging in different orders would disagree."""
        regs = [MetricsRegistry() for _ in range(3)]
        obs = [
            [("sink", 0.001), ("encode", 0.03), ("sink", 2.0)],
            [("sink", 0.5), ("readback", 0.004)],
            [("encode", 0.00002), ("sink", 0.009), ("queue_wait", 1.1)],
        ]
        for m, rows in zip(regs, obs):
            led = attr.StageLedger(m)
            for stage, v in rows:
                led.observe(stage, v)
        a, b, c = [m.struct_snapshot() for m in regs]
        left = merge_structs([merge_structs([a, b]), c])
        right = merge_structs([a, merge_structs([b, c])])
        stages = {
            n for n in left["histograms"] if n.startswith("stage_seconds")
        }
        assert stages == {
            n for n in right["histograms"] if n.startswith("stage_seconds")
        }
        assert len(stages) == 4
        for n in stages:
            hl = Histogram.from_state(left["histograms"][n])
            hr = Histogram.from_state(right["histograms"][n])
            assert hl.state()["counts"] == hr.state()["counts"]
            assert hl.count() == hr.count()
            assert hl.sum() == pytest.approx(hr.sum())
            for q in (0.5, 0.99, 0.999):
                assert hl.quantile(q) == hr.quantile(q)

    def test_fleet_gauge_merge_semantics(self):
        """Ratio/boolean gauges must not SUM across the fleet: two
        workers at 5.8% MFU are not an 11.6% fleet, and one breached
        worker among two must breach the aggregate ``slo_ok``."""
        a, b = MetricsRegistry(), MetricsRegistry()
        a.gauge("device_mfu").set(0.058)
        b.gauge("device_mfu").set(0.031)
        a.gauge("slo_ok").set(1.0)
        b.gauge("slo_ok").set(0.0)  # b is breached
        a.gauge('slo_burn_rate{window="300"}').set(0.5)
        b.gauge('slo_burn_rate{window="300"}').set(20.0)
        a.gauge("inflight_depth").set(2)  # totals still sum
        b.gauge("inflight_depth").set(3)
        g = merge_structs([a.struct_snapshot(), b.struct_snapshot()])["gauges"]
        assert g["device_mfu"]["value"] == 0.058  # worst/busiest, not sum
        assert g["slo_ok"]["value"] == 0.0  # any breached → breached
        assert g['slo_burn_rate{window="300"}']["value"] == 20.0
        assert g["inflight_depth"]["value"] == 5

    def test_registry_cache_does_not_leak(self):
        """ledger_for/profiler_for cache per-registry on weak keys; the
        cached value must not strongly reference the registry or every
        ephemeral bench/test registry lives forever."""
        import gc
        import weakref

        m = MetricsRegistry()
        attr.ledger_for(m).observe("sink", 0.001)
        profiler.profiler_for(m)
        ref = weakref.ref(m)
        del m
        gc.collect()
        assert ref() is None

    def test_exemplar_merge_keeps_worst_per_bucket(self):
        a, b = Histogram(), Histogram()
        a.observe(0.4, exemplar="tid-a")
        b.observe(0.5, exemplar="tid-b")  # same bucket, worse value
        a.merge(b)
        (ex,) = a.exemplars().values()
        assert ex[0] == "tid-b" and ex[1] == 0.5

    def test_observe_keeps_worst_exemplar_per_bucket(self):
        """A later rate-limited re-capture with a SMALLER same-bucket
        value must not displace the worst offender's trace link —
        observe() promises the same worst-per-bucket semantics merge()
        and fjt-top's 'worst observed per bucket' rendering do."""
        h = Histogram()
        assert h.bucket_index(0.35) == h.bucket_index(0.5)
        h.observe(0.5, exemplar="tid-worst")
        h.observe(0.35, exemplar="tid-later-smaller")
        (ex,) = h.exemplars().values()
        assert ex[0] == "tid-worst" and ex[1] == 0.5
        h.observe(0.55, exemplar="tid-worse")  # genuinely worse: wins
        (ex,) = h.exemplars().values()
        assert ex[0] == "tid-worse" and ex[1] == 0.55

    def test_summary_shares_and_quantiles(self):
        m = MetricsRegistry()
        led = attr.StageLedger(m)
        for _ in range(10):
            led.observe("sink", 0.001)
        led.observe("encode", 0.09)
        s = attr.summary(m)
        assert set(s) == {"sink", "encode"}
        assert s["sink"]["n"] == 10
        assert s["encode"]["share"] == pytest.approx(0.9, abs=0.01)
        assert sum(row["share"] for row in s.values()) == pytest.approx(
            1.0, abs=0.01
        )
        # struct-dump input renders identically to the live registry
        assert attr.summary(m.struct_snapshot()) == s
        assert attr.summary(MetricsRegistry()) is None
        assert attr.summary({}) is None


class TestExemplarFlightLinkage:
    def test_top_bucket_observation_links_scrape_to_flight(self):
        """The acceptance path: a tail observation produces (1) a
        trace-id'd latency_exemplar flight event, (2) the same trace id
        on the histogram's top bucket, and (3) an OpenMetrics exemplar
        suffix on the rendered _bucket line — all three resolve to each
        other."""
        m = MetricsRegistry()
        led = attr.StageLedger(m)
        led.observe("sink", 0.75)  # first obs is always a top-bucket
        h = m.histogram(attr.stage_metric_name("sink"))
        exs = h.exemplars()
        assert len(exs) == 1
        (tid, val, _ts) = next(iter(exs.values()))
        assert val == 0.75
        flight_tids = {
            e["trace_id"]
            for e in recorder.events()
            if e.get("kind") == "latency_exemplar"
        }
        assert tid in flight_tids
        text = prometheus_text({None: m}, openmetrics=True)
        scraped = re.findall(r'# \{trace_id="([^"]+)"\} ([\d.e+-]+)', text)
        assert (tid, "0.75") in scraped
        # a classic (non-negotiated) scrape must stay exemplar-free:
        # the 0.0.4 text format does not admit them
        assert "trace_id" not in prometheus_text({None: m})

    def test_repeat_same_bucket_is_rate_limited(self):
        m = MetricsRegistry()
        led = attr.StageLedger(m)
        before = len(
            [e for e in recorder.events() if e.get("kind") == "latency_exemplar"]
        )
        for _ in range(50):
            led.observe("sink", 0.75)  # same bucket, within 1s
        after = len(
            [e for e in recorder.events() if e.get("kind") == "latency_exemplar"]
        )
        assert after - before == 1  # only the first captured

    def test_queue_wait_stall_event(self, monkeypatch):
        monkeypatch.setenv("FJT_SLO_TARGET_MS", "100")  # threshold 50ms
        m = MetricsRegistry()
        led = attr.StageLedger(m)  # env read at construction

        def stalls():
            return [
                e for e in recorder.events() if e.get("kind") == "stage_stall"
            ]

        n0 = len(stalls())
        led.observe("queue_wait", 0.2)
        assert len(stalls()) == n0 + 1
        ev = stalls()[-1]
        assert ev["stage"] == "queue_wait" and ev["seconds"] == 0.2
        led.observe("queue_wait", 0.3)  # within the 1s min period
        assert len(stalls()) == n0 + 1
        led.observe("queue_wait", 0.04)  # under threshold: never
        assert len(stalls()) == n0 + 1
        # no deadline configured → inert
        monkeypatch.delenv("FJT_SLO_TARGET_MS")
        led2 = attr.StageLedger(MetricsRegistry())
        led2.observe("queue_wait", 99.0)
        assert len(stalls()) == n0 + 1


# ---------------------------------------------------------------------------
# Device profiler: rate limiter + kernel cost ledger
# ---------------------------------------------------------------------------


def _profile(records=64):
    return {
        "records": records,
        "flops_per_record": 1280.0,
        "bytes_per_record": 6.0,
        "model": "m1",
        "backend": "xla",
    }


class TestDeviceProfilerRateLimiter:
    def _prof(self, tmp_path, clk, interval=1.0, budget=0.01):
        m = MetricsRegistry()
        ledger = profiler.KernelCostLedger(
            path=str(tmp_path / "kc.json"), flush_interval_s=0.0, clock=clk
        )
        return m, profiler.DeviceProfiler(
            m, interval_s=interval, overhead_budget=budget,
            clock=clk, cost_ledger=ledger,
        )

    def test_interval_gate(self, tmp_path):
        clk = FakeClock(0.0)
        _, prof = self._prof(tmp_path, clk)
        assert not prof.should_sample()  # 0s since "last": not yet due
        clk.advance(1.0)
        assert prof.should_sample()  # claims the slot
        assert not prof.should_sample()  # same instant: claimed
        clk.advance(0.5)
        assert not prof.should_sample()
        clk.advance(0.5)
        assert prof.should_sample()

    def test_overhead_budget_gate(self, tmp_path):
        """A sample whose serialization cost dwarfs the budget pauses
        sampling until wall clock amortizes it back under 1%."""
        clk = FakeClock(0.0)
        _, prof = self._prof(tmp_path, clk)
        clk.advance(1.0)
        assert prof.should_sample()
        prof.record_sample(0.4, _profile(), overhead_s=0.5)
        clk.advance(1.0)  # t=2: 0.5/2 = 25% ≫ 1%
        assert not prof.should_sample()
        clk.t = 49.0  # 0.5/49 ≈ 1.02% > 1%
        assert not prof.should_sample()
        clk.t = 51.0  # 0.5/51 ≈ 0.98% ≤ 1%
        assert prof.should_sample()

    def test_overhead_stays_bounded_over_a_run(self, tmp_path):
        """Simulated hour at one claim attempt per 100ms, each sample
        costing 80ms: granted samples must keep cumulative sampling
        overhead ≤ budget + one sample's worth of slack."""
        clk = FakeClock(0.0)
        _, prof = self._prof(tmp_path, clk)
        per_sample = 0.08
        spent = 0.0
        for _ in range(36_000):
            clk.advance(0.1)
            if prof.should_sample():
                prof.record_sample(
                    per_sample, _profile(), overhead_s=per_sample
                )
                spent += per_sample
        assert spent / clk.t <= 0.01 + per_sample / clk.t
        assert spent > 0  # the limiter throttles, it doesn't starve

    def test_disabled_by_env(self, tmp_path, monkeypatch):
        monkeypatch.setenv("FJT_PROF_SAMPLE", "off")
        prof = profiler.DeviceProfiler(
            MetricsRegistry(),
            cost_ledger=profiler.KernelCostLedger(
                path=str(tmp_path / "kc.json")
            ),
        )
        assert not prof.enabled
        assert not prof.should_sample()

    def test_sample_feeds_gauges_and_device_stage(self, tmp_path):
        clk = FakeClock(10.0)
        m, prof = self._prof(tmp_path, clk)
        prof.record_sample(0.001, _profile(records=1000), overhead_s=0.002)
        snap = m.struct_snapshot()
        assert snap["counters"]["device_samples"] == 1
        assert snap["gauges"]["device_ns_per_record"]["value"] == pytest.approx(
            1000.0
        )
        assert snap["gauges"]["flops_per_record"]["value"] == 1280.0
        # unknown (CPU) device kind → nominal-peak fallback keeps the
        # live gauges present and positive
        assert snap["gauges"]["device_mfu"]["value"] > 0
        assert snap["gauges"]["device_membw_util"]["value"] > 0
        h = Histogram.from_state(
            snap["histograms"][attr.stage_metric_name("device")]
        )
        assert h.count() == 1


class TestKernelCostLedger:
    def test_persist_merge_and_corrupt_tolerance(self, tmp_path):
        path = tmp_path / "kernel_costs.json"
        # a foreign process's entry already on disk must survive
        path.write_text(json.dumps(
            {"version": 1, "entries": {"other|xla": {"samples": 3}}}
        ))
        led = profiler.KernelCostLedger(
            path=str(path), flush_interval_s=0.0
        )
        led.update("m1", "xla", 0.001, 1000, 1280.0, 6.0)
        data = json.loads(path.read_text())
        assert set(data["entries"]) == {"other|xla", "m1|xla"}
        e = data["entries"]["m1|xla"]
        assert e["samples"] == 1
        assert e["device_s_per_record"] == pytest.approx(1e-6)
        assert e["rec_s"] == pytest.approx(1e6)
        # EWMA folds the second sample rather than replacing
        led.update("m1", "xla", 0.002, 1000, 1280.0, 6.0)
        e2 = json.loads(path.read_text())["entries"]["m1|xla"]
        assert e2["samples"] == 2
        assert 1e-6 < e2["device_s_per_record"] < 2e-6
        # corrupt disk state: overwritten, never raises
        path.write_text("{nope")
        led.update("m2", "xla", 0.001, 10, None, None)
        data = json.loads(path.read_text())
        assert "m2|xla" in data["entries"]

    def test_flush_rate_limited(self, tmp_path):
        clk = FakeClock(0.0)
        path = tmp_path / "kc.json"
        led = profiler.KernelCostLedger(
            path=str(path), flush_interval_s=5.0, clock=clk
        )
        clk.advance(10.0)
        led.update("m1", "xla", 0.001, 100, None, None)  # due → writes
        assert path.exists()
        mtime = path.stat().st_mtime_ns
        clk.advance(1.0)
        led.update("m1", "xla", 0.001, 100, None, None)  # not due
        assert path.stat().st_mtime_ns == mtime
        led.flush()  # explicit flush always writes the dirty state
        assert json.loads(path.read_text())["entries"]["m1|xla"]["samples"] == 2


class TestRoofline:
    def test_known_chip_strict_and_fallback(self):
        assert profiler.chip_peaks("TPU v4") == (275e12, 1228e9)
        assert profiler.chip_peaks("weird chip", strict=True) is None
        assert profiler.chip_peaks("weird chip") == (1e12, 100e9)

    def test_peaks_env_override(self, monkeypatch):
        monkeypatch.setenv("FJT_PROF_PEAKS", "2e12,5e11")
        assert profiler.chip_peaks("weird chip") == (2e12, 5e11)
        monkeypatch.setenv("FJT_PROF_PEAKS", "garbage")
        assert profiler.chip_peaks("weird chip") == (1e12, 100e9)

    def test_roofline_math(self):
        mfu, membw = profiler.roofline(1e6, 1280.0, 6.0, (1e12, 1e9))
        assert mfu == pytest.approx(1.28e-3)
        assert membw == pytest.approx(6e-3)
        assert profiler.roofline(0.0, 1.0, 1.0, (1e12, 1e9)) == (None, None)
        assert profiler.roofline(1e6, None, None, (1e12, 1e9)) == (None, None)

    def test_dispatch_profile_f32_fallback_is_honest(self):
        prof = attr.dispatch_profile(object(), 32)
        assert prof["records"] == 32
        assert prof["flops_per_record"] is None
        assert prof["bytes_per_record"] is None


class TestDispatcherSampling:
    """The sampled device-timing bracket inside OverlappedDispatcher
    (the launch-path integration of obs/profiler.py)."""

    class _Leaf:
        def __init__(self, fail=None):
            self.fail = fail

        def block_until_ready(self):
            if self.fail is not None:
                raise self.fail

    def _disp(self, tmp_path, interval=0.0):
        from flink_jpmml_tpu.runtime.pipeline import OverlappedDispatcher

        m = MetricsRegistry()
        # interval 0 disables; a tiny positive interval samples every
        # launch once the clock has moved at all
        prof = profiler.DeviceProfiler(
            m, interval_s=interval,
            cost_ledger=profiler.KernelCostLedger(
                path=str(tmp_path / "kc.json")
            ),
        )
        return m, OverlappedDispatcher(depth=2, metrics=m, profiler=prof)

    def test_sampled_launch_feeds_profiler(self, tmp_path):
        m, disp = self._disp(tmp_path, interval=1e-9)
        for _ in range(3):
            disp.launch(lambda: self._Leaf(), profile=_profile())
        disp.close()
        snap = m.struct_snapshot()
        assert snap["counters"]["device_samples"] >= 1
        assert attr.stage_metric_name("device") in snap["histograms"]
        assert snap["gauges"]["device_mfu"]["value"] > 0

    def test_device_sample_excludes_dispatch_host_time(self, tmp_path):
        """The sampling bracket times only the post-dispatch wait:
        dispatch_fn's host work (featurize/staging on the host-encode
        path) runs before the kernel is queued, so folding it in would
        book host time as device time — inflating device_ns_per_record
        and double-booking what dispatch_quantized already attributed
        to encode/h2d."""
        import time as _time

        m, disp = self._disp(tmp_path, interval=1e-9)

        class _SlowReady:
            def block_until_ready(self):
                _time.sleep(0.02)

        def dispatch():
            _time.sleep(0.08)  # host featurize/staging stand-in
            return _SlowReady()

        disp.launch(dispatch, profile=_profile())
        snap = m.struct_snapshot()
        dev = snap["histograms"][attr.stage_metric_name("device")]
        assert dev["n"] == 1
        assert 0.02 <= dev["sum"] < 0.06, (
            f"device sample {dev['sum']:.3f}s books dispatch host time"
        )
        disp.close()

    def test_no_profile_means_no_sample(self, tmp_path):
        m, disp = self._disp(tmp_path, interval=1e-9)
        disp.launch(lambda: self._Leaf())  # profile-less launch
        disp.close()
        assert m.struct_snapshot()["counters"].get("device_samples", 0) == 0

    def test_poisoned_inflight_batch_never_leaks_into_launch(self, tmp_path):
        """The sampler's window drain touches OLDER batches' handles; a
        poisoned one must surface its error at finish_oldest (right
        meta, right caller), never out of a later launch()."""
        m, disp = self._disp(tmp_path, interval=1e-9)
        boom = RuntimeError("device says no")
        disp.launch(lambda: self._Leaf(fail=boom), meta="bad")
        # this launch drains the window for its sample: must NOT raise
        h2 = disp.launch(lambda: self._Leaf(), meta="good", profile=_profile())
        with pytest.raises(RuntimeError, match="device says no"):
            disp.finish_oldest()
        out, meta = disp.finish_oldest()
        assert meta == "good"
        disp.close()
        assert h2.done

    def test_queue_wait_excludes_completion_callback(self, tmp_path):
        """The overflow wait books ONLY the blocking device wait as
        queue_wait — the complete callback (sink, checkpoint) that
        finish_oldest runs afterwards books its own stage, never
        inflating queue_wait (one interval, one stage)."""
        import time as _time

        from flink_jpmml_tpu.runtime.pipeline import OverlappedDispatcher

        m = MetricsRegistry()
        disp = OverlappedDispatcher(
            depth=1, metrics=m,
            complete=lambda out, meta: _time.sleep(0.02),
        )
        disp._profiler = None
        for i in range(4):
            disp.launch(lambda: self._Leaf(), meta=i)
        disp.close()
        q = Histogram.from_state(
            m.struct_snapshot()["histograms"][
                attr.stage_metric_name("queue_wait")
            ]
        )
        assert q.count() == 3  # launches 2..4 overflowed depth-1
        # 3 × 20ms sink sleeps must NOT land in queue_wait: the waits
        # themselves are no-op block_until_ready calls
        assert q.sum() < 0.01

    def test_depth0_books_readback_not_queue_wait(self):
        """A depth-0 dispatcher (in_flight=1, the latency operating
        point) has no window for a ready batch to wait in: launch's
        immediate drain of its own just-dispatched batch is readback.
        Booking it as queue_wait would read as 'window too shallow'
        (and fire stage_stall events) on every batch of a normal
        synchronous pipeline."""
        from flink_jpmml_tpu.runtime.pipeline import OverlappedDispatcher

        m = MetricsRegistry()
        disp = OverlappedDispatcher(depth=0, metrics=m)
        disp._profiler = None
        for i in range(4):
            disp.launch(lambda: self._Leaf(), meta=i)
        disp.close()
        snap = m.struct_snapshot()
        rname = attr.stage_metric_name("readback")
        assert Histogram.from_state(snap["histograms"][rname]).count() == 4
        assert attr.stage_metric_name("queue_wait") not in snap["histograms"]

    def test_queue_wait_attribution_on_full_window(self, tmp_path):
        m, disp = self._disp(tmp_path, interval=0.0)
        for i in range(5):
            disp.launch(lambda: self._Leaf(), meta=i)
        disp.close()
        snap = m.struct_snapshot()
        qname = attr.stage_metric_name("queue_wait")
        rname = attr.stage_metric_name("readback")
        # launches 3..5 overflowed the depth-2 window → queue_wait;
        # close() drains the remaining two → readback
        assert Histogram.from_state(snap["histograms"][qname]).count() == 3
        assert Histogram.from_state(snap["histograms"][rname]).count() == 2


# ---------------------------------------------------------------------------
# SLO burn-rate tracker
# ---------------------------------------------------------------------------


class TestSLOTracker:
    def _tracker(self, clk, **kw):
        m = MetricsRegistry()
        kw.setdefault("deadline_s", 0.01)
        kw.setdefault("objective", 0.9)  # budget 0.1: burns stay small
        kw.setdefault("windows", ((10.0, 2.0), (60.0, 1.5)))
        t = slo.SLOTracker(
            m, source="batch_latency_s", clock=clk, interval_s=1.0, **kw
        )
        return m, t

    def _observe(self, m, good=0, bad=0):
        h = m.histogram("batch_latency_s")
        for _ in range(good):
            h.observe(0.001)
        for _ in range(bad):
            h.observe(0.1)

    def test_inert_without_deadline(self, monkeypatch):
        monkeypatch.delenv("FJT_SLO_TARGET_MS", raising=False)
        m = MetricsRegistry()
        t = slo.SLOTracker(m, deadline_s=None)
        assert not t.enabled
        assert t.maybe_tick() is None and t.tick() is None
        assert t.health() == {}
        assert "slo_ok" not in m.struct_snapshot()["gauges"]

    def test_breach_and_clear_transitions(self):
        """The promote/clear drill: all-good baseline → a fast burn
        breaches (flight event, slo_ok 0, counter), recovery clears
        (flight event, slo_ok 1) — and the breach needed EVERY
        evaluable window over threshold."""
        clk = FakeClock(1000.0)
        m, t = self._tracker(clk)
        ev0 = len(recorder.events())
        self._observe(m, good=100)
        t.tick()  # baseline frame; no window evaluable yet
        assert not t.breached
        clk.advance(6.0)  # ≥ half the 10s window: cold-start fallback
        self._observe(m, bad=100)
        out = t.tick()
        assert out["transition"] == "breach" and t.breached
        snap = m.struct_snapshot()
        assert snap["gauges"]["slo_ok"]["value"] == 0.0
        assert snap["counters"]["slo_breaches"] == 1
        assert snap["gauges"]['slo_burn_rate{window="10"}']["value"] > 2.0
        kinds = [e["kind"] for e in recorder.events()[ev0:]]
        assert "slo_breach" in kinds and "slo_clear" not in kinds
        assert t.health()["slo"]["ok"] is False
        # recovery: a flood of good observations drains the burn
        clk.advance(6.0)
        self._observe(m, good=2000)
        out = t.tick()
        assert out["transition"] == "clear" and not t.breached
        snap = m.struct_snapshot()
        assert snap["gauges"]["slo_ok"]["value"] == 1.0
        assert snap["counters"]["slo_breaches"] == 1  # transitions, not ticks
        kinds = [e["kind"] for e in recorder.events()[ev0:]]
        assert "slo_clear" in kinds
        assert t.health()["slo"]["ok"] is True

    def test_multi_window_and_semantics(self):
        """A short-window blip alone must NOT breach once the long
        window is evaluable and healthy — the whole point of the
        multi-window shape."""
        clk = FakeClock(0.0)
        m, t = self._tracker(clk, windows=((10.0, 2.0), (60.0, 1.5)))
        self._observe(m, good=10_000)
        t.tick()
        # make both windows evaluable with a healthy history
        for _ in range(7):
            clk.advance(10.0)
            self._observe(m, good=100)
            t.tick()
        # a blip: 50 bad in the last 10s window (short burn ~4.5x > 2,
        # long burn over 1100 obs ~0.45 < 1.5)
        clk.advance(10.0)
        self._observe(m, good=50, bad=50)
        out = t.tick()
        assert out["burns"][10.0] > 2.0  # short window IS violating
        assert out["burns"][60.0] < 1.5
        assert not out["breached"]  # the long window held the page back

    def test_maybe_tick_rate_limit(self):
        clk = FakeClock(5.0)
        m, t = self._tracker(clk)
        self._observe(m, good=10)
        assert t.maybe_tick() is not None
        assert t.maybe_tick() is None  # same instant
        clk.advance(1.01)
        assert t.maybe_tick() is not None

    def test_health_fn_composes(self):
        clk = FakeClock(0.0)
        _, t = self._tracker(clk)
        fn = t.health_fn(lambda: {"ok": True, "depth": 2})
        out = fn()
        assert out["ok"] is True and out["depth"] == 2
        assert out["slo"]["deadline_ms"] == 10.0

    def test_env_window_parsing(self, monkeypatch):
        monkeypatch.setenv("FJT_SLO_WINDOWS", "5:10,60:2,junk,0:3")
        assert slo._env_windows() == ((5.0, 10.0), (60.0, 2.0))
        monkeypatch.setenv("FJT_SLO_WINDOWS", "all junk")
        assert slo._env_windows() == slo._DEFAULT_WINDOWS


# ---------------------------------------------------------------------------
# Buffered span writer: bounded crash loss
# ---------------------------------------------------------------------------


def _span_events(path):
    raw = open(path, encoding="utf-8").read()
    return json.loads(raw.rstrip().rstrip(",") + "]")


class TestSpanBuffering:
    def test_crash_loss_bounded_at_buffer_events(self, tmp_path):
        """The contract the buffered writer trades on: an abrupt kill
        loses at most ``buffer_events`` events — everything before the
        last buffer fill is already on disk."""
        w = spans.SpanWriter(
            str(tmp_path / "t.trace.json"),
            buffer_events=8, flush_interval_s=1e9,
        )
        for i in range(7):
            w.emit("s", float(i), 0.001)
        assert _span_events(w.path) == []  # buffered, none on disk yet
        w.emit("s", 7.0, 0.001)  # 8th fills the buffer → flush
        assert len(_span_events(w.path)) == 8
        for i in range(30):
            w.emit("s", float(8 + i), 0.001)
        # a crash NOW loses only what's in the buffer: < buffer_events
        on_disk = len(_span_events(w.path))
        assert 38 - on_disk < 8
        w.flush()
        assert len(_span_events(w.path)) == 38
        w.close()

    def test_interval_flush(self, tmp_path):
        w = spans.SpanWriter(
            str(tmp_path / "t.trace.json"),
            buffer_events=10_000, flush_interval_s=0.0,
        )
        w.emit("s", 0.0, 0.001)
        assert len(_span_events(w.path)) == 1  # interval 0: every emit
        w.close()

    def test_close_flushes(self, tmp_path):
        w = spans.SpanWriter(
            str(tmp_path / "t.trace.json"),
            buffer_events=100, flush_interval_s=1e9,
        )
        w.emit("s", 0.0, 0.001)
        w.close()
        assert len(_span_events(w.path)) == 1

    def test_flight_dump_flushes_spans(self, tmp_path, monkeypatch):
        """The postmortem contract: a flight-recorder dump flushes the
        buffered span writer so the trace file ends at the dump."""
        monkeypatch.setenv("FJT_TRACE_DIR", str(tmp_path))
        monkeypatch.setattr(spans, "_writer", None)
        monkeypatch.setattr(spans, "_writer_dir", None)
        try:
            spans.emit("pre_crash", 1.0, 0.5)
            w = spans.writer()
            r = recorder.FlightRecorder()
            r.record("worker_death", pid=123)
            assert r.dump(path=str(tmp_path / "f.jsonl")) is not None
            names = [e["name"] for e in _span_events(w.path)]
            assert "pre_crash" in names
        finally:
            spans._writer.close()
            monkeypatch.setattr(spans, "_writer", None)

    def test_module_flush_without_writer_is_noop(self, monkeypatch):
        monkeypatch.setattr(spans, "_writer", None)
        monkeypatch.delenv("FJT_TRACE_DIR", raising=False)
        spans.flush()  # must not create a writer or raise
        assert spans._writer is None


# ---------------------------------------------------------------------------
# fjt-top
# ---------------------------------------------------------------------------


class TestFjtTop:
    def _struct(self):
        m = MetricsRegistry()
        led = attr.StageLedger(m)
        for _ in range(20):
            led.observe("sink", 0.001)
        led.observe("readback", 0.08)
        m.gauge("device_mfu").set(0.058)
        m.gauge("device_membw_util").set(0.0001)
        m.gauge("device_ns_per_record").set(920.0)
        m.gauge("slo_ok").set(1.0)
        m.gauge('slo_burn_rate{window="300"}').set(0.25)
        return m.struct_snapshot()

    def test_renders_struct_dump(self, tmp_path, capsys):
        from flink_jpmml_tpu.cli import top_main

        dump = tmp_path / "varz.json"
        dump.write_text(json.dumps(self._struct()))
        assert top_main([str(dump)]) == 0
        out = capsys.readouterr().out
        assert "readback" in out and "sink" in out
        # ranked by total: readback (80ms) above sink (20ms)
        assert out.index("readback") < out.index("sink")
        assert "mfu   5.80%" in out
        assert "slo      OK" in out and "300s: 0.25x" in out

    def test_renders_bench_artifact_and_varz_mapping(self, tmp_path, capsys):
        from flink_jpmml_tpu.cli import top_main

        s = self._struct()
        # a /varz-style {label: struct} mapping: aggregate + one worker
        dump = tmp_path / "fleet.json"
        dump.write_text(json.dumps({"": s, "w0": s}))
        assert top_main([str(dump)]) == 0
        out = capsys.readouterr().out
        assert "== aggregate ==" in out and "== w0 ==" in out
        assert top_main([str(dump), "--worker", "w0"]) == 0
        out = capsys.readouterr().out
        assert "== w0 ==" in out and "== aggregate ==" not in out
        # a bench artifact embedding varz, incl. the driver's
        # {"parsed": <bench line>} wrapper form
        art = tmp_path / "BENCH.json"
        art.write_text(json.dumps({"metric": "x", "varz": s}))
        assert top_main([str(art)]) == 0
        out = capsys.readouterr().out
        assert "sink" in out
        # the headline varz struct renders ONCE, as the aggregate —
        # not a second time under a bogus "varz" label
        assert "== aggregate ==" in out and "== varz ==" not in out
        wrapped = tmp_path / "BENCH_r9.json"
        wrapped.write_text(
            json.dumps({"rc": 0, "parsed": {"metric": "x", "varz": s}})
        )
        assert top_main([str(wrapped)]) == 0
        assert "sink" in capsys.readouterr().out

    def test_empty_struct_says_so(self, tmp_path, capsys):
        from flink_jpmml_tpu.cli import top_main

        dump = tmp_path / "varz.json"
        dump.write_text(json.dumps({"counters": {}, "histograms": {}}))
        assert top_main([str(dump)]) == 0
        assert "no stage attribution" in capsys.readouterr().out

    def test_rejects_garbage(self, tmp_path):
        from flink_jpmml_tpu.cli import top_main

        p = tmp_path / "nope.json"
        p.write_text("[1, 2]")
        with pytest.raises(SystemExit):
            top_main([str(p)])
        with pytest.raises(SystemExit):
            top_main([str(tmp_path / "missing.json")])


class TestFjtTopFreshness:
    """The --freshness panel (ISSUE 7): obs/freshness.py +
    obs/pressure.py rendered as one operator view."""

    def _struct(self, diverging=False):
        m = MetricsRegistry()
        m.gauge("pressure").set(0.72)
        m.gauge("pressure_ring").set(0.72)
        m.gauge("pressure_window").set(0.10)
        m.gauge("pressure_wait").set(0.05)
        m.counter("pressure_breaches").inc(2)
        m.gauge("lag_drain_eta_s").set(12.5)
        m.gauge("lag_trend").set(-340.0)
        m.gauge("lag_diverging").set(1.0 if diverging else 0.0)
        m.gauge("watermark_ts").set(1_700_000_000.0)
        m.gauge('watermark_lag_s{partition="0"}').set(1.25)
        m.gauge('watermark_lag_s{partition="1"}').set(0.4)
        m.gauge('kafka_lag{partition="0"}').set(5000.0)
        m.gauge('kafka_lag_age_s{partition="0"}').set(0.3)
        h = m.histogram("record_staleness_s")
        for v in (0.5, 0.8, 1.4, 2.0):
            h.observe(v)
        return m.struct_snapshot()

    def test_renders_panel(self, tmp_path, capsys):
        from flink_jpmml_tpu.cli import top_main

        dump = tmp_path / "varz.json"
        dump.write_text(json.dumps(self._struct()))
        assert top_main([str(dump), "--freshness"]) == 0
        out = capsys.readouterr().out
        assert "freshness" in out
        assert "pressure  0.72" in out
        assert "ring 0.72" in out and "breaches 2" in out
        assert "eta 12.5s" in out and "-340.0 rec/s" in out
        assert "stale" in out and "p99" in out
        # per-partition table: both partitions, missing cells dashed
        assert re.search(r"^0\s+1\.250\s+5,000\s+0\.3$", out, re.M)
        assert re.search(r"^1\s+0\.400\s+-\s+-$", out, re.M)

    def test_diverging_renders_loudly(self, tmp_path, capsys):
        from flink_jpmml_tpu.cli import top_main

        dump = tmp_path / "varz.json"
        dump.write_text(json.dumps(self._struct(diverging=True)))
        assert top_main([str(dump), "--freshness"]) == 0
        out = capsys.readouterr().out
        assert "DIVERGING" in out
        assert "12.5s" not in out  # a frozen ETA must not read as live

    def test_empty_struct_says_so(self, tmp_path, capsys):
        from flink_jpmml_tpu.cli import top_main

        dump = tmp_path / "varz.json"
        dump.write_text(json.dumps({"counters": {}, "gauges": {}}))
        assert top_main([str(dump), "--freshness"]) == 0
        assert "no freshness telemetry" in capsys.readouterr().out

    def test_fleet_mapping_renders_each_source(self, tmp_path, capsys):
        from flink_jpmml_tpu.cli import top_main

        s = self._struct()
        dump = tmp_path / "fleet.json"
        dump.write_text(json.dumps({"": s, "w0": s}))
        assert top_main([str(dump), "--freshness"]) == 0
        out = capsys.readouterr().out
        assert "== aggregate · freshness ==" in out
        assert "== w0 · freshness ==" in out


class TestFjtTopWatch:
    """--watch N: the operator-console loop re-renders from a live
    source and retries through fetch failures instead of exiting."""

    def _interrupt_after(self, monkeypatch, n):
        import time as time_mod

        calls = {"n": 0}

        def fake_sleep(secs):
            calls["n"] += 1
            if calls["n"] >= n:
                raise KeyboardInterrupt

        monkeypatch.setattr(time_mod, "sleep", fake_sleep)

    def test_watch_rerenders_until_interrupted(
        self, tmp_path, capsys, monkeypatch
    ):
        from flink_jpmml_tpu.cli import top_main

        m = MetricsRegistry()
        attr.StageLedger(m).observe("sink", 0.002)
        dump = tmp_path / "varz.json"
        dump.write_text(json.dumps(m.struct_snapshot()))
        self._interrupt_after(monkeypatch, 2)
        with pytest.raises(KeyboardInterrupt):
            top_main([str(dump), "--watch", "0.01"])
        out = capsys.readouterr().out
        assert out.count("sink") >= 2  # rendered once per cycle

    def test_watch_retries_through_fetch_failures(
        self, tmp_path, capsys, monkeypatch
    ):
        from flink_jpmml_tpu.cli import top_main

        self._interrupt_after(monkeypatch, 2)
        with pytest.raises(KeyboardInterrupt):
            top_main([str(tmp_path / "gone.json"), "--watch", "0.01"])
        err = capsys.readouterr().err
        assert "retrying" in err  # noted, not fatal — twice
        assert err.count("retrying") == 2

    def test_watch_retries_missing_worker_label(
        self, tmp_path, capsys, monkeypatch
    ):
        from flink_jpmml_tpu.cli import top_main

        m = MetricsRegistry()
        attr.StageLedger(m).observe("sink", 0.002)
        dump = tmp_path / "fleet.json"
        dump.write_text(json.dumps({"": m.struct_snapshot()}))
        self._interrupt_after(monkeypatch, 1)
        with pytest.raises(KeyboardInterrupt):
            top_main([str(dump), "--watch", "0.01", "--worker", "w9"])
        err = capsys.readouterr().err
        assert "w9" in err and "retrying" in err

    def test_watch_validation(self, tmp_path):
        from flink_jpmml_tpu.cli import top_main

        with pytest.raises(SystemExit):
            top_main([str(tmp_path / "x.json"), "--watch", "0"])
        with pytest.raises(SystemExit):
            top_main([str(tmp_path / "x.json"), "--watch", "-2"])

    def test_watermark_only_struct_renders_without_fallback(
        self, tmp_path, capsys
    ):
        from flink_jpmml_tpu.cli import top_main

        m = MetricsRegistry()
        m.gauge("watermark_ts").set(1_700_000_000.0)
        dump = tmp_path / "varz.json"
        dump.write_text(json.dumps(m.struct_snapshot()))
        assert top_main([str(dump), "--freshness"]) == 0
        out = capsys.readouterr().out
        assert "low-watermark" in out
        assert "no freshness telemetry" not in out

    def test_empty_staleness_histogram_is_not_telemetry(
        self, tmp_path, capsys
    ):
        """freshness_for registers record_staleness_s eagerly; an
        all-empty registry that merely touched the tracker must still
        say 'no freshness telemetry' (review finding, pinned)."""
        from flink_jpmml_tpu.cli import top_main
        from flink_jpmml_tpu.obs.freshness import freshness_for

        m = MetricsRegistry()
        freshness_for(m)  # registers the (empty) staleness histogram
        dump = tmp_path / "varz.json"
        dump.write_text(json.dumps(m.struct_snapshot()))
        assert top_main([str(dump), "--freshness"]) == 0
        assert "no freshness telemetry" in capsys.readouterr().out
