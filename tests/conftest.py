"""Test harness: force an 8-device virtual CPU mesh (SURVEY.md §5).

The reference tested "distributed" behavior on Flink's in-process MiniCluster;
our equivalent is a single-process 8-device CPU JAX runtime — sharding tests
exercise real ``Mesh``/``shard_map`` code paths without TPU hardware. Must run
before the first ``import jax`` anywhere in the test session.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon TPU plugin ignores the JAX_PLATFORMS env var in this image, so
# force the CPU backend through the config API as well — otherwise "CPU"
# tests silently run on the real chip. FJT_TEST_PLATFORM overrides (e.g.
# =tpu to run the golden suites against real TPU numerics — how the
# round-3 HIGHEST-precision gaps were caught; multi-device tests still
# need the virtual CPU mesh and should be deselected then).
import jax

_plat = os.environ.get("FJT_TEST_PLATFORM", "cpu")
if _plat != "default":  # "default": let jax pick (the tunneled chip
    # registers under a plugin name, not "tpu", so pinning can't find it)
    jax.config.update("jax_platforms", _plat)

import pathlib
import sys
import tempfile

# Make the repo root importable regardless of how pytest is invoked.
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# Hermetic rank-wire autotune cache (compile/autotune.py):
# build_quantized_scorer consults it on EVERY compile, including ones
# inside class/session-scoped fixtures that run before any
# function-scoped monkeypatch — so the redirect must happen at conftest
# import, unconditionally (a developer's real ~/.cache entry would
# otherwise silently switch golden models to tuned configs per machine).
os.environ["FJT_AUTOTUNE_CACHE"] = os.path.join(
    tempfile.mkdtemp(prefix="fjt-test-autotune-"), "autotune.json"
)

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _isolated_autotune_cache(tmp_path, monkeypatch):
    """Per-test cache file on top of the import-time session redirect
    above: one test's sweep must not leak tuned configs into another's
    compiles (higher-scoped fixtures still use the session file)."""
    monkeypatch.setenv("FJT_AUTOTUNE_CACHE", str(tmp_path / "autotune.json"))


@pytest.fixture(scope="session")
def assets_dir(tmp_path_factory):
    """Generated PMML fixtures shared across the test session."""
    from assets.generate import generate_all

    out = tmp_path_factory.mktemp("pmml_assets")
    generate_all(str(out))
    return out
