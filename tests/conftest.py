"""Test harness: force an 8-device virtual CPU mesh (SURVEY.md §5).

The reference tested "distributed" behavior on Flink's in-process MiniCluster;
our equivalent is a single-process 8-device CPU JAX runtime — sharding tests
exercise real ``Mesh``/``shard_map`` code paths without TPU hardware. Must run
before the first ``import jax`` anywhere in the test session.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon TPU plugin ignores the JAX_PLATFORMS env var in this image, so
# force the CPU backend through the config API as well — otherwise "CPU"
# tests silently run on the real chip.
import jax

jax.config.update("jax_platforms", "cpu")

import pathlib
import sys

# Make the repo root importable regardless of how pytest is invoked.
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def assets_dir(tmp_path_factory):
    """Generated PMML fixtures shared across the test session."""
    from assets.generate import generate_all

    out = tmp_path_factory.mktemp("pmml_assets")
    generate_all(str(out))
    return out
