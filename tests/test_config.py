"""utils/config.py: dataclass validation + FJT_* env overrides."""

import pytest

from flink_jpmml_tpu.utils.config import (
    BatchConfig,
    MeshConfig,
    RuntimeConfig,
    from_env,
)


class TestValidation:
    def test_batch_rejections(self):
        with pytest.raises(ValueError, match="batch size"):
            BatchConfig(size=0)
        with pytest.raises(ValueError, match="deadline"):
            BatchConfig(deadline_us=0)

    def test_mesh_rejections(self):
        with pytest.raises(ValueError, match="mesh axes"):
            MeshConfig(data=0)
        with pytest.raises(ValueError, match="mesh axes"):
            MeshConfig(model=-1)

    def test_compile_rejections(self):
        from flink_jpmml_tpu.utils.config import CompileConfig

        with pytest.raises(ValueError, match="matmul_dtype"):
            CompileConfig(matmul_dtype="float64typo")
        with pytest.raises(ValueError, match="max_dense_depth"):
            CompileConfig(max_dense_depth=0)


class TestFromEnv:
    def test_no_env_is_identity(self, monkeypatch):
        for v in ("FJT_BATCH_SIZE", "FJT_BATCH_DEADLINE_US",
                  "FJT_MESH_DATA", "FJT_MESH_MODEL",
                  "FJT_MATMUL_DTYPE", "FJT_CHECKPOINT_DIR"):
            monkeypatch.delenv(v, raising=False)
        base = RuntimeConfig()
        assert from_env(base) == base

    def test_overrides_apply(self, monkeypatch):
        monkeypatch.setenv("FJT_BATCH_SIZE", "512")
        monkeypatch.setenv("FJT_BATCH_DEADLINE_US", "1500")
        monkeypatch.setenv("FJT_MESH_DATA", "4")
        monkeypatch.setenv("FJT_MESH_MODEL", "2")
        monkeypatch.setenv("FJT_MATMUL_DTYPE", "float32")
        monkeypatch.setenv("FJT_CHECKPOINT_DIR", "/ck")
        cfg = from_env()
        assert cfg.batch.size == 512
        assert cfg.batch.deadline_us == 1500
        assert cfg.mesh.data == 4 and cfg.mesh.model == 2
        assert cfg.compile.matmul_dtype == "float32"
        assert cfg.checkpoint_dir == "/ck"

    def test_invalid_override_is_typed(self, monkeypatch):
        # a bad value must surface as the dataclass's own validation,
        # not silently produce a broken config
        monkeypatch.setenv("FJT_BATCH_SIZE", "0")
        with pytest.raises(ValueError, match="batch size"):
            from_env()
        monkeypatch.delenv("FJT_BATCH_SIZE")
        monkeypatch.setenv("FJT_MATMUL_DTYPE", "float64typo")
        with pytest.raises(ValueError, match="matmul_dtype"):
            from_env()

    def test_set_but_empty_keeps_defaults(self, monkeypatch):
        # common CI/k8s templating artifact: VAR= (empty) means unset
        monkeypatch.setenv("FJT_MATMUL_DTYPE", "")
        monkeypatch.setenv("FJT_CHECKPOINT_DIR", "")
        monkeypatch.setenv("FJT_BATCH_SIZE", "")
        base = RuntimeConfig(checkpoint_dir="/keep")
        cfg = from_env(base)
        assert cfg.compile.matmul_dtype == "bfloat16"
        assert cfg.checkpoint_dir == "/keep"
        assert cfg.batch.size == base.batch.size
