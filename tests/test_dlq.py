"""Delivery-correctness plane (runtime/dlq.py): the dead-letter queue,
record-level poison isolation on both hot paths, crash-loop
fingerprinting, the decode-error quarantine, and the fjt-dlq CLI.

The kill-anywhere acceptance drill lives in bench.py
(--recovery-drill) with a smoke-scale tripwire in tools/perf_smoke.py;
this file pins the mechanisms one at a time.
"""

import json
import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from flink_jpmml_tpu.runtime import faults
from flink_jpmml_tpu.runtime.dlq import (
    CrashFingerprint,
    DeadLetterQueue,
    PoisonIsolationOverflow,
    dlq_for_checkpoint,
    fingerprint,
    make_envelope,
    payload_bytes,
    serialize_record,
)
from flink_jpmml_tpu.utils.metrics import MetricsRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("FJT_RESTART_STREAK", raising=False)
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def small_gbm():
    """One tiny compiled GBM shared by the module (compile once)."""
    import tempfile

    from flink_jpmml_tpu.assets_gen import gen_gbm
    from flink_jpmml_tpu.compile import compile_pmml
    from flink_jpmml_tpu.pmml import parse_pmml_file

    tmp = tempfile.mkdtemp(prefix="fjt-dlq-model-")
    return compile_pmml(
        parse_pmml_file(gen_gbm(tmp, n_trees=3, depth=3, n_features=4)),
        batch_size=32,
    )


class TestDeadLetterQueue:
    def test_roundtrip_and_fingerprint(self, tmp_path):
        q = DeadLetterQueue(str(tmp_path / "dlq"))
        env = q.quarantine(
            b"\x01\x02", offset=7, reason="score",
            error=ValueError("boom"), partition=3,
        )
        got = list(q.scan())
        assert got == [env]
        assert payload_bytes(got[0]) == b"\x01\x02"
        assert got[0]["exception"] == "ValueError: boom"
        assert got[0]["partition"] == 3
        # content-addressed: same bytes → same fingerprint, any offset
        assert got[0]["fingerprint"] == fingerprint(b"\x01\x02")
        assert make_envelope(b"\x01\x02", 99, "decode")["fingerprint"] \
            == got[0]["fingerprint"]

    def test_rotation_reopen_and_bound(self, tmp_path):
        m = MetricsRegistry()
        q = DeadLetterQueue(
            str(tmp_path / "dlq"), max_records=6, segment_records=2,
            metrics=m,
        )
        for i in range(5):
            q.quarantine(b"p%d" % i, offset=i, reason="score")
        # a reopened DLQ continues the segment sequence, loses nothing
        q2 = DeadLetterQueue(
            str(tmp_path / "dlq"), max_records=6, segment_records=2,
            metrics=m,
        )
        q2.quarantine(b"p5", offset=5, reason="decode")
        assert q2.offsets() == [0, 1, 2, 3, 4, 5]
        # past the bound: OLDEST segments drop, counted
        for i in range(6, 10):
            q2.quarantine(b"p%d" % i, offset=i, reason="decode")
        offs = q2.offsets()
        assert len(offs) <= 8 and offs[-1] == 9 and 0 not in offs
        snap = m.struct_snapshot()["counters"]
        assert snap['dlq_records{reason="score"}'] == 5
        assert snap['dlq_records{reason="decode"}'] == 5
        assert snap["dlq_dropped"] >= 2

    def test_corrupt_line_skipped(self, tmp_path):
        q = DeadLetterQueue(str(tmp_path / "dlq"), segment_records=8)
        q.quarantine(b"a", offset=1, reason="score")
        q.quarantine(b"b", offset=2, reason="score")
        seg = [p for p in os.listdir(q.directory)
               if p.startswith("dlq-")][0]
        path = os.path.join(q.directory, seg)
        lines = open(path).read().splitlines()
        lines.insert(1, "{torn garbage")
        open(path, "w").write("\n".join(lines) + "\n")
        assert q.offsets() == [1, 2]  # neighbors survive the damage

    def test_concurrent_puts_lose_nothing(self, tmp_path):
        # the default wiring shares one DLQ between the ingest thread
        # (decode poison) and the score thread (scoring poison): puts
        # racing a segment rotation must not drop envelopes
        import threading

        q = DeadLetterQueue(
            str(tmp_path / "dlq"), segment_records=3, max_records=10_000,
        )

        def writer(base):
            for i in range(100):
                q.quarantine(b"p", offset=base + i, reason="score")

        ts = [
            threading.Thread(target=writer, args=(b,))
            for b in (0, 10_000)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        offs = q.offsets()
        assert len(offs) == 200
        assert sorted(offs) == sorted(
            list(range(100)) + list(range(10_000, 10_100))
        )

    def test_dlq_for_checkpoint_colocation(self, tmp_path):
        from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager

        ck = CheckpointManager(str(tmp_path / "ck"))
        q = dlq_for_checkpoint(ck)
        assert q.directory == os.path.join(ck.directory, "dlq")
        assert dlq_for_checkpoint(None) is None

    def test_serialize_record_shapes(self):
        assert json.loads(serialize_record({"a": 1})) == {"a": 1}
        # non-JSON payloads still serialize to something inspectable
        assert b"object" in serialize_record(object())


class TestCrashFingerprint:
    def test_restore_counting(self, tmp_path):
        fp = CrashFingerprint(str(tmp_path))
        assert fp.note_restore(5) == 1
        assert fp.note_restore(5) == 2
        assert fp.note_restore(5) == 3
        assert fp.note_restore(9) == 1  # progress resets the loop count

    def test_marker_roundtrip(self, tmp_path):
        fp = CrashFingerprint(str(tmp_path))
        assert fp.read_marker() is None
        fp.write_marker(10, 74, attempts=2)
        assert fp.read_marker() == {"lo": 10, "hi": 74, "attempts": 2}
        fp.clear_marker()
        assert fp.read_marker() is None
        fp.clear_marker()  # idempotent


class TestDispatcherOnError:
    def test_handled_error_is_swallowed_fifo_continues(self):
        from flink_jpmml_tpu.runtime.pipeline import OverlappedDispatcher

        class Boom:
            def block_until_ready(self):
                raise RuntimeError("device says no")

        handled = []
        done = []
        disp = OverlappedDispatcher(
            depth=None,
            complete=lambda out, meta: done.append(meta),
            on_error=lambda out, meta, e: (
                handled.append((meta, str(e))) or True
            ),
        )
        disp.launch(lambda: 1, meta="a")
        disp.launch(lambda: Boom(), meta="b")
        disp.launch(lambda: 3, meta="c")
        disp.flush()  # must NOT raise: b is handled, a/c complete
        assert done == ["a", "c"]
        assert handled == [("b", "device says no")]

    def test_unhandled_error_still_raises(self):
        from flink_jpmml_tpu.runtime.pipeline import OverlappedDispatcher

        class Boom:
            def block_until_ready(self):
                raise RuntimeError("no")

        disp = OverlappedDispatcher(
            depth=None, on_error=lambda out, meta, e: False,
        )
        disp.launch(lambda: Boom(), meta="b")
        with pytest.raises(RuntimeError, match="no"):
            disp.flush()


class TestBlockPathIsolation:
    def _run(self, small_gbm, tmp_path, data, restore=False, **pipe_kw):
        from flink_jpmml_tpu.runtime.block import (
            BlockPipeline, FiniteBlockSource,
        )
        from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager
        from flink_jpmml_tpu.utils.config import BatchConfig, RuntimeConfig

        emitted = []

        def sink(out, n, first_off):
            emitted.append((first_off, n))

        pipe = BlockPipeline(
            FiniteBlockSource(data, 64), small_gbm, sink,
            RuntimeConfig(
                batch=BatchConfig(size=32, deadline_us=1000),
                checkpoint_interval_s=0.05,
            ),
            checkpoint=CheckpointManager(str(tmp_path / "ck")),
            **pipe_kw,
        )
        if restore:
            assert pipe.restore()
        pipe.run_until_exhausted(timeout=60)
        return pipe, emitted

    def test_poison_goes_to_dlq_rest_to_sink(self, small_gbm, tmp_path):
        N = 400
        rng = np.random.default_rng(0)
        data = rng.normal(0, 1, size=(N, 4)).astype(np.float32)
        faults.inject("poison_record", offset=97)
        faults.inject("poison_record", offset=255)
        pipe, emitted = self._run(small_gbm, tmp_path, data)
        covered = np.zeros(N, np.int64)
        for off, n in emitted:
            covered[off: off + n] += 1
        assert sorted(np.flatnonzero(covered == 0).tolist()) == [97, 255]
        assert (covered <= 1).all()
        assert pipe.committed_offset == N  # parked poison still commits
        dlq = DeadLetterQueue(str(tmp_path / "ck" / "dlq"))
        envs = {e["offset"]: e for e in dlq.scan()}
        assert sorted(envs) == [97, 255]
        assert envs[97]["reason"] == "score"
        # the payload is the raw f32 row — redrivable
        assert payload_bytes(envs[97]) == data[97].tobytes()
        snap = pipe.metrics.struct_snapshot()["counters"]
        assert snap['dlq_records{reason="score"}'] == 2
        # suspect gauge returned to 0 after the transient isolation
        assert (
            pipe.metrics.struct_snapshot()["gauges"][
                "poison_suspect_mode"
            ]["value"] == 0.0
        )

    def test_without_dlq_error_is_fatal(self, small_gbm, tmp_path):
        from flink_jpmml_tpu.runtime.block import (
            BlockPipeline, FiniteBlockSource,
        )
        from flink_jpmml_tpu.utils.config import BatchConfig, RuntimeConfig

        data = np.zeros((64, 4), np.float32)
        faults.inject("poison_record", offset=5)
        pipe = BlockPipeline(
            FiniteBlockSource(data, 64), small_gbm,
            lambda *a: None,
            RuntimeConfig(batch=BatchConfig(size=32, deadline_us=1000)),
            # no checkpoint → no DLQ → historical fail-fast behavior
        )
        with pytest.raises(faults.InjectedPoisonRecord):
            pipe.run_until_exhausted(timeout=30)

    def test_quarantine_budget_aborts_isolation(
        self, small_gbm, tmp_path, monkeypatch
    ):
        # every record poisoned: a model-level failure must NOT be
        # converted into mass quarantine — isolation aborts and the
        # original error kills the pipeline honestly
        monkeypatch.setenv("FJT_DLQ_MAX_PER_BATCH", "4")
        data = np.zeros((64, 4), np.float32)
        faults.inject("poison_record", every=1)
        from flink_jpmml_tpu.runtime.block import (
            BlockPipeline, FiniteBlockSource,
        )
        from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager
        from flink_jpmml_tpu.utils.config import BatchConfig, RuntimeConfig

        pipe = BlockPipeline(
            FiniteBlockSource(data, 64), small_gbm, lambda *a: None,
            RuntimeConfig(batch=BatchConfig(size=32, deadline_us=1000)),
            checkpoint=CheckpointManager(str(tmp_path / "ck")),
        )
        with pytest.raises(PoisonIsolationOverflow):
            pipe.run_until_exhausted(timeout=30)
        dlq = DeadLetterQueue(str(tmp_path / "ck" / "dlq"))
        assert dlq.count() <= 4

    def test_replay_counter_on_restore(self, small_gbm, tmp_path):
        # phase 1: commit partway, leave an in-flight high-water mark;
        # phase 2: restore → records below inflight_hi count as replays
        N = 320
        data = np.arange(N * 4, dtype=np.float32).reshape(N, 4)
        pipe, _ = self._run(small_gbm, tmp_path, data)
        assert pipe.committed_offset == N
        state = pipe._ckpt_state()
        assert state["inflight_hi"] == N
        # simulate a torn run: rewind the checkpoint to mid-stream with
        # a wider in-flight range, then restore a fresh pipeline
        from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager

        ck = CheckpointManager(str(tmp_path / "ck"))
        time.sleep(0.002)
        ck.save({"source_offset": 128, "inflight_hi": 256})
        pipe2, emitted2 = self._run(
            small_gbm, tmp_path, data, restore=True
        )
        assert pipe2.committed_offset == N
        snap = pipe2.metrics.struct_snapshot()["counters"]
        assert snap["records_replayed"] == 256 - 128
        assert emitted2[0][0] == 128  # resumed at the commit, not 0


class TestRecordPathIsolation:
    class _ListSource:
        def __init__(self, rows):
            self._rows = rows
            self._i = 0

        def poll(self, max_n):
            out = []
            while self._i < len(self._rows) and len(out) < max_n:
                out.append((self._i + 1, self._rows[self._i]))
                self._i += 1
            return out

        def seek(self, offset):
            self._i = offset

        @property
        def exhausted(self):
            return self._i >= len(self._rows)

    def test_poison_record_isolated_on_engine_path(
        self, small_gbm, tmp_path
    ):
        from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager
        from flink_jpmml_tpu.runtime.engine import Pipeline, StaticScorer
        from flink_jpmml_tpu.runtime.sinks import CollectSink
        from flink_jpmml_tpu.utils.config import BatchConfig, RuntimeConfig

        N = 200
        rng = np.random.default_rng(1)
        rows = [
            rng.normal(0, 1, size=4).astype(np.float32).tolist()
            for _ in range(N)
        ]
        # offset targeting uses the record's TRUE offset on this path
        # too (stamps are resume points = offset+1): offset=K names
        # the same record here as on the block path
        faults.inject("poison_record", offset=56)
        sink = CollectSink()
        pipe = Pipeline(
            self._ListSource(rows), StaticScorer(small_gbm), sink,
            RuntimeConfig(
                batch=BatchConfig(size=32, deadline_us=1000),
                checkpoint_interval_s=0.05,
            ),
            checkpoint=CheckpointManager(str(tmp_path / "ck")),
        )
        pipe.run_until_exhausted(timeout=60)
        assert len(sink.items) == N - 1
        assert pipe.committed_offset == N
        dlq = DeadLetterQueue(str(tmp_path / "ck" / "dlq"))
        envs = list(dlq.scan())
        assert [e["offset"] for e in envs] == [56]
        # the record payload round-trips as JSON
        assert json.loads(payload_bytes(envs[0])) == rows[56]


class TestCrashLoopFingerprint:
    pytestmark = pytest.mark.slow  # multi-incarnation subprocess drill

    _WORKER = textwrap.dedent(r"""
        import glob, os, sys
        sys.path.insert(0, sys.argv[2])
        import jax
        jax.config.update("jax_platforms", "cpu")
        import numpy as np
        from flink_jpmml_tpu.compile import compile_pmml
        from flink_jpmml_tpu.pmml import parse_pmml_file
        from flink_jpmml_tpu.runtime.block import (
            BlockPipeline, FiniteBlockSource,
        )
        from flink_jpmml_tpu.runtime.checkpoint import CheckpointManager
        from flink_jpmml_tpu.utils.config import BatchConfig, RuntimeConfig

        tmp = sys.argv[1]
        pmml = glob.glob(os.path.join(tmp, "*.pmml"))[0]
        cm = compile_pmml(parse_pmml_file(pmml), batch_size=32)
        rng = np.random.default_rng(0)
        N = 200
        data = rng.normal(0, 1, size=(N, 4)).astype(np.float32)
        out = open(os.path.join(tmp, "sink.log"), "a", buffering=1)

        def sink(o, n, first_off):
            out.write(f"{first_off} {n}\n")

        pipe = BlockPipeline(
            FiniteBlockSource(data, 64), cm, sink,
            RuntimeConfig(
                batch=BatchConfig(size=32, deadline_us=1000),
                checkpoint_interval_s=0.02,
            ),
            checkpoint=CheckpointManager(os.path.join(tmp, "ck")),
            max_dispatch_chunks=1,
        )
        pipe.restore()
        pipe.run_until_exhausted(timeout=60)
        print("DONE", pipe.committed_offset, flush=True)
    """)

    def test_process_killing_record_converges_to_dlq(self, tmp_path):
        """A record that SIGKILLs the worker on every dispatch is
        fingerprinted across restarts (count via crashes.json +
        FJT_RESTART_STREAK), bisected under persisted markers, and
        quarantined WITHOUT a final dispatch — in ≤ log2(batch)+
        threshold incarnations, with zero loss elsewhere."""
        from flink_jpmml_tpu.assets_gen import gen_gbm

        gen_gbm(str(tmp_path), n_trees=3, depth=3, n_features=4)
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["FJT_FAULTS"] = "worker_crash:site=score_batch:offset=117"
        env["FJT_POISON_RESTARTS"] = "1"
        env["FJT_XLA_CACHE"] = str(tmp_path / "xla")
        env.pop("FJT_RESTART_STREAK", None)
        deaths = 0
        for attempt in range(14):
            proc = subprocess.run(
                [sys.executable, "-c", self._WORKER,
                 str(tmp_path), REPO],
                env=env, capture_output=True, text=True, timeout=120,
            )
            if proc.returncode == 0:
                break
            assert proc.returncode == -9, proc.stderr[-2000:]
            deaths += 1
        else:
            pytest.fail(f"no convergence after {deaths} deaths")
        assert deaths >= 1  # it DID crash-loop before converging
        dlq = DeadLetterQueue(str(tmp_path / "ck" / "dlq"))
        envs = {e["offset"]: e for e in dlq.scan()}
        assert sorted(envs) == [117]
        assert envs[117]["reason"] == "crash_loop"
        covered = np.zeros(200, np.int64)
        for ln in open(tmp_path / "sink.log"):
            off, n = map(int, ln.split())
            covered[off: off + n] += 1
        assert np.flatnonzero(covered == 0).tolist() == [117]
        # marker cleaned up after convergence
        assert not (tmp_path / "ck" / "suspect-marker.json").exists()


class TestProduceAndCLI:
    def test_produce_roundtrip(self):
        from flink_jpmml_tpu.runtime.kafka import (
            KafkaClient, MiniKafkaBroker,
        )

        broker = MiniKafkaBroker(topic="t")
        try:
            c = KafkaClient(broker.host, broker.port)
            assert c.produce("t", 0, [b"abc", b"def"]) == 0
            assert c.produce("t", 0, [b"ghi"]) == 2
            hw, recs = c.fetch("t", 0, 0)
            assert hw == 3
            assert [v for _, v in recs] == [b"abc", b"def", b"ghi"]
            c.close()
        finally:
            broker.close()

    def test_cli_list_inspect_redrive(self, tmp_path, capsys):
        from flink_jpmml_tpu.cli import dlq_main
        from flink_jpmml_tpu.runtime.kafka import (
            KafkaClient, MiniKafkaBroker,
        )

        ck = tmp_path / "ck"
        q = DeadLetterQueue(str(ck / "dlq"))
        row = np.arange(4, dtype=np.float32)
        q.quarantine(row.tobytes(), offset=137, reason="score",
                     error=ValueError("boom"), partition=0)
        q.quarantine(b"junk", offset=200, reason="decode", partition=0)
        # a duplicate envelope (same bytes, same offset — a replayed
        # quarantine): redrive must dedupe it
        q.quarantine(row.tobytes(), offset=137, reason="score")

        assert dlq_main(["list", str(ck)]) == 0
        out = capsys.readouterr().out
        assert "137" in out and "score" in out and "boom" in out

        assert dlq_main(["inspect", str(ck), "--offset", "137"]) == 0
        out = capsys.readouterr().out
        assert "as f32 row: [0.0, 1.0, 2.0, 3.0]" in out

        broker = MiniKafkaBroker(topic="re")
        try:
            assert dlq_main([
                "redrive", str(ck), "--host", broker.host,
                "--port", str(broker.port), "--topic", "re",
                "--reason", "score",
            ]) == 0
            c = KafkaClient(broker.host, broker.port)
            _, recs = c.fetch("re", 0, 0)
            # deduped: ONE produce despite two score envelopes
            assert [v for _, v in recs] == [row.tobytes()]
            c.close()
        finally:
            broker.close()

    def test_cli_redrive_nothing_matches(self, tmp_path):
        from flink_jpmml_tpu.cli import dlq_main

        q = DeadLetterQueue(str(tmp_path / "dlq"))
        q.quarantine(b"x", offset=1, reason="decode")
        with pytest.raises(SystemExit, match="nothing to redrive"):
            dlq_main([
                "redrive", str(tmp_path), "--host", "h", "--port", "1",
                "--topic", "t", "--reason", "score",
            ])
