"""invalidValueTreatment semantics (VERDICT r2 missing #3 / r3 task):
DataDictionary validity (declared category Values; continuous Intervals)
× mining-schema treatment (returnInvalid — the spec default — asMissing,
asIs, asValue), golden-diffed compiled vs oracle."""

import numpy as np
import pytest

from flink_jpmml_tpu.compile import compile_pmml
from flink_jpmml_tpu.pmml import parse_pmml
from flink_jpmml_tpu.pmml.interp import evaluate


def _doc_xml(treatment_attr="", interval="", cat_values=True, x_attr=None):
    values = (
        '<Value value="red"/><Value value="green"/><Value value="blue"/>'
        if cat_values
        else ""
    )
    return f"""<PMML version="4.3"><DataDictionary>
      <DataField name="color" optype="categorical" dataType="string">
        {values}</DataField>
      <DataField name="x" optype="continuous" dataType="double">
        {interval}</DataField>
      </DataDictionary>
      <TreeModel functionName="regression" missingValueStrategy="none">
      <MiningSchema>
        <MiningField name="color" {treatment_attr}/>
        <MiningField name="x" {x_attr if x_attr is not None else treatment_attr}/>
      </MiningSchema>
      <Node id="r"><True/>
        <Node id="a" score="10">
          <SimplePredicate field="color" operator="equal" value="red"/></Node>
        <Node id="b" score="20">
          <SimplePredicate field="x" operator="greaterThan" value="0"/></Node>
        <Node id="c" score="30"><True/></Node>
      </Node></TreeModel></PMML>"""


def _assert_parity(doc, records):
    cm = compile_pmml(doc)
    preds = cm.score_records(records)
    for rec, p in zip(records, preds):
        o = evaluate(doc, rec)
        assert o.is_missing == p.is_empty, (rec, o, p)
        if not o.is_missing:
            assert p.score.value == pytest.approx(o.value, rel=1e-5), rec


class TestCategoricalInvalid:
    def test_default_return_invalid(self):
        doc = parse_pmml(_doc_xml())
        recs = [
            {"color": "red", "x": 1.0},      # valid → 10
            {"color": "violet", "x": 1.0},   # invalid → EMPTY
            {"color": "green", "x": 1.0},    # valid → 20
            {"x": 1.0},                      # missing color → 20
        ]
        _assert_parity(doc, recs)
        o = evaluate(doc, recs[1])
        assert o.is_missing  # returnInvalid = empty result

    def test_as_missing(self):
        doc = parse_pmml(_doc_xml('invalidValueTreatment="asMissing"'))
        recs = [
            {"color": "violet", "x": -1.0},  # invalid→missing → else branch
            {"color": "violet", "x": 2.0},
        ]
        _assert_parity(doc, recs)
        assert evaluate(doc, recs[0]).value == 30.0
        assert evaluate(doc, recs[1]).value == 20.0

    def test_as_is_matches_nothing_but_not_missing(self):
        doc = parse_pmml(_doc_xml('invalidValueTreatment="asIs"'))
        recs = [
            {"color": "violet", "x": 2.0},   # ≠ red, not missing → 20
            {"color": "violet", "x": -2.0},  # → 30
        ]
        _assert_parity(doc, recs)
        assert evaluate(doc, recs[0]).value == 20.0
        assert evaluate(doc, recs[1]).value == 30.0

    def test_as_value_replaces(self):
        doc = parse_pmml(
            _doc_xml(
                'invalidValueTreatment="asValue" '
                'invalidValueReplacement="red"'
            )
        )
        recs = [{"color": "violet", "x": 2.0}]  # violet→red → 10
        _assert_parity(doc, recs)
        assert evaluate(doc, recs[0]).value == 10.0


class TestIntervalInvalid:
    IVL = '<Interval closure="closedClosed" leftMargin="-5" rightMargin="5"/>'

    def test_out_of_interval_default_invalid(self):
        doc = parse_pmml(_doc_xml(interval=self.IVL))
        recs = [
            {"color": "red", "x": 3.0},    # in range → 10
            {"color": "green", "x": 7.0},  # out of range → EMPTY
            {"color": "green", "x": -7.0},
            {"color": "green"},            # x missing: never invalid → 30
        ]
        _assert_parity(doc, recs)
        assert evaluate(doc, recs[1]).is_missing
        assert not evaluate(doc, recs[3]).is_missing

    def test_open_closure_boundaries(self):
        ivl = (
            '<Interval closure="openClosed" leftMargin="0" rightMargin="5"/>'
        )
        doc = parse_pmml(_doc_xml(interval=ivl))
        recs = [
            {"color": "red", "x": 0.0},  # open left: 0 is invalid
            {"color": "red", "x": 5.0},  # closed right: valid
            {"color": "red", "x": 0.1},
        ]
        _assert_parity(doc, recs)
        assert evaluate(doc, recs[0]).is_missing
        assert not evaluate(doc, recs[1]).is_missing

    def test_multiple_intervals_union(self):
        ivl = (
            '<Interval closure="closedClosed" leftMargin="0" rightMargin="1"/>'
            '<Interval closure="closedClosed" leftMargin="10" rightMargin="11"/>'
        )
        doc = parse_pmml(_doc_xml(interval=ivl))
        recs = [
            {"color": "red", "x": 0.5},
            {"color": "red", "x": 10.5},
            {"color": "red", "x": 5.0},  # in the gap → invalid
        ]
        _assert_parity(doc, recs)
        assert evaluate(doc, recs[2]).is_missing

    def test_interval_as_missing(self):
        doc = parse_pmml(
            _doc_xml(
                'invalidValueTreatment="asMissing"', interval=self.IVL
            )
        )
        recs = [{"color": "blue", "x": 99.0}]  # → missing x → 30
        _assert_parity(doc, recs)
        assert evaluate(doc, recs[0]).value == 30.0

    def test_interval_as_value(self):
        # the numeric replacement goes on x only — a numeric replacement
        # on the categorical color column is (correctly) a compile error
        doc = parse_pmml(
            _doc_xml(
                interval=self.IVL,
                x_attr='invalidValueTreatment="asValue" '
                       'invalidValueReplacement="1"',
            )
        )
        recs = [{"color": "blue", "x": 99.0}]  # 99→1 → x>0 → 20
        _assert_parity(doc, recs)
        assert evaluate(doc, recs[0]).value == 20.0


class TestWireAndBatchBehavior:
    def test_quantized_wire_disabled_under_invalid_policy(self, tmp_path):
        # a GBM whose fields declare Intervals must stay on the f32 path
        # (the rank wire bypasses the sanitize stage)
        from flink_jpmml_tpu.compile.qtrees import build_quantized_scorer

        xml = """<PMML version="4.3"><DataDictionary>
          <DataField name="f0" optype="continuous" dataType="double">
            <Interval closure="closedClosed" leftMargin="-10" rightMargin="10"/>
          </DataField></DataDictionary>
          <TreeModel functionName="regression">
          <MiningSchema><MiningField name="f0"/></MiningSchema>
          <Node id="r"><True/>
            <Node id="l" score="1"><SimplePredicate field="f0"
              operator="lessThan" value="0"/></Node>
            <Node id="rr" score="2"><True/></Node>
          </Node></TreeModel></PMML>"""
        doc = parse_pmml(xml)
        assert build_quantized_scorer(doc) is None
        # while a plain doc (no Values/Intervals) keeps the wire
        from assets.generate import gen_gbm
        from flink_jpmml_tpu.pmml import parse_pmml_file

        plain = parse_pmml_file(gen_gbm(str(tmp_path), n_trees=5, depth=3,
                                        n_features=4))
        assert build_quantized_scorer(plain) is not None

    def test_mixed_batch_lanes_independent(self):
        # one invalid lane must not poison its neighbors
        doc = parse_pmml(_doc_xml())
        recs = [
            {"color": "red", "x": 1.0},
            {"color": "martian", "x": 1.0},
            {"color": "blue", "x": -1.0},
        ]
        cm = compile_pmml(doc)
        preds = cm.score_records(recs)
        assert [p.is_empty for p in preds] == [False, True, False]
        assert preds[0].score.value == 10.0
        assert preds[2].score.value == 30.0


def _nn_xml(layer_attrs, neuron_extra=None, net_attrs="", last_identity=True):
    """Tiny 2-input regression NN: one custom layer (2 neurons) then an
    identity output neuron summing them."""
    neuron_extra = neuron_extra or ["", ""]
    last = (
        '<NeuralLayer activationFunction="identity">'
        '<Neuron id="o" bias="0">'
        '<Con from="h0" weight="1"/><Con from="h1" weight="1"/>'
        "</Neuron></NeuralLayer>"
        if last_identity
        else ""
    )
    out_neuron = "o" if last_identity else "h0"
    return f"""<PMML version="4.3"><DataDictionary>
      <DataField name="a" optype="continuous" dataType="double"/>
      <DataField name="b" optype="continuous" dataType="double"/>
      <DataField name="y" optype="continuous" dataType="double"/>
      </DataDictionary>
      <NeuralNetwork functionName="regression"
          activationFunction="identity" {net_attrs}>
      <MiningSchema><MiningField name="y" usageType="target"/>
        <MiningField name="a"/><MiningField name="b"/></MiningSchema>
      <NeuralInputs>
        <NeuralInput id="i0"><DerivedField optype="continuous"
          dataType="double"><FieldRef field="a"/></DerivedField></NeuralInput>
        <NeuralInput id="i1"><DerivedField optype="continuous"
          dataType="double"><FieldRef field="b"/></DerivedField></NeuralInput>
      </NeuralInputs>
      <NeuralLayer {layer_attrs}>
        <Neuron id="h0" bias="0.5" {neuron_extra[0]}>
          <Con from="i0" weight="1.0"/><Con from="i1" weight="-2.0"/></Neuron>
        <Neuron id="h1" bias="-1.0" {neuron_extra[1]}>
          <Con from="i0" weight="0.5"/><Con from="i1" weight="3.0"/></Neuron>
      </NeuralLayer>
      {last}
      <NeuralOutputs><NeuralOutput outputNeuron="{out_neuron}">
        <DerivedField optype="continuous" dataType="double">
        <FieldRef field="y"/></DerivedField></NeuralOutput></NeuralOutputs>
      </NeuralNetwork></PMML>"""


class TestNeuralActivations:
    """threshold and radialBasis activations (VERDICT r2 missing #3):
    compiled vs oracle vs hand-computed spec formulas."""

    def _parity(self, xml, n=64, seed=0):
        import math

        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        rng = np.random.default_rng(seed)
        recs = [
            {"a": float(x), "b": float(y)}
            for x, y in rng.normal(0, 1.5, size=(n, 2))
        ]
        preds = cm.score_records(recs)
        for rec, p in zip(recs, preds):
            o = evaluate(doc, rec)
            assert not p.is_empty and o.value is not None
            assert p.score.value == pytest.approx(o.value, rel=1e-4,
                                                  abs=1e-5), rec
        return doc

    def test_threshold_layer_default_cut(self):
        doc = self._parity(_nn_xml('activationFunction="threshold"'))
        # hand check: z0 = .5 + a − 2b ; z1 = −1 + .5a + 3b ; cut 0
        o = evaluate(doc, {"a": 1.0, "b": 0.0})
        assert o.value == (1.0 if 1.5 > 0 else 0.0) + (1.0 if -0.5 > 0 else 0.0)
        assert o.value == 1.0

    def test_threshold_layer_custom_cut(self):
        doc = self._parity(
            _nn_xml('activationFunction="threshold" threshold="2.0"')
        )
        o = evaluate(doc, {"a": 3.0, "b": 0.0})
        # z0 = 3.5 > 2 → 1 ; z1 = 0.5 > 2 → 0
        assert o.value == 1.0

    def test_radial_basis_layer(self):
        import math

        doc = self._parity(
            _nn_xml(
                'activationFunction="radialBasis"',
                neuron_extra=['width="1.5"', 'width="0.8"'],
            )
        )
        # spec formula, hand-computed: out_j = exp(fanIn·ln(alt) −
        # Σ(w−x)²/(2·width²)); alt defaults 1 → exp(−z/(2w²)); bias unused
        a, b = 0.3, -0.7
        z0 = (1.0 - a) ** 2 + (-2.0 - b) ** 2
        z1 = (0.5 - a) ** 2 + (3.0 - b) ** 2
        expect = math.exp(-z0 / (2 * 1.5**2)) + math.exp(-z1 / (2 * 0.8**2))
        o = evaluate(doc, {"a": a, "b": b})
        assert o.value == pytest.approx(expect, rel=1e-9)

    def test_radial_basis_altitude_and_layer_width(self):
        import math

        doc = self._parity(
            _nn_xml(
                'activationFunction="radialBasis" width="2.0" altitude="1.7"'
            )
        )
        a, b = -0.2, 0.4
        z0 = (1.0 - a) ** 2 + (-2.0 - b) ** 2
        z1 = (0.5 - a) ** 2 + (3.0 - b) ** 2
        la = math.log(1.7)
        expect = math.exp(2 * la - z0 / 8.0) + math.exp(2 * la - z1 / 8.0)
        o = evaluate(doc, {"a": a, "b": b})
        assert o.value == pytest.approx(expect, rel=1e-6)

    def test_radial_basis_without_width_rejected(self):
        from flink_jpmml_tpu.utils.exceptions import (
            ModelCompilationException,
        )

        doc = parse_pmml(_nn_xml('activationFunction="radialBasis"'))
        with pytest.raises(ModelCompilationException, match="width"):
            compile_pmml(doc)


def _clustering_xml(measure, cfields):
    return f"""<PMML version="4.3"><DataDictionary>
      <DataField name="u" optype="continuous" dataType="double"/>
      <DataField name="v" optype="continuous" dataType="double"/>
      </DataDictionary>
      <ClusteringModel functionName="clustering" modelClass="centerBased"
          numberOfClusters="3">
      <MiningSchema><MiningField name="u"/><MiningField name="v"/>
      </MiningSchema>
      {measure}
      {cfields}
      <Cluster id="c1"><Array n="2" type="real">0 0</Array></Cluster>
      <Cluster id="c2"><Array n="2" type="real">2 1</Array></Cluster>
      <Cluster id="c3"><Array n="2" type="real">-1 3</Array></Cluster>
      </ClusteringModel></PMML>"""


class TestClusteringCompareFunctions:
    """compareFunctions beyond absDiff + the minkowski metric (VERDICT r2
    missing #3): gaussSim / delta / equal per measure or per field,
    golden-diffed compiled vs oracle and spot-checked by hand."""

    def _parity(self, xml, n=100, seed=0):
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        rng = np.random.default_rng(seed)
        recs = [
            {"u": float(a), "v": float(b)}
            for a, b in rng.normal(0.5, 2.0, size=(n, 2))
        ]
        # a few exact center hits so delta/equal branch both ways
        recs += [{"u": 0.0, "v": 0.0}, {"u": 2.0, "v": 1.0},
                 {"u": 2.0, "v": 3.0}]
        preds = cm.score_records(recs)
        for rec, p in zip(recs, preds):
            o = evaluate(doc, rec)
            assert p.target.label == o.label, (rec, p.target.label, o.label)
        return doc

    def test_gauss_sim_per_field(self):
        cf = ('<ClusteringField field="u" compareFunction="gaussSim" '
              'similarityScale="1.5"/>'
              '<ClusteringField field="v" compareFunction="gaussSim" '
              'similarityScale="0.7"/>')
        self._parity(_clustering_xml(
            '<ComparisonMeasure kind="distance"><cityBlock/>'
            "</ComparisonMeasure>", cf))

    def test_delta_and_equal_mixed(self):
        cf = ('<ClusteringField field="u" compareFunction="delta"/>'
              '<ClusteringField field="v" compareFunction="absDiff"/>')
        self._parity(_clustering_xml(
            '<ComparisonMeasure kind="distance"><squaredEuclidean/>'
            "</ComparisonMeasure>", cf))

    def test_measure_level_compare_function(self):
        cf = ('<ClusteringField field="u"/>'
              '<ClusteringField field="v"/>')
        self._parity(_clustering_xml(
            '<ComparisonMeasure kind="distance" compareFunction="delta">'
            "<cityBlock/></ComparisonMeasure>", cf))

    def test_minkowski_metric(self):
        import math

        cf = ('<ClusteringField field="u" fieldWeight="2.0"/>'
              '<ClusteringField field="v"/>')
        doc = self._parity(_clustering_xml(
            '<ComparisonMeasure kind="distance">'
            '<minkowski p-parameter="3"/></ComparisonMeasure>', cf))
        # hand check vs the spec formula: d = (Σ w·|x−z|^p)^(1/p)
        o = evaluate(doc, {"u": 1.0, "v": 1.0})
        d1 = (2.0 * 1.0**3 + 1.0**3) ** (1 / 3)          # vs (0,0)
        d2 = (2.0 * 1.0**3 + 0.0**3) ** (1 / 3)          # vs (2,1)
        d3 = (2.0 * 2.0**3 + 2.0**3) ** (1 / 3)          # vs (-1,3)
        assert min((d1, d2, d3)) == d2
        assert o.label == "c2"
        assert o.probabilities[o.label] == pytest.approx(d2)

    def test_field_weight_multiplies_powered_comparison(self):
        # Σ w·c², not Σ (w·c)² — spec/JPMML semantics
        cf = ('<ClusteringField field="u" fieldWeight="9.0"/>'
              '<ClusteringField field="v"/>')
        doc = self._parity(_clustering_xml(
            '<ComparisonMeasure kind="distance"><squaredEuclidean/>'
            "</ComparisonMeasure>", cf))
        o = evaluate(doc, {"u": 1.0, "v": 0.0})
        # vs c1 (0,0): 9·1² + 0 = 9 ; with the wrong (w·c)² it would be 81
        assert o.probabilities[o.label] == pytest.approx(9.0)

    def test_gauss_sim_without_scale_rejected(self):
        from flink_jpmml_tpu.utils.exceptions import (
            ModelCompilationException,
        )

        cf = ('<ClusteringField field="u" compareFunction="gaussSim"/>'
              '<ClusteringField field="v"/>')
        doc = parse_pmml(_clustering_xml(
            '<ComparisonMeasure kind="distance"><cityBlock/>'
            "</ComparisonMeasure>", cf))
        with pytest.raises(ModelCompilationException, match="similarityScale"):
            compile_pmml(doc)


class TestTopLevelOutput:
    """Top-level <Output> (VERDICT r2 missing #3): predictedValue /
    probability / transformedValue on standalone models, identical between
    the compiled decode and the oracle (one shared implementation)."""

    CLS_XML = """<PMML version="4.3"><DataDictionary>
      <DataField name="f" optype="continuous" dataType="double"/>
      <DataField name="y" optype="categorical" dataType="string">
        <Value value="no"/><Value value="yes"/></DataField>
      </DataDictionary>
      <RegressionModel functionName="classification"
          normalizationMethod="softmax">
      <MiningSchema><MiningField name="y" usageType="target"/>
        <MiningField name="f"/></MiningSchema>
      <Output>
        <OutputField name="pred" feature="predictedValue"/>
        <OutputField name="p_yes" feature="probability" value="yes"/>
        <OutputField name="p_win" feature="probability"/>
        <OutputField name="double_p" feature="transformedValue">
          <Apply function="*"><FieldRef field="p_yes"/>
            <Constant>2.0</Constant></Apply>
        </OutputField>
      </Output>
      <RegressionTable intercept="0.2" targetCategory="yes">
        <NumericPredictor name="f" coefficient="1.3"/></RegressionTable>
      <RegressionTable intercept="0" targetCategory="no"/>
      </RegressionModel></PMML>"""

    def test_classification_outputs_parity(self):
        doc = parse_pmml(self.CLS_XML)
        cm = compile_pmml(doc)
        rng = np.random.default_rng(2)
        recs = [{"f": float(v)} for v in rng.normal(0, 2, size=40)]
        preds = cm.score_records(recs)
        for rec, p in zip(recs, preds):
            o = evaluate(doc, rec)
            assert p.outputs is not None and o.outputs
            assert p.outputs["pred"] == o.outputs["pred"] == o.label
            assert p.outputs["p_yes"] == pytest.approx(
                o.outputs["p_yes"], rel=1e-4
            )
            assert p.outputs["p_win"] == pytest.approx(
                o.probabilities[o.label], rel=1e-4
            )
            assert p.outputs["double_p"] == pytest.approx(
                2.0 * p.outputs["p_yes"], rel=1e-6
            )

    def test_regression_predicted_and_transformed(self):
        xml = """<PMML version="4.3"><DataDictionary>
          <DataField name="f" optype="continuous" dataType="double"/>
          <DataField name="y" optype="continuous" dataType="double"/>
          </DataDictionary>
          <RegressionModel functionName="regression">
          <MiningSchema><MiningField name="y" usageType="target"/>
            <MiningField name="f"/></MiningSchema>
          <Output>
            <OutputField name="raw" feature="predictedValue"/>
            <OutputField name="scaled" feature="transformedValue">
              <Apply function="+"><Apply function="*">
                <FieldRef field="raw"/><Constant>10.0</Constant></Apply>
                <Constant>5.0</Constant></Apply>
            </OutputField>
          </Output>
          <RegressionTable intercept="1.0">
            <NumericPredictor name="f" coefficient="2.0"/></RegressionTable>
          </RegressionModel></PMML>"""
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        p = cm.score_records([{"f": 3.0}])[0]
        o = evaluate(doc, {"f": 3.0})
        assert p.score.value == pytest.approx(7.0)
        assert p.outputs["raw"] == pytest.approx(7.0)
        assert p.outputs["scaled"] == pytest.approx(75.0)
        assert o.outputs["scaled"] == pytest.approx(75.0)

    def test_transformed_value_may_not_reference_inputs(self):
        from flink_jpmml_tpu.utils.exceptions import (
            ModelCompilationException,
        )

        xml = """<PMML version="4.3"><DataDictionary>
          <DataField name="f" optype="continuous" dataType="double"/>
          <DataField name="y" optype="continuous" dataType="double"/>
          </DataDictionary>
          <RegressionModel functionName="regression">
          <MiningSchema><MiningField name="y" usageType="target"/>
            <MiningField name="f"/></MiningSchema>
          <Output>
            <OutputField name="bad" feature="transformedValue">
              <FieldRef field="f"/>
            </OutputField>
          </Output>
          <RegressionTable intercept="1.0"/>
          </RegressionModel></PMML>"""
        with pytest.raises(ModelCompilationException, match="previously"):
            compile_pmml(parse_pmml(xml))

    def test_output_disables_rank_wire(self, tmp_path):
        from flink_jpmml_tpu.compile.qtrees import build_quantized_scorer
        from flink_jpmml_tpu.pmml import parse_pmml_file
        from assets.generate import gen_gbm
        import pathlib

        plain_path = gen_gbm(str(tmp_path), n_trees=4, depth=3, n_features=4)
        text = pathlib.Path(plain_path).read_text()
        # inject a top-level Output into the GBM document
        with_out = text.replace(
            "<Segmentation",
            '<Output><OutputField name="pred" feature="predictedValue"/>'
            "</Output><Segmentation",
            1,
        )
        doc = parse_pmml(with_out)
        assert doc.output_fields
        assert build_quantized_scorer(doc) is None
        cm = compile_pmml(doc)
        p = cm.score_records([{f"f{j}": 0.1 * j for j in range(4)}])[0]
        assert p.outputs["pred"] == pytest.approx(p.score.value)


class TestReviewRegressions:
    def test_dense_path_out_of_table_code_is_invalid(self):
        """Pre-encoded category codes outside the declared table must hit
        the same returnInvalid default as undeclared strings — on both
        paths (review: the compiled path only caught the string marker)."""
        doc = parse_pmml(_doc_xml())
        cm = compile_pmml(doc)
        # color codes: valid 0/1/2 — 7.0 and 1.5 are out-of-table
        vecs = np.array(
            [[0.0, 1.0], [7.0, 1.0], [1.5, 1.0], [2.0, 1.0]], np.float32
        )
        preds = cm.score_dense(vecs)
        assert [p.is_empty for p in preds] == [False, True, True, False]
        for row, p in zip(vecs, preds):
            o = evaluate(doc, {"color": float(row[0]), "x": float(row[1])})
            assert o.is_missing == p.is_empty, row

    def test_as_value_with_undeclared_replacement_rejected(self):
        from flink_jpmml_tpu.utils.exceptions import (
            ModelCompilationException,
        )

        doc = parse_pmml(
            _doc_xml(
                'invalidValueTreatment="asValue" '
                'invalidValueReplacement="chartreuse"'
            )
        )
        with pytest.raises(ModelCompilationException, match="declared"):
            compile_pmml(doc)

    def test_clustering_output_probability_parity(self):
        """Top-level <Output> probability on a clustering model: the
        per-cluster distance map must be keyed identically on both paths
        (review: the oracle used a magic 'distance' key)."""
        cf = '<ClusteringField field="u"/><ClusteringField field="v"/>'
        xml = _clustering_xml(
            '<ComparisonMeasure kind="distance"><squaredEuclidean/>'
            "</ComparisonMeasure>", cf,
        ).replace(
            '<Cluster id="c1"',
            '<Output><OutputField name="win_d" feature="probability"/>'
            '</Output><Cluster id="c1"',
            1,
        )
        doc = parse_pmml(xml)
        cm = compile_pmml(doc)
        rng = np.random.default_rng(3)
        recs = [
            {"u": float(a), "v": float(b)}
            for a, b in rng.normal(0.5, 2.0, size=(30, 2))
        ]
        for rec, p in zip(recs, cm.score_records(recs)):
            o = evaluate(doc, rec)
            assert o.outputs["win_d"] is not None
            assert p.outputs["win_d"] == pytest.approx(
                o.outputs["win_d"], rel=1e-4
            ), rec
